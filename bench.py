"""Benchmark: GPT-2 causal-LM training throughput on one TPU chip.

Prints ONE JSON line on stdout: {"metric", "value", "unit", "vs_baseline"}.

``vs_baseline`` is achieved model TFLOP/s per chip divided by the
reference's headline per-device training throughput claim (64 TFLOP/s per
V100, BERT-large pretrain — BASELINE.md / reference
``docs/_posts/2020-05-28-fastest-bert-training.md:13``). Model FLOPs use
the standard 6*N*T causal-LM estimate.

Structure (hardened after round 1, where one bad TPU-backend init erased
the round's perf evidence — it either crashed in seconds or hung forever):

- parent process (no jax import): probes the accelerator backend in a
  subprocess under a hard timeout, retries once, then runs the real
  benchmark in a subprocess under a hard timeout;
- if the accelerator never comes up or the bench dies, falls back to a
  small CPU-pinned benchmark (axon/TPU plugin disabled via env scrub) so
  *some* JSON line always prints;
- every subprocess gets a wall-clock budget; the parent always emits
  exactly one JSON line, even on total failure.

Tunables: BENCH_MODEL / BENCH_MICRO_BS / BENCH_SEQ / BENCH_STEPS and
BENCH_PROBE_TIMEOUT / BENCH_RUN_TIMEOUT / BENCH_CPU_TIMEOUT (seconds).
"""

import json
import os
import subprocess
import sys
import time

BASELINE_TFLOPS = 64.0  # reference headline, BASELINE.md


# --------------------------------------------------------------------------
# child: the actual benchmark (runs in a subprocess; may crash or hang —
# the parent owns the timeout)
# --------------------------------------------------------------------------

def run_child():
    import numpy as np
    import jax

    # persistent compile cache: repeat bench runs (and the CPU fallback,
    # whose time budget is mostly compilation) skip straight to execution
    try:
        jax.config.update("jax_compilation_cache_dir",
                          os.environ.get("JAX_CACHE_DIR", os.path.join(
                              os.path.dirname(os.path.abspath(__file__)), ".jax_cache")))
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)
    except Exception:
        pass  # older jax without the knobs — compile cold

    import deepspeed_tpu
    from deepspeed_tpu.models import GPT2LMHeadModel, get_gpt2_config

    import jax.numpy as jnp

    model_name = os.environ.get("BENCH_MODEL", "350m")
    # mb=8 measured fastest on v5e (69-75 TFLOPS/chip vs 62 at mb=4; mb=16
    # OOMs) — r3 sweep, tools/perf_sweep2.py
    micro_bs = int(os.environ.get("BENCH_MICRO_BS", "8"))
    seq = int(os.environ.get("BENCH_SEQ", "1024"))
    steps = int(os.environ.get("BENCH_STEPS", "60"))
    # remat measured slightly faster at this size on v5e (415.7 vs 425.3 ms
    # per step, r3 sweep) — the step is memory-bound, so trading HBM traffic
    # for recompute wins
    remat = os.environ.get("BENCH_REMAT", "1") == "1"

    n_dev = jax.device_count()
    attn = os.environ.get("BENCH_ATTN", "flash" if jax.default_backend() in ("tpu", "axon") else "xla")
    # compute in bf16 end-to-end: without an explicit dtype the flax modules
    # force fp32 compute even though the engine casts params to bf16
    overrides = {}
    # vocab padded to a lane-aligned multiple (Megatron-style): 50257 → 50304
    # tiles the LM-head matmul cleanly on the MXU. Both this and the
    # scatter-free embedding backward measured faster on v5e (r3 sweep:
    # 68.2 → 75.0 TFLOPS at mb=8) — on by default, opt out with "0"/"".
    vocab_override = int(os.environ.get("BENCH_VOCAB", "50304") or 0)
    if vocab_override > 0:
        overrides["vocab_size"] = vocab_override
    if os.environ.get("BENCH_EMBED_ONEHOT", "1") == "1":
        overrides["embed_onehot_grad"] = True
    # chunked fused LM-head loss (no [B,L,V] logits buffer) — measured
    # faster than the plain head at mb=8 on v5e (70.1 vs 69.0 TFLOPS,
    # tools/perf_sweep2.py r3 session 5) — on by default, opt out with "0"
    if os.environ.get("BENCH_FUSED_XENT", "1") == "1":
        overrides["fused_head_loss_chunk"] = int(os.environ.get("BENCH_XENT_CHUNK", "1024"))
    cfg_model = get_gpt2_config(model_name, n_positions=seq, remat=remat,
                                attention_backend=attn, dtype=jnp.bfloat16,
                                **overrides)
    model = GPT2LMHeadModel(cfg_model)

    zero_stage = int(os.environ.get("BENCH_ZERO", "1" if n_dev > 1 else "0"))
    zero_cfg = {"stage": zero_stage}
    # BENCH_OFFLOAD=1: the ZeRO-Infinity recipe (stage 3 + host-resting
    # streamed params + host C++ Adam) — the quick on-chip A/B for the
    # offload path's overhead vs the dense step
    if os.environ.get("BENCH_OFFLOAD", "0") == "1":
        zero_cfg = {"stage": 3,
                    "offload_param": {"device": "cpu", "pin_memory": True},
                    "offload_optimizer": {"device": "cpu", "pin_memory": True}}
    ds_config = {
        "train_batch_size": micro_bs * n_dev,
        "gradient_accumulation_steps": 1,
        "optimizer": {"type": "AdamW", "params": {"lr": 1e-4, "weight_decay": 0.01}},
        "bf16": {"enabled": True},
        "gradient_clipping": 1.0,
        "zero_optimization": zero_cfg,
        "steps_per_print": 10**9,
    }
    engine, _, _, _ = deepspeed_tpu.initialize(model=model, config=ds_config)

    rng = np.random.default_rng(0)
    batch = {"input_ids": rng.integers(0, cfg_model.vocab_size,
                                       (micro_bs * n_dev, seq)).astype(np.int32)}

    engine.initialize_state(batch)
    n_params = sum(int(np.prod(p.shape)) for p in jax.tree.leaves(engine.state.params))

    # >1: run that many optimizer steps per device dispatch (lax.scan inside
    # one jit call) — amortizes host→device dispatch latency, the idiomatic
    # TPU training-loop shape. Falls back to the per-dispatch loop if the
    # scanned program fails to build (keeps the driver's bench robust).
    # Depth 30: the tunnel pays ~200ms RTT per dispatch, so depth-10
    # inflated the measured step by ~21ms (225.7 -> 212.3 ms at depth 30;
    # PERF.md round-5 ladder erratum has the same decomposition for the
    # BERT rungs).
    fused = int(os.environ.get("BENCH_FUSED_STEPS", "30"))
    fused = max(1, min(fused, steps))  # BENCH_STEPS=10 means 10 steps, not 30
    if fused > 1:
        try:
            stack = {"input_ids": np.broadcast_to(batch["input_ids"],
                                                  (fused,) + batch["input_ids"].shape)}
            engine.train_batches(stack)  # warmup/compile
            jax.block_until_ready(engine.state.params)
        except Exception as e:  # noqa: BLE001 — any build failure → fallback
            print(f"# fused-step path failed ({type(e).__name__}: {e}); "
                  f"falling back to per-dispatch", flush=True)
            fused = 1
    if fused > 1:
        outer = max(1, steps // fused)
        t0 = time.time()
        for _ in range(outer):
            engine.train_batches(stack)
        jax.block_until_ready(engine.state.params)
        dt = time.time() - t0
        steps = outer * fused
    else:
        for _ in range(2):  # warmup/compile
            engine.train_batch(batch)
        jax.block_until_ready(engine.state.params)
        t0 = time.time()
        for _ in range(steps):
            engine.train_batch(batch)
        jax.block_until_ready(engine.state.params)
        dt = time.time() - t0

    tokens = micro_bs * n_dev * seq * steps
    tok_per_sec_chip = tokens / dt / n_dev
    # FLOPs/token = 6N + causal attention term (6*L*s*hidden) — the bare 6N
    # estimate omits the O(L^2) score matmuls and understates long-context
    # MFU by up to ~2x at seq=8k (tools/bench_core.model_flops_per_token)
    sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)), "tools"))
    from bench_core import flops_per_token_from_cfg
    fpt = flops_per_token_from_cfg(n_params, cfg_model, seq)
    model_tflops = fpt * tok_per_sec_chip / 1e12
    print(json.dumps({
        "metric": f"gpt2_{model_name}_train_tokens_per_sec_per_chip",
        "value": round(tok_per_sec_chip, 1),
        "unit": "tokens/s/chip",
        "vs_baseline": round(model_tflops / BASELINE_TFLOPS, 4),
        "backend": jax.default_backend(),
        "tflops_per_chip": round(model_tflops, 2),
        "n_params": n_params,
        "step_ms": round(dt / steps * 1e3, 1),
        "attn_flops_frac": round(1.0 - 6.0 * n_params / fpt, 3),
    }))


def run_parity():
    """Emit this backend's reproducible loss curve (tools/parity_check)."""
    sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)), "tools"))
    import parity_check
    parity_check.main()


def run_probe():
    """Tiny end-to-end check that the backend can init AND compile."""
    import jax
    import jax.numpy as jnp

    n = jax.device_count()
    out = jax.jit(lambda x: x * 2.0 + 1.0)(jnp.float32(20.5))
    assert float(out) == 42.0
    print(f"probe ok: {n} {jax.default_backend()} device(s)", flush=True)


# --------------------------------------------------------------------------
# parent orchestration (never imports jax)
# --------------------------------------------------------------------------

def _run(mode, env, timeout):
    """Run this file in `mode` as a subprocess. Returns (rc, stdout, stderr);
    rc=124 on timeout."""
    try:
        p = subprocess.run(
            [sys.executable, os.path.abspath(__file__), mode],
            env=env, capture_output=True, text=True, timeout=timeout,
            cwd=os.path.dirname(os.path.abspath(__file__)),
        )
        return p.returncode, p.stdout, p.stderr
    except subprocess.TimeoutExpired as e:
        from envutil import to_text
        return 124, to_text(e.stdout), to_text(e.stderr)


def _parity_report(timeout):
    """BASELINE north star: accelerator-vs-CPU loss-curve parity. Runs the
    reproducible curve (tools/parity_check) once on the accelerator and
    once on a plugin-scrubbed CPU subprocess, and reports bit-identity /
    max-ULP. Failures degrade to an explanatory dict — parity must never
    cost the bench its throughput number."""
    try:
        rc_a, out_a, err_a = _run("parity", dict(os.environ), timeout)
        a = _last_json_line(out_a)
        if rc_a != 0 or a is None:
            return {"error": f"accel curve rc={rc_a}: "
                    f"{err_a.strip().splitlines()[-1] if err_a.strip() else 'no output'}"}
        from envutil import cpu_subprocess_env
        # one pinned CPU device: the curve's workload is single-device by
        # construction (parity_check.curve), keep the device count fixed too
        rc_c, out_c, err_c = _run("parity", cpu_subprocess_env(n_virtual_devices=1), timeout)
        c = _last_json_line(out_c)
        if rc_c != 0 or c is None:
            return {"error": f"cpu curve rc={rc_c}: "
                    f"{err_c.strip().splitlines()[-1] if err_c.strip() else 'no output'}"}
        sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)), "tools"))
        import parity_check
        rep = parity_check.compare(parity_check.from_hex(a["curve_hex"]),
                                   parity_check.from_hex(c["curve_hex"]))
        rep["backends"] = [a.get("backend"), c.get("backend")]
        envelope = int(os.environ.get("PARITY_MAX_ULP", "0"))
        rep["within_envelope"] = rep["max_ulp"] <= envelope or rep["bit_identical"]
        rep["envelope_ulp"] = envelope
        # the accelerator curve's per-(src->dst, scope) upcast inventory
        # (R002 via tools/parity_check) rides along so a refused bank
        # carries its own ULP-hunt evidence
        if a.get("precision_attribution") is not None:
            rep["precision_attribution"] = a["precision_attribution"]
        return rep
    except Exception as e:  # noqa: BLE001
        return {"error": f"{type(e).__name__}: {e}"}


def _attribution_by_scope(attribution):
    """Collapse R002's ``"src->dst @ scope": count`` tally to per-scope
    totals — the compact summary a refused bank records (which scopes
    widen, not every op instance)."""
    by_scope = {}
    for key, count in (attribution or {}).items():
        if not isinstance(count, int):
            continue  # error dicts degrade to empty
        scope = key.split("@", 1)[1].strip() if "@" in key else key
        by_scope[scope] = by_scope.get(scope, 0) + count
    return dict(sorted(by_scope.items(), key=lambda kv: -kv[1]))


def _apply_parity_bank_gate(result, banked_path):
    """ROADMAP item 4, last clause: a round whose parity phase reports
    ``within_envelope: false`` must not bank its throughput number
    silently. The refusal (or the explicit ``PARITY_BANK_ANYWAY=1``
    override) and a per-scope ``precision_attribution`` summary are
    recorded in the bench JSON either way, so every banked number carries
    its parity verdict. Returns True when the banked number survives."""
    par = result.get("parity") or {}
    if par.get("within_envelope") is not False:
        return True
    gate = {
        "within_envelope": False,
        "max_ulp": par.get("max_ulp"),
        "envelope_ulp": par.get("envelope_ulp"),
        "precision_attribution_by_scope":
            _attribution_by_scope(par.get("precision_attribution")),
    }
    if os.environ.get("PARITY_BANK_ANYWAY", "0") == "1":
        gate["banked_anyway"] = True
        result["parity_bank"] = gate
        print("# parity outside envelope; banking anyway (PARITY_BANK_ANYWAY=1)",
              flush=True)
        return True
    gate["refused"] = ("parity within_envelope=false — throughput number not "
                       "banked; set PARITY_BANK_ANYWAY=1 to override")
    result["parity_bank"] = gate
    try:
        os.unlink(banked_path)
    except OSError:
        pass
    print(f"# BANK REFUSED: {gate['refused']}", flush=True)
    return False


def _last_json_line(text):
    for line in reversed(text.strip().splitlines()):
        line = line.strip()
        if line.startswith("{"):
            try:
                return json.loads(line)
            except json.JSONDecodeError:
                continue
    return None


def main():
    # run budget sized for a COLD compile cache: the fused-scan 350M
    # program (depth 30; scan length doesn't change program size) can take
    # >8 min to compile on the tunnel, and killing the
    # claim-holding child mid-compile wedges the tunnel for hours (wedge #4,
    # PERF.md). The repo-local .jax_cache (survives reboots, unlike /tmp)
    # makes warm runs finish in ~2-3 min.
    probe_timeout = int(os.environ.get("BENCH_PROBE_TIMEOUT", "120"))
    run_timeout = int(os.environ.get("BENCH_RUN_TIMEOUT", "2400"))
    cpu_timeout = int(os.environ.get("BENCH_CPU_TIMEOUT", "600"))
    errors = []
    # clear the previous run's banked number: the file is read after hangs,
    # exactly when staleness would be invisible
    try:
        os.unlink(os.path.join(os.path.dirname(os.path.abspath(__file__)),
                               ".bench_banked.json"))
    except OSError:
        pass

    # 1) accelerator probe, two attempts
    accel_ok = False
    for attempt in range(2):
        rc, out, err = _run("probe", dict(os.environ), probe_timeout)
        if rc == 0:
            accel_ok = True
            break
        errors.append(f"probe attempt {attempt + 1}: rc={rc} "
                      f"{(err or out).strip().splitlines()[-1] if (err or out).strip() else 'no output'}")
        if attempt == 0:
            time.sleep(5)

    # 2) real benchmark on the accelerator
    if accel_ok:
        rc, out, err = _run("child", dict(os.environ), run_timeout)
        result = _last_json_line(out)
        if rc == 0 and result is not None:
            # bank the throughput number BEFORE the parity phase (which runs
            # two more training subprocesses, up to 2x BENCH_PARITY_TIMEOUT):
            # a parity-phase hang on a flaky tunnel must never cost the
            # round its banked number (r4 advisor finding)
            banked = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                  ".bench_banked.json")
            try:
                with open(banked, "w") as f:
                    json.dump(result, f)
            except OSError:
                pass
            print(f"# banked pre-parity: {json.dumps(result)}", flush=True)
            if os.environ.get("BENCH_PARITY", "1") == "1":
                result["parity"] = _parity_report(
                    int(os.environ.get("BENCH_PARITY_TIMEOUT", "600")))
                # an out-of-envelope round un-banks the pre-parity number
                # (ROADMAP 4: determinism is a product feature, not a
                # footnote) — the JSON line still reports everything
                _apply_parity_bank_gate(result, banked)
            print(json.dumps(result))
            return
        errors.append(f"accel bench: rc={rc} "
                      f"{err.strip().splitlines()[-1] if err.strip() else 'no json output'}")

    # 3) CPU fallback: force a small model so some number always lands
    # (an inherited BENCH_MODEL=350m would blow the CPU time budget)
    from envutil import cpu_subprocess_env
    env = cpu_subprocess_env()
    env["BENCH_MODEL"] = os.environ.get("BENCH_CPU_MODEL", "125m")
    env["BENCH_MICRO_BS"] = os.environ.get("BENCH_CPU_MICRO_BS", "1")
    env["BENCH_SEQ"] = os.environ.get("BENCH_CPU_SEQ", "256")
    env["BENCH_STEPS"] = os.environ.get("BENCH_CPU_STEPS", "3")
    env["BENCH_ATTN"] = "xla"
    env["BENCH_FUSED_STEPS"] = "1"  # a deep scan would blow the CPU budget
    rc, out, err = _run("child", env, cpu_timeout)
    result = _last_json_line(out)
    if rc == 0 and result is not None:
        result["note"] = ("CPU FALLBACK (accelerator unavailable; last live-chip "
                          "measurement documented in PERF.md): " + " | ".join(errors))
        print(json.dumps(result))
        return
    errors.append(f"cpu fallback: rc={rc} "
                  f"{err.strip().splitlines()[-1] if err.strip() else 'no json output'}")

    # 4) total failure still prints a parseable line
    print(json.dumps({
        "metric": "gpt2_train_tokens_per_sec_per_chip",
        "value": 0.0,
        "unit": "tokens/s/chip",
        "vs_baseline": 0.0,
        "error": " | ".join(errors),
    }))


if __name__ == "__main__":
    if len(sys.argv) > 1 and sys.argv[1] == "child":
        run_child()
    elif len(sys.argv) > 1 and sys.argv[1] == "probe":
        run_probe()
    elif len(sys.argv) > 1 and sys.argv[1] == "parity":
        run_parity()
    else:
        main()
