"""Benchmark: GPT-2 350M causal-LM training throughput on one TPU chip.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.

``vs_baseline`` is achieved model TFLOP/s per chip divided by the
reference's headline per-device training throughput claim (64 TFLOP/s per
V100, BERT-large pretrain — BASELINE.md / reference
``docs/_posts/2020-05-28-fastest-bert-training.md:13``). Model FLOPs use
the standard 6*N*T causal-LM estimate.

Run on the real TPU (leave JAX_PLATFORMS alone). Select a smaller model or
batch via BENCH_MODEL / BENCH_MICRO_BS / BENCH_SEQ env vars.
"""

import json
import os
import sys
import time

import numpy as np


def main():
    import jax

    import deepspeed_tpu
    from deepspeed_tpu.models import GPT2LMHeadModel, get_gpt2_config

    model_name = os.environ.get("BENCH_MODEL", "350m")
    micro_bs = int(os.environ.get("BENCH_MICRO_BS", "4"))
    seq = int(os.environ.get("BENCH_SEQ", "1024"))
    steps = int(os.environ.get("BENCH_STEPS", "10"))

    n_dev = jax.device_count()
    attn = os.environ.get("BENCH_ATTN", "flash" if jax.default_backend() == "tpu" else "xla")
    cfg_model = get_gpt2_config(model_name, n_positions=seq, remat=True, attention_backend=attn)
    model = GPT2LMHeadModel(cfg_model)

    ds_config = {
        "train_batch_size": micro_bs * n_dev,
        "gradient_accumulation_steps": 1,
        "optimizer": {"type": "AdamW", "params": {"lr": 1e-4, "weight_decay": 0.01}},
        "bf16": {"enabled": True},
        "gradient_clipping": 1.0,
        "zero_optimization": {"stage": 1 if n_dev > 1 else 0},
        "steps_per_print": 10**9,
    }
    engine, _, _, _ = deepspeed_tpu.initialize(model=model, config=ds_config)

    rng = np.random.default_rng(0)
    batch = {"input_ids": rng.integers(0, cfg_model.vocab_size,
                                       (micro_bs * n_dev, seq)).astype(np.int32)}

    # param count for FLOPs estimate
    engine.initialize_state(batch)
    n_params = sum(int(np.prod(p.shape)) for p in jax.tree.leaves(engine.state.params))

    # warmup (compile)
    for _ in range(2):
        engine.train_batch(batch)
    jax.block_until_ready(engine.state.params)

    t0 = time.time()
    for _ in range(steps):
        engine.train_batch(batch)
    jax.block_until_ready(engine.state.params)
    dt = time.time() - t0

    tokens = micro_bs * n_dev * seq * steps
    tok_per_sec_chip = tokens / dt / n_dev
    model_tflops = 6.0 * n_params * tok_per_sec_chip / 1e12
    print(json.dumps({
        "metric": "gpt2_350m_train_tokens_per_sec_per_chip",
        "value": round(tok_per_sec_chip, 1),
        "unit": "tokens/s/chip",
        "vs_baseline": round(model_tflops / 64.0, 4),
    }))
    print(f"# n_params={n_params/1e6:.1f}M devices={n_dev} step_time={dt/steps*1e3:.1f}ms "
          f"model_tflops/chip={model_tflops:.2f}", file=sys.stderr)


if __name__ == "__main__":
    main()
