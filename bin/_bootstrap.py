"""Shared path shim for the bin/ scripts: allow running from a source
checkout without installation (bin/ itself is sys.path[0] when a script
runs, so `import _bootstrap` resolves here)."""

import os
import sys

_repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if os.path.isdir(os.path.join(_repo, "deepspeed_tpu")) and _repo not in sys.path:
    sys.path.insert(0, _repo)
