// Host-side vectorized Adam/AdamW for offloaded optimizer states.
// TPU-native counterpart of reference csrc/adam/cpu_adam.cpp (+ cpu_adam_impl.cpp,
// includes/simd.h): updates fp32 master params + moments resident in host RAM
// while the device keeps only the working-precision copy.
//
// AVX2 (+FMA) fast path with scalar tail; scalar fallback elsewhere.
// Exposed as a C ABI for ctypes (pybind11 is not in the image).

#include <cmath>
#include <cstdint>
#include <cstring>

#if defined(__AVX2__)
#include <immintrin.h>
#endif

extern "C" {

// One fused Adam step over a contiguous span.
//   p, g, m, v : fp32 arrays of length n (updated in place except g)
//   step       : 1-based optimizer step (for bias correction)
//   adamw      : 1 → decoupled weight decay (AdamW), 0 → L2-into-grad (Adam)
void ds_adam_update(float* __restrict p,
                    const float* __restrict g,
                    float* __restrict m,
                    float* __restrict v,
                    int64_t n,
                    int32_t step,
                    float lr,
                    float beta1,
                    float beta2,
                    float eps,
                    float weight_decay,
                    int32_t adamw,
                    int32_t bias_correction) {
    float bc1 = 1.0f, bc2 = 1.0f;
    if (bias_correction) {
        bc1 = 1.0f - std::pow(beta1, (float)step);
        bc2 = 1.0f - std::pow(beta2, (float)step);
    }
    const float step_size = lr / bc1;
    const float bc2_sqrt = std::sqrt(bc2);
    const float omb1 = 1.0f - beta1;
    const float omb2 = 1.0f - beta2;

    int64_t i = 0;
#if defined(__AVX2__) && defined(__FMA__)
    const __m256 vb1 = _mm256_set1_ps(beta1);
    const __m256 vb2 = _mm256_set1_ps(beta2);
    const __m256 vomb1 = _mm256_set1_ps(omb1);
    const __m256 vomb2 = _mm256_set1_ps(omb2);
    const __m256 veps = _mm256_set1_ps(eps);
    const __m256 vstep = _mm256_set1_ps(step_size);
    const __m256 vbc2s = _mm256_set1_ps(bc2_sqrt);
    const __m256 vwd = _mm256_set1_ps(weight_decay);
    const __m256 vlrwd = _mm256_set1_ps(lr * weight_decay);
    for (; i + 8 <= n; i += 8) {
        __m256 gp = _mm256_loadu_ps(g + i);
        __m256 pp = _mm256_loadu_ps(p + i);
        if (weight_decay != 0.0f && !adamw) gp = _mm256_fmadd_ps(vwd, pp, gp);
        __m256 mp = _mm256_loadu_ps(m + i);
        __m256 vp = _mm256_loadu_ps(v + i);
        mp = _mm256_fmadd_ps(vb1, mp, _mm256_mul_ps(vomb1, gp));
        vp = _mm256_fmadd_ps(vb2, vp, _mm256_mul_ps(vomb2, _mm256_mul_ps(gp, gp)));
        __m256 denom = _mm256_add_ps(_mm256_div_ps(_mm256_sqrt_ps(vp), vbc2s), veps);
        __m256 update = _mm256_div_ps(mp, denom);
        if (weight_decay != 0.0f && adamw) pp = _mm256_fnmadd_ps(vlrwd, pp, pp);
        pp = _mm256_fnmadd_ps(vstep, update, pp);
        _mm256_storeu_ps(p + i, pp);
        _mm256_storeu_ps(m + i, mp);
        _mm256_storeu_ps(v + i, vp);
    }
#endif
    for (; i < n; ++i) {
        float gi = g[i];
        if (weight_decay != 0.0f && !adamw) gi += weight_decay * p[i];
        m[i] = beta1 * m[i] + omb1 * gi;
        v[i] = beta2 * v[i] + omb2 * gi * gi;
        float denom = std::sqrt(v[i]) / bc2_sqrt + eps;
        if (weight_decay != 0.0f && adamw) p[i] -= lr * weight_decay * p[i];
        p[i] -= step_size * (m[i] / denom);
    }
}

// Update + copy params out as bfloat16 (round-to-nearest-even), saving the
// separate cast pass when the device copy is bf16
// (reference adam_update_copy, cpu_adam.cpp:303).
void ds_adam_update_copy_bf16(float* __restrict p,
                              const float* __restrict g,
                              float* __restrict m,
                              float* __restrict v,
                              uint16_t* __restrict p_bf16,
                              int64_t n,
                              int32_t step,
                              float lr,
                              float beta1,
                              float beta2,
                              float eps,
                              float weight_decay,
                              int32_t adamw,
                              int32_t bias_correction) {
    ds_adam_update(p, g, m, v, n, step, lr, beta1, beta2, eps, weight_decay, adamw, bias_correction);
    for (int64_t i = 0; i < n; ++i) {
        uint32_t bits;
        std::memcpy(&bits, p + i, 4);
        uint32_t rounding = 0x7FFF + ((bits >> 16) & 1);
        p_bf16[i] = (uint16_t)((bits + rounding) >> 16);
    }
}

// Vectorized Adagrad (reference csrc/adagrad/cpu_adagrad.cpp).
void ds_adagrad_update(float* __restrict p,
                       const float* __restrict g,
                       float* __restrict h,
                       int64_t n,
                       float lr,
                       float eps,
                       float weight_decay) {
    for (int64_t i = 0; i < n; ++i) {
        float gi = g[i] + weight_decay * p[i];
        h[i] += gi * gi;
        p[i] -= lr * gi / (std::sqrt(h[i]) + eps);
    }
}

}  // extern "C"
