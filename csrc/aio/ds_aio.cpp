// Async host file I/O engine for NVMe offload (ZeRO-Infinity spill).
// TPU-native counterpart of reference csrc/aio/ (deepspeed_py_aio_handle.cpp,
// deepspeed_aio_common.cpp): a thread-pool handle with submit/wait semantics.
// The reference drives libaio O_DIRECT; this engine uses a worker pool of
// pread/pwrite (the reference's own fallback scheme) — same interface
// contract: async submit, bounded queue, explicit wait.
//
// C ABI for ctypes.

#include <atomic>
#include <condition_variable>
#include <cstdint>
#include <cstdio>
#include <cstring>
#include <fcntl.h>
#include <functional>
#include <mutex>
#include <queue>
#include <string>
#include <thread>
#include <unistd.h>
#include <vector>

namespace {

struct AioHandle {
    std::vector<std::thread> workers;
    std::queue<std::function<void()>> tasks;
    std::mutex mu;
    std::condition_variable cv;
    std::condition_variable done_cv;
    std::atomic<int64_t> inflight{0};
    std::atomic<int64_t> errors{0};
    bool stop = false;

    explicit AioHandle(int n_threads) {
        for (int i = 0; i < n_threads; ++i) {
            workers.emplace_back([this] {
                for (;;) {
                    std::function<void()> task;
                    {
                        std::unique_lock<std::mutex> lk(mu);
                        cv.wait(lk, [this] { return stop || !tasks.empty(); });
                        if (stop && tasks.empty()) return;
                        task = std::move(tasks.front());
                        tasks.pop();
                    }
                    task();
                    if (--inflight == 0) {
                        std::lock_guard<std::mutex> lk(mu);
                        done_cv.notify_all();
                    }
                }
            });
        }
    }

    ~AioHandle() {
        {
            std::lock_guard<std::mutex> lk(mu);
            stop = true;
        }
        cv.notify_all();
        for (auto& w : workers) w.join();
    }

    void submit(std::function<void()> fn) {
        ++inflight;
        {
            std::lock_guard<std::mutex> lk(mu);
            tasks.push(std::move(fn));
        }
        cv.notify_one();
    }

    int wait() {
        std::unique_lock<std::mutex> lk(mu);
        done_cv.wait(lk, [this] { return inflight.load() == 0; });
        return (int)errors.exchange(0);
    }
};

bool write_all(const char* path, const void* buf, int64_t nbytes) {
    int fd = ::open(path, O_WRONLY | O_CREAT | O_TRUNC, 0644);
    if (fd < 0) return false;
    const char* src = (const char*)buf;
    int64_t left = nbytes;
    off_t off = 0;
    while (left > 0) {
        ssize_t w = ::pwrite(fd, src + off, (size_t)left, off);
        if (w <= 0) {
            ::close(fd);
            return false;
        }
        left -= w;
        off += w;
    }
    ::close(fd);
    return true;
}

bool read_all(const char* path, void* buf, int64_t nbytes) {
    int fd = ::open(path, O_RDONLY);
    if (fd < 0) return false;
    char* dst = (char*)buf;
    int64_t left = nbytes;
    off_t off = 0;
    while (left > 0) {
        ssize_t r = ::pread(fd, dst + off, (size_t)left, off);
        if (r <= 0) {
            ::close(fd);
            return false;
        }
        left -= r;
        off += r;
    }
    ::close(fd);
    return true;
}

}  // namespace

extern "C" {

void* aio_handle_create(int n_threads) {
    if (n_threads < 1) n_threads = 1;
    return new AioHandle(n_threads);
}

void aio_handle_destroy(void* h) { delete (AioHandle*)h; }

// async write of nbytes from buf to path (buf must stay alive until wait)
void aio_pwrite_async(void* h, const char* path, const void* buf, int64_t nbytes) {
    auto* handle = (AioHandle*)h;
    std::string p(path);
    handle->submit([handle, p, buf, nbytes] {
        if (!write_all(p.c_str(), buf, nbytes)) ++handle->errors;
    });
}

// async read of nbytes from path into buf (buf must stay alive until wait)
void aio_pread_async(void* h, const char* path, void* buf, int64_t nbytes) {
    auto* handle = (AioHandle*)h;
    std::string p(path);
    handle->submit([handle, p, buf, nbytes] {
        if (!read_all(p.c_str(), buf, nbytes)) ++handle->errors;
    });
}

// block until every submitted op completes; returns the number of failed ops
// since the last wait
int aio_wait(void* h) { return ((AioHandle*)h)->wait(); }

// synchronous helpers (reference deepspeed_py_aio.cpp sync paths)
int aio_write_sync(const char* path, const void* buf, int64_t nbytes) {
    return write_all(path, buf, nbytes) ? 0 : -1;
}

int aio_read_sync(const char* path, void* buf, int64_t nbytes) {
    return read_all(path, buf, nbytes) ? 0 : -1;
}

}  // extern "C"
