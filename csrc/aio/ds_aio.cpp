// Async host file I/O engine for NVMe offload (ZeRO-Infinity spill).
// TPU-native counterpart of reference csrc/aio/ (deepspeed_py_aio_handle.cpp,
// deepspeed_aio_common.cpp): a thread-pool handle with submit/wait semantics.
// Like the reference (deepspeed_aio_common.cpp:335 O_DIRECT regular_read_write),
// the data path can bypass the page cache: O_DIRECT transfers through a
// posix_memalign'd bounce buffer in aligned chunks, with the unaligned tail
// finished on a buffered descriptor + fsync. Falls back to plain
// pread/pwrite where the filesystem refuses O_DIRECT (tmpfs).
//
// C ABI for ctypes.

#include <algorithm>
#include <atomic>
#include <condition_variable>
#include <cstdint>
#include <cstdio>
#include <cstdlib>
#include <cstring>
#include <fcntl.h>
#include <functional>
#include <mutex>
#include <queue>
#include <string>
#include <thread>
#include <unistd.h>
#include <vector>

namespace {

struct AioHandle {
    std::vector<std::thread> workers;
    std::queue<std::function<void()>> tasks;
    std::mutex mu;
    std::condition_variable cv;
    std::condition_variable done_cv;
    std::atomic<int64_t> inflight{0};
    std::atomic<int64_t> errors{0};
    std::atomic<int64_t> direct_fallbacks{0};  // direct-requested ops that ran buffered
    bool stop = false;
    bool direct = false;  // O_DIRECT data path (page-cache bypass)

    explicit AioHandle(int n_threads) {
        for (int i = 0; i < n_threads; ++i) {
            workers.emplace_back([this] {
                for (;;) {
                    std::function<void()> task;
                    {
                        std::unique_lock<std::mutex> lk(mu);
                        cv.wait(lk, [this] { return stop || !tasks.empty(); });
                        if (stop && tasks.empty()) return;
                        task = std::move(tasks.front());
                        tasks.pop();
                    }
                    task();
                    if (--inflight == 0) {
                        std::lock_guard<std::mutex> lk(mu);
                        done_cv.notify_all();
                    }
                }
            });
        }
    }

    ~AioHandle() {
        {
            std::lock_guard<std::mutex> lk(mu);
            stop = true;
        }
        cv.notify_all();
        for (auto& w : workers) w.join();
    }

    void submit(std::function<void()> fn) {
        ++inflight;
        {
            std::lock_guard<std::mutex> lk(mu);
            tasks.push(std::move(fn));
        }
        cv.notify_one();
    }

    int wait() {
        std::unique_lock<std::mutex> lk(mu);
        done_cv.wait(lk, [this] { return inflight.load() == 0; });
        return (int)errors.exchange(0);
    }
};

constexpr int64_t kAlign = 4096;           // O_DIRECT sector alignment
constexpr int64_t kBounce = 8 * 1024 * 1024;  // bounce-buffer chunk

bool write_all_buffered(int fd, const char* src, int64_t nbytes, off_t base) {
    int64_t left = nbytes;
    off_t off = 0;
    while (left > 0) {
        ssize_t w = ::pwrite(fd, src + off, (size_t)left, base + off);
        if (w <= 0) return false;
        left -= w;
        off += w;
    }
    return true;
}

bool read_all_buffered(int fd, char* dst, int64_t nbytes, off_t base) {
    int64_t left = nbytes;
    off_t off = 0;
    while (left > 0) {
        ssize_t r = ::pread(fd, dst + off, (size_t)left, base + off);
        if (r <= 0) return false;
        left -= r;
        off += r;
    }
    return true;
}

bool write_all(const char* path, const void* buf, int64_t nbytes, bool use_direct,
               bool* fell_back = nullptr) {
    const char* src = (const char*)buf;
#ifdef O_DIRECT
    if (use_direct && nbytes < kAlign && fell_back) *fell_back = true;  // sub-sector: buffered
    if (use_direct && nbytes >= kAlign) {
        int dfd = ::open(path, O_WRONLY | O_CREAT | O_TRUNC | O_DIRECT, 0644);
        if (dfd >= 0) {
            void* bounce = nullptr;
            if (posix_memalign(&bounce, (size_t)kAlign, (size_t)kBounce) != 0) {
                ::close(dfd);
                return false;
            }
            int64_t aligned = (nbytes / kAlign) * kAlign;
            bool ok = true;
            off_t off = 0;
            while (ok && off < aligned) {
                int64_t n = std::min<int64_t>(kBounce, aligned - off);
                std::memcpy(bounce, src + off, (size_t)n);
                // short direct writes are legal POSIX; retry while the next
                // offset stays sector-aligned, else finish buffered below
                int64_t done = 0;
                while (done < n) {
                    ssize_t w = ::pwrite(dfd, (char*)bounce + done, (size_t)(n - done), off + done);
                    if (w <= 0) { ok = false; break; }
                    done += w;
                    if (done < n && (done % kAlign) != 0) break;  // unaligned resume
                }
                off += done;
                if (ok && done < n) break;  // aligned prefix written; tail goes buffered
            }
            ::close(dfd);
            free(bounce);
            if (!ok) return false;
            if (off < nbytes) {  // remainder (unaligned tail or short-write rest)
                int fd = ::open(path, O_WRONLY, 0644);
                if (fd < 0) return false;
                bool tail_ok = write_all_buffered(fd, src + off, nbytes - off, off);
                if (tail_ok) ::fsync(fd);
                ::close(fd);
                return tail_ok;
            }
            return true;
        }
        // open with O_DIRECT failed (e.g. tmpfs): buffered fallback below
        if (fell_back) *fell_back = true;
    }
#else
    (void)use_direct;
    if (fell_back) *fell_back = use_direct;
#endif
    int fd = ::open(path, O_WRONLY | O_CREAT | O_TRUNC, 0644);
    if (fd < 0) return false;
    bool ok = write_all_buffered(fd, src, nbytes, 0);
    ::close(fd);
    return ok;
}

bool read_all(const char* path, void* buf, int64_t nbytes, bool use_direct,
              bool* fell_back = nullptr) {
    char* dst = (char*)buf;
#ifdef O_DIRECT
    if (use_direct && nbytes < kAlign && fell_back) *fell_back = true;  // sub-sector: buffered
    if (use_direct && nbytes >= kAlign) {
        int dfd = ::open(path, O_RDONLY | O_DIRECT);
        if (dfd >= 0) {
            void* bounce = nullptr;
            if (posix_memalign(&bounce, (size_t)kAlign, (size_t)kBounce) != 0) {
                ::close(dfd);
                return false;
            }
            int64_t aligned = (nbytes / kAlign) * kAlign;
            bool ok = true;
            off_t off = 0;
            while (ok && off < aligned) {
                int64_t n = std::min<int64_t>(kBounce, aligned - off);
                int64_t done = 0;
                while (done < n) {  // short direct reads are legal; retry aligned
                    ssize_t r = ::pread(dfd, (char*)bounce + done, (size_t)(n - done), off + done);
                    if (r <= 0) { ok = false; break; }
                    done += r;
                    if (done < n && (done % kAlign) != 0) break;
                }
                if (done > 0) std::memcpy(dst + off, bounce, (size_t)done);
                off += done;
                if (ok && done < n) break;  // rest goes buffered
            }
            ::close(dfd);
            free(bounce);
            if (!ok) return false;
            if (off < nbytes) {  // remainder via buffered descriptor
                int fd = ::open(path, O_RDONLY);
                if (fd < 0) return false;
                bool tail_ok = read_all_buffered(fd, dst + off, nbytes - off, off);
                ::close(fd);
                return tail_ok;
            }
            return true;
        }
        // open with O_DIRECT failed (e.g. tmpfs): buffered fallback below
        if (fell_back) *fell_back = true;
    }
#else
    (void)use_direct;
    if (fell_back) *fell_back = use_direct;
#endif
    int fd = ::open(path, O_RDONLY);
    if (fd < 0) return false;
    bool ok = read_all_buffered(fd, dst, nbytes, 0);
    ::close(fd);
    return ok;
}

}  // namespace

extern "C" {

void* aio_handle_create(int n_threads) {
    if (n_threads < 1) n_threads = 1;
    return new AioHandle(n_threads);
}

// reference aio_config single_submit/overlap_events knobs are owned by the
// pool; use_direct selects the page-cache-bypassing path
void* aio_handle_create2(int n_threads, int use_direct) {
    if (n_threads < 1) n_threads = 1;
    auto* h = new AioHandle(n_threads);
    h->direct = use_direct != 0;
    return h;
}

void aio_handle_destroy(void* h) { delete (AioHandle*)h; }

// async write of nbytes from buf to path (buf must stay alive until wait)
void aio_pwrite_async(void* h, const char* path, const void* buf, int64_t nbytes) {
    auto* handle = (AioHandle*)h;
    std::string p(path);
    handle->submit([handle, p, buf, nbytes] {
        bool fb = false;
        if (!write_all(p.c_str(), buf, nbytes, handle->direct, &fb)) ++handle->errors;
        if (fb) ++handle->direct_fallbacks;
    });
}

// async read of nbytes from path into buf (buf must stay alive until wait)
void aio_pread_async(void* h, const char* path, void* buf, int64_t nbytes) {
    auto* handle = (AioHandle*)h;
    std::string p(path);
    handle->submit([handle, p, buf, nbytes] {
        bool fb = false;
        if (!read_all(p.c_str(), buf, nbytes, handle->direct, &fb)) ++handle->errors;
        if (fb) ++handle->direct_fallbacks;
    });
}

// block until every submitted op completes; returns the number of failed ops
// since the last wait
int aio_wait(void* h) { return ((AioHandle*)h)->wait(); }

// direct-requested ops that silently ran buffered (tmpfs etc.) since create
int64_t aio_direct_fallbacks(void* h) { return ((AioHandle*)h)->direct_fallbacks.load(); }

// synchronous helpers (reference deepspeed_py_aio.cpp sync paths)
int aio_write_sync(const char* path, const void* buf, int64_t nbytes) {
    return write_all(path, buf, nbytes, false) ? 0 : -1;
}

int aio_read_sync(const char* path, void* buf, int64_t nbytes) {
    return read_all(path, buf, nbytes, false) ? 0 : -1;
}

}  // extern "C"
