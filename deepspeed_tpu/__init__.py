"""deepspeed_tpu: a TPU-native large-model training & inference framework.

Capability parity with DeepSpeed v0.10.1 (see SURVEY.md), built on
JAX/XLA/Pallas: sharding-spec ZeRO over a device mesh instead of runtime
hooks, `jax.lax` collectives over ICI/DCN instead of NCCL, Pallas kernels
instead of CUDA.

Public surface mirrors the reference (``deepspeed/__init__.py``):
``initialize`` (:64), ``init_inference`` (:269), ``comm``, ``zero``,
``add_config_arguments`` (:246).
"""

from deepspeed_tpu.version import __version__, __capability_parity__

# installs jax.shard_map / lax.axis_size shims on older jax runtimes so
# every call site can use the modern spelling
from deepspeed_tpu.utils import jax_compat as _jax_compat  # noqa: F401

from deepspeed_tpu.utils.logging import logger, log_dist
from deepspeed_tpu import comm

__git_hash__ = None
__git_branch__ = None
git_hash = None
git_branch = None
# reference parity: deepspeed.version is the version STRING (its module
# form lives at git_version_info) — this intentionally shadows attribute
# access to the version.py submodule; import it via
# `from deepspeed_tpu.version import ...` (unaffected)
version = __version__
import re as _re

_m = _re.match(r"(\d+)\.(\d+)\.(\d+)", __version__)
__version_major__, __version_minor__, __version_patch__ = (
    (int(_m.group(1)), int(_m.group(2)), int(_m.group(3))) if _m else (0, 0, 0))
HAS_TRITON = False  # reference flag (Triton kernels; TPU uses Pallas)

# typing aliases (reference runtime/engine.py DeepSpeedOptimizerCallable /
# DeepSpeedSchedulerCallable: factories receiving params / optimizer)
from typing import Any as _Any, Callable as _Callable

DeepSpeedOptimizerCallable = _Callable[..., _Any]
DeepSpeedSchedulerCallable = _Callable[..., _Any]

_LAZY = {
    "initialize": ("deepspeed_tpu.runtime.entry", "initialize"),
    "init_inference": ("deepspeed_tpu.inference.entry", "init_inference"),
    "add_config_arguments": ("deepspeed_tpu.runtime.entry", "add_config_arguments"),
    "zero": ("deepspeed_tpu.runtime.zero", None),
    "DeepSpeedEngine": ("deepspeed_tpu.runtime.engine", "DeepSpeedEngine"),
    "DeepSpeedConfig": ("deepspeed_tpu.runtime.config", "DeepSpeedConfig"),
    "DeepSpeedConfigError": ("deepspeed_tpu.runtime.config", "DeepSpeedConfigError"),
    "DeepSpeedHybridEngine": ("deepspeed_tpu.runtime.hybrid_engine", "DeepSpeedHybridEngine"),
    "PipelineEngine": ("deepspeed_tpu.runtime.pipe.engine", "PipelineEngine"),
    "PipelineModule": ("deepspeed_tpu.runtime.pipe.module", "PipelineModule"),
    "InferenceEngine": ("deepspeed_tpu.inference.engine", "InferenceEngine"),
    "DeepSpeedInferenceConfig": ("deepspeed_tpu.inference.config", "DeepSpeedInferenceConfig"),
    "DeepSpeedTransformerLayer": ("deepspeed_tpu.ops.transformer", "DeepSpeedTransformerLayer"),
    "DeepSpeedTransformerConfig": ("deepspeed_tpu.ops.transformer", "DeepSpeedTransformerConfig"),
    "checkpointing": ("deepspeed_tpu.runtime.activation_checkpointing.checkpointing", None),
    "get_accelerator": ("deepspeed_tpu.accelerator", "get_accelerator"),
    "init_distributed": ("deepspeed_tpu.comm.comm", "init_distributed"),
    "OnDevice": ("deepspeed_tpu.utils.memory", "OnDevice"),
    "module_inject": ("deepspeed_tpu.module_inject", None),
    "ops": ("deepspeed_tpu.ops", None),
    "moe": ("deepspeed_tpu.moe", None),
    "pipe": ("deepspeed_tpu.pipe", None),
    "runtime": ("deepspeed_tpu.runtime", None),
    "DeepSpeedOptimizer": ("deepspeed_tpu.runtime", "DeepSpeedOptimizer"),
    "ZeROOptimizer": ("deepspeed_tpu.runtime", "ZeROOptimizer"),
    "ADAM_OPTIMIZER": ("deepspeed_tpu.runtime.constants", "ADAM_OPTIMIZER"),
    "LAMB_OPTIMIZER": ("deepspeed_tpu.runtime.constants", "LAMB_OPTIMIZER"),
    "add_tuning_arguments": ("deepspeed_tpu.runtime.lr_schedules", "add_tuning_arguments"),
    "replace_transformer_layer": ("deepspeed_tpu.module_inject.replace_module",
                                  "replace_transformer_layer"),
    "revert_transformer_layer": ("deepspeed_tpu.module_inject.replace_module",
                                 "revert_transformer_layer"),
}


def __getattr__(name):
    if name in _LAZY:
        import importlib

        mod_name, attr = _LAZY[name]
        try:
            mod = importlib.import_module(mod_name)
            obj = mod if attr is None else getattr(mod, attr)
        except (ImportError, AttributeError) as e:
            # keep hasattr() semantics sane for not-yet-built components
            raise AttributeError(f"deepspeed_tpu.{name} is not available: {e}") from e
        globals()[name] = obj
        return obj
    raise AttributeError(f"module 'deepspeed_tpu' has no attribute {name!r}")


def __dir__():
    # PEP 562: keep dir()/tab-completion aware of the lazy exports
    return sorted(set(globals()) | set(_LAZY))
