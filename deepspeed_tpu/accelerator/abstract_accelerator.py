"""Accelerator abstraction (reference
``accelerator/abstract_accelerator.py`` ``DeepSpeedAccelerator`` ABC).

The seam that lets runtime code ask device questions without naming a
backend. Torch-tensor constructors and CUDA stream/event surface collapse
on TPU — XLA owns streams and JAX owns dtypes — so those reference methods
map to their JAX equivalents (``synchronize`` = block_until_ready of a
token; RNG = seeded ``jax.random`` keys) or honest no-ops with documented
semantics.
"""

import abc
from typing import Optional


class DeepSpeedAccelerator(abc.ABC):
    """Subset of the reference ABC that has TPU meaning; names kept
    identical so runtime code ports."""

    def __init__(self):
        self._name: Optional[str] = None
        self._communication_backend_name: Optional[str] = None

    # -- device ---------------------------------------------------------
    @abc.abstractmethod
    def is_synchronized_device(self) -> bool: ...

    @abc.abstractmethod
    def device_name(self, device_index: Optional[int] = None) -> str: ...

    @abc.abstractmethod
    def device(self, device_index: Optional[int] = None): ...

    @abc.abstractmethod
    def set_device(self, device_index: int) -> None: ...

    @abc.abstractmethod
    def current_device(self) -> int: ...

    @abc.abstractmethod
    def current_device_name(self) -> str: ...

    @abc.abstractmethod
    def device_count(self) -> int: ...

    @abc.abstractmethod
    def synchronize(self, device_index: Optional[int] = None) -> None: ...

    # -- RNG ------------------------------------------------------------
    @abc.abstractmethod
    def manual_seed(self, seed: int) -> None: ...

    @abc.abstractmethod
    def manual_seed_all(self, seed: int) -> None: ...

    @abc.abstractmethod
    def initial_seed(self) -> int: ...

    @abc.abstractmethod
    def get_rng_state(self, device_index: Optional[int] = None): ...

    @abc.abstractmethod
    def set_rng_state(self, new_state, device_index: Optional[int] = None) -> None: ...

    # -- memory ---------------------------------------------------------
    @abc.abstractmethod
    def empty_cache(self) -> None: ...

    @abc.abstractmethod
    def memory_allocated(self, device_index: Optional[int] = None) -> int: ...

    @abc.abstractmethod
    def max_memory_allocated(self, device_index: Optional[int] = None) -> int: ...

    @abc.abstractmethod
    def memory_stats(self, device_index: Optional[int] = None) -> dict: ...

    @abc.abstractmethod
    def total_memory(self, device_index: Optional[int] = None) -> int: ...

    @abc.abstractmethod
    def available_memory(self, device_index: Optional[int] = None) -> int: ...

    # -- dtype / capability ---------------------------------------------
    @abc.abstractmethod
    def is_bf16_supported(self) -> bool: ...

    @abc.abstractmethod
    def is_fp16_supported(self) -> bool: ...

    @abc.abstractmethod
    def supported_dtypes(self) -> list: ...

    @abc.abstractmethod
    def is_available(self) -> bool: ...

    @abc.abstractmethod
    def communication_backend_name(self) -> str: ...

    # -- data movement ---------------------------------------------------
    @abc.abstractmethod
    def pin_memory(self, array): ...

    @abc.abstractmethod
    def on_accelerator(self, array) -> bool: ...

    # -- op builders ------------------------------------------------------
    @abc.abstractmethod
    def op_builder_dir(self) -> str: ...

    @abc.abstractmethod
    def create_op_builder(self, class_name: str): ...

    @abc.abstractmethod
    def get_op_builder(self, class_name: str): ...

    # -- CUDA-vocabulary surface with shared TPU semantics ----------------
    # (reference abstract_accelerator.py:118-177 streams/events, :178-190
    # graph/amp hooks — XLA owns scheduling, so these are honest
    # immediates/no-ops rather than unimplemented holes)

    class _NullStream:
        """XLA orders execution itself; a stream is a no-op context."""

        def __enter__(self):
            return self

        def __exit__(self, *exc):
            return False

        def synchronize(self):
            return None

        def wait_stream(self, other):
            return None

    class _Event:
        """Host-clock event (reference CUDA events time device work; on
        TPU wall-clock around ``block_until_ready`` is the analog — the
        engine's timers do exactly that, ``utils/timer.py``)."""

        def __init__(self, enable_timing: bool = False, **_):
            self._t = None

        def record(self, stream=None):
            import time
            self._t = time.perf_counter()

        def synchronize(self):
            return None

        def query(self):
            return True

        def elapsed_time(self, end) -> float:
            return (end._t - self._t) * 1e3

    def Stream(self, *args, **kwargs):
        return DeepSpeedAccelerator._NullStream()

    def stream(self, stream_obj):
        return stream_obj if hasattr(stream_obj, "__enter__") else self.Stream()

    def current_stream(self, device_index: Optional[int] = None):
        return DeepSpeedAccelerator._NullStream()

    def default_stream(self, device_index: Optional[int] = None):
        return DeepSpeedAccelerator._NullStream()

    def Event(self, enable_timing: bool = False, **kwargs):
        return DeepSpeedAccelerator._Event(enable_timing=enable_timing, **kwargs)

    def random(self):
        """The RNG module handle (reference returns ``torch.random``)."""
        import jax

        return jax.random

    def default_generator(self, device_index: Optional[int] = None):
        """A seeded PRNG key stands in for torch's Generator."""
        import jax

        return jax.random.PRNGKey(self.initial_seed())

    def reset_peak_memory_stats(self, device_index: Optional[int] = None) -> None:
        return None  # peaks come from memory_stats() snapshots

    def memory_reserved(self, device_index: Optional[int] = None) -> int:
        return self.memory_allocated(device_index)

    def max_memory_reserved(self, device_index: Optional[int] = None) -> int:
        return self.max_memory_allocated(device_index)

    def amp(self):
        """Mixed precision is config-driven (bf16/fp16 blocks), not an
        autocast context — the reference returns ``torch.cuda.amp``."""
        return None

    def lazy_call(self, callback):
        """Reference defers one-time CUDA init; jit tracing gives laziness
        for free, so the callback runs now."""
        return callback()

    def is_triton_supported(self) -> bool:
        return False  # Pallas is the kernel DSL on TPU

    def build_extension(self):
        """torch.cpp_extension hook; our C++ goes through the ctypes op
        builders (``ops/op_builder``)."""
        from deepspeed_tpu.ops import op_builder

        return op_builder

    def export_envs(self) -> list:
        """Env vars the launcher forwards to workers (reference lists
        NCCL/PYTHONPATH prefixes)."""
        return ["JAX", "XLA", "LIBTPU", "TPU", "PYTHON"]

    def is_pinned(self, array) -> bool:
        """Host numpy buffers are always directly DMA-able by the runtime;
        there is no separate pinned pool to test membership of."""
        return True
