"""Accelerator abstraction (reference
``accelerator/abstract_accelerator.py`` ``DeepSpeedAccelerator`` ABC).

The seam that lets runtime code ask device questions without naming a
backend. Torch-tensor constructors and CUDA stream/event surface collapse
on TPU — XLA owns streams and JAX owns dtypes — so those reference methods
map to their JAX equivalents (``synchronize`` = block_until_ready of a
token; RNG = seeded ``jax.random`` keys) or honest no-ops with documented
semantics.
"""

import abc
from typing import Optional


class DeepSpeedAccelerator(abc.ABC):
    """Subset of the reference ABC that has TPU meaning; names kept
    identical so runtime code ports."""

    def __init__(self):
        self._name: Optional[str] = None
        self._communication_backend_name: Optional[str] = None

    # -- device ---------------------------------------------------------
    @abc.abstractmethod
    def is_synchronized_device(self) -> bool: ...

    @abc.abstractmethod
    def device_name(self, device_index: Optional[int] = None) -> str: ...

    @abc.abstractmethod
    def device(self, device_index: Optional[int] = None): ...

    @abc.abstractmethod
    def set_device(self, device_index: int) -> None: ...

    @abc.abstractmethod
    def current_device(self) -> int: ...

    @abc.abstractmethod
    def current_device_name(self) -> str: ...

    @abc.abstractmethod
    def device_count(self) -> int: ...

    @abc.abstractmethod
    def synchronize(self, device_index: Optional[int] = None) -> None: ...

    # -- RNG ------------------------------------------------------------
    @abc.abstractmethod
    def manual_seed(self, seed: int) -> None: ...

    @abc.abstractmethod
    def manual_seed_all(self, seed: int) -> None: ...

    @abc.abstractmethod
    def initial_seed(self) -> int: ...

    @abc.abstractmethod
    def get_rng_state(self, device_index: Optional[int] = None): ...

    @abc.abstractmethod
    def set_rng_state(self, new_state, device_index: Optional[int] = None) -> None: ...

    # -- memory ---------------------------------------------------------
    @abc.abstractmethod
    def empty_cache(self) -> None: ...

    @abc.abstractmethod
    def memory_allocated(self, device_index: Optional[int] = None) -> int: ...

    @abc.abstractmethod
    def max_memory_allocated(self, device_index: Optional[int] = None) -> int: ...

    @abc.abstractmethod
    def memory_stats(self, device_index: Optional[int] = None) -> dict: ...

    @abc.abstractmethod
    def total_memory(self, device_index: Optional[int] = None) -> int: ...

    @abc.abstractmethod
    def available_memory(self, device_index: Optional[int] = None) -> int: ...

    # -- dtype / capability ---------------------------------------------
    @abc.abstractmethod
    def is_bf16_supported(self) -> bool: ...

    @abc.abstractmethod
    def is_fp16_supported(self) -> bool: ...

    @abc.abstractmethod
    def supported_dtypes(self) -> list: ...

    @abc.abstractmethod
    def is_available(self) -> bool: ...

    @abc.abstractmethod
    def communication_backend_name(self) -> str: ...

    # -- data movement ---------------------------------------------------
    @abc.abstractmethod
    def pin_memory(self, array): ...

    @abc.abstractmethod
    def on_accelerator(self, array) -> bool: ...

    # -- op builders ------------------------------------------------------
    @abc.abstractmethod
    def op_builder_dir(self) -> str: ...

    @abc.abstractmethod
    def create_op_builder(self, class_name: str): ...

    @abc.abstractmethod
    def get_op_builder(self, class_name: str): ...
