"""CPU accelerator (reference ``accelerator/cpu_accelerator.py``): the
development/CI backend — same seam over JAX's CPU platform."""

from typing import Optional

import numpy as np

import jax
import jax.numpy as jnp

from deepspeed_tpu.accelerator.tpu_accelerator import TPU_Accelerator


class CPU_Accelerator(TPU_Accelerator):
    """JAX-CPU flavor: memory stats come from psutil when the backend
    reports none; collectives ride XLA's host transport (gloo analog)."""

    def __init__(self):
        super().__init__()
        self._name = "cpu"
        self._communication_backend_name = "xla-cpu"

    def _devices(self):
        try:
            return jax.devices("cpu")
        except RuntimeError:
            return jax.devices()

    def device_name(self, device_index: Optional[int] = None) -> str:
        return "cpu" if device_index is None else f"cpu:{device_index}"

    def device_count(self) -> int:
        return len(self._devices())

    def is_available(self) -> bool:
        return True

    def is_bf16_supported(self) -> bool:
        return True

    def is_fp16_supported(self) -> bool:
        return True

    def _stats(self, device_index):
        stats = super()._stats(device_index)
        if stats:
            return stats
        try:
            import psutil
            vm = psutil.virtual_memory()
            return {"bytes_limit": int(vm.total), "bytes_in_use": int(vm.used),
                    "peak_bytes_in_use": int(vm.used)}
        except Exception:
            return {}

    def on_accelerator(self, array) -> bool:
        try:
            return all(getattr(d, "platform", "") == "cpu" for d in array.devices())
        except AttributeError:
            return False
