"""Accelerator selection (reference ``accelerator/real_accelerator.py:45``
``get_accelerator``): ``DS_ACCELERATOR`` env override, then auto-detect —
TPU when a TPU-class backend is live, CPU otherwise."""

import os
from typing import Optional

from deepspeed_tpu.accelerator.abstract_accelerator import DeepSpeedAccelerator
from deepspeed_tpu.utils.logging import logger

DS_ACCELERATOR_LIST = ["tpu", "cpu"]

_accelerator: Optional[DeepSpeedAccelerator] = None


def _detect() -> str:
    try:
        import jax
        for d in jax.devices():
            if d.platform == "tpu" or "TPU" in getattr(d, "device_kind", ""):
                return "tpu"
    except Exception:
        pass
    return "cpu"


def get_accelerator() -> DeepSpeedAccelerator:
    global _accelerator
    if _accelerator is not None:
        return _accelerator
    name = os.environ.get("DS_ACCELERATOR")
    if name is not None:
        name = name.lower()
        if name not in DS_ACCELERATOR_LIST:
            raise ValueError(f"DS_ACCELERATOR={name!r} not supported; "
                             f"choose from {DS_ACCELERATOR_LIST}")
    else:
        name = _detect()
    if name == "tpu":
        from deepspeed_tpu.accelerator.tpu_accelerator import TPU_Accelerator
        _accelerator = TPU_Accelerator()
    else:
        from deepspeed_tpu.accelerator.cpu_accelerator import CPU_Accelerator
        _accelerator = CPU_Accelerator()
    logger.info(f"accelerator selected: {_accelerator._name} "
                f"({'env override' if os.environ.get('DS_ACCELERATOR') else 'auto-detected'})")
    return _accelerator


def set_accelerator(accel: DeepSpeedAccelerator) -> None:
    """(reference ``set_accelerator``) — install an explicit accelerator."""
    global _accelerator
    _accelerator = accel


def is_current_accelerator_supported() -> bool:
    return get_accelerator()._name in DS_ACCELERATOR_LIST
