"""TPU accelerator (reference ``accelerator/cuda_accelerator.py``
``CUDA_Accelerator`` — same seam, JAX/TPU semantics)."""

from typing import Optional

import numpy as np

import jax
import jax.numpy as jnp

from deepspeed_tpu.accelerator.abstract_accelerator import DeepSpeedAccelerator


class TPU_Accelerator(DeepSpeedAccelerator):

    def __init__(self):
        super().__init__()
        self._name = "tpu"
        self._communication_backend_name = "xla"  # ICI/DCN collectives via XLA
        self._current = 0
        self._seed = 0

    # -- device ---------------------------------------------------------
    def is_synchronized_device(self) -> bool:
        return False  # dispatch is async; jax.block_until_ready syncs

    def _devices(self):
        return jax.devices()

    def device_name(self, device_index: Optional[int] = None) -> str:
        if device_index is None:
            return "tpu"
        return f"tpu:{device_index}"

    def device(self, device_index: Optional[int] = None):
        return self._devices()[device_index if device_index is not None else self._current]

    def set_device(self, device_index: int) -> None:
        self._current = int(device_index)

    def current_device(self) -> int:
        return self._current

    def current_device_name(self) -> str:
        return self.device_name(self._current)

    def device_count(self) -> int:
        return jax.device_count()

    def synchronize(self, device_index: Optional[int] = None) -> None:
        # a tiny computation fenced to completion orders everything before it
        jax.block_until_ready(jnp.zeros((), jnp.float32))

    # -- RNG (the JAX model: explicit keys derived from one seed) --------
    def manual_seed(self, seed: int) -> None:
        self._seed = int(seed)

    manual_seed_all = manual_seed

    def initial_seed(self) -> int:
        return self._seed

    def get_rng_state(self, device_index: Optional[int] = None):
        return np.asarray(jax.random.PRNGKey(self._seed))

    def set_rng_state(self, new_state, device_index: Optional[int] = None) -> None:
        # a PRNGKey array: recover the seed fold (best effort — the JAX
        # model derives all randomness from keys the caller threads)
        self._seed = int(np.asarray(new_state).reshape(-1)[-1])

    # -- memory ---------------------------------------------------------
    def empty_cache(self) -> None:
        # XLA owns the arena; deleting unreachable buffers is the GC's job
        import gc
        gc.collect()

    def _stats(self, device_index):
        d = self.device(device_index)
        return getattr(d, "memory_stats", lambda: None)() or {}

    def memory_allocated(self, device_index: Optional[int] = None) -> int:
        return int(self._stats(device_index).get("bytes_in_use", 0))

    def max_memory_allocated(self, device_index: Optional[int] = None) -> int:
        return int(self._stats(device_index).get("peak_bytes_in_use",
                                                 self.memory_allocated(device_index)))

    def memory_stats(self, device_index: Optional[int] = None) -> dict:
        return dict(self._stats(device_index))

    def total_memory(self, device_index: Optional[int] = None) -> int:
        return int(self._stats(device_index).get("bytes_limit", 0))

    def available_memory(self, device_index: Optional[int] = None) -> int:
        s = self._stats(device_index)
        return int(s.get("bytes_limit", 0)) - int(s.get("bytes_in_use", 0))

    # -- dtype / capability ---------------------------------------------
    def is_bf16_supported(self) -> bool:
        return True

    def is_fp16_supported(self) -> bool:
        return True  # storage supported; bf16 is the native compute type

    def supported_dtypes(self):
        return [jnp.float32, jnp.bfloat16, jnp.float16, jnp.int8, jnp.int32]

    def is_available(self) -> bool:
        try:
            return any(d.platform in ("tpu",) or "TPU" in getattr(d, "device_kind", "")
                       for d in jax.devices())
        except Exception:
            return False

    def communication_backend_name(self) -> str:
        return self._communication_backend_name

    # -- data movement ---------------------------------------------------
    def pin_memory(self, array):
        # host staging buffers: contiguity is what matters for DMA
        return np.ascontiguousarray(array)

    def on_accelerator(self, array) -> bool:
        try:
            return any(getattr(d, "platform", "") != "cpu"
                       for d in array.devices())
        except AttributeError:
            return False

    # -- op builders ------------------------------------------------------
    def op_builder_dir(self) -> str:
        return "deepspeed_tpu.ops.op_builder"

    def create_op_builder(self, class_name: str):
        cls = self.get_op_builder(class_name)
        return cls() if cls is not None else None

    def get_op_builder(self, class_name: str):
        import deepspeed_tpu.ops.op_builder as ob
        return getattr(ob, class_name, None) or ob.ALL_BUILDERS.get(class_name)
