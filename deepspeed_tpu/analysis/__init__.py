"""graft-lint: rule-based static analysis over traced programs.

Walks closed jaxprs (recursing into pjit/scan/remat/custom_vjp
sub-jaxprs) and lowered StableHLO, plus a source-level AST pass, against
a registry of named rules (R001..R008) that encode this repo's
perf/determinism invariants — dense-MoE-route absence, precision
hygiene on the parity path, no host transfers in jitted steps, donation
hygiene, recompile hazards, sharding coverage, owned_device_put. CLI:
``tools/graft_lint.py``; scenario matrix: :mod:`.scenarios`; gate
semantics: :mod:`.report`.

Quick in-test usage (what tests/unit/moe/test_moe_routing.py's R001
migration calls)::

    from deepspeed_tpu.analysis import check_program
    findings = check_program(jaxpr, rules=["R001"],
                             metadata={"moe_sec": [(S, E, C)]})
"""

from deepspeed_tpu.analysis.core import (ERROR, INFO, RULES, WARN, Finding, Rule, Waiver,
                                         apply_waivers, ast_rules, cost_rules,
                                         load_waivers, program_rules)
from deepspeed_tpu.analysis.program import (ProgramAnalyzer, ProgramInfo, aval_bytes,
                                            run_program_rules)
from deepspeed_tpu.analysis import rules as _rules  # noqa: F401 — registers R001-R007
from deepspeed_tpu.analysis import source_rules as _source_rules  # noqa: F401 — registers R008
from deepspeed_tpu.analysis.memory import MemoryEstimate, estimate_memory
from deepspeed_tpu.analysis.cost import (CostInfo, build_cost, cost_baseline_from,
                                         cost_engine_program, load_cost_baseline,
                                         r013_cost_ratchet, run_cost_rules,
                                         static_price_from_jaxpr,
                                         static_price_from_programs)  # registers R009-R013
from deepspeed_tpu.analysis.search import (SPACES, Candidate, SearchSpace,
                                           enumerate_candidates, flops_proxy,
                                           gate_space_names, load_search_artifact,
                                           pareto, price_candidate,
                                           r014_search_frontier, run_space,
                                           search_artifact_from,
                                           verify_spaces)  # registers R014
from deepspeed_tpu.analysis.calibrate import (CalibrationError, calibrated_seconds,
                                              calibration_entry, calibration_from,
                                              collect_samples,
                                              default_calibration_path, fit_entry,
                                              fit_groups, load_calibration,
                                              naive_seconds, r016_calibration_drift,
                                              residual_summary,
                                              verify_calibration)  # registers R016
from deepspeed_tpu.analysis.report import (baseline_from, build_report, load_baseline,
                                           matrix_signature, new_errors, rules_markdown,
                                           summarize, write_report)

__all__ = [
    "ERROR", "WARN", "INFO", "RULES", "Finding", "Rule", "Waiver",
    "apply_waivers", "load_waivers", "program_rules", "ast_rules", "cost_rules",
    "ProgramAnalyzer", "ProgramInfo", "aval_bytes", "run_program_rules",
    "check_program", "lint_engine_program",
    "MemoryEstimate", "estimate_memory",
    "CostInfo", "build_cost", "run_cost_rules", "r013_cost_ratchet",
    "load_cost_baseline", "cost_baseline_from", "cost_engine_program",
    "static_price_from_jaxpr", "static_price_from_programs",
    "SPACES", "Candidate", "SearchSpace", "enumerate_candidates", "flops_proxy",
    "gate_space_names", "load_search_artifact", "pareto", "price_candidate",
    "r014_search_frontier", "run_space", "search_artifact_from", "verify_spaces",
    "CalibrationError", "calibrated_seconds", "calibration_entry",
    "calibration_from", "collect_samples", "default_calibration_path",
    "fit_entry", "fit_groups", "load_calibration", "naive_seconds",
    "r016_calibration_drift", "residual_summary", "verify_calibration",
    "baseline_from", "build_report", "load_baseline", "matrix_signature",
    "new_errors", "rules_markdown", "summarize", "write_report",
]


def check_program(jaxpr=None, rules=None, metadata=None, name="adhoc",
                  hlo_text=None, kind="fwd_bwd"):
    """One-call rule check over a single traced program — the in-test
    entry point. Returns the findings list (empty == clean)."""
    info = ProgramInfo(name=name, jaxpr=jaxpr, hlo_text=hlo_text, kind=kind,
                       metadata=metadata)
    findings, _ = run_program_rules(info, rules=rules)
    return findings


def _repo_waivers():
    """The repo's program-layer waivers (analysis_results/waivers.json),
    shared between the CLI and lint_engine_program so ladder evidence rows
    never disagree with the gate about what is acknowledged."""
    import json
    import os
    path = os.path.join(os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__)))), "analysis_results", "waivers.json")
    if not os.path.exists(path):
        return []
    with open(path) as fh:
        return load_waivers(json.load(fh))


def lint_engine_program(engine, example_batch, rules=None, programs=None):
    """Analyze a live engine's traced step program and return the compact
    evidence summary perf_ladder embeds in its rows: rule hit counts,
    waiver count, error count, clean flag. Chip-window rows carry this so
    a banked TFLOPS number provably came from a lint-clean program.
    Applies the repo's waivers.json — the row must agree with the gate.
    Pass ``programs`` (a prior ``engine.traced_programs`` result) to
    share one trace with the cost evidence instead of re-tracing."""
    programs = programs or engine.traced_programs(example_batch)
    step = programs["train_step"]
    info = ProgramInfo(name="engine_train_step", jaxpr=step["jaxpr"],
                       hlo_text=step["hlo_text"], kind="train_step",
                       metadata=step["metadata"])
    findings, _ = run_program_rules(info, rules=rules)
    apply_waivers(findings, _repo_waivers())
    s = summarize(findings)
    return {"lint_rule_hits": s["rule_hits"], "lint_waived": s["waived"],
            "lint_errors": s["errors"], "lint_clean": s["clean"]}
