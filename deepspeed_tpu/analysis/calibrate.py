"""graft-calibrate: fit the static cost model against measured telemetry.

PR 12 prices every program statically (``flops_proxy``, ``bytes_moved``,
liveness bytes — proxy units) and PR 13 measures the same programs at run
time (``drift`` events: median step seconds vs the run header's stamped
static price). Until now nobody read the drift tables back: graft-search
ranked candidates in proxy units that cannot trade compute against
memory traffic, and the predicted-vs-measured loop ended at a printout
(ROADMAP item 2). This module closes it — the reference autotuner's
*measured mode* (``/root/reference/deepspeed/autotuning/``), built on
telemetry the repo already accumulates instead of burning chip minutes:

1. **Collect** — :func:`collect_samples` walks accumulated graft-trace
   JSONL runs (or the machine-readable drift sidecars
   ``tools/trace_report.py --drift`` writes): one sample per drift
   window, ``x = (flops_proxy, bytes_moved)`` from the run header's
   static price, ``y = median_step_s`` measured, grouped per
   ``(backend, scope)`` — training steps and graft-fleet serving ticks
   calibrate side by side (the worker stamps ``scope: serve_decode``).
   Each run's FIRST window is dropped when more follow (it absorbs the
   compile); a single-window run keeps its only evidence.

2. **Fit** — :func:`fit_entry`: per-group linear coefficients
   ``seconds = base_s + s_per_flop·flops_proxy + s_per_byte·bytes_moved``
   by iteratively-reweighted (Huber) least squares — deterministic, pure
   numpy, no RNG — with non-negativity enforced by drop-and-refit, an
   all-zero feature recorded as *unidentifiable* (``None``, distinct
   from an identified ``0.0``), and loud :class:`CalibrationError`
   refusals for fewer-than-:data:`MIN_SAMPLES` or degenerate
   (constant-feature) inputs instead of extrapolating from one point.

3. **Commit** — ``analysis_results/cost_calibration.json`` (the
   ``search_pareto.json`` pattern: version pin, unknown-key rejection,
   merge semantics per entry; ``tools/graft_calibrate.py`` banks it).
   Every entry embeds its *training samples*, so the artifact is
   self-verifying: refitting the embedded samples must reproduce the
   committed coefficients byte-for-byte — a perturbed coefficient is
   caught hermetically, with no telemetry on disk.

4. **Gate** — rule **R016** extends the R014 ratchet: ERROR when the
   committed artifact is self-inconsistent (perturbed coefficients /
   residual evidence), when its jax signature no longer matches the
   interpreter, when fresh telemetry's residuals drift past tolerance
   under the committed coefficients, or when the committed search
   frontier's ``predicted_seconds`` re-rank is stale against the
   committed calibration (including a winner now *dominated* under
   calibrated seconds). Wired into full-matrix ``graft_lint --cost``
   next to R014; ``tools/graft_calibrate.py verify`` is the standalone
   entry (rc 1 on any ERROR).

``analysis/search.py`` cashes the artifact in: ``run_space(...,
calibration=...)`` appends a ``predicted_seconds`` objective priced
under the calibrated model and a ``seconds_rank`` over the frontier —
the total order in *seconds* the proxy objectives could not give, which
``tools/perf_ladder.py`` uses to order and stamp the ``350m_search_*``
rungs a chip window measures.
"""

import json
import math
import os
from typing import Any, Dict, Iterable, List, Optional, Tuple

import numpy as np

from deepspeed_tpu.analysis.core import ERROR, INFO, LAYER_COST, Finding, rule

CALIBRATION_VERSION = 1
#: R016 residual-drift tolerance: fresh telemetry's median |relative
#: error| under the committed coefficients may exceed the committed fit's
#: own residual level by at most this many error-fraction points
DEFAULT_RESIDUAL_TOLERANCE = 0.10
#: loud-refusal floor: a linear model with an intercept has no business
#: extrapolating from fewer points than this
MIN_SAMPLES = 4
#: fixed IRLS iteration budget — determinism over adaptive stopping
IRLS_ITERS = 8
_HUBER_K = 1.345
#: (price metric, coefficient name) in fixed fit order
FEATURES = (("flops_proxy", "s_per_flop"), ("bytes_moved", "s_per_byte"))
#: self-consistency slack for the hermetic refit check (float round-trip)
_REFIT_RTOL = 1e-9
_MAX_FINDINGS_PER_SCENARIO = 8

_ARTIFACT_TOP_KEYS = {"version", "tolerance", "jax_version", "entries"}
_ENTRY_KEYS = {"coeffs", "fit", "samples"}

#: the *uncalibrated* conversion R016's whole reason to exist replaces —
#: documented nominal peaks per backend, (FLOP/s, bytes/s):
#: one modern x86 core ~1e11 fp32 FLOP/s FMA peak / ~1e10 B/s sustained
#: stream; a TPU v4 chip 2.75e14 bf16 FLOP/s / 1.2e12 B/s HBM. PERF.md
#: §PR18 measures the calibrated model against exactly this baseline.
NAIVE_PEAKS: Dict[str, Tuple[float, float]] = {
    "cpu": (1.0e11, 1.0e10),
    "tpu": (2.75e14, 1.2e12),
}


class CalibrationError(ValueError):
    """A fit refused: too few samples, or degenerate inputs a linear
    model must not extrapolate from. Loud by contract."""


# ---------------------------------------------------------------------------
# artifact IO (merge semantics, the search_pareto.json pattern)
# ---------------------------------------------------------------------------
def default_calibration_path() -> str:
    repo = os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    return os.path.join(repo, "analysis_results", "cost_calibration.json")


def load_calibration(path: Optional[str] = None) -> Dict:
    path = path or default_calibration_path()
    if not os.path.exists(path):
        return {"version": CALIBRATION_VERSION,
                "tolerance": DEFAULT_RESIDUAL_TOLERANCE, "entries": {}}
    with open(path) as fh:
        artifact = json.load(fh)
    if artifact.get("version") != CALIBRATION_VERSION:
        raise ValueError(f"calibration artifact {path} has version "
                         f"{artifact.get('version')}, expected "
                         f"{CALIBRATION_VERSION} — regenerate with "
                         f"tools/graft_calibrate.py fit --update")
    unknown = set(artifact) - _ARTIFACT_TOP_KEYS
    if unknown:
        raise ValueError(f"calibration artifact {path} has unknown top-level "
                         f"keys {sorted(unknown)}")
    for key, entry in artifact.get("entries", {}).items():
        bad = set(entry) - _ENTRY_KEYS
        if bad:
            raise ValueError(f"calibration entry {key!r} has unknown keys "
                             f"{sorted(bad)} (valid: {sorted(_ENTRY_KEYS)})")
    artifact.setdefault("tolerance", DEFAULT_RESIDUAL_TOLERANCE)
    artifact.setdefault("entries", {})
    return artifact


def calibration_from(entries: Dict[str, dict],
                     prior: Optional[Dict] = None) -> Dict:
    """Bank fitted entries. MERGE semantics: refitting one (backend,
    scope) group never drops another's entry — dropping it would silently
    un-price every consumer of that scope."""
    import jax
    merged = dict((prior or {}).get("entries", {}))
    merged.update(entries)
    return {"version": CALIBRATION_VERSION,
            "tolerance": (prior or {}).get("tolerance",
                                           DEFAULT_RESIDUAL_TOLERANCE),
            "jax_version": jax.__version__,
            "entries": dict(sorted(merged.items()))}


def calibration_entry(calibration: Optional[Dict], backend: Optional[str] = None,
                      scope: str = "train_step") -> Tuple[Optional[dict], str]:
    """(entry or None, the ``<backend>/<scope>`` key looked up)."""
    if backend is None:
        import jax
        backend = jax.default_backend()
    key = f"{backend}/{scope}"
    return (calibration or {}).get("entries", {}).get(key), key


# ---------------------------------------------------------------------------
# the model
# ---------------------------------------------------------------------------
def calibrated_seconds(metrics: Dict, coeffs: Dict) -> Optional[float]:
    """Predicted wall seconds for one static price under fitted
    coefficients. ``None`` when the price exercises a feature the fit
    could not identify (coefficient ``None`` with a nonzero input) —
    unpriceable is an answer, a silently dropped term is not."""
    total = coeffs.get("base_s") or 0.0
    for feat, cname in FEATURES:
        x = metrics.get(feat) or 0
        if not x:
            continue
        c = coeffs.get(cname)
        if c is None:
            return None
        total += c * float(x)
    return total


def naive_seconds(metrics: Dict, backend: Optional[str] = None) -> Optional[float]:
    """The uncalibrated conversion (flops ÷ nominal peak FLOP/s + bytes ÷
    nominal peak B/s) — PERF.md §PR18's comparison baseline, never a
    consumer-facing prediction."""
    if backend is None:
        import jax
        backend = jax.default_backend()
    peaks = NAIVE_PEAKS.get(backend)
    if peaks is None:
        return None
    return (float(metrics.get("flops_proxy") or 0) / peaks[0]
            + float(metrics.get("bytes_moved") or 0) / peaks[1])


def residual_summary(samples: List[dict], coeffs: Dict) -> Dict[str, Any]:
    """Per-coefficient-set residual evidence over a sample set: the
    |relative error| distribution of the model's predictions."""
    errs = []
    unpriced = 0
    for s in samples:
        pred = calibrated_seconds(s, coeffs)
        y = s.get("measured_s")
        if pred is None or not y:
            unpriced += 1
            continue
        errs.append(abs(pred - y) / y)
    errs.sort()

    def pct(p):
        return errs[min(len(errs) - 1, int(math.ceil(p / 100.0 * len(errs))) - 1)]

    if not errs:
        return {"samples": 0, "unpriced": unpriced}
    return {"samples": len(errs), "unpriced": unpriced,
            "median_abs_rel_err": pct(50), "p90_abs_rel_err": pct(90),
            "max_abs_rel_err": errs[-1]}


# ---------------------------------------------------------------------------
# the fitter
# ---------------------------------------------------------------------------
def _irls(X: np.ndarray, y: np.ndarray, iters: int = IRLS_ITERS) -> np.ndarray:
    """Huber IRLS: plain least squares re-solved ``iters`` times with
    weights shrinking residuals past 1.345·MAD — a fixed iteration budget
    (not a convergence test) so two fits of the same data are bit-equal."""
    w = np.ones(len(y))
    beta = np.zeros(X.shape[1])
    for _ in range(max(1, iters)):
        beta, _, _, _ = np.linalg.lstsq(X * w[:, None], y * w, rcond=None)
        r = y - X @ beta
        scale = 1.4826 * float(np.median(np.abs(r)))
        if scale <= 0.0:
            break  # exact fit: weights would divide by zero, and can't improve
        a = np.abs(r) / (_HUBER_K * scale)
        w = np.sqrt(np.where(a <= 1.0, 1.0, 1.0 / a))
    return beta


def fit_entry(samples: List[dict], min_samples: int = MIN_SAMPLES) -> dict:
    """Fit one (backend, scope) group. Returns the committed-artifact
    entry: coefficients, residual evidence, and the samples themselves
    (the hermetic self-verification set R016 refits)."""
    samples = list(samples)
    if len(samples) < min_samples:
        raise CalibrationError(
            f"{len(samples)} sample(s) < minimum {min_samples} — refusing to "
            f"fit a 3-coefficient model; accumulate more telemetry windows")
    y = np.asarray([float(s["measured_s"]) for s in samples])
    if np.any(y <= 0):
        raise CalibrationError("non-positive measured_s in the sample set")
    coeffs: Dict[str, Optional[float]] = {"base_s": None}
    cols, names, scales = [], [], []
    for feat, cname in FEATURES:
        x = np.asarray([float(s.get(feat) or 0) for s in samples])
        if not np.any(x):
            coeffs[cname] = None  # unidentifiable: the data never moved it
            continue
        if np.ptp(x) == 0.0:
            raise CalibrationError(
                f"degenerate input: {feat} is constant ({x[0]:g}) across all "
                f"{len(samples)} samples — a slope fitted here would be pure "
                f"extrapolation; vary the workload (or fit intercept-only "
                f"telemetry under a different scope)")
        scale = float(np.max(x))
        cols.append(x / scale)
        names.append(cname)
        scales.append(scale)
        coeffs[cname] = 0.0
    # non-negativity by drop-and-refit, first negative in fixed column
    # order each round (deterministic): a negative seconds-per-flop is a
    # confounded fit, not a discount
    active = [True] * (1 + len(cols))  # [intercept] + feature columns
    beta_full = np.zeros(1 + len(cols))
    while True:
        X = np.column_stack(
            [np.ones(len(y)) if i == 0 else cols[i - 1]
             for i, on in enumerate(active) if on])
        if X.shape[1] == 0:
            break
        beta = _irls(X, y)
        beta_full = np.zeros(1 + len(cols))
        beta_full[[i for i, on in enumerate(active) if on]] = beta
        neg = next((i for i, on in enumerate(active)
                    if on and beta_full[i] < 0.0), None)
        if neg is None:
            break
        active[neg] = False
        beta_full[neg] = 0.0
    coeffs["base_s"] = float(beta_full[0])
    for j, cname in enumerate(names):
        coeffs[cname] = float(beta_full[1 + j] / scales[j])
    entry_samples = [_canonical_sample(s) for s in samples]
    fit = {"samples": len(samples),
           "features": names,
           "clamped": [n for i, n in enumerate(["base_s"] + names)
                       if not active[i]],
           "irls_iters": IRLS_ITERS}
    fit.update({k: v for k, v in residual_summary(entry_samples, coeffs).items()
                if k not in ("samples",)})
    return {"coeffs": coeffs, "fit": fit, "samples": entry_samples}


def _canonical_sample(s: dict) -> dict:
    out = {"flops_proxy": int(s.get("flops_proxy") or 0),
           "bytes_moved": int(s.get("bytes_moved") or 0),
           "measured_s": float(s["measured_s"])}
    for k in ("window_steps", "source"):
        if s.get(k) is not None:
            out[k] = s[k]
    return out


def fit_groups(groups: Dict[str, List[dict]], min_samples: int = MIN_SAMPLES,
               log=None) -> Tuple[Dict[str, dict], Dict[str, str]]:
    """Fit every (backend, scope) group; refusals are collected per key
    (and reported), never silently dropped."""
    entries, refused = {}, {}
    for key in sorted(groups):
        try:
            entries[key] = fit_entry(groups[key], min_samples=min_samples)
            if log:
                c = entries[key]["coeffs"]
                log(f"fit {key}: base_s={c['base_s']:.6g} "
                    f"s_per_flop={c['s_per_flop']} s_per_byte={c['s_per_byte']} "
                    f"med|rel|={entries[key]['fit'].get('median_abs_rel_err')}")
        except CalibrationError as e:
            refused[key] = str(e)
            if log:
                log(f"refused {key}: {e}")
    return entries, refused


# ---------------------------------------------------------------------------
# sample collection (telemetry JSONL + trace_report --drift sidecars)
# ---------------------------------------------------------------------------
def collect_samples(paths: Iterable[str],
                    default_scope: str = "train_step") -> Dict[str, List[dict]]:
    """Walk run directories / ``telemetry.jsonl`` files / ``--drift``
    sidecar JSONs into per-``<backend>/<scope>`` sample groups. Runs
    without a usable static price (disabled, or stamped ``{"error":...}``)
    contribute nothing; deterministic order (input order, event order)
    so two collections over the same files are identical.

    graft-prefix-cache separation: a serving run whose header declares
    ``prefix_cache: "on"`` skips part of prefill (restored KV rows), so
    its tick timings fit a DIFFERENT cost line than full-prefill serving
    — those runs group under ``<scope>_cached``. Serve runs MISSING the
    ``prefix_cache``/``cached_prefix_tokens`` header fields (pre-PR-19
    telemetry) are ambiguous — they cannot be pooled with marked runs of
    the same group without silently mixing the two populations, so a mix
    raises :class:`CalibrationError` instead of fitting garbage.

    graft-rlhf separation (same pattern): ``rlhf_rollout`` /
    ``rlhf_learner`` scopes join the fit set. An overlapped rollout
    run's tick timings carry interleaved learner work (the overlap being
    priced!), so runs whose header declares ``rlhf_overlap: "on"`` group
    under ``<scope>_overlap``; rlhf runs missing the ``rlhf_overlap``
    header field are ambiguous and a marked/unmarked mix in one group
    refuses loudly."""
    groups: Dict[str, List[dict]] = {}
    serve_marking: Dict[str, set] = {}
    rlhf_marking: Dict[str, set] = {}
    for path in paths:
        for run, price, windows in _iter_runs(path):
            if not isinstance(price, dict) or price.get("error") \
                    or not price.get("flops_proxy"):
                continue
            backend = (run or {}).get("backend") or "unknown"
            scope = (run or {}).get("scope") or default_scope
            if scope.startswith("serve"):
                marked = ("prefix_cache" in (run or {})
                          or "cached_prefix_tokens" in (run or {}))
                serve_marking.setdefault(f"{backend}/{scope}",
                                         set()).add(marked)
                if marked and (run or {}).get("prefix_cache") == "on":
                    scope = f"{scope}_cached"
            elif scope.startswith("rlhf"):
                marked = "rlhf_overlap" in (run or {})
                rlhf_marking.setdefault(f"{backend}/{scope}",
                                        set()).add(marked)
                if marked and (run or {}).get("rlhf_overlap") == "on":
                    scope = f"{scope}_overlap"
            key = f"{backend}/{scope}"
            usable = windows[1:] if len(windows) > 1 else windows
            source = (run or {}).get("config_sig") or (run or {}).get("bench") \
                or os.path.basename(os.path.dirname(os.path.abspath(path))) or "run"
            for w in usable:
                med = w.get("median_step_s")
                if not med or med <= 0:
                    continue
                groups.setdefault(key, []).append({
                    "flops_proxy": int(price.get("flops_proxy") or 0),
                    "bytes_moved": int(price.get("bytes_moved") or 0),
                    "measured_s": float(med),
                    "window_steps": int(w.get("window_steps") or 0),
                    "source": str(source)})
    mixed = sorted(k for k, flags in serve_marking.items() if len(flags) > 1)
    if mixed:
        raise CalibrationError(
            f"serve sample group(s) {mixed} mix runs WITH the "
            f"prefix_cache/cached_prefix_tokens header fields and runs "
            f"WITHOUT them — unmarked runs may contain cached-prefill "
            f"ticks, so pooling them with full-prefill samples would fit "
            f"a meaningless cost line; re-collect the unmarked runs with "
            f"current telemetry (fleet/worker.py stamps the fields) or "
            f"drop them from the collection")
    mixed_rlhf = sorted(k for k, flags in rlhf_marking.items()
                        if len(flags) > 1)
    if mixed_rlhf:
        raise CalibrationError(
            f"rlhf sample group(s) {mixed_rlhf} mix runs WITH the "
            f"rlhf_overlap header field and runs WITHOUT it — unmarked "
            f"runs may contain overlapped-learner ticks, so pooling them "
            f"with rollout-only samples would fit a meaningless cost "
            f"line; re-collect the unmarked runs with current telemetry "
            f"(tools/rlhf_bench.py stamps the field) or drop them from "
            f"the collection")
    return {k: groups[k] for k in sorted(groups)}


def _iter_runs(path: str):
    """Yield (run_info, static_price, drift_windows) per run in a
    telemetry JSONL (a file may hold several runs back to back), a run
    dir containing one, or a ``trace_report --drift`` sidecar JSON."""
    from deepspeed_tpu.runtime.telemetry.sink import iter_events

    if os.path.isdir(path):
        from deepspeed_tpu.runtime.telemetry.core import TELEMETRY_FILE
        candidate = os.path.join(path, TELEMETRY_FILE)
        if not os.path.exists(candidate):
            raise FileNotFoundError(f"no {TELEMETRY_FILE} under {path}")
        path = candidate
    if path.endswith(".json"):
        with open(path) as fh:
            doc = json.load(fh)
        if "windows" not in doc:
            raise ValueError(f"{path}: not a trace_report --drift sidecar "
                             f"(no 'windows' key)")
        yield doc.get("run") or {}, doc.get("predicted"), list(doc["windows"])
        return
    run, price, windows = None, None, []
    for rec in iter_events(path):
        kind = rec.get("event")
        if kind == "run_start":
            if windows:
                yield run, price, windows
            run, price, windows = rec.get("run") or {}, rec.get("static_price"), []
        elif kind == "drift":
            windows.append(rec)
    if windows:
        yield run, price, windows


# ---------------------------------------------------------------------------
# R016 — the calibration ratchet
# ---------------------------------------------------------------------------
@rule("R016", "the committed cost calibration must not drift stale", ERROR,
      LAYER_COST)
def r016_calibration_drift(calibration: Dict,
                           search_artifact: Optional[Dict] = None,
                           current_samples: Optional[Dict[str, List[dict]]] = None,
                           tolerance: Optional[float] = None) -> List[Finding]:
    """Judge the committed ``cost_calibration.json``: ERROR when (a) an
    entry is self-inconsistent — refitting its embedded samples does not
    reproduce the committed coefficients/residual evidence (a perturbed
    or hand-edited artifact; hermetic, no telemetry needed); (b) the
    artifact's jax signature no longer matches the interpreter (the
    coefficients were fitted against a different dispatch stack); (c)
    fresh telemetry's residuals under the committed coefficients exceed
    the committed fit's own error level by more than ``tolerance``; or
    (d) the committed search frontier's ``predicted_seconds`` re-rank is
    stale against the calibration — recomputed seconds disagree, the
    seconds_rank is unsorted, or a committed winner is now *dominated*
    once calibrated seconds joins the objectives. An absent artifact or
    a not-yet-re-ranked space reports INFO (bank explicitly with
    ``tools/graft_calibrate.py fit --update`` /
    ``tools/graft_search.py --update``, never silently)."""
    findings: List[Finding] = []
    entries = calibration.get("entries", {})
    if not entries:
        findings.append(Finding(
            rule="R016", severity=INFO, scenario="calibration:artifact",
            message="no committed calibration — fit and bank with "
                    "tools/graft_calibrate.py fit <runs...> --update"))
        return findings
    tol = float(tolerance if tolerance is not None
                else calibration.get("tolerance", DEFAULT_RESIDUAL_TOLERANCE))
    import jax
    if calibration.get("jax_version") \
            and calibration["jax_version"] != jax.__version__:
        findings.append(Finding(
            rule="R016", severity=ERROR, scenario="calibration:artifact",
            message=f"jax signature mismatch: artifact fitted under "
                    f"{calibration['jax_version']}, interpreter runs "
                    f"{jax.__version__} — refit with tools/graft_calibrate.py",
            location="jax_version"))
    for key, entry in sorted(entries.items()):
        scenario = f"calibration:{key}"
        per: List[Finding] = []
        per.extend(_entry_self_consistency(scenario, entry))
        if current_samples and current_samples.get(key):
            cur = residual_summary(
                [_canonical_sample(s) for s in current_samples[key]],
                entry["coeffs"])
            base_err = entry.get("fit", {}).get("median_abs_rel_err")
            cur_err = cur.get("median_abs_rel_err")
            if cur_err is None:
                per.append(Finding(
                    rule="R016", severity=ERROR, scenario=scenario,
                    message="current telemetry is unpriceable under the "
                            "committed coefficients (unidentified feature now "
                            "nonzero) — refit",
                    location="residuals"))
            elif base_err is not None and cur_err > base_err + tol:
                per.append(Finding(
                    rule="R016", severity=ERROR, scenario=scenario,
                    message=f"calibration residuals drifted: median |rel err| "
                            f"{cur_err:.3f} on current telemetry vs "
                            f"{base_err:.3f} committed (+{tol:.0%} tolerance) "
                            f"— the machine changed; refit and re-bank",
                    location="residuals"))
        findings.extend(per[:_MAX_FINDINGS_PER_SCENARIO])
    if search_artifact is not None:
        findings.extend(_frontier_rerank_findings(calibration, search_artifact))
    return findings


def _rel_close(a: Optional[float], b: Optional[float],
               rtol: float = _REFIT_RTOL) -> bool:
    # purely relative: coefficients live at 1e-12 scale, so any absolute
    # floor would wave perturbations through
    if a is None or b is None:
        return a is None and b is None
    if a == b:
        return True
    return abs(a - b) <= rtol * max(abs(a), abs(b))


def _entry_self_consistency(scenario: str, entry: dict) -> List[Finding]:
    out: List[Finding] = []
    try:
        refit = fit_entry(entry.get("samples") or [])
    except CalibrationError as e:
        return [Finding(rule="R016", severity=ERROR, scenario=scenario,
                        message=f"embedded sample set no longer fits: {e}",
                        location="samples")]
    committed = entry.get("coeffs", {})
    for cname in ("base_s",) + tuple(c for _, c in FEATURES):
        if not _rel_close(committed.get(cname), refit["coeffs"].get(cname)):
            out.append(Finding(
                rule="R016", severity=ERROR, scenario=scenario,
                message=f"coefficient {cname} = {committed.get(cname)} does "
                        f"not refit from the embedded samples "
                        f"(got {refit['coeffs'].get(cname)}) — perturbed or "
                        f"hand-edited artifact; re-bank with "
                        f"tools/graft_calibrate.py fit --update",
                location=cname))
    for metric in ("median_abs_rel_err", "p90_abs_rel_err", "max_abs_rel_err"):
        if not _rel_close(entry.get("fit", {}).get(metric),
                          refit["fit"].get(metric), rtol=1e-6):
            out.append(Finding(
                rule="R016", severity=ERROR, scenario=scenario,
                message=f"residual evidence {metric} = "
                        f"{entry.get('fit', {}).get(metric)} inconsistent with "
                        f"the embedded samples (recomputed "
                        f"{refit['fit'].get(metric)})",
                location=f"fit.{metric}"))
    return out


def _frontier_rerank_findings(calibration: Dict,
                              search_artifact: Dict) -> List[Finding]:
    from deepspeed_tpu.analysis.search import pareto  # lazy: import cycle
    findings: List[Finding] = []
    entries = calibration.get("entries", {})
    for name, space in sorted(search_artifact.get("spaces", {}).items()):
        scenario = f"calibration:search:{name}"
        per: List[Finding] = []
        objectives = list(space.get("objectives") or ())
        if "predicted_seconds" not in objectives:
            findings.append(Finding(
                rule="R016", severity=INFO, scenario=scenario,
                message="space not re-ranked under the committed calibration "
                        "— regenerate with tools/graft_search.py --update"))
            continue
        prov = space.get("calibration") or {}
        entry = entries.get(prov.get("key") or "")
        if entry is None:
            findings.append(Finding(
                rule="R016", severity=ERROR, scenario=scenario,
                message=f"space re-ranked under calibration key "
                        f"{prov.get('key')!r} that the committed artifact no "
                        f"longer carries — regenerate the frontier",
                location="calibration.key"))
            continue
        cands = space.get("candidates", {})
        recomputed: Dict[str, Optional[float]] = {}
        for cid, cand in cands.items():
            metrics = cand.get("metrics", {})
            sec = calibrated_seconds(metrics, entry["coeffs"])
            recomputed[cid] = sec
            stored = metrics.get("predicted_seconds")
            if sec is None or stored is None or not _rel_close(stored, sec):
                per.append(Finding(
                    rule="R016", severity=ERROR, scenario=scenario,
                    message=f"stale re-rank: {cid} predicted_seconds {stored} "
                            f"vs {sec} under the committed coefficients — "
                            f"regenerate with tools/graft_search.py --update",
                    location=cid))
        if not per:
            shadow = {cid: {"metrics": dict(c.get("metrics", {}),
                                            predicted_seconds=recomputed[cid])}
                      for cid, c in cands.items()}
            frontier_now, dominated_by = pareto(shadow, objectives)
            for cid in space.get("frontier", []):
                if cid not in frontier_now:
                    per.append(Finding(
                        rule="R016", severity=ERROR, scenario=scenario,
                        message=f"committed winner {cid} is dominated under "
                                f"calibrated seconds (by "
                                f"{dominated_by.get(cid, [])[:3]}) — the "
                                f"frontier a chip window would measure is "
                                f"stale",
                        location=cid))
            rank = space.get("seconds_rank")
            if rank is not None:
                secs = [recomputed.get(cid) for cid in rank]
                if (sorted(rank) != sorted(space.get("frontier", []))
                        or any(s is None for s in secs)
                        or any(secs[i] > secs[i + 1]
                               for i in range(len(secs) - 1))):
                    per.append(Finding(
                        rule="R016", severity=ERROR, scenario=scenario,
                        message="seconds_rank provenance is not the frontier "
                                "sorted by calibrated seconds — regenerate "
                                "with tools/graft_search.py --update",
                        location="seconds_rank"))
        findings.extend(per[:_MAX_FINDINGS_PER_SCENARIO])
    return findings


def verify_calibration(calibration_path: Optional[str] = None,
                       search_pareto_path: Optional[str] = None,
                       runs: Optional[List[str]] = None,
                       tolerance: Optional[float] = None,
                       log=None) -> List[Finding]:
    """Load the committed artifacts and judge them with R016 — the shared
    entry point for ``graft_lint --cost`` and
    ``tools/graft_calibrate.py verify``. ``runs`` (telemetry run dirs /
    JSONLs / drift sidecars) additionally enables the fresh-telemetry
    residual-drift check."""
    calibration = load_calibration(calibration_path)
    search_artifact = None
    if search_pareto_path is None:
        search_pareto_path = os.path.join(
            os.path.dirname(default_calibration_path()), "search_pareto.json")
    if os.path.exists(search_pareto_path):
        from deepspeed_tpu.analysis.search import load_search_artifact
        search_artifact = load_search_artifact(search_pareto_path)
    current = collect_samples(runs) if runs else None
    if log and current:
        for key, samples in current.items():
            log(f"collected {len(samples)} current sample(s) for {key}")
    return r016_calibration_drift(calibration, search_artifact, current,
                                  tolerance=tolerance)
