"""graft-lint core: the rule registry, findings, and waivers.

PR 5 and PR 6 each hardened a program-level invariant by hand ("no
``[*,S,E,C]`` tensor in the sorted-route jaxpr", "owned_device_put on the
0.4.37 container", pinned matmul precision on the parity path) — one-off
assertions that protect nothing outside their own test. This package
turns those invariants into a *registry of named rules* checked
mechanically against every traced program, the same role the reference's
op-builder compatibility checks play for its CUDA ops
(``/root/reference/op_builder/builder.py``): convention becomes
enforcement.

A :class:`Rule` declares an id (``R001``..), severity, and the layer it
inspects (``jaxpr`` — walked closed jaxprs; ``hlo`` — lowered StableHLO
text; ``ast`` — repo source). Rules yield :class:`Finding`s; a
:class:`Waiver` (from ``analysis_results/waivers.json`` or an inline
``# graft-lint: waive R00X reason`` comment for AST rules) marks a
finding as acknowledged so it reports but does not gate. The CLI
(``tools/graft_lint.py``) gates on *new* ERROR findings against a
committed baseline.
"""

import dataclasses
import fnmatch
import hashlib
from typing import Callable, Dict, Iterable, List, Optional

ERROR = "ERROR"
WARN = "WARN"
INFO = "INFO"

_SEVERITIES = (ERROR, WARN, INFO)

#: layers a rule can inspect
LAYER_JAXPR = "jaxpr"
LAYER_HLO = "hlo"
LAYER_AST = "ast"
LAYER_COST = "cost"  # quantitative rules fed by the cost engine (analysis/cost.py)


@dataclasses.dataclass
class Finding:
    """One rule violation (or waived acknowledgement) at one site."""

    rule: str
    severity: str
    scenario: str  # program name (jaxpr/hlo rules) or repo-relative file (ast)
    message: str
    location: str = ""  # scope path inside the program, or file:line
    waived: bool = False
    waiver_reason: str = ""

    def fingerprint(self) -> str:
        """Stable identity for baseline comparison. The full location
        (including AST line numbers) is part of the identity: two raw
        ``device_put`` calls in one file are two findings, and a new one
        must not hide behind an old one's fingerprint. Line-shift churn is
        handled by inline waiver comments (which move with the code), not
        by the baseline."""
        raw = f"{self.rule}|{self.scenario}|{self.location}|{self.message}"
        return hashlib.sha1(raw.encode()).hexdigest()[:16]

    def to_dict(self) -> dict:
        d = dataclasses.asdict(self)
        d["fingerprint"] = self.fingerprint()
        return d


@dataclasses.dataclass(frozen=True)
class Rule:
    """A named invariant. ``check`` signature depends on ``layer``:

    * ``jaxpr`` / ``hlo`` rules: ``check(program, analyzer) -> Iterable[Finding]``
      (``program``: :class:`~deepspeed_tpu.analysis.program.ProgramInfo`,
      ``analyzer``: the shared :class:`ProgramAnalyzer` walk);
    * ``ast`` rules: ``check(files) -> Iterable[Finding]`` where ``files``
      is ``[(relpath, source_text, ast_module)]``.
    """

    id: str
    title: str
    severity: str
    layer: str
    doc: str
    check: Callable

    def __post_init__(self):
        assert self.severity in _SEVERITIES, self.severity


RULES: Dict[str, Rule] = {}


def register(rule: Rule) -> Rule:
    assert rule.id not in RULES, f"duplicate rule id {rule.id}"
    RULES[rule.id] = rule
    return rule


def rule(id: str, title: str, severity: str, layer: str):  # noqa: A002 — rule id
    """Decorator: ``@rule("R001", "...", ERROR, LAYER_JAXPR)`` over a check
    function registers it; the function's docstring becomes the rule doc."""

    def wrap(fn):
        register(Rule(id=id, title=title, severity=severity, layer=layer,
                      doc=(fn.__doc__ or "").strip(), check=fn))
        return fn

    return wrap


def program_rules() -> List[Rule]:
    return [r for r in RULES.values() if r.layer in (LAYER_JAXPR, LAYER_HLO)]


def ast_rules() -> List[Rule]:
    return [r for r in RULES.values() if r.layer == LAYER_AST]


def cost_rules() -> List[Rule]:
    """Cost-layer rules run only in the ``--cost`` pass: they need the
    memory estimate + collective inventory a plain trace walk doesn't
    build (R013 additionally needs the committed cost baseline)."""
    return [r for r in RULES.values() if r.layer == LAYER_COST]


@dataclasses.dataclass(frozen=True)
class Waiver:
    """Acknowledge a finding without fixing it. ``scenario`` is an fnmatch
    pattern against ``Finding.scenario`` (program name or file path);
    ``match`` optionally narrows to findings whose message contains it."""

    rule: str
    scenario: str = "*"
    match: str = ""
    reason: str = ""

    def covers(self, f: Finding) -> bool:
        return (self.rule == f.rule
                and fnmatch.fnmatch(f.scenario, self.scenario)
                and (not self.match or self.match in f.message))


def apply_waivers(findings: Iterable[Finding], waivers: Iterable[Waiver]) -> List[Finding]:
    out = []
    for f in findings:
        for w in waivers:
            if not f.waived and w.covers(f):
                f.waived = True
                f.waiver_reason = w.reason or f"waived by {w.rule}/{w.scenario}"
        out.append(f)
    return out


def stale_config_waivers(findings: Iterable[Finding],
                         waivers: Iterable[Waiver]) -> List[Waiver]:
    """Waivers that cover no current finding. A waiver is an
    acknowledged debt; once the debt is paid (or the rule/scenario
    renamed) the entry keeps matching nothing forever — the CLI WARNs so
    dead waivers get pruned instead of silently accumulating into a
    blanket that could swallow a future real finding."""
    findings = list(findings)
    return [w for w in waivers if not any(w.covers(f) for f in findings)]


def load_waivers(entries: Optional[Iterable[dict]]) -> List[Waiver]:
    """Parse the ``waivers.json`` list-of-dicts form (unknown keys rejected
    so a typo'd waiver fails loudly instead of silently not waiving)."""
    out = []
    for e in entries or []:
        unknown = set(e) - {"rule", "scenario", "match", "reason"}
        if unknown:
            raise ValueError(f"waiver {e!r} has unknown keys {sorted(unknown)}")
        if "rule" not in e:
            raise ValueError(f"waiver {e!r} missing 'rule'")
        out.append(Waiver(**e))
    return out
