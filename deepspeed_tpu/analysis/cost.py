"""graft-audit cost engine: static memory + collective cost per program,
rules R009-R013, and the ratcheted cost baseline.

PR 7's rules answered yes/no questions; this layer answers *how much* —
statically estimated peak live bytes (:mod:`.memory`), the collective
inventory with analytic wire bytes (:mod:`.hlo_cost`), and a
cross-check against the backend's own ``cost_analysis()``/
``memory_analysis()`` where the compiled executable provides them. On
top sit the quantitative gates:

* **R009** — per-scenario collective-signature drift. Scenario metadata
  declares ``collective_signature``: a list of assertions over the
  inventory, each ``{"layer", "kind", "count"|"min_count"|"max_count",
  "max_bytes", "backends", "note"}``. Entries whose layer has no
  inventory on this run (or whose ``backends`` excludes this backend —
  e.g. the reduce-scatter expectation XLA:CPU decomposes away) are
  recorded as *unchecked*, never silently passed.
* **R010** — statically estimated ``peak_transient_bytes`` above the
  metadata-declared ``activation_budget_bytes``. The pre-wired gate for
  the ROADMAP-2 1F1B refactor: the pipe engine stamps its budget from
  config (``pipeline.activation_budget_mb``) or ``DS_PIPE_ACT_BUDGET_MB``.
* **R011** — redundant collectives: identical (primitive, operands,
  axes) collective eqns, or a collective inside ``scan`` whose operands
  are loop-invariant (hoistable: it pays per-tick wire bytes for a
  constant).
* **R012** — host-transfer bytes in the step program above
  ``host_transfer_budget_bytes`` (default 1 MiB). R003 flags the
  *presence* of host primitives; R012 prices the ones metadata allowed.
* **R013** — the cost ratchet: current peak bytes / wire bytes /
  collective counts vs the committed
  ``analysis_results/cost_baseline.json``, gating on growth beyond
  tolerance (same contract as PR 7's fingerprint baseline; shrinkage
  reports as an improvement to bank with ``--update-baseline``).
"""

import dataclasses
import json
import os
from typing import Any, Dict, Iterable, List, Optional, Tuple

from deepspeed_tpu.analysis import hlo_cost
from deepspeed_tpu.analysis.core import (ERROR, INFO, LAYER_COST, WARN, Finding,
                                         cost_rules, rule)
from deepspeed_tpu.analysis.memory import MemoryEstimate, estimate_memory
from deepspeed_tpu.analysis.program import ProgramAnalyzer, ProgramInfo, aval_bytes

COST_BASELINE_VERSION = 1
DEFAULT_TOLERANCE = 0.05  # relative growth allowed before R013 gates
_ABS_FLOOR = 64 << 10  # ignore sub-64KiB absolute drift (fingerprint noise)

#: signature-entry schema (unknown keys rejected loudly, like waivers)
_SIG_KEYS = {"layer", "kind", "count", "min_count", "max_count", "max_bytes",
             "backends", "note"}


@dataclasses.dataclass
class CostInfo:
    """Everything the cost rules judge for one program."""

    program: str
    memory: MemoryEstimate
    ops: List[hlo_cost.CollectiveOp]
    inventory: Dict[str, Dict[str, Any]]  # layer -> {counts, bytes_moved, bytes_by_axis}
    backend_stats: Optional[Dict[str, Any]] = None  # compiled cross-check
    compile_error: str = ""
    unchecked_signature: Optional[List[dict]] = None

    def counts(self, layer: str) -> Dict[str, int]:
        return dict(self.inventory.get(layer, {}).get("counts", {}))

    def bytes_moved(self) -> Dict[str, int]:
        return {layer: inv["bytes_moved"] for layer, inv in self.inventory.items()}

    def to_dict(self) -> dict:
        return {
            "memory": self.memory.to_dict(),
            "collectives": {layer: {k: v for k, v in inv.items()}
                            for layer, inv in self.inventory.items()},
            "backend_stats": self.backend_stats,
            "compile_error": self.compile_error,
            "unchecked_signature": self.unchecked_signature or [],
        }


def _backend_stats(compiled) -> Dict[str, Any]:
    """Flops + per-device memory stats from the compiled executable —
    the on-backend numbers the static estimate is cross-checked against.
    jax 0.4.37 returns ``cost_analysis()`` as a list of per-computation
    dicts (the PR 5 autotuner handling)."""
    out: Dict[str, Any] = {}
    try:
        ca = compiled.cost_analysis()
        entry = ca[0] if isinstance(ca, (list, tuple)) and ca else ca
        if isinstance(entry, dict):
            for key in ("flops", "bytes accessed", "transcendentals"):
                if key in entry:
                    out[key.replace(" ", "_")] = float(entry[key])
    except Exception as e:  # noqa: BLE001 — stats are evidence, never fatal
        out["cost_analysis_error"] = f"{type(e).__name__}: {str(e)[:120]}"
    try:
        ma = compiled.memory_analysis()
        if ma is not None:
            for key in ("argument_size_in_bytes", "output_size_in_bytes",
                        "temp_size_in_bytes", "alias_size_in_bytes",
                        "host_argument_size_in_bytes"):
                val = getattr(ma, key, None)
                if val is not None:
                    out[key] = int(val)
    except Exception as e:  # noqa: BLE001
        out["memory_analysis_error"] = f"{type(e).__name__}: {str(e)[:120]}"
    return out


def build_cost(program: ProgramInfo, analyzer: Optional[ProgramAnalyzer] = None,
               compile: bool = True) -> CostInfo:  # noqa: A002 — mirrors the CLI flag
    """Assemble the cost view of one program. ``compile=False`` keeps it
    trace-only (perf_ladder evidence on a chip window must not pay a
    second compile); the compiled inventory/stat layers then stay absent
    and signature entries against them report as unchecked."""
    analyzer = analyzer or ProgramAnalyzer(program)
    mesh_axes = program.metadata.get("mesh_axes")
    ops: List[hlo_cost.CollectiveOp] = []
    if program.jaxpr is not None:
        ops.extend(hlo_cost.jaxpr_collectives(analyzer, mesh_axes))
    if program.hlo_text:
        ops.extend(hlo_cost.stablehlo_collectives(program.hlo_text))
    backend_stats, compile_error = None, ""
    if compile:
        try:
            compiled = program.compiled()
            if compiled is not None:
                ops.extend(hlo_cost.compiled_collectives(compiled.as_text(), mesh_axes))
                backend_stats = _backend_stats(compiled)
        except Exception as e:  # noqa: BLE001 — a backend that cannot compile
            # the program is a report entry, not a crash
            compile_error = f"{type(e).__name__}: {str(e)[:200]}"
    inv = hlo_cost.inventory(ops)
    # logical kinds the cost engine counts on top of hlo_cost's ops
    sec_sites = _dense_dispatch_sites(program, analyzer)
    if sec_sites:
        inv.setdefault("jaxpr", {"counts": {}, "bytes_moved": 0, "bytes_by_axis": {}})
        inv["jaxpr"]["counts"]["dense_dispatch"] = sec_sites
    mem = estimate_memory(program)
    return CostInfo(program=program.name, memory=mem, ops=ops, inventory=inv,
                    backend_stats=backend_stats, compile_error=compile_error)


def _dense_dispatch_sites(program: ProgramInfo, analyzer: ProgramAnalyzer) -> int:
    """Distinct sites materializing a ``[*,S,E,C]``-signature intermediate
    (R001's shape test, counted rather than judged): the route-drift
    component of the MoE collective signature — a dense dispatch feeds the
    all-to-all endpoints with an O(S*E*C) einsum instead of a gather."""
    sigs = [tuple(s) for s in program.metadata.get("moe_sec", ())]
    if not sigs:
        return 0
    seen = set()
    for rec, aval in analyzer.iter_avals():
        if tuple(aval.shape)[-3:] in sigs:
            seen.add((tuple(aval.shape), rec.scope))
    return len(seen)


# ---------------------------------------------------------------------------
# R009 — collective-signature drift
# ---------------------------------------------------------------------------
def _validate_signature(entries: Iterable[dict]):
    for e in entries:
        unknown = set(e) - _SIG_KEYS
        if unknown:
            raise ValueError(f"collective_signature entry {e!r} has unknown keys "
                             f"{sorted(unknown)} (valid: {sorted(_SIG_KEYS)})")
        if "layer" not in e or "kind" not in e:
            raise ValueError(f"collective_signature entry {e!r} needs 'layer' and 'kind'")


@rule("R009", "per-scenario collective signature must not drift", ERROR, LAYER_COST)
def r009_collective_signature(program: ProgramInfo, cost: CostInfo) -> List[Finding]:
    """The comms schedule is part of a scenario's contract: sorted MoE =
    exactly two capacity-bounded all-to-all reshards per layer direction
    (and ZERO dense-dispatch einsums feeding them), ZeRO>=2 = param
    movement via all-gather with gradients reduce-scattered (declared
    per-backend: XLA:CPU decomposes RS, so that entry checks on TPU and
    is inventoried as unchecked here). Any count/byte drift from the
    declared signature is an ERROR — the drift that silently turns a
    banked TFLOPS number into fiction."""
    entries = list(program.metadata.get("collective_signature", ()))
    if not entries:
        return []
    _validate_signature(entries)
    import jax
    backend = jax.default_backend()
    findings = []
    cost.unchecked_signature = cost.unchecked_signature or []
    for e in entries:
        layer, kind = e["layer"], e["kind"]
        if e.get("backends") and backend not in e["backends"]:
            cost.unchecked_signature.append(dict(e, reason=f"backend {backend} excluded"))
            continue
        if layer not in cost.inventory:
            if layer == "compiled" and cost.compile_error:
                cost.unchecked_signature.append(dict(e, reason=cost.compile_error))
                continue
            # layer genuinely absent (e.g. trace-only run): unchecked
            cost.unchecked_signature.append(dict(e, reason=f"no {layer} inventory"))
            continue
        count = cost.counts(layer).get(kind, 0)
        want = e.get("count")
        if want is not None and count != want:
            findings.append(Finding(
                rule="R009", severity=ERROR, scenario=program.name,
                message=f"collective signature drift: expected exactly {want} "
                        f"{kind}@{layer}, found {count}"
                        + (f" ({e['note']})" if e.get("note") else ""),
                location=layer))
        lo, hi = e.get("min_count"), e.get("max_count")
        if lo is not None and count < lo:
            findings.append(Finding(
                rule="R009", severity=ERROR, scenario=program.name,
                message=f"collective signature drift: expected >={lo} "
                        f"{kind}@{layer}, found {count}"
                        + (f" ({e['note']})" if e.get("note") else ""),
                location=layer))
        if hi is not None and count > hi:
            findings.append(Finding(
                rule="R009", severity=ERROR, scenario=program.name,
                message=f"collective signature drift: expected <={hi} "
                        f"{kind}@{layer}, found {count}"
                        + (f" ({e['note']})" if e.get("note") else ""),
                location=layer))
        max_bytes = e.get("max_bytes")
        if max_bytes is not None:
            fat = [op for op in cost.ops
                   if op.layer == layer and op.kind == kind and op.bytes_in > max_bytes]
            for op in fat[:4]:
                findings.append(Finding(
                    rule="R009", severity=ERROR, scenario=program.name,
                    message=f"{kind}@{layer} moves {op.bytes_in} bytes "
                            f"(> declared max {max_bytes})"
                            + (f" ({e['note']})" if e.get("note") else ""),
                    location=f"{layer}:{op.scope or op.axes}"))
    return findings


# ---------------------------------------------------------------------------
# R010 — activation budget
# ---------------------------------------------------------------------------
@rule("R010", "static peak activations must fit the declared budget", ERROR, LAYER_COST)
def r010_activation_budget(program: ProgramInfo, cost: CostInfo) -> List[Finding]:
    """A schedule's activation bound is only real if something fails when
    it is exceeded. Programs that declare ``activation_budget_bytes``
    (the pipe engine stamps it from ``pipeline.activation_budget_mb``)
    gate their statically estimated transient peak against it — the
    CPU-checkable stand-in for the ROADMAP-2 ``<=1F1B`` bound, pre-wired
    so the refactor lands against a live gate."""
    budget = program.metadata.get("activation_budget_bytes")
    if not budget:
        return []
    peak = cost.memory.peak_transient_bytes
    if peak <= budget:
        return []
    # attribution reads the TRANSIENT timeline's own peak slot — the
    # total-peak slot may be params-dominated and name the wrong buffer
    top = cost.memory.top_transient[0] if cost.memory.top_transient else {}
    return [Finding(
        rule="R010", severity=ERROR, scenario=program.name,
        message=f"statically estimated peak activations {peak / 2**20:.1f} MiB "
                f"exceed declared budget {budget / 2**20:.1f} MiB "
                f"(largest live: {top.get('shape')} {top.get('dtype')} "
                f"@ {top.get('scope')})",
        location="memory")]


# ---------------------------------------------------------------------------
# R011 — redundant collectives
# ---------------------------------------------------------------------------
_COLLECTIVE_PRIMS = set(hlo_cost._PRIM_KIND)


@rule("R011", "no redundant or loop-invariant collectives", WARN, LAYER_COST)
def r011_redundant_collectives(program: ProgramInfo, cost: CostInfo,
                               analyzer: Optional[ProgramAnalyzer] = None) -> List[Finding]:
    """Two shapes of wasted wire bytes: (a) byte-identical collectives —
    same primitive, same operand vars, same axes — dispatched twice
    (XLA's CSE may or may not save you across fusion boundaries; the
    program shouldn't bet on it); (b) a collective inside a ``scan`` body
    whose operands derive only from loop *constants* — it moves the same
    bytes every tick and belongs hoisted above the loop."""
    if program.jaxpr is None:
        return []
    analyzer = analyzer or ProgramAnalyzer(program)
    findings: List[Finding] = []
    seen: Dict[tuple, int] = {}
    seen_eqns = set()
    for rec in analyzer.records():
        if rec.primitive not in _COLLECTIVE_PRIMS:
            continue
        # a shared sub-jaxpr (pjit/remat caches the body) reaches the walk
        # once per CALL SITE with the same eqn object — that is reuse on
        # different runtime data, not a duplicate dispatch
        if id(rec.eqn) in seen_eqns:
            continue
        seen_eqns.add(id(rec.eqn))
        key = (rec.primitive,
               tuple(id(v) for v in rec.eqn.invars if hasattr(v, "count")),
               str(rec.eqn.params.get("axes") or rec.eqn.params.get("axis_name")),
               str(rec.eqn.params.get("perm", "")))
        seen[key] = seen.get(key, 0) + 1
        if seen[key] == 2:  # report once per duplicate set
            findings.append(Finding(
                rule="R011", severity=WARN, scenario=program.name,
                message=f"duplicate {rec.primitive} over identical operands and "
                        f"axes — one dispatch of the result would do",
                location=rec.scope))
    # loop-invariant collectives inside scan bodies
    seen_scans = set()
    for rec in analyzer.records():
        if rec.primitive != "scan" or id(rec.eqn) in seen_scans:
            continue
        seen_scans.add(id(rec.eqn))
        closed = rec.eqn.params.get("jaxpr")
        body = getattr(closed, "jaxpr", closed)
        if body is None:
            continue
        num_consts = int(rec.eqn.params.get("num_consts", 0))
        variant = set()  # vars derived from carry/xs
        for v in body.invars[num_consts:]:
            variant.add(v)
        for eqn in body.eqns:
            derived = any(v in variant for v in eqn.invars if hasattr(v, "count"))
            if derived:
                variant.update(o for o in eqn.outvars)
            if (eqn.primitive.name in _COLLECTIVE_PRIMS and not derived
                    and any(hasattr(v, "count") for v in eqn.invars)):
                findings.append(Finding(
                    rule="R011", severity=WARN, scenario=program.name,
                    message=f"{eqn.primitive.name} inside scan on loop-invariant "
                            f"operands — pays per-tick wire bytes for a constant; "
                            f"hoist above the loop",
                    location=rec.scope + "/scan"))
    return findings


# ---------------------------------------------------------------------------
# R012 — host-transfer bytes
# ---------------------------------------------------------------------------
_HOST_PRIMS = ("device_put", "io_callback", "pure_callback", "outside_call",
               "infeed", "outfeed", "debug_callback")


@rule("R012", "host-transfer bytes in the step must fit the budget", WARN, LAYER_COST)
def r012_host_transfer_bytes(program: ProgramInfo, cost: CostInfo,
                             analyzer: Optional[ProgramAnalyzer] = None) -> List[Finding]:
    """R003 bans host primitives outright (with an allowlist for paths
    that intentionally stream, e.g. offload); this rule prices whatever
    survived: total bytes crossing the host boundary per step above
    ``host_transfer_budget_bytes`` (default 1 MiB) is a WARN — the PCIe
    tax the offload A/B rungs measure on chip, now visible statically."""
    if program.jaxpr is None:
        return []
    budget = int(program.metadata.get("host_transfer_budget_bytes", 1 << 20))
    analyzer = analyzer or ProgramAnalyzer(program)
    total, sites = 0, 0
    for rec in analyzer.records():
        if rec.primitive in _HOST_PRIMS:
            sites += 1
            total += max(
                sum(aval_bytes(getattr(v, "aval", None)) for v in rec.eqn.invars
                    if hasattr(v, "aval")),
                sum(aval_bytes(v.aval) for v in rec.eqn.outvars if hasattr(v, "aval")))
    if total <= budget:
        return []
    return [Finding(
        rule="R012", severity=WARN, scenario=program.name,
        message=f"{total} bytes cross the host boundary per step over {sites} "
                f"site(s) (budget {budget}): every dispatch pays this transfer",
        location="host")]


# ---------------------------------------------------------------------------
# R013 — the cost ratchet
# ---------------------------------------------------------------------------
_BASELINE_PROGRAM_KEYS = {"peak_bytes", "peak_transient_bytes", "bytes_moved",
                          "collective_counts"}
_BASELINE_TOP_KEYS = {"version", "tolerance", "programs", "jax_version"}


def load_cost_baseline(path: str) -> Dict:
    """Committed cost baseline, unknown keys rejected loudly (a typo'd
    key would silently stop ratcheting the metric it meant to pin)."""
    if not os.path.exists(path):
        return {"version": COST_BASELINE_VERSION, "tolerance": DEFAULT_TOLERANCE,
                "programs": {}}
    with open(path) as fh:
        baseline = json.load(fh)
    if baseline.get("version") != COST_BASELINE_VERSION:
        raise ValueError(f"cost baseline {path} has version {baseline.get('version')}, "
                         f"expected {COST_BASELINE_VERSION} — regenerate with "
                         f"--cost --update-baseline")
    unknown = set(baseline) - _BASELINE_TOP_KEYS
    if unknown:
        raise ValueError(f"cost baseline {path} has unknown top-level keys "
                         f"{sorted(unknown)}")
    for name, entry in baseline.get("programs", {}).items():
        bad = set(entry) - _BASELINE_PROGRAM_KEYS
        if bad:
            raise ValueError(f"cost baseline entry {name!r} has unknown keys "
                             f"{sorted(bad)} (valid: {sorted(_BASELINE_PROGRAM_KEYS)})")
    baseline.setdefault("tolerance", DEFAULT_TOLERANCE)
    baseline.setdefault("programs", {})
    return baseline


def cost_baseline_from(cost_by_program: Dict[str, CostInfo],
                       prior: Optional[Dict] = None,
                       tolerance: float = DEFAULT_TOLERANCE) -> Dict:
    """A baseline acknowledging the current costs. MERGE semantics: a
    subset run (``--scenarios a,b --update-baseline``) refreshes only its
    own programs' entries — unlike the fingerprint baseline, dropping an
    entry here would *loosen* the ratchet for every untouched scenario."""
    import jax
    programs = dict((prior or {}).get("programs", {}))
    for name, cost in cost_by_program.items():
        programs[name] = {
            "peak_bytes": cost.memory.peak_bytes,
            "peak_transient_bytes": cost.memory.peak_transient_bytes,
            "bytes_moved": cost.bytes_moved(),
            "collective_counts": {layer: cost.counts(layer)
                                  for layer in cost.inventory},
        }
    return {"version": COST_BASELINE_VERSION,
            "tolerance": (prior or {}).get("tolerance", tolerance),
            "jax_version": jax.__version__,
            "programs": dict(sorted(programs.items()))}


@rule("R013", "static cost must not regress vs the committed baseline", ERROR, LAYER_COST)
def r013_cost_ratchet(cost_by_program: Dict[str, CostInfo],
                      baseline: Dict) -> List[Finding]:
    """The quantitative ratchet: per scenario, statically estimated peak
    bytes (total + transient), analytic wire bytes per inventory layer,
    and per-kind collective counts may not grow past the committed
    baseline (relative ``tolerance``, 64 KiB absolute floor for the byte
    metrics). Shrinkage and new scenarios report as INFO so improvements
    get banked explicitly with ``--cost --update-baseline``, never
    silently."""
    tol = float(baseline.get("tolerance", DEFAULT_TOLERANCE))
    findings: List[Finding] = []
    for name, cost in sorted(cost_by_program.items()):
        entry = baseline.get("programs", {}).get(name)
        if entry is None:
            findings.append(Finding(
                rule="R013", severity=INFO, scenario=name,
                message="no cost baseline entry — bank with --cost --update-baseline"))
            continue
        current = {"peak_bytes": cost.memory.peak_bytes,
                   "peak_transient_bytes": cost.memory.peak_transient_bytes}
        for metric, cur in current.items():
            base = entry.get(metric)
            if base is None:
                continue
            if cur > base * (1 + tol) and cur - base > _ABS_FLOOR:
                findings.append(Finding(
                    rule="R013", severity=ERROR, scenario=name,
                    message=f"cost regression: {metric} {cur / 2**20:.2f} MiB vs "
                            f"baseline {base / 2**20:.2f} MiB (tolerance {tol:.0%})",
                    location=metric))
            elif base > cur * (1 + tol) and base - cur > _ABS_FLOOR:
                findings.append(Finding(
                    rule="R013", severity=INFO, scenario=name,
                    message=f"cost improvement: {metric} {cur / 2**20:.2f} MiB vs "
                            f"baseline {base / 2**20:.2f} MiB — bank with "
                            f"--update-baseline",
                    location=metric))
        moved = cost.bytes_moved()
        for layer, base_moved in (entry.get("bytes_moved") or {}).items():
            cur_moved = moved.get(layer)
            if cur_moved is None:
                continue  # layer absent this run (e.g. compile skipped)
            if cur_moved > base_moved * (1 + tol) and cur_moved - base_moved > _ABS_FLOOR:
                findings.append(Finding(
                    rule="R013", severity=ERROR, scenario=name,
                    message=f"comms regression: {layer}-layer wire bytes "
                            f"{cur_moved} vs baseline {base_moved} (tolerance {tol:.0%})",
                    location=f"bytes_moved:{layer}"))
        for layer, base_counts in (entry.get("collective_counts") or {}).items():
            cur_counts = cost.counts(layer) if layer in cost.inventory else None
            if cur_counts is None:
                # layer absent this run (e.g. --no-compile): can't compare
                continue
            # union of kinds: a KIND the baseline never saw is exactly the
            # "new collectives appeared" class this rule exists to catch
            for kind in sorted(set(base_counts) | set(cur_counts)):
                base_n, cur_n = base_counts.get(kind, 0), cur_counts.get(kind, 0)
                if cur_n > base_n:
                    findings.append(Finding(
                        rule="R013", severity=ERROR, scenario=name,
                        message=f"comms regression: {cur_n} {kind}@{layer} vs "
                                f"baseline {base_n} — new collectives appeared",
                        location=f"counts:{layer}:{kind}"))
        # an inventory LAYER the baseline has no entry for (e.g. the
        # baseline was banked with --no-compile) can't be ratcheted —
        # surface it instead of silently skipping
        for layer in sorted(set(cost.inventory) - set(entry.get("collective_counts") or {})):
            if cost.counts(layer):
                findings.append(Finding(
                    rule="R013", severity=INFO, scenario=name,
                    message=f"{layer}-layer inventory has no baseline entry — "
                            f"bank with --cost --update-baseline",
                    location=f"counts:{layer}"))
    return findings


# ---------------------------------------------------------------------------
def run_cost_rules(program: ProgramInfo, cost: CostInfo,
                   analyzer: Optional[ProgramAnalyzer] = None) -> List[Finding]:
    """R009-R012 for one program (R013 is cross-program: see
    :func:`r013_cost_ratchet`)."""
    findings: List[Finding] = []
    findings.extend(r009_collective_signature(program, cost))
    findings.extend(r010_activation_budget(program, cost))
    findings.extend(r011_redundant_collectives(program, cost, analyzer))
    findings.extend(r012_host_transfer_bytes(program, cost, analyzer))
    return findings


def cost_engine_program(engine, example_batch, compile: bool = False,  # noqa: A002
                        programs: Optional[Dict] = None) -> Dict[str, Any]:
    """The compact static-cost evidence perf_ladder stamps next to a
    banked TFLOPS number: predicted peak bytes (total + transient) and
    analytic wire bytes per inventory layer. Trace-only by default — a
    chip window must not pay a second compile for evidence. Pass
    ``programs`` (a prior ``engine.traced_programs`` result) to share
    one trace with the lint evidence instead of re-tracing the step."""
    programs = programs or engine.traced_programs(example_batch)
    step = programs["train_step"]
    info = ProgramInfo(name="engine_train_step", jaxpr=step["jaxpr"],
                       hlo_text=step["hlo_text"], kind="train_step",
                       metadata=step["metadata"], lower=step.get("lower"))
    cost = build_cost(info, compile=compile)
    return {
        "cost_peak_bytes": cost.memory.peak_bytes,
        "cost_peak_transient_bytes": cost.memory.peak_transient_bytes,
        "cost_comms_bytes": cost.bytes_moved(),
        "cost_collectives": {layer: cost.counts(layer) for layer in cost.inventory},
        "cost_hlo_layers": sorted(cost.inventory),
    }


def static_price_from_jaxpr(closed_jaxpr, metadata: Optional[Dict] = None,
                            name: str = "program",
                            kind: str = "train_step") -> Dict[str, Any]:
    """Jaxpr-only static price of ONE closed jaxpr — no StableHLO
    lowering (the same fast path graft-search prices candidates on):
    ``flops_proxy`` (trip-count-weighted dot FLOPs), liveness
    ``peak_bytes``/``peak_transient_bytes``, analytic jaxpr-layer
    ``bytes_moved``, and the eqn count (the R015 identity metric). The
    shared pricer behind the training run header AND the serving
    scheduler's program price — both stamp this dict so graft-calibrate
    fits every scope in the same units."""
    from deepspeed_tpu.analysis.search import flops_proxy

    metadata = metadata or {}
    info = ProgramInfo(name=name, jaxpr=closed_jaxpr, kind=kind,
                       metadata=metadata)
    mem = estimate_memory(info)
    analyzer = ProgramAnalyzer(info)
    ops = hlo_cost.jaxpr_collectives(analyzer, metadata.get("mesh_axes"))
    inv = hlo_cost.inventory(ops)
    return {"flops_proxy": int(flops_proxy(closed_jaxpr)),
            "peak_bytes": int(mem.peak_bytes),
            "peak_transient_bytes": int(mem.peak_transient_bytes),
            "bytes_moved": int(sum(e["bytes_moved"] for e in inv.values())),
            "eqns": int(mem.eqns)}


def static_price_from_programs(programs: Dict) -> Dict[str, Any]:
    """The step program's static price from a prior
    ``engine.traced_programs(batch, lower=False)`` result. This is what
    the telemetry run header stamps so every run's JSONL carries the
    prediction its drift events are measured against."""
    step = programs["train_step"]
    return static_price_from_jaxpr(step["jaxpr"], metadata=step["metadata"],
                                   name="engine_train_step", kind="train_step")
