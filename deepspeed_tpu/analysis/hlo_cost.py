"""Collective inventory + analytic bytes-moved model over traced programs.

The DeepSpeed blueprint's comms claims are countable: ZeRO-3 partitions
gradients with reduce-scatter not all-reduce, the sorted MoE route pays
exactly two capacity-bounded all-to-alls per layer, the pipe scan moves
one boundary activation per tick over ``collective_permute``. This module
turns a traced program into a list of :class:`CollectiveOp`s so R009 can
gate those signatures and R013 can ratchet total wire bytes.

Three inventory layers, honest about what each can see:

* ``jaxpr`` — explicit collective primitives (``psum``/``ppermute``/
  ``all_gather``/``psum_scatter``/``all_to_all``; only ``shard_map``
  regions have them, e.g. the pipe engine) **plus** *logical* collectives:
  chained ``sharding_constraint`` pairs (the MoE dispatch/combine
  G-sharded→E-sharded reshard idiom — a capacity-bounded all-to-all in
  intent, whatever GSPMD lowers it to). Backend-independent.
* ``stablehlo`` — ``stablehlo.all_reduce`` etc. in the lowered module
  (again only manual regions; GSPMD programs carry ``Sharding`` custom
  calls, not collectives, before partitioning).
* ``compiled`` — the post-SPMD, post-optimization HLO of
  ``lowered.compile().as_text()``: the collectives that actually run.
  **Backend caveat (measured on the pinned jax 0.4.37 CPU container):**
  XLA:CPU decomposes reduce-scatter into all-reduce + dynamic-slice, so
  kind-exact reduce-scatter expectations must be declared per-backend
  (R009 ``backends`` field) and are *inventoried as unchecked* elsewhere
  rather than silently passed. (All-to-all survives on CPU — as a
  tuple-typed variadic op, which the parser handles.)

The per-op analytic model (``CollectiveOp.bytes_moved``) is the standard
ring/bidirectional-exchange cost **per participant** — the number that
must stay flat as the mesh grows:

=================  =================================
all_reduce          ``2 * bytes_in * (g-1)/g``
all_gather          ``bytes_out * (g-1)/g``
reduce_scatter      ``bytes_in * (g-1)/g``
all_to_all          ``bytes_in * (g-1)/g``
collective_permute  ``bytes_in``
resharding          ``bytes_in`` (whole-buffer upper bound)
=================  =================================
"""

import dataclasses
import re
from typing import Any, Dict, Iterable, List, Optional, Tuple

import numpy as np

from deepspeed_tpu.analysis.program import ProgramAnalyzer, aval_bytes

#: canonical collective kinds (plus the jaxpr-only logical kinds
#: ``resharding`` and ``dense_dispatch`` counted by the cost engine)
KINDS = ("all_reduce", "all_gather", "reduce_scatter", "all_to_all",
         "collective_permute")

#: jaxpr primitive -> canonical kind (psum2 is shard_map's rep-rewritten
#: psum on jax 0.4.37; check_rep=False regions keep plain psum)
_PRIM_KIND = {
    "psum": "all_reduce",
    "psum2": "all_reduce",
    "pmax": "all_reduce",
    "pmin": "all_reduce",
    "all_gather": "all_gather",
    "psum_scatter": "reduce_scatter",
    "all_to_all": "all_to_all",
    "ppermute": "collective_permute",
}

_MLIR_DTYPE_BYTES = {"f64": 8, "f32": 4, "f16": 2, "bf16": 2, "f8E4M3FN": 1,
                     "i64": 8, "ui64": 8, "i32": 4, "ui32": 4, "i16": 2,
                     "ui16": 2, "i8": 1, "ui8": 1, "i1": 1}
_HLO_DTYPE_BYTES = {"f64": 8, "f32": 4, "f16": 2, "bf16": 2, "s64": 8,
                    "u64": 8, "s32": 4, "u32": 4, "s16": 2, "u16": 2,
                    "s8": 1, "u8": 1, "pred": 1, "c64": 8, "c128": 16}


@dataclasses.dataclass
class CollectiveOp:
    """One collective (or logical-collective) site in one inventory
    layer."""

    kind: str
    layer: str  # jaxpr | stablehlo | compiled
    bytes_in: int
    bytes_out: int
    group_size: int  # participants per replica group (0 = unknown)
    n_groups: int
    axes: str  # mesh-axis attribution ("pipe", "data+fsdp", "g4", "unknown")
    scope: str = ""  # jaxpr scope path or HLO op name

    def bytes_moved(self) -> int:
        """Analytic wire bytes per participant (module docstring table).
        Unknown group size conservatively uses the g->inf factor of 1."""
        g = self.group_size
        f = (g - 1) / g if g > 1 else (0.0 if g == 1 else 1.0)
        if self.kind == "all_reduce":
            return int(2 * self.bytes_in * f)
        if self.kind == "all_gather":
            return int(self.bytes_out * f)
        if self.kind in ("reduce_scatter", "all_to_all"):
            return int(self.bytes_in * f)
        if self.kind == "collective_permute":
            return self.bytes_in
        return self.bytes_in  # resharding: whole-buffer upper bound

    def to_dict(self) -> dict:
        d = dataclasses.asdict(self)
        d["bytes_moved"] = self.bytes_moved()
        return d


# ---------------------------------------------------------------------------
# jaxpr layer
# ---------------------------------------------------------------------------
def _axis_names(params: dict) -> Tuple[str, ...]:
    axes = params.get("axes") or params.get("axis_name") or ()
    if isinstance(axes, str):
        axes = (axes,)
    return tuple(str(a) for a in axes)


def _group_size(axes: Tuple[str, ...], mesh_axes: Dict[str, int]) -> int:
    if not axes:
        return 0
    size = 1
    for a in axes:
        if a not in mesh_axes:
            return 0
        size *= int(mesh_axes[a])
    return size


def jaxpr_collectives(analyzer: ProgramAnalyzer,
                      mesh_axes: Optional[Dict[str, int]] = None) -> List[CollectiveOp]:
    """Explicit collective primitives + chained-constraint logical
    reshardings from the shared analyzer walk."""
    mesh_axes = dict(mesh_axes or {})
    total_devices = int(np.prod(list(mesh_axes.values()))) if mesh_axes else 0
    ops: List[CollectiveOp] = []
    producer = {}
    for rec in analyzer.records():
        for v in rec.eqn.outvars:
            producer[id(v)] = rec
    for rec in analyzer.records():
        prim = rec.primitive
        kind = _PRIM_KIND.get(prim)
        if kind is not None:
            bytes_in = sum(aval_bytes(getattr(v, "aval", None))
                           for v in rec.eqn.invars if hasattr(v, "aval"))
            bytes_out = sum(aval_bytes(v.aval) for v in rec.eqn.outvars
                            if hasattr(v, "aval"))
            axes = _axis_names(rec.eqn.params)
            g = _group_size(axes, mesh_axes) or int(rec.eqn.params.get("axis_size", 0) or 0)
            ops.append(CollectiveOp(
                kind=kind, layer="jaxpr", bytes_in=bytes_in, bytes_out=bytes_out,
                group_size=g,
                n_groups=(total_devices // g) if (g and total_devices) else 0,
                axes="+".join(axes) or "unknown", scope=rec.scope))
        elif prim == "sharding_constraint":
            # a constraint whose operand is itself a fresh constraint output
            # is an explicit reshard: the MoE dispatch/combine a2a idiom
            src = rec.eqn.invars[0] if rec.eqn.invars else None
            src_rec = producer.get(id(src))
            if src_rec is not None and src_rec.primitive == "sharding_constraint":
                nbytes = aval_bytes(getattr(src, "aval", None))
                ops.append(CollectiveOp(
                    kind="resharding", layer="jaxpr", bytes_in=nbytes,
                    bytes_out=nbytes, group_size=0, n_groups=0,
                    axes="reshard", scope=rec.scope))
    return ops


# ---------------------------------------------------------------------------
# StableHLO layer
# ---------------------------------------------------------------------------
def _mlir_tensor_bytes(spec: str) -> int:
    """``"2x4xf32"`` (or ``"f32"`` for rank 0) -> bytes."""
    parts = spec.split("x")
    dtype = parts[-1]
    n = 1
    for p in parts[:-1]:
        if not p.isdigit():
            return 0  # dynamic dims: not our programs
        n *= int(p)
    return n * _MLIR_DTYPE_BYTES.get(dtype, 0)


_STABLEHLO_OP = re.compile(r"stablehlo\.(all_reduce|all_gather|all_to_all|"
                           r"reduce_scatter|collective_permute)\W")
_MLIR_GROUPS = re.compile(r"replica_groups\s*=\s*dense<[^>]*>\s*:\s*"
                          r"tensor<(\d+)x(\d+)xi64>")
_MLIR_PAIRS = re.compile(r"source_target_pairs\s*=\s*dense<[^>]*>\s*:\s*"
                         r"tensor<(\d+)x2xi64>")
_MLIR_SIG = re.compile(r":\s*\(tensor<([^>]+)>[^)]*\)\s*->\s*\(?tensor<([^>]+)>")


def stablehlo_collectives(text: str) -> List[CollectiveOp]:
    """Parse collective ops out of lowered StableHLO text. The reduction
    region of ``all_reduce`` spans lines, so each op is judged on a
    bounded window from its mnemonic to its type signature."""
    ops = []
    for m in _STABLEHLO_OP.finditer(text):
        window = text[m.start():m.start() + 6000]
        kind = m.group(1)
        groups = _MLIR_GROUPS.search(window)
        pairs = _MLIR_PAIRS.search(window)
        sig = _MLIR_SIG.search(window)
        bytes_in = _mlir_tensor_bytes(sig.group(1)) if sig else 0
        bytes_out = _mlir_tensor_bytes(sig.group(2)) if sig else 0
        if kind == "collective_permute":
            g, n = 2, int(pairs.group(1)) if pairs else 0
        else:
            n, g = (int(groups.group(1)), int(groups.group(2))) if groups else (0, 0)
        ops.append(CollectiveOp(kind=kind, layer="stablehlo", bytes_in=bytes_in,
                                bytes_out=bytes_out, group_size=g, n_groups=n,
                                axes="unknown"))
    return ops


# ---------------------------------------------------------------------------
# compiled (post-SPMD) layer
# ---------------------------------------------------------------------------
#: result type is either one array type or a tuple (async -start pairs on
#: TPU: "(f32[8]{0}, f32[64]{0}) all-gather-start(...)")
_HLO_OP = re.compile(r"%(\S+)\s*=\s*(\([^)]*\)|[a-z0-9]+\[[^\]=]*\]\S*)\s+"
                     r"(all-reduce|all-gather|all-to-all|reduce-scatter|"
                     r"collective-permute)(-start)?\(")
_HLO_OPERAND = re.compile(r"([a-z0-9]+)\[([\d,]*)\]\S*\s+%")
_HLO_GROUPS_EXPLICIT = re.compile(r"replica_groups=\{(\{[\d,]*\}(?:,\{[\d,]*\})*)\}")
_HLO_GROUPS_IOTA = re.compile(r"replica_groups=\[(\d+),(\d+)\]<=\[([\d,]+)\]"
                              r"(?:T\(([\d,]+)\))?")
_HLO_PAIRS = re.compile(r"source_target_pairs=\{(\{[\d,]+\}(?:,\{[\d,]+\})*)\}")


def _hlo_type_bytes(spec: str) -> int:
    m = re.match(r"([a-z0-9]+)\[([\d,]*)\]", spec)
    if not m:
        return 0
    n = 1
    for d in m.group(2).split(","):
        if d:
            n *= int(d)
    return n * _HLO_DTYPE_BYTES.get(m.group(1), 0)


def parse_replica_groups(line: str) -> Tuple[List[Tuple[int, ...]], int, int]:
    """(explicit groups, n_groups, group_size) from either HLO syntax;
    groups may be empty when only the iota shape was recoverable."""
    m = _HLO_GROUPS_EXPLICIT.search(line)
    if m:
        groups = [tuple(int(x) for x in grp.split(",") if x)
                  for grp in re.findall(r"\{([\d,]*)\}", m.group(0))]
        groups = [g for g in groups if g]
        if groups:
            return groups, len(groups), len(groups[0])
    m = _HLO_GROUPS_IOTA.search(line)
    if m:
        n, g = int(m.group(1)), int(m.group(2))
        reshape = [int(x) for x in m.group(3).split(",")]
        ids = np.arange(int(np.prod(reshape))).reshape(reshape)
        if m.group(4):
            ids = ids.transpose([int(x) for x in m.group(4).split(",")])
        groups = [tuple(int(x) for x in row) for row in ids.reshape(n, g)]
        return groups, n, g
    return [], 0, 0


def infer_axes(groups: List[Tuple[int, ...]],
               mesh_axes: Optional[Dict[str, int]]) -> str:
    """Name the mesh axis (or axis pair) a replica-group set communicates
    over, by regenerating each candidate's groups from the row-major mesh
    layout. Falls back to ``"full"`` / ``"g<size>"``."""
    if not groups:
        return "unknown"
    if not mesh_axes:
        return f"g{len(groups[0])}"
    names = list(mesh_axes)
    shape = [int(mesh_axes[a]) for a in names]
    n = int(np.prod(shape))
    if sum(len(g) for g in groups) != n:
        return f"g{len(groups[0])}"
    want = {frozenset(g) for g in groups}
    if want == {frozenset(range(n))}:
        return "full"
    ids = np.arange(n).reshape(shape)

    def groups_over(axis_idxs):
        moved = np.moveaxis(ids, axis_idxs, range(-len(axis_idxs), 0))
        rows = moved.reshape(-1, int(np.prod([shape[i] for i in axis_idxs])))
        return {frozenset(int(x) for x in row) for row in rows}

    for i, name in enumerate(names):
        if shape[i] > 1 and groups_over([i]) == want:
            return name
    for i in range(len(names)):
        for j in range(i + 1, len(names)):
            if shape[i] * shape[j] > 1 and groups_over([i, j]) == want:
                return f"{names[i]}+{names[j]}"
    return f"g{len(groups[0])}"


def compiled_collectives(text: str,
                         mesh_axes: Optional[Dict[str, int]] = None) -> List[CollectiveOp]:
    """Inventory the post-optimization HLO — the collectives that actually
    run on this backend (module docstring caveat: CPU decomposes RS/A2A)."""
    ops = []
    for line in text.splitlines():
        m = _HLO_OP.search(line)
        if m:
            kind = m.group(3).replace("-", "_")
            call = line[m.end():]
            bytes_in = 0
            for t, dims in _HLO_OPERAND.findall(call):
                n = 1
                for d in dims.split(","):
                    if d:
                        n *= int(d)
                bytes_in += n * _HLO_DTYPE_BYTES.get(t, 0)
            result = m.group(2)
            if result.startswith("("):
                # async tuple (operand alias, result, ...): the largest
                # element is the gathered/reduced payload
                bytes_out = max((_hlo_type_bytes(t) for t in
                                 re.findall(r"[a-z0-9]+\[[\d,]*\]", result)),
                                default=0)
            else:
                bytes_out = _hlo_type_bytes(result)
            if kind == "collective_permute":
                p = _HLO_PAIRS.search(line)
                n_pairs = len(re.findall(r"\{[\d,]+\}", p.group(1))) if p else 0
                ops.append(CollectiveOp(kind=kind, layer="compiled",
                                        bytes_in=bytes_in or bytes_out,
                                        bytes_out=bytes_out, group_size=2,
                                        n_groups=n_pairs, axes=_permute_axes(mesh_axes),
                                        scope=m.group(1)))
            else:
                groups, n, g = parse_replica_groups(line)
                ops.append(CollectiveOp(kind=kind, layer="compiled",
                                        bytes_in=bytes_in or bytes_out,
                                        bytes_out=bytes_out, group_size=g,
                                        n_groups=n,
                                        axes=infer_axes(groups, mesh_axes),
                                        scope=m.group(1)))
    return ops


def _permute_axes(mesh_axes):
    return "permute" if mesh_axes else "unknown"


# ---------------------------------------------------------------------------
def inventory(ops: Iterable[CollectiveOp]) -> Dict[str, Dict[str, Any]]:
    """Per-layer summary: op counts per kind + total analytic wire bytes —
    the shape R013 ratchets and perf_ladder rows embed."""
    out: Dict[str, Dict[str, Any]] = {}
    for op in ops:
        layer = out.setdefault(op.layer, {"counts": {}, "bytes_moved": 0,
                                          "bytes_by_axis": {}})
        layer["counts"][op.kind] = layer["counts"].get(op.kind, 0) + 1
        moved = op.bytes_moved()
        layer["bytes_moved"] += moved
        layer["bytes_by_axis"][op.axes] = layer["bytes_by_axis"].get(op.axes, 0) + moved
    for layer in out.values():
        layer["counts"] = dict(sorted(layer["counts"].items()))
        layer["bytes_by_axis"] = dict(sorted(layer["bytes_by_axis"].items(),
                                             key=lambda kv: -kv[1]))
    return out
