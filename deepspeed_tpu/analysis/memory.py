"""Static memory estimator: jaxpr-level buffer liveness -> peak live bytes.

graft-lint's boolean rules (R001..R008) can say *whether* a program does
something; the ROADMAP's open items are quantitative — "the chunked-wave
pipe schedule holds ~2x the 1F1B activation bound", "donation halves peak
state HBM" — and until now those numbers were only checkable on chip via
``compiled.memory_analysis()`` during scarce chip windows. This module
computes a backend-independent estimate from the traced jaxpr alone, so
the activation-bound gate (R010) and the cost ratchet (R013) run on every
CPU tier-1 pass.

Model
-----
A closed jaxpr is a linear schedule of eqns. Every variable is a buffer:
defined by one eqn (or as a program input), dead after its last consumer
(program outputs stay live to the end). Peak live bytes is the max over
schedule slots of the sum of live buffer sizes, plus — at the slot of an
eqn that carries sub-jaxprs (``pjit``/``scan``/``cond``/``remat2``/...)
— the sub-program's *internal transient peak* (its own peak minus its
boundary buffers, which the outer level already counts).

Two headline numbers per program:

* ``peak_bytes`` — everything live at the worst slot, inputs included.
  An **undonated upper bound**: donation (an HLO-layer property) aliases
  old state into new and is deliberately ignored, so the estimate cannot
  be gamed by aliasing it away.
* ``peak_transient_bytes`` — the same walk with top-level inputs
  (params, optimizer state, batch) excluded: the activations and temps
  the *schedule* controls. This is the number R010 judges against a
  declared activation budget, and the number the 1F1B refactor must
  drive down; donation does not move it.

Accuracy contract: this is a *scheduling* estimate, not a simulator —
XLA fuses, rematerializes and buffer-shares below this level. The
cross-check against ``compiled.memory_analysis()`` (where the backend
provides it) is tolerance-banded, not exact; see
``tests/unit/analysis/test_memory.py``.
"""

import dataclasses
import itertools
from typing import Any, Dict, List, Optional, Tuple

from deepspeed_tpu.analysis.program import _scope_label, aval_bytes

#: how many of the largest live buffers to name in the peak attribution
_TOP_LIVE = 8


@dataclasses.dataclass
class MemoryEstimate:
    """Static peak-liveness estimate for one traced program."""

    peak_bytes: int
    peak_transient_bytes: int
    input_bytes: int  # top-level invars + consts
    output_bytes: int
    eqns: int  # total eqns walked (all nesting levels)
    by_scope: Dict[str, int]  # live bytes at the peak slot, per defining scope
    top_live: List[Dict[str, Any]]  # largest live buffers at the peak slot
    #: largest non-input buffers at the TRANSIENT peak slot (R010's
    #: attribution — can be a different schedule slot than top_live's)
    top_transient: List[Dict[str, Any]] = dataclasses.field(default_factory=list)

    def to_dict(self) -> dict:
        return dataclasses.asdict(self)


def _is_tracked(v) -> bool:
    """Real jaxpr Vars only: Literals are inline constants (no buffer of
    their own worth tracking), DropVars are dead on arrival (XLA DCEs
    them)."""
    return hasattr(v, "aval") and type(v).__name__ not in ("Literal", "DropVar")


class _Liveness:
    """One liveness walk over one (sub-)jaxpr.

    Schedule slots: slot 0 = program entry (inputs become live), slot i+1
    = eqn i (operands must be live, outputs become live), slot T+1 =
    program exit (outputs still live). ``sub_peaks(cache)`` recursion
    bottoms out because jaxprs are acyclic.
    """

    def __init__(self, jaxpr, scope_path: Tuple[str, ...] = (),
                 cache: Optional[dict] = None):
        self.jaxpr = jaxpr
        self.scope_path = scope_path
        self.cache = cache if cache is not None else {}
        self.T = len(jaxpr.eqns)
        # var -> [def_slot, last_slot, nbytes, scope, is_input]
        self.vars: Dict[Any, list] = {}
        self.inner_extra = [0] * (self.T + 2)
        self.total_eqns = self.T
        self._walk()

    def _walk(self):
        scope = "/".join(self.scope_path) or "<top>"
        for v in itertools.chain(self.jaxpr.constvars, self.jaxpr.invars):
            if _is_tracked(v):
                self.vars[v] = [0, 0, aval_bytes(v.aval), "<inputs>", True]
        from deepspeed_tpu.analysis.program import _iter_sub_jaxprs
        for i, eqn in enumerate(self.jaxpr.eqns):
            slot = i + 1
            for v in eqn.invars:
                if _is_tracked(v) and v in self.vars:
                    self.vars[v][1] = max(self.vars[v][1], slot)
            for v in eqn.outvars:
                if _is_tracked(v):
                    self.vars[v] = [slot, slot, aval_bytes(v.aval), scope, False]
            # sub-jaxprs run *inside* this slot; alternatives (cond
            # branches) and single bodies both take the max internal
            # transient peak
            extra = 0
            for key, value in eqn.params.items():
                for sub, _ in _iter_sub_jaxprs(value):
                    sub_peak, sub_io, sub_eqns = self._sub_summary(
                        sub, self.scope_path + (_scope_label(eqn),))
                    extra = max(extra, max(0, sub_peak - sub_io))
                    self.total_eqns += sub_eqns
            self.inner_extra[slot] = extra
        for v in self.jaxpr.outvars:
            if _is_tracked(v) and v in self.vars:
                self.vars[v][1] = self.T + 1

    def _sub_summary(self, sub, sub_path) -> Tuple[int, int, int]:
        """(peak, boundary io bytes, eqn count) for a nested jaxpr.
        Cached by identity — pjit bodies repeat across call sites."""
        hit = self.cache.get(id(sub))
        if hit is not None:
            return hit
        lv = _Liveness(sub, sub_path, self.cache)
        peak, _ = lv.peaks()
        io = sum(aval_bytes(v.aval)
                 for v in itertools.chain(sub.constvars, sub.invars, sub.outvars)
                 if _is_tracked(v))
        self.cache[id(sub)] = (peak, io, lv.total_eqns)
        return self.cache[id(sub)]

    # ------------------------------------------------------------------
    def _timeline(self, include_inputs: bool) -> List[int]:
        diff = [0] * (self.T + 3)
        for def_slot, last_slot, nbytes, _, is_input in self.vars.values():
            if is_input and not include_inputs:
                continue
            diff[def_slot] += nbytes
            diff[last_slot + 1] -= nbytes
        live, acc = [], 0
        for s in range(self.T + 2):
            acc += diff[s]
            live.append(acc + self.inner_extra[s])
        return live

    def peaks(self) -> Tuple[int, int]:
        """(peak slot value, argmax slot) over the inputs-included
        timeline."""
        live = self._timeline(include_inputs=True)
        peak = max(live)
        return peak, live.index(peak)

    def transient_peak(self) -> Tuple[int, int]:
        """(peak, argmax slot) over the inputs-excluded timeline. The
        argmax can differ from the total timeline's (params dominate
        early, activations late) — R010's attribution must read THIS
        slot."""
        live = self._timeline(include_inputs=False)
        peak = max(live)
        return peak, live.index(peak)

    def live_at(self, slot: int):
        """The buffers live at ``slot``, largest first."""
        out = []
        for v, (d, l, nbytes, scope, is_input) in self.vars.items():
            if d <= slot <= l and nbytes > 0:
                out.append((nbytes, tuple(getattr(v.aval, "shape", ())),
                            str(getattr(v.aval, "dtype", "?")), scope, is_input))
        out.sort(key=lambda t: -t[0])
        return out


def estimate_memory(program_or_jaxpr) -> MemoryEstimate:
    """Estimate peak live bytes for a :class:`ProgramInfo` (or a bare
    ``ClosedJaxpr``). The per-scope attribution names where the bytes at
    the peak slot were *defined* — the handle the remat-policy and
    1F1B levers need."""
    closed = getattr(program_or_jaxpr, "jaxpr", program_or_jaxpr)
    if hasattr(closed, "jaxpr"):  # ClosedJaxpr -> open jaxpr
        open_jaxpr = closed.jaxpr
    else:
        open_jaxpr = closed
    lv = _Liveness(open_jaxpr)
    peak, peak_slot = lv.peaks()
    transient_peak, transient_slot = lv.transient_peak()
    live = lv.live_at(peak_slot)
    by_scope: Dict[str, int] = {}
    for nbytes, _, _, scope, _ in live:
        by_scope[scope] = by_scope.get(scope, 0) + nbytes
    if lv.inner_extra[peak_slot]:
        by_scope["<nested transients>"] = lv.inner_extra[peak_slot]
    top = [{"bytes": n, "shape": list(shape), "dtype": dt, "scope": scope}
           for n, shape, dt, scope, _ in live[:_TOP_LIVE]]
    top_transient = [{"bytes": n, "shape": list(shape), "dtype": dt, "scope": scope}
                     for n, shape, dt, scope, is_input
                     in lv.live_at(transient_slot) if not is_input][:_TOP_LIVE]
    input_bytes = sum(aval_bytes(v.aval)
                      for v in itertools.chain(open_jaxpr.constvars, open_jaxpr.invars)
                      if _is_tracked(v))
    output_bytes = sum(aval_bytes(v.aval) for v in open_jaxpr.outvars if _is_tracked(v))
    return MemoryEstimate(
        peak_bytes=peak,
        peak_transient_bytes=transient_peak,
        input_bytes=input_bytes,
        output_bytes=output_bytes,
        eqns=lv.total_eqns,
        by_scope=dict(sorted(by_scope.items(), key=lambda kv: -kv[1])),
        top_live=top,
        top_transient=top_transient,
    )
