"""ProgramAnalyzer: one shared walk over a traced program.

A :class:`ProgramInfo` bundles what graft-lint knows about one traced
program: its closed jaxpr, optionally the lowered StableHLO text (the
layer where donation/aliasing is visible — jaxpr-level ``donated_invars``
only exist on pjit eqns), and free-form ``metadata`` the scenario
builder supplies (the MoE ``[S,E,C]`` signature, whether the program is
the parity path, whether it runs on a multi-device mesh, size
thresholds).

:class:`ProgramAnalyzer` walks the jaxpr ONCE — recursing into every
sub-jaxpr it can find in eqn params (``pjit``/``scan``/``while``/
``cond`` branches/``remat2``/``custom_vjp``/``shard_map``), whether
stored as ``ClosedJaxpr``, open ``Jaxpr``, or tuples of either — and
caches flat :class:`EqnRecord`s that every rule then iterates. Scope
paths (``pjit:train_step/scan/remat2``) give findings a human-readable
location and give the precision rule its attribution key.
"""

import itertools
from typing import Any, Dict, Iterator, List, NamedTuple, Optional, Tuple

import numpy as np

import jax

# the public aliases exist on 0.4.37 (jax.extend.core); fall back to the
# private module defensively for other pins
try:
    from jax.extend.core import ClosedJaxpr, Jaxpr
except ImportError:  # pragma: no cover
    from jax.core import ClosedJaxpr, Jaxpr


class EqnRecord(NamedTuple):
    eqn: Any  # JaxprEqn
    path: Tuple[str, ...]  # enclosing sub-jaxpr scopes, outermost first
    in_remat: bool  # inside a remat/checkpoint region

    @property
    def primitive(self) -> str:
        return self.eqn.primitive.name

    @property
    def scope(self) -> str:
        return "/".join(self.path) or "<top>"


class ProgramInfo:
    """One traced program + everything a rule may need to judge it.

    ``lower`` is an optional zero-arg thunk returning the program's
    ``jax.stages.Lowered`` — the cost engine (``analysis/cost.py``) calls
    it (then ``.compile()``) only in the ``--cost`` pass, so plain lint
    runs stay trace-only. The compiled executable is cached: its
    ``as_text()`` is the post-SPMD collective inventory and its
    ``cost_analysis()``/``memory_analysis()`` cross-check the static
    memory estimate."""

    def __init__(self, name: str, jaxpr: Optional[ClosedJaxpr] = None,
                 hlo_text: Optional[str] = None, kind: str = "fwd_bwd",
                 metadata: Optional[Dict[str, Any]] = None,
                 lower=None):
        assert jaxpr is not None or hlo_text is not None, name
        self.name = name
        self.jaxpr = jaxpr
        self.hlo_text = hlo_text
        self.kind = kind  # fwd_bwd | train_step | layer | fixture
        self.metadata = dict(metadata or {})
        self.lower = lower
        self._compiled = None

    def compiled(self):
        """The compiled executable, or None when no lowering thunk was
        attached. Exceptions propagate — the caller records them as the
        program's ``compile_error`` evidence."""
        if self._compiled is None and self.lower is not None:
            self._compiled = self.lower().compile()
        return self._compiled


def aval_bytes(aval) -> int:
    shape = getattr(aval, "shape", None)
    dtype = getattr(aval, "dtype", None)
    if shape is None or dtype is None:
        return 0
    try:
        itemsize = np.dtype(dtype).itemsize
    except TypeError:  # extended dtypes (typed PRNG keys) aren't numpy dtypes
        itemsize = getattr(dtype, "itemsize", 0) or 0
    return int(np.prod(shape, dtype=np.int64)) * itemsize if shape else itemsize


def _iter_sub_jaxprs(value) -> Iterator[Tuple[Jaxpr, Optional[Any]]]:
    """Yield (open_jaxpr, consts_or_None) for every jaxpr nested in an eqn
    param value, whatever container it hides in (cond stores a tuple of
    ClosedJaxprs under ``branches``; remat2 stores an open Jaxpr)."""
    if isinstance(value, ClosedJaxpr):
        yield value.jaxpr, value.consts
    elif isinstance(value, Jaxpr):
        yield value, None
    elif isinstance(value, (tuple, list)):
        for v in value:
            yield from _iter_sub_jaxprs(v)
    elif isinstance(value, dict):
        for v in value.values():
            yield from _iter_sub_jaxprs(v)


_REMAT_PRIMS = ("remat", "remat2", "checkpoint")


def _scope_label(eqn) -> str:
    name = eqn.primitive.name
    inner = eqn.params.get("name")
    return f"{name}:{inner}" if isinstance(inner, str) and inner else name


class ProgramAnalyzer:
    """The cached single walk; rules share one instance per program."""

    def __init__(self, program: ProgramInfo):
        self.program = program
        self._records: List[EqnRecord] = []
        self.metrics: Dict[str, Any] = {}  # rules may deposit attribution here
        if program.jaxpr is not None:
            self._walk(program.jaxpr.jaxpr, (), False)

    def _walk(self, jaxpr: Jaxpr, path: Tuple[str, ...], in_remat: bool):
        for eqn in jaxpr.eqns:
            prim = eqn.primitive.name
            self._records.append(EqnRecord(eqn, path, in_remat))
            sub_remat = in_remat or any(prim.startswith(r) for r in _REMAT_PRIMS)
            for key, value in eqn.params.items():
                for sub, _ in _iter_sub_jaxprs(value):
                    self._walk(sub, path + (_scope_label(eqn),), sub_remat)

    # ------------------------------------------------------------------
    def records(self) -> List[EqnRecord]:
        return self._records

    def iter_avals(self, outputs_only: bool = False) -> Iterator[Tuple[EqnRecord, Any]]:
        """(record, aval) over eqn outvars (and invars unless
        ``outputs_only``) — invars included so rules see top-level-input
        shapes flowing into eqns, deduped per eqn by identity."""
        for rec in self._records:
            vs = rec.eqn.outvars if outputs_only else itertools.chain(rec.eqn.invars, rec.eqn.outvars)
            for v in vs:
                aval = getattr(v, "aval", None)
                if aval is not None and getattr(aval, "shape", None) is not None:
                    yield rec, aval

    def count_primitive(self, name: str) -> int:
        return sum(1 for r in self._records if r.primitive == name)

    def top_invars(self):
        return list(self.program.jaxpr.jaxpr.invars) if self.program.jaxpr is not None else []

    # ------------------------------------------------------------------
    def has_sharding_evidence(self) -> bool:
        """True when the program visibly participates in SPMD placement:
        an explicit ``sharding_constraint``, a ``shard_map`` region, or a
        pjit whose in/out shardings are not all unspecified."""
        for rec in self._records:
            if rec.primitive in ("sharding_constraint", "shard_map"):
                return True
            if rec.primitive == "pjit":
                for key in ("in_shardings", "out_shardings"):
                    for s in rec.eqn.params.get(key) or ():
                        if s is not None and "Unspecified" not in type(s).__name__:
                            return True
        return False


def run_program_rules(program: ProgramInfo, rules=None,
                      analyzer: Optional["ProgramAnalyzer"] = None) -> Tuple[List, Dict[str, Any]]:
    """Run every (or the given) jaxpr/hlo-layer rule against one program.
    Returns ``(findings, metrics)`` — metrics carry rule attributions
    (e.g. R002's per-scope precision-upcast counts) into the report.
    Pass ``analyzer`` to share one cached walk with the cost pass."""
    from deepspeed_tpu.analysis import rules as _rules  # noqa: F401 — registers on import
    from deepspeed_tpu.analysis.core import RULES, program_rules

    selected = program_rules() if rules is None else [RULES[r] for r in rules]
    bad = [r.id for r in selected if r.layer not in ("jaxpr", "hlo")]
    if bad:
        raise ValueError(f"{bad} are {'an ' if len(bad) == 1 else ''}non-program-layer rule(s) — "
                         f"ast rules take source files (tools/graft_lint.py --ast-only), "
                         f"cost rules need the cost engine (tools/graft_lint.py --cost)")
    analyzer = analyzer or ProgramAnalyzer(program)
    findings = []
    for r in selected:
        if r.layer == "jaxpr" and program.jaxpr is None:
            continue
        findings.extend(r.check(program, analyzer))
    return findings, analyzer.metrics
