"""Report + baseline layer: JSON emission and the CI gate semantics.

``lint_<sig>.json`` is the evidence artifact (``analysis_results/``,
next to the autotuner's winner files): per-program rule hit counts,
waivers in effect, precision attribution, and every finding with its
stable fingerprint. The committed ``baseline.json`` holds the set of
acknowledged ERROR fingerprints; the CLI exits non-zero only on *new*
unwaived ERRORs, so the gate can hold the line while known debt is
burned down explicitly (same contract as a ratcheting type-checker)."""

import hashlib
import json
import os
import time
from typing import Dict, Iterable, List, Optional, Tuple

from deepspeed_tpu.analysis.core import ERROR, Finding

BASELINE_VERSION = 1


def matrix_signature(program_names: Iterable[str]) -> str:
    """Short stable id for 'which matrix produced this report' — the
    report filename key, so reports from different scenario subsets
    don't overwrite each other."""
    import jax
    raw = ",".join(sorted(program_names)) + "|" + jax.__version__
    return hashlib.sha1(raw.encode()).hexdigest()[:10]


def summarize(findings: List[Finding]) -> Dict:
    """Rule hit counts split by status — the shape perf_ladder evidence
    rows embed (rule_hits / waived / errors / clean)."""
    hits: Dict[str, int] = {}
    waived = errors = 0
    for f in findings:
        hits[f.rule] = hits.get(f.rule, 0) + 1
        if f.waived:
            waived += 1
        elif f.severity == ERROR:
            errors += 1
    return {"rule_hits": dict(sorted(hits.items())), "waived": waived,
            "errors": errors, "clean": errors == 0}


def build_report(per_program: Dict[str, Tuple[List[Finding], Dict]],
                 ast_findings: List[Finding],
                 skipped: Optional[Dict[str, str]] = None,
                 waivers_in_effect: Optional[List[dict]] = None,
                 cost_by_program: Optional[Dict] = None,
                 stale_waivers: Optional[List[dict]] = None) -> Dict:
    import jax
    all_findings = [f for fs, _ in per_program.values() for f in fs] + list(ast_findings)
    report = {
        "tool": "graft-lint",
        "version": BASELINE_VERSION,
        "jax_version": jax.__version__,
        "generated_unix": int(time.time()),
        "programs": {
            name: {"summary": summarize(fs), "metrics": metrics}
            for name, (fs, metrics) in per_program.items()
        },
        "ast": {"summary": summarize(list(ast_findings))},
        # structured blocking gaps ({"kind", "detail"} per skipped
        # scenario, scenarios.ScenarioSkipped.kind): the composition
        # scenario's first blocking gap is a ratchetable metric here, not
        # a prose string (ROADMAP-5 burn-down)
        "skipped_scenarios": dict(skipped or {}),
        "waivers_in_effect": list(waivers_in_effect or []),
        # waivers that covered no current finding: dead acknowledgements
        # to prune, surfaced as WARNs by the CLI (never gating)
        "stale_waivers": list(stale_waivers or []),
        "summary": summarize(all_findings),
        "findings": [f.to_dict() for f in all_findings],
    }
    if cost_by_program is not None:
        # the --cost pass: per-program static memory estimate + collective
        # inventory + backend cross-check (analysis/cost.py)
        report["cost"] = {name: cost.to_dict()
                          for name, cost in sorted(cost_by_program.items())}
    return report


def write_report(report: Dict, out_dir: str, sig: str) -> str:
    os.makedirs(out_dir, exist_ok=True)
    path = os.path.join(out_dir, f"lint_{sig}.json")
    with open(path, "w") as fh:
        json.dump(report, fh, indent=2, sort_keys=False)
        fh.write("\n")
    return path


def rules_markdown() -> str:
    """The README rule table, generated FROM the registry (``graft_lint
    --rules-md``). The README embeds this output verbatim and a tier-1
    test asserts every registry row is present, so a new rule can never
    ship with stale docs again (the R013 drift this replaced)."""
    from deepspeed_tpu.analysis.core import RULES
    lines = ["| rule | severity | layer | what it gates |",
             "|------|----------|-------|---------------|"]
    for r in sorted(RULES.values(), key=lambda r: r.id):
        lines.append(f"| {r.id} | {r.severity} | {r.layer} | {r.title} |")
    return "\n".join(lines)


# ---------------------------------------------------------------------------
def load_baseline(path: str) -> Dict:
    if not os.path.exists(path):
        return {"version": BASELINE_VERSION, "fingerprints": {}}
    with open(path) as fh:
        baseline = json.load(fh)
    if baseline.get("version") != BASELINE_VERSION:
        raise ValueError(f"baseline {path} has version {baseline.get('version')}, "
                         f"expected {BASELINE_VERSION} — regenerate with --update-baseline")
    return baseline


def baseline_from(findings: Iterable[Finding]) -> Dict:
    """A baseline acknowledging every current UNWAIVED ERROR — the
    ratchet's starting tooth. Waived findings are already acknowledged by
    their waiver (which travels with the code/config) and must not also
    occupy a baseline slot a future unwaived finding could hide behind."""
    fps = {}
    for f in findings:
        if f.severity == ERROR and not f.waived:
            fps[f.fingerprint()] = {"rule": f.rule, "scenario": f.scenario,
                                    "message": f.message}
    return {"version": BASELINE_VERSION, "fingerprints": dict(sorted(fps.items()))}


def new_errors(findings: Iterable[Finding], baseline: Dict) -> List[Finding]:
    """The gate: unwaived ERROR findings whose fingerprint the baseline
    does not acknowledge."""
    known = set(baseline.get("fingerprints", {}))
    return [f for f in findings
            if f.severity == ERROR and not f.waived and f.fingerprint() not in known]
