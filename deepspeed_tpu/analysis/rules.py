"""Program-layer rules R001–R007 and R015.

Each rule converts one piece of this repo's accumulated perf/correctness
folklore into an enforced check (ISSUE 7; the per-rule history is cited
inline). Severities: ERROR findings gate the CLI against the baseline;
WARN findings report (and feed evidence rows) without gating.
"""

import itertools
from typing import List

import numpy as np

import jax.numpy as jnp

from deepspeed_tpu.analysis.core import ERROR, INFO, LAYER_HLO, LAYER_JAXPR, WARN, Finding, rule
from deepspeed_tpu.analysis.program import aval_bytes

_MAX_SITES = 8  # per-rule per-program cap: first N deduped sites + a summary line


def _cap(findings: List[Finding], rule_id: str, scenario: str, suppressed: int) -> List[Finding]:
    """Append one INFO marker when deduped sites were dropped at the cap.
    INFO (never gates) with a count-free message: a count would make the
    fingerprint churn with unrelated edits and trip the baseline ratchet
    on noise."""
    if suppressed > 0:
        findings.append(Finding(rule=rule_id, severity=INFO, scenario=scenario,
                                message=f"additional sites suppressed (cap {_MAX_SITES})"))
    return findings


# ---------------------------------------------------------------------------
@rule("R001", "no dense [*,S,E,C] intermediate in MoE programs", ERROR, LAYER_JAXPR)
def r001_dense_sec(program, analyzer):
    """The dense GShard einsum route materializes a ``[G,S,E,C]``
    combine-weights tensor and pays O(S*E*C*M) in fwd AND bwd for what is
    a gather of <=k*S rows (PR 6 measured 49x dispatch+combine and 11.6x
    peak-bytes CPU wins from eliminating it). Any aval whose trailing
    shape matches a declared ``(S, E, C)`` signature — scenario metadata
    ``moe_sec``, from ``sharded_moe.sec_signature`` — anywhere in the
    program (including sub-jaxprs under remat/scan/pjit) is a
    reintroduction of the dense route."""
    sigs = [tuple(s) for s in program.metadata.get("moe_sec", ())]
    if not sigs:
        return []
    findings, seen, suppressed = [], set(), 0
    for rec, aval in analyzer.iter_avals():
        tail = tuple(aval.shape)[-3:]
        if tail in sigs:
            key = (tuple(aval.shape), rec.scope)
            if key in seen:
                continue
            seen.add(key)
            if len(findings) >= _MAX_SITES:
                suppressed += 1
                continue
            findings.append(Finding(
                rule="R001", severity=ERROR, scenario=program.name,
                message=f"dense [*,S,E,C] intermediate {tuple(aval.shape)} "
                        f"matches MoE signature (S,E,C)={tail}",
                location=rec.scope))
    return _cap(findings, "R001", program.name, suppressed)


# ---------------------------------------------------------------------------
_FLOAT_WIDTH = {"bfloat16": 16, "float16": 16, "float32": 32, "float64": 64}
_DEFAULT_PRECISION_ALLOWLIST = (
    # scopes where a local fp32 upcast is the *intended* numerics (mirrors
    # the pinned-precision parity levers, SURVEY.md:338): normalization
    # statistics, softmax/logsumexp, loss accumulation, optimizer moments
    "norm", "softmax", "logsumexp", "lse", "loss", "xent", "l_aux", "adam",
    "scale", "logits",
)


@rule("R002", "no silent precision widening on the parity path", ERROR, LAYER_JAXPR)
def r002_precision(program, analyzer):
    """The bit-identical parity envelope (ROADMAP item 4, 47-ULP gap)
    dies by a thousand silent dtype widenings. Two checks: (a) float64
    anywhere is an ERROR — no TPU path wants f64, it is always a leaked
    python float or numpy default; (b) on programs marked
    ``parity: True``, each 16->32-bit float upcast outside the allowlist
    scopes is a WARN, and ALL upcasts are tallied per (src->dst, scope)
    into the report's ``precision_attribution`` metric — the per-op
    attribution that feeds the ULP hunt."""
    allow_f64 = program.metadata.get("allow_f64", False)
    allowlist = tuple(program.metadata.get("precision_allowlist",
                                           _DEFAULT_PRECISION_ALLOWLIST))
    parity = program.metadata.get("parity", False)
    findings, seen64, suppressed64 = [], set(), 0
    attribution = {}
    for rec, aval in analyzer.iter_avals(outputs_only=True):
        if not allow_f64 and getattr(aval, "dtype", None) == jnp.float64:
            key = (tuple(aval.shape), rec.scope)
            if key in seen64:
                continue
            seen64.add(key)
            if len(findings) >= _MAX_SITES:
                suppressed64 += 1
                continue
            findings.append(Finding(
                rule="R002", severity=ERROR, scenario=program.name,
                message=f"float64 value {tuple(aval.shape)} in traced program",
                location=rec.scope))
    findings = _cap(findings, "R002", program.name, suppressed64)

    warned = set()
    for rec in analyzer.records():
        if rec.primitive != "convert_element_type":
            continue
        src = getattr(rec.eqn.invars[0].aval, "dtype", None)
        dst = rec.eqn.params.get("new_dtype")
        if src is None or dst is None:
            continue
        sw, dw = _FLOAT_WIDTH.get(str(src)), _FLOAT_WIDTH.get(str(np.dtype(dst)))
        if sw is None or dw is None or dw <= sw:
            continue  # not a float upcast
        key = f"{src}->{np.dtype(dst)} @ {rec.scope}"
        attribution[key] = attribution.get(key, 0) + 1
        scope_l = rec.scope.lower()
        if parity and not any(a in scope_l for a in allowlist) and key not in warned:
            warned.add(key)
            if sum(1 for f in findings if f.severity == WARN) < _MAX_SITES:
                findings.append(Finding(
                    rule="R002", severity=WARN, scenario=program.name,
                    message=f"silent float upcast {src}->{np.dtype(dst)} outside "
                            f"precision allowlist on parity path",
                    location=rec.scope))
    if attribution:
        analyzer.metrics["precision_attribution"] = dict(
            sorted(attribution.items(), key=lambda kv: -kv[1]))
    return findings


# ---------------------------------------------------------------------------
_HOST_PRIMS = {
    "device_put": ERROR,  # host<->device copy inside the step: a sync + a
    # transfer every dispatch, and on the 0.4.37 CPU container the
    # zero-copy alias hazard (utils/device.py)
    "io_callback": ERROR,
    "pure_callback": ERROR,
    "outside_call": ERROR,
    "infeed": ERROR,
    "outfeed": ERROR,
    "debug_callback": WARN,  # jax.debug.print/callback: host sync per step
}


@rule("R003", "no host transfer/callback inside a jitted step", ERROR, LAYER_JAXPR)
def r003_host_transfer(program, analyzer):
    """A ``device_put`` or host callback traced INTO the step program
    forces a host round-trip every dispatch — the exact class of silent
    step-time regression the MFU campaign (ROADMAP item 3) cannot afford.
    Host staging belongs outside the step (``_shard_batch``), not inside
    it. ``metadata["allow_callbacks"]`` exempts named primitives for
    programs that intentionally stream (e.g. offload paths)."""
    allowed = set(program.metadata.get("allow_callbacks", ()))
    findings, suppressed = [], 0
    for rec in analyzer.records():
        sev = _HOST_PRIMS.get(rec.primitive)
        if sev is None or rec.primitive in allowed:
            continue
        if len(findings) >= _MAX_SITES:
            suppressed += 1
            continue
        findings.append(Finding(
            rule="R003", severity=sev, scenario=program.name,
            message=f"host primitive '{rec.primitive}' inside traced step",
            location=rec.scope))
    return _cap(findings, "R003", program.name, suppressed)


# ---------------------------------------------------------------------------
@rule("R004", "large fwd activation outside the remat policy", WARN, LAYER_JAXPR)
def r004_remat_coverage(program, analyzer):
    """When a program uses remat at all (or the scenario declares
    ``expect_remat``), every activation above ``remat_threshold_bytes``
    (default 16 MiB) produced OUTSIDE a remat region is a residual the
    autodiff must hold live across the backward — exactly the non-matmul
    HBM sink the MFU campaign's remat-policy lever targets (ROADMAP 3a).
    Inside-remat values are rematerialized, not saved. Judged on the
    FORWARD program: under ``grad``'s partial-eval the covered primal is
    inlined to the top level, so coverage is only visible pre-transform
    (scenario builders hand R004 fwd jaxprs; on fwd+bwd programs the rule
    still flags genuinely uncovered fwd activations, plus their inlined
    shadows — same shapes, same fix)."""
    threshold = int(program.metadata.get("remat_threshold_bytes", 16 << 20))
    uses_remat = any(r.in_remat or r.primitive.startswith(("remat", "checkpoint"))
                     for r in analyzer.records())
    if not uses_remat and not program.metadata.get("expect_remat"):
        return []
    findings, seen, suppressed = [], set(), 0
    for rec, aval in analyzer.iter_avals(outputs_only=True):
        if rec.in_remat or rec.primitive.startswith(("remat", "checkpoint")):
            continue
        nbytes = aval_bytes(aval)
        if nbytes <= threshold:
            continue
        key = tuple(aval.shape)
        if key in seen:
            continue
        seen.add(key)
        if len(findings) >= _MAX_SITES:
            suppressed += 1
            continue
        findings.append(Finding(
            rule="R004", severity=WARN, scenario=program.name,
            message=f"activation {tuple(aval.shape)} ({nbytes >> 20} MiB) produced "
                    f"outside remat coverage (threshold {threshold >> 20} MiB)",
            location=rec.scope))
    return _cap(findings, "R004", program.name, suppressed)


# ---------------------------------------------------------------------------
@rule("R005", "step programs must donate their state buffers", ERROR, LAYER_HLO)
def r005_donation(program, analyzer):
    """A train step that does not donate its state doubles peak HBM (old
    + new TrainState live across the update) — the single largest static
    memory lever the engine owns (``donate_argnums`` on every step fn).
    Checked at the HLO layer, where donation is visible as
    ``tf.aliasing_output``/``jax.buffer_donor`` argument attributes; a
    duplicate output alias (two args donated into one output) would be
    the aliased-donation corruption class from utils/device.py."""
    if not program.metadata.get("expect_donation"):
        return []
    hlo = program.hlo_text
    if hlo is None:
        return [Finding(rule="R005", severity=INFO, scenario=program.name,
                        message="expect_donation set but no lowered HLO attached; "
                                "donation not verifiable at the jaxpr layer alone")]
    findings = []
    if "tf.aliasing_output" not in hlo and "jax.buffer_donor" not in hlo:
        findings.append(Finding(
            rule="R005", severity=ERROR, scenario=program.name,
            message="no donated buffers in lowered step program "
                    "(missing tf.aliasing_output/jax.buffer_donor): "
                    "old+new state both live across the update"))
    else:
        import re
        targets = re.findall(r"tf\.aliasing_output\s*=\s*(\d+)", hlo)
        dupes = {t for t in targets if targets.count(t) > 1}
        if dupes:
            findings.append(Finding(
                rule="R005", severity=ERROR, scenario=program.name,
                message=f"multiple arguments donate into output(s) {sorted(dupes)} — "
                        f"aliased donation"))
    return findings


# ---------------------------------------------------------------------------
@rule("R006", "no weak-typed (python scalar) program inputs", WARN, LAYER_JAXPR)
def r006_weak_types(program, analyzer):
    """A weak-typed top-level input means a raw python scalar reached the
    traced signature: the jit cache then keys on the scalar's *value
    class*, and a later call with a numpy/jnp scalar (or a different
    python type) silently recompiles the whole step — the recompilation
    hazard class behind 'why did step 1000 take 40 s'."""
    findings = []
    for i, v in enumerate(analyzer.top_invars()):
        aval = getattr(v, "aval", None)
        if aval is not None and getattr(aval, "weak_type", False):
            findings.append(Finding(
                rule="R006", severity=WARN, scenario=program.name,
                message=f"program input {i} is weak-typed "
                        f"({getattr(aval, 'dtype', '?')}) — python scalar leaked "
                        f"into the traced signature",
                location=f"invar[{i}]"))
    return findings


# ---------------------------------------------------------------------------
@rule("R007", "large intermediates need sharding on multi-device meshes", WARN, LAYER_JAXPR)
def r007_sharding_coverage(program, analyzer):
    """On a >1-device mesh, a program with NO sharding evidence anywhere
    (no ``sharding_constraint``, no ``shard_map``, no sharded pjit
    binding) leaves GSPMD free to replicate every large intermediate —
    an implicit all-gather per step. Declared via scenario metadata
    ``multi_device``; ``shard_threshold_bytes`` (default 8 MiB) bounds
    what counts as large."""
    if not program.metadata.get("multi_device"):
        return []
    if analyzer.has_sharding_evidence():
        return []
    threshold = int(program.metadata.get("shard_threshold_bytes", 8 << 20))
    findings, seen, suppressed = [], set(), 0
    for rec, aval in analyzer.iter_avals(outputs_only=True):
        nbytes = aval_bytes(aval)
        if nbytes <= threshold:
            continue
        key = tuple(aval.shape)
        if key in seen:
            continue
        seen.add(key)
        if len(findings) >= _MAX_SITES:
            suppressed += 1
            continue
        findings.append(Finding(
            rule="R007", severity=WARN, scenario=program.name,
            message=f"unsharded intermediate {tuple(aval.shape)} ({nbytes >> 20} MiB) "
                    f"in a multi-device program with no sharding constraints",
            location=rec.scope))
    return _cap(findings, "R007", program.name, suppressed)


# ---------------------------------------------------------------------------
@rule("R015", "telemetry must not enter the traced step program", ERROR, LAYER_JAXPR)
def r015_telemetry_identity(program, analyzer):
    """graft-trace (runtime/telemetry) instruments HOST phases only: spans
    wrap staging/dispatch/wait around the jitted step, never inside it. A
    single stray ``io_callback``/``debug_print``/eager sync traced into
    the step would silently tax every dispatch (the R003 class) — so the
    ``train_batch_telemetry`` scenario stamps ``expect_eqn_count``, the
    recursive eqn count of the SAME engine program traced telemetry-off,
    and this rule fails on any divergence. Zero tolerance on purpose: the
    two traces differ only by the telemetry config block, so any eqn
    delta IS instrumentation leaking into the compiled program."""
    expected = program.metadata.get("expect_eqn_count")
    if expected is None:
        return []
    actual = len(analyzer.records())
    if actual != int(expected):
        return [Finding(
            rule="R015", severity=ERROR, scenario=program.name,
            message=f"traced step has {actual} eqns but its telemetry-off twin "
                    f"has {expected} — instrumentation entered the compiled program",
            location="<jaxpr>")]
    return []
