"""The graft-lint scenario matrix: representative traced programs.

Each scenario builder traces one real program shape the repo ships —
model fwd+bwd (gpt2/llama/bert), the MoE sorted route (top1/top2, where
R001's ``[S,E,C]`` ban has teeth), the pipeline scan step, and the
engine's full ``train_batch`` step (the parity path, where donation and
precision are judged). Builders TRACE only — ``jax.make_jaxpr`` /
``.lower()`` — no compilation, no device buffers beyond tiny init
params, so the whole matrix runs on CPU in seconds and can gate CI
between chip windows.

Scenario metadata is where repo knowledge enters the rules: the MoE
scenarios declare their banned ``(S, E, C)`` signature via
``sharded_moe.sec_signature`` (single source with the gating cores);
``train_batch`` declares ``parity``/``expect_donation``; multi-device
scenarios declare ``multi_device``.

Route/kernel resolution inside the MoE scenarios goes through
``moe.routing.resolve_route`` (no explicit kwarg), so a forced
``DS_MOE_ROUTE=dense`` env — the seeded-regression acceptance check —
flows into the traced program exactly as it would into a bench run.
"""

from typing import Callable, Dict, List, Optional

import numpy as np

import jax
import jax.numpy as jnp

from deepspeed_tpu.analysis.program import ProgramInfo

SCENARIOS: Dict[str, Callable[[], ProgramInfo]] = {}


class ScenarioSkipped(Exception):
    """Raised by a builder when its program cannot trace on this runtime
    (e.g. partial-manual shard_map on jax 0.4.37) — reported, not fatal.
    ``kind`` is a stable machine-readable gap class so reports carry a
    structured ``blocking_gap: {kind, detail}`` instead of a prose string
    (the ROADMAP-5 burn-down reads the kind, not the wording)."""

    def __init__(self, detail: str, kind: str = "other"):
        super().__init__(detail)
        self.kind = kind


#: the composition scenario's gap burn-down order (ROADMAP item 5): each
#: entry blocks the ones after it, so progress is strictly monotone in
#: this list and the ratchet test (tests/unit/analysis/test_scenarios.py)
#: asserts the current gap's rank never moves backward.
COMPOSITION_GAP_ORDER = ("device_count", "partial_manual", "moe_in_pipe", "none")


def composition_gap_rank(kind: str) -> int:
    """Rank of a gap kind in the burn-down order; unknown kinds rank -1
    (strictly behind every known gap — a regression by definition)."""
    try:
        return COMPOSITION_GAP_ORDER.index(kind)
    except ValueError:
        return -1


def composition_blocking_gap() -> Dict[str, str]:
    """Build the ROADMAP-5 composition scenario and report its FIRST
    blocking gap as structured data: ``{"kind", "detail"}``, with kind
    ``"none"`` once the full pipe x expert x tensor x fsdp + qgZ program
    traces clean."""
    try:
        SCENARIOS["composition_3d_ep_zeropp"]()
    except ScenarioSkipped as e:
        return {"kind": e.kind, "detail": str(e)}
    return {"kind": "none", "detail": "composition traces clean"}


def scenario(name: str):
    def wrap(fn):
        SCENARIOS[name] = fn
        return fn

    return wrap


def _model_fwd_bwd(name, model, variables, loss):
    grad = jax.grad(loss)
    return ProgramInfo(name=name, jaxpr=jax.make_jaxpr(grad)(variables),
                       kind="fwd_bwd",
                       # the --cost pass compiles on demand for the
                       # post-SPMD collective inventory + backend
                       # memory/flops cross-check; plain runs never call it
                       lower=lambda: jax.jit(grad).lower(variables))


# ---------------------------------------------------------------------------
@scenario("gpt2_fwd_bwd")
def gpt2_fwd_bwd() -> ProgramInfo:
    from deepspeed_tpu.models import GPT2LMHeadModel, get_gpt2_config

    cfg = get_gpt2_config("test")
    model = GPT2LMHeadModel(cfg)
    ids = jnp.zeros((2, 32), jnp.int32)
    variables = model.init(jax.random.PRNGKey(0), ids)

    def loss(v):
        out = model.apply(v, ids)
        logits = out[0] if isinstance(out, tuple) else out
        return logits.astype(jnp.float32).sum()

    return _model_fwd_bwd("gpt2_fwd_bwd", model, variables, loss)


@scenario("llama_fwd_bwd")
def llama_fwd_bwd() -> ProgramInfo:
    from deepspeed_tpu.models import LlamaForCausalLM, get_llama_config

    cfg = get_llama_config("test")
    model = LlamaForCausalLM(cfg)
    ids = jnp.zeros((2, 32), jnp.int32)
    variables = model.init(jax.random.PRNGKey(0), ids)

    def loss(v):
        out = model.apply(v, ids)
        logits = out[0] if isinstance(out, tuple) else out
        return logits.astype(jnp.float32).sum()

    return _model_fwd_bwd("llama_fwd_bwd", model, variables, loss)


@scenario("bert_fwd_bwd")
def bert_fwd_bwd() -> ProgramInfo:
    from deepspeed_tpu.models import BertForMaskedLM, get_bert_config

    cfg = get_bert_config("test")
    model = BertForMaskedLM(cfg)
    ids = jnp.zeros((2, 32), jnp.int32)
    variables = model.init(jax.random.PRNGKey(0), ids)

    def loss(v):
        out = model.apply(v, ids)
        logits = out[0] if isinstance(out, tuple) else out
        return logits.astype(jnp.float32).sum()

    return _model_fwd_bwd("bert_fwd_bwd", model, variables, loss)


# ---------------------------------------------------------------------------
def _moe_program(name: str, k: int) -> ProgramInfo:
    import flax.linen as nn

    from deepspeed_tpu.moe.sharded_moe import MOELayer, sec_signature

    class _Expert(nn.Module):
        @nn.compact
        def __call__(self, x, deterministic=True):
            return nn.Dense(x.shape[-1], use_bias=False)(x)

    B, L, M, E, cf, min_cap = 2, 16, 8, 4, 1.0, 1
    S = B * L  # one group without a topology
    # no explicit route kwarg: resolution flows through env/config exactly
    # like a bench run, so DS_MOE_ROUTE=dense seeds the R001 regression
    layer = MOELayer(expert=_Expert(), model_dim=M, num_experts=E, k=k,
                     capacity_factor=cf, eval_capacity_factor=cf,
                     min_capacity=min_cap)
    x = jnp.zeros((B, L, M), jnp.float32)
    variables = layer.init(jax.random.PRNGKey(0), x)

    def loss(v, xx):
        (out, l_aux, _), _ = layer.apply(v, xx, mutable=["intermediates"])
        return (out ** 2).sum() + l_aux

    grad = jax.grad(loss, argnums=(0, 1))
    jaxpr = jax.make_jaxpr(grad)(variables, x)
    return ProgramInfo(
        name=name, jaxpr=jaxpr, kind="fwd_bwd",
        lower=lambda: jax.jit(grad).lower(variables, x),
        metadata={"moe_sec": [sec_signature(S, E, cf, min_cap, k=k)],
                  # the committed intent is the sorted route: zero dense
                  # [S,E,C] einsums feeding the dispatch/combine endpoints.
                  # DS_MOE_ROUTE=dense drifts the traced program but not
                  # this signature — the R009 seeded regression.
                  "collective_signature": [
                      {"layer": "jaxpr", "kind": "dense_dispatch", "count": 0,
                       "note": "sorted MoE dispatch is a permutation, "
                               "never an [S,E,C] einsum"}]})


@scenario("moe_top1_route")
def moe_top1_route() -> ProgramInfo:
    return _moe_program("moe_top1_route", k=1)


@scenario("moe_top2_route")
def moe_top2_route() -> ProgramInfo:
    return _moe_program("moe_top2_route", k=2)


# ---------------------------------------------------------------------------
def _engine_program(name: str, engine, example_batch, extra_metadata=None) -> ProgramInfo:
    programs = engine.traced_programs(example_batch)
    step = programs["train_step"]
    metadata = dict(step["metadata"])
    for key, value in (extra_metadata or {}).items():
        if key == "collective_signature":  # extend, don't clobber, the
            metadata.setdefault(key, [])   # engine-declared entries
            metadata[key] = list(metadata[key]) + list(value)
        else:
            metadata[key] = value
    return ProgramInfo(name=name, jaxpr=step["jaxpr"], hlo_text=step["hlo_text"],
                       kind="train_step", metadata=metadata,
                       lower=step.get("lower"))


@scenario("train_batch_parity")
def train_batch_parity() -> ProgramInfo:
    """The engine's fused train step for a tiny GPT-2 — the program the
    CPU parity envelope (ROADMAP item 4) judges. ``parity: True`` arms
    R002's upcast attribution; ``expect_donation`` arms R005."""
    import deepspeed_tpu
    from deepspeed_tpu.models import GPT2LMHeadModel, get_gpt2_config
    from deepspeed_tpu.parallel.topology import MeshTopology, set_topology

    set_topology(None)
    try:
        # pinned to the first 8 devices: a GRAFT_LINT_DEVICES=16 run must
        # not shift this program (and its cost baseline entry) onto a
        # different mesh
        topo = (MeshTopology(data=8, devices=jax.devices()[:8])
                if len(jax.devices()) >= 8 else None)
        engine, _, _, _ = deepspeed_tpu.initialize(
            model=GPT2LMHeadModel(get_gpt2_config("test")), topology=topo,
            config={"train_batch_size": 8,
                    "optimizer": {"type": "AdamW", "params": {"lr": 1e-3}},
                    "zero_optimization": {"stage": 0}})
        batch = {"input_ids": np.zeros((8, 32), np.int32)}
        return _engine_program("train_batch_parity", engine, batch,
                               {"parity": True})
    finally:
        set_topology(None)


@scenario("train_batch_telemetry")
def train_batch_telemetry() -> ProgramInfo:
    """The ``train_batch_parity`` engine config with the telemetry block
    ON — the gate that graft-trace instrumentation can never silently
    enter the compiled program. The builder traces the SAME engine twice
    (telemetry-off first, jaxpr-only) and stamps the off-trace's
    recursive eqn count as ``expect_eqn_count``; rule R015 fails on any
    divergence, and R003 must stay clean on the telemetry-on program
    (spans are host-side, so no callback can appear in the jaxpr)."""
    import deepspeed_tpu
    from deepspeed_tpu.analysis.program import ProgramAnalyzer
    from deepspeed_tpu.models import GPT2LMHeadModel, get_gpt2_config
    from deepspeed_tpu.parallel.topology import MeshTopology, set_topology

    base = {"train_batch_size": 8,
            "optimizer": {"type": "AdamW", "params": {"lr": 1e-3}},
            "zero_optimization": {"stage": 0}}
    batch = {"input_ids": np.zeros((8, 32), np.int32)}

    def build(extra):
        topo = (MeshTopology(data=8, devices=jax.devices()[:8])
                if len(jax.devices()) >= 8 else None)
        engine, _, _, _ = deepspeed_tpu.initialize(
            model=GPT2LMHeadModel(get_gpt2_config("test")), topology=topo,
            config={**base, **extra})
        return engine

    set_topology(None)
    try:
        off = build({}).traced_programs(batch, lower=False)["train_step"]
        off_count = len(ProgramAnalyzer(ProgramInfo(
            name="telemetry_off", jaxpr=off["jaxpr"], kind="train_step")).records())
        # enabled telemetry, default output_path: tracing never writes, so
        # no run dir is created (the sink is lazy; the header only lands on
        # a real train_batch)
        engine = build({"telemetry": {"enabled": True}})
        return _engine_program("train_batch_telemetry", engine, batch,
                               {"expect_eqn_count": off_count})
    finally:
        set_topology(None)


@scenario("pipe_scan_step")
def pipe_scan_step() -> ProgramInfo:
    """The pipeline engine's scan step on a pipe=2 mesh (auto axes size 1
    fold to full-manual, so this traces even on the 0.4.37 container —
    jax_compat docstring). Skips, not fails, where shard_map can't."""
    import deepspeed_tpu
    from deepspeed_tpu.models.gpt2 import gpt2_pipe_layers
    from deepspeed_tpu.models import get_gpt2_config
    from deepspeed_tpu.parallel.topology import MeshTopology, set_topology
    from deepspeed_tpu.runtime.pipe.module import PipelineModule

    if len(jax.devices()) < 8:
        raise ScenarioSkipped("pipe_scan_step expects >=8 host devices")
    set_topology(None)
    try:
        cfg = get_gpt2_config("test", n_layer=2)
        topo = MeshTopology(pipe=2, data=2, fsdp=2, devices=jax.devices()[:8])
        pipe = PipelineModule(layers=gpt2_pipe_layers(cfg), topology=topo)
        engine, _, _, _ = deepspeed_tpu.initialize(
            model=pipe, topology=topo,
            config={"train_batch_size": 16, "gradient_accumulation_steps": 4,
                    "optimizer": {"type": "AdamW", "params": {"lr": 1e-3}}})
        batch = {"input_ids": np.zeros((16, 32), np.int32)}
        return _engine_program("pipe_scan_step", engine, batch)
    except NotImplementedError as e:  # partial-manual shard_map gap
        raise ScenarioSkipped(f"shard_map unsupported here: {e}") from e
    finally:
        set_topology(None)


# ---------------------------------------------------------------------------
def _zero_step(name: str, stage: int) -> ProgramInfo:
    """A ZeRO-``stage`` step on a data=2 x fsdp=4 mesh: the program whose
    comms schedule the blueprint quantifies (state sharded over fsdp,
    grads averaged over data). The engine stamps the stage's collective
    signature from ``DeepSpeedZeroConfig.cost_metadata`` — all-gathers
    must exist (sharding is real), the reduce-scatter entry is TPU-judged
    (XLA:CPU decomposes RS into AR+slice; inventoried as unchecked)."""
    import deepspeed_tpu
    from deepspeed_tpu.models import GPT2LMHeadModel, get_gpt2_config
    from deepspeed_tpu.parallel.topology import MeshTopology, set_topology

    if len(jax.devices()) < 8:
        raise ScenarioSkipped(f"{name} expects >=8 host devices")
    set_topology(None)
    try:
        engine, _, _, _ = deepspeed_tpu.initialize(
            model=GPT2LMHeadModel(get_gpt2_config("test")),
            topology=MeshTopology(data=2, fsdp=4, devices=jax.devices()[:8]),
            config={"train_batch_size": 8,
                    "optimizer": {"type": "AdamW", "params": {"lr": 1e-3}},
                    "zero_optimization": {"stage": stage}})
        batch = {"input_ids": np.zeros((8, 32), np.int32)}
        return _engine_program(name, engine, batch)
    finally:
        set_topology(None)


@scenario("zero2_train_step")
def zero2_train_step() -> ProgramInfo:
    return _zero_step("zero2_train_step", stage=2)


@scenario("zero3_train_step")
def zero3_train_step() -> ProgramInfo:
    return _zero_step("zero3_train_step", stage=3)


@scenario("moe_ep_step")
def moe_ep_step() -> ProgramInfo:
    """The engine's MoE step on an expert=4 x data=2 mesh — where the
    sorted route's "exactly two capacity-bounded all-to-alls per layer"
    claim has wire bytes behind it. Each MoE layer applies the
    G-sharded->E-sharded constraint *pair* on the dispatch buffer and its
    mirror on the combine side (2 logical a2a per direction); the cost
    pass counts those chained-constraint reshards at the jaxpr layer,
    backend-independently."""
    import deepspeed_tpu
    from deepspeed_tpu.models import GPT2LMHeadModel, get_gpt2_config
    from deepspeed_tpu.parallel.topology import MeshTopology, set_topology

    if len(jax.devices()) < 8:
        raise ScenarioSkipped("moe_ep_step expects >=8 host devices")
    set_topology(None)
    try:
        cfg = get_gpt2_config("test", moe_num_experts=4, moe_layer_freq=2, moe_k=1)
        engine, _, _, _ = deepspeed_tpu.initialize(
            model=GPT2LMHeadModel(cfg),
            topology=MeshTopology(expert=4, data=2, devices=jax.devices()[:8]),
            config={"train_batch_size": 8,
                    "optimizer": {"type": "AdamW", "params": {"lr": 1e-3}},
                    "zero_optimization": {"stage": 0}})
        batch = {"input_ids": np.zeros((8, 32), np.int32)}
        return _engine_program("moe_ep_step", engine, batch, {
            "collective_signature": [
                {"layer": "jaxpr", "kind": "resharding", "min_count": 4,
                 "note": "2 capacity-bounded a2a reshards per MoE layer "
                         "per direction (dispatch + combine, fwd + bwd)"},
                # ...and the partitioner honors them: exactly 2 a2a per
                # layer per direction in the compiled program (1 MoE
                # layer here -> 4 total). More would mean GSPMD chose a
                # gather-everywhere strategy; fewer, a silently-local
                # (replicated) expert layout.
                {"layer": "compiled", "kind": "all_to_all", "count": 4,
                 "note": "exactly 2 all-to-alls per MoE layer per direction"}]})
    finally:
        set_topology(None)


#: the committed 1F1B activation budget (MiB) for the pipe=2 scenario
#: mesh below. Formula (README "Pipeline parallelism"): stash ring
#: ``2(S-1)`` boundary slots + 2 in transit (S=2: 4 x 16 KiB) + the fp32
#: grad accumulators (~0.6 MiB params) + one tick's recompute transient
#: (block internals + [mb, seq, vocab] epilogue logits) + the optimizer
#: update's own temporaries — measured 1.90 MiB static transient on the
#: pinned container, committed at 2.0 MiB (~5% headroom). Strictly below
#: the chunked schedule's 2.25 MiB measured transient (and its prior
#: 4 MiB commit), so the SAME budget fails the chunked schedule — the
#: ratchet with teeth (test_cost_gate).
PIPE_1F1B_BUDGET_MB = 2.0


def _pipe_engine_program(name: str, pipeline_cfg: dict) -> ProgramInfo:
    """Shared pipe=2-only builder (every auto axis size 1 folds to
    full-manual, so these trace on the 0.4.37 container where
    ``pipe_scan_step``'s pipe x data x fsdp mesh cannot)."""
    import deepspeed_tpu
    from deepspeed_tpu.models import get_gpt2_config
    from deepspeed_tpu.models.gpt2 import gpt2_pipe_layers
    from deepspeed_tpu.parallel.topology import MeshTopology, set_topology
    from deepspeed_tpu.runtime.pipe.module import PipelineModule

    if len(jax.devices()) < 2:
        raise ScenarioSkipped(f"{name} needs >=2 devices")
    set_topology(None)
    try:
        cfg = get_gpt2_config("test", n_layer=2)
        topo = MeshTopology(pipe=2, data=1, devices=jax.devices()[:2])
        pipe = PipelineModule(layers=gpt2_pipe_layers(cfg), topology=topo)
        engine, _, _, _ = deepspeed_tpu.initialize(
            model=pipe, topology=topo,
            config={"train_batch_size": 8, "gradient_accumulation_steps": 4,
                    "pipeline": pipeline_cfg,
                    "optimizer": {"type": "AdamW", "params": {"lr": 1e-3}}})
        batch = {"input_ids": np.zeros((8, 32), np.int32)}
        return _engine_program(name, engine, batch)
    except NotImplementedError as e:  # partial-manual shard_map gap
        raise ScenarioSkipped(f"shard_map unsupported here: {e}") from e
    finally:
        set_topology(None)


@scenario("pipe_chunked_step")
def pipe_chunked_step() -> ProgramInfo:
    """The chunked-wave pipeline schedule — kept as the A/B reference
    against ``pipe_1f1b_step`` (same mesh, same model, same microbatch
    count). Its committed budget is its own measured static transient
    (2.25 MiB) + headroom — tightened from the pre-1F1B 4 MiB commit;
    ``DS_PIPE_ACT_BUDGET_MB`` below the estimate (e.g. the 1F1B bound)
    is the seeded R010 regression proving the chunked schedule cannot
    pass the 1F1B budget."""
    return _pipe_engine_program(
        "pipe_chunked_step",
        # measured 2.25 MiB static transient on the pinned container
        {"chunk_microbatches": 2, "activation_budget_mb": 2.5})


@scenario("pipe_1f1b_step")
def pipe_1f1b_step() -> ProgramInfo:
    """The 1F1B schedule (the default) under its committed activation
    bound (:data:`PIPE_1F1B_BUDGET_MB` — formula in the constant's
    docstring). R010 gates the manual-vjp program's static transient
    against it; R009 pins the 4-``collective_permute`` signature (2 per
    tick boundary across the 3 phase bodies). Any schedule regression —
    an extra stash slot, autodiff residuals sneaking back in, a third
    boundary buffer — fails lint on CPU before a chip window pays for
    it."""
    return _pipe_engine_program(
        "pipe_1f1b_step",
        {"schedule": "1f1b", "activation_budget_mb": PIPE_1F1B_BUDGET_MB})


#: the committed activation budget (MiB) for the graft-serve decode tick
#: below (16 slots x 512 positions, tp=2, tiny GPT-2). Measured static
#: transient on the pinned container: 8.41 MiB with the committed
#: ``scatter`` KV write (4 per-slot scatters, O(slots) bytes each);
#: committed at 9.0 MiB (~7% headroom). The ``dense`` masked-rebuild
#: write measures 10.5 MiB — so ``DS_SERVE_KV_WRITE=dense`` fails R010
#: under this budget, the DS_MOE_ROUTE-pattern seeded regression for a
#: forced/leaked serving knob.
SERVE_DECODE_BUDGET_MB = 9.0


@scenario("serve_decode_step")
def serve_decode_step() -> ProgramInfo:
    """The graft-serve fixed-shape decode tick (inference/serving): one
    token per slot against the per-slot ragged cache, on a tensor=2
    serving mesh so the program carries real post-SPMD collectives. The
    traced program IS the served one — same ``make_apply_fn`` +
    ``build_decode_step`` the scheduler jits — so R009 pins the tp
    collective signature, R010 gates the per-tick transient against
    :data:`SERVE_DECODE_BUDGET_MB`, and R013 ratchets both against the
    committed cost baseline. The KV write strategy resolves through
    env/config exactly like a serve run (``resolve_kv_write``), which is
    what gives ``DS_SERVE_KV_WRITE=dense`` its teeth."""
    import deepspeed_tpu
    from deepspeed_tpu.inference.config import DeepSpeedInferenceConfig
    from deepspeed_tpu.inference.engine import InferenceEngine
    from deepspeed_tpu.inference.serving import make_slot_cache, resolve_intended_kv_write
    from deepspeed_tpu.inference.serving.programs import (build_decode_step,
                                                          make_apply_fn)
    from deepspeed_tpu.models import GPT2LMHeadModel, get_gpt2_config
    from deepspeed_tpu.parallel.topology import MeshTopology, set_topology

    if len(jax.devices()) < 2:
        raise ScenarioSkipped("serve_decode_step needs >=2 devices for the "
                              "tensor=2 serving mesh")
    set_topology(None)
    try:
        slots = 16
        cfg = get_gpt2_config("test", n_layer=2, n_positions=512)
        topo = MeshTopology(tensor=2, data=1, fsdp=1, devices=jax.devices()[:2])
        engine = InferenceEngine(GPT2LMHeadModel(cfg),
                                 DeepSpeedInferenceConfig(), topology=topo)
        cache = make_slot_cache(engine.module, slots)
        decode = build_decode_step(make_apply_fn(engine.module, engine._mparams),
                                   do_sample=False, temperature=1.0, top_k=0,
                                   top_p=1.0)
        tokens = jnp.zeros((slots,), jnp.int32)
        jaxpr = jax.make_jaxpr(decode)(engine.params, cache, tokens)
        return ProgramInfo(
            name="serve_decode_step", jaxpr=jaxpr, kind="serve_decode",
            lower=lambda: jax.jit(decode).lower(engine.params, cache, tokens),
            metadata={
                "serve_slots": slots,
                # the committed intent, env layer skipped — a forced env
                # override drifts the program but never this declaration
                "serve_kv_write": resolve_intended_kv_write(),
                "activation_budget_bytes": int(SERVE_DECODE_BUDGET_MB * 2**20),
                "collective_signature": [
                    # tp=2 row-parallel projections: attention out-proj +
                    # MLP out-proj per block, plus the tied LM head —
                    # 2*n_layer + 1 all-reduces per decode tick
                    {"layer": "compiled", "kind": "all_reduce", "count": 5,
                     "note": "2 all-reduces per block + 1 for the tied "
                             "LM head on the tp=2 serving mesh"},
                    {"layer": "compiled", "kind": "all_gather", "max_count": 2,
                     "note": "at most the two embedding-table gathers — "
                             "more would mean GSPMD re-gathers the KV pool "
                             "per tick"}]})
    finally:
        set_topology(None)


@scenario("composition_3d_ep_zeropp")
def composition_3d_ep_zeropp() -> ProgramInfo:
    """ROADMAP item 5's never-executed full composition: pipe x expert x
    tensor x fsdp (all >=2, 16 virtual devices) with qgZ quantized
    gradients. This builder ATTEMPTS the real construction so the first
    blocking gap on any runtime is *inventoried* in the report's
    skipped-scenarios section instead of staying folklore. On the pinned
    container the chain is: 8 forced host devices (raise with
    ``GRAFT_LINT_DEVICES=16``) -> the jax-0.4.37 partial-manual shard_map
    gap (pipe is manual, expert/tensor/fsdp stay auto at size 2) -> MoE
    blocks unsupported inside the pipelined GPT-2."""
    import deepspeed_tpu
    from deepspeed_tpu.models import get_gpt2_config
    from deepspeed_tpu.models.gpt2 import gpt2_pipe_layers
    from deepspeed_tpu.parallel.topology import MeshTopology, set_topology
    from deepspeed_tpu.utils.jax_compat import PARTIAL_MANUAL_OK
    from deepspeed_tpu.runtime.pipe.module import PipelineModule

    if len(jax.devices()) < 16:
        raise ScenarioSkipped(
            f"needs 16 virtual devices for pipe=2 x expert=2 x tensor=2 x "
            f"fsdp=2 (have {len(jax.devices())}; run tools/graft_lint.py "
            f"with GRAFT_LINT_DEVICES=16)", kind="device_count")
    if not PARTIAL_MANUAL_OK:
        raise ScenarioSkipped(
            "jax-0.4.37 partial-manual shard_map gap: the pipe axis is "
            "manual while expert/tensor/fsdp stay auto at size 2 "
            "(utils/jax_compat.py) — the composition traces on jax>=0.5",
            kind="partial_manual")
    set_topology(None)
    try:
        cfg = get_gpt2_config("test", n_layer=4, moe_num_experts=2,
                              moe_layer_freq=2, moe_k=1)
        topo = MeshTopology(pipe=2, expert=2, tensor=2, fsdp=2, data=1,
                            devices=jax.devices()[:16])
        try:
            layers = gpt2_pipe_layers(cfg)
        except ValueError as e:  # MoE-in-pipe unsupported (aux-loss drop)
            raise ScenarioSkipped(f"MoE blocks in the pipelined GPT-2: {e}",
                                  kind="moe_in_pipe") from e
        pipe = PipelineModule(layers=layers, topology=topo)
        engine, _, _, _ = deepspeed_tpu.initialize(
            model=pipe, topology=topo,
            config={"train_batch_size": 8, "gradient_accumulation_steps": 4,
                    "optimizer": {"type": "AdamW", "params": {"lr": 1e-3}},
                    "zero_optimization": {"stage": 3,
                                          "zero_quantized_gradients": True}})
        batch = {"input_ids": np.zeros((8, 32), np.int32)}
        return _engine_program("composition_3d_ep_zeropp", engine, batch)
    except NotImplementedError as e:
        raise ScenarioSkipped(f"composition untraceable here: {e}",
                              kind="partial_manual") from e
    finally:
        set_topology(None)


# ---------------------------------------------------------------------------
def build(names: Optional[List[str]] = None):
    """Build the matrix. Returns ``(programs, skipped)`` where ``skipped``
    maps each scenario this runtime cannot trace to its structured
    blocking gap ``{"kind", "detail"}`` (``ScenarioSkipped.kind``) — the
    shape the report commits as ``skipped_scenarios`` so gap burn-down is
    a metric, not a prose diff."""
    unknown = [n for n in names or [] if n not in SCENARIOS]
    if unknown:
        raise ValueError(f"unknown scenario(s) {unknown}; valid: {sorted(SCENARIOS)}")
    programs, skipped = [], {}
    for name in names or list(SCENARIOS):
        try:
            info = SCENARIOS[name]()
            if len(jax.devices()) > 1 and "multi_device" not in info.metadata:
                info.metadata["multi_device"] = info.kind == "train_step"
            programs.append(info)
        except ScenarioSkipped as e:
            skipped[name] = {"kind": e.kind, "detail": str(e)}
    return programs, skipped
