"""The graft-lint scenario matrix: representative traced programs.

Each scenario builder traces one real program shape the repo ships —
model fwd+bwd (gpt2/llama/bert), the MoE sorted route (top1/top2, where
R001's ``[S,E,C]`` ban has teeth), the pipeline scan step, and the
engine's full ``train_batch`` step (the parity path, where donation and
precision are judged). Builders TRACE only — ``jax.make_jaxpr`` /
``.lower()`` — no compilation, no device buffers beyond tiny init
params, so the whole matrix runs on CPU in seconds and can gate CI
between chip windows.

Scenario metadata is where repo knowledge enters the rules: the MoE
scenarios declare their banned ``(S, E, C)`` signature via
``sharded_moe.sec_signature`` (single source with the gating cores);
``train_batch`` declares ``parity``/``expect_donation``; multi-device
scenarios declare ``multi_device``.

Route/kernel resolution inside the MoE scenarios goes through
``moe.routing.resolve_route`` (no explicit kwarg), so a forced
``DS_MOE_ROUTE=dense`` env — the seeded-regression acceptance check —
flows into the traced program exactly as it would into a bench run.
"""

from typing import Callable, Dict, List, Optional

import numpy as np

import jax
import jax.numpy as jnp

from deepspeed_tpu.analysis.program import ProgramInfo

SCENARIOS: Dict[str, Callable[[], ProgramInfo]] = {}


class ScenarioSkipped(Exception):
    """Raised by a builder when its program cannot trace on this runtime
    (e.g. partial-manual shard_map on jax 0.4.37) — reported, not fatal.
    ``kind`` is a stable machine-readable gap class so reports carry a
    structured ``blocking_gap: {kind, detail}`` instead of a prose string
    (the ROADMAP-5 burn-down reads the kind, not the wording). ``probe``
    carries the 16-device subprocess probe's structured outcome
    (``"ok"``/``"failed"``/``"version"``) when one ran — consumers gate on
    it, never on the detail wording."""

    def __init__(self, detail: str, kind: str = "other", probe: Optional[str] = None):
        super().__init__(detail)
        self.kind = kind
        self.probe = probe


#: the composition scenario's gap burn-down order (ROADMAP item 5): each
#: entry blocks the ones after it, so progress is strictly monotone in
#: this list and the ratchet test (tests/unit/analysis/test_scenarios.py)
#: asserts the current gap's rank never moves backward. ``device_count``
#: is burned down: a <16-device run probes the 16-virtual-device build in
#: a subprocess (:func:`_probe_composition_16dev`) and reports the REAL
#: next gap, so the ambient device count no longer masks it.
COMPOSITION_GAP_ORDER = ("device_count", "partial_manual", "moe_in_pipe", "none")


_COMPOSITION_PROBE_CACHE = None


def _probe_composition_16dev() -> Dict[str, str]:
    """Build the composition scenario in a fresh subprocess with 16 forced
    virtual devices and report its blocking gap. The XLA host-device count
    is fixed at backend init, so an 8-device tier-1 run cannot raise it
    in-process — but the *gap inventory* must not stop at "device_count"
    when the real blocker is one notch further (the ROADMAP-5 burn-down
    metric). Cached per process; any probe failure degrades to the old
    device_count skip, never to a crash."""
    global _COMPOSITION_PROBE_CACHE
    if _COMPOSITION_PROBE_CACHE is not None:
        return _COMPOSITION_PROBE_CACHE
    from deepspeed_tpu.utils.jax_compat import PARTIAL_MANUAL_OK
    if not PARTIAL_MANUAL_OK:
        # the gap behind device_count is decided by a VERSION constant the
        # child would read identically: partial-manual shard_map support.
        # No subprocess needed on the pinned container — the probe only
        # forks on modern jax, where the next gap (moe_in_pipe or beyond)
        # requires actually attempting the 16-device build.
        _COMPOSITION_PROBE_CACHE = {
            "kind": "partial_manual", "probe": "version",
            "detail": "[16-device outcome version-determined] jax-0.4.37 "
                      "partial-manual shard_map gap: the pipe axis is manual "
                      "while expert/tensor/fsdp stay auto at size 2 "
                      "(utils/jax_compat.py) — the composition traces on jax>=0.5"}
        return _COMPOSITION_PROBE_CACHE
    import json
    import os
    import subprocess
    import sys
    repo = os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
    sys.path.insert(0, repo) if repo not in sys.path else None
    from envutil import cpu_subprocess_env
    child = (
        "import json, sys\n"
        f"sys.path.insert(0, {repo!r})\n"
        "import jax\n"
        "jax.config.update('jax_platforms', 'cpu')\n"
        "from deepspeed_tpu.analysis.scenarios import SCENARIOS, ScenarioSkipped\n"
        "try:\n"
        "    SCENARIOS['composition_3d_ep_zeropp']()\n"
        "    print('GAP ' + json.dumps({'kind': 'none',\n"
        "                               'detail': 'traces clean on 16 devices'}))\n"
        "except ScenarioSkipped as e:\n"
        "    print('GAP ' + json.dumps({'kind': e.kind, 'detail': str(e)}))\n")
    env = cpu_subprocess_env(n_virtual_devices=16)
    # recursion guard: if the forced device count does not take effect in
    # the child (flag ignored, env re-pinned), the child's own builder must
    # fall back to the plain device_count skip instead of forking a
    # grandchild probe
    env["DS_COMPOSITION_PROBE"] = "1"
    gap = None
    try:
        p = subprocess.run([sys.executable, "-c", child], env=env,
                           capture_output=True, text=True, timeout=120, cwd=repo)
        for line in p.stdout.splitlines():
            if line.startswith("GAP "):
                gap = json.loads(line[len("GAP "):])
    except Exception:  # noqa: BLE001 — probe is best-effort
        gap = None
    if gap is None or gap["kind"] == "device_count":
        gap = {"kind": "device_count", "probe": "failed",
               "detail": "needs 16 virtual devices and the 16-device probe "
                         "subprocess failed; run GRAFT_LINT_DEVICES=16"}
    else:
        gap = {"kind": gap["kind"], "probe": "ok",
               "detail": f"[probed on 16 subprocess devices] {gap['detail']}"}
    _COMPOSITION_PROBE_CACHE = gap
    return gap


def composition_gap_rank(kind: str) -> int:
    """Rank of a gap kind in the burn-down order; unknown kinds rank -1
    (strictly behind every known gap — a regression by definition)."""
    try:
        return COMPOSITION_GAP_ORDER.index(kind)
    except ValueError:
        return -1


def composition_blocking_gap() -> Dict[str, str]:
    """Build the ROADMAP-5 composition scenario and report its FIRST
    blocking gap as structured data: ``{"kind", "detail"}`` (plus
    ``"probe"`` when the 16-device subprocess probe produced the answer),
    with kind ``"none"`` once the full pipe x expert x tensor x fsdp +
    qgZ program traces clean."""
    try:
        SCENARIOS["composition_3d_ep_zeropp"]()
    except ScenarioSkipped as e:
        gap = {"kind": e.kind, "detail": str(e)}
        if e.probe is not None:
            gap["probe"] = e.probe
        return gap
    return {"kind": "none", "detail": "composition traces clean"}


def scenario(name: str):
    def wrap(fn):
        SCENARIOS[name] = fn
        return fn

    return wrap


def _model_fwd_bwd(name, model, variables, loss):
    grad = jax.grad(loss)
    return ProgramInfo(name=name, jaxpr=jax.make_jaxpr(grad)(variables),
                       kind="fwd_bwd",
                       # the --cost pass compiles on demand for the
                       # post-SPMD collective inventory + backend
                       # memory/flops cross-check; plain runs never call it
                       lower=lambda: jax.jit(grad).lower(variables))


# ---------------------------------------------------------------------------
@scenario("gpt2_fwd_bwd")
def gpt2_fwd_bwd() -> ProgramInfo:
    from deepspeed_tpu.models import GPT2LMHeadModel, get_gpt2_config

    cfg = get_gpt2_config("test")
    model = GPT2LMHeadModel(cfg)
    ids = jnp.zeros((2, 32), jnp.int32)
    variables = model.init(jax.random.PRNGKey(0), ids)

    def loss(v):
        out = model.apply(v, ids)
        logits = out[0] if isinstance(out, tuple) else out
        return logits.astype(jnp.float32).sum()

    return _model_fwd_bwd("gpt2_fwd_bwd", model, variables, loss)


@scenario("llama_fwd_bwd")
def llama_fwd_bwd() -> ProgramInfo:
    from deepspeed_tpu.models import LlamaForCausalLM, get_llama_config

    cfg = get_llama_config("test")
    model = LlamaForCausalLM(cfg)
    ids = jnp.zeros((2, 32), jnp.int32)
    variables = model.init(jax.random.PRNGKey(0), ids)

    def loss(v):
        out = model.apply(v, ids)
        logits = out[0] if isinstance(out, tuple) else out
        return logits.astype(jnp.float32).sum()

    return _model_fwd_bwd("llama_fwd_bwd", model, variables, loss)


@scenario("bert_fwd_bwd")
def bert_fwd_bwd() -> ProgramInfo:
    from deepspeed_tpu.models import BertForMaskedLM, get_bert_config

    cfg = get_bert_config("test")
    model = BertForMaskedLM(cfg)
    ids = jnp.zeros((2, 32), jnp.int32)
    variables = model.init(jax.random.PRNGKey(0), ids)

    def loss(v):
        out = model.apply(v, ids)
        logits = out[0] if isinstance(out, tuple) else out
        return logits.astype(jnp.float32).sum()

    return _model_fwd_bwd("bert_fwd_bwd", model, variables, loss)


# ---------------------------------------------------------------------------
def _moe_program(name: str, k: int) -> ProgramInfo:
    import flax.linen as nn

    from deepspeed_tpu.moe.sharded_moe import MOELayer, sec_signature

    class _Expert(nn.Module):
        @nn.compact
        def __call__(self, x, deterministic=True):
            return nn.Dense(x.shape[-1], use_bias=False)(x)

    B, L, M, E, cf, min_cap = 2, 16, 8, 4, 1.0, 1
    S = B * L  # one group without a topology
    # no explicit route kwarg: resolution flows through env/config exactly
    # like a bench run, so DS_MOE_ROUTE=dense seeds the R001 regression
    layer = MOELayer(expert=_Expert(), model_dim=M, num_experts=E, k=k,
                     capacity_factor=cf, eval_capacity_factor=cf,
                     min_capacity=min_cap)
    x = jnp.zeros((B, L, M), jnp.float32)
    variables = layer.init(jax.random.PRNGKey(0), x)

    def loss(v, xx):
        (out, l_aux, _), _ = layer.apply(v, xx, mutable=["intermediates"])
        return (out ** 2).sum() + l_aux

    grad = jax.grad(loss, argnums=(0, 1))
    jaxpr = jax.make_jaxpr(grad)(variables, x)
    return ProgramInfo(
        name=name, jaxpr=jaxpr, kind="fwd_bwd",
        lower=lambda: jax.jit(grad).lower(variables, x),
        metadata={"moe_sec": [sec_signature(S, E, cf, min_cap, k=k)],
                  # the committed intent is the sorted route: zero dense
                  # [S,E,C] einsums feeding the dispatch/combine endpoints.
                  # DS_MOE_ROUTE=dense drifts the traced program but not
                  # this signature — the R009 seeded regression.
                  "collective_signature": [
                      {"layer": "jaxpr", "kind": "dense_dispatch", "count": 0,
                       "note": "sorted MoE dispatch is a permutation, "
                               "never an [S,E,C] einsum"}]})


@scenario("moe_top1_route")
def moe_top1_route() -> ProgramInfo:
    return _moe_program("moe_top1_route", k=1)


@scenario("moe_top2_route")
def moe_top2_route() -> ProgramInfo:
    return _moe_program("moe_top2_route", k=2)


# ---------------------------------------------------------------------------
def _engine_program(name: str, engine, example_batch, extra_metadata=None) -> ProgramInfo:
    programs = engine.traced_programs(example_batch)
    step = programs["train_step"]
    metadata = dict(step["metadata"])
    for key, value in (extra_metadata or {}).items():
        if key == "collective_signature":  # extend, don't clobber, the
            metadata.setdefault(key, [])   # engine-declared entries
            metadata[key] = list(metadata[key]) + list(value)
        else:
            metadata[key] = value
    return ProgramInfo(name=name, jaxpr=step["jaxpr"], hlo_text=step["hlo_text"],
                       kind="train_step", metadata=metadata,
                       lower=step.get("lower"))


@scenario("train_batch_parity")
def train_batch_parity() -> ProgramInfo:
    """The engine's fused train step for a tiny GPT-2 — the program the
    CPU parity envelope (ROADMAP item 4) judges. ``parity: True`` arms
    R002's upcast attribution; ``expect_donation`` arms R005."""
    import deepspeed_tpu
    from deepspeed_tpu.models import GPT2LMHeadModel, get_gpt2_config
    from deepspeed_tpu.parallel.topology import MeshTopology, set_topology

    set_topology(None)
    try:
        # pinned to the first 8 devices: a GRAFT_LINT_DEVICES=16 run must
        # not shift this program (and its cost baseline entry) onto a
        # different mesh
        topo = (MeshTopology(data=8, devices=jax.devices()[:8])
                if len(jax.devices()) >= 8 else None)
        engine, _, _, _ = deepspeed_tpu.initialize(
            model=GPT2LMHeadModel(get_gpt2_config("test")), topology=topo,
            config={"train_batch_size": 8,
                    "optimizer": {"type": "AdamW", "params": {"lr": 1e-3}},
                    "zero_optimization": {"stage": 0}})
        batch = {"input_ids": np.zeros((8, 32), np.int32)}
        return _engine_program("train_batch_parity", engine, batch,
                               {"parity": True})
    finally:
        set_topology(None)


@scenario("train_batch_telemetry")
def train_batch_telemetry() -> ProgramInfo:
    """The ``train_batch_parity`` engine config with the telemetry block
    ON — the gate that graft-trace instrumentation can never silently
    enter the compiled program. The builder traces the SAME engine twice
    (telemetry-off first, jaxpr-only) and stamps the off-trace's
    recursive eqn count as ``expect_eqn_count``; rule R015 fails on any
    divergence, and R003 must stay clean on the telemetry-on program
    (spans are host-side, so no callback can appear in the jaxpr)."""
    import deepspeed_tpu
    from deepspeed_tpu.analysis.program import ProgramAnalyzer
    from deepspeed_tpu.models import GPT2LMHeadModel, get_gpt2_config
    from deepspeed_tpu.parallel.topology import MeshTopology, set_topology

    base = {"train_batch_size": 8,
            "optimizer": {"type": "AdamW", "params": {"lr": 1e-3}},
            "zero_optimization": {"stage": 0}}
    batch = {"input_ids": np.zeros((8, 32), np.int32)}

    def build(extra):
        topo = (MeshTopology(data=8, devices=jax.devices()[:8])
                if len(jax.devices()) >= 8 else None)
        engine, _, _, _ = deepspeed_tpu.initialize(
            model=GPT2LMHeadModel(get_gpt2_config("test")), topology=topo,
            config={**base, **extra})
        return engine

    set_topology(None)
    try:
        off = build({}).traced_programs(batch, lower=False)["train_step"]
        off_count = len(ProgramAnalyzer(ProgramInfo(
            name="telemetry_off", jaxpr=off["jaxpr"], kind="train_step")).records())
        # enabled telemetry, default output_path: tracing never writes, so
        # no run dir is created (the sink is lazy; the header only lands on
        # a real train_batch)
        engine = build({"telemetry": {"enabled": True}})
        return _engine_program("train_batch_telemetry", engine, batch,
                               {"expect_eqn_count": off_count})
    finally:
        set_topology(None)


@scenario("pipe_scan_step")
def pipe_scan_step() -> ProgramInfo:
    """The pipeline engine's scan step on a pipe=2 mesh (auto axes size 1
    fold to full-manual, so this traces even on the 0.4.37 container —
    jax_compat docstring). Skips, not fails, where shard_map can't."""
    import deepspeed_tpu
    from deepspeed_tpu.models.gpt2 import gpt2_pipe_layers
    from deepspeed_tpu.models import get_gpt2_config
    from deepspeed_tpu.parallel.topology import MeshTopology, set_topology
    from deepspeed_tpu.runtime.pipe.module import PipelineModule

    if len(jax.devices()) < 8:
        raise ScenarioSkipped("pipe_scan_step expects >=8 host devices")
    set_topology(None)
    try:
        cfg = get_gpt2_config("test", n_layer=2)
        topo = MeshTopology(pipe=2, data=2, fsdp=2, devices=jax.devices()[:8])
        pipe = PipelineModule(layers=gpt2_pipe_layers(cfg), topology=topo)
        engine, _, _, _ = deepspeed_tpu.initialize(
            model=pipe, topology=topo,
            config={"train_batch_size": 16, "gradient_accumulation_steps": 4,
                    "optimizer": {"type": "AdamW", "params": {"lr": 1e-3}}})
        batch = {"input_ids": np.zeros((16, 32), np.int32)}
        return _engine_program("pipe_scan_step", engine, batch)
    except NotImplementedError as e:  # partial-manual shard_map gap
        raise ScenarioSkipped(f"shard_map unsupported here: {e}") from e
    finally:
        set_topology(None)


# ---------------------------------------------------------------------------
def _zero_step(name: str, stage: int) -> ProgramInfo:
    """A ZeRO-``stage`` step on a data=2 x fsdp=4 mesh: the program whose
    comms schedule the blueprint quantifies (state sharded over fsdp,
    grads averaged over data). The engine stamps the stage's collective
    signature from ``DeepSpeedZeroConfig.cost_metadata`` — all-gathers
    must exist (sharding is real), the reduce-scatter entry is TPU-judged
    (XLA:CPU decomposes RS into AR+slice; inventoried as unchecked)."""
    import deepspeed_tpu
    from deepspeed_tpu.models import GPT2LMHeadModel, get_gpt2_config
    from deepspeed_tpu.parallel.topology import MeshTopology, set_topology

    if len(jax.devices()) < 8:
        raise ScenarioSkipped(f"{name} expects >=8 host devices")
    set_topology(None)
    try:
        engine, _, _, _ = deepspeed_tpu.initialize(
            model=GPT2LMHeadModel(get_gpt2_config("test")),
            topology=MeshTopology(data=2, fsdp=4, devices=jax.devices()[:8]),
            config={"train_batch_size": 8,
                    "optimizer": {"type": "AdamW", "params": {"lr": 1e-3}},
                    "zero_optimization": {"stage": stage}})
        batch = {"input_ids": np.zeros((8, 32), np.int32)}
        return _engine_program(name, engine, batch)
    finally:
        set_topology(None)


@scenario("zero2_train_step")
def zero2_train_step() -> ProgramInfo:
    return _zero_step("zero2_train_step", stage=2)


@scenario("zero3_train_step")
def zero3_train_step() -> ProgramInfo:
    return _zero_step("zero3_train_step", stage=3)


@scenario("moe_ep_step")
def moe_ep_step() -> ProgramInfo:
    """The engine's MoE step on an expert=4 x data=2 mesh — where the
    sorted route's "exactly two capacity-bounded all-to-alls per layer"
    claim has wire bytes behind it. Each MoE layer applies the
    G-sharded->E-sharded constraint *pair* on the dispatch buffer and its
    mirror on the combine side (2 logical a2a per direction); the cost
    pass counts those chained-constraint reshards at the jaxpr layer,
    backend-independently."""
    import deepspeed_tpu
    from deepspeed_tpu.models import GPT2LMHeadModel, get_gpt2_config
    from deepspeed_tpu.parallel.topology import MeshTopology, set_topology

    if len(jax.devices()) < 8:
        raise ScenarioSkipped("moe_ep_step expects >=8 host devices")
    set_topology(None)
    try:
        cfg = get_gpt2_config("test", moe_num_experts=4, moe_layer_freq=2, moe_k=1)
        engine, _, _, _ = deepspeed_tpu.initialize(
            model=GPT2LMHeadModel(cfg),
            topology=MeshTopology(expert=4, data=2, devices=jax.devices()[:8]),
            config={"train_batch_size": 8,
                    "optimizer": {"type": "AdamW", "params": {"lr": 1e-3}},
                    "zero_optimization": {"stage": 0}})
        batch = {"input_ids": np.zeros((8, 32), np.int32)}
        return _engine_program("moe_ep_step", engine, batch, {
            "collective_signature": [
                {"layer": "jaxpr", "kind": "resharding", "min_count": 4,
                 "note": "2 capacity-bounded a2a reshards per MoE layer "
                         "per direction (dispatch + combine, fwd + bwd)"},
                # ...and the partitioner honors them: exactly 2 a2a per
                # layer per direction in the compiled program (1 MoE
                # layer here -> 4 total). More would mean GSPMD chose a
                # gather-everywhere strategy; fewer, a silently-local
                # (replicated) expert layout.
                {"layer": "compiled", "kind": "all_to_all", "count": 4,
                 "note": "exactly 2 all-to-alls per MoE layer per direction"}]})
    finally:
        set_topology(None)


#: the committed 1F1B activation budget (MiB) for the pipe=2 scenario
#: mesh below. Formula (README "Pipeline parallelism"): stash ring
#: ``2(S-1)`` boundary slots + 2 in transit (S=2: 4 x 16 KiB) + the fp32
#: grad accumulators (~0.6 MiB params) + one tick's recompute transient
#: (block internals + [mb, seq, vocab] epilogue logits) + the optimizer
#: update's own temporaries — measured 1.90 MiB static transient on the
#: pinned container, committed at 2.0 MiB (~5% headroom). Strictly below
#: the chunked schedule's 2.25 MiB measured transient (and its prior
#: 4 MiB commit), so the SAME budget fails the chunked schedule — the
#: ratchet with teeth (test_cost_gate).
PIPE_1F1B_BUDGET_MB = 2.0


def _pipe_engine_program(name: str, pipeline_cfg: dict) -> ProgramInfo:
    """Shared pipe=2-only builder (every auto axis size 1 folds to
    full-manual, so these trace on the 0.4.37 container where
    ``pipe_scan_step``'s pipe x data x fsdp mesh cannot)."""
    import deepspeed_tpu
    from deepspeed_tpu.models import get_gpt2_config
    from deepspeed_tpu.models.gpt2 import gpt2_pipe_layers
    from deepspeed_tpu.parallel.topology import MeshTopology, set_topology
    from deepspeed_tpu.runtime.pipe.module import PipelineModule

    if len(jax.devices()) < 2:
        raise ScenarioSkipped(f"{name} needs >=2 devices")
    set_topology(None)
    try:
        cfg = get_gpt2_config("test", n_layer=2)
        topo = MeshTopology(pipe=2, data=1, devices=jax.devices()[:2])
        pipe = PipelineModule(layers=gpt2_pipe_layers(cfg), topology=topo)
        engine, _, _, _ = deepspeed_tpu.initialize(
            model=pipe, topology=topo,
            config={"train_batch_size": 8, "gradient_accumulation_steps": 4,
                    "pipeline": pipeline_cfg,
                    "optimizer": {"type": "AdamW", "params": {"lr": 1e-3}}})
        batch = {"input_ids": np.zeros((8, 32), np.int32)}
        return _engine_program(name, engine, batch)
    except NotImplementedError as e:  # partial-manual shard_map gap
        raise ScenarioSkipped(f"shard_map unsupported here: {e}") from e
    finally:
        set_topology(None)


@scenario("pipe_chunked_step")
def pipe_chunked_step() -> ProgramInfo:
    """The chunked-wave pipeline schedule — kept as the A/B reference
    against ``pipe_1f1b_step`` (same mesh, same model, same microbatch
    count). Its committed budget is its own measured static transient
    (2.25 MiB) + headroom — tightened from the pre-1F1B 4 MiB commit;
    ``DS_PIPE_ACT_BUDGET_MB`` below the estimate (e.g. the 1F1B bound)
    is the seeded R010 regression proving the chunked schedule cannot
    pass the 1F1B budget."""
    return _pipe_engine_program(
        "pipe_chunked_step",
        # measured 2.25 MiB static transient on the pinned container
        {"chunk_microbatches": 2, "activation_budget_mb": 2.5})


@scenario("pipe_1f1b_step")
def pipe_1f1b_step() -> ProgramInfo:
    """The 1F1B schedule (the default) under its committed activation
    bound (:data:`PIPE_1F1B_BUDGET_MB` — formula in the constant's
    docstring). R010 gates the manual-vjp program's static transient
    against it; R009 pins the 4-``collective_permute`` signature (2 per
    tick boundary across the 3 phase bodies). Any schedule regression —
    an extra stash slot, autodiff residuals sneaking back in, a third
    boundary buffer — fails lint on CPU before a chip window pays for
    it."""
    return _pipe_engine_program(
        "pipe_1f1b_step",
        {"schedule": "1f1b", "activation_budget_mb": PIPE_1F1B_BUDGET_MB})


#: the committed activation budget (MiB) for the graft-serve decode tick
#: below (16 slots x 512 positions, tp=2, tiny GPT-2). Measured static
#: transient on the pinned container: 8.41 MiB with the committed
#: ``scatter`` KV write (4 per-slot scatters, O(slots) bytes each);
#: committed at 9.0 MiB (~7% headroom). The ``dense`` masked-rebuild
#: write measures 10.5 MiB — so ``DS_SERVE_KV_WRITE=dense`` fails R010
#: under this budget, the DS_MOE_ROUTE-pattern seeded regression for a
#: forced/leaked serving knob.
SERVE_DECODE_BUDGET_MB = 9.0


@scenario("serve_decode_step")
def serve_decode_step() -> ProgramInfo:
    """The graft-serve fixed-shape decode tick (inference/serving): one
    token per slot against the per-slot ragged cache, on a tensor=2
    serving mesh so the program carries real post-SPMD collectives. The
    traced program IS the served one — same ``make_apply_fn`` +
    ``build_decode_step`` the scheduler jits — so R009 pins the tp
    collective signature, R010 gates the per-tick transient against
    :data:`SERVE_DECODE_BUDGET_MB`, and R013 ratchets both against the
    committed cost baseline. The KV write strategy resolves through
    env/config exactly like a serve run (``resolve_kv_write``), which is
    what gives ``DS_SERVE_KV_WRITE=dense`` its teeth."""
    import deepspeed_tpu
    from deepspeed_tpu.inference.config import DeepSpeedInferenceConfig
    from deepspeed_tpu.inference.engine import InferenceEngine
    from deepspeed_tpu.inference.serving import make_slot_cache, resolve_intended_kv_write
    from deepspeed_tpu.inference.serving.programs import (build_decode_step,
                                                          make_apply_fn)
    from deepspeed_tpu.models import GPT2LMHeadModel, get_gpt2_config
    from deepspeed_tpu.parallel.topology import MeshTopology, set_topology

    if len(jax.devices()) < 2:
        raise ScenarioSkipped("serve_decode_step needs >=2 devices for the "
                              "tensor=2 serving mesh")
    set_topology(None)
    try:
        slots = 16
        cfg = get_gpt2_config("test", n_layer=2, n_positions=512)
        topo = MeshTopology(tensor=2, data=1, fsdp=1, devices=jax.devices()[:2])
        engine = InferenceEngine(GPT2LMHeadModel(cfg),
                                 DeepSpeedInferenceConfig(), topology=topo)
        cache = make_slot_cache(engine.module, slots)
        decode = build_decode_step(make_apply_fn(engine.module, engine._mparams),
                                   do_sample=False, temperature=1.0, top_k=0,
                                   top_p=1.0)
        tokens = jnp.zeros((slots,), jnp.int32)
        jaxpr = jax.make_jaxpr(decode)(engine.params, cache, tokens)
        return ProgramInfo(
            name="serve_decode_step", jaxpr=jaxpr, kind="serve_decode",
            lower=lambda: jax.jit(decode).lower(engine.params, cache, tokens),
            metadata={
                "serve_slots": slots,
                # the committed intent, env layer skipped — a forced env
                # override drifts the program but never this declaration
                "serve_kv_write": resolve_intended_kv_write(),
                "activation_budget_bytes": int(SERVE_DECODE_BUDGET_MB * 2**20),
                "collective_signature": [
                    # tp=2 row-parallel projections: attention out-proj +
                    # MLP out-proj per block, plus the tied LM head —
                    # 2*n_layer + 1 all-reduces per decode tick
                    {"layer": "compiled", "kind": "all_reduce", "count": 5,
                     "note": "2 all-reduces per block + 1 for the tied "
                             "LM head on the tp=2 serving mesh"},
                    {"layer": "compiled", "kind": "all_gather", "max_count": 2,
                     "note": "at most the two embedding-table gathers — "
                             "more would mean GSPMD re-gathers the KV pool "
                             "per tick"}]})
    finally:
        set_topology(None)


#: committed activation budget (MiB) for the QUANTIZED graft-serve decode
#: tick (8 slots x 256 positions, n_embd=128 bf16 compute, tp=2). The
#: int8-weight program's transient is dominated by the int8 KV pools +
#: bf16 dequant/attention temporaries; measured static transient on the
#: pinned container: 2.63 MiB, committed at 2.9 MiB (~10% headroom).
#: ``DS_SERVE_WQ=fp`` swings the program back to full-width fp kernels —
#: peak bytes jump ~40% past the R013 tolerance, the seeded regression
#: for a forced/leaked served weight dtype.
SERVE_QUANT_DECODE_BUDGET_MB = 2.9


@scenario("serve_quant_decode_step")
def serve_quant_decode_step() -> ProgramInfo:
    """graft-quant-serve's decode tick: the SAME ``make_apply_fn`` +
    ``build_decode_step`` program as :func:`serve_decode_step`, but served
    the way the quantized scheduler builds it — int8 per-group weight
    codes with dequant fused into the GEMM (``_quant_view``), int8 KV
    pools (``make_slot_cache(kv_quant=True)``), bf16 compute. A weight-
    heavier config (n_embd=128) than the fp reference makes the weight
    path the dominant term, so the A/B against ``serve_decode_step``
    prices exactly what quantization buys per tick.

    The served dtype resolves at the BUILDER (``resolve_weight_dtype``
    over the scenario's installed config default), never inside the
    module — so ``DS_SERVE_WQ`` drifts the traced program while
    ``serve_weight_dtype`` metadata stays the committed intent
    (``resolve_intended_weight_dtype``), and R013 fails the drift."""
    import deepspeed_tpu
    from deepspeed_tpu.inference.config import DeepSpeedInferenceConfig
    from deepspeed_tpu.inference.engine import InferenceEngine
    from deepspeed_tpu.inference.serving import (make_slot_cache,
                                                 resolve_intended_weight_dtype,
                                                 resolve_weight_dtype,
                                                 set_default_weight_dtype)
    from deepspeed_tpu.inference.serving.programs import (build_decode_step,
                                                          make_apply_fn)
    from deepspeed_tpu.inference.serving.scheduler import _quant_view
    from deepspeed_tpu.models import GPT2LMHeadModel, get_gpt2_config
    from deepspeed_tpu.parallel.topology import MeshTopology, set_topology

    if len(jax.devices()) < 2:
        raise ScenarioSkipped("serve_quant_decode_step needs >=2 devices for "
                              "the tensor=2 serving mesh")
    set_topology(None)
    set_default_weight_dtype("int8")  # the committed serving config
    try:
        slots = 8
        cfg = get_gpt2_config("test", n_layer=2, n_embd=128, n_head=8,
                              n_positions=256, dtype=jnp.bfloat16)
        topo = MeshTopology(tensor=2, data=1, fsdp=1, devices=jax.devices()[:2])
        engine = InferenceEngine(GPT2LMHeadModel(cfg),
                                 DeepSpeedInferenceConfig(), topology=topo)
        # builder-level resolution, exactly the scheduler's seam: env
        # outranks the installed config default, so a forced DS_SERVE_WQ
        # changes WHAT GETS BUILT here while the metadata below does not
        wd, _src = resolve_weight_dtype(None)
        module, params = engine.module, engine.params
        if wd != "fp":
            module, params = _quant_view(module, params, wd, 64)
        cache = make_slot_cache(module, slots, kv_quant=True)
        decode = build_decode_step(make_apply_fn(module, engine._mparams),
                                   do_sample=False, temperature=1.0, top_k=0,
                                   top_p=1.0)
        tokens = jnp.zeros((slots,), jnp.int32)
        jaxpr = jax.make_jaxpr(decode)(params, cache, tokens)
        return ProgramInfo(
            name="serve_quant_decode_step", jaxpr=jaxpr, kind="serve_decode",
            lower=lambda: jax.jit(decode).lower(params, cache, tokens),
            metadata={
                "serve_slots": slots,
                # committed intent, env layer skipped — the drift anchor
                "serve_weight_dtype": resolve_intended_weight_dtype(None),
                "serve_kv_quant": True,
                "activation_budget_bytes": int(SERVE_QUANT_DECODE_BUDGET_MB * 2**20),
                "collective_signature": [
                    # same tp=2 skeleton as serve_decode_step: 2 row-parallel
                    # all-reduces per block + 1 for the tied LM head — but in
                    # bf16, so the compiled wire bytes land strictly below
                    # the fp tick's (the headline A/B the baseline pins)
                    {"layer": "compiled", "kind": "all_reduce", "count": 5,
                     "note": "2 all-reduces per block + 1 for the tied "
                             "LM head, bf16 activations on the tp=2 mesh"},
                    {"layer": "compiled", "kind": "all_gather", "max_count": 2,
                     "note": "at most the two embedding-table gathers — "
                             "more would mean GSPMD re-gathers the int8 "
                             "codes or the KV pool per tick"}]})
    finally:
        set_default_weight_dtype(None)
        set_topology(None)


@scenario("serve_prefix_decode_step")
def serve_prefix_decode_step() -> ProgramInfo:
    """graft-prefix-cache's decode tick: the SAME program as
    :func:`serve_decode_step` built with the prefix cache installed as
    the committed serving default. The cache is a HOST-SIDE allocator
    change — ref-counted content-addressed blocks, restore/publish
    through host row copies — so the compiled decode program must be
    BYTE-IDENTICAL to the uncached one: same budget, same tp=2
    collective signature (R009), same banked cost (R013). Any delta here
    means prefix caching leaked into the traced program, which would put
    the cache on the latency path it exists to shorten."""
    import deepspeed_tpu
    from deepspeed_tpu.inference.config import DeepSpeedInferenceConfig
    from deepspeed_tpu.inference.engine import InferenceEngine
    from deepspeed_tpu.inference.serving import (make_slot_cache,
                                                 resolve_intended_kv_write,
                                                 resolve_intended_prefix_cache,
                                                 set_default_prefix_cache)
    from deepspeed_tpu.inference.serving.programs import (build_decode_step,
                                                          make_apply_fn)
    from deepspeed_tpu.models import GPT2LMHeadModel, get_gpt2_config
    from deepspeed_tpu.parallel.topology import MeshTopology, set_topology

    if len(jax.devices()) < 2:
        raise ScenarioSkipped("serve_prefix_decode_step needs >=2 devices "
                              "for the tensor=2 serving mesh")
    set_topology(None)
    set_default_prefix_cache("on")  # the committed serving config
    try:
        slots = 16
        cfg = get_gpt2_config("test", n_layer=2, n_positions=512)
        topo = MeshTopology(tensor=2, data=1, fsdp=1, devices=jax.devices()[:2])
        engine = InferenceEngine(GPT2LMHeadModel(cfg),
                                 DeepSpeedInferenceConfig(), topology=topo)
        cache = make_slot_cache(engine.module, slots)
        decode = build_decode_step(make_apply_fn(engine.module, engine._mparams),
                                   do_sample=False, temperature=1.0, top_k=0,
                                   top_p=1.0)
        tokens = jnp.zeros((slots,), jnp.int32)
        jaxpr = jax.make_jaxpr(decode)(engine.params, cache, tokens)
        return ProgramInfo(
            name="serve_prefix_decode_step", jaxpr=jaxpr, kind="serve_decode",
            lower=lambda: jax.jit(decode).lower(engine.params, cache, tokens),
            metadata={
                "serve_slots": slots,
                # committed intent, env layer skipped — the drift anchors
                "serve_kv_write": resolve_intended_kv_write(),
                "serve_prefix_cache": resolve_intended_prefix_cache(None),
                # same budget as serve_decode_step ON PURPOSE: prefix
                # caching must not move the decode tick's transient a byte
                "activation_budget_bytes": int(SERVE_DECODE_BUDGET_MB * 2**20),
                "collective_signature": [
                    {"layer": "compiled", "kind": "all_reduce", "count": 5,
                     "note": "2 all-reduces per block + 1 for the tied "
                             "LM head on the tp=2 serving mesh — identical "
                             "to serve_decode_step (host-side cache only)"},
                    {"layer": "compiled", "kind": "all_gather", "max_count": 2,
                     "note": "at most the two embedding-table gathers — "
                             "more would mean prefix caching leaked into "
                             "the compiled program"}]})
    finally:
        set_default_prefix_cache(None)
        set_topology(None)


#: committed activation budget (MiB) for the graft-rlhf rollout decode
#: tick below (8 slots x 128 positions, tiny GPT-2 served at
#: tensor=2/data=4 from a ZeRO-3 hybrid engine's inference view).
#: Measured static transient on the pinned container: 1.05 MiB;
#: committed at 1.25 MiB (~19% headroom).
RLHF_ROLLOUT_BUDGET_MB = 1.25


@scenario("rlhf_rollout_step")
def rlhf_rollout_step() -> ProgramInfo:
    """graft-rlhf's rollout decode tick: the continuous-scheduler decode
    program exactly as the RLHF loop serves it — built over a
    ``DeepSpeedHybridEngine``'s inference view (ZeRO-3 training params on
    a data=2/fsdp=4 mesh, relayouted into the tp=2 serving placement
    through the PR-15 planner), one token per slot against the per-slot
    ragged cache. R009 pins the tp collective signature of the tick the
    learner overlaps with, R010 gates its per-tick transient against
    :data:`RLHF_ROLLOUT_BUDGET_MB`, and R013 ratchets both against the
    committed baseline. The planner's priced summary of the
    train-mesh→serve-mesh weight sync (the per-``sync_every`` cost the
    rollout loop stamps as evidence) rides the metadata next to the
    compiled inventory — the reshard_resume pattern."""
    import deepspeed_tpu
    import numpy as np
    from deepspeed_tpu.inference.serving import make_slot_cache
    from deepspeed_tpu.inference.serving.programs import (build_decode_step,
                                                          make_apply_fn)
    from deepspeed_tpu.models import GPT2LMHeadModel, get_gpt2_config
    from deepspeed_tpu.parallel.topology import MeshTopology, set_topology
    from deepspeed_tpu.runtime.rlhf.sync import plan_params_sync

    if len(jax.devices()) < 8:
        raise ScenarioSkipped("rlhf_rollout_step expects >=8 host devices "
                              "(data=2/fsdp=4 train mesh, tp=2 serve mesh)")
    set_topology(None)
    try:
        slots = 8
        seq = 32
        cfg = get_gpt2_config("test", n_layer=2, n_positions=128)
        ds = {"train_batch_size": 8,
              "optimizer": {"type": "AdamW", "params": {"lr": 1e-3}},
              "zero_optimization": {"stage": 3,
                                    "stage3_param_persistence_threshold": 0},
              "hybrid_engine": {"enabled": True, "max_out_tokens": 128,
                                "inference_tp_size": 2},
              "steps_per_print": 10**9}
        engine, _, _, _ = deepspeed_tpu.initialize(
            model=GPT2LMHeadModel(cfg), config=ds,
            loss_fn=lambda logits, batch: logits.mean(),
            topology=MeshTopology(data=2, fsdp=4))
        engine.initialize_state({"input_ids": np.zeros((8, seq), np.int32)})
        engine._infer_engine = engine._build_inference_engine()
        infer = engine._infer_engine
        sync_plan = plan_params_sync(engine._inference_params_value(),
                                     engine.mesh, infer.params, infer.mesh)
        sync_plan.pop("plan_s", None)  # static evidence only, no wall time
        set_topology(infer.topology)
        cache = make_slot_cache(infer.module, slots)
        decode = build_decode_step(make_apply_fn(infer.module, infer._mparams),
                                   do_sample=False, temperature=1.0, top_k=0,
                                   top_p=1.0)
        tokens = jnp.zeros((slots,), jnp.int32)
        jaxpr = jax.make_jaxpr(decode)(infer.params, cache, tokens)
        return ProgramInfo(
            name="rlhf_rollout_step", jaxpr=jaxpr, kind="serve_decode",
            lower=lambda: jax.jit(decode).lower(infer.params, cache, tokens),
            metadata={
                "serve_slots": slots,
                "rlhf_weight_sync_plan": sync_plan,
                "activation_budget_bytes": int(RLHF_ROLLOUT_BUDGET_MB * 2**20),
                "collective_signature": [
                    # the hybrid engine builds its serve mesh over ALL
                    # devices (tensor=2, data=4 on the 8-device rig), so
                    # the compiled tick carries the serve_decode_step tp
                    # skeleton PLUS small data-axis redistributions of
                    # the 8-slot batch (measured: 3072 bytes/tick on the
                    # g4 axis — the slot ids land data-sharded, GSPMD
                    # regathers them for the replicated cache update)
                    {"layer": "compiled", "kind": "all_reduce", "count": 5,
                     "note": "2 all-reduces per block + 1 for the tied "
                             "LM head on the hybrid engine's tp=2 serve "
                             "mesh"},
                    {"layer": "compiled", "kind": "all_gather",
                     "max_count": 14,
                     "note": "2 embedding-table gathers + the data-axis "
                             "slot-batch regathers of the tensor=2/data=4 "
                             "hybrid serve mesh; more would mean the "
                             "learner's ZeRO layout leaked through the "
                             "weight sync into the compiled rollout tick"},
                    {"layer": "compiled", "kind": "collective_permute",
                     "max_count": 4,
                     "note": "slot-batch redistribution between the "
                             "data-sharded token ids and the replicated "
                             "KV cache — O(slots) bytes, not O(params)"}]})
    finally:
        set_topology(None)


@scenario("reshard_resume")
def reshard_resume() -> ProgramInfo:
    """graft-elastic's restore-path data movement, as a static program the
    cost rules can gate. A world-size change reshards every leaf: the
    traced program maps the gpt2 ``test`` ZeRO param tree from its saved
    4-way ``fsdp`` chunking to (a) the scale-up 8-way layout and (b) the
    scale-down 2-way layout on the same 8-device fleet — the two
    directions ``resume_elastic`` executes (scale-up = slice+permute,
    scale-down = gather). R009 pins the compiled collective signature;
    R013 ratchets the restore path's gather bytes (``bytes_moved``)
    against the committed baseline. The host-side planner prices the same
    transition (``runtime/elastic/planner.py``) and its summary rides the
    metadata as evidence next to the compiled inventory."""
    import deepspeed_tpu
    from deepspeed_tpu.models import GPT2LMHeadModel, get_gpt2_config
    from deepspeed_tpu.parallel.topology import MeshTopology, set_topology
    from deepspeed_tpu.runtime.elastic.layout import spec_entries
    from deepspeed_tpu.runtime.elastic.planner import plan_reshard
    from jax.sharding import NamedSharding, PartitionSpec as P

    if len(jax.devices()) < 8:
        raise ScenarioSkipped("reshard_resume expects >=8 host devices")
    set_topology(None)
    try:
        engine, _, _, _ = deepspeed_tpu.initialize(
            model=GPT2LMHeadModel(get_gpt2_config("test")),
            topology=MeshTopology(data=2, fsdp=4, devices=jax.devices()[:8]),
            config={"train_batch_size": 8,
                    "optimizer": {"type": "AdamW", "params": {"lr": 1e-3}},
                    # stage 3 with persistence threshold 0: every param is
                    # fsdp-sharded — the layout a world-size change actually
                    # has to re-chunk (the test model's params are all tiny)
                    "zero_optimization": {"stage": 3,
                                          "stage3_param_persistence_threshold": 0}})
        batch = {"input_ids": np.zeros((8, 32), np.int32)}
        abstract = engine.abstract_state(batch)
        mesh = engine.mesh
        src_shardings = engine.state_shardings.params
        aparams = abstract.params

        def remap(spec, shape, repl):
            """The target layout's spec: every ``fsdp``-chunked dim re-chunks
            over ``repl`` ("data","fsdp") = 8-way scale-up or ("data",) =
            2-way scale-down, where divisibility allows."""
            width = 1
            for a in repl:
                width *= mesh.shape[a]
            entries = []
            for entry, n in zip(spec_entries(spec, len(shape)), shape):
                if entry == ["fsdp"] and n % width == 0:
                    entry = list(repl)
                if entry is None:
                    entries.append(None)
                else:
                    entries.append(tuple(entry) if len(entry) > 1 else entry[0])
            return P(*entries)

        def retarget(repl):
            return jax.tree.map(
                lambda s, a: NamedSharding(mesh, remap(s.spec, a.shape, repl)),
                src_shardings, aparams)

        up, down = retarget(("data", "fsdp")), retarget(("data",))

        def reshard(params):
            return params, params  # two restore directions, one program

        jaxpr = jax.make_jaxpr(reshard)(aparams)
        # host-planner evidence: the same transition priced without devices
        # (world 4 -> 8 and 4 -> 2 over a pure fsdp axis)
        def layout_for(axes):
            return {"version": 1, "world_size": axes["fsdp"], "mesh_axes": axes,
                    "leaves": {str(i): {"shape": list(a.shape), "dtype": str(a.dtype),
                                        "spec": [["fsdp"] if a.shape and a.shape[0] % 8 == 0
                                                 else None] + [None] * (len(a.shape) - 1)}
                               for i, a in enumerate(jax.tree.leaves(aparams))}}
        plan_up = plan_reshard(layout_for({"fsdp": 4}), layout_for({"fsdp": 8}))
        plan_down = plan_reshard(layout_for({"fsdp": 4}), layout_for({"fsdp": 2}))
        return ProgramInfo(
            name="reshard_resume", jaxpr=jaxpr, kind="reshard",
            lower=lambda: jax.jit(reshard, in_shardings=(src_shardings,),
                                  out_shardings=(up, down)).lower(aparams),
            metadata={
                "multi_device": True,
                "mesh_axes": {str(a): int(s) for a, s in mesh.shape.items()},
                "reshard_plan": {"scale_up": plan_up.summary(),
                                 "scale_down": plan_down.summary()},
                "collective_signature": [
                    # scale-down re-chunks 4-way -> 2-way: each wider target
                    # shard gathers its halves — the restore path's gather leg
                    {"layer": "compiled", "kind": "all_gather", "min_count": 1,
                     "note": "scale-down leg gathers saved shards into the "
                             "wider target chunks"},
                    # a reshard never REDUCES: any all-reduce would mean the
                    # identity program is summing state
                    {"layer": "compiled", "kind": "all_reduce", "count": 0,
                     "note": "resharding moves bytes, never sums them"}]})
    finally:
        set_topology(None)


@scenario("composition_3d_ep_zeropp")
def composition_3d_ep_zeropp() -> ProgramInfo:
    """ROADMAP item 5's never-executed full composition: pipe x expert x
    tensor x fsdp (all >=2, 16 virtual devices) with qgZ quantized
    gradients. This builder ATTEMPTS the real construction so the first
    blocking gap on any runtime is *inventoried* in the report's
    skipped-scenarios section instead of staying folklore. The old first
    link — 8 forced host devices — is burned down: a <16-device run
    probes the 16-device build in a subprocess and reports the gap
    *behind* it, so on the pinned container the chain now starts at the
    jax-0.4.37 partial-manual shard_map gap (pipe is manual,
    expert/tensor/fsdp stay auto at size 2) -> MoE blocks unsupported
    inside the pipelined GPT-2."""
    import deepspeed_tpu
    from deepspeed_tpu.models import get_gpt2_config
    from deepspeed_tpu.models.gpt2 import gpt2_pipe_layers
    from deepspeed_tpu.parallel.topology import MeshTopology, set_topology
    from deepspeed_tpu.utils.jax_compat import PARTIAL_MANUAL_OK
    from deepspeed_tpu.runtime.pipe.module import PipelineModule

    if len(jax.devices()) < 16:
        import os
        if os.environ.get("DS_COMPOSITION_PROBE"):
            # already inside a probe child whose forced device count did
            # not take effect: report plainly, never fork a grandchild
            raise ScenarioSkipped(
                f"needs 16 virtual devices (probe child has "
                f"{len(jax.devices())})", kind="device_count")
        # device_count burn-down: the host-device count cannot change after
        # backend init, but the blocking-gap INVENTORY must not stop here —
        # probe the 16-device build out of process and report the real gap
        # (partial_manual on the pinned container). In-process tracing still
        # needs GRAFT_LINT_DEVICES=16.
        gap = _probe_composition_16dev()
        raise ScenarioSkipped(gap["detail"], kind=gap["kind"], probe=gap.get("probe"))
    if not PARTIAL_MANUAL_OK:
        raise ScenarioSkipped(
            "jax-0.4.37 partial-manual shard_map gap: the pipe axis is "
            "manual while expert/tensor/fsdp stay auto at size 2 "
            "(utils/jax_compat.py) — the composition traces on jax>=0.5",
            kind="partial_manual")
    set_topology(None)
    try:
        cfg = get_gpt2_config("test", n_layer=4, moe_num_experts=2,
                              moe_layer_freq=2, moe_k=1)
        topo = MeshTopology(pipe=2, expert=2, tensor=2, fsdp=2, data=1,
                            devices=jax.devices()[:16])
        try:
            layers = gpt2_pipe_layers(cfg)
        except ValueError as e:  # MoE-in-pipe unsupported (aux-loss drop)
            raise ScenarioSkipped(f"MoE blocks in the pipelined GPT-2: {e}",
                                  kind="moe_in_pipe") from e
        pipe = PipelineModule(layers=layers, topology=topo)
        engine, _, _, _ = deepspeed_tpu.initialize(
            model=pipe, topology=topo,
            config={"train_batch_size": 8, "gradient_accumulation_steps": 4,
                    "optimizer": {"type": "AdamW", "params": {"lr": 1e-3}},
                    "zero_optimization": {"stage": 3,
                                          "zero_quantized_gradients": True}})
        batch = {"input_ids": np.zeros((8, 32), np.int32)}
        return _engine_program("composition_3d_ep_zeropp", engine, batch)
    except NotImplementedError as e:
        raise ScenarioSkipped(f"composition untraceable here: {e}",
                              kind="partial_manual") from e
    finally:
        set_topology(None)


# ---------------------------------------------------------------------------
def build(names: Optional[List[str]] = None):
    """Build the matrix. Returns ``(programs, skipped)`` where ``skipped``
    maps each scenario this runtime cannot trace to its structured
    blocking gap ``{"kind", "detail"}`` (``ScenarioSkipped.kind``) — the
    shape the report commits as ``skipped_scenarios`` so gap burn-down is
    a metric, not a prose diff."""
    unknown = [n for n in names or [] if n not in SCENARIOS]
    if unknown:
        raise ValueError(f"unknown scenario(s) {unknown}; valid: {sorted(SCENARIOS)}")
    programs, skipped = [], {}
    for name in names or list(SCENARIOS):
        try:
            info = SCENARIOS[name]()
            if len(jax.devices()) > 1 and "multi_device" not in info.metadata:
                info.metadata["multi_device"] = info.kind == "train_step"
            programs.append(info)
        except ScenarioSkipped as e:
            skipped[name] = {"kind": e.kind, "detail": str(e)}
    return programs, skipped
