"""The graft-lint scenario matrix: representative traced programs.

Each scenario builder traces one real program shape the repo ships —
model fwd+bwd (gpt2/llama/bert), the MoE sorted route (top1/top2, where
R001's ``[S,E,C]`` ban has teeth), the pipeline scan step, and the
engine's full ``train_batch`` step (the parity path, where donation and
precision are judged). Builders TRACE only — ``jax.make_jaxpr`` /
``.lower()`` — no compilation, no device buffers beyond tiny init
params, so the whole matrix runs on CPU in seconds and can gate CI
between chip windows.

Scenario metadata is where repo knowledge enters the rules: the MoE
scenarios declare their banned ``(S, E, C)`` signature via
``sharded_moe.sec_signature`` (single source with the gating cores);
``train_batch`` declares ``parity``/``expect_donation``; multi-device
scenarios declare ``multi_device``.

Route/kernel resolution inside the MoE scenarios goes through
``moe.routing.resolve_route`` (no explicit kwarg), so a forced
``DS_MOE_ROUTE=dense`` env — the seeded-regression acceptance check —
flows into the traced program exactly as it would into a bench run.
"""

from typing import Callable, Dict, List, Optional

import numpy as np

import jax
import jax.numpy as jnp

from deepspeed_tpu.analysis.program import ProgramInfo

SCENARIOS: Dict[str, Callable[[], ProgramInfo]] = {}


class ScenarioSkipped(Exception):
    """Raised by a builder when its program cannot trace on this runtime
    (e.g. partial-manual shard_map on jax 0.4.37) — reported, not fatal."""


def scenario(name: str):
    def wrap(fn):
        SCENARIOS[name] = fn
        return fn

    return wrap


def _model_fwd_bwd(name, model, variables, loss):
    return ProgramInfo(name=name, jaxpr=jax.make_jaxpr(jax.grad(loss))(variables),
                       kind="fwd_bwd")


# ---------------------------------------------------------------------------
@scenario("gpt2_fwd_bwd")
def gpt2_fwd_bwd() -> ProgramInfo:
    from deepspeed_tpu.models import GPT2LMHeadModel, get_gpt2_config

    cfg = get_gpt2_config("test")
    model = GPT2LMHeadModel(cfg)
    ids = jnp.zeros((2, 32), jnp.int32)
    variables = model.init(jax.random.PRNGKey(0), ids)

    def loss(v):
        out = model.apply(v, ids)
        logits = out[0] if isinstance(out, tuple) else out
        return logits.astype(jnp.float32).sum()

    return _model_fwd_bwd("gpt2_fwd_bwd", model, variables, loss)


@scenario("llama_fwd_bwd")
def llama_fwd_bwd() -> ProgramInfo:
    from deepspeed_tpu.models import LlamaForCausalLM, get_llama_config

    cfg = get_llama_config("test")
    model = LlamaForCausalLM(cfg)
    ids = jnp.zeros((2, 32), jnp.int32)
    variables = model.init(jax.random.PRNGKey(0), ids)

    def loss(v):
        out = model.apply(v, ids)
        logits = out[0] if isinstance(out, tuple) else out
        return logits.astype(jnp.float32).sum()

    return _model_fwd_bwd("llama_fwd_bwd", model, variables, loss)


@scenario("bert_fwd_bwd")
def bert_fwd_bwd() -> ProgramInfo:
    from deepspeed_tpu.models import BertForMaskedLM, get_bert_config

    cfg = get_bert_config("test")
    model = BertForMaskedLM(cfg)
    ids = jnp.zeros((2, 32), jnp.int32)
    variables = model.init(jax.random.PRNGKey(0), ids)

    def loss(v):
        out = model.apply(v, ids)
        logits = out[0] if isinstance(out, tuple) else out
        return logits.astype(jnp.float32).sum()

    return _model_fwd_bwd("bert_fwd_bwd", model, variables, loss)


# ---------------------------------------------------------------------------
def _moe_program(name: str, k: int) -> ProgramInfo:
    import flax.linen as nn

    from deepspeed_tpu.moe.sharded_moe import MOELayer, sec_signature

    class _Expert(nn.Module):
        @nn.compact
        def __call__(self, x, deterministic=True):
            return nn.Dense(x.shape[-1], use_bias=False)(x)

    B, L, M, E, cf, min_cap = 2, 16, 8, 4, 1.0, 1
    S = B * L  # one group without a topology
    # no explicit route kwarg: resolution flows through env/config exactly
    # like a bench run, so DS_MOE_ROUTE=dense seeds the R001 regression
    layer = MOELayer(expert=_Expert(), model_dim=M, num_experts=E, k=k,
                     capacity_factor=cf, eval_capacity_factor=cf,
                     min_capacity=min_cap)
    x = jnp.zeros((B, L, M), jnp.float32)
    variables = layer.init(jax.random.PRNGKey(0), x)

    def loss(v, xx):
        (out, l_aux, _), _ = layer.apply(v, xx, mutable=["intermediates"])
        return (out ** 2).sum() + l_aux

    jaxpr = jax.make_jaxpr(jax.grad(loss, argnums=(0, 1)))(variables, x)
    return ProgramInfo(
        name=name, jaxpr=jaxpr, kind="fwd_bwd",
        metadata={"moe_sec": [sec_signature(S, E, cf, min_cap, k=k)]})


@scenario("moe_top1_route")
def moe_top1_route() -> ProgramInfo:
    return _moe_program("moe_top1_route", k=1)


@scenario("moe_top2_route")
def moe_top2_route() -> ProgramInfo:
    return _moe_program("moe_top2_route", k=2)


# ---------------------------------------------------------------------------
def _engine_program(name: str, engine, example_batch, extra_metadata=None) -> ProgramInfo:
    programs = engine.traced_programs(example_batch)
    step = programs["train_step"]
    metadata = dict(step["metadata"])
    metadata.update(extra_metadata or {})
    return ProgramInfo(name=name, jaxpr=step["jaxpr"], hlo_text=step["hlo_text"],
                       kind="train_step", metadata=metadata)


@scenario("train_batch_parity")
def train_batch_parity() -> ProgramInfo:
    """The engine's fused train step for a tiny GPT-2 — the program the
    CPU parity envelope (ROADMAP item 4) judges. ``parity: True`` arms
    R002's upcast attribution; ``expect_donation`` arms R005."""
    import deepspeed_tpu
    from deepspeed_tpu.models import GPT2LMHeadModel, get_gpt2_config
    from deepspeed_tpu.parallel.topology import set_topology

    set_topology(None)
    try:
        engine, _, _, _ = deepspeed_tpu.initialize(
            model=GPT2LMHeadModel(get_gpt2_config("test")),
            config={"train_batch_size": 8,
                    "optimizer": {"type": "AdamW", "params": {"lr": 1e-3}},
                    "zero_optimization": {"stage": 0}})
        batch = {"input_ids": np.zeros((8, 32), np.int32)}
        return _engine_program("train_batch_parity", engine, batch,
                               {"parity": True})
    finally:
        set_topology(None)


@scenario("pipe_scan_step")
def pipe_scan_step() -> ProgramInfo:
    """The pipeline engine's scan step on a pipe=2 mesh (auto axes size 1
    fold to full-manual, so this traces even on the 0.4.37 container —
    jax_compat docstring). Skips, not fails, where shard_map can't."""
    import deepspeed_tpu
    from deepspeed_tpu.models.gpt2 import gpt2_pipe_layers
    from deepspeed_tpu.models import get_gpt2_config
    from deepspeed_tpu.parallel.topology import MeshTopology, set_topology
    from deepspeed_tpu.runtime.pipe.module import PipelineModule

    if len(jax.devices()) != 8:
        raise ScenarioSkipped("pipe_scan_step expects the 8-device host mesh")
    set_topology(None)
    try:
        cfg = get_gpt2_config("test", n_layer=2)
        topo = MeshTopology(pipe=2, data=2, fsdp=2)
        pipe = PipelineModule(layers=gpt2_pipe_layers(cfg), topology=topo)
        engine, _, _, _ = deepspeed_tpu.initialize(
            model=pipe, topology=topo,
            config={"train_batch_size": 16, "gradient_accumulation_steps": 4,
                    "optimizer": {"type": "AdamW", "params": {"lr": 1e-3}}})
        batch = {"input_ids": np.zeros((16, 32), np.int32)}
        return _engine_program("pipe_scan_step", engine, batch)
    except NotImplementedError as e:  # partial-manual shard_map gap
        raise ScenarioSkipped(f"shard_map unsupported here: {e}") from e
    finally:
        set_topology(None)


# ---------------------------------------------------------------------------
def build(names: Optional[List[str]] = None):
    """Build the matrix. Returns ``(programs, skipped)`` where ``skipped``
    is ``{name: reason}`` for scenarios this runtime cannot trace."""
    unknown = [n for n in names or [] if n not in SCENARIOS]
    if unknown:
        raise ValueError(f"unknown scenario(s) {unknown}; valid: {sorted(SCENARIOS)}")
    programs, skipped = [], {}
    for name in names or list(SCENARIOS):
        try:
            info = SCENARIOS[name]()
            if len(jax.devices()) > 1 and "multi_device" not in info.metadata:
                info.metadata["multi_device"] = info.kind == "train_step"
            programs.append(info)
        except ScenarioSkipped as e:
            skipped[name] = str(e)
    return programs, skipped
