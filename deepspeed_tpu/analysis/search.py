"""graft-search: static cost-model-driven program search over engine knobs.

PR 7 (graft-lint) and PR 10 (graft-audit) built a static stack that can
*price* a traced program — liveness-walk peak/transient bytes plus the
per-participant bytes-moved collective model — in seconds on CPU, but
until now it only gated. This module turns the gate into a *search*
(ROADMAP item 3): a deterministic enumerator over a declared candidate
space — remat policy at block boundaries (none / every-block / every-k /
save-dot variants), LM-head loss/grad chunk sizes, QKV & attention-output
projection fusion, and optimizer-fusion variants — that traces every
candidate through the real engine knobs (the "program" config block +
``optimizer.legacy_fusion``), prices it statically, and commits only the
Pareto frontier to ``analysis_results/search_pareto.json``. The next chip
window measures exactly the statically-surviving set instead of burning
chip minutes on dominated losers (the DeepSpeed-autotuner move, executed
on CPU).

Pricing is **jaxpr-only** by design: ``engine.traced_programs(batch,
lower=False)`` skips the StableHLO lowering that dominates a full
``--cost`` pass at real model sizes (the 350M step traces in ~7 s but
lowers in ~40 s on the 1-core rig), so the whole judged-config space
prices inside a chip window's coffee break. Objectives per candidate:

* ``peak_transient_bytes`` — the liveness walk's schedule-controlled
  activation peak (``analysis/memory.py``), what remat/chunking buy;
* ``flops_proxy`` — a trip-count-weighted ``dot_general`` FLOP walk over
  the jaxpr (scan bodies multiplied by their length, cond branches taken
  at the max), what remat *costs*. Pinned against the backend's own
  ``cost_analysis()`` in ``tests/unit/analysis/test_search.py``;
* ``bytes_moved`` — total analytic wire bytes over the jaxpr-layer
  collective inventory (``analysis/hlo_cost.py``). Always recorded, but
  an *objective* only on multi-device spaces (both committed spaces pin
  a 1-device topology, where it is zero for every candidate).

Rule **R014** ratchets the committed frontier: on ``tools/graft_lint.py
--cost`` every ``gate=True`` space is re-enumerated and re-priced, and
the run fails when the candidate set drifts, a committed winner's price
drifts beyond tolerance (default 5%), or a committed winner is now
dominated — the drift that would silently invalidate the Pareto set a
chip window is about to spend minutes measuring. Improvements (a new
frontier entrant) report as INFO to bank explicitly with
``tools/graft_search.py --update``, never silently.
"""

import dataclasses
import hashlib
import itertools
import json
import os
from typing import Any, Dict, List, Optional, Tuple

from deepspeed_tpu.analysis import hlo_cost
from deepspeed_tpu.analysis.core import ERROR, INFO, LAYER_COST, WARN, Finding, rule
from deepspeed_tpu.analysis.memory import estimate_memory
from deepspeed_tpu.analysis.program import ProgramAnalyzer, ProgramInfo, _iter_sub_jaxprs

SEARCH_ARTIFACT_VERSION = 1
DEFAULT_TOLERANCE = 0.05  # winner price drift allowed before R014 gates
_MAX_FINDINGS_PER_SPACE = 8

_ARTIFACT_TOP_KEYS = {"version", "tolerance", "jax_version", "spaces"}


# ---------------------------------------------------------------------------
# candidate grammar
# ---------------------------------------------------------------------------
@dataclasses.dataclass(frozen=True)
class Candidate:
    """One point of the search space. ``remat`` grammar:
    ``"none" | "every_<k>[:<policy>]"`` — ``every_1`` checkpoints every
    block (plain ``jax.checkpoint``, full recompute), ``every_2`` every
    second block, ``:dots_saveable`` etc. select a
    ``runtime/activation_checkpointing`` save policy (the save-dot
    variants). ``lm_head_chunk`` is tokens per fused LM-head loss chunk
    (0 = the unfused ``[B, L, V]`` logits head). ``optimizer`` is
    ``"fused"`` (the single tree-map chain) or ``"chained"``
    (``optimizer.legacy_fusion``: optax's staged composition)."""

    remat: str
    lm_head_chunk: int
    fused_qkv: bool = True
    fused_attn_out: bool = True
    optimizer: str = "fused"

    def __post_init__(self):
        mode, _, _ = self.remat.partition(":")
        if mode != "none":
            stride = mode[len("every_"):] if mode.startswith("every_") else ""
            if not stride.isdigit() or int(stride) < 1:
                raise ValueError(f"bad remat spec {self.remat!r}: "
                                 f"'none' or 'every_<k>[:<policy>]' with k >= 1")
        if self.optimizer not in ("fused", "chained"):
            raise ValueError(f"bad optimizer variant {self.optimizer!r}")

    @property
    def cid(self) -> str:
        return (f"remat={self.remat}|head={self.lm_head_chunk}"
                f"|qkv={'fused' if self.fused_qkv else 'split'}"
                f"|out={'fused' if self.fused_attn_out else 'reshape'}"
                f"|opt={self.optimizer}")

    def program_block(self) -> dict:
        """The engine "program" config block realizing this candidate —
        the same knobs a production JSON would set (runtime/config.py
        ``ProgramConfig``), so the priced program IS the runnable one."""
        mode, _, policy = self.remat.partition(":")
        if mode == "none":
            block = {"remat": False}
        else:
            block = {"remat": True, "remat_every": int(mode[len("every_"):]),
                     "remat_policy": policy or "none"}
        block["lm_head_chunk"] = int(self.lm_head_chunk)
        block["fused_qkv"] = bool(self.fused_qkv)
        block["fused_attn_out"] = bool(self.fused_attn_out)
        return block


_AXIS_ORDER = ("remat", "lm_head_chunk", "fused_qkv", "fused_attn_out", "optimizer")
_AXIS_DEFAULTS = {"remat": ("none",), "lm_head_chunk": (0,),
                  "fused_qkv": (True,), "fused_attn_out": (True,),
                  "optimizer": ("fused",)}


@dataclasses.dataclass
class SearchSpace:
    """A declared candidate space over one judged engine config. ``axes``
    maps axis name -> value tuple (unlisted axes stay at their default);
    ``probes`` appends explicit off-product candidates (e.g. one
    optimizer-fusion A/B at the expected winner) without squaring the
    product. ``gate=True`` spaces are re-priced and ratcheted by R014 on
    every ``graft_lint --cost`` run — keep those small and CPU-fast."""

    name: str
    model_name: str
    micro_bs: int
    seq: int
    dtype: str = "float32"
    model_overrides: Dict[str, Any] = dataclasses.field(default_factory=dict)
    ds_base: Dict[str, Any] = dataclasses.field(default_factory=dict)
    axes: Dict[str, tuple] = dataclasses.field(default_factory=dict)
    probes: Tuple[Candidate, ...] = ()
    #: Pareto objectives, declared PER SPACE. ``bytes_moved`` is always
    #: recorded as a metric but only belongs in the objective tuple on
    #: multi-device spaces — on the 1-device topologies both committed
    #: spaces pin, it is structurally zero for every candidate and would
    #: be a dead dimension masquerading as a live one.
    objectives: Tuple[str, ...] = ("peak_transient_bytes", "flops_proxy")
    gate: bool = False

    def signature(self) -> str:
        raw = json.dumps({"model": self.model_name, "mb": self.micro_bs,
                          "seq": self.seq, "dtype": self.dtype,
                          "overrides": dict(sorted(self.model_overrides.items())),
                          "ds": self.ds_base,
                          "axes": {k: list(v) for k, v in sorted(self.axes.items())},
                          "probes": [p.cid for p in self.probes],
                          "objectives": list(self.objectives)},
                         sort_keys=True, default=str)
        return hashlib.sha1(raw.encode()).hexdigest()[:12]


def enumerate_candidates(space: SearchSpace) -> List[Candidate]:
    """The deterministic enumeration: full product over the declared axes
    (fixed axis order, declared value order) followed by the probes,
    deduped by candidate id preserving first occurrence."""
    unknown = sorted(set(space.axes) - set(_AXIS_ORDER))
    if unknown:
        raise ValueError(f"space {space.name!r} declares unknown axes {unknown}; "
                         f"valid: {list(_AXIS_ORDER)}")
    values = [tuple(space.axes.get(a, _AXIS_DEFAULTS[a])) for a in _AXIS_ORDER]
    out, seen = [], set()
    for combo in itertools.product(*values):
        cand = Candidate(**dict(zip(_AXIS_ORDER, combo)))
        if cand.cid not in seen:
            seen.add(cand.cid)
            out.append(cand)
    for cand in space.probes:
        if cand.cid not in seen:
            seen.add(cand.cid)
            out.append(cand)
    return out


# ---------------------------------------------------------------------------
# the declared spaces
# ---------------------------------------------------------------------------
def _ds_base(bf16: bool) -> dict:
    ds = {"optimizer": {"type": "AdamW", "params": {"lr": 1e-4, "weight_decay": 0.01}},
          "gradient_clipping": 1.0,
          "zero_optimization": {"stage": 0},
          "steps_per_print": 10**9}
    if bf16:
        ds["bf16"] = {"enabled": True}
    return ds


#: the registry. ``350m_judged`` mirrors the bench methodology's judged
#: single-chip operating point (bench.py: mb8 / seq1024 / bf16 / padded
#: vocab / one-hot embedding backward); attention stays on the XLA
#: backend so pricing is backend-reproducible — flash block geometry has
#: its own tuner (tools/attn_tune.py). ``gpt2_test_gate`` is the small
#: CPU-fast space R014 re-prices on every ``graft_lint --cost`` run.
SPACES: Dict[str, SearchSpace] = {
    "350m_judged": SearchSpace(
        name="350m_judged",
        model_name="350m", micro_bs=8, seq=1024, dtype="bfloat16",
        model_overrides={"vocab_size": 50304, "embed_onehot_grad": True},
        ds_base=_ds_base(bf16=True),
        axes={"remat": ("none", "every_1", "every_1:dots_saveable",
                        "every_2:dots_saveable"),
              "lm_head_chunk": (0, 512, 1024),
              "fused_qkv": (True, False)},
        probes=(Candidate(remat="every_1:dots_saveable", lm_head_chunk=1024,
                          fused_attn_out=False),
                Candidate(remat="every_1:dots_saveable", lm_head_chunk=1024,
                          optimizer="chained")),
        gate=False),
    "gpt2_test_gate": SearchSpace(
        name="gpt2_test_gate",
        model_name="test", micro_bs=4, seq=64, dtype="float32",
        # vocab 512: the test preset's 256 collides with 4*n_embd, which
        # would confound the [*, V]-shaped LM-head trace evidence with MLP
        # dots (and flatten the chunk-vs-full memory spread the gate's
        # drift check needs)
        model_overrides={"vocab_size": 512},
        ds_base=_ds_base(bf16=False),
        axes={"remat": ("none", "every_1:dots_saveable", "every_2"),
              "lm_head_chunk": (0, 32)},
        probes=(Candidate(remat="every_1", lm_head_chunk=32, fused_qkv=False),
                Candidate(remat="every_1", lm_head_chunk=32, optimizer="chained")),
        gate=True),
}


# ---------------------------------------------------------------------------
# pricing
# ---------------------------------------------------------------------------
def build_candidate_engine(space: SearchSpace, cand: Candidate):
    """Engine + example batch for one candidate, every knob routed through
    the engine surface (the "program" block + ``optimizer.legacy_fusion``)
    — the priced program is exactly what ``deepspeed_tpu.initialize`` with
    this JSON would dispatch. Topology is pinned to ONE device so prices
    never depend on the host's virtual-device count."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    import deepspeed_tpu
    from deepspeed_tpu.models import GPT2LMHeadModel, get_gpt2_config
    from deepspeed_tpu.parallel.topology import MeshTopology, set_topology

    set_topology(None)
    dtype = {"float32": jnp.float32, "bfloat16": jnp.bfloat16}[space.dtype]
    cfg = get_gpt2_config(space.model_name, n_positions=space.seq, dtype=dtype,
                          **space.model_overrides)
    ds = json.loads(json.dumps(space.ds_base))  # deep copy, JSON-shaped by contract
    ds["train_batch_size"] = space.micro_bs
    ds["program"] = cand.program_block()
    if cand.optimizer == "chained":
        ds.setdefault("optimizer", {"type": "AdamW", "params": {"lr": 1e-4}})
        ds["optimizer"]["legacy_fusion"] = True
    topo = MeshTopology(data=1, devices=jax.devices()[:1])
    engine, _, _, _ = deepspeed_tpu.initialize(
        model=GPT2LMHeadModel(cfg), topology=topo, config=ds)
    batch = {"input_ids": np.zeros((space.micro_bs, space.seq), np.int32)}
    return engine, batch, engine.module.config


def _dot_flops(eqn) -> int:
    (lc, rc), (lb, rb) = eqn.params["dimension_numbers"]
    lhs = tuple(eqn.invars[0].aval.shape)
    rhs = tuple(eqn.invars[1].aval.shape)
    batch = k = m = n = 1
    for i in lb:
        batch *= lhs[i]
    for i in lc:
        k *= lhs[i]
    for i, d in enumerate(lhs):
        if i not in lc and i not in lb:
            m *= d
    for i, d in enumerate(rhs):
        if i not in rc and i not in rb:
            n *= d
    return 2 * batch * m * n * k


def flops_proxy(closed_jaxpr) -> int:
    """Trip-count-weighted ``dot_general`` FLOPs over the whole jaxpr:
    scan bodies multiply by their ``length``, ``cond`` branches take the
    max (alternatives), ``while`` bodies count once (trip count is not
    static — a documented underestimate; no step program in this repo
    carries a while-loop matmul). A grad jaxpr naturally contains the
    forward, backward AND remat-recompute dots, so the proxy prices
    exactly what remat trades: transient bytes for recompute FLOPs."""
    jaxpr = getattr(closed_jaxpr, "jaxpr", closed_jaxpr)

    def walk(j, mult: int) -> int:
        total = 0
        for eqn in j.eqns:
            name = eqn.primitive.name
            if name == "dot_general":
                total += mult * _dot_flops(eqn)
                continue
            sub_mult = mult
            if name == "scan":
                sub_mult = mult * max(int(eqn.params.get("length") or 1), 1)
            subs = [sub for value in eqn.params.values()
                    for sub, _ in _iter_sub_jaxprs(value)]
            if not subs:
                continue
            if name == "cond":
                total += sub_mult * max(walk(s, 1) for s in subs)
            else:
                for s in subs:
                    total += walk(s, sub_mult)
        return total

    return walk(jaxpr, 1)


def _trace_evidence(analyzer: ProgramAnalyzer, model_cfg) -> dict:
    """Trace-level proof that each knob actually landed in the program:
    remat2 coverage (+ whether a save policy is attached), the LM-head
    chunk visible as ``[chunk, V]`` logits dots (vs the full-rank
    ``[B, L, V]`` einsum), and the projection-fusion dot shapes."""
    vocab = int(model_cfg.vocab_size)
    n_head, head_dim, n_embd = (int(model_cfg.n_head), int(model_cfg.head_dim),
                                int(model_cfg.n_embd))
    remat_eqns, policy_saved = set(), False
    head_chunks, full_logits = set(), False
    qkv_fused = qkv_split = out_fused = out_reshaped = 0
    for rec in analyzer.records():
        if rec.primitive == "remat2":
            remat_eqns.add(id(rec.eqn))
            if rec.eqn.params.get("policy") is not None:
                policy_saved = True
        if rec.primitive != "dot_general":
            continue
        out_aval = getattr(rec.eqn.outvars[0], "aval", None)
        shape = tuple(getattr(out_aval, "shape", ()))
        if shape and shape[-1] == vocab:
            # a dot emitting logits: [chunk, V] = the fused-head scan body,
            # rank>=3 [..., V] = the unfused whole-sequence head
            if len(shape) == 2:
                head_chunks.add(int(shape[0]))
            else:
                full_logits = True
        rhs = getattr(rec.eqn.invars[1], "aval", None)
        rhs_shape = tuple(getattr(rhs, "shape", ()))
        if rhs_shape == (n_embd, 3, n_head, head_dim):
            qkv_fused += 1
        elif rhs_shape == (n_embd, n_head, head_dim):
            qkv_split += 1
        if rhs_shape == (n_head, head_dim, n_embd):
            out_fused += 1
        elif rhs_shape == (n_head * head_dim, n_embd):
            out_reshaped += 1
    return {"remat2_sites": len(remat_eqns),
            "remat_policy_saved": policy_saved,
            "lm_head_chunks": sorted(head_chunks),
            "full_logits": full_logits,
            "qkv_fused_dots": qkv_fused,
            "qkv_split_dots": qkv_split,
            "attn_out_fused_dots": out_fused,
            "attn_out_reshaped_dots": out_reshaped}


def price_candidate(space: SearchSpace, cand: Candidate) -> dict:
    """Build + trace + statically price one candidate. Deterministic by
    construction: same code + same knobs -> same jaxpr -> same numbers
    (the property the two-run determinism test pins)."""
    from deepspeed_tpu.parallel.topology import set_topology

    engine, batch, model_cfg = build_candidate_engine(space, cand)
    try:
        programs = engine.traced_programs(batch, lower=False)
    finally:
        set_topology(None)
    step = programs["train_step"]
    info = ProgramInfo(name=cand.cid, jaxpr=step["jaxpr"], kind="train_step",
                       metadata=step["metadata"])
    analyzer = ProgramAnalyzer(info)
    mem = estimate_memory(info)
    ops = hlo_cost.jaxpr_collectives(analyzer, step["metadata"].get("mesh_axes"))
    inventory = hlo_cost.inventory(ops)
    bytes_moved = sum(inv["bytes_moved"] for inv in inventory.values())
    metrics = {"peak_bytes": mem.peak_bytes,
               "peak_transient_bytes": mem.peak_transient_bytes,
               "bytes_moved": int(bytes_moved),
               "flops_proxy": flops_proxy(step["jaxpr"]),
               "eqns": mem.eqns}
    return {"knobs": dataclasses.asdict(cand),
            "metrics": metrics,
            "evidence": _trace_evidence(analyzer, model_cfg)}


# ---------------------------------------------------------------------------
# Pareto
# ---------------------------------------------------------------------------
def _dominates(a: dict, b: dict, objectives) -> bool:
    return (all(a[o] <= b[o] for o in objectives)
            and any(a[o] < b[o] for o in objectives))


def pareto(candidates: Dict[str, dict], objectives) -> Tuple[List[str], Dict[str, List[str]]]:
    """(frontier ids in enumeration order, dominated-candidate provenance:
    id -> the frontier ids that dominate it)."""
    ids = list(candidates)
    frontier = [cid for cid in ids
                if not any(_dominates(candidates[o]["metrics"],
                                      candidates[cid]["metrics"], objectives)
                           for o in ids if o != cid)]
    dominated_by = {}
    for cid in ids:
        if cid in frontier:
            continue
        dominated_by[cid] = [f for f in frontier
                             if _dominates(candidates[f]["metrics"],
                                           candidates[cid]["metrics"], objectives)]
    return frontier, dominated_by


def run_space(space_or_name, log=None, calibration=None) -> dict:
    """Enumerate + price + frontier one space. The returned dict is the
    committed artifact's per-space entry — pure data, no timestamps, so
    two runs of unchanged code compare equal (the determinism contract).

    ``calibration`` (a loaded ``cost_calibration.json`` artifact) adds the
    measured-mode leg: every candidate additionally priced in predicted
    wall **seconds** under the fitted coefficients, ``predicted_seconds``
    joins the run's objective tuple (run-time only — the declared
    ``space.signature()`` never hashes it, so calibrated and uncalibrated
    runs of the same declaration share a space_sig), the frontier is
    recomputed over the extended objectives, and ``seconds_rank`` records
    the frontier in calibrated-seconds order with full provenance — the
    total order the proxy objectives could not give."""
    space = SPACES[space_or_name] if isinstance(space_or_name, str) else space_or_name
    candidates = {}
    for i, cand in enumerate(enumerate_candidates(space)):
        if log:
            log(f"  [{i + 1}] pricing {cand.cid}")
        candidates[cand.cid] = price_candidate(space, cand)
    frontier, dominated_by = pareto(candidates, space.objectives)
    for cid, doms in dominated_by.items():
        candidates[cid]["dominated_by"] = doms
    result = {"space_sig": space.signature(),
              "model": {"name": space.model_name, "micro_bs": space.micro_bs,
                        "seq": space.seq, "dtype": space.dtype},
              "axes": {k: list(v) for k, v in space.axes.items()},
              "objectives": list(space.objectives),
              "gate": space.gate,
              "candidates": candidates,
              "frontier": frontier}
    if calibration is not None:
        _apply_calibration(result, calibration, log=log)
    return result


def _apply_calibration(result: dict, calibration: dict, log=None) -> dict:
    """Price every candidate of a freshly-run space in calibrated seconds
    and re-rank. All-or-nothing per space: if any candidate is unpriceable
    (a ``None`` coefficient meets a nonzero feature) the objective is not
    half-added — a frontier mixing priced and unpriced members would be
    incomparable. No matching calibration entry is a loud no-op."""
    from deepspeed_tpu.analysis.calibrate import calibrated_seconds, calibration_entry

    entry, key = calibration_entry(calibration, scope="train_step")
    if entry is None:
        if log:
            log(f"  no calibration entry for {key} — seconds objective skipped")
        return result
    candidates = result["candidates"]
    seconds = {cid: calibrated_seconds(c["metrics"], entry["coeffs"])
               for cid, c in candidates.items()}
    if any(s is None for s in seconds.values()):
        if log:
            log(f"  calibration {key} cannot price every candidate — "
                f"seconds objective skipped")
        return result
    for cid, c in candidates.items():
        c["metrics"]["predicted_seconds"] = seconds[cid]
        c.pop("dominated_by", None)
    objectives = list(result["objectives"]) + ["predicted_seconds"]
    frontier, dominated_by = pareto(candidates, objectives)
    for cid, doms in dominated_by.items():
        candidates[cid]["dominated_by"] = doms
    result["objectives"] = objectives
    result["frontier"] = frontier
    # stable sort: ties in calibrated seconds keep the proxy
    # (enumeration) order, so the re-rank is a refinement, not a shuffle
    result["seconds_rank"] = sorted(frontier, key=lambda cid: seconds[cid])
    result["calibration"] = {"key": key, "coeffs": dict(entry["coeffs"])}
    return result


# ---------------------------------------------------------------------------
# artifact IO (merge semantics, like the cost baseline)
# ---------------------------------------------------------------------------
def load_search_artifact(path: str) -> dict:
    if not os.path.exists(path):
        return {"version": SEARCH_ARTIFACT_VERSION, "tolerance": DEFAULT_TOLERANCE,
                "spaces": {}}
    with open(path) as fh:
        artifact = json.load(fh)
    if artifact.get("version") != SEARCH_ARTIFACT_VERSION:
        raise ValueError(f"search artifact {path} has version "
                         f"{artifact.get('version')}, expected "
                         f"{SEARCH_ARTIFACT_VERSION} — regenerate with "
                         f"tools/graft_search.py --update")
    unknown = set(artifact) - _ARTIFACT_TOP_KEYS
    if unknown:
        raise ValueError(f"search artifact {path} has unknown top-level keys "
                         f"{sorted(unknown)}")
    artifact.setdefault("tolerance", DEFAULT_TOLERANCE)
    artifact.setdefault("spaces", {})
    return artifact


def search_artifact_from(results: Dict[str, dict], prior: Optional[dict] = None) -> dict:
    """Bank current space results. MERGE semantics: a single-space
    ``--update`` refreshes only its own entry — dropping another space's
    entry would silently un-gate it."""
    import jax
    spaces = dict((prior or {}).get("spaces", {}))
    spaces.update(results)
    return {"version": SEARCH_ARTIFACT_VERSION,
            "tolerance": (prior or {}).get("tolerance", DEFAULT_TOLERANCE),
            "jax_version": jax.__version__,
            "spaces": dict(sorted(spaces.items()))}


# ---------------------------------------------------------------------------
# R014 — the frontier ratchet
# ---------------------------------------------------------------------------
@rule("R014", "the committed search frontier must not regress", ERROR, LAYER_COST)
def r014_search_frontier(artifact: dict, current_by_space: Dict[str, dict],
                         tolerance: Optional[float] = None) -> List[Finding]:
    """Re-priced gate spaces vs the committed
    ``analysis_results/search_pareto.json``: ERROR when the enumerated
    candidate set or declared space drifts without re-banking, when a
    committed frontier winner's static price drifts beyond tolerance on
    any objective, or when a committed winner is now dominated (the
    frontier regressed — or improved past its commit; either way the
    Pareto set a chip window would consume is stale). New frontier
    entrants and un-banked spaces report as INFO so improvements are
    banked explicitly with ``tools/graft_search.py --update``."""
    tol = float(tolerance if tolerance is not None
                else artifact.get("tolerance", DEFAULT_TOLERANCE))
    findings: List[Finding] = []
    for name, cur in sorted(current_by_space.items()):
        scenario = f"search:{name}"
        space_findings: List[Finding] = []
        base = artifact.get("spaces", {}).get(name)
        if base is None:
            findings.append(Finding(
                rule="R014", severity=INFO, scenario=scenario,
                message="no committed search entry for this space — bank with "
                        "tools/graft_search.py --update"))
            continue
        if base.get("space_sig") != cur.get("space_sig"):
            findings.append(Finding(
                rule="R014", severity=ERROR, scenario=scenario,
                message=f"declared candidate space drifted (sig "
                        f"{base.get('space_sig')} -> {cur.get('space_sig')}) — "
                        f"re-bank with tools/graft_search.py --update",
                location="space_sig"))
            continue
        base_c, cur_c = base["candidates"], cur["candidates"]
        if set(base_c) != set(cur_c):
            added = sorted(set(cur_c) - set(base_c))[:4]
            gone = sorted(set(base_c) - set(cur_c))[:4]
            findings.append(Finding(
                rule="R014", severity=ERROR, scenario=scenario,
                message=f"enumerated candidates drifted from the committed set "
                        f"(+{added} -{gone}) — re-bank with "
                        f"tools/graft_search.py --update",
                location="candidates"))
            continue
        objectives = base.get("objectives", list(cur.get("objectives", ())))
        for cid in base["frontier"]:
            for obj in objectives:
                b = base_c[cid]["metrics"].get(obj)
                c = cur_c[cid]["metrics"].get(obj)
                if b is None or c is None:
                    continue
                drift = abs(c - b) / b if b else (1.0 if c else 0.0)
                if drift > tol:
                    space_findings.append(Finding(
                        rule="R014", severity=ERROR, scenario=scenario,
                        message=f"winner price drift: {cid} {obj} {b} -> {c} "
                                f"({drift:+.1%} vs {tol:.0%} tolerance)",
                        location=f"{cid}:{obj}"))
        cur_frontier = set(cur["frontier"])
        for cid in base["frontier"]:
            if cid not in cur_frontier:
                doms = cur_c[cid].get("dominated_by", [])
                space_findings.append(Finding(
                    rule="R014", severity=ERROR, scenario=scenario,
                    message=f"committed winner {cid} regresses the frontier — "
                            f"now dominated by {doms[:3]}; re-bank or fix",
                    location=cid))
        for cid in sorted(cur_frontier - set(base["frontier"])):
            space_findings.append(Finding(
                rule="R014", severity=INFO, scenario=scenario,
                message=f"frontier improvement: {cid} now survives — bank with "
                        f"tools/graft_search.py --update",
                location=cid))
        # non-winner drift: diagnostic, never gating (the frontier is the
        # contract; dominated candidates may drift freely inside it)
        for cid in sorted(set(base_c) - set(base["frontier"])):
            for obj in objectives:
                b, c = base_c[cid]["metrics"].get(obj), cur_c[cid]["metrics"].get(obj)
                if b and c is not None and abs(c - b) / b > tol:
                    space_findings.append(Finding(
                        rule="R014", severity=WARN, scenario=scenario,
                        message=f"dominated-candidate price drift: {cid} {obj} "
                                f"{b} -> {c}",
                        location=f"{cid}:{obj}"))
                    break
        findings.extend(space_findings[:_MAX_FINDINGS_PER_SPACE])
    return findings


def gate_space_names() -> List[str]:
    return [name for name, space in SPACES.items() if space.gate]


def verify_spaces(artifact_path: str, names: Optional[List[str]] = None,
                  log=None, calibration=None) -> List[Finding]:
    """Re-price ``names`` (default: every gate space) and judge them with
    R014 against the committed artifact — the shared entry point for the
    lint CLI and tools/graft_search.py's verify mode. ``calibration``
    defaults to the committed ``cost_calibration.json`` so a re-priced
    space carries the same ``predicted_seconds`` objective the banked one
    does; an absent artifact degrades to proxy-only pricing (R014's drift
    check skips objectives only one side carries)."""
    artifact = load_search_artifact(artifact_path)
    if calibration is None:
        from deepspeed_tpu.analysis.calibrate import load_calibration
        calibration = load_calibration()
    names = list(names if names is not None else gate_space_names())
    current = {name: run_space(name, log=log, calibration=calibration)
               for name in names}
    return r014_search_frontier(artifact, current)
