"""Source-layer (AST) rule R008 + the inline-waiver comment scanner.

The jaxpr rules see what got traced; this pass sees what *can't* be
traced — host-side API misuse. Two bans, both from hard-won container
folklore:

* raw ``jax.device_put`` anywhere in the package (outside
  ``utils/device.py`` itself): on the pinned 0.4.37 CPU runtime a
  zero-copy ``device_put`` of host data aliases foreign memory, and
  donating that array corrupts the heap (glibc "corrupted double-linked
  list" several dispatches later). ``owned_device_put`` is the safe
  spelling. Audited-safe sites (jax-owned sources, device->device
  resharding) carry an inline waiver:

      jax.device_put(x, sharding)  # graft-lint: waive R008 jax-owned source

* ``time.time()``/``time.perf_counter()``/``np.random``/``random.*``
  inside a ``@jax.jit``-decorated body: traced once at compile time,
  frozen forever after — the classic "my timestamps/noise never change"
  bug.
"""

import ast
import re
from typing import Iterable, List, Tuple

from deepspeed_tpu.analysis.core import ERROR, LAYER_AST, Finding, rule

WAIVE_RE = re.compile(r"#\s*graft-lint:\s*waive\s+(R\d{3})(?:\s+(.*))?")

#: files allowed to call jax.device_put directly (the safe wrapper itself)
DEVICE_PUT_ALLOWED = ("utils/device.py",)


def line_waivers(source: str):
    """{lineno: (rule_id, reason)} for inline waiver comments."""
    out = {}
    for i, line in enumerate(source.splitlines(), start=1):
        m = WAIVE_RE.search(line)
        if m:
            out[i] = (m.group(1), (m.group(2) or "").strip())
    return out


def _string_literal_lines(tree) -> set:
    """Line numbers covered by string constants (docstrings): a waiver
    pattern in there is documentation of the syntax, not a waiver."""
    lines = set()
    for node in ast.walk(tree):
        if isinstance(node, ast.Constant) and isinstance(node.value, str):
            end = getattr(node, "end_lineno", node.lineno)
            lines.update(range(node.lineno, end + 1))
    return lines


def stale_inline_waivers(files, findings) -> List[dict]:
    """Inline ``# graft-lint: waive`` comments that sit on a line no
    current finding points at — the code they excused moved or was fixed,
    and a stale comment on the wrong line could silently excuse the NEXT
    edit. Reported as WARNs by the CLI (mirror of
    :func:`core.stale_config_waivers` for the AST layer)."""
    locations = {f.location for f in findings}
    out = []
    for rel, source, tree in files:
        doc_lines = _string_literal_lines(tree)
        for line, (rule_id, reason) in line_waivers(source).items():
            if line not in doc_lines and f"{rel}:{line}" not in locations:
                out.append({"kind": "inline", "file": rel, "line": line,
                            "rule": rule_id, "reason": reason})
    return out


def _dotted(node) -> str:
    """'jax.device_put' for Attribute/Name chains, '' otherwise."""
    parts = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return ""


def _is_jit_decorator(dec) -> bool:
    """Matches @jax.jit, @jit, @partial(jax.jit, ...), @functools.partial(jit, ...),
    and @jax.jit(...) call forms."""
    if isinstance(dec, ast.Call):
        name = _dotted(dec.func)
        if name.endswith("partial"):
            return any(_is_jit_decorator(a) for a in dec.args)
        dec_name = name
    else:
        dec_name = _dotted(dec)
    return dec_name in ("jit", "jax.jit", "pjit", "jax.pjit")


_FROZEN_HOST_CALLS = ("time.time", "time.perf_counter", "time.monotonic",
                      "datetime.now", "datetime.datetime.now")
_FROZEN_HOST_PREFIXES = ("np.random.", "numpy.random.", "random.")


def _frozen_host_call(name: str) -> bool:
    return name in _FROZEN_HOST_CALLS or any(name.startswith(p) for p in _FROZEN_HOST_PREFIXES)


@rule("R008", "raw jax.device_put / frozen host state in jitted bodies", ERROR, LAYER_AST)
def r008_source(files: Iterable[Tuple[str, str, ast.Module]]) -> List[Finding]:
    """See module docstring. ``files``: (relpath, source, parsed module)."""
    findings = []
    for relpath, source, tree in files:
        waivers = line_waivers(source)

        def emit(lineno, message, _rel=relpath, _w=waivers):
            w = _w.get(lineno)
            waived = bool(w and w[0] == "R008")
            findings.append(Finding(
                rule="R008", severity=ERROR, scenario=_rel, message=message,
                location=f"{_rel}:{lineno}", waived=waived,
                waiver_reason=w[1] if waived else ""))

        device_put_ok = any(relpath.endswith(a) for a in DEVICE_PUT_ALLOWED)
        # names bound by `from jax import device_put [as alias]`
        dp_aliases = set()
        for node in ast.walk(tree):
            if isinstance(node, ast.ImportFrom) and node.module == "jax":
                for a in node.names:
                    if a.name == "device_put":
                        dp_aliases.add(a.asname or a.name)

        jit_stack: List[bool] = []

        class V(ast.NodeVisitor):
            def _visit_fn(self, node):
                jitted = any(_is_jit_decorator(d) for d in node.decorator_list)
                jit_stack.append(bool(jitted or (jit_stack and jit_stack[-1])))
                self.generic_visit(node)
                jit_stack.pop()

            visit_FunctionDef = _visit_fn
            visit_AsyncFunctionDef = _visit_fn

            def visit_Call(self, node):
                name = _dotted(node.func)
                if not device_put_ok and (name == "jax.device_put" or name in dp_aliases):
                    emit(node.lineno,
                         "raw jax.device_put — use "
                         "deepspeed_tpu.utils.device.owned_device_put (0.4.37 "
                         "zero-copy donation hazard) or waive with an audit note")
                if jit_stack and jit_stack[-1] and _frozen_host_call(name):
                    emit(node.lineno,
                         f"'{name}' inside a @jit-decorated body is evaluated "
                         f"once at trace time and frozen into the compiled program")
                self.generic_visit(node)

        V().visit(tree)
    return findings
