"""Autotuning: compile-and-cost candidate DeepSpeed configs on the live
mesh and pick the fastest runnable one (reference ``deepspeed/autotuning``)."""

from deepspeed_tpu.autotuning.attention_tuner import (AttentionBlockTuner,
                                                      tune_attention_blocks)
from deepspeed_tpu.autotuning.autotuner import Autotuner, Experiment
from deepspeed_tpu.autotuning.config import DeepSpeedAutotuningConfig, get_autotuning_config

__all__ = ["Autotuner", "Experiment", "DeepSpeedAutotuningConfig", "get_autotuning_config",
           "AttentionBlockTuner", "tune_attention_blocks"]
