"""Kernel-level autotuner for the Pallas flash-attention block geometry.

The config-level :class:`~deepspeed_tpu.autotuning.autotuner.Autotuner`
searches (ZeRO stage, micro-batch, mesh); this tuner searches one level
below it — the attention kernel's work partitioning (forward/backward
block sizes, backward causal-skip granularity, recompute policy) per call
shape. FlashAttention-2's result is that this partitioning, not the
algorithm, is where the last 1.5-2x of long-context throughput lives; the
best geometry depends on (seq, head_dim, heads, micro-batch, causal,
dtype), so winners are keyed by that signature and persisted through the
same artifact layout as the config tuner:

* ``exps_dir/attn_<signature>.json`` — every candidate's record (geometry,
  measured seconds, status/error), the per-experiment evidence trail;
* ``results_dir/attention_blocks.json`` — the shape-keyed winners cache
  that ``flash_attention`` resolves through at call time
  (``ops.pallas.attention_geometry``), the ``ds_config_optimal.json``
  analog.

Timing methodology matches the bench tools: one jitted program per
candidate, warmup dispatch, then the best of ``repeats`` timed dispatches
(min — perturbations only ever add time). The default sweep is STAGED to
keep a shape at tens of compiles instead of the ~150 of the full
cross-product: the forward (q, kv) pair is chosen first by forward-only
timing (backward knobs cannot affect it), then the backward axes sweep
fwd+bwd with the forward pair pinned. On non-TPU backends the kernels run
in interpret mode; the selection machinery is identical, so CI smokes the
persist/reload path with tiny shapes while chip windows produce the real
numbers.
"""

import json
import os
import time
from typing import Any, Dict, List, Optional, Sequence, Tuple

import numpy as np

from deepspeed_tpu.ops.pallas.attention_geometry import (CACHE_BASENAME,
                                                         AttentionGeometry,
                                                         signature,
                                                         store_winner)
from deepspeed_tpu.utils.logging import log_dist, logger

# candidate block edges, largest first pruned by divisibility/VMEM below
_BLOCK_EDGES = (1024, 512, 256, 128)
# per-grid-cell VMEM budget for candidate pruning (v5e has ~16 MiB more
# details in the Pallas guide's budget formula; leave headroom for Mosaic's
# double-buffered input windows)
_VMEM_BUDGET_BYTES = 10 * 2**20


def _vmem_bytes(blk_q: int, blk_k: int, head_dim: int, itemsize: int) -> int:
    """Working-set estimate for one grid cell of the fwd/bwd kernels: q/k/v
    input windows (x2 for double buffering), the fp32 scores tile, and the
    fp32 accumulator scratch."""
    tiles = 2 * (blk_q + 2 * blk_k) * head_dim * itemsize  # q + k + v, dbl-buffered
    scores = blk_q * blk_k * 4
    acc = (blk_q + 2 * blk_k) * head_dim * 4
    return tiles + scores + acc


def candidate_axes(lq: int, lk: int, head_dim: int, causal: bool,
                   itemsize: int = 2,
                   ) -> Tuple[List[Tuple[int, int]], List[Tuple[int, int]],
                              Tuple[str, ...]]:
    """The sweep axes for one shape — forward block pairs, backward block
    pairs, backward skip granularities — pruned by divisibility and the
    VMEM budget. The default tune() sweeps them STAGED (forward pair
    first, forward-only timing; then the backward axes on the winning
    pair): the full cross-product would be ~150 compiles per shape, the
    staged sweep tens."""
    def edges(length):
        return [e for e in _BLOCK_EDGES if e <= length and length % e == 0] or [length]

    fwd_pairs = []
    for bq in edges(lq)[:2]:
        for bk in edges(lk)[:3]:
            if _vmem_bytes(bq, bk, head_dim, itemsize) <= _VMEM_BUDGET_BYTES:
                fwd_pairs.append((bq, bk))
    bwd_pairs = []
    for bq in edges(lq)[:3]:
        for bk in edges(lk)[:2]:
            if _vmem_bytes(bq, bk, head_dim, itemsize) <= _VMEM_BUDGET_BYTES:
                bwd_pairs.append((bq, bk))
    skips = ("block", "none") if causal else ("block",)
    return fwd_pairs, bwd_pairs, skips


def default_candidates(lq: int, lk: int, head_dim: int, causal: bool,
                       itemsize: int = 2) -> List[AttentionGeometry]:
    """The flat cross-product of :func:`candidate_axes` — the exhaustive
    grid for callers that want it. tune() does NOT sweep this by default
    (see the staged sweep there); pass it as ``candidates=`` to force the
    full grid."""
    fwd_pairs, bwd_pairs, skips = candidate_axes(lq, lk, head_dim, causal, itemsize)
    cands = []
    for fq, fk in fwd_pairs:
        for bq, bk in bwd_pairs:
            for skip in skips:
                for policy in ("lse", "recompute"):
                    cands.append(AttentionGeometry(
                        block_q=fq, block_k=fk, block_q_bwd=bq, block_k_bwd=bk,
                        bwd_skip=skip, policy=policy))
    return cands


class AttentionBlockTuner:
    """Sweep candidate geometries for one attention call shape and persist
    the winner (see module docstring for the artifact layout)."""

    def __init__(self,
                 results_dir: str = "autotuning_results",
                 exps_dir: str = "autotuning_exps",
                 repeats: int = 3,
                 candidates: Optional[Sequence[AttentionGeometry]] = None,
                 interpret: Optional[bool] = None):
        self.results_dir = results_dir
        self.exps_dir = exps_dir
        self.repeats = max(int(repeats), 1)
        self.candidates = list(candidates) if candidates is not None else None
        self.interpret = interpret
        self.records: List[Dict[str, Any]] = []

    # ------------------------------------------------------------------
    def _time_candidate(self, geom: AttentionGeometry, q, k, v, causal: bool,
                        train: bool) -> float:
        import jax
        import jax.numpy as jnp

        from deepspeed_tpu.ops.pallas.flash_attention import flash_attention

        kwargs = dict(geom.call_kwargs(), causal=causal, interpret=self.interpret)

        if train:
            def loss(q_, k_, v_):
                return (flash_attention(q_, k_, v_, **kwargs).astype(jnp.float32) ** 2).sum()

            fn = jax.jit(jax.grad(loss, argnums=(0, 1, 2)))
        else:
            fn = jax.jit(lambda q_, k_, v_: flash_attention(q_, k_, v_, **kwargs))

        jax.block_until_ready(fn(q, k, v))  # compile + warm
        best = float("inf")
        for _ in range(self.repeats):
            t0 = time.perf_counter()
            jax.block_until_ready(fn(q, k, v))
            best = min(best, time.perf_counter() - t0)
        return best

    # ------------------------------------------------------------------
    def _sweep(self, cands: Sequence[AttentionGeometry], q, k, v, causal: bool,
               train: bool, stage: Optional[str] = None,
               ) -> Tuple[Optional[AttentionGeometry], float]:
        best_geom, best_s = None, float("inf")
        for geom in cands:
            rec: Dict[str, Any] = {"geometry": geom.as_dict(), "status": "pending"}
            if stage is not None:
                rec["stage"] = stage
            try:
                s = self._time_candidate(geom, q, k, v, causal, train)
                rec.update(status="measured", seconds=s)
                if s < best_s:
                    best_geom, best_s = geom, s
            except Exception as e:  # unlowerable/oom candidates prune cleanly
                rec.update(status="failed", error=f"{type(e).__name__}: {str(e)[:200]}")
                logger.warning(f"attention autotune: {geom.spec()} failed: "
                               f"{rec['error'][:120]}")
            self.records.append(rec)
        return best_geom, best_s

    # ------------------------------------------------------------------
    def tune(self, *, seq: int, head_dim: int, heads: int = 1, batch: int = 1,
             seq_k: Optional[int] = None, causal: bool = True, dtype=None,
             train: bool = True) -> Tuple[Optional[AttentionGeometry], List[Dict[str, Any]]]:
        """Sweep the shape, persist and return the winner. ``train=True``
        targets the training hot path, ``train=False`` forward-only
        (prefill/serving).

        With no explicit ``candidates``, the sweep is STAGED to stay at
        tens of compiles per shape: the forward (q, kv) pair is picked
        first with forward-only timing (backward knobs can't affect it),
        then the backward axes (bwd pair x skip x policy) sweep fwd+bwd on
        the winning pair. ``train=False`` stops after the first stage."""
        import jax
        import jax.numpy as jnp

        dtype = dtype or jnp.bfloat16
        lk = seq_k or seq
        sig = signature(seq, lk, head_dim, heads, batch, causal, jnp.dtype(dtype))
        rng = np.random.default_rng(0)
        q = jnp.asarray(rng.standard_normal((batch, seq, heads, head_dim)), dtype)
        k = jnp.asarray(rng.standard_normal((batch, lk, heads, head_dim)), dtype)
        v = jnp.asarray(rng.standard_normal((batch, lk, heads, head_dim)), dtype)

        self.records = []
        if self.candidates is not None:
            log_dist(f"attention autotune: {sig} — {len(self.candidates)} "
                     f"explicit candidates on {jax.default_backend()}")
            best_geom, best_s = self._sweep(self.candidates, q, k, v, causal, train)
        else:
            fwd_pairs, bwd_pairs, skips = candidate_axes(
                seq, lk, head_dim, causal, itemsize=jnp.dtype(dtype).itemsize)
            fwd_cands = [AttentionGeometry(block_q=fq, block_k=fk)
                         for fq, fk in fwd_pairs]
            stage2 = 0 if not train else len(bwd_pairs) * len(skips) * 2
            log_dist(f"attention autotune: {sig} — staged sweep "
                     f"({len(fwd_cands)} fwd + {stage2} bwd candidates) "
                     f"on {jax.default_backend()}")
            best_geom, best_s = self._sweep(fwd_cands, q, k, v, causal,
                                            train=False, stage="fwd")
            if train:
                fq, fk = ((best_geom.block_q, best_geom.block_k)
                          if best_geom is not None else (None, None))
                cands = [AttentionGeometry(block_q=fq, block_k=fk,
                                           block_q_bwd=bq, block_k_bwd=bk,
                                           bwd_skip=skip, policy=policy)
                         for bq, bk in bwd_pairs
                         for skip in skips
                         for policy in ("lse", "recompute")]
                best_geom, best_s = self._sweep(cands, q, k, v, causal,
                                                train=True, stage="train")

        self._write_exps(sig, batch=batch, heads=heads, seq=seq, seq_k=lk,
                         head_dim=head_dim, causal=causal, train=train,
                         dtype=jnp.dtype(dtype).name,
                         backend=jax.default_backend())
        if best_geom is not None:
            path = store_winner(
                sig, best_geom,
                path=os.path.join(self.results_dir, CACHE_BASENAME),
                seconds=best_s, backend=jax.default_backend(),
                candidates=len(self.records), train=bool(train),
                timestamp=time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime()))
            log_dist(f"attention autotune: {sig} -> {best_geom.spec()} "
                     f"({best_s * 1e3:.2f} ms, winners cache {path})")
        return best_geom, self.records

    # ------------------------------------------------------------------
    def _write_exps(self, sig: str, **meta: Any) -> str:
        os.makedirs(self.exps_dir, exist_ok=True)
        path = os.path.join(self.exps_dir, f"attn_{sig}.json")
        with open(path, "w") as f:
            json.dump({"signature": sig, **meta, "records": self.records},
                      f, indent=2)
        return path


def tune_attention_blocks(*, seq: int, head_dim: int, heads: int = 1,
                          batch: int = 1, causal: bool = True, dtype=None,
                          train: bool = True,
                          results_dir: str = "autotuning_results",
                          exps_dir: str = "autotuning_exps",
                          **tuner_kwargs) -> Optional[AttentionGeometry]:
    """One-call convenience wrapper: sweep, persist, return the winner."""
    tuner = AttentionBlockTuner(results_dir=results_dir, exps_dir=exps_dir,
                                **tuner_kwargs)
    best, _ = tuner.tune(seq=seq, head_dim=head_dim, heads=heads, batch=batch,
                         causal=causal, dtype=dtype, train=train)
    return best
