"""TPU-native autotuner (reference ``autotuning/autotuner.py``).

The reference tunes by launching one training *process* per candidate config
and scraping metrics from logs (``Autotuner.tune`` autotuner.py:404,
``run_ds_config`` :1052, resource manager ``scheduler.py``). On TPU the
compiler is the experiment harness: every candidate is AOT-compiled in
process (``DeepSpeedEngine.lower_train_step``) and XLA reports exactly how
much HBM the step needs (``memory_analysis()``) and how many flops/bytes it
moves (``cost_analysis()``). OOM candidates are pruned without ever
allocating a buffer; only the top-k survivors get real timed steps.

Search space (reference ``DEFAULT_TUNING_SPACE_ZERO_*`` constants.py:150):
ZeRO stage x micro-batch-size ladder. The micro-batch ladder per stage
doubles until compilation reports the step no longer fits
(reference ``get_min_max_micro_batch_size`` autotuner.py:849).
"""

import json
import os
import time
from dataclasses import dataclass
from typing import Any, Callable, Dict, List, Optional

import numpy as np

from deepspeed_tpu.autotuning.config import (AUTOTUNING, AUTOTUNING_METRIC_FLOPS,
                                             AUTOTUNING_METRIC_LATENCY,
                                             DeepSpeedAutotuningConfig,
                                             get_autotuning_config)
from deepspeed_tpu.utils.logging import log_dist, logger

# per-chip peaks for the roofline cost model, bf16 matmul TFLOP/s and HBM GB/s
_PEAKS = {
    "TPU v5 lite": (197e12, 819e9),
    "TPU v5": (459e12, 1228e9),
    "TPU v4": (275e12, 1228e9),
    "TPU v3": (123e12, 900e9),
    "cpu": (1e12, 100e9),  # only relative ranking matters on the test backend
}


def _device_peaks():
    import jax
    kind = getattr(jax.devices()[0], "device_kind", "cpu") or "cpu"
    for prefix, peaks in _PEAKS.items():
        if kind.startswith(prefix):
            return peaks
    return _PEAKS["cpu"]


def _device_mem_budget() -> int:
    import jax
    stats = getattr(jax.devices()[0], "memory_stats", lambda: None)()
    if stats and stats.get("bytes_limit"):
        return int(stats["bytes_limit"])
    return 16 * 2**30  # assume one v5e-class chip when the backend won't say


@dataclass
class Experiment:
    """One tuning candidate (reference exp dicts, ``autotuner.py:304``)."""
    name: str
    zero_stage: int
    micro_batch_size: int
    config: Dict[str, Any]
    tensor: int = 1
    sequence: int = 1
    offload: str = "none"          # none | optimizer | infinity
    status: str = "pending"        # pruned | compiled | measured | failed
    mem_bytes: Optional[int] = None
    arg_bytes: Optional[int] = None  # device-resident inputs (state) alone
    flops: Optional[float] = None
    bytes_accessed: Optional[float] = None
    est_step_s: Optional[float] = None
    measured_step_s: Optional[float] = None
    metric_val: Optional[float] = None
    error: str = ""

    def record(self) -> Dict[str, Any]:
        return {k: getattr(self, k) for k in
                ("name", "zero_stage", "micro_batch_size", "tensor", "sequence", "offload",
                 "status", "mem_bytes", "arg_bytes", "flops",
                 "bytes_accessed", "est_step_s", "measured_step_s", "metric_val", "error")} | {
                    "ds_config": self.config}


class Autotuner:
    """Discover the fastest runnable (ZeRO stage, micro batch size) for a
    model on the current mesh (reference ``Autotuner`` autotuner.py:42).

    ``model_factory(overrides: dict) -> module`` lets candidates rebuild the
    model (e.g. to flip ``remat``); plain ``model=`` tunes engine knobs only.
    """

    def __init__(self, model=None, config: Optional[Dict[str, Any]] = None,
                 example_batch=None, topology=None,
                 model_factory: Optional[Callable[[Dict[str, Any]], Any]] = None):
        assert (model is None) != (model_factory is None), \
            "pass exactly one of model= or model_factory="
        assert config is not None and example_batch is not None
        self.user_config = dict(config)
        self.autotuning_config: DeepSpeedAutotuningConfig = get_autotuning_config(self.user_config)
        self.model_factory = model_factory or (lambda overrides: model)
        self.example_batch = example_batch
        self.topology = topology
        self.records: List[Experiment] = []
        self.best: Optional[Experiment] = None
        self.model_info: Dict[str, Any] = {}
        self.start_time: Optional[float] = None

    # ------------------------------------------------------------------
    def metric(self) -> str:
        return self.autotuning_config.metric

    def fast_enabled(self) -> bool:
        return self.autotuning_config.fast

    def mp_size(self) -> int:
        return self.autotuning_config.mp_size

    def max_train_micro_batch_size_per_gpu(self) -> int:
        return self.autotuning_config.max_train_micro_batch_size_per_gpu

    def min_train_micro_batch_size_per_gpu(self) -> int:
        return self.autotuning_config.min_train_micro_batch_size_per_gpu

    def get_model_num_params(self):
        return self.model_info.get("num_params")

    # ------------------------------------------------------------------
    def model_info_profile_run(self) -> Dict[str, Any]:
        """Parameter count/bytes via ``jax.eval_shape`` — no process launch,
        no allocation (reference launches a whole profile experiment,
        ``model_info_profile_run`` autotuner.py:663)."""
        import jax

        engine = self._build_engine({})
        abstract = engine.abstract_state(self.example_batch)
        leaves = jax.tree.leaves(abstract.params)
        num_params = sum(int(np.prod(l.shape)) for l in leaves)
        param_bytes = sum(int(np.prod(l.shape)) * l.dtype.itemsize for l in leaves)
        self.model_info = {"num_params": num_params, "param_bytes": param_bytes}
        log_dist(f"autotuning: model has {num_params / 1e6:.1f}M parameters")
        return self.model_info

    # ------------------------------------------------------------------
    def _dp_world(self, tensor: int = 1, sequence: int = 1) -> int:
        if (tensor, sequence) == (1, 1):
            if self.topology is not None:
                return (self.topology.mesh.shape["data"] * self.topology.mesh.shape["fsdp"]
                        * self.topology.mesh.shape["expert"])
            import jax
            return max(len(jax.devices()) // self.mp_size(), 1)
        # tuned mesh: candidates resolve their topology from the config's
        # mesh block (see _candidate_topology) — dp is what that resolution
        # yields: everything not on the tensor/sequence axes, minus a
        # user-pinned pipe axis (preserved by the _candidate_config merge).
        # expert stays OUT of the divisor: the expert axis carries batch
        # (dp_world includes it everywhere else — topology.data_parallel_size)
        import jax
        um = self.user_config.get("mesh") or {}
        fixed = tensor * sequence * int(um.get("pipe", 1))
        return max(len(jax.devices()) // max(fixed, 1), 1)

    def _candidate_topology(self, tensor: int, sequence: int):
        """Mesh for a candidate. When the axes are NOT being tuned, the
        user's topology passes through. When they are, return None so the
        ENGINE resolves the mesh from the candidate config's mesh block —
        the same resolve_topology_axes path production takes with the
        emitted ds_config_optimal.json, including the stage-aware fsdp
        carve (a hand-built MeshTopology(tensor=t) would leave fsdp=1 and
        benchmark a mesh the shipped config never produces)."""
        if (tensor, sequence) == (1, 1):
            return self.topology
        return None

    def _build_engine(self, overrides: Dict[str, Any], micro_batch_size: int = 1,
                      tensor: int = 1, sequence: int = 1, offload: str = "none"):
        """Build the engine for a candidate from the SAME config dict that
        gets recorded/emitted (``_candidate_config``) — one construction
        path, so the benchmarked engine and the optimal-config artifact can
        never drift."""
        import deepspeed_tpu

        stage = overrides.get("zero_stage",
                              (self.user_config.get("zero_optimization") or {}).get("stage", 0))
        cfg = self._candidate_config(stage, micro_batch_size, tensor, sequence, offload)
        model = self.model_factory(overrides)
        engine, _, _, _ = deepspeed_tpu.initialize(
            model=model, config=cfg, topology=self._candidate_topology(tensor, sequence))
        # candidate engines must never re-enter autotuning themselves
        # (DS_AUTOTUNING is still set in the environment)
        engine._autotune = None
        return engine

    @staticmethod
    def _apply_offload(zero: Dict[str, Any], offload: str) -> None:
        if offload == "optimizer":
            zero["offload_optimizer"] = {"device": "cpu"}
        elif offload == "infinity":
            # the full ZeRO-Infinity recipe (stage 3 enforced by candidate
            # generation): params rest pinned-host + host C++ Adam
            zero["offload_param"] = {"device": "cpu"}
            zero["offload_optimizer"] = {"device": "cpu"}

    def _scaled_batch(self, global_batch: int):
        """Tile the user's example batch out to ``global_batch`` samples."""
        def tile(x):
            x = np.asarray(x)
            reps = (global_batch + x.shape[0] - 1) // x.shape[0]
            return np.concatenate([x] * reps, axis=0)[:global_batch]
        import jax
        return jax.tree.map(tile, self.example_batch)

    # ------------------------------------------------------------------
    def _compile_candidate(self, exp: Experiment, mem_budget: int) -> bool:
        """AOT-compile one candidate; fill mem/cost stats; prune on OOM.
        Returns True if the candidate fits."""
        peak_flops, peak_bw = _device_peaks()
        try:
            engine = self._build_engine({"zero_stage": exp.zero_stage}, exp.micro_batch_size,
                                        exp.tensor, exp.sequence, exp.offload)
            batch = self._scaled_batch(engine.config.train_batch_size)
            compiled = engine.lower_train_step(batch).compile()
        except Exception as e:  # shape/mesh/unsupported combos prune cleanly
            exp.status, exp.error = "failed", f"{type(e).__name__}: {e}"
            logger.warning(f"autotuning: {exp.name} failed to compile: {exp.error[:200]}")
            return False
        ma = compiled.memory_analysis()
        if ma is not None:
            exp.mem_bytes = int(ma.argument_size_in_bytes + ma.output_size_in_bytes
                                - ma.alias_size_in_bytes + ma.temp_size_in_bytes)
            exp.arg_bytes = int(ma.argument_size_in_bytes)
        ca = compiled.cost_analysis()
        if isinstance(ca, list):  # jax 0.4.x: one dict per device program
            ca = ca[0] if ca else None
        if ca:
            exp.flops = float(ca.get("flops", 0.0))
            exp.bytes_accessed = float(ca.get("bytes accessed", 0.0))
            exp.est_step_s = max(exp.flops / peak_flops, exp.bytes_accessed / peak_bw)
        if exp.mem_bytes is not None and exp.mem_bytes > mem_budget:
            exp.status = "pruned"
            log_dist(f"autotuning: {exp.name} pruned "
                     f"({exp.mem_bytes / 2**30:.2f} GiB > {mem_budget / 2**30:.2f} GiB budget)")
            return False
        exp.status = "compiled"
        return True

    def _measure_candidate(self, exp: Experiment) -> None:
        """Run real timed steps for a compile-survivor (reference
        ``run_tuning_micro_batch_sizes`` autotuner.py:740).

        Timing goes through ``engine.train_batches`` (one ``lax.scan`` of
        ``steps`` optimizer steps per dispatch): per-dispatch loops report
        FAKE times on the tunnel (its dedupe cache replays identical
        dispatches — PERF.md r3 session 2/3), and the fused dispatch is the
        production loop shape anyway. Host-driven schedules (offload,
        1-bit) fall back to per-step inside train_batches itself."""
        import jax
        at = self.autotuning_config
        steps = max(at.end_profile_step - at.start_profile_step, 1)
        try:
            engine = self._build_engine({"zero_stage": exp.zero_stage}, exp.micro_batch_size,
                                        exp.tensor, exp.sequence, exp.offload)
            batch = self._scaled_batch(engine.config.train_batch_size)
            engine.initialize_state(batch)
            stack = jax.tree.map(
                lambda x: np.broadcast_to(np.asarray(x), (steps,) + np.shape(x)), batch)
            engine.train_batches(stack)  # warmup + compile
            jax.block_until_ready(engine.state.params)
            t0 = time.perf_counter()
            engine.train_batches(stack)
            jax.block_until_ready(engine.state.params)
            exp.measured_step_s = (time.perf_counter() - t0) / steps
            exp.status = "measured"
        except Exception as e:
            exp.status, exp.error = "failed", f"{type(e).__name__}: {e}"
            # a config that crashed at runtime must never be selected on the
            # strength of its compile-time estimate
            exp.metric_val = None
            logger.warning(f"autotuning: {exp.name} failed to run: {exp.error[:200]}")

    def _metric_val(self, exp: Experiment) -> Optional[float]:
        """Higher is better for every metric (latency is negated)."""
        step_s = exp.measured_step_s if exp.measured_step_s is not None else exp.est_step_s
        if step_s is None or step_s <= 0:
            return None
        if self.metric() == AUTOTUNING_METRIC_LATENCY:
            return -step_s
        if self.metric() == AUTOTUNING_METRIC_FLOPS:
            return (exp.flops or 0.0) / step_s
        # throughput: samples/sec across the job
        return exp.config.get("train_batch_size", exp.micro_batch_size) / step_s

    # ------------------------------------------------------------------
    def _stages_to_tune(self) -> List[int]:
        zs = self.autotuning_config.zero_stages
        user_stage = (self.user_config.get("zero_optimization") or {}).get("stage", None)
        if isinstance(zs, list):
            return sorted(set(int(s) for s in zs))
        if zs == "all":
            if isinstance(user_stage, int):
                return [user_stage]  # reference honors an explicit user stage
            return [0, 1, 2, 3]
        return [int(zs)]

    def _mbs_ladder(self, tensor: int = 1, sequence: int = 1) -> List[int]:
        lo = max(self.min_train_micro_batch_size_per_gpu(), 1)
        hi = self.max_train_micro_batch_size_per_gpu()
        if self.autotuning_config.max_train_batch_size:
            gas = int(self.user_config.get("gradient_accumulation_steps", 1))
            # cap against the CANDIDATE's dp world: a tp=4 mesh has fewer dp
            # replicas, so its per-replica micro-batch may legally be larger
            hi = min(hi, self.autotuning_config.max_train_batch_size
                     // (gas * self._dp_world(tensor, sequence)))
        ladder, v = [], lo
        while v <= hi:
            ladder.append(v)
            v *= 2
        return ladder

    def tune(self) -> Optional[Experiment]:
        """Main loop (reference ``Autotuner.tune`` autotuner.py:404): per
        ZeRO stage, walk the micro-batch ladder; compile-prune; rank by the
        roofline estimate; measure the global top-k; pick the best."""
        self.start_time = time.time()
        self.model_info_profile_run()
        at = self.autotuning_config
        mem_budget = at.mem_budget_bytes or _device_mem_budget()
        log_dist(f"autotuning: memory budget {mem_budget / 2**30:.2f} GiB, "
                 f"metric={self.metric()}, stages={self._stages_to_tune()}")

        import jax
        n_dev = len(jax.devices())
        meshes = []
        for t in sorted(set(int(x) for x in at.tp_sizes)):
            for sq in sorted(set(int(x) for x in at.sp_sizes)):
                if t * sq <= n_dev and n_dev % (t * sq) == 0:
                    meshes.append((t, sq))
                else:
                    logger.warning(f"autotuning: mesh tensor={t} x sequence={sq} does not "
                                   f"divide {n_dev} devices; skipped")
        if not meshes:
            raise ValueError(f"autotuning: no (tp, sp) pair from tp_sizes="
                             f"{at.tp_sizes} x sp_sizes={at.sp_sizes} divides "
                             f"{n_dev} devices — include 1 in the lists for a baseline")
        for stage in self._stages_to_tune():
            offloads = ["none"]
            if at.tune_offload:
                offloads.append("optimizer")
                if stage == 3:
                    offloads.append("infinity")
            for t, sq in meshes:
                for off in offloads:
                    suffix = (f"_tp{t}" if t > 1 else "") + (f"_sp{sq}" if sq > 1 else "") \
                        + (f"_{off}" if off != "none" else "")
                    for mbs in self._mbs_ladder(t, sq):
                        exp = Experiment(name=f"z{stage}_mbs{mbs}{suffix}",
                                         zero_stage=stage, micro_batch_size=mbs,
                                         tensor=t, sequence=sq, offload=off,
                                         config=self._candidate_config(stage, mbs, t, sq, off))
                        self.records.append(exp)
                        if not self._compile_candidate(exp, mem_budget):
                            # doubling mbs only grows memory: end this ladder
                            # on the first pruned (or failed) candidate —
                            # reference get_min_max_micro_batch_size stops
                            # the same way
                            break

        survivors = [e for e in self.records if e.status == "compiled"]
        for exp in survivors:
            exp.metric_val = self._metric_val(exp)

        if at.measure and survivors:
            top = sorted(survivors, key=lambda e: e.metric_val or 0.0, reverse=True)[:at.top_k]
            # offload estimates come from the grads-only device program and
            # omit host-update time — optimistic. Guarantee the best DENSE
            # survivor is also measured so offload crowding the top_k can
            # never shadow a faster dense config.
            if any(e.offload != "none" for e in top):
                dense = [e for e in survivors if e.offload == "none" and e not in top
                         and e.metric_val is not None]
                if dense:
                    top.append(max(dense, key=lambda e: e.metric_val))
            for exp in top:
                self._measure_candidate(exp)
                if exp.status == "measured":
                    exp.metric_val = self._metric_val(exp)

        # measured times beat roofline estimates — never compare across the
        # two (the estimate is an optimistic lower bound on step time)
        ranked = [e for e in self.records if e.metric_val is not None]
        measured = [e for e in ranked if e.status == "measured"]
        self.best = max(measured or ranked, key=lambda e: e.metric_val, default=None)
        self.write_tuning_results()
        if self.best is not None:
            log_dist(f"autotuning: best = {self.best.name} "
                     f"({self.metric()}={self.best.metric_val:.2f}, "
                     f"{len(self.records)} experiments, {time.time() - self.start_time:.0f}s)")
        return self.best

    def _candidate_config(self, stage: int, mbs: int, tensor: int = 1,
                          sequence: int = 1, offload: str = "none") -> Dict[str, Any]:
        cfg = json.loads(json.dumps({k: v for k, v in self.user_config.items() if k != AUTOTUNING}))
        zero = cfg.setdefault("zero_optimization", {})
        zero["stage"] = stage
        self._apply_offload(zero, offload)
        gas = int(cfg.get("gradient_accumulation_steps", 1))
        cfg["train_batch_size"] = mbs * gas * self._dp_world(tensor, sequence)
        cfg["train_micro_batch_size_per_gpu"] = mbs
        if tensor > 1 or sequence > 1:
            # merge over any user mesh block: tuned axes override, the rest
            # (pipe/expert/data) keep the user's intent
            mesh = dict(cfg.get("mesh") or {})
            mesh.update(tensor=tensor, sequence=sequence)
            cfg["mesh"] = mesh
        return cfg

    # ------------------------------------------------------------------
    def write_tuning_results(self) -> None:
        """Persist per-experiment records + the winning config (reference
        ``write_optimal_config`` autotuner.py:1072)."""
        at = self.autotuning_config
        os.makedirs(at.exps_dir, exist_ok=True)
        os.makedirs(at.results_dir, exist_ok=True)
        for exp in self.records:
            with open(os.path.join(at.exps_dir, f"{exp.name}.json"), "w") as f:
                json.dump(exp.record(), f, indent=2)
        if self.best is not None:
            with open(os.path.join(at.results_dir, "ds_config_optimal.json"), "w") as f:
                json.dump(self.best.config, f, indent=2)
            with open(os.path.join(at.results_dir, "summary.json"), "w") as f:
                json.dump({"best": self.best.name, "metric": self.metric(),
                           "metric_val": self.best.metric_val,
                           "num_experiments": len(self.records),
                           "model_info": self.model_info}, f, indent=2)

    def print_tuning_results(self) -> None:
        """Tabulated result dump (reference ``print_tuning_results``
        autotuner.py:108)."""
        cols = ("name", "status", "mem_bytes", "est_step_s", "measured_step_s", "metric_val")
        rows = [[str(getattr(e, c)) for c in cols] for e in self.records]
        widths = [max(len(c), *(len(r[i]) for r in rows)) if rows else len(c)
                  for i, c in enumerate(cols)]
        line = "  ".join(c.ljust(w) for c, w in zip(cols, widths))
        print(line)
        print("-" * len(line))
        for r in rows:
            print("  ".join(v.ljust(w) for v, w in zip(r, widths)))
        if self.best is not None:
            print(f"optimal: {self.best.name} -> {os.path.join(self.autotuning_config.results_dir, 'ds_config_optimal.json')}")
