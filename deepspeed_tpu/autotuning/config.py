"""Autotuning configuration (reference ``autotuning/config.py`` +
``autotuning/constants.py``).

Field names mirror the reference's ``"autotuning"`` config block so user
configs port unchanged; TPU-only knobs (``mem_budget_bytes``, ``measure``,
``remat``) are additive.
"""

from typing import Any, Dict, List, Optional, Union

from pydantic import BaseModel, ConfigDict

AUTOTUNING = "autotuning"

AUTOTUNING_METRIC_LATENCY = "latency"
AUTOTUNING_METRIC_THROUGHPUT = "throughput"
AUTOTUNING_METRIC_FLOPS = "flops"

AUTOTUNING_TUNER_GRIDSEARCH = "gridsearch"
AUTOTUNING_TUNER_RANDOM = "random"
AUTOTUNING_TUNER_MODELBASED = "model_based"


class DeepSpeedAutotuningConfig(BaseModel):
    """Typed ``"autotuning"`` block (reference ``DeepSpeedAutotuningConfig``,
    ``autotuning/config.py:11``)."""

    enabled: bool = False
    fast: bool = True
    results_dir: str = "autotuning_results"
    exps_dir: str = "autotuning_exps"
    overwrite: bool = True
    start_profile_step: int = 3
    end_profile_step: int = 5
    metric: str = AUTOTUNING_METRIC_THROUGHPUT
    tuner_type: str = AUTOTUNING_TUNER_GRIDSEARCH
    tuner_early_stopping: int = 5
    tuner_num_trials: int = 50
    mp_size: int = 1
    max_train_batch_size: Optional[int] = None
    min_train_batch_size: int = 1
    max_train_micro_batch_size_per_gpu: int = 1024
    min_train_micro_batch_size_per_gpu: int = 1
    num_tuning_micro_batch_sizes: int = 3
    # reference-only knobs, accepted so ported configs don't fail validation
    # (process-launch experiment plumbing has no TPU analog)
    arg_mappings: Optional[Dict[str, Any]] = None
    metric_path: Optional[str] = None
    model_info: Optional[Dict[str, Any]] = None
    model_info_path: Optional[str] = None
    # which ZeRO stages to explore; "all" or explicit list. The reference
    # derives this from the user config's zero stage (autotuner.py:432).
    zero_stages: Union[str, List[int]] = "all"

    # ---- TPU-native knobs (no reference analog) ----
    # Device memory budget for pruning compiled candidates; default = the
    # device's bytes_limit. Tests set a small budget to exercise pruning.
    mem_budget_bytes: Optional[int] = None
    # measure=False ranks purely on the XLA roofline cost model (compile
    # only — no buffers are allocated, usable without idle hardware time)
    measure: bool = True
    # how many compile-survivors get real timed steps
    top_k: int = 3
    # mesh-axis search space: tensor/sequence sizes to explore per (stage,
    # mbs) point. The reference fixes mp_size as an input (autotuner mp_size
    # knob); here the mesh IS a tunable — candidates whose axes don't divide
    # the device count or the model's heads prune at compile. [1] = off.
    tp_sizes: List[int] = [1]
    sp_sizes: List[int] = [1]
    # explore ZeRO-Offload / ZeRO-Infinity variants: adds offload_optimizer
    # (any stage) and offload_param+offload_optimizer (stage 3) candidates —
    # the configs that trade HBM for host traffic when nothing dense fits
    tune_offload: bool = False

    model_config = ConfigDict(extra="ignore")


def get_autotuning_config(param_dict: Dict[str, Any]) -> DeepSpeedAutotuningConfig:
    return DeepSpeedAutotuningConfig(**(param_dict.get(AUTOTUNING) or {}))
