"""Checkpoint tools (reference ``deepspeed/checkpoint`` + ``utils/zero_to_fp32.py``):
offline fp32/bf16 consolidation and the universal (HP-fragment) format."""

from deepspeed_tpu.checkpoint.reshape_meg_2d import (get_mpu_ranks,
                                                     meg_2d_parallel_map,
                                                     reshape_meg_2d_parallel)
from deepspeed_tpu.checkpoint.universal_checkpoint import (ds_to_universal,
                                                           load_universal_fragments,
                                                           load_universal_into_state,
                                                           universal_metadata)
from deepspeed_tpu.checkpoint.zero_to_fp32 import (convert_zero_checkpoint_to_fp32_state_dict,
                                                   get_fp32_state_dict_from_zero_checkpoint,
                                                   load_state_dict_from_npz)

__all__ = ["convert_zero_checkpoint_to_fp32_state_dict",
           "get_fp32_state_dict_from_zero_checkpoint", "load_state_dict_from_npz",
           "ds_to_universal", "load_universal_fragments", "load_universal_into_state",
           "universal_metadata", "reshape_meg_2d_parallel", "meg_2d_parallel_map",
           "get_mpu_ranks"]
