"""Offline 2-D (pipeline x tensor) checkpoint regrouping maps
(reference ``checkpoint/reshape_meg_2d.py:80`` ``reshape_meg_2d_parallel``
and ``checkpoint/deepspeed_checkpoint.py:33``'s 2-D file maps).

Pure index bookkeeping: given checkpoints written by a pp_old x tp_old
job, decide which OLD shard files each NEW (pp, tp) rank must read. Both
degrees may only change by integer factors (merge k:1 or split 1:k) — the
same contract the reference enforces. The actual tensor surgery is done by
``runtime/state_dict_factory.MegatronSDLoader`` (TP merge/split with
Megatron key conventions); ``bin/ds_reshape_ckpt`` wires the two into the
offline CLI.

On TPU this tool matters for IMPORTING Megatron-partitioned checkpoints at
a mesh shape other than the one that wrote them; checkpoints written by
this framework itself are orbax and reshape on load (cross-topology
restore), no offline pass needed.
"""

from typing import Any, Dict, List, Optional, Tuple

from deepspeed_tpu.utils.logging import logger


class meg_2d_parallel_map:
    """(pp_index, tp_index) -> list of data items (reference
    ``meg_2d_parallel_map``, ``reshape_meg_2d.py:9``)."""

    def __init__(self, pp_degree: int, tp_degree: int):
        self.pp_degree = int(pp_degree)
        self.tp_degree = int(tp_degree)
        self.map: Dict[Tuple[int, int], List[Any]] = {}

    def simple_init(self):
        """Identity layout: cell (p, t) holds the single global rank index
        ``p * tp + t`` — the layout a fresh pp x tp job writes."""
        for p in range(self.pp_degree):
            for t in range(self.tp_degree):
                self.map[(p, t)] = [p * self.tp_degree + t]
        return self

    def add_data(self, pp_index: int, tp_index: int, data: List[Any]):
        self._validate(pp_index, tp_index)
        self.map.setdefault((pp_index, tp_index), []).extend(list(data))

    def get_data(self, pp_index: Optional[int] = None,
                 tp_index: Optional[int] = None) -> List[Any]:
        pps = range(self.pp_degree) if pp_index is None else [pp_index]
        tps = range(self.tp_degree) if tp_index is None else [tp_index]
        out: List[Any] = []
        for p in pps:
            for t in tps:
                self._validate(p, t)
                out.extend(self.map.get((p, t), []))
        return out

    def print_data(self, tag: str):
        for (p, t), data in sorted(self.map.items()):
            logger.info(f"{tag} [pp={p} tp={t}] -> {data}")

    def _validate(self, pp_index: int, tp_index: int):
        if not (0 <= pp_index < self.pp_degree and 0 <= tp_index < self.tp_degree):
            raise ValueError(f"index (pp={pp_index}, tp={tp_index}) outside "
                             f"{self.pp_degree} x {self.tp_degree} map")


def _factor(old: int, new: int, axis: str) -> None:
    if old % new != 0 and new % old != 0:
        raise ValueError(f"{axis} degree may only change by an integer factor "
                         f"(got {old} -> {new})")


def _reshape_tp_dimension(old_map: meg_2d_parallel_map, new_tp: int) -> meg_2d_parallel_map:
    """Regroup along tp only: merging (old_tp > new_tp) gives each new tp
    cell the ``old_tp/new_tp`` consecutive old cells whose shards
    concatenate into it; splitting (new_tp > old_tp) points the
    ``new_tp/old_tp`` new cells at their one source cell (the tensor split
    itself happens in the SD loader)."""
    old_tp = old_map.tp_degree
    _factor(old_tp, new_tp, "tp")
    new_map = meg_2d_parallel_map(old_map.pp_degree, new_tp)
    for p in range(old_map.pp_degree):
        if new_tp <= old_tp:
            ratio = old_tp // new_tp
            for t_new in range(new_tp):
                for t_old in range(t_new * ratio, (t_new + 1) * ratio):
                    new_map.add_data(p, t_new, old_map.get_data(p, t_old))
        else:
            ratio = new_tp // old_tp
            for t_new in range(new_tp):
                new_map.add_data(p, t_new, old_map.get_data(p, t_new // ratio))
    return new_map


def _reshape_pp_dimension(old_map: meg_2d_parallel_map, new_pp: int) -> meg_2d_parallel_map:
    """Regroup along pp only (layer ownership moves between stages)."""
    old_pp = old_map.pp_degree
    _factor(old_pp, new_pp, "pp")
    new_map = meg_2d_parallel_map(new_pp, old_map.tp_degree)
    for t in range(old_map.tp_degree):
        if new_pp <= old_pp:
            ratio = old_pp // new_pp
            for p_new in range(new_pp):
                for p_old in range(p_new * ratio, (p_new + 1) * ratio):
                    new_map.add_data(p_new, t, old_map.get_data(p_old, t))
        else:
            ratio = new_pp // old_pp
            for p_new in range(new_pp):
                new_map.add_data(p_new, t, old_map.get_data(p_new // ratio, t))
    return new_map


def reshape_meg_2d_parallel(old_pp_degree: int, old_tp_degree: int,
                            new_pp_degree: int, new_tp_degree: int,
                            verbose: bool = False) -> meg_2d_parallel_map:
    """Full 2-D regroup (reference ``reshape_meg_2d.py:80``): each NEW
    (pp, tp) cell lists the OLD global rank indices whose shard files feed
    it, tp reshaped first, then pp."""
    old_map = meg_2d_parallel_map(old_pp_degree, old_tp_degree).simple_init()
    if verbose:
        old_map.print_data("before")
    new_map = _reshape_tp_dimension(old_map, new_tp_degree)
    new_map = _reshape_pp_dimension(new_map, new_pp_degree)
    if verbose:
        new_map.print_data("after")
    return new_map


def get_mpu_ranks(tp_size: int = 1, pp_size: int = 1, dp_size: int = 1):
    """Enumerate the rank groups of a tp x pp x dp decomposition (reference
    ``reshape_meg_2d.py:107``): returns (tp_groups, pp_groups, dp_groups)
    as lists of global-rank lists, Megatron order (tp fastest, then dp,
    then pp)."""
    world = tp_size * pp_size * dp_size
    tp_groups = [list(range(start, start + tp_size))
                 for start in range(0, world, tp_size)]
    dp_groups = []
    for p in range(pp_size):
        for t in range(tp_size):
            dp_groups.append([p * tp_size * dp_size + d * tp_size + t
                              for d in range(dp_size)])
    pp_groups = []
    for d in range(dp_size):
        for t in range(tp_size):
            pp_groups.append([p * tp_size * dp_size + d * tp_size + t
                              for p in range(pp_size)])
    return tp_groups, pp_groups, dp_groups
