"""Universal checkpoint: topology- and param-group-independent HP fragments
(reference ``deepspeed/checkpoint/universal_checkpoint.py:12``
``load_hp_checkpoint_state`` + ``ds_to_universal.py`` conversion tool).

The reference's universal format exists to reshape rank-flattened optimizer
partitions across topology changes. Orbax restore already reshapes across
topologies, so the TPU universal format targets what orbax can't do:
**optimizer-state surgery** — resuming when the *param tree itself* changed
(layers added/removed, adapters attached, param groups reorganised). Every
leaf (fp32 master, exp_avg, exp_avg_sq, counters) becomes one ``.npy``
fragment keyed by its tree path; loading matches fragments by path,
initialises missing leaves from the new model's abstract state, and warns
about both directions of drift.
"""

import json
import os
import re
from typing import Dict, Optional

import numpy as np

from deepspeed_tpu.checkpoint.zero_to_fp32 import _flatten, _restore_numpy
from deepspeed_tpu.utils.device import owned_device_put
from deepspeed_tpu.utils.logging import log_dist, logger

MANIFEST = "universal_manifest.json"


def _fragment_name(path: str) -> str:
    """Tree path → safe filename (reversible via the manifest)."""
    return re.sub(r"[^A-Za-z0-9_.-]", "__", path) + ".npy"


def ds_to_universal(checkpoint_dir: str, output_dir: str, tag: Optional[str] = None) -> str:
    """Explode an engine checkpoint into per-leaf HP fragments (reference
    ``checkpoint/ds_to_universal.py`` main flow: extract → slice-merge →
    save; the slice-merge leg is unnecessary here because leaves are whole
    logical arrays)."""
    state = _restore_numpy(checkpoint_dir, tag)
    meta = {}
    from deepspeed_tpu.checkpoint.zero_to_fp32 import _latest_tag
    real_tag = tag or _latest_tag(checkpoint_dir)
    meta_path = os.path.join(checkpoint_dir, real_tag, "metadata.json")
    if os.path.exists(meta_path):
        with open(meta_path) as f:
            meta = json.load(f)

    flat = _flatten(state)
    os.makedirs(output_dir, exist_ok=True)
    entries = {}
    for path, arr in flat.items():
        fname = _fragment_name(path)
        np.save(os.path.join(output_dir, fname), arr)
        entries[path] = {"file": fname, "shape": list(arr.shape), "dtype": str(arr.dtype)}
    with open(os.path.join(output_dir, MANIFEST), "w") as f:
        json.dump({"version": 1, "source_tag": real_tag, "metadata": meta,
                   "fragments": entries}, f, indent=2)
    log_dist(f"universal checkpoint: {len(entries)} fragments -> {output_dir}")
    return output_dir


def load_universal_fragments(universal_dir: str) -> Dict[str, np.ndarray]:
    with open(os.path.join(universal_dir, MANIFEST)) as f:
        manifest = json.load(f)
    out = {}
    for path, entry in manifest["fragments"].items():
        out[path] = np.load(os.path.join(universal_dir, entry["file"]))
    return out


def universal_metadata(universal_dir: str) -> Dict:
    with open(os.path.join(universal_dir, MANIFEST)) as f:
        return json.load(f)["metadata"]


def load_universal_into_state(universal_dir: str, abstract_state, shardings):
    """Rebuild a concrete TrainState-shaped pytree from fragments.

    Matching is by tree path (reference matches by param name + HP keys,
    ``universal_checkpoint.py:12``). A fragment whose path is absent from
    the new model is skipped with a warning; a new-model leaf with no
    fragment keeps ``fill`` zeros (fresh optimizer moments for new params —
    the param-group-surgery semantics the reference format exists for).
    """
    import jax

    fragments = load_universal_fragments(universal_dir)
    used = set()

    flat_abs, treedef = jax.tree_util.tree_flatten_with_path(abstract_state)
    flat_shard = jax.tree_util.tree_flatten_with_path(shardings)[0]

    from deepspeed_tpu.utils.tree import keypath_str as norm

    leaves = []
    for (path, leaf), (_, shard) in zip(flat_abs, flat_shard):
        key = norm(path)
        shape = tuple(leaf.shape)
        dtype = leaf.dtype
        if key in fragments and tuple(fragments[key].shape) == shape:
            value = fragments[key].astype(dtype)
            used.add(key)
        else:
            if key in fragments:
                logger.warning(f"universal load: shape mismatch for {key} "
                               f"({fragments[key].shape} vs {shape}); reinitializing")
                used.add(key)
            else:
                logger.warning(f"universal load: no fragment for {key}; initializing zeros")
            value = np.zeros(shape, dtype)
        # owned_device_put: these host-numpy fragments become engine state
        # that train_step donates (utils/device.py zero-copy hazard)
        leaves.append(owned_device_put(value, shard))

    unused = set(fragments) - used
    for key in sorted(unused):
        logger.warning(f"universal load: fragment {key} has no home in the new model; skipped")
    return jax.tree_util.tree_unflatten(treedef, leaves)
