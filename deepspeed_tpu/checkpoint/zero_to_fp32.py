"""Offline consolidation of a ZeRO checkpoint into plain fp32 (or bf16)
weights — no mesh, no engine, no devices (reference
``deepspeed/utils/zero_to_fp32.py``; ``save_16bit_model`` analog of
reference ``runtime/engine.py:3376``).

The reference stitches flattened rank-partitioned fragments back together
(``_get_fp32_state_dict_from_zero_checkpoint`` zero_to_fp32.py:190, with
per-rank ``parse_optim_states`` :141). Orbax already stores every array as
one logical tensorstore, so consolidation is a host-side read + dtype cast;
what this module adds is the *offline deployment format*: a single ``.npz``
of ``path/to/param`` → array that plain flax/numpy users can load with no
deepspeed_tpu (or jax) at all.
"""

import argparse
import json
import os
from typing import Dict, Optional

import numpy as np

WEIGHTS_NAME = "model_weights.npz"          # reference writes pytorch_model.bin


def _latest_tag(checkpoint_dir: str) -> str:
    latest = os.path.join(checkpoint_dir, "latest")
    if not os.path.exists(latest):
        raise FileNotFoundError(f"no 'latest' file in {checkpoint_dir}; pass tag= explicitly")
    with open(latest) as f:
        return f.read().strip()


def _restore_numpy(checkpoint_dir: str, tag: Optional[str] = None,
                   params_only: bool = False) -> Dict:
    """TrainState as host values — no abstract tree, no mesh.

    ``params_only`` skips reading the optimizer moments entirely
    (orbax PLACEHOLDER partial restore): serving-time loads touch ~1/3 of
    the checkpoint bytes and hold no Adam state in host RAM.
    """
    import orbax.checkpoint as ocp
    tag = tag or _latest_tag(checkpoint_dir)
    path = os.path.join(os.path.abspath(checkpoint_dir), str(tag), "state")
    if not os.path.exists(path):
        raise FileNotFoundError(f"checkpoint state not found at {path}")
    if not params_only:
        return ocp.StandardCheckpointer().restore(path)
    import jax
    raw_meta = ocp.StandardCheckpointer().metadata(path)
    # orbax >= 0.10 wraps the tree in .item_metadata; 0.7 returns the
    # tree-shaped dict directly
    meta = dict(getattr(raw_meta, "item_metadata", raw_meta))
    placeholder = getattr(ocp, "PLACEHOLDER", None)
    if placeholder is None:
        # old orbax has no partial-restore placeholder: restore everything
        # and keep only params (costs moment bytes transiently)
        out = ocp.StandardCheckpointer().restore(path)
        return {"params": jax.tree.map(np.asarray, dict(out)["params"])}
    item = {k: jax.tree.map(lambda m: placeholder, v) for k, v in meta.items()}
    item["params"] = jax.tree.map(lambda m: jax.ShapeDtypeStruct(m.shape, m.dtype),
                                  meta["params"])
    out = ocp.PyTreeCheckpointer().restore(path, ocp.args.PyTreeRestore(item=item))
    return {"params": jax.tree.map(np.asarray, out["params"])}


def _flatten(tree: Dict, prefix: str = "") -> Dict[str, np.ndarray]:
    out = {}
    for k, v in tree.items():
        key = f"{prefix}/{k}" if prefix else str(k)
        if isinstance(v, dict):
            out.update(_flatten(v, key))
        else:
            out[key] = np.asarray(v)
    return out


def _unflatten(flat: Dict[str, np.ndarray]) -> Dict:
    tree: Dict = {}
    for key, v in flat.items():
        parts = key.split("/")
        node = tree
        for p in parts[:-1]:
            node = node.setdefault(p, {})
        node[parts[-1]] = v
    return tree


def get_fp32_state_dict_from_zero_checkpoint(checkpoint_dir: str,
                                             tag: Optional[str] = None) -> Dict:
    """Nested dict of fp32 numpy params (reference
    ``get_fp32_state_dict_from_zero_checkpoint`` zero_to_fp32.py:500-ish
    public entry)."""
    state = _restore_numpy(checkpoint_dir, tag, params_only=True)
    params = state["params"]
    return _unflatten({
        p: a.astype(np.float32) if np.issubdtype(a.dtype, np.floating) else a
        for p, a in _flatten(params).items()})


def convert_zero_checkpoint_to_fp32_state_dict(checkpoint_dir: str,
                                               output_dir: str,
                                               tag: Optional[str] = None,
                                               save_dtype: str = "float32") -> str:
    """Write the consolidated weights npz + manifest; returns the npz path
    (reference ``convert_zero_checkpoint_to_fp32_state_dict``). Pass
    ``save_dtype='bfloat16'`` for the ``save_16bit_model`` deployment
    format."""
    import ml_dtypes
    state = _restore_numpy(checkpoint_dir, tag, params_only=True)
    flat = _flatten(state["params"])
    dt = ml_dtypes.bfloat16 if save_dtype in ("bfloat16", "bf16") else np.dtype(save_dtype)
    cast = {k: (v.astype(dt) if np.issubdtype(v.dtype, np.floating) else v)
            for k, v in flat.items()}
    os.makedirs(output_dir, exist_ok=True)
    out_path = os.path.join(output_dir, WEIGHTS_NAME)
    save_npz(out_path, cast)
    with open(os.path.join(output_dir, "manifest.json"), "w") as f:
        json.dump({"dtype": str(save_dtype),
                   "num_params": int(sum(int(np.prod(v.shape)) for v in cast.values())),
                   "keys": sorted(cast.keys())}, f, indent=2)
    return out_path


def save_npz(out_path: str, flat: Dict[str, np.ndarray]) -> None:
    """npz writer that survives bfloat16: numpy's npz can't represent it, so
    bf16 leaves are stored as uint16 views with a dtype map under a reserved
    key, reversed transparently by ``load_state_dict_from_npz``."""
    import ml_dtypes
    flat = {k: np.asarray(v) for k, v in flat.items()}
    dtypes = {k: str(v.dtype) for k, v in flat.items()}
    storable = {k: (v.view(np.uint16) if v.dtype == ml_dtypes.bfloat16 else v)
                for k, v in flat.items()}
    np.savez(out_path, __dtypes__=np.frombuffer(json.dumps(dtypes).encode(), np.uint8),
             **storable)


def load_state_dict_from_npz(path: str) -> Dict:
    """Deployment-side loader: npz → nested param dict (plain numpy)."""
    import ml_dtypes
    if os.path.isdir(path):
        path = os.path.join(path, WEIGHTS_NAME)
    with np.load(path) as z:
        dtypes = {}
        if "__dtypes__" in z.files:
            dtypes = json.loads(bytes(z["__dtypes__"]).decode())
        flat = {}
        for k in z.files:
            if k == "__dtypes__":
                continue
            v = z[k]
            if dtypes.get(k) == "bfloat16":
                v = v.view(ml_dtypes.bfloat16)
            flat[k] = v
        return _unflatten(flat)


def main(argv=None):
    p = argparse.ArgumentParser(
        description="Consolidate a deepspeed_tpu ZeRO checkpoint into a plain "
                    "fp32 (or bf16) weights npz (reference utils/zero_to_fp32.py)")
    p.add_argument("checkpoint_dir", help="dir passed to engine.save_checkpoint")
    p.add_argument("output_dir", help="where to write model_weights.npz")
    p.add_argument("-t", "--tag", default=None, help="checkpoint tag (default: latest)")
    p.add_argument("-d", "--dtype", default="float32", choices=["float32", "bfloat16", "float16"])
    args = p.parse_args(argv)
    out = convert_zero_checkpoint_to_fp32_state_dict(args.checkpoint_dir, args.output_dir,
                                                     tag=args.tag, save_dtype=args.dtype)
    print(out)


if __name__ == "__main__":
    main()
