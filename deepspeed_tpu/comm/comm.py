"""Unified communication API — the TPU-native ``deepspeed.comm``.

The reference layers a torch.distributed-like API over NCCL/gloo/oneCCL
(``deepspeed/comm/comm.py``: ``init_distributed`` :598, ``all_reduce`` :477,
``all_gather_into_tensor`` :297, ``all_to_all_single`` :331, …). On TPU
there is no rendezvous daemon or process-group handle:

* **Process level** — ``init_distributed()`` wraps
  ``jax.distributed.initialize`` (multi-host ICI/DCN bootstrap);
  ``get_rank``/``get_world_size`` report process (host) coordinates.
* **Program level** — collectives are ``jax.lax`` primitives over *named
  mesh axes*. A "process group" is a tuple of axis names (see
  ``deepspeed_tpu.parallel.topology``). These functions must be called
  inside ``shard_map``/``pjit`` traced code; XLA schedules them on ICI/DCN.

Unlike torch.distributed these are **functional**: they return the result
instead of mutating in place.
"""

import os
from typing import Optional, Sequence, Union

import jax
import jax.numpy as jnp
from jax import lax

from deepspeed_tpu.comm.reduce_op import ReduceOp
from deepspeed_tpu.utils.logging import logger, log_dist
from deepspeed_tpu.utils import comms_logging

AxisNames = Union[str, Sequence[str]]

_INITIALIZED = False
comms_logger = comms_logging.CommsLogger()


def _normalize_axes(group: AxisNames):
    if group is None:
        raise ValueError("collective requires a mesh-axis group (str or tuple of axis names)")
    if isinstance(group, str):
        return (group,)
    return tuple(group)


def is_initialized() -> bool:
    return _INITIALIZED


def init_distributed(dist_backend: Optional[str] = None,
                     auto_mpi_discovery: bool = True,
                     distributed_port: int = 29500,
                     verbose: bool = True,
                     timeout=None,
                     init_method=None,
                     dist_init_required=None,
                     config=None,
                     rank: int = -1,
                     world_size: int = -1) -> None:
    """Bootstrap multi-host execution (reference ``comm/comm.py:598``).

    On TPU pods each host already knows its slice topology; when the
    coordinator env vars are present (or explicit rank/world_size given)
    this calls ``jax.distributed.initialize``. Single-host runs are a
    no-op. The torch-style arguments are accepted for API parity; the
    meaningful ones are ``distributed_port``, ``rank`` and ``world_size``.
    """
    global _INITIALIZED
    if _INITIALIZED:
        return
    coordinator = os.environ.get("DSTPU_COORDINATOR_ADDRESS") or os.environ.get("COORDINATOR_ADDRESS")
    # env contract: DSTPU_* (harness/tests) or JAX_* (launcher/launch.py
    # build_child_env) — reading only one family made launcher-spawned
    # multi-node jobs silently fall through to N disjoint single-host jobs
    n_procs = world_size if world_size > 0 else int(
        os.environ.get("DSTPU_NUM_PROCESSES")
        or os.environ.get("JAX_NUM_PROCESSES", "0") or 0)
    proc_id = rank if rank >= 0 else int(
        os.environ.get("DSTPU_PROCESS_ID")
        or os.environ.get("JAX_PROCESS_ID", "-1") or -1)
    if coordinator and n_procs == 0:
        logger.warning(
            f"coordinator address {coordinator} is set but no process count "
            f"(DSTPU_NUM_PROCESSES / JAX_NUM_PROCESSES / world_size=) — "
            f"treating as single-process; multi-host jobs MUST set the count "
            f"or every host trains alone")
    if coordinator and n_procs > 1:
        # Explicit multi-host config: failures here must be fatal, otherwise
        # N hosts silently train as N disjoint single-host jobs.
        if proc_id < 0:
            raise ValueError("multi-host init requires a process id: pass rank= or set DSTPU_PROCESS_ID")
        jax.distributed.initialize(coordinator_address=f"{coordinator}:{distributed_port}"
                                   if ":" not in coordinator else coordinator,
                                   num_processes=n_procs,
                                   process_id=proc_id)
    elif os.environ.get("MEGASCALE_COORDINATOR_ADDRESS"):
        # TPU-VM metadata path: jax discovers everything itself.
        try:
            jax.distributed.initialize()
        except RuntimeError as e:  # already initialised (e.g. by the launcher)
            logger.warning(f"jax.distributed.initialize skipped: {e}")
    _INITIALIZED = True
    if verbose:
        log_dist(f"dstpu.comm initialized: process {get_rank()}/{get_world_size()}, "
                 f"{jax.local_device_count()} local / {jax.device_count()} global devices")


def configure(config=None, enabled=None, prof_all=None, prof_ops=None, verbose=None):
    """Configure comms logging (reference ``comm/comm.py:configure``)."""
    comms_logger.configure(config=config, enabled=enabled, prof_all=prof_all, prof_ops=prof_ops, verbose=verbose)


# -- process-level topology -------------------------------------------------
def get_rank(group=None) -> int:
    """Process (host) index. One process per host on TPU — the reference's
    one-process-per-GPU ranks have no analog; device-level parallelism is
    inside the mesh."""
    return jax.process_index()


def get_world_size(group=None) -> int:
    return jax.process_count()


def get_local_rank() -> int:
    return 0


def device_count() -> int:
    return jax.device_count()


def local_device_count() -> int:
    return jax.local_device_count()


def barrier(group=None, name: str = "dstpu_barrier"):
    """Cross-host barrier: blocks until every process reaches it (reference
    ``comm.py:barrier``). Uses a global-device sync collective; a no-op on a
    single host beyond draining the local device queue."""
    if jax.process_count() > 1:
        from jax.experimental import multihost_utils

        multihost_utils.sync_global_devices(name)
    else:
        (jax.device_put(0.0) + 0).block_until_ready()  # graft-lint: waive R008 fresh jax scalar barrier, never donated


# -- in-program collectives over mesh axes ----------------------------------
def _maybe_log(op_name, tensor, group):
    if comms_logger.enabled:
        comms_logger.append(op_name=op_name, size=tensor.size * tensor.dtype.itemsize, group=group)


def all_reduce(tensor, op: ReduceOp = ReduceOp.SUM, group: AxisNames = None, async_op=False):
    """All-reduce over the mesh axes in ``group`` (reference ``comm.py:477``)."""
    axes = _normalize_axes(group)
    _maybe_log("all_reduce", tensor, axes)
    if op in (ReduceOp.SUM, ReduceOp.AVG):
        out = lax.psum(tensor, axes)
        if op == ReduceOp.AVG:
            out = out / _axis_size(axes)
        return out
    if op == ReduceOp.MAX:
        return lax.pmax(tensor, axes)
    if op == ReduceOp.MIN:
        return lax.pmin(tensor, axes)
    if op == ReduceOp.PRODUCT:
        # exp(sum(log|x|)) with explicit sign/zero handling so negative or
        # zero members don't produce NaN.
        is_zero = (tensor == 0)
        log_mag = jnp.where(is_zero, 0.0, jnp.log(jnp.abs(jnp.where(is_zero, 1.0, tensor))))
        magnitude = jnp.exp(lax.psum(log_mag, axes))
        neg_count = lax.psum((tensor < 0).astype(jnp.int32), axes)
        any_zero = lax.psum(is_zero.astype(jnp.int32), axes) > 0
        sign = 1.0 - 2.0 * (neg_count % 2).astype(tensor.dtype)
        return jnp.where(any_zero, jnp.zeros_like(magnitude), sign * magnitude)
    raise NotImplementedError(f"ReduceOp {op} not supported on TPU collectives")


def inference_all_reduce(tensor, op: ReduceOp = ReduceOp.SUM, group: AxisNames = None):
    """Latency-optimized all-reduce for inference (reference ``comm.py:494``).
    On TPU the compiler already specializes small-message ICI reductions, so
    this is the same primitive."""
    return all_reduce(tensor, op=op, group=group)


def all_gather(tensor, group: AxisNames = None, axis: int = 0, tiled: bool = True):
    """All-gather along ``axis`` over mesh axes (reference
    ``all_gather_into_tensor`` ``comm.py:297``). ``tiled=True`` concatenates
    shards along ``axis`` (torch semantics); ``tiled=False`` stacks a new
    leading axis."""
    axes = _normalize_axes(group)
    _maybe_log("all_gather", tensor, axes)
    return lax.all_gather(tensor, axes, axis=axis, tiled=tiled)


# alias for torch-API parity
def all_gather_into_tensor(tensor, group: AxisNames = None, axis: int = 0):
    return all_gather(tensor, group=group, axis=axis, tiled=True)


def reduce_scatter(tensor, op: ReduceOp = ReduceOp.SUM, group: AxisNames = None, axis: int = 0):
    """Reduce-scatter (reference ``reduce_scatter_tensor`` ``comm.py:280``):
    sum over the group, each member keeps its slice along ``axis``."""
    axes = _normalize_axes(group)
    _maybe_log("reduce_scatter", tensor, axes)
    out = lax.psum_scatter(tensor, axes, scatter_dimension=axis, tiled=True)
    if op == ReduceOp.AVG:
        out = out / _axis_size(axes)
    elif op != ReduceOp.SUM:
        raise NotImplementedError(f"reduce_scatter op {op}")
    return out


def all_to_all_single(tensor, group: AxisNames = None, split_axis: int = 0, concat_axis: int = 0):
    """All-to-all (reference ``all_to_all_single`` ``comm.py:331``): split
    ``tensor`` along ``split_axis`` into group-size chunks, exchange, concat
    received chunks along ``concat_axis``."""
    axes = _normalize_axes(group)
    _maybe_log("all_to_all_single", tensor, axes)
    return lax.all_to_all(tensor, axes, split_axis=split_axis, concat_axis=concat_axis, tiled=True)


def broadcast(tensor, src: int = 0, group: AxisNames = None):
    """Broadcast the ``src`` member's value to all members of the group
    (reference ``comm.py:broadcast``). Inside SPMD this is a masked psum."""
    axes = _normalize_axes(group)
    _maybe_log("broadcast", tensor, axes)
    idx = _group_index(axes)
    masked = jnp.where(idx == src, tensor, jnp.zeros_like(tensor))
    return lax.psum(masked, axes)


def send_recv(tensor, perm, group: AxisNames = None):
    """Point-to-point permutation (reference ``pipe/p2p.py`` send/recv):
    ``perm`` is a list of (src, dst) pairs along a single mesh axis."""
    axes = _normalize_axes(group)
    assert len(axes) == 1, "send_recv permutes along exactly one mesh axis"
    _maybe_log("send_recv", tensor, axes)
    return lax.ppermute(tensor, axes[0], perm)


# -- torch.distributed-shaped aliases / SPMD translations ------------------
# (reference comm.py exposes the full torch.distributed vocabulary; under
# SPMD some ops collapse into others — each alias documents the mapping)

def reduce_scatter_tensor(tensor, op: ReduceOp = ReduceOp.SUM, group: AxisNames = None,
                          axis: int = 0):
    """Alias of :func:`reduce_scatter` (reference ``comm.py:280`` names the
    tensor-in/tensor-out variant this way)."""
    return reduce_scatter(tensor, op=op, group=group, axis=axis)


def all_to_all(tensor, group: AxisNames = None, split_axis: int = 0, concat_axis: int = 0):
    """Alias of :func:`all_to_all_single`: jax's single-array all_to_all IS
    the list-form exchange with the list stacked on ``split_axis``."""
    return all_to_all_single(tensor, group=group, split_axis=split_axis,
                             concat_axis=concat_axis)


def reduce(tensor, dst: int = 0, op: ReduceOp = ReduceOp.SUM, group: AxisNames = None):
    """Reduce-to-root (reference ``comm.py`` ``reduce``). SPMD has no
    rank-private storage — every member computes the reduction, which IS
    the root's value (``dst`` kept for signature parity)."""
    del dst
    return all_reduce(tensor, op=op, group=group)


def monitored_barrier(group=None, timeout=None, wait_all_ranks=False, name="monitored_barrier"):
    """Reference ``monitored_barrier``: rank-failure detection belongs to
    the runtime (jax.distributed heartbeats), so this reduces to
    :func:`barrier`."""
    del timeout, wait_all_ranks
    return barrier(group=group, name=name)


def gather(tensor, gather_list=None, dst: int = 0, group: AxisNames = None, axis: int = 0):
    """Gather-to-root (reference ``comm.py`` ``gather(tensor, gather_list,
    dst, ...)``): under SPMD every member materializes the gathered value
    (= the root's view). ``gather_list`` is accepted for positional-call
    parity with the reference signature; SPMD returns the gathered array
    instead of filling a list, so a non-None list is rejected loudly."""
    if gather_list is not None:
        if isinstance(gather_list, int):
            raise TypeError(
                "gather(tensor, dst) positional form changed to match the "
                "reference signature gather(tensor, gather_list=None, dst=0, "
                "...) — pass dst as a keyword: gather(tensor, dst=%d)" % gather_list)
        raise ValueError(
            "gather_list is torch.distributed's out-parameter; under SPMD "
            "gather() RETURNS the gathered array — drop the list argument")
    del dst
    return all_gather(tensor, group=group, axis=axis)


def scatter(tensor, scatter_list=None, src: int = 0, group: AxisNames = None, axis: int = 0):
    """Scatter from root (reference ``comm.py`` ``scatter(tensor,
    scatter_list, src, ...)``): each member keeps its chunk of the ``src``
    member's tensor along ``axis``. Lowered as a masked psum_scatter —
    reduce-scatter cost, no full-size broadcast temporary. ``scatter_list``
    is accepted for positional-call parity and rejected loudly if non-None
    (SPMD scatters the root's full ``tensor``, not a per-rank list)."""
    if scatter_list is not None:
        if isinstance(scatter_list, int):
            raise TypeError(
                "scatter(tensor, src) positional form changed to match the "
                "reference signature scatter(tensor, scatter_list=None, src=0, "
                "...) — pass src as a keyword: scatter(tensor, src=%d)" % scatter_list)
        raise ValueError(
            "scatter_list is torch.distributed's per-rank input list; under "
            "SPMD pass the root's full tensor and it is split along `axis`")
    axes = _normalize_axes(group)
    size = _axis_size(axes)
    if tensor.shape[axis] % size != 0:
        raise ValueError(f"scatter dim {axis} of size {tensor.shape[axis]} must divide "
                         f"evenly over the {size}-member group (torch.distributed "
                         f"errors on unequal splits too)")
    _maybe_log("scatter", tensor, axes)
    idx = _group_index(axes)
    masked = jnp.where(idx == src, tensor, jnp.zeros_like(tensor))
    return lax.psum_scatter(masked, axes, scatter_dimension=axis, tiled=True)


def send(tensor, dst, group=None, tag=0):
    """One-sided point-to-point does not exist in the SPMD model — both
    sides of a transfer appear in one program (reference send/recv become
    ``ppermute`` pairs). Use :func:`send_recv` with an explicit
    permutation instead."""
    raise NotImplementedError(
        "send/recv are one-sided torch.distributed ops; under SPMD use "
        "deepspeed_tpu.comm.send_recv(tensor, perm=[(src, dst)], group=...)")


def recv(tensor, src, group=None, tag=0):
    """See :func:`send`."""
    raise NotImplementedError(
        "send/recv are one-sided torch.distributed ops; under SPMD use "
        "deepspeed_tpu.comm.send_recv(tensor, perm=[(src, dst)], group=...)")


def new_group(ranks=None, axes: AxisNames = None):
    """Reference ``comm.py:181`` ``new_group``. Groups here ARE mesh
    sub-axes: pass ``axes=("data", "fsdp")`` (or a single name) and get
    back the normalized axis tuple used as ``group=`` everywhere.
    Arbitrary rank lists cannot name a mesh sub-axis and are rejected with
    guidance (the reference builds NCCL communicators from rank lists; the
    SPMD analog is choosing/reshaping the mesh axes in MeshTopology)."""
    if axes is not None:
        return _normalize_axes(axes)
    raise NotImplementedError(
        "new_group(ranks=[...]) has no SPMD analog — groups are named mesh "
        "axes; construct the MeshTopology with the axis layout you need and "
        "pass group=('axis', ...) to collectives")


def get_global_rank(group: AxisNames = None, group_rank: int = 0,
                    coords: Optional[dict] = None) -> int:
    """Translate a group-relative rank to a global rank (reference
    ``utils.get_global_rank``): with groups = mesh sub-axes, the global
    rank of group member ``group_rank`` follows from the mesh's row-major
    axis order. ``coords`` fixes the coordinates on the NON-group axes
    (``{"tensor": 1}``); axes not given default to coordinate 0 — under
    SPMD there is no per-rank Python frame whose "own" coordinates could
    be implied, so identifying a peer in another slice requires saying
    which slice."""
    from deepspeed_tpu.parallel.topology import get_topology
    topo = get_topology()
    if topo is None:
        return int(group_rank)
    mesh = topo.mesh
    axes = _normalize_axes(group)
    sizes = dict(mesh.shape)
    # decompose group_rank into coords over the group axes (row-major)
    pos = dict(coords or {})
    for a, c in pos.items():
        if a in axes:
            raise ValueError(f"coords names group axis {a!r}; group axes are "
                             f"addressed by group_rank")
        if a not in sizes:
            raise ValueError(f"coords axis {a!r} is not a mesh axis {tuple(sizes)}")
        if not 0 <= int(c) < sizes[a]:
            raise ValueError(f"coords[{a!r}]={c} out of range for axis size {sizes[a]}")
    group_size = 1
    for a in axes:
        group_size *= sizes[a]
    if not 0 <= int(group_rank) < group_size:
        raise ValueError(f"group_rank {group_rank} out of range for group size {group_size}")
    rem = int(group_rank)
    for a in reversed(axes):
        pos[a] = rem % sizes[a]
        rem //= sizes[a]
    global_rank = 0
    for a in mesh.axis_names:
        global_rank = global_rank * sizes[a] + pos.get(a, 0)
    return global_rank


def _axis_size(axes):
    total = 1
    for a in axes:
        total = total * lax.axis_size(a)
    return total


def _group_index(axes):
    """Linear index of this shard within the (possibly multi-axis) group."""
    idx = 0
    for a in axes:
        idx = idx * lax.axis_size(a) + lax.axis_index(a)
    return idx


def get_axis_index(axis: str):
    return lax.axis_index(axis)


def get_axis_size(axis: str):
    return lax.axis_size(axis)


def log_summary(show_straggler=False):
    """Print accumulated comms statistics (reference ``comm.py:416``)."""
    comms_logger.log_all(print_log=True, show_straggler=show_straggler)
