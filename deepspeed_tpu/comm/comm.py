"""Unified communication API — the TPU-native ``deepspeed.comm``.

The reference layers a torch.distributed-like API over NCCL/gloo/oneCCL
(``deepspeed/comm/comm.py``: ``init_distributed`` :598, ``all_reduce`` :477,
``all_gather_into_tensor`` :297, ``all_to_all_single`` :331, …). On TPU
there is no rendezvous daemon or process-group handle:

* **Process level** — ``init_distributed()`` wraps
  ``jax.distributed.initialize`` (multi-host ICI/DCN bootstrap);
  ``get_rank``/``get_world_size`` report process (host) coordinates.
* **Program level** — collectives are ``jax.lax`` primitives over *named
  mesh axes*. A "process group" is a tuple of axis names (see
  ``deepspeed_tpu.parallel.topology``). These functions must be called
  inside ``shard_map``/``pjit`` traced code; XLA schedules them on ICI/DCN.

Unlike torch.distributed these are **functional**: they return the result
instead of mutating in place.
"""

import os
from typing import Optional, Sequence, Union

import jax
import jax.numpy as jnp
from jax import lax

from deepspeed_tpu.comm.reduce_op import ReduceOp
from deepspeed_tpu.utils.logging import logger, log_dist
from deepspeed_tpu.utils import comms_logging

AxisNames = Union[str, Sequence[str]]

_INITIALIZED = False
comms_logger = comms_logging.CommsLogger()


def _normalize_axes(group: AxisNames):
    if group is None:
        raise ValueError("collective requires a mesh-axis group (str or tuple of axis names)")
    if isinstance(group, str):
        return (group,)
    return tuple(group)


def is_initialized() -> bool:
    return _INITIALIZED


def init_distributed(dist_backend: Optional[str] = None,
                     auto_mpi_discovery: bool = True,
                     distributed_port: int = 29500,
                     verbose: bool = True,
                     timeout=None,
                     init_method=None,
                     dist_init_required=None,
                     config=None,
                     rank: int = -1,
                     world_size: int = -1) -> None:
    """Bootstrap multi-host execution (reference ``comm/comm.py:598``).

    On TPU pods each host already knows its slice topology; when the
    coordinator env vars are present (or explicit rank/world_size given)
    this calls ``jax.distributed.initialize``. Single-host runs are a
    no-op. The torch-style arguments are accepted for API parity; the
    meaningful ones are ``distributed_port``, ``rank`` and ``world_size``.
    """
    global _INITIALIZED
    if _INITIALIZED:
        return
    coordinator = os.environ.get("DSTPU_COORDINATOR_ADDRESS") or os.environ.get("COORDINATOR_ADDRESS")
    n_procs = world_size if world_size > 0 else int(os.environ.get("DSTPU_NUM_PROCESSES", "0") or 0)
    proc_id = rank if rank >= 0 else int(os.environ.get("DSTPU_PROCESS_ID", "-1"))
    if coordinator and n_procs > 1:
        # Explicit multi-host config: failures here must be fatal, otherwise
        # N hosts silently train as N disjoint single-host jobs.
        if proc_id < 0:
            raise ValueError("multi-host init requires a process id: pass rank= or set DSTPU_PROCESS_ID")
        jax.distributed.initialize(coordinator_address=f"{coordinator}:{distributed_port}"
                                   if ":" not in coordinator else coordinator,
                                   num_processes=n_procs,
                                   process_id=proc_id)
    elif os.environ.get("MEGASCALE_COORDINATOR_ADDRESS"):
        # TPU-VM metadata path: jax discovers everything itself.
        try:
            jax.distributed.initialize()
        except RuntimeError as e:  # already initialised (e.g. by the launcher)
            logger.warning(f"jax.distributed.initialize skipped: {e}")
    _INITIALIZED = True
    if verbose:
        log_dist(f"dstpu.comm initialized: process {get_rank()}/{get_world_size()}, "
                 f"{jax.local_device_count()} local / {jax.device_count()} global devices")


def configure(config=None, enabled=None, prof_all=None, prof_ops=None, verbose=None):
    """Configure comms logging (reference ``comm/comm.py:configure``)."""
    comms_logger.configure(config=config, enabled=enabled, prof_all=prof_all, prof_ops=prof_ops, verbose=verbose)


# -- process-level topology -------------------------------------------------
def get_rank(group=None) -> int:
    """Process (host) index. One process per host on TPU — the reference's
    one-process-per-GPU ranks have no analog; device-level parallelism is
    inside the mesh."""
    return jax.process_index()


def get_world_size(group=None) -> int:
    return jax.process_count()


def get_local_rank() -> int:
    return 0


def device_count() -> int:
    return jax.device_count()


def local_device_count() -> int:
    return jax.local_device_count()


def barrier(group=None, name: str = "dstpu_barrier"):
    """Cross-host barrier: blocks until every process reaches it (reference
    ``comm.py:barrier``). Uses a global-device sync collective; a no-op on a
    single host beyond draining the local device queue."""
    if jax.process_count() > 1:
        from jax.experimental import multihost_utils

        multihost_utils.sync_global_devices(name)
    else:
        (jax.device_put(0.0) + 0).block_until_ready()


# -- in-program collectives over mesh axes ----------------------------------
def _maybe_log(op_name, tensor, group):
    if comms_logger.enabled:
        comms_logger.append(op_name=op_name, size=tensor.size * tensor.dtype.itemsize, group=group)


def all_reduce(tensor, op: ReduceOp = ReduceOp.SUM, group: AxisNames = None, async_op=False):
    """All-reduce over the mesh axes in ``group`` (reference ``comm.py:477``)."""
    axes = _normalize_axes(group)
    _maybe_log("all_reduce", tensor, axes)
    if op in (ReduceOp.SUM, ReduceOp.AVG):
        out = lax.psum(tensor, axes)
        if op == ReduceOp.AVG:
            out = out / _axis_size(axes)
        return out
    if op == ReduceOp.MAX:
        return lax.pmax(tensor, axes)
    if op == ReduceOp.MIN:
        return lax.pmin(tensor, axes)
    if op == ReduceOp.PRODUCT:
        # exp(sum(log|x|)) with explicit sign/zero handling so negative or
        # zero members don't produce NaN.
        is_zero = (tensor == 0)
        log_mag = jnp.where(is_zero, 0.0, jnp.log(jnp.abs(jnp.where(is_zero, 1.0, tensor))))
        magnitude = jnp.exp(lax.psum(log_mag, axes))
        neg_count = lax.psum((tensor < 0).astype(jnp.int32), axes)
        any_zero = lax.psum(is_zero.astype(jnp.int32), axes) > 0
        sign = 1.0 - 2.0 * (neg_count % 2).astype(tensor.dtype)
        return jnp.where(any_zero, jnp.zeros_like(magnitude), sign * magnitude)
    raise NotImplementedError(f"ReduceOp {op} not supported on TPU collectives")


def inference_all_reduce(tensor, op: ReduceOp = ReduceOp.SUM, group: AxisNames = None):
    """Latency-optimized all-reduce for inference (reference ``comm.py:494``).
    On TPU the compiler already specializes small-message ICI reductions, so
    this is the same primitive."""
    return all_reduce(tensor, op=op, group=group)


def all_gather(tensor, group: AxisNames = None, axis: int = 0, tiled: bool = True):
    """All-gather along ``axis`` over mesh axes (reference
    ``all_gather_into_tensor`` ``comm.py:297``). ``tiled=True`` concatenates
    shards along ``axis`` (torch semantics); ``tiled=False`` stacks a new
    leading axis."""
    axes = _normalize_axes(group)
    _maybe_log("all_gather", tensor, axes)
    return lax.all_gather(tensor, axes, axis=axis, tiled=tiled)


# alias for torch-API parity
def all_gather_into_tensor(tensor, group: AxisNames = None, axis: int = 0):
    return all_gather(tensor, group=group, axis=axis, tiled=True)


def reduce_scatter(tensor, op: ReduceOp = ReduceOp.SUM, group: AxisNames = None, axis: int = 0):
    """Reduce-scatter (reference ``reduce_scatter_tensor`` ``comm.py:280``):
    sum over the group, each member keeps its slice along ``axis``."""
    axes = _normalize_axes(group)
    _maybe_log("reduce_scatter", tensor, axes)
    out = lax.psum_scatter(tensor, axes, scatter_dimension=axis, tiled=True)
    if op == ReduceOp.AVG:
        out = out / _axis_size(axes)
    elif op != ReduceOp.SUM:
        raise NotImplementedError(f"reduce_scatter op {op}")
    return out


def all_to_all_single(tensor, group: AxisNames = None, split_axis: int = 0, concat_axis: int = 0):
    """All-to-all (reference ``all_to_all_single`` ``comm.py:331``): split
    ``tensor`` along ``split_axis`` into group-size chunks, exchange, concat
    received chunks along ``concat_axis``."""
    axes = _normalize_axes(group)
    _maybe_log("all_to_all_single", tensor, axes)
    return lax.all_to_all(tensor, axes, split_axis=split_axis, concat_axis=concat_axis, tiled=True)


def broadcast(tensor, src: int = 0, group: AxisNames = None):
    """Broadcast the ``src`` member's value to all members of the group
    (reference ``comm.py:broadcast``). Inside SPMD this is a masked psum."""
    axes = _normalize_axes(group)
    _maybe_log("broadcast", tensor, axes)
    idx = _group_index(axes)
    masked = jnp.where(idx == src, tensor, jnp.zeros_like(tensor))
    return lax.psum(masked, axes)


def send_recv(tensor, perm, group: AxisNames = None):
    """Point-to-point permutation (reference ``pipe/p2p.py`` send/recv):
    ``perm`` is a list of (src, dst) pairs along a single mesh axis."""
    axes = _normalize_axes(group)
    assert len(axes) == 1, "send_recv permutes along exactly one mesh axis"
    _maybe_log("send_recv", tensor, axes)
    return lax.ppermute(tensor, axes[0], perm)


def _axis_size(axes):
    total = 1
    for a in axes:
        total = total * lax.axis_size(a)
    return total


def _group_index(axes):
    """Linear index of this shard within the (possibly multi-axis) group."""
    idx = 0
    for a in axes:
        idx = idx * lax.axis_size(a) + lax.axis_index(a)
    return idx


def get_axis_index(axis: str):
    return lax.axis_index(axis)


def get_axis_size(axis: str):
    return lax.axis_size(axis)


def log_summary(show_straggler=False):
    """Print accumulated comms statistics (reference ``comm.py:416``)."""
    comms_logger.log_all(print_log=True, show_straggler=show_straggler)
