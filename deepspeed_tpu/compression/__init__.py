"""Compression library (reference ``deepspeed/compression``): scheduled
weight/activation quantization and sparse/row/head/channel pruning,
functional over flax param pytrees."""

from deepspeed_tpu.compression.compress import (build_compression_transform, export_compressed,
                                                init_compression, load_compressed,
                                                redundancy_clean)
from deepspeed_tpu.compression.config import get_compression_config

__all__ = ["init_compression", "redundancy_clean", "build_compression_transform",
           "export_compressed", "load_compressed", "get_compression_config"]
