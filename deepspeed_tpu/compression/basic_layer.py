"""Functional compression primitives (reference
``compression/basic_layer.py:121-611`` ``LinearLayer_Compress`` /
``Embedding_Compress`` and ``compression/utils.py`` quantizers).

The reference compresses by swapping ``nn.Linear`` for stateful modules
that mutate their own weights in ``forward``. Flax params are immutable
pytrees, so each technique here is a pure ``(weight, step) -> weight``
transform; the engine composes them over the param tree inside the jitted
training step (schedules are ``jnp.where`` gates on the step counter, so
one compiled program covers the whole schedule)."""

from typing import Optional

import jax
import jax.numpy as jnp

from deepspeed_tpu.ops.quantizer.core import divisor_groups


def qdq_weight(w: jax.Array, bits, groups: int = 1, symmetric: bool = True) -> jax.Array:
    """Quantize-dequantize at ``bits`` (traced scalar ok) with grouped scales
    (reference ``WeightQuantization`` utils.py; STE gradient comes free from
    the straight-through pattern)."""
    flat = w.reshape(-1)
    g = divisor_groups(flat.size, max(flat.size // max(groups, 1), 1))
    grouped = flat.reshape(g, -1).astype(jnp.float32)
    levels = 2.0 ** (bits - 1) - 1.0
    if symmetric:
        scale = jnp.max(jnp.abs(grouped), axis=-1, keepdims=True) / levels
        scale = jnp.maximum(scale, 1e-12)
        q = jnp.clip(jnp.round(grouped / scale), -levels - 1, levels)
        dq = q * scale
    else:
        lo = jnp.min(grouped, axis=-1, keepdims=True)
        hi = jnp.max(grouped, axis=-1, keepdims=True)
        scale = jnp.maximum((hi - lo) / (2.0 * levels + 1.0), 1e-12)
        q = jnp.clip(jnp.round((grouped - lo) / scale), 0, 2 * levels + 1)
        dq = q * scale + lo
    out = dq.reshape(w.shape).astype(w.dtype)
    # straight-through estimator: gradient flows as if identity
    return w + jax.lax.stop_gradient(out - w)


def scheduled_bits(step, start_bits: int, target_bits: int, period: int):
    """Bit-width schedule (reference ``quantization_period`` semantics,
    basic_layer.py:159-170): halve from start toward target every
    ``period`` steps past the offset (traced)."""
    if start_bits <= target_bits:
        return jnp.asarray(float(target_bits))
    n_halvings = jnp.floor_divide(jnp.maximum(step, 0), max(period, 1))
    bits = jnp.maximum(start_bits / (2.0 ** n_halvings.astype(jnp.float32)),
                       float(target_bits))
    return bits


def sparse_prune_mask(w: jax.Array, dense_ratio: float, method: str = "l1") -> jax.Array:
    """Unstructured magnitude mask keeping ``dense_ratio`` of entries
    (reference ``SparsePruning_Compress`` l1/topk)."""
    flat = jnp.abs(w.reshape(-1).astype(jnp.float32))
    k = max(int(flat.size * dense_ratio), 1)
    thresh = jnp.sort(flat)[-k]
    return (jnp.abs(w) >= thresh.astype(w.dtype)).astype(w.dtype)


def row_prune_mask(w: jax.Array, dense_ratio: float) -> jax.Array:
    """Keep the highest-l1 output rows (flax kernel [in, out] → axis 1;
    reference ``LinearLayer_Compress.row_pruning`` prunes torch rows
    [out, in] → the same output neurons)."""
    scores = jnp.sum(jnp.abs(w.astype(jnp.float32)), axis=tuple(range(w.ndim - 1)))
    k = max(int(scores.size * dense_ratio), 1)
    thresh = jnp.sort(scores)[-k]
    keep = (scores >= thresh).astype(w.dtype)
    return jnp.broadcast_to(keep, w.shape)


def head_prune_mask(w: jax.Array, dense_ratio: float, num_heads: int) -> jax.Array:
    """Keep the highest-l1 heads: the output dim splits into ``num_heads``
    blocks (reference ``head_pruning`` on attention projections)."""
    out_dim = w.shape[-1]
    assert out_dim % num_heads == 0, f"out dim {out_dim} not divisible by {num_heads} heads"
    per = out_dim // num_heads
    blocks = w.reshape(-1, num_heads, per)
    scores = jnp.sum(jnp.abs(blocks.astype(jnp.float32)), axis=(0, 2))
    k = max(int(num_heads * dense_ratio), 1)
    thresh = jnp.sort(scores)[-k]
    keep = (scores >= thresh).astype(w.dtype)                     # [heads]
    return jnp.broadcast_to(keep[None, :, None], blocks.shape).reshape(w.shape)


def channel_prune_mask(w: jax.Array, dense_ratio: float) -> jax.Array:
    """Keep the highest-l1 INPUT channels (flax kernel axis 0)."""
    scores = jnp.sum(jnp.abs(w.astype(jnp.float32)), axis=tuple(range(1, w.ndim)))
    k = max(int(scores.size * dense_ratio), 1)
    thresh = jnp.sort(scores)[-k]
    keep = (scores >= thresh).astype(w.dtype)
    return jnp.broadcast_to(keep.reshape((-1,) + (1,) * (w.ndim - 1)), w.shape)


def quantize_activation(x: jax.Array, bits: int = 8, symmetric: bool = True,
                        rng: Optional[jax.Array] = None) -> jax.Array:
    """Dynamic-range activation QDQ (reference ``QuantAct``
    basic_layer.py:548): per-tensor scale, STE gradient. Use inside model
    code (flax has no module-swap hook; ``ActivationQuantizer`` wraps it)."""
    return qdq_weight(x, float(bits), groups=1, symmetric=symmetric)
