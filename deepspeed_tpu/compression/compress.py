"""Compression entry points (reference ``compression/compress.py:100``
``init_compression`` and ``:148`` ``redundancy_clean``).

Functional formulation: ``build_compression_transform`` compiles the config
into one pure ``(params, step) -> params`` function; ``init_compression``
installs it on an engine (applied to the compute params inside the jitted
step, so the schedule gates are ``jnp.where`` on the live step counter —
no recompiles as techniques activate); ``redundancy_clean`` bakes the
end-state compression into the weights for export, and
``export_compressed`` writes genuinely smaller int8 checkpoints.
"""

import fnmatch
import json
import os
from typing import Any, Callable, Dict, List, Optional, Tuple

import numpy as np

import jax
import jax.numpy as jnp

from deepspeed_tpu.compression import basic_layer as BL
from deepspeed_tpu.compression.config import (CHANNEL_PRUNING,
                                              DIFFERENT_GROUPS, HEAD_PRUNING,
                                              LAYER_REDUCTION, ROW_PRUNING,
                                              SHARED_PARAMETERS, SPARSE_PRUNING,
                                              WEIGHT_QUANTIZATION, get_compression_config)
from deepspeed_tpu.utils.logging import log_dist, logger


def _match(path: str, patterns: List[str]) -> bool:
    """Reference patterns are torch dotted module names; tree paths are
    slash-joined — match both spellings, on SEGMENT boundaries (a bare
    substring check would let "h_1" also select h_10/h_11)."""
    bounded = "/" + path + "/"
    for pat in patterns:
        p = pat.replace(".", "/")
        if any(ch in p for ch in "*?["):
            if fnmatch.fnmatch(path, p) or fnmatch.fnmatch(bounded, f"*/{p}/*"):
                return True
        elif f"/{p}/" in bounded:
            return True
    return False


def _param_paths(params) -> List[Tuple[str, Any]]:
    from deepspeed_tpu.utils.tree import keypath_str
    return [(keypath_str(kp), leaf)
            for kp, leaf in jax.tree_util.tree_flatten_with_path(params)[0]]


class CompressionSpec:
    """Config resolved against a concrete param tree: an ordered rule list
    ``(path, technique, group_params, shared)`` for the matrix-shaped leaves
    each group's module patterns select (the analog of the reference's
    ``layer_added_compress_methods``, compress.py:60)."""

    def __init__(self, config: Dict[str, Any], params):
        self.config = config
        self.rules: Dict[str, List[Tuple[str, Dict, Dict]]] = {}
        n = 0
        for tech in (WEIGHT_QUANTIZATION, SPARSE_PRUNING, ROW_PRUNING, HEAD_PRUNING,
                     CHANNEL_PRUNING):
            shared = config[tech][SHARED_PARAMETERS]
            if not shared.get("enabled", False):
                continue
            for gname, group in config[tech][DIFFERENT_GROUPS].items():
                for path, leaf in _param_paths(params):
                    if leaf.ndim < 2 or not jnp.issubdtype(jnp.asarray(leaf).dtype, jnp.floating):
                        continue
                    if not path.endswith("kernel") and "embedding" not in path and "wte" not in path:
                        continue
                    if _match(path, group["modules"]):
                        self.rules.setdefault(path, []).append((tech, group["params"], shared))
                        n += 1
        log_dist(f"compression: {n} (param, technique) rules across "
                 f"{len(self.rules)} params")

    def transform(self) -> Callable:
        """One pure fn(params, step) -> params applying every rule with its
        schedule gate."""
        rules = self.rules

        def apply(params, step):
            from deepspeed_tpu.utils.tree import keypath_str
            step = jnp.asarray(step)
            flat = jax.tree_util.tree_flatten_with_path(params)
            leaves = []
            for kp, leaf in flat[0]:
                path = keypath_str(kp)
                for tech, gp, shared in rules.get(path, ()):
                    offset = int(shared.get("schedule_offset", 0))
                    active = step >= offset
                    if tech == WEIGHT_QUANTIZATION:
                        bits = BL.scheduled_bits(step - offset, int(gp["start_bits"]),
                                                 int(gp["target_bits"]),
                                                 int(gp["quantization_period"]))
                        sym = shared.get("quantization_type", "symmetric") == "symmetric"
                        new = BL.qdq_weight(leaf, bits, groups=int(shared.get("quantize_groups", 1)),
                                            symmetric=sym)
                    elif tech == SPARSE_PRUNING:
                        new = leaf * BL.sparse_prune_mask(leaf, float(gp["dense_ratio"]),
                                                          shared.get("method", "l1"))
                    elif tech == ROW_PRUNING:
                        new = leaf * BL.row_prune_mask(leaf, float(gp["dense_ratio"]))
                    elif tech == HEAD_PRUNING:
                        heads = gp.get("num_heads") or shared.get("num_heads")
                        if heads:
                            new = leaf * BL.head_prune_mask(leaf, float(gp["dense_ratio"]),
                                                            int(heads))
                        else:
                            logger.warning(f"head_pruning on {path}: num_heads not set; skipped")
                            new = leaf
                    else:  # CHANNEL_PRUNING
                        new = leaf * BL.channel_prune_mask(leaf, float(gp["dense_ratio"]))
                    leaf = jnp.where(active, new, leaf)
                leaves.append(leaf)
            return jax.tree_util.tree_unflatten(flat[1], leaves)

        return apply


def build_compression_transform(params, ds_config: Dict[str, Any]) -> Optional[Callable]:
    """Resolve the config against ``params``; None when nothing is enabled."""
    spec = CompressionSpec(get_compression_config(ds_config), params)
    return spec.transform() if spec.rules else None


def _layer_key(prefix: str, idx: int) -> str:
    """Reference dotted layer path → flax tree key: ``transformer.h`` + 3 →
    ``h_3`` (our zoo names blocks ``{base}_{i}`` at one tree level)."""
    base = prefix.replace(".", "/").rstrip("/").split("/")[-1]
    return f"{base}_{idx}"


def student_initialization(student_params, teacher_params, deepspeed_config):
    """Reinitialize a shallower student from selected teacher layers
    (reference ``student_initialization``, ``compress.py:192``): layer
    ``teacher_layer[i]`` of the teacher seeds layer ``i`` of the student,
    and ``other_module_name`` subtrees (embeddings, final LN, heads) copy
    over verbatim. Operates on flax param PYTREES — the TPU analog of the
    reference's ``recursive_getattr`` + ``param.data.copy_`` walk — and
    returns a NEW student tree (host arrays; the caller places it)."""
    cfg = get_compression_config(deepspeed_config if isinstance(deepspeed_config, dict)
                                 else deepspeed_config.raw_dict)
    lr = cfg[LAYER_REDUCTION]
    if not lr.get("enabled", False):
        return student_params
    prefix = lr.get("module_name_prefix", "h")
    teacher_layer = list(lr.get("teacher_layer", []))
    other = list(lr.get("other_module_name", []))

    out = dict(student_params)
    for s_idx, t_idx in enumerate(teacher_layer):
        t_key, s_key = _layer_key(prefix, int(t_idx)), _layer_key(prefix, s_idx)
        if s_key not in out or t_key not in teacher_params:
            raise KeyError(f"layer_reduction: student[{s_key}] or teacher[{t_key}] missing "
                           f"(student keys: {sorted(student_params)[:8]}...)")
        src, dst = teacher_params[t_key], out[s_key]
        jax.tree.map(lambda a, b: None, src, dst)  # structure must match
        out[s_key] = jax.tree.map(jnp.asarray, src)
    for name in other:
        key = name.replace(".", "/").rstrip("/").split("/")[-1]
        if key not in teacher_params or key not in out:
            raise KeyError(f"layer_reduction other_module_name {name!r}: {key!r} not a "
                           f"top-level subtree of both trees")
        out[key] = jax.tree.map(jnp.asarray, teacher_params[key])
    n = sum(1 for _ in teacher_layer) + len(other)
    log_dist(f"student_initialization: {n} subtrees seeded from the teacher "
             f"(layers {teacher_layer} -> 0..{len(teacher_layer) - 1})")
    return out


def init_compression(model_or_engine, deepspeed_config=None, teacher_model=None, mpu=None):
    """Install compression on an engine (reference ``init_compression``
    compress.py:100 swaps modules in place; here the engine's jitted step
    transforms the compute params). Returns its argument for API parity.

    ``teacher_model``: honored (reference ``compress.py:119``): required
    when ``layer_reduction`` is enabled — the student's layers are seeded
    from the teacher — and when ``knowledge_distillation`` is enabled the
    teacher forward runs IN-GRAPH (stop-gradient) inside the student's
    jitted step, its logit-KL and layerwise hidden-MSE terms mixed into
    the loss under the schedule's in-graph gate. Accepts a flax module
    (params from the engine's init rng), a ``(module, params)`` tuple, or
    a torch module convertible via ``module_inject.from_hf``."""
    from deepspeed_tpu.runtime.engine import DeepSpeedEngine

    if isinstance(model_or_engine, DeepSpeedEngine):
        engine = model_or_engine
        if engine.global_steps > 0:
            raise RuntimeError("init_compression must run before the first train_batch "
                               "(rebuilding the step mid-run would discard live "
                               "optimizer side-state)")
        raw = deepspeed_config if isinstance(deepspeed_config, dict) else engine.config.raw_dict
        engine._compression_config = raw
        engine._compression_pending = True

        cfg = get_compression_config(raw)
        from deepspeed_tpu.compression.config import KNOWLEDGE_DISTILLATION, LAYER_REDUCTION as LR
        needs_teacher = cfg[LR].get("enabled", False) or cfg[KNOWLEDGE_DISTILLATION]["enabled"]
        if needs_teacher and teacher_model is None:
            raise ValueError("Teacher model is required for layer reduction / knowledge "
                             "distillation (reference compress.py:119)")
        if cfg[KNOWLEDGE_DISTILLATION]["enabled"]:
            # KD's schedule gate rides the fused step's in-graph counter; the
            # host-driven optimizer schedules never inject it — fail loudly
            # instead of silently training pure CE with a dead teacher forward
            zc = engine.config.zero_config
            off = zc.offload_optimizer is not None and getattr(
                zc.offload_optimizer, "device", "none") not in (None, "none")
            from deepspeed_tpu.runtime import constants as _C
            onebit = engine.config.optimizer_name in (
                _C.ONEBIT_ADAM_OPTIMIZER, _C.ONEBIT_LAMB_OPTIMIZER,
                _C.ZERO_ONE_ADAM_OPTIMIZER)
            if off or onebit:
                raise ValueError("knowledge_distillation requires the fused "
                                 "train_batch path; offload_optimizer and 1-bit/0-1 "
                                 "Adam schedules never reach the KD gate")
        if teacher_model is not None and needs_teacher:
            t_module, t_params = _resolve_teacher(teacher_model, engine)
            if cfg[LR].get("enabled", False):
                # the engine's consumer owns the apply (owned buffers via
                # utils/device.py in one place)
                engine._pending_student_init = (t_params, raw)
                engine._maybe_apply_student_init()
            if cfg[KNOWLEDGE_DISTILLATION]["enabled"]:
                t_placed = _place_teacher(t_module, t_params, engine)
                engine._kd_config = dict(cfg[KNOWLEDGE_DISTILLATION],
                                         module=t_module, params=t_placed)
                log_dist(f"knowledge distillation active: kd_coef="
                         f"{engine._kd_config['kd_coef']} T={engine._kd_config['temperature']} "
                         f"layerwise={engine._kd_config['layerwise_coef']} "
                         f"steps [{engine._kd_config['schedule_offset']}, "
                         f"{engine._kd_config['schedule_offset_end']})")

        # force a rebuild so the compression hook lands in the step program
        engine._train_step_fn = None
        if engine.state is not None:
            engine._build_step_fns()
        log_dist("compression installed on engine (applies inside the jitted step)")
        return engine
    raise TypeError("init_compression expects a DeepSpeedEngine; for raw flax params use "
                    "build_compression_transform(params, ds_config)")


def _place_teacher(t_module, t_params, engine):
    """Shard the teacher over the engine's mesh with the planner's own
    rules (the teacher module carries the same logical-axis metadata as
    every zoo model), so the KD forward's teacher weights rest 1/fsdp per
    chip instead of riding the trace as replicated constants — the HBM
    difference between a viable and an impossible big-teacher distillation.
    Falls back to the host tree (closure constants) when the teacher's
    structure defeats the plan (exotic custom modules)."""
    from deepspeed_tpu.models.common import is_seq2seq_module
    from deepspeed_tpu.runtime.zero.planner import build_plan
    try:
        ids = jnp.zeros((1, 8), jnp.int32)
        kwargs = {"decoder_input_ids": ids} if is_seq2seq_module(t_module) else {}
        aboxed = jax.eval_shape(
            lambda: t_module.init(jax.random.PRNGKey(0), ids,
                                  deterministic=True, **kwargs))
        # the teacher carries no optimizer state, so fsdp-sharding it is
        # safe at ANY student stage — force the stage-3 carve rather than
        # inheriting a stage-0/1/2 plan that would leave it replicated
        zc = engine.config.zero_config.model_copy(update={"stage": 3})
        plan = build_plan(aboxed["params"], zc, engine.topology)
        # owned copy: teacher host buffers feed the captured KD step
        # (utils/device.py zero-copy + donation hazard)
        from deepspeed_tpu.utils.device import owned_device_put
        placed = owned_device_put(t_params, plan.param_shardings())
        log_dist("KD teacher placed fsdp-sharded over the mesh (stage-3 carve)")
        return placed
    except Exception as e:  # noqa: BLE001 — placement is an optimization
        logger.warning(f"KD teacher placement fell back to host constants "
                       f"({type(e).__name__}: {str(e)[:120]})")
        return t_params


def _resolve_teacher(teacher_model, engine):
    """Normalize teacher_model to (flax module, host param tree).

    A bare flax ``nn.Module`` is REJECTED: flax modules carry no weights,
    so accepting one would silently distill against freshly-initialized
    noise — pass ``(module, trained_params)`` (or an HF torch module,
    whose weights travel with it)."""
    import flax.linen as fnn
    if isinstance(teacher_model, tuple):
        module, params = teacher_model
        return module, jax.device_get(fnn.meta.unbox(params))
    if isinstance(teacher_model, fnn.Module):
        raise TypeError("teacher_model is a bare flax Module, which has no weights — "
                        "pass (module, trained_params) so the student distills from "
                        "the TRAINED teacher, not from a fresh init")
    try:  # torch module → flax via the injection importer
        from deepspeed_tpu.module_inject.from_hf import from_hf
        module, params = from_hf(teacher_model)
        return module, jax.device_get(params)
    except Exception as e:  # noqa: BLE001
        raise TypeError(f"teacher_model must be a (flax module, params) tuple or "
                        f"an HF torch module ({type(teacher_model).__name__}: {e})")


def redundancy_clean(params, deepspeed_config: Dict[str, Any], step: Optional[int] = None):
    """Bake the end-state compression into the weights (reference
    ``redundancy_clean`` compress.py:148 makes masks/quantization permanent
    for deployment). ``step`` defaults to past every schedule offset."""
    transform = build_compression_transform(params, deepspeed_config)
    if transform is None:
        return params
    if step is None:
        cfg = get_compression_config(deepspeed_config)
        step = 1 + max(int(cfg[t][SHARED_PARAMETERS].get("schedule_offset", 0))
                       for t in (WEIGHT_QUANTIZATION, SPARSE_PRUNING, ROW_PRUNING,
                                 HEAD_PRUNING, CHANNEL_PRUNING))
        # weight quantization must land at target_bits: jump past every period
        step += 10 ** 9
    return jax.jit(lambda p: transform(p, jnp.asarray(step)))(params)


def export_compressed(params, deepspeed_config: Dict[str, Any], output_dir: str) -> str:
    """Write a deployment checkpoint where weight-quantized kernels are
    stored as REAL int8 codes + scales (smaller file, not QDQ-fp32) and
    pruning is baked in. Returns the npz path."""
    from deepspeed_tpu.checkpoint.zero_to_fp32 import _flatten, save_npz
    from deepspeed_tpu.ops.quantizer.core import divisor_groups, quantize

    cleaned = jax.device_get(redundancy_clean(params, deepspeed_config))
    cfg = get_compression_config(deepspeed_config)
    wq = cfg[WEIGHT_QUANTIZATION]
    spec = CompressionSpec(cfg, params)
    q_paths = {p for p, rules in spec.rules.items()
               if any(t == WEIGHT_QUANTIZATION for t, _, _ in rules)}
    target_bits = {p: int(gp["target_bits"]) for p, rules in spec.rules.items()
                   for t, gp, _ in rules if t == WEIGHT_QUANTIZATION}

    flat = _flatten(cleaned)
    out = {}
    for path, arr in flat.items():
        if path in q_paths and target_bits.get(path, 8) <= 8:
            groups = divisor_groups(arr.size, 2048)
            q, qp = quantize(jnp.asarray(arr), num_bits=8, symmetric=True, num_groups=groups)
            out[path + ".int8"] = np.asarray(q, np.int8)
            out[path + ".scale"] = np.asarray(qp.scale, np.float32)
            out[path + ".shape"] = np.asarray(arr.shape, np.int64)
        else:
            out[path] = np.asarray(arr)
    os.makedirs(output_dir, exist_ok=True)
    out_path = os.path.join(output_dir, "compressed_weights.npz")
    save_npz(out_path, out)
    with open(os.path.join(output_dir, "compression_manifest.json"), "w") as f:
        json.dump({"int8_params": sorted(q_paths)}, f, indent=2)
    return out_path


def load_compressed(path: str):
    """Inverse of ``export_compressed``: nested fp32 param dict."""
    from deepspeed_tpu.checkpoint.zero_to_fp32 import _unflatten
    if os.path.isdir(path):
        path = os.path.join(path, "compressed_weights.npz")
    from deepspeed_tpu.checkpoint.zero_to_fp32 import load_state_dict_from_npz
    flat_nested = load_state_dict_from_npz(path)
    # re-flatten to find .int8 triplets
    from deepspeed_tpu.checkpoint.zero_to_fp32 import _flatten
    flat = _flatten(flat_nested)
    out = {}
    for k, v in flat.items():
        if k.endswith(".int8"):
            base = k[:-5]
            scale = flat[base + ".scale"]
            shape = tuple(int(x) for x in flat[base + ".shape"])
            vals = (v.astype(np.float32).reshape(scale.shape[0], -1)
                    * scale.reshape(scale.shape[0], -1))
            out[base] = vals.reshape(shape)
        elif k.endswith(".scale") or k.endswith(".shape"):
            continue
        else:
            out[k] = v
    return _unflatten(out)
