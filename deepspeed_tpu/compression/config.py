"""Compression config parsing (reference ``compression/config.py`` +
``compression/constants.py``) — same ``"compression_training"`` block
layout: per-technique ``shared_parameters`` + ``different_groups``, each
group carrying method params and module-name patterns."""

from typing import Any, Dict

COMPRESSION_TRAINING = "compression_training"

WEIGHT_QUANTIZATION = "weight_quantization"
ACTIVATION_QUANTIZATION = "activation_quantization"
SPARSE_PRUNING = "sparse_pruning"
ROW_PRUNING = "row_pruning"
HEAD_PRUNING = "head_pruning"
CHANNEL_PRUNING = "channel_pruning"
LAYER_REDUCTION = "layer_reduction"
# staged knowledge distillation (the reference keeps the KD loss in its
# example training scripts — DeepSpeedExamples model_compression — and the
# schedule in compression/scheduler.py; here both live in the framework so
# `teacher_model` passed to init_compression actually does something)
KNOWLEDGE_DISTILLATION = "knowledge_distillation"

_KD_DEFAULTS: Dict[str, Any] = dict(
    enabled=False,
    kd_coef=0.5,           # weight of the logit-KD term in the mixed loss
    temperature=2.0,       # softmax temperature (Hinton KD); loss scales T^2
    layerwise_coef=0.0,    # weight of the hidden-state MSE term (staged/layerwise)
    schedule_offset=0,     # step the KD terms switch ON (in-graph gate)
    schedule_offset_end=2 ** 31 - 1,  # step the KD terms switch back OFF
)

SHARED_PARAMETERS = "shared_parameters"
DIFFERENT_GROUPS = "different_groups"

TECHNIQUES = (WEIGHT_QUANTIZATION, ACTIVATION_QUANTIZATION, SPARSE_PRUNING,
              ROW_PRUNING, HEAD_PRUNING, CHANNEL_PRUNING)

_SHARED_DEFAULTS: Dict[str, Dict[str, Any]] = {
    WEIGHT_QUANTIZATION: dict(enabled=False, quantizer_kernel=False, schedule_offset=0,
                              quantize_groups=1, quantize_verbose=False,
                              quantization_type="symmetric", rounding="nearest",
                              quantize_weight_in_forward=True,
                              fp16_mixed_quantize=False, quantize_change_ratio=0.001),
    ACTIVATION_QUANTIZATION: dict(enabled=False, quantization_type="symmetric",
                                  range_calibration="dynamic", schedule_offset=1000),
    SPARSE_PRUNING: dict(enabled=False, method="l1", schedule_offset=1000,
                         schedule_offset_end=1000, schedule_offset_stride=1,
                         block_pattern="4x1", dense_ratio=0.1, excluded_modules=[]),
    ROW_PRUNING: dict(enabled=False, method="l1", schedule_offset=1000),
    HEAD_PRUNING: dict(enabled=False, method="topk", schedule_offset=1000,
                       num_heads=None),
    CHANNEL_PRUNING: dict(enabled=False, method="l1", schedule_offset=1000),
}

_GROUP_PARAM_DEFAULTS: Dict[str, Dict[str, Any]] = {
    WEIGHT_QUANTIZATION: dict(start_bits=8, target_bits=8, quantization_period=1),
    ACTIVATION_QUANTIZATION: dict(bits=8),
    SPARSE_PRUNING: dict(dense_ratio=0.5),
    ROW_PRUNING: dict(dense_ratio=0.5),
    HEAD_PRUNING: dict(dense_ratio=0.5, num_heads=None),
    CHANNEL_PRUNING: dict(dense_ratio=0.5),
}


def get_compression_config(param_dict: Dict[str, Any]) -> Dict[str, Any]:
    """Parse ``compression_training`` into the normalized structure the
    reference's ``get_compression_config`` (``compression/config.py``)
    returns: technique → {shared_parameters, different_groups:
    {name: {params, modules, related_modules}}}."""
    block = param_dict.get(COMPRESSION_TRAINING, {}) or {}
    out: Dict[str, Any] = {}
    for tech in TECHNIQUES:
        tech_cfg = block.get(tech, {}) or {}
        shared = dict(_SHARED_DEFAULTS[tech])
        shared.update(tech_cfg.get(SHARED_PARAMETERS, {}) or {})
        groups: Dict[str, Any] = {}
        for gname, gcfg in (tech_cfg.get(DIFFERENT_GROUPS, {}) or {}).items():
            params = dict(_GROUP_PARAM_DEFAULTS[tech])
            params.update(gcfg.get("params", {}) or {})
            groups[gname] = dict(params=params,
                                 modules=list(gcfg.get("modules", ["*"])),
                                 related_modules=gcfg.get("related_modules"))
        out[tech] = {SHARED_PARAMETERS: shared, DIFFERENT_GROUPS: groups}
    lr = block.get(LAYER_REDUCTION, {}) or {}
    out[LAYER_REDUCTION] = dict(enabled=bool(lr.get("enabled", False)), **{
        k: v for k, v in lr.items() if k != "enabled"})
    kd = block.get(KNOWLEDGE_DISTILLATION, {}) or {}
    kd_out = dict(_KD_DEFAULTS)
    kd_out.update(kd)
    kd_out["enabled"] = bool(kd_out.get("enabled", False))
    out[KNOWLEDGE_DISTILLATION] = kd_out
    return out
