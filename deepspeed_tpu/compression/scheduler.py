"""Compression scheduling (reference ``compression/scheduler.py:12``
``compression_scheduler``).

On TPU the schedule gates are *in-graph* — ``jnp.where(step >= offset)``
on the live step counter inside the jitted train step
(``compress.CompressionSpec.transform``), so techniques activate without
retraces and without this class. What the reference class additionally
provides is host-side bookkeeping: a ``step()`` the training loop calls,
``training_steps``, and activation logging/flags the moment a technique's
offset is crossed. This class keeps that surface (and the KD schedule
below) so reference training loops port unchanged.
"""

from typing import Any, Dict

from deepspeed_tpu.compression.config import (CHANNEL_PRUNING, HEAD_PRUNING,
                                              ROW_PRUNING, SHARED_PARAMETERS,
                                              SPARSE_PRUNING, WEIGHT_QUANTIZATION,
                                              get_compression_config)
from deepspeed_tpu.utils.logging import log_dist

_TECHNIQUES = (WEIGHT_QUANTIZATION, SPARSE_PRUNING, HEAD_PRUNING, ROW_PRUNING,
               CHANNEL_PRUNING)
ACTIVATION_QUANTIZATION = "activation_quantization"


class compression_scheduler:
    """Reference-shaped scheduler: tracks ``training_steps`` and reports
    which techniques are active. ``model`` may be an engine, module, or
    params pytree — activation is config-driven (offsets), not hook-driven,
    so the model is held only for API parity."""

    def __init__(self, model, compression_config: Dict[str, Any]):
        self.model = model
        # accept a raw ds_config or an already-resolved compression block
        if WEIGHT_QUANTIZATION not in compression_config:
            compression_config = get_compression_config(compression_config)
        self.compression_config = compression_config
        self.training_steps = 0
        self.weight_quantization_enabled = False
        self.verbose = {t: False for t in _TECHNIQUES}
        self.verbose[ACTIVATION_QUANTIZATION] = False

    def _offset(self, tech: str) -> int:
        return int(self.compression_config[tech][SHARED_PARAMETERS].get(
            "schedule_offset", 0))

    def _enabled(self, tech: str) -> bool:
        return bool(self.compression_config[tech][SHARED_PARAMETERS].get(
            "enabled", False))

    def is_active(self, tech: str) -> bool:
        return self._enabled(tech) and self.training_steps >= self._offset(tech)

    def _check(self, tech: str):
        if not self._enabled(tech):
            return
        if self.training_steps >= self._offset(tech) and not self.verbose[tech]:
            log_dist(f"{tech} is enabled at step {self.training_steps}")
            self.verbose[tech] = True
            if tech == WEIGHT_QUANTIZATION:
                self.weight_quantization_enabled = True

    def check_weight_quantization(self):
        self._check(WEIGHT_QUANTIZATION)

    def check_activation_quantization(self):
        # activation quantization is not a weight transform; the engine's
        # in-forward QDQ handles it — flag only
        pass

    def check_sparse_pruning(self):
        self._check(SPARSE_PRUNING)

    def check_head_pruning(self):
        self._check(HEAD_PRUNING)

    def check_row_pruning(self):
        self._check(ROW_PRUNING)

    def check_channel_pruning(self):
        self._check(CHANNEL_PRUNING)

    def check_all_modules(self):
        for tech in _TECHNIQUES:
            self._check(tech)

    def step(self, step_zero_check: bool = False):
        """Advance the step counter (reference increments then re-checks
        every technique's gate)."""
        if not step_zero_check:
            self.training_steps += 1
        self.check_all_modules()
