"""Elastic training (reference ``deepspeed/elasticity/``)."""

from deepspeed_tpu.elasticity.elastic_agent import (DSElasticAgent, heartbeat_age,
                                                    read_heartbeat, touch_heartbeat)
from deepspeed_tpu.elasticity.elasticity import (ElasticityConfig, ElasticityConfigError,
                                                 ElasticityError, ElasticityIncompatibleWorldSize,
                                                 compute_elastic_config, elasticity_enabled)

__all__ = ["compute_elastic_config", "elasticity_enabled", "ElasticityConfig", "ElasticityError",
           "ElasticityConfigError", "ElasticityIncompatibleWorldSize", "DSElasticAgent",
           "touch_heartbeat", "read_heartbeat", "heartbeat_age"]
