"""Elastic restart supervisor (reference ``elasticity/elastic_agent.py:28``
``DSElasticAgent``).

The reference plugs into torchelastic: it watches rendezvous membership,
tears the job down when a worker dies, and relaunches training at the
surviving world size, with DeepSpeed's elasticity config guaranteeing a
valid batch configuration at every size. On TPU there is no torchelastic;
the equivalent role is a LAUNCHER-LEVEL supervisor around a single-process
SPMD job:

* liveness = process exit code + a heartbeat file the training loop
  touches (a wedged accelerator backend hangs *inside* a dispatch, so
  exit-code monitoring alone never fires — heartbeat staleness is the
  TPU-shaped failure detector);
* recovery = respawn the training command at the surviving device count
  (``DS_ELASTIC_WORLD_SIZE`` env the script reads), with the elasticity
  batch math (``elasticity.compute_elastic_config``) validating the new
  size and the orbax checkpoint engine's cross-topology restore resuming
  from the last durable step.

The supervisor is deliberately command-agnostic: it runs any argv, so it
doubles as a bench/babysitter harness (a hung tunnel run gets killed and
retried instead of wedging the session).
"""

import json
import os
import signal
import subprocess
import sys
import time
from typing import Callable, Dict, List, Optional, Sequence

from deepspeed_tpu.utils.logging import logger

HEARTBEAT_ENV = "DS_ELASTIC_HEARTBEAT_FILE"
WORLD_ENV = "DS_ELASTIC_WORLD_SIZE"
RESTART_ENV = "DS_ELASTIC_RESTART_COUNT"


_LAST_TOUCH = {}  # path -> monotonic time of last touch (cadence throttle)


def touch_heartbeat(path: Optional[str] = None, min_interval: float = 0.0,
                    payload: Optional[Dict] = None) -> None:
    """Called by the training loop (each step / each checkpoint): refreshes
    the supervisor's liveness signal. No-op when not under an agent.

    ``min_interval``: skip the filesystem touch if this path was refreshed
    less than that many seconds ago — the engine's per-step call site runs
    cadenced (``resilience.heartbeat_interval``) so liveness costs one
    write per interval, not one per step, off the hot path. Supervisors
    must size ``heartbeat_timeout`` well above the producer's interval.

    The file carries a small JSON payload (pid, monotonic clock, wall
    time, plus caller fields — the engine sends ``global_step`` and the
    last telemetry span name) so a supervisor or ``tools/fault_bench.py``
    can report *how far* a child got, not just that it was alive; mtime
    stays the liveness clock (:func:`read_heartbeat` for the payload).

    A payload-less call on an existing file refreshes the mtime ONLY: a
    supervisor's backoff sleeps and bench arm-touches share the child's
    file and must not clobber the training process's progress record."""
    path = path or os.environ.get(HEARTBEAT_ENV)
    if not path:
        return
    if min_interval > 0.0:
        now = time.monotonic()
        if now - _LAST_TOUCH.get(path, float("-inf")) < min_interval:
            return
        _LAST_TOUCH[path] = now
    if payload is None and os.path.exists(path):
        os.utime(path, None)
        return
    data = {"pid": os.getpid(), "monotonic": time.monotonic(), "time": time.time()}
    if payload:
        data.update(payload)
    try:
        blob = json.dumps(data)
    except (TypeError, ValueError):  # unserializable caller field
        blob = json.dumps({k: data[k] for k in ("pid", "monotonic", "time")})
    # atomic publish: a SIGKILL (or a supervisor read) landing mid-write
    # must never see a truncated record — the post-mortem payload is the
    # whole point of the file
    tmp = f"{path}.tmp.{os.getpid()}"
    try:
        with open(tmp, "w") as fh:
            fh.write(blob)
        os.replace(tmp, path)
    finally:
        try:
            os.unlink(tmp)
        except OSError:
            pass
    os.utime(path, None)


def read_heartbeat(path: Optional[str] = None) -> Optional[Dict]:
    """The last heartbeat payload, or None (missing file / pre-payload
    empty file / torn write — a reader must never crash on liveness
    metadata)."""
    path = path or os.environ.get(HEARTBEAT_ENV)
    if not path:
        return None
    try:
        with open(path) as fh:
            blob = fh.read()
    except OSError:
        return None
    if not blob.strip():
        return None
    try:
        data = json.loads(blob)
    except json.JSONDecodeError:
        return None
    return data if isinstance(data, dict) else None


def heartbeat_age(path: Optional[str] = None,
                  now: Optional[float] = None) -> Optional[float]:
    """Seconds since the heartbeat file was last touched, or None when
    there is no file (never started / already reaped). Mtime is the
    liveness clock — the payload's ``monotonic`` field is the *writer's*
    clock and only comparable in-host; mtime staleness is what both the
    supervisor's hang detector and the fleet router's liveness probe
    compare against their timeout."""
    path = path or os.environ.get(HEARTBEAT_ENV)
    if not path:
        return None
    try:
        mtime = os.stat(path).st_mtime
    except OSError:
        return None
    return max(0.0, (now if now is not None else time.time()) - mtime)


class DSElasticAgent:
    """Supervise a training command; on death or heartbeat silence, restart
    it at the next world size.

    Args:
        cmd: argv of the training job. It must read ``DS_ELASTIC_WORLD_SIZE``
            (device count to train at), call :func:`touch_heartbeat`
            regularly, and resume from its checkpoint dir on start.
        world_sizes: descending ladder of world sizes to try — index
            ``restart_count`` is used (clamped to the last entry). The
            training config's elasticity block should admit each size
            (``compute_elastic_config`` raises otherwise — validate with
            :meth:`validate_world_sizes`).
        heartbeat_timeout: seconds of heartbeat silence before the child is
            declared hung and killed (the wedge detector).
        max_restarts: give up after this many restarts.
        env: extra environment for the child.
        on_restart: callback ``(restart_count, world_size) -> None``.
        checkpoint_dir: the job's checkpoint dir. When set, every attempt's
            history row records the old→new topology transition — the
            stamped world size of the newest intact tag vs the attempt's
            target world — and whether the relaunch resumes plain,
            reshards (graft-elastic ``resume_elastic``), or starts fresh.
            Read from ``metadata.json`` stamps only: the supervisor never
            opens checkpoint state (and never initializes jax).
    """

    def __init__(self, cmd: Sequence[str], world_sizes: Sequence[int],
                 heartbeat_timeout: float = 60.0, max_restarts: int = 3,
                 env: Optional[dict] = None, poll_interval: float = 0.5,
                 startup_timeout: Optional[float] = None,
                 on_restart: Optional[Callable[[int, int], None]] = None,
                 checkpoint_dir: Optional[str] = None):
        assert world_sizes, "world_sizes ladder must be non-empty"
        self.cmd = list(cmd)
        self.world_sizes = list(world_sizes)
        self.checkpoint_dir = checkpoint_dir
        self.heartbeat_timeout = float(heartbeat_timeout)
        # a child cannot heartbeat until backend init + first-step compile
        # finish (minutes on a cold cache) — the staleness clock before the
        # FIRST touch uses this longer budget so a healthy-but-compiling
        # child is not declared hung and killed into a restart cascade
        self.startup_timeout = (float(startup_timeout) if startup_timeout is not None
                                else max(self.heartbeat_timeout, 1800.0))
        self.max_restarts = int(max_restarts)
        self.env = dict(env or {})
        self.poll_interval = float(poll_interval)
        self.on_restart = on_restart
        self.restart_count = 0
        self.history: List[dict] = []

    def validate_world_sizes(self, ds_config: dict) -> None:
        """Check every ladder entry admits a valid elastic batch config
        (reference: torchelastic would rendezvous into an invalid size and
        die late; here it fails before the first launch)."""
        from deepspeed_tpu.elasticity.elasticity import compute_elastic_config
        for w in self.world_sizes:
            compute_elastic_config(ds_config, world_size=w)

    def _spawn(self, world_size: int, heartbeat_path: str) -> subprocess.Popen:
        env = dict(os.environ)
        env.update(self.env)
        env[WORLD_ENV] = str(world_size)
        env[HEARTBEAT_ENV] = heartbeat_path
        env[RESTART_ENV] = str(self.restart_count)
        # drop the previous attempt's progress record so a child that dies
        # before its first touch is not credited with the old payload; the
        # fresh base record carries OUR pid, which _run filters out
        try:
            os.unlink(heartbeat_path)
        except OSError:
            pass
        touch_heartbeat(heartbeat_path)  # fresh clock for the new child
        return subprocess.Popen(self.cmd, env=env,
                                start_new_session=True)  # own group: kill cleanly

    def _kill(self, proc: subprocess.Popen) -> None:
        """Terminate a hung child and its process group. NB on a real TPU
        tunnel this is the claim-holder hazard (PERF.md wedge #3/#4): the
        supervisor kills only AFTER the heartbeat declared the backend
        already dead/hung — at that point the claim is lost either way and
        restart is the only path forward."""
        try:
            os.killpg(os.getpgid(proc.pid), signal.SIGTERM)
            try:
                proc.wait(timeout=10)
                return
            except subprocess.TimeoutExpired:
                os.killpg(os.getpgid(proc.pid), signal.SIGKILL)
        except ProcessLookupError:
            pass
        try:
            proc.wait(timeout=10)
        except subprocess.TimeoutExpired:
            logger.error("elastic agent: child survived SIGKILL; abandoning it")

    def run(self, workdir: Optional[str] = None) -> int:
        """Supervise until the job exits 0, or restarts are exhausted.
        Returns the final exit code (0 on success)."""
        workdir = workdir or os.getcwd()
        # unique per-agent file: two supervisors sharing a workdir must not
        # keep each other's heartbeat fresh (masked hangs)
        heartbeat_path = os.path.join(workdir, f".ds_elastic_heartbeat.{os.getpid()}")
        try:
            return self._run(heartbeat_path)
        finally:
            try:
                os.unlink(heartbeat_path)
            except OSError:
                pass

    def _resume_decision(self, world: int) -> Optional[Dict]:
        """How this attempt will come back up (plain / reshard / fresh),
        from checkpoint metadata stamps alone. None without a
        ``checkpoint_dir``; never raises — a supervisor's bookkeeping must
        not take down a restartable job."""
        if not self.checkpoint_dir:
            return None
        try:
            from deepspeed_tpu.runtime.elastic.agent import decide_resume
            return decide_resume(self.checkpoint_dir, world)
        except Exception as e:  # noqa: BLE001 — diagnostics only
            logger.warning(f"elastic agent: cannot read checkpoint topology: {e}")
            return None

    def _run(self, heartbeat_path: str) -> int:
        prev_world: Optional[int] = None
        while True:
            idx = min(self.restart_count, len(self.world_sizes) - 1)
            world = self.world_sizes[idx]
            decision = self._resume_decision(world)
            logger.info(f"elastic agent: launching attempt {self.restart_count + 1} "
                     f"at world size {world}"
                     + (f" ({decision['resume']} resume from tag {decision['tag']}"
                        + (f", reshard {decision['ckpt_world']} -> {world}"
                           if decision["resume"] == "reshard" else "")
                        + ")" if decision else ""))
            t0 = time.time()
            proc = self._spawn(world, heartbeat_path)
            armed_mtime = os.path.getmtime(heartbeat_path)
            rc: Optional[int] = None
            reason = ""
            while True:
                rc = proc.poll()
                if rc is not None:
                    reason = f"exit rc={rc}"
                    break
                try:
                    mt = os.path.getmtime(heartbeat_path)
                except FileNotFoundError:
                    # deleted out from under us (workdir cleanup): recreate
                    # and keep supervising rather than crashing and orphaning
                    # the live child
                    touch_heartbeat(heartbeat_path)
                    armed_mtime = os.path.getmtime(heartbeat_path)
                    continue
                age = time.time() - mt
                # before the child's first touch, the mtime is still our own
                # arm-touch: apply the startup budget (backend init + cold
                # compile), not the steady-state step budget
                budget = self.startup_timeout if mt <= armed_mtime else self.heartbeat_timeout
                if age > budget:
                    phase = "startup" if mt <= armed_mtime else "heartbeat"
                    reason = f"{phase} silent {age:.1f}s (hung backend)"
                    self._kill(proc)
                    # a graceful SIGTERM handler may exit 0 — the AGENT
                    # declared this attempt dead; rc must reflect that or a
                    # 5%-done job would be reported as finished
                    rc = proc.returncode if proc.returncode not in (None, 0) else -9
                    break
                time.sleep(self.poll_interval)
            # the payload says how far the child got (global_step + last
            # telemetry span) — restart logs and post-mortems report
            # progress, not just liveness
            hb = read_heartbeat(heartbeat_path)
            if hb and hb.get("pid") == os.getpid():
                hb = None  # our own arm-touch record: the child never reported
            progress = ({k: hb[k] for k in ("global_step", "last_span", "pid",
                                            "world_size", "mesh_axes")
                         if k in hb} if hb else None)
            row = dict(world_size=world, rc=rc, reason=reason,
                       duration_s=round(time.time() - t0, 2),
                       last_heartbeat=progress)
            # old→new topology record: what this attempt resumed from and
            # how (plain / reshard / fresh) — restart logs and post-mortems
            # narrate fleet reshapes, not just exit codes. The row always
            # carries the full documented key set; without a checkpoint_dir
            # the decision fields stay None (resume mode unobservable).
            topo = dict(prev_world_size=prev_world, world_size=world,
                        resume=None, tag=None, ckpt_world=None, ckpt_axes=None)
            topo.update(decision or {})
            row["topology"] = topo
            self.history.append(row)
            prev_world = world
            if rc == 0:
                logger.info(f"elastic agent: job finished at world size {world}")
                return 0
            if self.restart_count >= self.max_restarts:
                logger.error(f"elastic agent: giving up after {self.restart_count + 1} "
                             f"attempts ({reason})")
                return rc if rc is not None else 1
            self.restart_count += 1
            next_world = self.world_sizes[min(self.restart_count, len(self.world_sizes) - 1)]
            logger.info(f"elastic agent: attempt failed ({reason}"
                        + (f"; last progress {progress}" if progress else "")
                        + f"); restarting at world size {next_world}")
            if self.on_restart is not None:
                self.on_restart(self.restart_count, next_world)

# NB: this module deliberately uses plain `logger`, never `log_dist` —
# log_dist resolves the process index, which initializes the jax backend;
# a supervisor must stay alive when the accelerator is exactly what's hung.
