"""Elastic training: chip-count-agnostic batch configuration
(reference ``deepspeed/elasticity/elasticity.py``: v0.1 ``:83``, v0.2
``:126``, ``compute_elastic_config`` ``:233``).

Same algorithm, TPU vocabulary: "gpus" → chips, node = TPU host (the v0.2
granularity constraint maps to chips-per-host). Wiring: ``DeepSpeedConfig``
applies the elastic plan to the batch triangle when the block is enabled
(``runtime/config.py:_apply_elastic_config``), and ``bin/ds_elastic``
explores valid chip counts offline. Elastic *recovery* is the
checkpoint-reshape path (orbax cross-topology restore,
``runtime/checkpoint_engine/orbax_engine.py``): resharding a saved state
onto a different mesh is how a TPU job resumes at a new world size.
"""

import math
from functools import reduce
from typing import Dict, List

LATEST_ELASTICITY_VERSION = 0.2
MINIMUM_DEEPSPEED_VERSION = "0.3.8"
ENABLED = "enabled"
ENABLED_DEFAULT = False


class ElasticityError(Exception):
    """Base (reference ``elasticity/constants.py`` error family)."""


class ElasticityConfigError(ElasticityError):
    pass


class ElasticityIncompatibleWorldSize(ElasticityError):
    pass


class ElasticityConfig:
    """Reference ``elasticity/config.py``: typed view of the elasticity
    config block."""

    def __init__(self, param_dict: Dict):
        self.enabled = param_dict.get(ENABLED, ENABLED_DEFAULT)
        if self.enabled:
            if "max_train_batch_size" not in param_dict:
                raise ElasticityConfigError("Max train batch size is needed for elasticity")
            if "micro_batch_sizes" not in param_dict:
                raise ElasticityConfigError("Micro batch sizes are needed for elasticity")
        self.max_acceptable_batch_size = param_dict.get("max_train_batch_size", 0)
        self.micro_batches = param_dict.get("micro_batch_sizes", [])
        if not isinstance(self.micro_batches, list) or not all(
                isinstance(m, int) and m > 0 for m in self.micro_batches):
            raise ElasticityConfigError(f"micro_batch_sizes must be positive ints, got "
                                        f"{self.micro_batches}")
        self.min_gpus = param_dict.get("min_gpus", 1)
        self.max_gpus = param_dict.get("max_gpus", -1)
        if self.min_gpus < 1 or (self.max_gpus != -1 and self.max_gpus < self.min_gpus):
            raise ElasticityConfigError(f"invalid min/max chips: {self.min_gpus}/{self.max_gpus}")
        self.min_time = param_dict.get("min_time", 0)
        self.version = param_dict.get("version", LATEST_ELASTICITY_VERSION)
        self.prefer_larger_batch_size = param_dict.get("prefer_larger_batch", True)
        self.ignore_non_elastic_batch_info = param_dict.get("ignore_non_elastic_batch_info", False)
        self.num_gpus_per_node = param_dict.get("num_gpus_per_node", 1)
        self.model_parallel_size = param_dict.get("model_parallel_size", 1)


def get_candidate_batch_sizes(base_list: List[int], max_acceptable_batch_size: int) -> List[int]:
    """Reference ``:27``: largest multiple of each base ≤ max."""
    candidates = set()
    for base in base_list:
        if base <= max_acceptable_batch_size:
            candidates.add((max_acceptable_batch_size // base) * base)
    return sorted(candidates)


def get_valid_gpus(batch_size: int, micro_batches: List[int], min_valid_gpus: int,
                   max_valid_gpus: int) -> List[int]:
    """Reference ``:41``: chip counts n such that some micro-batch divides
    batch_size/n evenly."""
    valid = []
    for n in range(min_valid_gpus, max_valid_gpus + 1):
        if batch_size % n != 0:
            continue
        per = batch_size // n
        if any(per % mb == 0 for mb in micro_batches):
            valid.append(n)
    return valid


def get_best_candidates(candidate_batch_sizes, micro_batches, min_gpus, max_gpus, prefer_larger):
    """Reference ``:63``: most compatible chip counts wins; ties prefer the
    larger (or smaller) batch."""
    max_valid_gpus = 0
    valid_gpus = None
    final_batch_size = int(min(micro_batches))
    for batch_size in candidate_batch_sizes:
        current = get_valid_gpus(batch_size, micro_batches, min_gpus, max_gpus)
        if len(current) > max_valid_gpus or (len(current) == max_valid_gpus and
                                             ((prefer_larger and batch_size > final_batch_size) or
                                              (not prefer_larger and batch_size < final_batch_size))):
            max_valid_gpus = len(current)
            valid_gpus = current
            final_batch_size = batch_size
    return final_batch_size, valid_gpus


def _get_compatible_gpus_v01(micro_batches, max_acceptable_batch_size, min_gpus=None, max_gpus=None,
                             prefer_larger=True):
    """Reference ``:83``: LCM + per-micro-batch bases, brute-force count."""
    min_gpus = min_gpus or 1
    max_gpus = max_gpus or max_acceptable_batch_size // min(micro_batches)
    if not all(mb <= max_acceptable_batch_size for mb in micro_batches):
        raise ValueError("All micro batches must be <= max_acceptable_batch_size")
    lcm = reduce(math.lcm, micro_batches)
    base_list = list(micro_batches) + [lcm]
    candidates = get_candidate_batch_sizes(base_list, max_acceptable_batch_size)
    return get_best_candidates(candidates, micro_batches, min_gpus, max_gpus, prefer_larger)


def _get_compatible_gpus_v02(micro_batches, max_acceptable_batch_size, current_num_gpus,
                             min_gpus=None, max_gpus=None, prefer_larger=True, num_gpus_per_node=1,
                             model_parallel_size=1):
    """Reference ``:126``: node-granular world sizes under model parallelism."""
    if num_gpus_per_node % model_parallel_size != 0:
        raise ElasticityError(f"chips per host {num_gpus_per_node} must be divisible by model "
                              f"parallel size {model_parallel_size}")
    dp_size_per_node = num_gpus_per_node // model_parallel_size
    final_batch_size, valid_world_size = _get_compatible_gpus_v01(
        micro_batches, int(max_acceptable_batch_size / dp_size_per_node),
        (min_gpus or 1) // num_gpus_per_node or 1,
        (max_gpus or max_acceptable_batch_size // min(micro_batches)) // num_gpus_per_node,
        prefer_larger)
    final_batch_size = int(final_batch_size) * dp_size_per_node
    valid_dp_world_sizes = [i * dp_size_per_node for i in valid_world_size]
    valid_world_sizes = [i * model_parallel_size for i in valid_dp_world_sizes]
    if current_num_gpus // model_parallel_size in valid_dp_world_sizes:
        micro = None
        for mb in micro_batches:
            if final_batch_size // (current_num_gpus // model_parallel_size) % mb == 0:
                if micro is None or (prefer_larger and mb > micro):
                    micro = mb
        return final_batch_size, valid_world_sizes, micro
    raise ElasticityIncompatibleWorldSize(
        f"world size {current_num_gpus} with MP {model_parallel_size} is not in the valid set "
        f"{valid_world_sizes}")


def elasticity_enabled(ds_config: dict) -> bool:
    return ds_config.get("elasticity", {}).get(ENABLED, ENABLED_DEFAULT)


def compute_elastic_config(ds_config: dict, target_deepspeed_version: str = "", world_size: int = 0,
                           return_microbatch: bool = False):
    """Reference ``:233``: resolve the elastic batch plan; validates the
    current world size when given."""
    elastic_config_dict = ds_config.get("elasticity", {})
    elastic_config = ElasticityConfig(elastic_config_dict)
    if not elastic_config.enabled:
        raise ElasticityConfigError("elasticity is not enabled in the config")

    if float(elastic_config.version) == 0.1:
        final_batch_size, valid_gpus = _get_compatible_gpus_v01(
            micro_batches=elastic_config.micro_batches,
            max_acceptable_batch_size=elastic_config.max_acceptable_batch_size,
            min_gpus=elastic_config.min_gpus,
            max_gpus=None if elastic_config.max_gpus == -1 else elastic_config.max_gpus,
            prefer_larger=elastic_config.prefer_larger_batch_size)
        micro_batch = None
        if world_size > 0:
            if world_size not in valid_gpus:
                raise ElasticityIncompatibleWorldSize(f"world size {world_size} not in valid set "
                                                      f"{valid_gpus}")
            if return_microbatch:
                per = final_batch_size // world_size
                cands = [mb for mb in elastic_config.micro_batches if per % mb == 0]
                micro_batch = max(cands) if elastic_config.prefer_larger_batch_size else min(cands)
        if return_microbatch:
            return final_batch_size, valid_gpus, micro_batch
        return final_batch_size, valid_gpus
    if float(elastic_config.version) == 0.2:
        final_batch_size, valid_gpus, micro_batch = _get_compatible_gpus_v02(
            micro_batches=elastic_config.micro_batches,
            max_acceptable_batch_size=elastic_config.max_acceptable_batch_size,
            current_num_gpus=world_size or elastic_config.num_gpus_per_node,
            min_gpus=elastic_config.min_gpus,
            max_gpus=None if elastic_config.max_gpus == -1 else elastic_config.max_gpus,
            prefer_larger=elastic_config.prefer_larger_batch_size,
            num_gpus_per_node=elastic_config.num_gpus_per_node,
            model_parallel_size=elastic_config.model_parallel_size)
        if return_microbatch:
            return final_batch_size, valid_gpus, micro_batch
        return final_batch_size, valid_gpus
    raise ElasticityConfigError(f"unknown elasticity version {elastic_config.version}")
