"""Environment/compatibility report (reference ``deepspeed/env_report.py`` +
``bin/ds_report``): versions, accelerator status, and the op-builder
compatibility matrix, so users can see at a glance what this install can do.

The accelerator probe runs in a subprocess under a timeout: a wedged TPU
plugin must degrade the report, not hang it (the reference equivalent is
``real_accelerator`` probing with try/except, ``real_accelerator.py:90``).
"""

import json
import os
import shutil
import subprocess
import sys

GREEN_OK = "\033[92m[OKAY]\033[0m"
RED_NO = "\033[91m[NO]\033[0m"


def _versions() -> dict:
    out = {"python": sys.version.split()[0]}
    for mod in ("jax", "jaxlib", "flax", "optax", "orbax.checkpoint", "numpy"):
        try:
            m = __import__(mod)
            for part in mod.split(".")[1:]:
                m = getattr(m, part)
            out[mod] = getattr(m, "__version__", "?")
        except Exception:
            out[mod] = "not installed"
    try:
        from deepspeed_tpu.version import __version__ as v
        out["deepspeed_tpu"] = v
    except Exception:
        out["deepspeed_tpu"] = "?"
    return out


def _probe_accelerator(timeout: int = 45) -> dict:
    code = ("import jax,json;"
            "print(json.dumps({'backend': jax.default_backend(),"
            "'devices': [str(d) for d in jax.devices()]}))")
    try:
        p = subprocess.run([sys.executable, "-c", code], capture_output=True,
                           text=True, timeout=timeout, env=dict(os.environ))
        for line in reversed(p.stdout.strip().splitlines()):
            if line.startswith("{"):
                return json.loads(line)
        return {"error": (p.stderr.strip().splitlines() or ["no output"])[-1]}
    except subprocess.TimeoutExpired:
        return {"error": f"accelerator probe timed out after {timeout}s "
                         "(TPU plugin unreachable?)"}
    except Exception as e:
        return {"error": f"{type(e).__name__}: {e}"}


def _op_compat() -> list:
    """(name, compatible, detail) per registered op builder (reference
    ds_report's op compatibility matrix over ALL_OPS)."""
    rows = []
    try:
        from deepspeed_tpu.ops.op_builder import ALL_BUILDERS
        for name, builder_cls in sorted(ALL_BUILDERS.items()):
            try:
                b = builder_cls()
                compat = b.is_compatible()
                ok, why = compat if isinstance(compat, tuple) else (bool(compat), "")
                if ok and not why:
                    why = f"compiler={b.compiler()}"
                rows.append((name, ok, why))
            except Exception as e:
                rows.append((name, False, f"{type(e).__name__}: {e}"))
    except Exception as e:
        rows.append(("op_builder registry", False, str(e)))
    return rows


def _toolchain() -> list:
    return [(tool, shutil.which(tool) or "not found")
            for tool in ("g++", "cmake", "ninja", "make")]


def main(argv=None) -> int:
    print("-" * 74)
    print("DeepSpeed-TPU environment report (ds_report)")
    print("-" * 74)
    print("\nversions:")
    for k, v in _versions().items():
        print(f"  {k:<18} {v}")
    print("\naccelerator:")
    acc = _probe_accelerator()
    if "error" in acc:
        print(f"  {RED_NO} {acc['error']}")
    else:
        print(f"  {GREEN_OK} backend={acc['backend']} devices={len(acc['devices'])}")
        for d in acc["devices"][:8]:
            print(f"         {d}")
    print("\nnative toolchain:")
    for tool, path in _toolchain():
        mark = GREEN_OK if path != "not found" else RED_NO
        print(f"  {mark} {tool:<8} {path}")
    print("\nop builder compatibility:")
    for name, ok, why in _op_compat():
        print(f"  {GREEN_OK if ok else RED_NO} {name:<22} {why}")
    print("-" * 74)
    return 0


if __name__ == "__main__":
    sys.exit(main())
