from deepspeed_tpu.inference.config import DeepSpeedInferenceConfig
from deepspeed_tpu.inference.engine import InferenceEngine
from deepspeed_tpu.inference.entry import init_inference
from deepspeed_tpu.inference import fleet, serving

__all__ = ["DeepSpeedInferenceConfig", "InferenceEngine", "init_inference",
           "fleet", "serving"]
