"""Inference config (reference ``deepspeed/inference/config.py``,
``DeepSpeedInferenceConfig``): same knob vocabulary, TPU semantics.

CUDA-specific fields (``enable_cuda_graph``, ``use_triton`` etc.) are
accepted and ignored with a note — jit compilation already gives the
capture/replay behavior CUDA graphs add."""

from typing import Any, Dict, Optional, Union

import jax.numpy as jnp
from pydantic import Field, field_validator

from deepspeed_tpu.runtime.config_utils import DeepSpeedConfigModel

from deepspeed_tpu.runtime.config_utils import dtype_names

_DTYPES = dtype_names()


class DeepSpeedTPConfig(DeepSpeedConfigModel):
    """Reference ``DeepSpeedTPConfig``."""
    enabled: bool = True
    tp_size: int = 1
    mpu: Optional[Any] = None
    tp_group: Optional[Any] = None


class DeepSpeedMoEConfig(DeepSpeedConfigModel):
    """Reference ``DeepSpeedMoEConfig`` (inference)."""
    enabled: bool = True
    ep_size: int = 1
    moe_experts: Union[int, list] = Field(1, alias="num_experts")
    type: str = "standard"


class QuantizationConfig(DeepSpeedConfigModel):
    enabled: bool = False
    bits: int = 8
    group_size: int = 64


class DeepSpeedInferenceConfig(DeepSpeedConfigModel):
    """Reference ``inference/config.py`` surface."""

    replace_with_kernel_inject: bool = Field(False, alias="kernel_inject")
    dtype: Any = None
    tensor_parallel: DeepSpeedTPConfig = Field(default_factory=DeepSpeedTPConfig, alias="tp")
    moe: Union[bool, DeepSpeedMoEConfig] = Field(default_factory=DeepSpeedMoEConfig)
    quant: QuantizationConfig = Field(default_factory=QuantizationConfig)
    checkpoint: Optional[Union[str, Dict]] = None
    base_dir: str = ""
    max_tokens: int = Field(1024, alias="max_out_tokens")
    min_out_tokens: int = Field(1, alias="min_tokens")
    max_new_tokens: int = 64
    injection_policy: Optional[Dict] = Field(None, alias="injection_dict")
    replace_method: str = Field("auto", json_schema_extra={"deprecated": True})
    # CUDA-era knobs: accepted, ignored (jit subsumes graph capture)
    enable_cuda_graph: bool = False
    use_triton: bool = False
    triton_autotune: bool = False
    # TPU-native extras
    use_flash_prefill: bool = False  # Pallas flash attention for prefill
    batch_size: int = 1

    @field_validator("dtype", mode="before")
    @classmethod
    def _resolve_dtype(cls, v):
        if v is None or isinstance(v, str) and v in ("", "auto"):
            return None
        if isinstance(v, str):
            key = v.lower().replace("torch.", "")
            if key not in _DTYPES:
                raise ValueError(f"unknown dtype {v!r}; accepted: {sorted(_DTYPES)}")
            return _DTYPES[key]
        # torch dtype objects arrive as e.g. torch.float16
        s = str(v).replace("torch.", "").lower()
        return _DTYPES.get(s, v)

    @field_validator("moe", mode="before")
    @classmethod
    def _moe_bool(cls, v):
        if isinstance(v, bool):
            return DeepSpeedMoEConfig(enabled=v)
        return v
