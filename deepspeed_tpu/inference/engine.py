"""InferenceEngine: jitted serving with TP sharding and a KV-cache decode
loop (reference ``deepspeed/inference/engine.py:89`` ``InferenceEngine``).

TPU-native redesign of the reference's serving path:

* MP/TP group creation (``engine.py:259``) → a ``tensor`` mesh axis; weights
  are placed by logical-axis rules or AutoTP (``module_inject`` here).
* Kernel injection (``engine.py:413`` → fused CUDA decode ops,
  ``pt_binding.cpp:1935-1975``) → the model's fused decode path (static KV
  cache + masked attention) compiled by XLA, optionally with the Pallas
  flash kernel for prefill.
* CUDA-graph capture/replay (``engine.py:532,551``) → ``jax.jit``: the
  decode step is one compiled program reused every token.
* ``generate`` runs prefill + a ``lax.while_loop`` token loop entirely on
  device, with greedy/temperature/top-k/top-p sampling and EOS early exit.
"""

from typing import Any, Optional

import numpy as np

import jax
import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec as P

import flax.linen as nn

from deepspeed_tpu import comm as dist
from deepspeed_tpu.inference.config import DeepSpeedInferenceConfig
from deepspeed_tpu.models.common import init_cache
from deepspeed_tpu.module_inject.replace_module import replace_transformer_layer, tp_shard_params
from deepspeed_tpu.parallel.topology import MeshTopology, set_topology
from deepspeed_tpu.utils.logging import log_dist


def _load_checkpoint_params(spec, base_dir: str = ""):
    """Load serving weights named by ``config.checkpoint`` (reference
    ``InferenceEngine`` checkpoint loading, ``inference/engine.py:336``).

    Accepts a consolidated ``.npz`` (``save_16bit_model`` /
    ``zero_to_fp32`` output), an engine ``save_checkpoint`` directory
    (``latest``/tag orbax checkpoint — consolidated on the fly), or a dict
    ``{"checkpoint_dir"|"path": ..., "tag": ...}``.
    """
    import os

    from deepspeed_tpu.checkpoint.zero_to_fp32 import (
        WEIGHTS_NAME, get_fp32_state_dict_from_zero_checkpoint, load_state_dict_from_npz)

    tag, original = None, spec
    if isinstance(spec, dict):
        tag = spec.get("tag")
        spec = spec.get("checkpoint_dir") or spec.get("path")
    if not isinstance(spec, str) or not spec:
        raise ValueError(f"unsupported checkpoint spec {original!r}: pass a .npz path, an "
                         f"engine checkpoint dir, or {{'checkpoint_dir': ..., 'tag': ...}}")
    path = os.path.join(base_dir, spec) if base_dir else spec
    if path.endswith(".npz"):
        if not os.path.isfile(path):
            raise ValueError(f"checkpoint npz {path!r} does not exist")
        params = load_state_dict_from_npz(path)
    elif os.path.isdir(path) and (tag is not None or os.path.exists(os.path.join(path, "latest"))):
        params = get_fp32_state_dict_from_zero_checkpoint(path, tag=tag)
    elif os.path.isdir(path) and os.path.isfile(os.path.join(path, WEIGHTS_NAME)):
        params = load_state_dict_from_npz(path)
    else:
        raise ValueError(f"checkpoint path {path!r} is neither a .npz file nor a "
                         f"checkpoint directory (no 'latest', no {WEIGHTS_NAME})")
    log_dist(f"inference weights loaded from {path}")
    return params


def _unwrap_logits(out):
    """MoE models return (logits, aux_loss); serving wants the logits."""
    if isinstance(out, (tuple, list)):
        return out[0]
    return out


def sample_logits(logits, rng, do_sample: bool, temperature: float, top_k: int, top_p: float):
    """Next-token selection on [B, V] logits (greedy or filtered sampling)."""
    if not do_sample:
        return jnp.argmax(logits, axis=-1)
    logits = logits / jnp.maximum(temperature, 1e-6)
    if top_k > 0:
        k = min(int(top_k), logits.shape[-1])  # clamp to vocab
        kth = jnp.sort(logits, axis=-1)[:, -k][:, None]
        logits = jnp.where(logits < kth, -jnp.inf, logits)
    if top_p < 1.0:
        sorted_logits = jnp.sort(logits, axis=-1)[:, ::-1]
        probs = jax.nn.softmax(sorted_logits, axis=-1)
        cum = jnp.cumsum(probs, axis=-1)
        # smallest set with cumulative prob >= top_p; find threshold logit.
        # Pinned edge cases (graft-serve satellite): an EMPTY nucleus —
        # top_p <= 0, or a low temperature concentrating cum[0] ~ 1.0 above
        # top_p — keeps cutoff_idx at 0, i.e. falls back to the single
        # argmax token (never a NaN renormalization over an empty support);
        # the clip handles the opposite edge, where rounding keeps cum
        # strictly below top_p forever and the unclipped index would walk
        # off the vocab axis.
        cutoff_idx = jnp.sum(cum < top_p, axis=-1, keepdims=True)
        cutoff_idx = jnp.minimum(cutoff_idx, logits.shape[-1] - 1)
        cutoff = jnp.take_along_axis(sorted_logits, cutoff_idx, axis=-1)
        logits = jnp.where(logits < cutoff, -jnp.inf, logits)
    return jax.random.categorical(rng, logits, axis=-1)


class InferenceEngine:
    """Serving wrapper. ``engine(input_ids)`` → logits;
    ``engine.generate(input_ids, ...)`` → generated token ids."""

    def __init__(self,
                 model: nn.Module,
                 config: DeepSpeedInferenceConfig,
                 params: Optional[Any] = None,
                 topology: Optional[MeshTopology] = None,
                 seed: int = 0):
        if not dist.is_initialized():
            dist.init_distributed(verbose=False)
        self.config = config

        # -- mesh: tensor axis from tp_size, rest data (engine.py:259)
        if topology is None:
            tp = max(1, config.tensor_parallel.tp_size)
            n = jax.device_count()
            if n % tp != 0:
                raise ValueError(f"tp_size {tp} must divide device count {n}")
            topology = MeshTopology(tensor=tp, data=n // tp, fsdp=1)
        self.topology = topology
        self.mesh = topology.mesh
        set_topology(topology)

        # -- injection policy (engine.py:413)
        self.module = replace_transformer_layer(model, config)
        self.mcfg = getattr(self.module, "config", None)

        self._rng = jax.random.PRNGKey(seed)
        example = jnp.zeros((1, 8), jnp.int32)
        from deepspeed_tpu.models.common import is_seq2seq_module
        self._is_seq2seq = is_seq2seq_module(self.module)
        example_extra = {"decoder_input_ids": example} if self._is_seq2seq else {}

        if params is None and config.checkpoint is not None:
            params = _load_checkpoint_params(config.checkpoint, config.base_dir)
        if params is None:
            params = self.module.init(self._rng, example, **example_extra)["params"]
        # callers may hand in boxed trees straight from model.init(); the
        # TP spec derivation below needs raw arrays (boxed leaves have no
        # .shape, so every spec would silently fall back to replicated)
        params = nn.meta.unbox(params)
        # int8 dtype means QUANTIZED weights (reference dtype=torch.int8):
        # floats are cast to the serve dtype here and quantized after TP
        # sharding below — a raw astype(int8) would destroy the weights
        quant_on = bool(config.quant.enabled) or config.dtype == jnp.int8
        cast_dtype = (jnp.bfloat16 if config.dtype == jnp.int8 else config.dtype)
        if cast_dtype is not None:
            params = jax.tree.map(
                lambda p: p.astype(cast_dtype) if jnp.issubdtype(p.dtype, jnp.floating) else p, params)
        # -- TP weight placement (ReplaceWithTensorSlicing / AutoTP)
        self.params, self.param_specs = tp_shard_params(params, self.module, topology, example,
                                                        policy=config.injection_policy)

        # -- int8 weight quantization (reference WeightQuantization applied
        # at checkpoint load; here on the already-sharded tree, engine.py:299)
        self._wq_scales = None
        self._serve_dtype = cast_dtype or jnp.float32
        if quant_on:
            from deepspeed_tpu.runtime.weight_quantizer import WeightQuantization
            # mp_size=1: JAX sharded arrays keep their GLOBAL shape, so the
            # reference's local-shard ratio recovery must not re-multiply
            wq = WeightQuantization(mp_size=1)
            self.params, self._wq_scales = wq.model_quantize(
                self.params, quantize_bits=config.quant.bits,
                group_size=max(1, config.quant.group_size))

        self._forward_fn = None
        self._prefill_fn = None
        self._decode_fn = None
        self._max_len = self._model_max_len()
        log_dist(f"InferenceEngine: tp={topology.tensor_parallel_size} "
                 f"dtype={getattr(config.dtype, '__name__', 'model-default')} max_len={self._max_len}")

    # ------------------------------------------------------------------
    def _model_max_len(self):
        for attr in ("max_position_embeddings", "n_positions"):
            v = getattr(self.mcfg, attr, None)
            if v is not None:
                return int(v)
        return self.config.max_tokens

    def _place_batch(self, ids):
        """Shard the batch over the data axes when it divides evenly —
        otherwise serve replicated (small/odd batches)."""
        dp = self.topology.data_parallel_size
        if dp > 1 and ids.shape[0] % dp == 0:
            return jax.device_put(ids, NamedSharding(self.mesh, P(("expert", "data", "fsdp"))))  # graft-lint: waive R008 inference batch, never donated
        return jax.device_put(ids, NamedSharding(self.mesh, P()))  # graft-lint: waive R008 inference batch, never donated

    def _mparams(self, params):
        """Runtime view of the weights: dequantizes int8 leaves in-graph
        (the HBM copy stays int8; XLA materializes the serve-dtype view
        per program, reference dequant-gemm kernels)."""
        if self._wq_scales is None:
            return params
        from deepspeed_tpu.runtime.weight_quantizer import dequantize_tree
        return dequantize_tree(params, self._wq_scales, self._serve_dtype)

    def _apply_decode(self, params, cache, ids):
        """One cached decode step; single source of the MoE logits unwrap."""
        logits, upd = self.module.apply({"params": self._mparams(params), "cache": cache},
                                        ids, decode=True, mutable=["cache"])
        return _unwrap_logits(logits), upd

    # ------------------------------------------------------------------
    def forward(self, input_ids, **kwargs):
        """Full-sequence logits (no cache) — reference ``engine.py:592``."""
        if self._forward_fn is None:
            def fwd(params, ids):
                return _unwrap_logits(self.module.apply({"params": self._mparams(params)}, ids))
            self._forward_fn = jax.jit(fwd)
        ids = self._place_batch(jnp.asarray(np.asarray(input_ids), jnp.int32))
        return self._forward_fn(self.params, ids)

    __call__ = forward

    # ------------------------------------------------------------------
    # serving programs — bucketed so varying requests reuse compilations
    # (VERDICT r2 weak: the old design compiled one program per
    # (batch, prompt_len, max_new, sampling) tuple, inference/engine.py:189)
    # ------------------------------------------------------------------
    PREFILL_CHUNK = 16

    def _build_serving(self, batch: int, do_sample: bool, temperature: float,
                       top_k: int, top_p: float, eos_token_id: Optional[int], cap: int):
        """THREE programs serve every (prompt_len, max_new) combination:
        a fixed-chunk prefill, a 1-token prefill for the remainder, and one
        generation loop whose token budget is a TRACED argument. Prompts of
        any length run ceil(p/C) chunked calls + (p mod C) single calls; no
        per-shape recompiles (reference per-token kernels +
        ``inference_context.h`` workspace reuse achieve the same)."""
        eos = -1 if eos_token_id is None else int(eos_token_id)

        apply_decode = self._apply_decode

        def prefill(params, cache, ids):
            logits, upd = apply_decode(params, cache, ids)
            return upd["cache"], logits[:, -1]

        def gen_loop(params, cache, last_logits, rng, max_new):
            rng, key = jax.random.split(rng)
            tok = sample_logits(last_logits, key, do_sample, temperature, top_k, top_p).astype(jnp.int32)
            out0 = jnp.zeros((batch, cap), jnp.int32)
            done0 = (tok == eos)
            out0 = out0.at[:, 0].set(tok)

            def cond(state):
                t, done, *_ = state
                return (t < max_new) & ~jnp.all(done)

            def body(state):
                t, done, tok, cache, out, rng = state
                logits, upd = apply_decode(params, cache, tok[:, None])
                rng, key = jax.random.split(rng)
                nxt = sample_logits(logits[:, 0], key, do_sample, temperature,
                                    top_k, top_p).astype(jnp.int32)
                nxt = jnp.where(done, eos if eos >= 0 else 0, nxt)
                out = out.at[:, t].set(nxt)
                done = done | (nxt == eos)
                return t + 1, done, nxt, upd["cache"], out, rng

            t, done, tok, cache, out, rng = jax.lax.while_loop(
                cond, body, (jnp.int32(1), done0, tok, cache, out0, rng))
            # the final cache is returned (and discarded by the caller) so
            # the donated input cache has an output to alias — without it
            # donation is dead and JAX warns on every first compile
            return out, t, cache

        return {
            # one jitted prefill specializes to exactly two shapes: the
            # C-token chunk and the 1-token remainder
            "prefill": jax.jit(prefill, donate_argnums=(1,)),
            "gen_loop": jax.jit(gen_loop, donate_argnums=(1,)),
        }

    def _make_beam_fns(self, batch, beams, eos_token_id, cap, length_penalty,
                       decode_fn):
        """Generic beam-search machinery shared by decoder-only and
        encoder-decoder serving. ``decode_fn(params, cache, tok_2d, extra)
        -> (logits [batch*beams, V], new_cache)`` is the one-step decoder;
        ``extra`` is any per-call operand the step cross-references (the
        replicated encoder output for seq2seq; ``()`` for decoder-only).
        Each live hypothesis is one row of a [batch*beams] decode batch; the
        KV cache reindexes by the winning beams' source indices every step."""
        eos = -1 if eos_token_id is None else int(eos_token_id)

        def replicate(cache):
            # leaves with a leading batch dim fan out to [batch*beams, ...];
            # scalars (cache_index counters) stay shared
            def rep(x):
                if x.ndim > 0 and x.shape[0] == batch:
                    return jnp.repeat(x, beams, axis=0)
                return x
            return jax.tree.map(rep, cache)

        def reindex(cache, beam_src):
            # beam_src [batch, beams]: winning hypotheses' source beams
            def gather(x):
                if x.ndim > 0 and x.shape[0] == batch * beams:
                    xb = x.reshape((batch, beams) + x.shape[1:])
                    idx = beam_src.reshape((batch, beams) + (1,) * (x.ndim - 1))
                    return jnp.take_along_axis(xb, idx, axis=1).reshape(x.shape)
                return x
            return jax.tree.map(gather, cache)

        def beam_loop(params, cache, extra, last_logits, max_new):
            # cache arrives ALREADY replicated to [batch*beams, ...] (the
            # caller runs the jitted replicate first) so the donated input
            # aliases the loop-carried cache — inside-loop replication would
            # leave donation dead and hold 1+beams cache copies in HBM
            lp0 = jax.nn.log_softmax(last_logits.astype(jnp.float32), axis=-1)  # [B, V]
            scores, tok = jax.lax.top_k(lp0, beams)  # [B, beams]
            tok = tok.astype(jnp.int32)
            out0 = jnp.zeros((batch, beams, cap), jnp.int32).at[:, :, 0].set(tok)
            done0 = tok == eos
            len0 = jnp.ones((batch, beams), jnp.int32)
            vocab = lp0.shape[-1]
            # candidate set for a finished beam: only "stay finished" (eos,
            # score unchanged) — standard done-beam handling
            done_lp = jnp.full((vocab,), -jnp.inf).at[max(eos, 0)].set(0.0)

            def cond(state):
                t, done, *_ = state
                return (t < max_new) & ~jnp.all(done)

            def body(state):
                t, done, tok, scores, lens, cache, out = state
                logits, new_cache = decode_fn(params, cache,
                                              tok.reshape(batch * beams, 1), extra)
                lp = jax.nn.log_softmax(logits.astype(jnp.float32), axis=-1)
                lp = lp.reshape(batch, beams, vocab)
                lp = jnp.where(done[:, :, None], done_lp[None, None, :], lp)
                total = scores[:, :, None] + lp  # [B, beams, V]
                new_scores, flat = jax.lax.top_k(total.reshape(batch, beams * vocab), beams)
                beam_src = (flat // vocab).astype(jnp.int32)
                new_tok = (flat % vocab).astype(jnp.int32)
                take = lambda a: jnp.take_along_axis(a, beam_src, axis=1)
                prev_done = take(done)
                new_done = prev_done | (new_tok == eos)
                new_lens = take(lens) + (~prev_done).astype(jnp.int32)
                out = jnp.take_along_axis(out, beam_src[:, :, None], axis=1)
                # a finished beam keeps emitting eos (or 0) — already its token
                out = out.at[:, :, t].set(jnp.where(prev_done, max(eos, 0), new_tok))
                cache = reindex(new_cache, beam_src)
                return t + 1, new_done, new_tok, new_scores, new_lens, cache, out

            t, done, tok, scores, lens, cache, out = jax.lax.while_loop(
                cond, body, (jnp.int32(1), done0, tok, scores, len0, cache, out0))
            # HF-style length normalization (length_penalty=1.0 → mean logprob)
            norm = scores / (lens.astype(jnp.float32) ** length_penalty)
            best = jnp.argmax(norm, axis=-1)
            best_out = jnp.take_along_axis(out, best[:, None, None], axis=1)[:, 0]
            return best_out, t, cache

        # replicate is NOT donated (outputs are beams× larger, nothing can
        # alias); the prefill cache dies naturally after this call
        return {"replicate": jax.jit(replicate),
                "loop": jax.jit(beam_loop, donate_argnums=(1,))}

    def _build_beam_loop(self, batch, beams, eos_token_id, cap, length_penalty):
        """Decoder-only beam search (reference relies on HF ``generate``
        over the injected kernels; here the whole search is one jitted
        while_loop over :meth:`_make_beam_fns`)."""
        apply_decode = self._apply_decode

        def decode_fn(params, cache, tok, extra):
            del extra
            logits, upd = apply_decode(params, cache, tok)
            return logits[:, 0], upd["cache"]

        return self._make_beam_fns(batch, beams, eos_token_id, cap,
                                   length_penalty, decode_fn)

    def _build_seq2seq_beam(self, batch, beams, eos_token_id, cap,
                            length_penalty):
        """Encoder-decoder beam search: encode once, replicate the decoder
        self-attention cache AND the encoder output to [batch*beams], then
        run the shared beam while_loop with a cross-attending step."""
        step = self._seq2seq_step
        encode = self._seq2seq_encode

        def first(params, cache, enc_out, start_tok):
            # the start-token step runs on the UNREPLICATED batch (every
            # beam of a row would compute the same thing); its logits seed
            # the beam fan-out exactly like decoder-only prefill logits
            logits, cache = step(params, cache, enc_out, start_tok)
            return logits[:, -1], cache

        def decode_fn(params, cache, tok, enc_rep):
            logits, cache = step(params, cache, enc_rep, tok)
            return logits[:, 0], cache

        fns = self._make_beam_fns(batch, beams, eos_token_id, cap,
                                  length_penalty, decode_fn)
        fns["first"] = jax.jit(first, donate_argnums=(1,))
        # the encoder output fans out to [batch*beams] by the SAME rule as
        # the cache (one shared jitted repeat — the row alignment between
        # the two replications is load-bearing for cross-attention)
        fns["rep_enc"] = fns["replicate"]
        fns["encode"] = jax.jit(encode)
        return fns

    def _seq2seq_step(self, params, cache, enc_out, tok):
        """One decoder step of an encoder-decoder model: self-attend the
        cache, cross-attend the encoder output (shared by the greedy and
        beam builders so the two paths cannot drift)."""
        model = self.module
        logits, upd = model.apply({"params": self._mparams(params), "cache": cache},
                                  decoder_input_ids=tok, encoder_outputs=enc_out,
                                  decode=True, mutable=["cache"])
        return _unwrap_logits(logits), upd["cache"]

    def _seq2seq_encode(self, params, enc_ids):
        model = self.module
        return model.apply({"params": self._mparams(params)}, enc_ids,
                           method=type(model).encode)

    def _build_seq2seq_serving(self, batch, do_sample, temperature, top_k, top_p,
                               eos_token_id, cap):
        """Encoder-decoder serving (T5-style): encode once, then a jitted
        decoder while_loop against the self-attention cache, cross-attending
        the encoder output every step."""
        eos = -1 if eos_token_id is None else int(eos_token_id)
        step = self._seq2seq_step
        encode = self._seq2seq_encode

        def gen_loop(params, cache, enc_out, start_tok, rng, max_new):
            logits, cache = step(params, cache, enc_out, start_tok)
            rng, key = jax.random.split(rng)
            tok = sample_logits(logits[:, -1], key, do_sample, temperature,
                                top_k, top_p).astype(jnp.int32)
            out0 = jnp.zeros((batch, cap), jnp.int32).at[:, 0].set(tok)
            done0 = tok == eos

            def cond(state):
                t, done, *_ = state
                return (t < max_new) & ~jnp.all(done)

            def body(state):
                t, done, tok, cache, out, rng = state
                logits, cache = step(params, cache, enc_out, tok[:, None])
                rng, key = jax.random.split(rng)
                nxt = sample_logits(logits[:, 0], key, do_sample, temperature,
                                    top_k, top_p).astype(jnp.int32)
                nxt = jnp.where(done, eos if eos >= 0 else 0, nxt)
                out = out.at[:, t].set(nxt)
                done = done | (nxt == eos)
                return t + 1, done, nxt, cache, out, rng

            t, done, tok, cache, out, rng = jax.lax.while_loop(
                cond, body, (jnp.int32(1), done0, tok, cache, out0, rng))
            return out, t, cache

        return {"encode": jax.jit(encode),
                "gen_loop": jax.jit(gen_loop, donate_argnums=(1,))}

    def _generate_seq2seq(self, ids_np, real_batch, batch, max_new, do_sample,
                          temperature, top_k, top_p, eos_token_id, rng,
                          decoder_start_token_id, num_beams=1,
                          length_penalty=1.0):
        mcap = getattr(self.mcfg, "max_cache_length", None) or self._max_len
        # cache slots consumed = max_new (the start token plus the max_new-1
        # fed-back tokens; the final sample is never fed back)
        if max_new > mcap:
            raise ValueError(f"max_new_tokens ({max_new}) exceeds the decoder cache "
                             f"capacity {mcap} (max_cache_length)")
        if max_new > int(self.config.max_tokens or mcap):
            raise ValueError(f"max_new_tokens ({max_new}) exceeds the configured output "
                             f"budget max_tokens={self.config.max_tokens}; raise it in "
                             f"the inference config (silently truncating would hide the miss)")
        cap = int(min(mcap, self.config.max_tokens or mcap))
        if num_beams > 1:
            key = ("seq2seq_beam", batch, num_beams, eos_token_id,
                   float(length_penalty))
        else:
            key = ("seq2seq", batch, do_sample, float(temperature), int(top_k),
                   float(top_p), eos_token_id)
        if not hasattr(self, "_gen_cache"):
            self._gen_cache = {}
        if key not in self._gen_cache:
            self._gen_cache[key] = (
                self._build_seq2seq_beam(batch, num_beams, eos_token_id, cap,
                                         float(length_penalty))
                if num_beams > 1 else
                self._build_seq2seq_serving(batch, do_sample, temperature,
                                            top_k, top_p, eos_token_id, cap))
        fns = self._gen_cache[key]
        start = jnp.full((batch, 1), int(decoder_start_token_id), jnp.int32)
        if max_new <= 0:  # parity with the decoder-only path's no-op return
            return np.broadcast_to(np.int32(decoder_start_token_id), (real_batch, 1))
        # NOTE: the encoder runs at the exact prompt length (no padding —
        # the encode() surface carries no padding mask, and padded tokens
        # would perturb bidirectional attention); one compile per length.
        # The encoder program is sampling-independent: cached per batch only
        if not hasattr(self, "_enc_cache"):
            self._enc_cache = {}
        if batch not in self._enc_cache:
            self._enc_cache[batch] = fns["encode"]
        enc_out = self._enc_cache[batch](self.params, self._place_batch(jnp.asarray(ids_np)))
        cache = jax.device_put(init_cache(self.module, batch),  # graft-lint: waive R008 jax-owned init_cache zeros
                               NamedSharding(self.mesh, P()))
        if num_beams > 1:
            last_logits, cache = fns["first"](self.params, cache, enc_out, start)
            cache = fns["replicate"](cache)
            enc_rep = fns["rep_enc"](enc_out)
            out, n, _ = fns["loop"](self.params, cache, enc_rep, last_logits,
                                    jnp.int32(min(max_new, cap)))
        else:
            out, n, _ = fns["gen_loop"](self.params, cache, enc_out, start, rng,
                                        jnp.int32(min(max_new, cap)))
        n = int(n)
        full = jnp.concatenate([start, out[:, :n]], axis=1)
        return full[:real_batch]

    @staticmethod
    def _pow2_bucket(n: int) -> int:
        b = 1
        while b < n:
            b *= 2
        return b

    def generate(self, input_ids, max_new_tokens: Optional[int] = None, do_sample: bool = False,
                 temperature: float = 1.0, top_k: int = 0, top_p: float = 1.0,
                 eos_token_id: Optional[int] = None, rng: Optional[jax.Array] = None,
                 num_beams: int = 1, length_penalty: float = 1.0, **kwargs):
        """Generate ``max_new_tokens`` continuations (reference routes
        ``generate`` through the injected model's fused decode kernels).
        ``num_beams > 1`` runs beam search (greedy expansion; HF-style
        length normalization via ``length_penalty``)."""
        if num_beams > 1 and do_sample:
            raise ValueError("num_beams > 1 requires do_sample=False (beam-sample "
                             "hybrid is not supported)")
        ids_np = np.asarray(input_ids, np.int32)
        real_batch, prompt_len = ids_np.shape
        max_new = int(max_new_tokens if max_new_tokens is not None else self.config.max_new_tokens)
        def bucket_pad_and_rng(ids_np, rng):
            # bucket/pad + rng split happen AFTER validation so a rejected
            # call never advances the engine's rng stream (seeded-run
            # reproducibility must not depend on failed requests)
            batch = self._pow2_bucket(real_batch)
            if batch != real_batch:
                ids_np = np.concatenate(
                    [ids_np, np.repeat(ids_np[:1], batch - real_batch, axis=0)], axis=0)
            if rng is None:
                # engine-stream key unless the caller supplied one (keeps
                # later rng-less calls independent of any caller key)
                self._rng, rng = jax.random.split(self._rng)
            return ids_np, batch, rng

        if self._is_seq2seq:
            start_id = kwargs.get("decoder_start_token_id",
                                  getattr(self.mcfg, "decoder_start_token_id", None))
            if start_id is None:
                raise ValueError("encoder-decoder generate needs decoder_start_token_id "
                                 "(pass it or set it on the model config) — defaulting "
                                 "silently would seed generation from the wrong token")
            ids_np, batch, rng = bucket_pad_and_rng(ids_np, rng)
            return self._generate_seq2seq(
                ids_np, real_batch, batch, max_new, do_sample, temperature, top_k,
                top_p, eos_token_id, rng, int(start_id),
                num_beams=num_beams, length_penalty=length_penalty)
        if prompt_len + max_new > self._max_len:
            raise ValueError(f"prompt ({prompt_len}) + max_new_tokens ({max_new}) exceeds the model "
                             f"context/cache length {self._max_len} "
                             f"(reference maps this to max_out_tokens)")
        if max_new > int(self.config.max_tokens or self._max_len):
            raise ValueError(f"max_new_tokens ({max_new}) exceeds the configured output budget "
                             f"max_tokens={self.config.max_tokens}; raise it in the inference "
                             f"config (silently truncating would hide the miss)")
        ids_np, batch, rng = bucket_pad_and_rng(ids_np, rng)
        cap = min(self._max_len, int(self.config.max_tokens or self._max_len))

        key = (batch, do_sample, float(temperature), int(top_k), float(top_p), eos_token_id)
        if not hasattr(self, "_gen_cache"):
            self._gen_cache = {}
        if key not in self._gen_cache:
            # every (bucket, sampling) combination stays warm — alternating
            # request shapes must not discard compiled programs
            self._gen_cache[key] = self._build_serving(batch, do_sample, temperature,
                                                       top_k, top_p, eos_token_id, cap)
        self._gen_key = key
        self._gen_fns = fns = self._gen_cache[key]


        ids = self._place_batch(jnp.asarray(ids_np))
        # commit the fresh cache so its placement matches the donated outputs
        # of later calls (an uncommitted first cache costs a recompile)
        cache = jax.device_put(init_cache(self.module, batch),  # graft-lint: waive R008 jax-owned init_cache zeros
                               NamedSharding(self.mesh, P()))
        C = self.PREFILL_CHUNK
        pos = 0
        last_logits = None
        while pos + C <= prompt_len:
            cache, last_logits = fns["prefill"](self.params, cache, ids[:, pos:pos + C])
            pos += C
        while pos < prompt_len:
            cache, last_logits = fns["prefill"](self.params, cache, ids[:, pos:pos + 1])
            pos += 1
        if max_new <= 0:
            return jnp.asarray(ids_np[:real_batch])
        if num_beams > 1:
            bkey = (batch, num_beams, eos_token_id, float(length_penalty))
            if not hasattr(self, "_beam_cache"):
                self._beam_cache = {}
            if bkey not in self._beam_cache:
                self._beam_cache[bkey] = self._build_beam_loop(
                    batch, num_beams, eos_token_id, cap, float(length_penalty))
            bfns = self._beam_cache[bkey]
            cache = bfns["replicate"](cache)
            out, n, _ = bfns["loop"](self.params, cache, (), last_logits,
                                     jnp.int32(min(max_new, cap)))
        else:
            out, n, _ = fns["gen_loop"](self.params, cache, last_logits, rng,
                                        jnp.int32(min(max_new, cap)))
        n = int(n)
        full = jnp.concatenate([jnp.asarray(ids_np), out[:, :n]], axis=1)
        return full[:real_batch]
