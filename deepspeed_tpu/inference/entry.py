"""``init_inference`` — parity with reference ``deepspeed/__init__.py:269``."""
from deepspeed_tpu.inference.config import DeepSpeedInferenceConfig
from deepspeed_tpu.inference.engine import InferenceEngine
from deepspeed_tpu.utils.logging import log_dist
from deepspeed_tpu.version import __version__


def init_inference(model, config=None, params=None, topology=None, **kwargs):
    """Build an :class:`InferenceEngine` (reference ``init_inference``).

    ``config`` may be a dict/``DeepSpeedInferenceConfig``; legacy kwargs
    (``mp_size=``, ``dtype=``, ``replace_with_kernel_inject=`` …) are folded
    in for parity with the reference's kwarg path (``__init__.py:306``).
    """
    log_dist(f"DeepSpeed-TPU inference info: version={__version__}")
    cfg_dict = dict(config) if isinstance(config, dict) else {}
    if isinstance(config, DeepSpeedInferenceConfig):
        if kwargs:
            # reference raises on conflicting config + kwargs (__init__.py:318)
            raise ValueError(f"init_inference got both a DeepSpeedInferenceConfig and kwargs "
                             f"{sorted(kwargs)}; fold the kwargs into the config")
        ds_config = config
    else:
        # legacy kwarg names (reference maps mp_size → tensor_parallel.tp_size)
        if "mp_size" in kwargs:
            cfg_dict.setdefault("tensor_parallel", {})
            if isinstance(cfg_dict["tensor_parallel"], dict):
                cfg_dict["tensor_parallel"].setdefault("tp_size", kwargs.pop("mp_size"))
        cfg_dict.update(kwargs)
        ds_config = DeepSpeedInferenceConfig(**cfg_dict)
    if hasattr(model, "state_dict") and hasattr(model, "config") and params is None:
        # HF torch module handed in directly (the reference's calling
        # convention): convert arch + config + weights in one step. int8
        # means QUANTIZED WEIGHTS, never int8 compute — match the engine's
        # cast_dtype mapping (inference/engine.py)
        import jax.numpy as jnp

        from deepspeed_tpu.module_inject.from_hf import from_hf
        compute_dtype = jnp.bfloat16 if ds_config.dtype == jnp.int8 else ds_config.dtype
        # explicit checkpoint wins over the module's own weights (the
        # reference's meta-tensor convention: arch from the module, weights
        # from the checkpoint) — skip the state_dict conversion entirely
        model, params = from_hf(model, dtype=compute_dtype,
                                weights=ds_config.checkpoint is None)
    return InferenceEngine(model, ds_config, params=params, topology=topology)
