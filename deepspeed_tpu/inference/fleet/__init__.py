"""graft-fleet: multi-replica serving — router, autoscaler, live KV
migration (ISSUE 17 / ROADMAP item 1, the "millions of users" layer
above one graft-serve process).

* :mod:`protocol` — the line-delimited JSON wire format workers speak.
* :mod:`replica` — replica handles: in-process (:class:`LocalReplica`,
  SimClock-testable) and subprocess (:class:`SubprocessReplica`, real
  pipes + PR-13 heartbeat liveness).
* :mod:`router` — :class:`FleetRouter`: least-loaded dispatch from the
  replicas' own tick signals, at-most-once completion accounting,
  death recovery (bundle re-admission / re-dispatch).
* :mod:`autoscaler` — :class:`Autoscaler`: hysteretic replica-count
  decisions from the same ``serve_tick`` signals, offline-replayable.
* :mod:`migrate` — the KV migration codec over the PR-9 manifest+digest
  machinery (save/load/verify bundles, scheduler restore).
* :mod:`worker` — ``python -m deepspeed_tpu.inference.fleet.worker``.
"""

from deepspeed_tpu.inference.fleet.autoscaler import AutoscalePolicy, Autoscaler
from deepspeed_tpu.inference.fleet.migrate import (load_bundle,
                                                   make_bundle_migrate,
                                                   receive_bundle,
                                                   restore_into, save_bundle)
from deepspeed_tpu.inference.fleet.replica import LocalReplica, SubprocessReplica
from deepspeed_tpu.inference.fleet.router import FleetRouter

__all__ = ["AutoscalePolicy", "Autoscaler", "FleetRouter", "LocalReplica",
           "SubprocessReplica", "load_bundle", "make_bundle_migrate",
           "receive_bundle", "restore_into", "save_bundle"]
