"""graft-fleet autoscaler: replica count from the serve_tick signals.

Pure decision core (:class:`Autoscaler.decide`) over the same per-replica
signal dicts the router caches from ``tick`` messages and the replicas
land as ``serve_tick`` JSONL — so a decision is reproducible offline
from the run directories alone (``events.last_tick_signals``). The
thresholds:

* **scale up** (+1) when the fleet is saturated: mean queue depth per
  replica above ``queue_high``, OR worst-replica TTFT p99 above
  ``ttft_p99_high`` (when set), OR mean BlockPool fragmentation above
  ``frag_tokens_high`` (admission is starving on fragments, not
  capacity — more replicas add whole pools).
* **scale down** (−1) when the fleet is idle: zero queued everywhere and
  mean slot occupancy below ``occupancy_low`` — and only when the
  survivors could absorb the load (total in-flight fits N−1 replicas'
  slots).
* **hysteresis**: each direction has its own cooldown; a decision
  timestamps the clock and the opposite direction is also suppressed
  briefly (``flap_guard``) so a drain-then-spike does not thrash.

The autoscaler only *decides*; acting (spawning a SubprocessReplica /
SIGTERM-with-migrate on the victim) is the caller's to wire, which keeps
this testable under SimClock with zero processes.
"""

import dataclasses
import time
from typing import Callable, Dict, List, Optional


@dataclasses.dataclass
class AutoscalePolicy:
    """Thresholds (documented in README "Serving fleet")."""

    min_replicas: int = 1
    max_replicas: int = 4
    #: mean queued requests per replica that means "saturated"
    queue_high: float = 4.0
    #: worst-replica TTFT p99 (seconds) that means "saturated"; None
    #: disables the latency trigger (CPU rigs: absolute numbers vary)
    ttft_p99_high: Optional[float] = None
    #: mean BlockPool fragmentation (tokens) that means admission is
    #: starving on fragments; None disables
    frag_tokens_high: Optional[float] = None
    #: mean in_flight/slots below which the fleet is "idle"
    occupancy_low: float = 0.25
    scale_up_cooldown_s: float = 5.0
    scale_down_cooldown_s: float = 30.0
    #: after any decision, the OPPOSITE direction waits at least this long
    flap_guard_s: float = 10.0


class Autoscaler:
    """Hysteretic replica-count decisions from aggregated tick signals."""

    def __init__(self, policy: Optional[AutoscalePolicy] = None,
                 clock: Optional[Callable[[], float]] = None):
        self.policy = policy or AutoscalePolicy()
        self.clock = clock or time.monotonic
        self._last_up = float("-inf")
        self._last_down = float("-inf")
        self.last_reason = "no signals yet"
        self.decisions: List[dict] = []

    # -- aggregation ---------------------------------------------------
    @staticmethod
    def aggregate(signals_by_replica: Dict[str, Optional[dict]]) -> Optional[dict]:
        """Fleet-level view of the per-replica signal dicts; None until at
        least one replica has reported."""
        rows = [s for s in signals_by_replica.values() if s]
        if not rows:
            return None
        n = len(rows)
        ttfts = [s["ttft_p99"] for s in rows if s.get("ttft_p99") is not None]
        slots = sum(s.get("slots", 0) for s in rows)
        in_flight = sum(s.get("in_flight", 0) for s in rows)
        return {
            "replicas": n,
            "mean_queue_depth": sum(s.get("queue_depth", 0) for s in rows) / n,
            "total_in_flight": in_flight,
            "total_slots": slots,
            "occupancy": in_flight / slots if slots else 0.0,
            "worst_ttft_p99": max(ttfts) if ttfts else None,
            "mean_frag_tokens": sum(s.get("pool_fragmentation_tokens", 0)
                                    for s in rows) / n,
        }

    # -- decision ------------------------------------------------------
    def decide(self, signals_by_replica: Dict[str, Optional[dict]],
               now: Optional[float] = None) -> int:
        """+1 / 0 / −1 replicas; the reason lands in ``last_reason`` and
        the decision log (what the fleet bench row commits)."""
        p = self.policy
        now = self.clock() if now is None else now
        agg = self.aggregate(signals_by_replica)
        if agg is None:
            self.last_reason = "no signals yet"
            return 0
        n = agg["replicas"]

        saturated = []
        if agg["mean_queue_depth"] > p.queue_high:
            saturated.append(f"mean_queue {agg['mean_queue_depth']:.1f} "
                             f"> {p.queue_high}")
        if (p.ttft_p99_high is not None and agg["worst_ttft_p99"] is not None
                and agg["worst_ttft_p99"] > p.ttft_p99_high):
            saturated.append(f"ttft_p99 {agg['worst_ttft_p99']:.3f}s "
                             f"> {p.ttft_p99_high}s")
        if (p.frag_tokens_high is not None
                and agg["mean_frag_tokens"] > p.frag_tokens_high):
            saturated.append(f"frag {agg['mean_frag_tokens']:.0f} tok "
                             f"> {p.frag_tokens_high}")
        if saturated:
            if n >= p.max_replicas:
                self.last_reason = (f"saturated ({'; '.join(saturated)}) but "
                                    f"at max_replicas={p.max_replicas}")
                return 0
            if (now - self._last_up < p.scale_up_cooldown_s
                    or now - self._last_down < p.flap_guard_s):
                self.last_reason = "saturated but in cooldown"
                return 0
            self._last_up = now
            self.last_reason = "; ".join(saturated)
            self._log(now, +1, agg)
            return +1

        idle = (agg["mean_queue_depth"] == 0
                and agg["occupancy"] < p.occupancy_low)
        if idle and n > p.min_replicas:
            # survivors must absorb the in-flight load (migration target
            # capacity): N−1 replicas' slots must fit what's in flight
            survivor_slots = agg["total_slots"] - agg["total_slots"] // max(n, 1)
            if agg["total_in_flight"] > survivor_slots:
                self.last_reason = "idle but survivors could not absorb in-flight"
                return 0
            if (now - self._last_down < p.scale_down_cooldown_s
                    or now - self._last_up < p.flap_guard_s):
                self.last_reason = "idle but in cooldown"
                return 0
            self._last_down = now
            self.last_reason = (f"idle (occupancy {agg['occupancy']:.2f} "
                                f"< {p.occupancy_low}, queue empty)")
            self._log(now, -1, agg)
            return -1
        self.last_reason = "steady"
        return 0

    def _log(self, now: float, delta: int, agg: dict) -> None:
        self.decisions.append({"t": now, "delta": delta,
                               "reason": self.last_reason, **agg})

    # -- offline replay ------------------------------------------------
    @staticmethod
    def signals_from_telemetry(paths: Dict[str, str]) -> Dict[str, Optional[dict]]:
        """Per-replica signals from telemetry JSONL files (replica name →
        run file) — the file-tailing deployment where the autoscaler has
        no pipe to the replicas, and the offline replay of any decision."""
        from deepspeed_tpu.inference.serving.events import last_tick_signals
        return {name: last_tick_signals(path) for name, path in paths.items()}
