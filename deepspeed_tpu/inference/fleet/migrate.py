"""graft-fleet live KV migration codec: scheduler payloads ⇄ a durable,
digest-verified bundle directory.

A SIGTERM'd replica exports every in-flight request
(``scheduler.export_inflight``: host bookkeeping + the slot's committed
KV rows, quantized KV riding as-is — PR-16 codes + scales are just
smaller rows) and lands them as ONE bundle directory through the PR-9
checkpoint machinery:

* each request's arrays in ``req_<origin_id>.npz`` (prompt, output,
  token_times, and every KV leaf keyed by its cache ``keystr`` path);
* scalar bookkeeping in ``bundle.json``;
* ``manifest.json`` with the file inventory AND per-leaf
  shape/dtype/sha256 of the KV pytree (``state_leaf_entries``);
* published crash-atomically (``staging → fsync → rename``), so a
  receiver never observes a partial bundle.

The receiver verifies twice: ``verify_checkpoint_dir`` (file inventory,
before deserializing anything) and ``verify_state_leaves`` (per-leaf
digests over the DESERIALIZED arrays — the end-to-end "bit-exact KV"
proof the acceptance criterion names). Verification failure raises
``CheckpointCorruptError``→``MigrationError``; capacity shortfalls on
the receiver are NOT errors — ``restore_into`` returns the refused
payloads so the router re-dispatches them elsewhere.
"""

import json
import os
from typing import Callable, Dict, List, Optional, Tuple

import numpy as np

from deepspeed_tpu.inference.serving.scheduler import MigrationError
from deepspeed_tpu.runtime.resilience.manifest import (CheckpointCorruptError,
                                                       atomic_publish,
                                                       build_manifest,
                                                       staging_path,
                                                       state_leaf_entries,
                                                       verify_checkpoint_dir,
                                                       verify_state_leaves,
                                                       write_manifest)
from deepspeed_tpu.utils.logging import log_dist

BUNDLE_META = "bundle.json"
BUNDLE_VERSION = 1

#: payload fields that are plain JSON scalars/lists (everything else —
#: prompt, kv — travels in the npz). graft-prefix-cache rides here too:
#: the KV rows in the npz are already MATERIALIZED (per-slot dense —
#: shared prefix blocks export their bytes, never their refs), so a
#: bundle needs only the accounting scalar (cached_prefix_tokens) plus
#: the prefix_cache compat knob the importer's envelope check refuses on
_SCALAR_FIELDS = ("request_id", "state", "max_new_tokens", "eos_token_id",
                  "arrival_time", "output", "prefill_pos", "first_token_time",
                  "token_times", "drafted_tokens", "accepted_tokens", "meta",
                  "length", "next_token", "kv_quant", "weight_dtype",
                  "capacity", "spec_k", "cached_prefix_tokens", "prefix_cache")


def _npz_name(origin_id: int) -> str:
    return f"req_{int(origin_id)}.npz"


def _kv_state(payloads: List[dict]) -> Dict[str, Dict[str, Dict[str, np.ndarray]]]:
    """The bundle's KV arrays as one nested pytree keyed by origin id —
    the structure ``state_leaf_entries`` digests at save and the receiver
    re-digests after deserialization (same structure ⇒ same leaf keys)."""
    return {str(p["request_id"]): {role: dict(leaves)
                                   for role, leaves in p["kv"].items()}
            for p in payloads}


def save_bundle(payloads: List[dict], bundle_dir: str) -> str:
    """Land ``payloads`` (from ``scheduler.export_inflight``) as a
    published bundle directory; returns ``bundle_dir``. Crash-atomic: a
    kill mid-save leaves only an inert staging dir, never a torn bundle."""
    if not payloads:
        raise MigrationError("empty migration payload list — nothing to bundle")
    base = os.path.dirname(os.path.abspath(bundle_dir)) or "."
    os.makedirs(base, exist_ok=True)
    staging = staging_path(base, os.path.basename(bundle_dir))
    os.makedirs(staging, exist_ok=True)
    meta = {"version": BUNDLE_VERSION, "requests": []}
    for p in payloads:
        rec = {k: p[k] for k in _SCALAR_FIELDS}
        rec["npz"] = _npz_name(p["request_id"])
        arrays = {"prompt": np.asarray(p["prompt"], np.int32)}
        for role, leaves in p["kv"].items():
            for key, arr in leaves.items():
                arrays[f"{role}::{key}"] = np.asarray(arr)
        np.savez(os.path.join(staging, rec["npz"]), **arrays)
        meta["requests"].append(rec)
    with open(os.path.join(staging, BUNDLE_META), "w") as fh:
        json.dump(meta, fh, indent=1)
    manifest = build_manifest(staging,
                              leaf_entries=state_leaf_entries(_kv_state(payloads)),
                              extra={"kind": "kv_migration_bundle"})
    write_manifest(staging, manifest)
    atomic_publish(staging, bundle_dir)
    log_dist(f"graft-fleet: migration bundle published at {bundle_dir} "
             f"({len(payloads)} requests)")
    return bundle_dir


def load_bundle(bundle_dir: str) -> List[dict]:
    """Read + verify a bundle back into scheduler payloads.

    Two integrity gates, both PR-9 machinery: the file inventory BEFORE
    deserializing (truncation/bit-flip caught without touching numpy) and
    the per-leaf KV digests AFTER (the npz decode round trip proven, not
    assumed). Either failing raises :class:`MigrationError` — garbage KV
    must never reach a slot."""
    try:
        manifest = verify_checkpoint_dir(bundle_dir)
    except CheckpointCorruptError as e:
        raise MigrationError(f"migration bundle failed integrity "
                             f"verification: {e}") from e
    meta_path = os.path.join(bundle_dir, BUNDLE_META)
    try:
        with open(meta_path) as fh:
            meta = json.load(fh)
    except (OSError, ValueError) as e:
        raise MigrationError(f"unreadable bundle meta {meta_path}: {e}") from e
    payloads: List[dict] = []
    for rec in meta.get("requests", []):
        with np.load(os.path.join(bundle_dir, rec["npz"])) as npz:
            kv: Dict[str, Dict[str, np.ndarray]] = {}
            prompt = None
            for key in npz.files:
                if key == "prompt":
                    prompt = np.asarray(npz[key], np.int32)
                    continue
                role, _, leaf_key = key.partition("::")
                kv.setdefault(role, {})[leaf_key] = np.asarray(npz[key])
        p = {k: rec[k] for k in _SCALAR_FIELDS}
        p["prompt"] = prompt
        p["kv"] = kv
        payloads.append(p)
    try:
        verify_state_leaves(_kv_state(payloads), manifest, bundle_dir)
    except CheckpointCorruptError as e:
        raise MigrationError(f"migrated KV failed digest verification: "
                             f"{e}") from e
    return payloads


def restore_into(scheduler, payloads: List[dict],
                 bundle_dir: str = "") -> Tuple[List, List[dict]]:
    """Admit verified payloads into ``scheduler``; returns ``(admitted
    requests, refused payloads)``. Capacity refusals (no free slot / pool
    blocks) come back as payloads for the router to place elsewhere;
    compat mismatches raise (``admit_migrated``'s contract)."""
    admitted, refused = [], []
    for p in payloads:
        req = scheduler.admit_migrated(p)
        if req is None:
            refused.append(p)
        else:
            admitted.append(req)
    if scheduler.telemetry is not None:
        scheduler.telemetry.emit("serve_migrate_in", migrated=len(admitted),
                                 refused=len(refused), bundle=str(bundle_dir))
    return admitted, refused


def receive_bundle(scheduler, bundle_dir: str) -> Tuple[List, List[dict]]:
    """The receiver's whole path: verify, deserialize, re-digest, admit."""
    return restore_into(scheduler, load_bundle(bundle_dir), bundle_dir)


def make_bundle_migrate(bundle_dir: str) -> Callable:
    """A ``scheduler.serve(migrate=...)`` hook that lands in-flight work
    at ``bundle_dir``. Export happens WITHOUT releasing slots; only a
    successfully published bundle releases them — a failed save leaves
    the scheduler able to fall back to the PR-14 drain."""
    def _migrate(scheduler, signal: str) -> dict:
        payloads = scheduler.export_inflight(release=False)
        if not payloads:
            return {"migrated": 0, "bundle": bundle_dir}
        try:
            save_bundle(payloads, bundle_dir)
        except MigrationError:
            raise
        except Exception as e:  # noqa: BLE001 — any save failure means drain
            raise MigrationError(f"bundle save failed: "
                                 f"{type(e).__name__}: {e}") from e
        scheduler.release_inflight()
        return {"migrated": len(payloads), "bundle": bundle_dir}
    return _migrate


def bundle_rids(payloads: List[dict]) -> List[Optional[str]]:
    """The fleet-wide ids riding each payload's ``meta`` (None for
    requests submitted outside a router)."""
    return [p.get("meta", {}).get("fleet_rid") for p in payloads]
