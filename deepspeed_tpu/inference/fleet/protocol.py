"""graft-fleet wire protocol: line-delimited JSON over pipes.

One replica worker (``fleet/worker.py``) speaks to the router over its
stdin/stdout: every message is one JSON object on one line, ``type``
names the kind. The framing is deliberately the dumbest thing that
works — a torn line (SIGKILL mid-write) or a stray non-JSON print from
a library is SKIPPED by the parser, never fatal, the same torn-tail
contract as ``telemetry/sink.iter_events``. Logs go to stderr; stdout
belongs to the protocol.

Router → worker::

    {"type": "request", "rid": "<fleet id>", "prompt": [ints],
     "max_new_tokens": N, "eos_token_id": null}
    {"type": "migrate_in", "bundle": "<dir>"}   # restore a peer's bundle
    {"type": "stop"}                            # clean shutdown

Worker → router::

    {"type": "ready", "pid": N, "slots": S, "capacity": C}
    {"type": "tick", "signals": {...scheduler.signals()...}}
    {"type": "done", "rid": "...", "output": [ints], "stats": {...}}
    {"type": "refused", "rid": "...", "reason": "..."}
    {"type": "migrated_out", "bundle": "<dir>", "rids": [...]}
    {"type": "migrated_in", "rids": [...], "refused_rids": [...]}
    {"type": "bye", "exit": code}

``rid`` is the ROUTER's fleet-wide id (a string), carried through the
scheduler in ``Request.meta["fleet_rid"]`` — worker-local integer
request ids never cross the wire, because every worker counts from 0.
"""

import json
from typing import IO, Iterable, List, Optional

# worker -> router message kinds
WORKER_KINDS = ("ready", "tick", "done", "refused", "migrated_out",
                "migrated_in", "bye")
# router -> worker message kinds
ROUTER_KINDS = ("request", "migrate_in", "stop")


def encode(msg: dict) -> str:
    """One protocol message as one newline-terminated JSON line."""
    if "type" not in msg:
        raise ValueError(f"protocol message needs a 'type': {msg!r}")
    return json.dumps(msg, separators=(",", ":")) + "\n"


def send(stream: IO, msg: dict) -> None:
    """Write + flush one message (pipes buffer; an unflushed 'done' is a
    request the router re-admits after a kill — at-most-once accounting
    absorbs that, but don't create the duplicate for free)."""
    stream.write(encode(msg))
    stream.flush()


def parse_line(line: str) -> Optional[dict]:
    """One wire line → message dict, or None for noise: blank lines,
    non-JSON prints a worker's libraries leaked onto stdout, torn tails
    from a SIGKILL mid-write, or JSON without a ``type``."""
    line = line.strip()
    if not line or not line.startswith("{"):
        return None
    try:
        msg = json.loads(line)
    except json.JSONDecodeError:
        return None
    if not isinstance(msg, dict) or "type" not in msg:
        return None
    return msg


def parse_lines(lines: Iterable[str]) -> List[dict]:
    out = []
    for line in lines:
        msg = parse_line(line)
        if msg is not None:
            out.append(msg)
    return out


def request_msg(rid: str, prompt, max_new_tokens: int,
                eos_token_id: Optional[int] = None) -> dict:
    return {"type": "request", "rid": str(rid),
            "prompt": [int(t) for t in prompt],
            "max_new_tokens": int(max_new_tokens),
            "eos_token_id": eos_token_id}
