"""graft-fleet replica handles: one object per serving process the
router dispatches to.

Two implementations of one small duck-typed surface (``send`` /
``poll`` / ``alive`` / ``load``):

* :class:`LocalReplica` — wraps a ``ContinuousBatchingScheduler``
  in-process. No pipes, no sleeps: the router's tier-1 tests drive N of
  these (sharing one engine, so compiled programs are paid once) under a
  simulated clock, and ``sigterm``/``sigkill`` are method calls that
  replay the exact drain→migrate / hard-death paths the subprocess
  worker takes on real signals.
* :class:`SubprocessReplica` — spawns ``python -m
  deepspeed_tpu.inference.fleet.worker`` speaking the line-delimited
  JSON protocol over pipes, stderr to a per-replica log file, liveness
  from the PR-13 heartbeat file (``heartbeat_age``) plus the exit code.

The router never cares which it holds.
"""

import os
import select
import signal
import subprocess
import sys
import time
from typing import Dict, List, Optional

import numpy as np

from deepspeed_tpu.inference.fleet import protocol
from deepspeed_tpu.inference.serving.request import REFUSED, Request
from deepspeed_tpu.inference.serving.scheduler import MigrationError
from deepspeed_tpu.utils.logging import log_dist


class LocalReplica:
    """In-process replica: a scheduler + an outbox of protocol messages.

    ``pump()`` advances the scheduler and is the local stand-in for the
    worker's main loop; the router calls it from ``step()``. Signals are
    simulated as method calls so SimClock tests cover the migrate/readmit
    logic with zero subprocesses."""

    def __init__(self, name: str, scheduler):
        self.name = name
        self.scheduler = scheduler
        self.dead = False
        self.exit_code: Optional[int] = None
        self._out: List[dict] = []
        self._fin_idx = 0  # scheduler.finished watermark → done messages
        self._out.append({"type": "ready", "pid": os.getpid(),
                          "slots": scheduler.slots,
                          "capacity": scheduler.capacity})

    # -- router-facing surface -----------------------------------------
    @property
    def alive(self) -> bool:
        return not self.dead

    def load(self) -> float:
        """Dispatch score: outstanding work (queued + in flight). Dead
        replicas never win."""
        if self.dead:
            return float("inf")
        s = self.scheduler
        return len(s.queue) + len(s.in_flight)

    def signals(self) -> Optional[Dict]:
        return None if self.dead else self.scheduler.signals()

    def send(self, msg: dict) -> None:
        if self.dead:
            raise RuntimeError(f"replica {self.name} is dead")
        kind = msg["type"]
        if kind == "request":
            req = Request(prompt=np.asarray(msg["prompt"], np.int32),
                          max_new_tokens=msg["max_new_tokens"],
                          eos_token_id=msg.get("eos_token_id"))
            req.meta["fleet_rid"] = msg["rid"]
            self.scheduler.submit(req)
            if req.state == REFUSED:
                self._out.append({"type": "refused", "rid": msg["rid"],
                                  "reason": req.refuse_reason})
        elif kind == "migrate_in":
            from deepspeed_tpu.inference.fleet.migrate import (bundle_rids,
                                                               receive_bundle)
            admitted, refused = receive_bundle(self.scheduler, msg["bundle"])
            self._out.append({"type": "migrated_in",
                              "rids": [r.meta.get("fleet_rid")
                                       for r in admitted],
                              "refused_rids": bundle_rids(refused)})
        elif kind == "stop":
            self.dead = True
            self.exit_code = 0
            self._out.append({"type": "bye", "exit": 0})
        else:
            raise ValueError(f"unknown router->replica message {kind!r}")

    def poll(self) -> List[dict]:
        out, self._out = self._out, []
        return out

    # -- progress ------------------------------------------------------
    def pump(self, max_ticks: int = 1) -> None:
        """Advance the scheduler up to ``max_ticks`` non-idle ticks and
        convert newly finished requests into ``done`` messages plus one
        ``tick`` signals message (the pipe-borne twin of ``serve_tick``)."""
        if self.dead:
            return
        s = self.scheduler
        for _ in range(max_ticks):
            if not (s.in_flight or len(s.queue)):
                break
            s.step()
        self._drain_finished()
        self._out.append({"type": "tick", "signals": s.signals()})

    def _drain_finished(self) -> None:
        s = self.scheduler
        while self._fin_idx < len(s.finished):
            req = s.finished[self._fin_idx]
            self._fin_idx += 1
            self._out.append({"type": "done",
                              "rid": req.meta.get("fleet_rid"),
                              "output": list(req.output),
                              "stats": req.stats()})

    # -- simulated signals ---------------------------------------------
    def sigterm(self, bundle_dir: str) -> None:
        """Replay the worker's SIGTERM path in-process: refuse the queue,
        try the bundle migrate, fall back to the PR-14 drain (finish
        in-flight locally) on :class:`MigrationError`."""
        from deepspeed_tpu.inference.fleet.migrate import (bundle_rids,
                                                           save_bundle)
        s = self.scheduler
        refused = s.queue.refuse_all("draining on SIGTERM")
        for req in refused:
            self._out.append({"type": "refused",
                              "rid": req.meta.get("fleet_rid"),
                              "reason": req.refuse_reason})
        if s.in_flight:
            try:
                payloads = s.export_inflight(release=False)
                save_bundle(payloads, bundle_dir)
                s.release_inflight()
                self._out.append({"type": "migrated_out",
                                  "bundle": bundle_dir,
                                  "rids": bundle_rids(payloads)})
            except MigrationError as e:
                log_dist(f"graft-fleet: {self.name} migration refused ({e}) "
                         f"— draining")
                s.run_until_drained(admit=False)
                self._drain_finished()
        self.dead = True
        self.exit_code = 143

    def sigkill(self) -> None:
        """Hard death: no drain, no messages, queued + in-flight work
        simply gone — the router's liveness probe must recover it."""
        self.dead = True
        self.exit_code = -signal.SIGKILL


class SubprocessReplica:
    """One ``fleet/worker.py`` child on pipes.

    ``env`` overlays the parent environment (FLEET_*/SERVE_* knobs); the
    replica's heartbeat file and stderr log land under ``workdir``.
    Liveness = process exit code OR heartbeat staleness — a replica
    wedged inside a dispatch never exits, so the router also compares
    ``heartbeat_age()`` against its timeout (the PR-13 lesson)."""

    def __init__(self, name: str, workdir: str,
                 env: Optional[Dict[str, str]] = None,
                 bundle_dir: Optional[str] = None):
        self.name = name
        self.workdir = workdir
        os.makedirs(workdir, exist_ok=True)
        self.heartbeat_path = os.path.join(workdir, f"{name}.heartbeat")
        self.stderr_path = os.path.join(workdir, f"{name}.stderr")
        self.bundle_dir = bundle_dir or os.path.join(workdir, f"{name}.bundle")
        child_env = dict(os.environ)
        child_env.update(env or {})
        child_env["DS_ELASTIC_HEARTBEAT_FILE"] = self.heartbeat_path
        child_env["FLEET_BUNDLE_DIR"] = self.bundle_dir
        self._stderr_fh = open(self.stderr_path, "wb")
        self.proc = subprocess.Popen(
            [sys.executable, "-m", "deepspeed_tpu.inference.fleet.worker"],
            stdin=subprocess.PIPE, stdout=subprocess.PIPE,
            stderr=self._stderr_fh, env=child_env, text=False)
        os.set_blocking(self.proc.stdout.fileno(), False)
        self._buf = b""
        self._pending: List[dict] = []  # messages seen before 'ready'
        self._last_signals: Optional[Dict] = None
        self.ticks_seen = 0  # tick messages received (bench evidence)
        # requests sent since the last tick snapshot: a burst of submits
        # between ticks must not all price this replica at its stale
        # (pre-burst) load — least-loaded dispatch would pile the whole
        # burst onto one worker
        self._sent_since_tick = 0

    # -- router-facing surface -----------------------------------------
    @property
    def alive(self) -> bool:
        return self.proc.poll() is None

    @property
    def exit_code(self) -> Optional[int]:
        return self.proc.poll()

    def heartbeat_age(self) -> Optional[float]:
        from deepspeed_tpu.elasticity import heartbeat_age
        return heartbeat_age(self.heartbeat_path)

    def load(self) -> float:
        if not self.alive:
            return float("inf")
        if self._last_signals is None:
            # fresh replica: only the unacknowledged sends count
            return float(self._sent_since_tick)
        return (self._last_signals.get("queue_depth", 0)
                + self._last_signals.get("in_flight", 0)
                + self._sent_since_tick)

    def signals(self) -> Optional[Dict]:
        return self._last_signals

    def send(self, msg: dict) -> None:
        if not self.alive:
            raise RuntimeError(f"replica {self.name} is dead")
        self.proc.stdin.write(protocol.encode(msg).encode())
        self.proc.stdin.flush()
        if msg.get("type") == "request":
            self._sent_since_tick += 1

    def poll(self) -> List[dict]:
        """Drain whatever the child has written without blocking; a
        half-line stays buffered until its newline arrives."""
        fd = self.proc.stdout.fileno()
        while True:
            try:
                ready, _, _ = select.select([fd], [], [], 0)
            except (OSError, ValueError):
                break
            if not ready:
                break
            try:
                chunk = os.read(fd, 65536)
            except (BlockingIOError, OSError):
                break
            if not chunk:
                break
            self._buf += chunk
        msgs: List[dict] = self._pending
        self._pending = []
        while b"\n" in self._buf:
            line, self._buf = self._buf.split(b"\n", 1)
            msg = protocol.parse_line(line.decode("utf-8", "replace"))
            if msg is not None:
                if msg["type"] == "tick":
                    self._last_signals = msg.get("signals")
                    self._sent_since_tick = 0
                    self.ticks_seen += 1
                msgs.append(msg)
        return msgs

    def wait_ready(self, timeout: float = 300.0) -> dict:
        """Block until the child's ``ready`` handshake (engine built,
        programs warm) or raise — the fleet smoke must not time a compile
        into its goodput window."""
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            batch = self.poll()
            for i, msg in enumerate(batch):
                if msg["type"] == "ready":
                    # messages after 'ready' in this batch stay queued
                    self._pending.extend(batch[i + 1:])
                    return msg
                self._pending.append(msg)
            if not self.alive:
                raise RuntimeError(
                    f"replica {self.name} died before ready "
                    f"(exit {self.exit_code}); stderr: {self.stderr_path}")
            time.sleep(0.05)
        raise TimeoutError(f"replica {self.name} not ready in {timeout}s")

    # -- signals -------------------------------------------------------
    def sigterm(self) -> None:
        if self.alive:
            self.proc.send_signal(signal.SIGTERM)

    def sigkill(self) -> None:
        if self.alive:
            self.proc.kill()

    def wait(self, timeout: float = 60.0) -> Optional[int]:
        try:
            return self.proc.wait(timeout=timeout)
        except subprocess.TimeoutExpired:
            return None

    def close(self) -> None:
        if self.alive:
            try:
                self.send({"type": "stop"})
            except (OSError, RuntimeError, ValueError):
                pass
            if self.wait(10.0) is None:
                self.proc.kill()
                self.proc.wait()
        for fh in (self.proc.stdin, self.proc.stdout):
            try:
                fh.close()
            except OSError:
                pass
        self._stderr_fh.close()
