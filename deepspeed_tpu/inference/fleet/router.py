"""graft-fleet router: least-loaded dispatch + at-most-once completion
accounting over N replicas.

The router owns the fleet-wide request ids (``rid``) and three tables:

* ``pending`` — rid → record (the wire message + the replica currently
  holding it). A request is *pending* from submit until its first
  ``done``; a replica death or refusal moves it back through
  ``dispatch`` (a fresh replica choice) without losing it.
* ``completed`` — rid → done message, FIRST completion wins. A migrated
  or re-admitted request can legitimately finish twice (the SIGTERM'd
  replica's ack raced its death; a SIGKILL re-admission re-ran work an
  unflushed ``done`` had already finished) — duplicates are *counted*
  (``duplicate_completions``), never double-delivered. This is the
  at-most-once guarantee: at most one delivery per rid, with
  re-admission providing the at-least-once half for killed replicas.
* ``replicas`` — name → handle (``LocalReplica`` / ``SubprocessReplica``;
  the router never distinguishes them).

Dispatch is least-loaded with prefix affinity (graft-prefix-cache): a
replica's tick signals advertise the ``prefix_key``s of its indexed
position-0 KV blocks (``prefix_hot``) plus its block size; a request
whose prompt's first block matches an advertised key routes to the
least-loaded *matching* replica — its prefix cache already holds the KV
that request would otherwise re-prefill — unless that replica is more
than ``affinity_load_gap`` outstanding requests busier than the global
least-loaded choice (affinity must never defeat balancing under
pressure). Between ticks the router's own ``_affinity_recent`` map
remembers where each prefix key last landed, so a same-prefix burst
co-locates even before the target's next tick advertises the block.
Liveness is ``alive`` (exit code) plus, for subprocess replicas, PR-13
heartbeat staleness; a dead replica's pending rids are re-dispatched
and its unacked migration bundle (SIGTERM that died before a peer
accepted) is re-admitted from disk.
"""

import itertools
import os
import time
from typing import Dict, List, Optional

from deepspeed_tpu.inference.fleet import protocol
from deepspeed_tpu.inference.serving.blocks import prefix_key
from deepspeed_tpu.inference.serving.scheduler import MigrationError
from deepspeed_tpu.utils.logging import log_dist


class FleetRouter:
    """Load-balance requests across replicas; survive their deaths."""

    def __init__(self, telemetry=None, heartbeat_timeout: float = 30.0,
                 affinity: bool = True, affinity_load_gap: float = 8.0):
        self.replicas: Dict[str, object] = {}
        self.telemetry = telemetry
        self.heartbeat_timeout = float(heartbeat_timeout)
        #: prefix-affinity dispatch (the A/B control arm sets False to
        #: measure pure least-loaded on the same trace)
        self.affinity = bool(affinity)
        self.affinity_load_gap = float(affinity_load_gap)
        self._rid_counter = itertools.count()
        #: rid -> {"msg": wire request, "replica": name|None}
        self.pending: Dict[str, dict] = {}
        #: rid -> first done message (at-most-once delivery table)
        self.completed: Dict[str, dict] = {}
        #: rid -> terminal refusal (no alive replica could take it)
        self.failed: Dict[str, str] = {}
        self.duplicate_completions = 0
        self.readmitted = 0  # re-dispatches after death/refusal/migration
        #: replica name -> completions it delivered (balance evidence)
        self.completed_by: Dict[str, int] = {}
        #: prefix key -> replica name of the last dispatch (covers the
        #: advertisement lag of pipe-borne tick signals)
        self._affinity_recent: Dict[str, str] = {}
        self.affinity_hits = 0      # dispatches routed by prefix match
        self.affinity_overruled = 0  # matches dropped by the load-gap guard

    # -- fleet membership ----------------------------------------------
    def add_replica(self, name: str, replica) -> None:
        if name in self.replicas:
            raise ValueError(f"duplicate replica name {name!r}")
        self.replicas[name] = replica

    def remove_replica(self, name: str) -> None:
        self.replicas.pop(name, None)

    def alive_replicas(self) -> Dict[str, object]:
        return {n: r for n, r in self.replicas.items() if self._is_alive(r)}

    def _is_alive(self, replica) -> bool:
        if not replica.alive:
            return False
        age_fn = getattr(replica, "heartbeat_age", None)
        if age_fn is not None:
            age = age_fn()
            if age is not None and age > self.heartbeat_timeout:
                return False  # wedged inside a dispatch: exit never fires
        return True

    # -- submission ----------------------------------------------------
    def submit(self, prompt, max_new_tokens: int,
               eos_token_id: Optional[int] = None) -> str:
        """Admit one request to the fleet; returns its fleet-wide rid."""
        rid = f"r{next(self._rid_counter)}"
        msg = protocol.request_msg(rid, prompt, max_new_tokens, eos_token_id)
        self.pending[rid] = {"msg": msg, "replica": None}
        self.dispatch(rid)
        return rid

    def dispatch(self, rid: str) -> Optional[str]:
        """Send a pending rid to the least-loaded alive replica; returns
        the chosen name (None = no alive replica, stays queued with the
        router until one appears)."""
        rec = self.pending.get(rid)
        if rec is None:
            return None
        alive = self.alive_replicas()
        if not alive:
            rec["replica"] = None
            return None
        name = self._pick_replica(alive, rec["msg"].get("prompt"))
        rec["replica"] = name
        alive[name].send(rec["msg"])
        return name

    def _pick_replica(self, alive: Dict[str, object], prompt) -> str:
        """Least-loaded, upgraded by prefix affinity: prefer the least-
        loaded replica whose advertised ``prefix_hot`` set (or the
        router's own recent-dispatch memory) covers the prompt's first
        block, unless it is ``affinity_load_gap`` busier than the global
        least-loaded pick."""
        base = min(sorted(alive), key=lambda n: alive[n].load())
        if not self.affinity or prompt is None:
            return base
        keys, cands = set(), []
        for n in sorted(alive):
            sig = getattr(alive[n], "signals", lambda: None)() or {}
            bs = sig.get("prefix_block_size")
            if not bs or len(prompt) < bs:
                continue  # < one full block can never match a hot key
            key = prefix_key(prompt[:bs])
            keys.add(key)
            if key in (sig.get("prefix_hot") or ()):
                cands.append(n)
        for key in keys:
            n = self._affinity_recent.get(key)
            if n in alive and n not in cands:
                cands.append(n)
        if not cands:
            for key in keys:
                self._affinity_recent[key] = base
            return base
        best = min(sorted(cands), key=lambda n: alive[n].load())
        if alive[best].load() - alive[base].load() > self.affinity_load_gap:
            self.affinity_overruled += 1
            choice = base
        else:
            self.affinity_hits += 1
            choice = best
        for key in keys:
            self._affinity_recent[key] = choice
        return choice

    # -- event pump ----------------------------------------------------
    def poll(self) -> List[dict]:
        """Drain every replica's outbox, update the accounting tables,
        recover from deaths. Returns the raw messages (tests inspect)."""
        seen: List[dict] = []
        for name, replica in list(self.replicas.items()):
            for msg in replica.poll():
                seen.append(msg)
                self._handle(name, msg)
        for name, replica in list(self.replicas.items()):
            if not self._is_alive(replica):
                self._handle_death(name, replica)
        return seen

    def _handle(self, name: str, msg: dict) -> None:
        kind = msg["type"]
        if kind == "done":
            rid = msg.get("rid")
            self.pending.pop(rid, None)
            if rid in self.completed:
                self.duplicate_completions += 1
                log_dist(f"graft-fleet: duplicate completion for {rid} "
                         f"(from {name}) — first delivery wins")
            else:
                self.completed[rid] = msg
                self.completed_by[name] = self.completed_by.get(name, 0) + 1
        elif kind == "refused":
            rid = msg.get("rid")
            rec = self.pending.get(rid)
            if rec is not None:
                # a drain refusal or admission refusal on one replica is
                # not terminal for the fleet: re-dispatch anywhere else.
                # A request EVERY replica refuses (oversized prompt) is —
                # bounded retries keep it from ping-ponging forever.
                rec["retries"] = rec.get("retries", 0) + 1
                if rec["retries"] > len(self.replicas) + 1:
                    self.failed[rid] = msg.get("reason", "refused")
                    self.pending.pop(rid, None)
                    return
                self.readmitted += 1
                if self.dispatch(rid) is None:
                    self.failed[rid] = msg.get("reason", "refused")
                    self.pending.pop(rid, None)
        elif kind == "migrated_out":
            self._place_bundle(name, msg["bundle"], msg.get("rids") or [])
        elif kind == "migrated_in":
            for rid in msg.get("rids") or []:
                if rid in self.pending:
                    self.pending[rid]["replica"] = name
            for rid in msg.get("refused_rids") or []:
                if rid in self.pending:
                    self.readmitted += 1
                    self.dispatch(rid)
        # 'ready' / 'tick' / 'bye' need no table updates (tick signals are
        # cached by the replica handle itself for load())

    def _place_bundle(self, origin: str, bundle: str, rids: List) -> None:
        """Hand a SIGTERM'd replica's bundle to a peer (migrate_in). With
        no alive peer the bundle stays on disk; the rids stay pending and
        a later re-dispatch re-runs them from the prompt."""
        peers = {n: r for n, r in self.alive_replicas().items() if n != origin}
        if not peers:
            log_dist(f"graft-fleet: no peer for bundle {bundle} — "
                     f"{len(rids)} requests will re-run from scratch")
            for rid in rids:
                if rid in self.pending:
                    self.pending[rid]["replica"] = None
            return
        peer = min(sorted(peers), key=lambda n: peers[n].load())
        for rid in rids:
            if rid in self.pending:
                self.pending[rid]["replica"] = peer
        peers[peer].send({"type": "migrate_in", "bundle": bundle})
        if self.telemetry is not None:
            self.telemetry.emit("fleet_migrate_route", origin=origin,
                                peer=peer, bundle=bundle, rids=len(rids))

    def _handle_death(self, name: str, replica) -> None:
        """A dead replica's pending rids are re-dispatched (at-least-once
        re-admission; the ``completed`` table keeps delivery at-most-once)
        and its on-disk bundle, if any was published but never routed, is
        recovered."""
        self.remove_replica(name)
        orphaned = [rid for rid, rec in self.pending.items()
                    if rec["replica"] == name]
        bundle = getattr(replica, "bundle_dir", None)
        if orphaned:
            log_dist(f"graft-fleet: replica {name} died "
                     f"(exit {getattr(replica, 'exit_code', None)}) with "
                     f"{len(orphaned)} requests outstanding — re-admitting")
        if orphaned and bundle and os.path.isdir(bundle):
            # SIGTERM published a bundle but died before a peer took it
            # (orphaned empty ⇒ the migrated_out message already routed
            # it — re-admitting from disk would duplicate the work)
            try:
                rids = self._readmit_bundle(bundle)
                orphaned = [r for r in orphaned if r not in rids]
            except MigrationError as e:
                log_dist(f"graft-fleet: bundle {bundle} unusable ({e}); "
                         f"falling back to re-run")
        for rid in orphaned:
            self.readmitted += 1
            self.pending[rid]["replica"] = None
            self.dispatch(rid)
        if self.telemetry is not None:
            self.telemetry.emit("fleet_replica_death", replica=name,
                                readmitted=len(orphaned))

    def _readmit_bundle(self, bundle: str) -> List:
        from deepspeed_tpu.inference.fleet.migrate import bundle_rids, load_bundle
        payloads = load_bundle(bundle)
        rids = bundle_rids(payloads)
        peers = self.alive_replicas()
        if not peers:
            raise MigrationError("no alive replica to receive the bundle")
        peer = min(sorted(peers), key=lambda n: peers[n].load())
        for rid in rids:
            if rid in self.pending:
                self.pending[rid]["replica"] = peer
        peers[peer].send({"type": "migrate_in", "bundle": bundle})
        return [r for r in rids if r is not None]

    # -- driving (local fleets) ----------------------------------------
    def step(self, ticks: int = 1) -> List[dict]:
        """Advance every LocalReplica ``ticks`` scheduler ticks, then
        poll. Subprocess replicas advance themselves; their messages are
        picked up by the same poll."""
        for replica in self.replicas.values():
            pump = getattr(replica, "pump", None)
            if pump is not None and replica.alive:
                pump(ticks)
        return self.poll()

    def run_until_complete(self, max_rounds: int = 100000,
                           idle_sleep: float = 0.0) -> int:
        """Pump/poll until nothing is pending; returns rounds used."""
        rounds = 0
        while self.pending and rounds < max_rounds:
            self.step()
            rounds += 1
            if idle_sleep:
                time.sleep(idle_sleep)
        return rounds

    # -- evidence ------------------------------------------------------
    def stats(self) -> dict:
        return {
            "replicas": len(self.replicas),
            "alive": len(self.alive_replicas()),
            "pending": len(self.pending),
            "completed": len(self.completed),
            "failed": len(self.failed),
            "duplicate_completions": self.duplicate_completions,
            "readmitted": self.readmitted,
            "completed_by": dict(self.completed_by),
            "affinity": self.affinity,
            "affinity_hits": self.affinity_hits,
            "affinity_overruled": self.affinity_overruled,
        }
