"""graft-fleet replica worker: ``python -m deepspeed_tpu.inference.fleet.worker``.

One serving process in the fleet: builds an engine + continuous-batching
scheduler (serve_bench's construction path), then loops — requests in as
line-delimited JSON on stdin, ``done``/``tick`` out on stdout
(``protocol.py``), logs on stderr, liveness through the PR-13 heartbeat
file the scheduler touches every tick.

SIGTERM is the migrate path: refuse the queue (``refused`` messages the
router re-dispatches), export every in-flight request's KV through the
manifest+digest bundle codec, announce ``migrated_out``, exit 143. A
``MigrationError`` (sampling on, save failed) falls back to the PR-14
drain — finish in-flight locally, then exit 143. SIGKILL gets no say,
which is the point: the router's heartbeat probe + at-most-once
re-admission are what recover from it.

Env (set by :class:`SubprocessReplica` / the fleet bench):
  FLEET_MODEL=test        model family config (gpt2 families)
  FLEET_SLOTS=4           decode slots
  FLEET_CHUNK=16          prefill chunk
  FLEET_POSITIONS=128     context length
  FLEET_KV_QUANT=1        int8 KV pools
  FLEET_PREFIX_CACHE=     on|off: content-hashed KV prefix caching
                          (unset = the DS_SERVE_PREFIX_CACHE/config
                          resolution, default on)
  FLEET_POOL_TOKENS=0     KV pool token budget (0 = slots x context);
                          the serve_prefix_fleet_* rungs size this
                          ABOVE slots x context so the pool has spare
                          capacity for cached prefixes
  FLEET_TICK_SLEEP_MS=0   emulated per-tick device time: on a real fleet
                          each replica owns an accelerator and the host
                          CPU idles while the tick runs on-device; the
                          1-core CPU rig has no such idle, so the
                          scaling row sleeps this long after each step
                          to reproduce the device-bound regime
  FLEET_BUNDLE_DIR=...    where a SIGTERM lands the migration bundle
  FLEET_TELEMETRY_DIR=... JSONL run dir (serve_tick etc.); unset = off
  FLEET_NAME=...          replica name (telemetry job name)
  DS_ELASTIC_HEARTBEAT_FILE=...  liveness file (parent-owned)
"""

import os
import select
import sys
import time

import numpy as np


def build_scheduler():
    import deepspeed_tpu
    from deepspeed_tpu.inference.serving import (ContinuousBatchingScheduler,
                                                 ServingConfig)
    from deepspeed_tpu.models import GPT2LMHeadModel, get_gpt2_config

    model = os.environ.get("FLEET_MODEL", "test")
    positions = int(os.environ.get("FLEET_POSITIONS", "128"))
    cfg = get_gpt2_config(model, n_positions=positions, dtype=None)
    engine = deepspeed_tpu.init_inference(GPT2LMHeadModel(cfg),
                                          replace_with_kernel_inject=True,
                                          max_out_tokens=positions)
    telemetry = None
    tdir = os.environ.get("FLEET_TELEMETRY_DIR")
    if tdir:
        from deepspeed_tpu.runtime.config import TelemetryConfig
        from deepspeed_tpu.runtime.telemetry import RuntimeTelemetry
        telemetry = RuntimeTelemetry(TelemetryConfig(
            enabled=True, output_path=tdir,
            job_name=os.environ.get("FLEET_NAME", f"replica_{os.getpid()}")))
    scfg = ServingConfig(
        slots=int(os.environ.get("FLEET_SLOTS", "4")),
        prefill_chunk=int(os.environ.get("FLEET_CHUNK", "16")),
        kv_quant=os.environ.get("FLEET_KV_QUANT", "1") == "1",
        kv_pool_tokens=int(os.environ.get("FLEET_POOL_TOKENS", "0")) or None,
        prefix_cache=os.environ.get("FLEET_PREFIX_CACHE") or None)
    sched = ContinuousBatchingScheduler(engine, scfg, telemetry=telemetry)
    if telemetry is not None:
        # the run header carries the serving program's static price +
        # backend/scope so this replica's JSONL is a graft-calibrate fit
        # source (scope serve_decode) exactly like a training run's
        import jax
        telemetry.write_run_header(
            {"bench": "fleet_worker", "model": model, "pid": os.getpid(),
             "backend": jax.default_backend(), "scope": "serve_decode",
             # graft-calibrate separation markers: runs whose prefill is
             # partly served from the prefix cache must not pool with
             # full-prefill serve_decode samples (the field's PRESENCE is
             # what collect_samples keys its mixed-run refusal on; the
             # per-request counts land in serve_request events)
             "prefix_cache": sched.prefix_cache,
             "cached_prefix_tokens": 0},
            static_price=sched.serving_static_price())
    sched.warmup()
    return sched, telemetry


def main() -> int:
    from deepspeed_tpu.inference.fleet import protocol
    from deepspeed_tpu.inference.fleet.migrate import bundle_rids, save_bundle
    from deepspeed_tpu.inference.serving import MigrationError, Request
    from deepspeed_tpu.runtime.resilience.signals import (
        DEFAULT_PREEMPT_EXIT_CODE, PreemptionGuard)

    out = sys.stdout
    guard = PreemptionGuard().install()
    sched, telemetry = build_scheduler()
    protocol.send(out, {"type": "ready", "pid": os.getpid(),
                        "slots": sched.slots, "capacity": sched.capacity})

    stdin_fd = sys.stdin.fileno()
    os.set_blocking(stdin_fd, False)
    buf = b""
    fin_idx = 0
    stopping = False
    tick = 0
    last_idle_tick = 0.0
    last_busy_tick = 0.0
    tick_sleep = float(os.environ.get("FLEET_TICK_SLEEP_MS", "0")) / 1e3

    def drain_finished():
        nonlocal fin_idx
        while fin_idx < len(sched.finished):
            req = sched.finished[fin_idx]
            fin_idx += 1
            protocol.send(out, {"type": "done",
                                "rid": req.meta.get("fleet_rid"),
                                "output": list(req.output),
                                "stats": req.stats()})

    def read_msgs():
        nonlocal buf
        msgs = []
        while True:
            try:
                ready, _, _ = select.select([stdin_fd], [], [], 0)
            except (OSError, ValueError):
                return msgs, True
            if not ready:
                return msgs, False
            try:
                chunk = os.read(stdin_fd, 65536)
            except (BlockingIOError, OSError):
                return msgs, False
            if not chunk:  # router hung up
                return msgs, True
            buf += chunk
            while b"\n" in buf:
                line, buf = buf.split(b"\n", 1)
                msg = protocol.parse_line(line.decode("utf-8", "replace"))
                if msg is not None:
                    msgs.append(msg)

    while True:
        if guard.requested:
            signal_name = guard.consume()
            refused = sched.queue.refuse_all(f"draining on {signal_name}")
            for req in refused:
                protocol.send(out, {"type": "refused",
                                    "rid": req.meta.get("fleet_rid"),
                                    "reason": req.refuse_reason})
            if telemetry is not None:
                telemetry.emit("serve_drain", signal=signal_name,
                               in_flight=len(sched.in_flight),
                               refused=len(refused))
            if sched.in_flight:
                bundle_dir = os.environ.get(
                    "FLEET_BUNDLE_DIR", f"/tmp/fleet_bundle_{os.getpid()}")
                try:
                    payloads = sched.export_inflight(release=False)
                    save_bundle(payloads, bundle_dir)
                    sched.release_inflight()
                    protocol.send(out, {"type": "migrated_out",
                                        "bundle": bundle_dir,
                                        "rids": bundle_rids(payloads)})
                    if telemetry is not None:
                        telemetry.emit("serve_migrate_out", signal=signal_name,
                                       migrated=len(payloads),
                                       bundle=bundle_dir)
                except MigrationError as e:
                    print(f"fleet worker: migration refused ({e}) — draining",
                          file=sys.stderr, flush=True)
                    sched.run_until_drained(admit=False)
                    drain_finished()
            protocol.send(out, {"type": "bye",
                                "exit": DEFAULT_PREEMPT_EXIT_CODE})
            if telemetry is not None:
                telemetry.close()
            return DEFAULT_PREEMPT_EXIT_CODE

        msgs, eof = read_msgs()
        for msg in msgs:
            kind = msg["type"]
            if kind == "request":
                req = Request(prompt=np.asarray(msg["prompt"], np.int32),
                              max_new_tokens=msg["max_new_tokens"],
                              eos_token_id=msg.get("eos_token_id"))
                req.meta["fleet_rid"] = msg["rid"]
                sched.submit(req)
                if req.state == "refused":
                    protocol.send(out, {"type": "refused", "rid": msg["rid"],
                                        "reason": req.refuse_reason})
            elif kind == "migrate_in":
                from deepspeed_tpu.inference.fleet.migrate import receive_bundle
                try:
                    admitted, refused_p = receive_bundle(sched, msg["bundle"])
                    protocol.send(out, {
                        "type": "migrated_in",
                        "rids": [r.meta.get("fleet_rid") for r in admitted],
                        "refused_rids": bundle_rids(refused_p)})
                except MigrationError as e:
                    print(f"fleet worker: bundle refused ({e})",
                          file=sys.stderr, flush=True)
                    protocol.send(out, {"type": "migrated_in", "rids": [],
                                        "refused_rids": [],
                                        "error": str(e)})
            elif kind == "stop":
                stopping = True

        if sched.in_flight or len(sched.queue):
            sched.step()
            if tick_sleep:
                time.sleep(tick_sleep)
            tick += 1
            drain_finished()
            # load signals are a cadence, not a per-step obligation — a
            # tick message per step doubles the pipe traffic the router
            # must parse while the signals barely change
            now = time.monotonic()
            if now - last_busy_tick > 0.05:
                last_busy_tick = now
                protocol.send(out, {"type": "tick",
                                    "signals": sched.signals()})
        elif stopping or eof:
            protocol.send(out, {"type": "bye", "exit": 0})
            if telemetry is not None:
                telemetry.close()
            return 0
        else:
            # idle: stay alive (heartbeat) and wait for work without
            # burning a core; a cadenced tick message keeps the router's
            # load view fresh even with no requests moving
            sched._touch_serving_heartbeat(tick)
            now = time.monotonic()
            if now - last_idle_tick > 0.2:
                last_idle_tick = now
                protocol.send(out, {"type": "tick",
                                    "signals": sched.signals()})
            try:
                select.select([stdin_fd], [], [], 0.02)
            except (OSError, ValueError):
                pass


if __name__ == "__main__":
    sys.exit(main())
