"""Paged KV cache: a shared page pool + per-sequence block tables
(reference ``csrc/transformer/inference/includes/inference_context.h`` KV
workspace management + ``pt_binding.cpp:1928`` ``allocate_workspace``).

The reference carves one big workspace and hands each request offsets into
it. The TPU formulation keeps a static-shape page pool
``[num_pages, page_size, heads, dim]`` (XLA-friendly) and drives it with a
host-side allocator: sequences own page lists, freeing returns pages to the
pool, and ``gather`` materializes a dense [b, L] view for attention via one
``jnp.take`` (the gather IS the block-table lookup). Memory scales with
TOKENS IN FLIGHT, not batch × max_len.
"""

from typing import List, Optional, Tuple

import numpy as np

import jax
import jax.numpy as jnp


class PagedKVCache:
    """One layer's K and V pools + the shared allocator state."""

    def __init__(self, num_pages: int, page_size: int, num_heads: int, head_dim: int,
                 num_layers: int = 1, dtype=jnp.bfloat16, quantize: bool = True):
        """``quantize=True`` (the serving default since graft-quant-serve —
        int8 KV is how the block pool admits deeper on the same HBM):
        pools store int8 with one bf16 scale per (page, position, head) —
        the reference's int8 KV path (``inference_context.h`` int8
        workspaces + dequant kernels) at 2x the tokens-in-flight per HBM
        byte; ``gather`` dequantizes on read into the compute dtype.
        ``quantize=False`` keeps exact fp pools (parity debugging)."""
        self.num_pages = num_pages
        self.page_size = page_size
        self.num_layers = num_layers
        self.quantize = quantize
        self.dtype = dtype
        shape = (num_layers, num_pages, page_size, num_heads, head_dim)
        pool_dtype = jnp.int8 if quantize else dtype
        self.k_pool = jnp.zeros(shape, pool_dtype)
        self.v_pool = jnp.zeros(shape, pool_dtype)
        if quantize:
            sshape = (num_layers, num_pages, page_size, num_heads, 1)
            # bf16 scales: same byte cost as fp16 but the fp32 exponent
            # range, so outlier K/V magnitudes cannot overflow to inf
            self.k_scale = jnp.zeros(sshape, jnp.bfloat16)
            self.v_scale = jnp.zeros(sshape, jnp.bfloat16)
        # allocator bookkeeping delegates to the shared BlockPool (the same
        # accounting the serving scheduler's admission control runs on, so
        # its counters — allocs/frees/peak/fragmentation — are one code path)
        from deepspeed_tpu.inference.serving.blocks import BlockPool
        self.pool = BlockPool(num_blocks=num_pages, block_size=page_size)

        # donated in-place page write: O(page) update, no pool copy
        def write(pool, vals, layer, page, in_page):
            return jax.lax.dynamic_update_slice(
                pool, vals[None, None].astype(pool.dtype), (layer, page, in_page, 0, 0))

        self._write = jax.jit(write, donate_argnums=(0,))

        def quant(vals):
            # per-(token, head) groups through the shared quantizer library
            # (ops/quantizer/core — one int8 implementation repo-wide; the
            # last-axis form is shape/sharding-preserving)
            from deepspeed_tpu.ops.quantizer.core import quantize_lastaxis
            q, scale = quantize_lastaxis(vals, num_bits=8)
            return q, scale.astype(jnp.bfloat16)

        self._quant = jax.jit(quant)

    # ------------------------------------------------------------------
    # allocator (host side — the reference's workspace bookkeeping)
    # ------------------------------------------------------------------
    @property
    def free_pages(self) -> int:
        return self.pool.free_blocks

    def allocate(self, seq_id: int) -> None:
        self.pool.allocate(seq_id)

    def free(self, seq_id: int) -> None:
        """Return a sequence's pages to the pool (reference frees by resetting
        the workspace offset; pages make it per-sequence)."""
        self.pool.free(seq_id)

    def _ensure_capacity(self, seq_id: int, new_tokens: int) -> None:
        try:
            self.pool.ensure(seq_id, new_tokens)
        except RuntimeError:
            raise RuntimeError(f"KV page pool exhausted ({self.num_pages} pages of "
                               f"{self.page_size}); free finished sequences first")

    def seq_len(self, seq_id: int) -> int:
        return self.pool.seq_len(seq_id)

    def block_table(self, seq_id: int) -> List[int]:
        return self.pool.block_table(seq_id)

    def counters(self) -> dict:
        """Allocator accounting (allocs/frees/peak/fragmentation) — the
        admission-control evidence surface, shared with BlockPool."""
        return self.pool.counters()

    # ------------------------------------------------------------------
    # device ops
    # ------------------------------------------------------------------
    def append(self, seq_id: int, k: jax.Array, v: jax.Array, layer: int = 0) -> None:
        """Write [t, heads, dim] new tokens for one sequence/layer."""
        t = k.shape[0]
        if layer == 0:
            self._ensure_capacity(seq_id, t)
        start = self.pool.seq_len(seq_id)
        table = self.pool.block_table(seq_id)
        # split the token run across page boundaries; each write is a jitted
        # donated dynamic_update_slice — O(page), never an O(pool) copy
        if self.quantize:
            k, k_s = self._quant(k)
            v, v_s = self._quant(v)
        off = 0
        while off < t:
            page_idx = (start + off) // self.page_size
            in_page = (start + off) % self.page_size
            n = min(self.page_size - in_page, t - off)
            page = table[page_idx]
            args = (jnp.int32(layer), jnp.int32(page), jnp.int32(in_page))
            self.k_pool = self._write(self.k_pool, k[off:off + n], *args)
            self.v_pool = self._write(self.v_pool, v[off:off + n], *args)
            if self.quantize:
                self.k_scale = self._write(self.k_scale, k_s[off:off + n], *args)
                self.v_scale = self._write(self.v_scale, v_s[off:off + n], *args)
            off += n
        if layer == self.num_layers - 1:
            # capacity was ensured at layer 0; this only advances the length
            self.pool.advance(seq_id, t)

    def gather(self, seq_ids: List[int], layer: int = 0,
               pad_to: Optional[int] = None) -> Tuple[jax.Array, jax.Array, jax.Array]:
        """Dense [b, L, heads, dim] K/V views + [b] true lengths. ``pad_to``
        buckets L so the consumer's attention program doesn't recompile per
        batch composition."""
        max_len = max(self.pool.seq_len(s) for s in seq_ids)
        L = pad_to or max_len
        assert L >= max_len
        pages_per = (L + self.page_size - 1) // self.page_size
        table = np.zeros((len(seq_ids), pages_per), np.int32)
        for i, s in enumerate(seq_ids):
            for j, p in enumerate(self.pool.block_table(s)[:pages_per]):
                table[i, j] = p
        # one gather = the block-table lookup: [b, pages_per, page, h, d]
        tbl = jnp.asarray(table)
        k = jnp.take(self.k_pool[layer], tbl, axis=0)
        v = jnp.take(self.v_pool[layer], tbl, axis=0)
        if self.quantize:
            k = k.astype(self.dtype) * jnp.take(self.k_scale[layer], tbl, axis=0).astype(self.dtype)
            v = v.astype(self.dtype) * jnp.take(self.v_scale[layer], tbl, axis=0).astype(self.dtype)
        b = len(seq_ids)
        k = k.reshape(b, pages_per * self.page_size, *k.shape[3:])[:, :L]
        v = v.reshape(b, pages_per * self.page_size, *v.shape[3:])[:, :L]
        lengths = jnp.asarray([self.pool.seq_len(s) for s in seq_ids], jnp.int32)
        return k, v, lengths

    def utilization(self) -> float:
        return self.pool.utilization()
