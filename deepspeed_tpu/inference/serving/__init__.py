"""graft-serve: continuous in-flight batching with chunked prefill and
speculative decoding (ISSUE 14 / ROADMAP item 1)."""

from deepspeed_tpu.inference.serving.blocks import BlockPool
from deepspeed_tpu.inference.serving.events import (SERVE_EVENT_SCHEMAS,
                                                    iter_serve_events,
                                                    last_tick_signals,
                                                    validate_event)
from deepspeed_tpu.inference.serving.config import (ENV_KV_WRITE,
                                                    ENV_PREFIX_CACHE,
                                                    ENV_WEIGHT_DTYPE,
                                                    ServingConfig,
                                                    SpeculationConfig,
                                                    resolve_intended_kv_write,
                                                    resolve_intended_prefix_cache,
                                                    resolve_intended_weight_dtype,
                                                    resolve_kv_write,
                                                    resolve_prefix_cache,
                                                    resolve_weight_dtype,
                                                    set_default_kv_write,
                                                    set_default_prefix_cache,
                                                    set_default_weight_dtype)
from deepspeed_tpu.inference.serving.programs import (make_slot_cache,
                                                      serve_programs,
                                                      slot_capacity,
                                                      stamp_lengths)
from deepspeed_tpu.inference.serving.queue import RequestQueue
from deepspeed_tpu.inference.serving.request import (ACTIVE, FINISHED, PREFILL,
                                                     QUEUED, REFUSED, Request)
from deepspeed_tpu.inference.serving.scheduler import (MIGRATABLE_STATES,
                                                       ContinuousBatchingScheduler,
                                                       MigrationError)

__all__ = [
    "ACTIVE", "FINISHED", "PREFILL", "QUEUED", "REFUSED",
    "BlockPool", "ContinuousBatchingScheduler", "ENV_KV_WRITE",
    "ENV_PREFIX_CACHE", "ENV_WEIGHT_DTYPE", "MIGRATABLE_STATES",
    "MigrationError", "Request",
    "RequestQueue", "SERVE_EVENT_SCHEMAS", "ServingConfig",
    "SpeculationConfig", "iter_serve_events", "last_tick_signals",
    "make_slot_cache",
    "resolve_intended_kv_write", "resolve_intended_prefix_cache",
    "resolve_intended_weight_dtype",
    "resolve_kv_write", "resolve_prefix_cache", "resolve_weight_dtype",
    "serve_programs",
    "set_default_kv_write", "set_default_prefix_cache",
    "set_default_weight_dtype", "slot_capacity",
    "stamp_lengths", "validate_event",
]
