"""Host-side KV block pool: the allocator behind both the paged KV cache
(``inference/paged_kv.py`` delegates its table bookkeeping here) and the
continuous-batching scheduler's admission control.

The reference carves one workspace and hands out offsets
(``inference_context.h``); block granularity makes the accounting
per-sequence and gives admission control a truthful currency: a request
is admitted only when ``blocks_for(prompt + max_new)`` blocks are free,
so the decode loop can never hit pool exhaustion mid-flight. Counters
(allocs/frees/peak/fragmentation) are exposed because the scheduler's
no-leak gate and the serve bench both read them as evidence.

graft-prefix-cache (ISSUE 19) rebuilds the pool ref-counted and
content-addressed. Every *full* block a sequence commits can be
``publish``ed under a rolling hash of ``(parent_block_hash, token_ids,
envelope)`` — the chained key means two blocks share a hash only when
their entire token prefix from position 0 is identical, so a hash hit
is a correctness-safe KV reuse. Freed blocks whose hash is still live
park on an LRU *cached-free* list instead of returning to the free list:
still reclaimable (``free_blocks`` counts them; eviction pops LRU when
the free list runs dry) but matchable until then. A new prompt is
matched block-by-block at reservation time; matched full blocks attach
by reference (ref += 1, zero new blocks), a partially-matching last
block is copy-on-write (the match reports how many rows to copy into a
FRESH private block — the shared block itself is never attached, never
mutated), and at least one prompt token is always left uncached so the
tail prefill produces the first-token logits.

The pool stays host-only accounting: it never touches device KV. The
opaque per-block ``payload`` (host KV rows, stored by the scheduler at
publish time) is what makes a hash hit restorable — blocks published
without a payload are not matchable, because admitting a prefix skip
without the bytes to restore would be silent corruption.
"""

import hashlib
from collections import OrderedDict
from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional, Sequence

#: chain seed for position-0 blocks (the hash "parent" of the first block)
_ROOT = "root"


def prefix_key(tokens: Sequence[int]) -> str:
    """Envelope-free content key of ONE block's token ids — the fleet
    affinity currency. Unlike :func:`chain_hash` it ignores the pool's
    kv_quant/weight-dtype envelope, so a router (which knows neither) can
    compute the same key from a raw prompt's first block and compare it
    against a replica's advertised hot set."""
    h = hashlib.sha256()
    for t in tokens:
        h.update(int(t).to_bytes(4, "little", signed=True))
    return h.hexdigest()[:16]


def chain_hash(parent: str, tokens: Sequence[int], envelope: str = "") -> str:
    """Rolling content hash of one full block: ``(parent_block_hash,
    token_ids, envelope)``. The envelope folds in whatever makes KV bytes
    non-interchangeable (kv_quant, served weight dtype, speculation) so a
    pool can never serve a cached block produced under different
    numerics."""
    h = hashlib.sha256()
    h.update(parent.encode("utf-8"))
    h.update(b"|")
    h.update(envelope.encode("utf-8"))
    h.update(b"|")
    for t in tokens:
        h.update(int(t).to_bytes(4, "little", signed=True))
    return h.hexdigest()


@dataclass
class PrefixMatch:
    """Result of matching a prompt against the hash index: what
    :meth:`BlockPool.reserve` attached and what the scheduler must
    restore into the slot before prefilling the tail.

    ``payloads`` has one entry per matched full block; ``partial_payload``
    (when ``partial_tokens > 0``) is the SHARED source block's payload —
    the consumer copies its first ``partial_tokens`` rows into the fresh
    private block reserve() already charged (copy-on-write: the shared
    block is never attached to the new sequence)."""

    cached_tokens: int = 0
    full_hashes: List[str] = field(default_factory=list)
    payloads: List[object] = field(default_factory=list)
    partial_payload: Optional[object] = None
    partial_tokens: int = 0


class BlockPool:
    """Fixed pool of ``num_blocks`` blocks of ``block_size`` tokens.

    ``prefix_cache=False`` (the paged-KV default) behaves exactly like
    the pre-ISSUE-19 pool: blocks are private, freed blocks return to
    the LIFO free list, nothing is hashed. ``prefix_cache=True`` turns
    on the content index + cached-free LRU described in the module doc.
    """

    def __init__(self, num_blocks: int, block_size: int,
                 prefix_cache: bool = False, envelope: str = ""):
        if num_blocks < 1 or block_size < 1:
            raise ValueError(f"need num_blocks >= 1 and block_size >= 1, got "
                             f"({num_blocks}, {block_size})")
        self.num_blocks = int(num_blocks)
        self.block_size = int(block_size)
        self.prefix_cache = bool(prefix_cache)
        self.envelope = str(envelope)
        # LIFO free list: freed blocks are reused hottest-first
        self._free: List[int] = list(range(num_blocks - 1, -1, -1))
        self._tables: Dict[int, List[int]] = {}   # seq id -> block list
        self._lengths: Dict[int, int] = {}        # seq id -> tokens used
        # content index (prefix_cache only): a block is *hashed* once its
        # full token content is published, *cached* once every reference
        # dropped but the hash is still worth matching
        self._refs: Dict[int, int] = {}           # block id -> live refs
        self._hash_of: Dict[int, str] = {}        # block id -> chain hash
        self._block_of: Dict[str, int] = {}       # chain hash -> block id
        self._tokens_of: Dict[str, tuple] = {}    # chain hash -> block tokens
        self._parent_of: Dict[str, str] = {}      # chain hash -> parent hash
        self._children: Dict[str, set] = {}       # parent hash -> child hashes
        self._payload: Dict[str, object] = {}     # chain hash -> opaque payload
        self._cached: "OrderedDict[str, int]" = OrderedDict()  # LRU: hash -> block
        self._matches: Dict[int, PrefixMatch] = {}  # seq id -> pending match
        # accounting for admission control + the scheduler's no-leak gate
        self.total_allocs = 0
        self.total_frees = 0
        self.peak_used_blocks = 0
        self.prefix_hits = 0          # reservations that reused >= 1 token
        self.prefix_misses = 0        # prompt-bearing reservations that didn't
        self.cached_tokens_served = 0  # total prompt tokens skipped via match
        self.prefix_evictions = 0     # cached-free blocks reclaimed under pressure
        self.published_blocks = 0     # blocks ever entered into the hash index

    # -- capacity ----------------------------------------------------------
    def blocks_for(self, tokens: int) -> int:
        return -(-max(int(tokens), 0) // self.block_size)

    @property
    def free_blocks(self) -> int:
        """Reclaimable blocks: truly free plus cached-free (a cached block
        is evicted on demand, so admission may count on it)."""
        return len(self._free) + len(self._cached)

    @property
    def used_blocks(self) -> int:
        return self.num_blocks - self.free_blocks

    @property
    def cached_blocks(self) -> int:
        """Cached-free blocks (ref 0, hash live, LRU-evictable)."""
        return len(self._cached)

    def can_allocate(self, tokens: int, prompt=None) -> bool:
        """Side-effect-free admission probe. With a ``prompt`` and the
        prefix cache on, matched full blocks that are currently IN USE by
        another sequence cost nothing (they attach by reference); matched
        cached-free blocks still count — reviving one consumes it from
        the reclaimable pool just like a fresh allocation."""
        need = self.blocks_for(tokens)
        if prompt is not None and self.prefix_cache:
            m = self.match_prefix(prompt)
            need -= sum(1 for h in m.full_hashes if h not in self._cached)
        return need <= self.free_blocks

    def utilization(self) -> float:
        return self.used_blocks / self.num_blocks

    def fragmentation_tokens(self) -> int:
        """Allocated-but-unused token slots (block-rounding waste plus any
        reserved-ahead capacity): the admission controller's honesty
        metric — high fragmentation means the pool refuses requests whose
        tokens would actually fit. Clamped at zero: shared prefix blocks
        make the sum of sequence lengths exceed the distinct blocks
        backing them, which is negative waste."""
        return max(0, self.used_blocks * self.block_size
                   - sum(self._lengths.values()))

    # -- content index -----------------------------------------------------
    def match_prefix(self, prompt) -> PrefixMatch:
        """Walk ``prompt`` block-by-block down the hash chain; stop at the
        first unindexed block. Always leaves >= 1 prompt token uncached
        (the tail prefill must produce the first-token logits), which is
        why a fully-indexed block-aligned prompt still ends in a
        ``block_size - 1``-row copy-on-write partial match. Blocks
        published without a payload are unmatchable — there would be no
        bytes to restore. Read-only: attaching happens in :meth:`reserve`."""
        m = PrefixMatch()
        if not self.prefix_cache:
            return m
        toks = [int(t) for t in prompt]
        limit = len(toks) - 1
        pos, parent = 0, _ROOT
        while pos + self.block_size <= limit:
            h = chain_hash(parent, toks[pos:pos + self.block_size], self.envelope)
            if h not in self._block_of or self._payload.get(h) is None:
                break
            m.full_hashes.append(h)
            m.payloads.append(self._payload[h])
            parent = h
            pos += self.block_size
        # partial last block: longest common prefix among the chain
        # children of the last matched block (COW — rows are copied out,
        # the shared block is never attached)
        best_k, best_h = 0, None
        for h in self._children.get(parent, ()):
            if h not in self._block_of or self._payload.get(h) is None:
                continue
            t = self._tokens_of.get(h, ())
            cap = min(len(t), limit - pos)
            k = 0
            while k < cap and toks[pos + k] == t[k]:
                k += 1
            if k > best_k:
                best_k, best_h = k, h
        if best_k > 0:
            m.partial_tokens = best_k
            m.partial_payload = self._payload.get(best_h)
        m.cached_tokens = pos + best_k
        return m

    def take_match(self, seq_id: int) -> Optional[PrefixMatch]:
        """Pop the :class:`PrefixMatch` a prompt-bearing :meth:`reserve`
        stashed — the consumer restores its payload rows into the slot
        exactly once, at admission."""
        return self._matches.pop(seq_id, None)

    def publish(self, seq_id: int, tokens,
                fetch: Optional[Callable[[int, int], object]] = None) -> int:
        """Enter ``seq_id``'s committed full blocks into the hash index.

        ``tokens`` is the sequence content backing the table (committed
        prompt, or prompt + generated output at retirement); only whole
        blocks index. ``fetch(start, stop)`` supplies the opaque payload
        (host KV rows) for a newly-indexed block — called only for blocks
        not already hashed, so re-publishing a matched prefix is free.
        Returns the number of blocks newly indexed. No-op when the prefix
        cache is off."""
        if not self.prefix_cache:
            return 0
        table = self._tables[seq_id]
        toks = [int(t) for t in tokens]
        n_full = min(len(toks) // self.block_size, len(table))
        parent, added = _ROOT, 0
        for i in range(n_full):
            blk = toks[i * self.block_size:(i + 1) * self.block_size]
            b = table[i]
            have = self._hash_of.get(b)
            if have is not None:
                # attached via prefix match — content identical by
                # construction, chain continues from the existing hash
                parent = have
                continue
            h = chain_hash(parent, blk, self.envelope)
            if h in self._block_of:
                # identical content raced into another block (two
                # same-prefix requests prefilled concurrently): keep the
                # first copy canonical, leave this block private
                parent = h
                continue
            self._hash_of[b] = h
            self._block_of[h] = b
            self._tokens_of[h] = tuple(blk)
            self._parent_of[h] = parent
            self._children.setdefault(parent, set()).add(h)
            self._payload[h] = fetch(i * self.block_size,
                                     (i + 1) * self.block_size) \
                if fetch is not None else None
            self.published_blocks += 1
            added += 1
            parent = h
        return added

    def hot_prefixes(self, limit: int = 16) -> List[str]:
        """Envelope-free :func:`prefix_key`s of the indexed position-0
        blocks — what a replica advertises in its tick signals so the
        fleet router can route same-prefix requests back to it."""
        out: List[str] = []
        for h in self._children.get(_ROOT, ()):
            t = self._tokens_of.get(h)
            if t:
                out.append(prefix_key(t))
            if len(out) >= limit:
                break
        return out

    def _drop_hash(self, h: str) -> None:
        """Unindex one hash (eviction / non-cacheable free). Children of
        ``h`` stay indexed but become unreachable from the match walk;
        the LRU reclaims them in their own time."""
        b = self._block_of.pop(h, None)
        if b is not None:
            self._hash_of.pop(b, None)
        self._tokens_of.pop(h, None)
        self._payload.pop(h, None)
        parent = self._parent_of.pop(h, None)
        kids = self._children.get(parent)
        if kids is not None:
            kids.discard(h)
            if not kids:
                del self._children[parent]

    def _take_block(self) -> int:
        """One free block: the free list first, then LRU eviction of a
        cached-free block (never a block with live refs — those are not
        on the cached list by invariant). RuntimeError on true
        exhaustion, same contract as before."""
        if self._free:
            return self._free.pop()
        if self._cached:
            h, b = next(iter(self._cached.items()))
            del self._cached[h]
            self._drop_hash(h)
            self.prefix_evictions += 1
            return b
        raise RuntimeError(f"KV block pool exhausted ({self.num_blocks} "
                           f"blocks of {self.block_size}); free finished "
                           f"sequences first")

    # -- per-sequence ------------------------------------------------------
    def allocate(self, seq_id: int) -> None:
        if seq_id in self._tables:
            raise KeyError(f"BlockPool.allocate: sequence {seq_id!r} already "
                           f"allocated")
        self._tables[seq_id] = []
        self._lengths[seq_id] = 0
        self.total_allocs += 1

    def ensure(self, seq_id: int, new_tokens: int) -> None:
        """Grow ``seq_id``'s table to cover ``new_tokens`` more tokens;
        raises ``RuntimeError`` on exhaustion (callers using
        :meth:`can_allocate` for admission never see it)."""
        need = self._lengths[seq_id] + int(new_tokens)
        table = self._tables[seq_id]
        while len(table) * self.block_size < need:
            b = self._take_block()
            self._refs[b] = 1
            table.append(b)
            self.peak_used_blocks = max(self.peak_used_blocks, self.used_blocks)

    def reserve(self, seq_id: int, tokens: int, prompt=None) -> None:
        """Allocate + pre-grow in one step (admission-time reservation).

        With a ``prompt`` and the prefix cache on, the indexed prefix
        attaches first: matched full blocks by reference (cached-free
        ones revive off the LRU), a partial match charges one FRESH
        private block (copy-on-write), and only the uncached tail grows
        new blocks. The sequence's length starts at ``cached_tokens`` —
        the consumer reads the :class:`PrefixMatch` via
        :meth:`take_match` and restores payload rows before prefilling
        the tail."""
        self.allocate(seq_id)
        table = self._tables[seq_id]
        cached = 0
        try:
            if prompt is not None and self.prefix_cache:
                match = self.match_prefix(prompt)
                for h in match.full_hashes:
                    b = self._block_of[h]
                    self._cached.pop(h, None)  # revive: off the LRU
                    self._refs[b] = self._refs.get(b, 0) + 1
                    table.append(b)
                if match.partial_tokens:
                    b = self._take_block()
                    self._refs[b] = 1
                    table.append(b)
                self.peak_used_blocks = max(self.peak_used_blocks,
                                            self.used_blocks)
                cached = match.cached_tokens
                self._lengths[seq_id] = cached
                if cached > 0:
                    self.prefix_hits += 1
                    self.cached_tokens_served += cached
                    self._matches[seq_id] = match
                else:
                    self.prefix_misses += 1
            self.ensure(seq_id, int(tokens) - cached)
        except RuntimeError:
            self.free(seq_id)
            raise

    def advance(self, seq_id: int, tokens: int) -> None:
        """Account ``tokens`` consumed (grows the table if not reserved)."""
        self.ensure(seq_id, tokens)
        self._lengths[seq_id] += int(tokens)

    def free(self, seq_id: int) -> None:
        """Release one reference on each of ``seq_id``'s blocks. A block
        dropping to zero refs returns to the free list — or, if its hash
        is live under the prefix cache, parks on the cached-free LRU.

        Loud refusal on an unknown or already-freed ``seq_id``: with
        ref-counted sharing a silent double-free would decrement some
        OTHER sequence's live blocks straight into the reusable pool —
        a correctness corruption, not a bookkeeping blemish."""
        table = self._tables.pop(seq_id, None)
        if table is None:
            raise KeyError(
                f"BlockPool.free: unknown or already-freed sequence "
                f"{seq_id!r} — double-free would corrupt ref-counted "
                f"prefix sharing; free exactly once per allocate/reserve")
        for b in table:
            refs = self._refs.get(b, 1) - 1
            if refs > 0:
                self._refs[b] = refs
                continue
            self._refs.pop(b, None)
            h = self._hash_of.get(b)
            if h is not None and self.prefix_cache:
                self._cached[h] = b
                self._cached.move_to_end(h)
            else:
                if h is not None:
                    self._drop_hash(h)
                self._free.append(b)
        del self._lengths[seq_id]
        self._matches.pop(seq_id, None)
        self.total_frees += 1

    def seq_len(self, seq_id: int) -> int:
        return self._lengths[seq_id]

    def block_table(self, seq_id: int) -> List[int]:
        return list(self._tables[seq_id])

    def live_sequences(self) -> List[int]:
        return list(self._tables)

    def counters(self) -> dict:
        return {"num_blocks": self.num_blocks, "block_size": self.block_size,
                "free_blocks": self.free_blocks, "used_blocks": self.used_blocks,
                "peak_used_blocks": self.peak_used_blocks,
                "total_allocs": self.total_allocs, "total_frees": self.total_frees,
                "fragmentation_tokens": self.fragmentation_tokens(),
                "prefix_cache": self.prefix_cache,
                "cached_blocks": self.cached_blocks,
                "prefix_hits": self.prefix_hits,
                "prefix_misses": self.prefix_misses,
                "cached_tokens_served": self.cached_tokens_served,
                "prefix_evictions": self.prefix_evictions,
                "published_blocks": self.published_blocks,
                "prefix_hit_rate": self.prefix_hit_rate()}

    def prefix_hit_rate(self) -> Optional[float]:
        """Fraction of prompt-bearing reservations that reused cached
        tokens; ``None`` before any prompt has been through admission."""
        total = self.prefix_hits + self.prefix_misses
        return self.prefix_hits / total if total else None
