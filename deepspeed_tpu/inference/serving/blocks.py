"""Host-side KV block pool: the allocator behind both the paged KV cache
(``inference/paged_kv.py`` delegates its table bookkeeping here) and the
continuous-batching scheduler's admission control.

The reference carves one workspace and hands out offsets
(``inference_context.h``); block granularity makes the accounting
per-sequence and gives admission control a truthful currency: a request
is admitted only when ``blocks_for(prompt + max_new)`` blocks are free,
so the decode loop can never hit pool exhaustion mid-flight. Counters
(allocs/frees/peak/fragmentation) are exposed because the scheduler's
no-leak gate and the serve bench both read them as evidence.
"""

from typing import Dict, List


class BlockPool:
    """Fixed pool of ``num_blocks`` blocks of ``block_size`` tokens."""

    def __init__(self, num_blocks: int, block_size: int):
        if num_blocks < 1 or block_size < 1:
            raise ValueError(f"need num_blocks >= 1 and block_size >= 1, got "
                             f"({num_blocks}, {block_size})")
        self.num_blocks = int(num_blocks)
        self.block_size = int(block_size)
        # LIFO free list: freed blocks are reused hottest-first
        self._free: List[int] = list(range(num_blocks - 1, -1, -1))
        self._tables: Dict[int, List[int]] = {}   # seq id -> block list
        self._lengths: Dict[int, int] = {}        # seq id -> tokens used
        # accounting for admission control + the scheduler's no-leak gate
        self.total_allocs = 0
        self.total_frees = 0
        self.peak_used_blocks = 0

    # -- capacity ----------------------------------------------------------
    def blocks_for(self, tokens: int) -> int:
        return -(-max(int(tokens), 0) // self.block_size)

    @property
    def free_blocks(self) -> int:
        return len(self._free)

    @property
    def used_blocks(self) -> int:
        return self.num_blocks - len(self._free)

    def can_allocate(self, tokens: int) -> bool:
        return self.blocks_for(tokens) <= self.free_blocks

    def utilization(self) -> float:
        return self.used_blocks / self.num_blocks

    def fragmentation_tokens(self) -> int:
        """Allocated-but-unused token slots (block-rounding waste plus any
        reserved-ahead capacity): the admission controller's honesty
        metric — high fragmentation means the pool refuses requests whose
        tokens would actually fit."""
        return self.used_blocks * self.block_size - sum(self._lengths.values())

    # -- per-sequence ------------------------------------------------------
    def allocate(self, seq_id: int) -> None:
        assert seq_id not in self._tables, f"sequence {seq_id} already allocated"
        self._tables[seq_id] = []
        self._lengths[seq_id] = 0
        self.total_allocs += 1

    def ensure(self, seq_id: int, new_tokens: int) -> None:
        """Grow ``seq_id``'s table to cover ``new_tokens`` more tokens;
        raises ``RuntimeError`` on exhaustion (callers using
        :meth:`can_allocate` for admission never see it)."""
        need = self._lengths[seq_id] + int(new_tokens)
        table = self._tables[seq_id]
        while len(table) * self.block_size < need:
            if not self._free:
                raise RuntimeError(f"KV block pool exhausted ({self.num_blocks} "
                                   f"blocks of {self.block_size}); free finished "
                                   f"sequences first")
            table.append(self._free.pop())
            self.peak_used_blocks = max(self.peak_used_blocks, self.used_blocks)

    def reserve(self, seq_id: int, tokens: int) -> None:
        """Allocate + pre-grow in one step (admission-time reservation)."""
        self.allocate(seq_id)
        try:
            self.ensure(seq_id, tokens)
        except RuntimeError:
            self.free(seq_id)
            raise

    def advance(self, seq_id: int, tokens: int) -> None:
        """Account ``tokens`` consumed (grows the table if not reserved)."""
        self.ensure(seq_id, tokens)
        self._lengths[seq_id] += int(tokens)

    def free(self, seq_id: int) -> None:
        for b in self._tables.pop(seq_id):
            self._free.append(b)
        del self._lengths[seq_id]
        self.total_frees += 1

    def seq_len(self, seq_id: int) -> int:
        return self._lengths[seq_id]

    def block_table(self, seq_id: int) -> List[int]:
        return list(self._tables[seq_id])

    def live_sequences(self) -> List[int]:
        return list(self._tables)

    def counters(self) -> dict:
        return {"num_blocks": self.num_blocks, "block_size": self.block_size,
                "free_blocks": self.free_blocks, "used_blocks": self.used_blocks,
                "peak_used_blocks": self.peak_used_blocks,
                "total_allocs": self.total_allocs, "total_frees": self.total_frees,
                "fragmentation_tokens": self.fragmentation_tokens()}
