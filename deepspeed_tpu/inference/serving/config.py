"""graft-serve configuration: the ``"serving"`` config block.

Continuous in-flight batching (ISSUE 14 / ROADMAP item 1) is driven by a
small set of knobs with the same layered resolution discipline as the MoE
route and the attention geometry: explicit > env > config > default, with
the env layer (``DS_SERVE_KV_WRITE``) existing so the graft-audit
``serve_decode_step`` scenario can catch a forced/leaked serving knob the
exact way ``DS_MOE_ROUTE=dense`` is caught — the traced program drifts,
the committed budget/signature does not, lint exits 1.
"""

import os
import threading
from typing import Optional, Tuple

from pydantic import Field, model_validator

from deepspeed_tpu.runtime.config_utils import DeepSpeedConfigModel

#: env override for the per-slot KV write strategy (the DS_MOE_ROUTE
#: pattern: drifts the traced program, never the committed intent)
ENV_KV_WRITE = "DS_SERVE_KV_WRITE"

KV_WRITE_CHOICES = ("scatter", "dense")
DEFAULT_KV_WRITE = "scatter"

#: env override for the served weight dtype (graft-quant-serve); same
#: drift seam: a forced/leaked value changes the traced decode program,
#: the serve_quant_decode_step budget stays priced for the intent
ENV_WEIGHT_DTYPE = "DS_SERVE_WQ"

WEIGHT_DTYPE_CHOICES = ("fp", "int8", "int4")
DEFAULT_WEIGHT_DTYPE = "fp"

#: env override for content-hashed KV prefix caching (graft-prefix-cache);
#: the same drift seam — forcing it off under the env changes admission
#: depth and prefill skip behaviour while the committed intent (and the
#: serve_prefix_decode_step budget priced for it) stays put
ENV_PREFIX_CACHE = "DS_SERVE_PREFIX_CACHE"

PREFIX_CACHE_CHOICES = ("on", "off")
DEFAULT_PREFIX_CACHE = "on"

_lock = threading.Lock()
_config_kv_write: Optional[str] = None
_config_weight_dtype: Optional[str] = None
_config_prefix_cache: Optional[str] = None


def _check(value: Optional[str], choices, what: str) -> Optional[str]:
    if value is not None and value not in choices:
        raise ValueError(f"unknown {what} {value!r}; choices: {list(choices)}")
    return value


def set_default_kv_write(mode: Optional[str]) -> None:
    """Install the scheduler-level default KV write mode (None clears)."""
    global _config_kv_write
    with _lock:
        _config_kv_write = _check(mode, KV_WRITE_CHOICES, "kv_write")


def resolve_kv_write(mode: Optional[str] = None) -> Tuple[str, str]:
    """Resolve ``(mode, source)`` for the per-slot KV cache write.

    ``scatter`` (default) appends each slot's new tokens with an O(slots x
    tokens) scatter whose out-of-bounds (parked-slot) updates drop;
    ``dense`` rebuilds the pool through a masked one-hot einsum — a
    per-layer O(slots x n_positions) transient kept as the seeded R010
    regression. ``source`` names the deciding layer, perf-ladder evidence
    convention (``explicit`` > ``env`` > ``config`` > ``default``)."""
    src, m = "default", DEFAULT_KV_WRITE
    if _config_kv_write is not None:
        m, src = _config_kv_write, "config"
    env = os.environ.get(ENV_KV_WRITE, "").strip() or None
    if env is not None:
        m, src = _check(env, KV_WRITE_CHOICES, f"kv_write (from {ENV_KV_WRITE})"), "env"
    if mode is not None:
        m, src = _check(mode, KV_WRITE_CHOICES, "kv_write"), "explicit"
    return m, src


def resolve_intended_kv_write(mode: Optional[str] = None) -> str:
    """The write mode the *committed configuration* intends, skipping the
    env layer — what the ``serve_decode_step`` scenario's budget is priced
    for (mirror of ``moe.routing.resolve_intended_route``)."""
    if mode is not None:
        return _check(mode, KV_WRITE_CHOICES, "kv_write")
    if _config_kv_write is not None:
        return _config_kv_write
    return DEFAULT_KV_WRITE


def set_default_weight_dtype(mode: Optional[str]) -> None:
    """Install the scheduler-level served weight dtype (None clears)."""
    global _config_weight_dtype
    with _lock:
        _config_weight_dtype = _check(mode, WEIGHT_DTYPE_CHOICES, "weight_dtype")


def resolve_weight_dtype(mode: Optional[str] = None) -> Tuple[str, str]:
    """Resolve ``(mode, source)`` for the served weight dtype.

    ``fp`` (default) serves the param tree as stored; ``int8``/``int4``
    serve per-group quantized codes with dequant fused into the GEMM
    (``ops/pallas/quant_matmul.py``). ``source`` names the deciding layer
    (``explicit`` > ``env`` > ``config`` > ``default``), the same evidence
    convention as :func:`resolve_kv_write`."""
    src, m = "default", DEFAULT_WEIGHT_DTYPE
    if _config_weight_dtype is not None:
        m, src = _config_weight_dtype, "config"
    env = os.environ.get(ENV_WEIGHT_DTYPE, "").strip() or None
    if env is not None:
        m, src = _check(env, WEIGHT_DTYPE_CHOICES,
                        f"weight_dtype (from {ENV_WEIGHT_DTYPE})"), "env"
    if mode is not None:
        m, src = _check(mode, WEIGHT_DTYPE_CHOICES, "weight_dtype"), "explicit"
    return m, src


def resolve_intended_weight_dtype(mode: Optional[str] = None) -> str:
    """The weight dtype the *committed configuration* intends, skipping
    the env layer — what ``serve_quant_decode_step`` prices its budget
    and collective signature for (mirror of
    :func:`resolve_intended_kv_write`)."""
    if mode is not None:
        return _check(mode, WEIGHT_DTYPE_CHOICES, "weight_dtype")
    if _config_weight_dtype is not None:
        return _config_weight_dtype
    return DEFAULT_WEIGHT_DTYPE


def set_default_prefix_cache(mode: Optional[str]) -> None:
    """Install the scheduler-level prefix-cache default (None clears)."""
    global _config_prefix_cache
    with _lock:
        _config_prefix_cache = _check(mode, PREFIX_CACHE_CHOICES, "prefix_cache")


def resolve_prefix_cache(mode: Optional[str] = None) -> Tuple[str, str]:
    """Resolve ``(mode, source)`` for content-hashed KV prefix caching.

    ``on`` (default) ref-counts and content-addresses the BlockPool:
    committed full blocks index under a rolling hash, freed blocks with a
    live hash park on a cached-free LRU, and new prompts prefill only
    their uncached tail. ``off`` restores the private-blocks pool (parity
    debugging / the A/B control arm). ``source`` names the deciding layer
    (``explicit`` > ``env`` > ``config`` > ``default``), the same
    evidence convention as :func:`resolve_kv_write`."""
    src, m = "default", DEFAULT_PREFIX_CACHE
    if _config_prefix_cache is not None:
        m, src = _config_prefix_cache, "config"
    env = os.environ.get(ENV_PREFIX_CACHE, "").strip() or None
    if env is not None:
        m, src = _check(env, PREFIX_CACHE_CHOICES,
                        f"prefix_cache (from {ENV_PREFIX_CACHE})"), "env"
    if mode is not None:
        m, src = _check(mode, PREFIX_CACHE_CHOICES, "prefix_cache"), "explicit"
    return m, src


def resolve_intended_prefix_cache(mode: Optional[str] = None) -> str:
    """The prefix-cache mode the *committed configuration* intends,
    skipping the env layer — what ``serve_prefix_decode_step`` stamps in
    its metadata so a forced/leaked ``DS_SERVE_PREFIX_CACHE`` drifts the
    traced evidence away from the committed intent (R013 catches it)."""
    if mode is not None:
        return _check(mode, PREFIX_CACHE_CHOICES, "prefix_cache")
    if _config_prefix_cache is not None:
        return _config_prefix_cache
    return DEFAULT_PREFIX_CACHE


class SpeculationConfig(DeepSpeedConfigModel):
    """Speculative decoding knobs. The drafter is the compression/KD
    student (``compression/compress.py`` ``student_initialization`` seeds
    it from the target's layers); verification is batched on the target
    and lossless under greedy decoding: a rejected draft position is
    replaced by the target's own argmax token."""

    enabled: bool = False
    #: draft tokens per speculation round (the verify block is k+1 wide:
    #: the last accepted token rides along so the target also produces
    #: the bonus token when every draft survives)
    k: int = Field(4, ge=1, le=16)


class ServingConfig(DeepSpeedConfigModel):
    """The ``"serving"`` block (scheduler knobs; README "Serving")."""

    #: decode slots (in-flight request capacity); bucketed to the next
    #: power of two so alternating deployments reuse compiled programs
    slots: int = Field(8, ge=1)
    #: KV block granularity for admission control (tokens per block)
    page_size: int = Field(16, ge=1)
    #: total KV token budget backing admission; None = slots x model
    #: context length (admission then only enforces per-request fit)
    kv_pool_tokens: Optional[int] = None
    #: total KV BYTE budget backing admission — converted to tokens from
    #: the cache's measured per-token footprint (codes + scales under
    #: ``kv_quant``), so quantized KV admits proportionally deeper on the
    #: same HBM; wins over ``kv_pool_tokens`` when both are set
    kv_pool_bytes: Optional[int] = None
    #: chunked prefill: prompt tokens consumed per prefill tick, so a 4k
    #: prompt cannot stall in-flight decodes for its whole prefill
    prefill_chunk: int = Field(16, ge=1)
    #: decode ticks guaranteed between two prefill-chunk ticks while
    #: decodes are in flight (0 = prefill greedily)
    prefill_interleave: int = Field(1, ge=0)
    #: queued requests beyond this are refused on submit
    max_queue: int = Field(1024, ge=1)
    #: per-slot KV append strategy; resolution via :func:`resolve_kv_write`
    kv_write: Optional[str] = None
    #: served weight dtype (graft-quant-serve); resolution via
    #: :func:`resolve_weight_dtype`. ``int8``/``int4`` quantize the served
    #: param tree per group (weights only; embeddings/norms stay fp) and
    #: fuse dequant into the GEMM
    weight_dtype: Optional[str] = None
    #: target rows per quantization group along the contraction axis
    weight_group_size: int = Field(64, ge=1)
    #: content-hashed KV prefix caching (graft-prefix-cache); resolution
    #: via :func:`resolve_prefix_cache` (default ``on``). ``off`` is the
    #: A/B control arm: private blocks, no hash index, full prefill
    prefix_cache: Optional[str] = None
    #: int8 KV pools for the per-slot serving cache (the serving default:
    #: codes + per-(slot, position, head) scales, quantize-on-write /
    #: dequantize-on-read). False keeps fp KV for parity debugging
    kv_quant: bool = True
    #: emit a schema'd ``serve_tick`` telemetry event (queue depth,
    #: in-flight slots, TTFT p50/p99, BlockPool fragmentation — the
    #: fleet router/autoscaler input signals) every N ticks; 0 disables.
    #: Events are buffered (window-cadence flush), not fsynced per tick
    tick_telemetry_every: int = Field(1, ge=0)
    #: cadence (seconds) of the serving-role heartbeat block
    #: (``touch_heartbeat`` payload: slots in flight, queue depth, last
    #: tick monotonic) — a no-op unless running under a supervisor that
    #: set ``DS_ELASTIC_HEARTBEAT_FILE``
    heartbeat_interval: float = Field(1.0, ge=0.0)
    #: sampling (scheduler-global; speculation requires greedy)
    do_sample: bool = False
    temperature: float = 1.0
    top_k: int = 0
    top_p: float = 1.0
    speculation: SpeculationConfig = Field(default_factory=SpeculationConfig)

    @model_validator(mode="after")
    def _validate(self):
        _check(self.kv_write, KV_WRITE_CHOICES, "kv_write")
        _check(self.weight_dtype, WEIGHT_DTYPE_CHOICES, "weight_dtype")
        _check(self.prefix_cache, PREFIX_CACHE_CHOICES, "prefix_cache")
        if self.speculation.enabled and self.do_sample:
            raise ValueError("speculative decoding is only lossless under greedy "
                             "decoding; set do_sample=False or disable speculation")
        return self
