"""Schema'd graft-trace events for the serving path (graft-fleet).

``scheduler.stats()`` always computed the per-tick load signals — queue
depth, in-flight slots, TTFT percentiles, BlockPool fragmentation — but
until graft-fleet nothing landed them in the telemetry sink. The fleet
router and autoscaler are pure *consumers* of these events: a replica's
``serve_tick`` JSONL line and the ``tick`` message it sends the router
over its pipe carry the SAME payload (``scheduler.signals()``), so the
autoscale decision is reproducible offline from the run directory alone.

Schema discipline mirrors ``telemetry/sink.py``: adding fields is free
(readers ignore unknown keys), removing/renaming one bumps
``TELEMETRY_SCHEMA_VERSION``. ``validate_event`` is the tier-1 gate that
keeps producers honest — every event the serving path emits must carry
at least its documented field set.
"""

from typing import Dict, Iterable, Optional

#: required fields per serving event kind (the documented schema: each
#: producer must supply at least these; ``t`` and ``event`` are stamped
#: by the sink itself)
SERVE_EVENT_SCHEMAS: Dict[str, frozenset] = {
    # one per scheduler tick (cadence: ServingConfig.tick_telemetry_every)
    # — the router/autoscaler input signals, straight from signals().
    # graft-prefix-cache adds the hit-rate evidence: prefix_cache_hit_rate
    # (None until a prompt has been through admission) and cached_blocks
    # (ref-0 blocks parked on the cached-free LRU, still reclaimable);
    # the optional prefix_hot list (advertised hot position-0 prefix
    # keys) rides along un-required — the router ignores its absence
    # graft-rlhf adds the rollout evidence triple: rollout_experience
    # (completed experience through this scheduler), learner_steps_over-
    # lapped (train_batch calls interleaved while requests were in
    # flight), weight_sync_generation (0 = still serving construction
    # weights; bumped by every swap_served_params)
    "serve_tick": frozenset({
        "tick", "kind", "queue_depth", "in_flight", "slots", "free_slots",
        "ttft_p50", "ttft_p99", "pool_free_blocks",
        "pool_fragmentation_tokens", "achieved_tok_s",
        "prefix_cache_hit_rate", "cached_blocks",
        "rollout_experience", "learner_steps_overlapped",
        "weight_sync_generation",
    }),
    # terminal accounting of a preemption drain (PR 14 contract)
    "serve_drain": frozenset({"signal", "in_flight", "refused"}),
    # per-request retirement row (cached_prefix_tokens: prompt tokens
    # restored from the prefix cache instead of prefilled — 0 on a miss)
    "serve_request": frozenset({"request_id", "state", "prompt_len",
                                "new_tokens", "cached_prefix_tokens"}),
    # live KV migration: SIGTERM'd replica hands in-flight work off
    "serve_migrate_out": frozenset({"signal", "migrated", "bundle"}),
    # peer accepted a migration bundle (digest-verified restore)
    "serve_migrate_in": frozenset({"migrated", "refused", "bundle"}),
    # one per restored request on the receiving replica
    "serve_admit_migrated": frozenset({"request_id", "migrated_from",
                                       "state", "length"}),
    # graft-rlhf: one per weight hot-swap — the planner-priced sync
    # evidence (gather_bytes/total_bytes may be None when the plan
    # degraded to an error stamp; digest_verified is the bit-identity
    # proof between learner-published and served params)
    "rlhf_weight_sync": frozenset({"generation", "gather_bytes",
                                   "total_bytes", "digest_verified",
                                   "in_flight"}),
}


def validate_event(record: Dict, kind: Optional[str] = None) -> None:
    """Raise ``ValueError`` when ``record`` does not carry the documented
    field set for its serving event kind. Unknown kinds pass (schema
    covers serving events only; readers must ignore foreign events)."""
    k = kind or record.get("event")
    want = SERVE_EVENT_SCHEMAS.get(k)
    if want is None:
        return
    missing = sorted(want - set(record))
    if missing:
        raise ValueError(f"serving event {k!r} missing fields {missing} "
                         f"(got {sorted(record)})")


def iter_serve_events(path: str, kinds: Optional[Iterable[str]] = None):
    """Yield serving events from a telemetry JSONL run file (torn tails
    skipped — same contract as ``sink.iter_events``)."""
    from deepspeed_tpu.runtime.telemetry.sink import iter_events
    want = set(kinds) if kinds is not None else set(SERVE_EVENT_SCHEMAS)
    for rec in iter_events(path):
        if rec.get("event") in want:
            yield rec


def last_tick_signals(path: str) -> Optional[Dict]:
    """The newest ``serve_tick`` event in a replica's telemetry JSONL —
    what a file-tailing autoscaler (no pipe to the replica) reads."""
    last = None
    for rec in iter_serve_events(path, kinds=("serve_tick",)):
        last = rec
    return last
