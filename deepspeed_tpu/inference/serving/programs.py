"""Fixed-shape serving programs over a per-slot (ragged) decode cache.

The compiled surface of graft-serve is THREE programs per slot bucket —
a chunked prefill, a one-token decode step, and (with speculation) a
k+1-token verify step — whose shapes never change while requests join
and leave. Join/leave is positional, not structural: the cache's index
leaves are [slots] WRITE-POSITION vectors the scheduler stamps from its
host-side length mirror before every tick; a parked slot carries the
sentinel position ``n_positions`` so its KV writes drop out of bounds
and its (garbage, finite) logits are discarded on the host. Rollback
after a rejected speculation is therefore free — the next tick's stamp
simply doesn't advance past the accepted prefix.

Programs are cached on the target :class:`InferenceEngine` keyed by the
pow2 slot bucket (``engine._pow2_bucket`` — the same bucketing discipline
as ``generate``), so schedulers and repeated deployments reuse
compilations instead of churning them.
"""

from typing import Any, Callable, Dict, Optional

import numpy as np

import jax
import jax.numpy as jnp

#: cache leaves that hold write positions (scalar in ``generate``'s
#: lockstep cache; [slots] vectors in the serving cache)
INDEX_LEAVES = ("cache_index", "position_index")


def _leaf_name(path) -> str:
    last = path[-1]
    return getattr(last, "key", None) or str(last)


def _is_index_leaf(path) -> bool:
    return _leaf_name(path) in INDEX_LEAVES


#: KV pool leaves (``models/gpt2.py`` SelfAttention decode cache)
KV_LEAVES = ("cached_key", "cached_value")


def make_slot_cache(module, slots: int, kv_quant: bool = False):
    """A per-slot serving cache: the model's decode cache with every index
    leaf widened from a scalar to a [slots] vector (which is what flips
    the model's decode branch to per-slot scatter writes + per-slot
    ``decode_lengths``). Slots start PARKED (sentinel position).

    ``kv_quant=True`` (the ``ServingConfig.kv_quant`` serving default)
    converts the KV pools to int8 codes and adds a
    ``<leaf>_scale [slots, P, H, 1]`` companion per pool — the provided
    cache dtype is what statically flips the model's decode branch to
    quantize-on-write / dequantize-on-read."""
    from deepspeed_tpu.models.common import init_cache
    cache = init_cache(module, slots)
    parked = slot_capacity(cache)

    def widen(path, leaf):
        if _is_index_leaf(path):
            return jnp.full((slots,), parked, jnp.int32)
        return leaf

    cache = jax.tree_util.tree_map_with_path(widen, cache)
    if kv_quant:
        cache = quantize_slot_cache(cache)
    return cache


def quantize_slot_cache(cache):
    """int8-KV view of a (fresh) slot cache: each KV pool becomes int8
    codes and gains a per-(slot, position, head) scale leaf in the pool's
    original dtype. Zero scales on parked/unwritten rows dequantize to the
    zeros the fp cache would hold."""

    def walk(tree):
        out = {}
        for name, leaf in tree.items():
            if isinstance(leaf, dict) or hasattr(leaf, "items"):
                out[name] = walk(leaf)
            elif name in KV_LEAVES:
                out[name] = jnp.zeros(leaf.shape, jnp.int8)
                out[name + "_scale"] = jnp.zeros(leaf.shape[:-1] + (1,),
                                                 leaf.dtype)
            else:
                out[name] = leaf
        return out

    return walk(cache)


def slot_capacity(cache) -> int:
    """Token capacity per slot = the KV pool's position extent (also the
    parked-slot sentinel: a write at this position drops out of bounds)."""
    for path, leaf in jax.tree_util.tree_flatten_with_path(cache)[0]:
        if _leaf_name(path) in ("cached_key", "cached_value"):
            return int(leaf.shape[1])
    raise ValueError("cache has no cached_key leaves — not a decode cache")


def stamp_lengths(cache, write_pos: np.ndarray):
    """Host-side stamp of the scheduler's authoritative per-slot write
    positions into every index leaf (tiny [slots] arrays — the big KV
    leaves pass through untouched, so donation chains tick to tick).
    Each leaf gets its OWN device buffer: the cache is donated, and
    donating one buffer through several leaves is an XLA error."""
    pos = np.asarray(write_pos, np.int32)

    def sub(path, leaf):
        return jnp.array(pos) if _is_index_leaf(path) else leaf

    return jax.tree_util.tree_map_with_path(sub, cache)


# ---------------------------------------------------------------------------
# step builders: apply_fn(params, cache, ids) -> (logits [S, L, V], cache')
# ---------------------------------------------------------------------------
def make_apply_fn(module, mparams: Optional[Callable] = None) -> Callable:
    """The one decode apply shared by every serving program (and by the
    ``serve_decode_step``/``serve_quant_decode_step`` audit scenarios, so
    the gated program IS the served one). ``mparams`` is the engine's
    runtime weight view hook (int8 dequant); identity when absent.

    A weight-quantized serving path passes ``params`` as the bundle
    ``{"params": codes, "quant": scales}`` (``quantize_params`` output);
    the quant collection rides into ``module.apply`` so projections read
    their scales via ``get_variable("quant", "kernel_scale")``."""
    mp = mparams or (lambda p: p)

    def apply_fn(params, cache, ids):
        if isinstance(params, dict) and "quant" in params and "params" in params:
            variables = {"params": mp(params["params"]),
                         "quant": params["quant"], "cache": cache}
        else:
            variables = {"params": mp(params), "cache": cache}
        out, upd = module.apply(variables, ids, decode=True, mutable=["cache"])
        logits = out[0] if isinstance(out, (tuple, list)) else out
        return logits, upd["cache"]

    return apply_fn


def build_prefill_step(apply_fn, do_sample: bool, temperature: float,
                       top_k: int, top_p: float) -> Callable:
    """One chunked-prefill tick: consume ``ids [S, C]`` at each slot's own
    write position. ``last_idx [S]`` names each slot's final REAL token in
    the chunk (a short final chunk is right-padded; pad positions write
    beyond the committed length, are re-written by later tokens, and —
    because the per-slot causal mask bounds every query by its own
    position — are never attended by real queries). The chunk that
    completes a prompt samples the request's FIRST token from its
    last-real-position logits, so TTFT stops at prefill completion."""
    import jax.numpy as jnp

    from deepspeed_tpu.inference.engine import sample_logits

    def last_logits(logits, last_idx):
        return jnp.take_along_axis(logits, last_idx[:, None, None], axis=1)[:, 0]

    if do_sample:
        def prefill(params, cache, ids, last_idx, rng):
            logits, cache = apply_fn(params, cache, ids)
            tok = sample_logits(last_logits(logits, last_idx), rng, True,
                                temperature, top_k, top_p).astype(jnp.int32)
            return cache, tok
    else:
        def prefill(params, cache, ids, last_idx):
            logits, cache = apply_fn(params, cache, ids)
            return cache, jnp.argmax(last_logits(logits, last_idx),
                                     axis=-1).astype(jnp.int32)

    return prefill


def build_decode_step(apply_fn, do_sample: bool, temperature: float,
                      top_k: int, top_p: float) -> Callable:
    """One decode tick: feed each slot's token, sample the next. Greedy
    builds a no-rng program (``decode(params, cache, tokens)``); sampling
    adds an rng operand."""
    from deepspeed_tpu.inference.engine import sample_logits

    if do_sample:
        def decode(params, cache, tokens, rng):
            logits, cache = apply_fn(params, cache, tokens[:, None])
            tok = sample_logits(logits[:, -1], rng, True, temperature,
                                top_k, top_p).astype(jnp.int32)
            return cache, tok
    else:
        def decode(params, cache, tokens):
            logits, cache = apply_fn(params, cache, tokens[:, None])
            return cache, jnp.argmax(logits[:, -1], axis=-1).astype(jnp.int32)

    return decode


def build_verify_step(apply_fn) -> Callable:
    """Batched target verification for speculative decoding: feed the
    k+1-token block ``[last_accepted, d_1..d_k]`` and return the target's
    greedy token at EVERY position — the host accepts the longest draft
    prefix the target reproduces and emits the target's own token at the
    first divergence (lossless under greedy decoding by construction)."""

    def verify(params, cache, tokens):
        logits, cache = apply_fn(params, cache, tokens)
        return cache, jnp.argmax(logits, axis=-1).astype(jnp.int32)  # [S, K+1]

    return verify


# ---------------------------------------------------------------------------
# engine-level program cache (satellite: serving reuses the bucketed cache)
# ---------------------------------------------------------------------------
def serve_programs(engine, slots_bucket: int, *, prefill_chunk: int,
                   do_sample: bool, temperature: float, top_k: int, top_p: float,
                   spec_k: int = 0, role: str = "target",
                   module=None, mparams=None,
                   kv_write: Optional[str] = None,
                   weight_dtype: Optional[str] = None) -> Dict[str, Any]:
    """The serving program dict for one pow2 slot bucket, cached on the
    ENGINE (``engine._serve_cache``) so every scheduler over the same
    engine — and re-created schedulers across deployments — reuse the
    same compiled programs (the ``_pow2_bucket`` recompile-churn
    satellite counts exactly one program set per bucket).

    ``role``/``module`` let the speculation drafter park its own programs
    in the same cache under a distinct key; ``kv_write`` and
    ``weight_dtype`` are the RESOLVED per-slot write mode / served weight
    dtype the caller will trace under — part of the key, so schedulers
    with different modes on one engine never share a program.

    The key carries the module's identity (the cached closures keep the
    module alive, so ``id`` cannot be recycled): two drafters with
    identical knobs but different modules must never share a compiled
    program closed over the first one's architecture. ``mparams`` is
    assumed determined by (engine, module) — identity for custom
    modules, the engine's weight view otherwise — and is not keyed."""
    if not hasattr(engine, "_serve_cache"):
        engine._serve_cache = {}
    mod = module if module is not None else engine.module
    key = (role, id(mod), int(slots_bucket), int(prefill_chunk), bool(do_sample),
           float(temperature), int(top_k), float(top_p), int(spec_k), kv_write,
           weight_dtype)
    if key in engine._serve_cache:
        return engine._serve_cache[key]
    apply_fn = make_apply_fn(mod,
                             mparams if mparams is not None else engine._mparams)
    fns: Dict[str, Any] = {
        "prefill": jax.jit(build_prefill_step(apply_fn, do_sample, temperature,
                                              top_k, top_p), donate_argnums=(1,)),
        "decode": jax.jit(build_decode_step(apply_fn, do_sample, temperature,
                                            top_k, top_p), donate_argnums=(1,)),
    }
    if spec_k > 0:
        fns["verify"] = jax.jit(build_verify_step(apply_fn), donate_argnums=(1,))
    engine._serve_cache[key] = fns
    return fns
