"""Request queue with KV-block admission control.

Orca-style continuous batching admits at every decode tick; what keeps it
honest is the admission currency: a request joins a slot only when the
block pool can RESERVE its worst-case KV footprint (prompt + max_new
tokens, block-rounded), so an admitted request can never die mid-flight
to pool exhaustion and blocks can never leak (reserve on admit, free on
retire — the scheduler's tier-1 no-leak gate counts both sides).

Strict FIFO: a request is only admitted if it is at the head of the
queue or everything ahead of it was admitted this tick — no small
request overtakes a large one, so no request starves (the simulated-
clock scheduler test asserts this).
"""

from collections import deque
from typing import Callable, List, Optional

from deepspeed_tpu.inference.serving.blocks import BlockPool
from deepspeed_tpu.inference.serving.request import QUEUED, REFUSED, Request


class RequestQueue:
    """FIFO queue + admission control against a :class:`BlockPool`."""

    def __init__(self, pool: BlockPool, max_queue: int = 1024,
                 max_total_tokens: Optional[int] = None,
                 clock: Optional[Callable[[], float]] = None):
        self.pool = pool
        self.max_queue = int(max_queue)
        #: hard per-request cap (model context length); oversize prompts are
        #: refused at submit — they could never be admitted
        self.max_total_tokens = max_total_tokens
        self._clock = clock or (lambda: 0.0)
        self._queue: deque = deque()
        self.submitted = 0
        self.refused = 0

    def __len__(self) -> int:
        return len(self._queue)

    @property
    def pending(self) -> List[Request]:
        return list(self._queue)

    def submit(self, request: Request) -> Request:
        """Enqueue (stamps arrival via the injected clock). Refuses —
        terminally, with a reason — on queue overflow or a request whose
        worst case could never fit the pool."""
        request.arrival_time = (request.arrival_time
                                if request.arrival_time is not None else self._clock())
        self.submitted += 1
        if len(self._queue) >= self.max_queue:
            return self._refuse(request, f"queue full ({self.max_queue})")
        if (self.max_total_tokens is not None
                and request.total_tokens > self.max_total_tokens):
            return self._refuse(request, f"prompt + max_new ({request.total_tokens}) "
                                         f"exceeds context capacity {self.max_total_tokens}")
        if self.pool.blocks_for(request.total_tokens) > self.pool.num_blocks:
            return self._refuse(request, "worst-case KV footprint exceeds the whole pool")
        request.state = QUEUED
        self._queue.append(request)
        return request

    def _refuse(self, request: Request, reason: str) -> Request:
        request.state = REFUSED
        request.refuse_reason = reason
        self.refused += 1
        return request

    def admit(self, free_slots: int) -> List[Request]:
        """Admit head-of-queue requests while a slot is free AND the pool
        can reserve their worst-case footprint. Reserves blocks here —
        the matching ``pool.free`` happens when the scheduler retires the
        request."""
        admitted: List[Request] = []
        while self._queue and len(admitted) < free_slots:
            head = self._queue[0]
            # the prompt rides along so a prefix-caching pool can match
            # indexed blocks: a shared prefix attaches by reference, so
            # the head may fit where its worst-case block count wouldn't
            if not self.pool.can_allocate(head.total_tokens,
                                          prompt=head.prompt):
                break  # strict FIFO: nothing overtakes the head
            self._queue.popleft()
            self.pool.reserve(head.request_id, head.total_tokens,
                              prompt=head.prompt)
            admitted.append(head)
        return admitted

    def refuse_all(self, reason: str) -> List[Request]:
        """Drain path: terminally refuse everything still queued."""
        refused = []
        while self._queue:
            refused.append(self._refuse(self._queue.popleft(), reason))
        return refused
