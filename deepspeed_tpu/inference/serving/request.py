"""Serving request: the unit the continuous-batching scheduler admits,
decodes, and retires. Pure host-side bookkeeping — tokens live in numpy,
timing in the scheduler's injected clock (so tests drive a simulated
clock with no wall sleeps)."""

import dataclasses
import itertools
from typing import List, Optional

import numpy as np

# request lifecycle (terminal states: FINISHED / REFUSED)
QUEUED = "queued"        # submitted, waiting for a slot + KV blocks
PREFILL = "prefill"      # admitted; prompt streaming in prefill chunks
ACTIVE = "active"        # decoding (prompt fully prefilled)
FINISHED = "finished"    # eos or max_new_tokens reached; blocks freed
REFUSED = "refused"      # queue overflow, oversize prompt, or drain

_ids = itertools.count()


@dataclasses.dataclass
class Request:
    """One generation request plus its serving statistics."""

    prompt: np.ndarray                    # [prompt_len] int32
    max_new_tokens: int
    eos_token_id: Optional[int] = None
    arrival_time: Optional[float] = None  # stamped by the queue's clock
    request_id: int = dataclasses.field(default_factory=lambda: next(_ids))

    state: str = QUEUED
    refuse_reason: str = ""
    output: List[int] = dataclasses.field(default_factory=list)
    prefill_pos: int = 0                  # prompt tokens already prefilled
    #: prompt tokens served from the prefix cache at admission (KV rows
    #: restored instead of prefilled — graft-prefix-cache); prefill_pos
    #: starts here, so TTFT only pays for the uncached tail
    cached_prefix_tokens: int = 0

    # latency accounting (clock units of the scheduler's injected clock)
    first_token_time: Optional[float] = None
    finish_time: Optional[float] = None
    token_times: List[float] = dataclasses.field(default_factory=list)

    # speculation accounting
    drafted_tokens: int = 0
    accepted_tokens: int = 0

    # opaque caller annotations riding the request (graft-fleet: the
    # router's fleet-wide id, a migrated request's origin id) — never
    # read by the scheduler itself
    meta: dict = dataclasses.field(default_factory=dict)

    def __post_init__(self):
        self.prompt = np.asarray(self.prompt, np.int32).reshape(-1)
        if self.prompt.size == 0:
            raise ValueError("empty prompt")
        if self.max_new_tokens < 1:
            raise ValueError("max_new_tokens must be >= 1")

    @property
    def prompt_len(self) -> int:
        return int(self.prompt.size)

    @property
    def total_tokens(self) -> int:
        """Worst-case KV footprint in tokens (admission reserves this)."""
        return self.prompt_len + self.max_new_tokens

    @property
    def done(self) -> bool:
        return self.state in (FINISHED, REFUSED)

    @property
    def ttft(self) -> Optional[float]:
        if self.first_token_time is None or self.arrival_time is None:
            return None
        return self.first_token_time - self.arrival_time

    @property
    def acceptance_rate(self) -> Optional[float]:
        if self.drafted_tokens == 0:
            return None
        return self.accepted_tokens / self.drafted_tokens

    def record_token(self, token: int, now: float) -> None:
        if not self.output:
            self.first_token_time = now
        self.output.append(int(token))
        self.token_times.append(now)

    def stats(self) -> dict:
        out = {"request_id": self.request_id, "state": self.state,
               "prompt_len": self.prompt_len, "new_tokens": len(self.output),
               "cached_prefix_tokens": self.cached_prefix_tokens}
        if self.ttft is not None:
            out["ttft"] = self.ttft
        if self.finish_time is not None and self.arrival_time is not None:
            out["latency"] = self.finish_time - self.arrival_time
        if self.drafted_tokens:
            out["drafted"] = self.drafted_tokens
            out["accepted"] = self.accepted_tokens
            out["acceptance_rate"] = self.acceptance_rate
        if self.refuse_reason:
            out["refuse_reason"] = self.refuse_reason
        return out
