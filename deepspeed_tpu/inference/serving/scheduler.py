"""graft-serve: continuous in-flight batching over the per-slot decode
cache (ISSUE 14 / ROADMAP item 1 — the latency-under-load axis).

One scheduler drives one target :class:`InferenceEngine` through three
fixed-shape programs (``serving/programs.py``): requests join and leave
decode slots on every tick without changing any compiled shape; chunked
prefill interleaves long prompts with in-flight decodes; speculative
decoding drafts with the compression/KD student and verifies in one
batched target pass. Admission is block-pool truthful (``queue.py``):
a request is admitted only when its worst-case KV footprint is
reservable, so nothing dies mid-flight and nothing leaks.

Host protocol (the part that makes rollback and join/leave free): the
scheduler's numpy ``lengths`` mirror is authoritative — every tick
stamps it into the cache's index leaves. A parked slot carries the
sentinel position (= slot capacity) so its writes drop out of bounds; a
rejected speculation simply never advances the mirror past the accepted
prefix.

Integration seams (the five the last PRs built):
* resilience — :meth:`serve` wires a ``PreemptionGuard``; SIGTERM drains
  in-flight requests (finish), refuses the queue, and returns exit 143.
* telemetry — per-tick spans + per-request latency/acceptance events
  ride a ``RuntimeTelemetry`` bus when one is attached.
* graft-audit — the decode program is the ``serve_decode_step`` scenario
  (same ``make_apply_fn``), budgeted and signature-pinned by R009/R010/R013.
* compression — the drafter is the KD student
  (``compression.compress.student_initialization``).
* engine — programs live in the engine's bucketed ``_serve_cache``.
"""

import time
from typing import Callable, Dict, List, Optional, Tuple

import numpy as np

import jax

from deepspeed_tpu.inference.serving.blocks import BlockPool
from deepspeed_tpu.inference.serving.config import (ServingConfig,
                                                    resolve_kv_write,
                                                    resolve_prefix_cache,
                                                    resolve_weight_dtype,
                                                    set_default_kv_write,
                                                    set_default_prefix_cache,
                                                    set_default_weight_dtype)
from deepspeed_tpu.inference.serving.programs import (KV_LEAVES, _leaf_name,
                                                      make_slot_cache, serve_programs,
                                                      slot_capacity, stamp_lengths)
from deepspeed_tpu.inference.serving.queue import RequestQueue
from deepspeed_tpu.inference.serving.request import (ACTIVE, FINISHED, PREFILL,
                                                     Request)
from deepspeed_tpu.runtime.telemetry.metrics import Histogram
from deepspeed_tpu.utils.logging import log_dist


class MigrationError(RuntimeError):
    """Live KV migration refused or failed verification (graft-fleet).

    Raised loudly instead of degrading: a half-migrated request is worse
    than a drained one, so callers (``serve``'s migrate hook, the fleet
    router) fall back to the PR-14 drain contract when they see it."""


#: request states the migration codec can serialize: a PREFILL request's
#: state is fully described by (prompt, prefill_pos, committed KV); an
#: ACTIVE one adds (output, next_token). QUEUED requests never migrate —
#: they have no KV and are simply re-admitted by the router.
MIGRATABLE_STATES = (PREFILL, ACTIVE)


def _quant_view(module, params, weight_dtype: str, group_size: int):
    """graft-quant-serve: the (quant module, params bundle) pair a
    quantized serving path closes over. The module is rebuilt with
    ``serve_weight_dtype`` set EXPLICITLY — projections must statically
    declare the code layout the param tree actually carries (int4 halves
    the contraction axis), so env resolution never reaches the module;
    the ``DS_SERVE_WQ`` seam acts here, at the builder. Refuses model
    families without the seam rather than silently serving fp."""
    import dataclasses

    from deepspeed_tpu.ops.quantizer.weights import quantize_params
    cfg = getattr(module, "config", None)
    if (cfg is None or not dataclasses.is_dataclass(cfg)
            or not any(f.name == "serve_weight_dtype"
                       for f in dataclasses.fields(cfg))):
        raise NotImplementedError(
            f"{type(module).__name__} does not declare the serve_weight_dtype "
            f"seam — weight-quantized serving needs projections that read "
            f"int8/int4 kernels (models/gpt2.py pattern)")
    q_module = type(module)(dataclasses.replace(cfg, serve_weight_dtype=weight_dtype))
    qparams, qscales = quantize_params(params, weight_dtype, group_size)
    return q_module, {"params": qparams, "quant": qscales}


def _restore_rows_jit_impl(flat_cache, rows, slot, kv_idx):
    out = list(flat_cache)
    for j, i in enumerate(kv_idx):
        out[i] = out[i].at[slot, :rows[j].shape[0]].set(rows[j])
    return out


#: One program writes every KV leaf's restored rows into a slot, with the
#: cache DONATED so XLA updates the pool buffers in place. ``slot`` rides
#: as a traced scalar (no per-slot recompile); the row length keys the
#: jit cache through the row shapes. Restores happen per prefix-cache
#: admission, so the eager alternative — per-leaf ``.at[].set``, each
#: copying the entire pool — is a serving-throughput bug, not a style
#: choice.
_restore_rows_jit = jax.jit(_restore_rows_jit_impl,
                            static_argnums=(3,), donate_argnums=(0,))


class ContinuousBatchingScheduler:
    """Continuous (in-flight) batching over one target engine.

    ``drafter``: optional ``(flax module, params)`` — the speculation
    drafter (typically the layer-reduced KD student). Required when
    ``config.speculation.enabled``.

    ``clock``: injectable time source (``time.monotonic`` default); the
    tier-1 scheduler test drives a simulated clock with no wall sleeps.
    """

    def __init__(self, engine, config=None, drafter: Optional[Tuple] = None,
                 clock: Optional[Callable[[], float]] = None, telemetry=None,
                 seed: int = 0):
        if config is None:
            config = ServingConfig()
        elif isinstance(config, dict):
            config = ServingConfig(**config)
        self.config = config
        self.engine = engine
        self.module = engine.module
        self.clock = clock or time.monotonic
        self.telemetry = telemetry

        # graft-quant-serve: resolve the served weight dtype (env outranks
        # config — the DS_SERVE_WQ drift seam, same layering as kv_write)
        # and, when quantized, swap in the quant module + code/scale bundle
        # every program below closes over. The engine's own params stay fp.
        set_default_weight_dtype(config.weight_dtype)
        self.weight_dtype, self.weight_dtype_source = resolve_weight_dtype(None)
        self.kv_quant = bool(config.kv_quant)
        self._serve_params = engine.params
        if self.weight_dtype != "fp":
            if getattr(engine, "_wq_scales", None) is not None:
                raise ValueError(
                    "engine already serves an int8 weight view (engine quant "
                    "config); serving.weight_dtype would double-quantize — "
                    "enable one of the two")
            self.module, self._serve_params = _quant_view(
                engine.module, engine.params, self.weight_dtype,
                config.weight_group_size)

        # pow2 slot bucket: alternating deployments reuse compiled programs
        self.slots = engine._pow2_bucket(config.slots)
        # the fresh cache must carry the SAME engine-mesh sharding its
        # steady-state successors (program outputs) will: a bare
        # make_slot_cache is SingleDeviceSharding, and the first tick fed
        # the evolved NamedSharding cache would silently recompile every
        # program (~0.7 s mid-serve, measured as request 0's TTFT tail)
        from jax.sharding import NamedSharding, PartitionSpec
        self._placement = NamedSharding(engine.mesh, PartitionSpec())
        self._cache = jax.device_put(  # graft-lint: waive R008 jax-owned fresh cache zeros, never donated before first use
            make_slot_cache(self.module, self.slots, kv_quant=self.kv_quant),
            self._placement)
        self.capacity = slot_capacity(self._cache)  # tokens per slot
        self._probe_slot_decode()

        # admission: block-pool truthful KV accounting. A byte budget is
        # sized into tokens from the cache's ACTUAL per-token footprint
        # (int8 codes + scales under kv_quant), which is how quantized KV
        # turns the same HBM into more blocks and deeper admission.
        pool_tokens = config.kv_pool_tokens or self.slots * self.capacity
        if config.kv_pool_bytes:
            pool_tokens = max(config.page_size,
                              int(config.kv_pool_bytes /
                                  max(1.0, self._kv_bytes_per_token())))
        # graft-prefix-cache: content-address the pool (resolve-intent
        # layering, DS_SERVE_PREFIX_CACHE drift seam). The hash envelope
        # folds in every knob that makes cached KV bytes non-reusable —
        # kv_quant changes the stored codes/scales, the served weight
        # dtype changes the values prefill computes, speculation adds a
        # drafter cache role the payload must also carry.
        set_default_prefix_cache(config.prefix_cache)
        self.prefix_cache, self.prefix_cache_source = resolve_prefix_cache(None)
        self.spec_k = int(config.speculation.k) if config.speculation.enabled else 0
        envelope = (f"kvq:{int(self.kv_quant)}/wq:{self.weight_dtype}"
                    f"/spec:{self.spec_k}")
        self.pool = BlockPool(num_blocks=max(1, pool_tokens // config.page_size),
                              block_size=config.page_size,
                              prefix_cache=self.prefix_cache == "on",
                              envelope=envelope)
        self.queue = RequestQueue(self.pool, max_queue=config.max_queue,
                                  max_total_tokens=self.capacity, clock=self.clock)

        # the config's kv_write must reach the TRACED program, not just the
        # evidence row: install it as the process default (the engine
        # attention-block install/clear pattern — None clears), resolve the
        # mode the program will actually trace under (env still outranks
        # config, which is the DS_SERVE_KV_WRITE drift seam), and re-install
        # at every tick so a program traced lazily after another scheduler's
        # construction still binds THIS scheduler's mode.
        set_default_kv_write(config.kv_write)
        self.kv_write, self.kv_write_source = resolve_kv_write(None)
        if self.spec_k and drafter is None:
            raise ValueError("speculation.enabled needs a drafter: pass "
                             "drafter=(module, params) — e.g. the KD student from "
                             "compression.student_initialization")
        sampling = dict(do_sample=config.do_sample, temperature=config.temperature,
                        top_k=config.top_k, top_p=config.top_p)
        quantized = self.weight_dtype != "fp"
        self.fns = serve_programs(engine, self.slots,
                                  module=self.module if quantized else None,
                                  mparams=(lambda p: p) if quantized else None,
                                  prefill_chunk=config.prefill_chunk,
                                  spec_k=self.spec_k, kv_write=self.kv_write,
                                  weight_dtype=self.weight_dtype if quantized else None,
                                  **sampling)
        self._drafter = None
        if drafter is not None and self.spec_k:
            d_module, d_params = drafter
            d_weight_dtype = None
            if quantized:
                # the drafter rides int8 whenever the target serves
                # quantized: speculation gets cheaper in the same units
                d_module, d_params = _quant_view(d_module, d_params, "int8",
                                                 config.weight_group_size)
                d_weight_dtype = "int8"
            self._drafter = (d_module, jax.device_put(d_params))  # graft-lint: waive R008 drafter weights, never donated
            self._drafter_cache = jax.device_put(  # graft-lint: waive R008 jax-owned fresh cache zeros, same placement contract as the target cache
                make_slot_cache(d_module, self.slots, kv_quant=self.kv_quant),
                self._placement)
            if slot_capacity(self._drafter_cache) < self.capacity:
                raise ValueError("drafter context capacity is smaller than the "
                                 "target's — it cannot draft to the end of a "
                                 "maximal request")
            self.dfns = serve_programs(engine, self.slots, role="drafter",
                                       module=d_module, mparams=lambda p: p,
                                       prefill_chunk=config.prefill_chunk,
                                       spec_k=self.spec_k, kv_write=self.kv_write,
                                       weight_dtype=d_weight_dtype,
                                       **sampling)

        # host-side authoritative slot state
        self._slot_req: List[Optional[Request]] = [None] * self.slots
        self._lengths = np.full(self.slots, self.capacity, np.int64)  # parked sentinel
        self._next_token = np.zeros(self.slots, np.int32)
        self._decode_ticks_since_prefill = 10**9  # first prefill never waits
        self._rng = jax.random.PRNGKey(seed)

        # evidence: latency histograms + tick/speculation counters
        self.ttft_hist = Histogram()
        self.tok_hist = Histogram()
        self.ticks = {"prefill": 0, "decode": 0, "spec": 0, "idle": 0}
        # achieved-throughput clock zero: the first non-idle tick, so an
        # idle replica's achieved_tok_s reads None instead of decaying
        self._serve_t0: Optional[float] = None
        self.drafted_total = 0
        self.accepted_total = 0
        self.finished: List[Request] = []
        # graft-rlhf rollout evidence: experience completed through this
        # scheduler, learner steps the rollout loop interleaved while
        # requests were in flight, and the weight-sync generation counter
        # (bumped by swap_served_params — 0 means construction weights)
        self.rollout_experience = 0
        self.learner_steps_overlapped = 0
        self.weight_sync_generation = 0
        self.last_weight_sync: Optional[dict] = None
        log_dist(f"graft-serve: slots={self.slots} capacity={self.capacity} "
                 f"pool={self.pool.num_blocks}x{self.pool.block_size} "
                 f"chunk={config.prefill_chunk} kv_write={self.kv_write}"
                 f"({self.kv_write_source}) wq={self.weight_dtype}"
                 f"({self.weight_dtype_source}) kv_quant={self.kv_quant} "
                 f"spec_k={self.spec_k} prefix_cache={self.prefix_cache}"
                 f"({self.prefix_cache_source})")

    # ------------------------------------------------------------------
    def _probe_slot_decode(self) -> None:
        """Fail at construction — with the model family named — when the
        module's decode path cannot take a per-slot index vector (only
        families with ragged-decode support, e.g. GPT-2, can serve)."""
        try:
            import jax.numpy as jnp

            from deepspeed_tpu.inference.serving.programs import make_apply_fn
            ids = jnp.zeros((self.slots, 1), jnp.int32)
            probe = make_apply_fn(self.module)
            jax.eval_shape(lambda p, c: probe(p, c, ids),
                           self._serve_params, self._cache)
        except Exception as e:
            raise NotImplementedError(
                f"{type(self.module).__name__} does not support the per-slot "
                f"(ragged) decode cache graft-serve schedules against — its "
                f"decode path rejected a [slots] cache_index vector: "
                f"{type(e).__name__}: {e}") from e

    def _kv_bytes_per_token(self) -> float:
        """Measured KV bytes per cached token, straight off the slot
        cache's pool (+ scale) leaves — the unit that converts a byte
        budget into admission depth and prices bytes-per-KV-block in the
        bench rows. Int8 KV: 1 code byte per element plus the per-(slot,
        position, head) scale, vs the fp pool's full element width."""
        total = 0
        for path, leaf in jax.tree_util.tree_flatten_with_path(self._cache)[0]:
            name = _leaf_name(path)
            if name in KV_LEAVES or name.endswith("_scale"):
                total += leaf.size * leaf.dtype.itemsize
        return total / float(self.slots * self.capacity)

    def _span(self, name: str):
        if self.telemetry is not None:
            return self.telemetry.span(name)
        import contextlib
        return contextlib.nullcontext()

    # ------------------------------------------------------------------
    def warmup(self) -> None:
        """Compile every program this scheduler can ever run, off the
        clock: one call each against fully-parked caches, so every KV
        write drops out of bounds and the outputs are garbage to discard.
        A latency-under-load run must not charge a mid-serve request for
        XLA compile time — and a warm *request* cannot reliably reach the
        rare-path programs (the drafter's refeed verify only runs when
        some slot accepts all k drafts). Touches no request accounting,
        no histograms, and not the sampling rng stream."""
        set_default_kv_write(self.config.kv_write)
        set_default_weight_dtype(self.config.weight_dtype)
        parked = np.full(self.slots, self.capacity, np.int64)
        rng = ((jax.random.PRNGKey(0),) if self.config.do_sample else ())
        C = self.config.prefill_chunk
        ids = jax.numpy.zeros((self.slots, C), jax.numpy.int32)
        last_idx = jax.numpy.zeros((self.slots,), jax.numpy.int32)
        tok = jax.numpy.zeros((self.slots,), jax.numpy.int32)
        block = jax.numpy.zeros((self.slots, self.spec_k + 1), jax.numpy.int32)
        # a spec-mode scheduler never runs the target's plain decode
        # (step() always spec-ticks) — don't pay its compile
        target_calls = ([("prefill", (ids, last_idx) + rng)]
                        + ([("verify", (block,))] if self.spec_k
                           else [("decode", (tok,) + rng)]))
        per_role = [(self.fns, "_cache", self._serve_params, target_calls)]
        if self._drafter is not None:
            # the draft loop feeds decode a mesh-committed token (see
            # _spec_tick); every other tick input arrives uncommitted
            dtok = jax.device_put(tok, self._placement)  # graft-lint: waive R008 warmup operand placement parity w/ the draft loop, never donated
            per_role.append((self.dfns, "_drafter_cache", self._drafter[1],
                             [("prefill", (ids, last_idx) + rng),
                              ("decode", (dtok,) + rng), ("verify", (block,))]))
        for fns, cache_attr, params, calls in per_role:
            for name, args in calls:
                if name in fns:
                    cache = stamp_lengths(getattr(self, cache_attr), parked)
                    cache, _ = fns[name](params, cache, *args)
                    setattr(self, cache_attr, cache)

    # ------------------------------------------------------------------
    # submission / admission
    # ------------------------------------------------------------------
    def submit(self, request: Request) -> Request:
        return self.queue.submit(request)

    @property
    def in_flight(self) -> List[Request]:
        return [r for r in self._slot_req if r is not None]

    def _free_slots(self) -> List[int]:
        return [i for i, r in enumerate(self._slot_req) if r is None]

    def _admit(self) -> int:
        free = self._free_slots()
        admitted = self.queue.admit(len(free))
        for slot, req in zip(free, admitted):
            self._slot_req[slot] = req
            # graft-prefix-cache: the reservation may have matched an
            # indexed prefix — restore its KV rows into the slot and
            # start prefill AFTER them, so the tick only pays for the
            # uncached tail (the match always leaves >= 1 prompt token
            # so the tail's last position samples the first new token)
            cached = 0
            match = self.pool.take_match(req.request_id)
            if match is not None and match.cached_tokens:
                self._restore_prefix(slot, match)
                cached = match.cached_tokens
            self._lengths[slot] = cached
            req.state = PREFILL
            req.prefill_pos = cached
            req.cached_prefix_tokens = cached
        return len(admitted)

    # -- prefix cache (graft-prefix-cache) -----------------------------
    def _kv_rows(self, cache, slot: int, start: int, stop: int) -> Dict[str, np.ndarray]:
        """Host copies of rows ``[start:stop)`` of one slot's KV leaves —
        the publish payload. Reads through the whole-leaf ``device_get``
        (zero-copy on the CPU backend — the migration exporter's lesson)
        and copies ONLY the requested rows: an eager device-side slice
        would compile a fresh XLA program per (start, stop) offset, one
        per publishing request. ``np.array(copy=True)`` because a view
        would alias the device buffer the next donated decode step
        frees."""
        out: Dict[str, np.ndarray] = {}
        for path, leaf in jax.tree_util.tree_flatten_with_path(cache)[0]:
            name = _leaf_name(path)
            if name in KV_LEAVES or name.endswith("_scale"):
                host = np.asarray(jax.device_get(leaf))
                out[jax.tree_util.keystr(path)] = np.array(
                    host[slot, start:stop], copy=True)
        return out

    def _restore_prefix(self, slot: int, match) -> None:
        """Write a :class:`PrefixMatch`'s payload rows into ``slot`` for
        every cache role (target + drafter when speculating): full-block
        payloads concatenate, the partial block contributes its first
        ``partial_tokens`` rows (the COW copy — the shared source block's
        payload is read, never written). Payload rows restore through the
        migration writer (``.at[slot, :n].set``) so the buffers stay
        XLA-owned on the existing placement."""
        roles = [("target", "_cache")]
        if self._drafter is not None:
            roles.append(("drafter", "_drafter_cache"))
        for role, attr in roles:
            parts: Dict[str, list] = {}
            for payload in match.payloads + (
                    [match.partial_payload] if match.partial_tokens else []):
                if not isinstance(payload, dict) or role not in payload:
                    raise MigrationError(
                        f"prefix-cache payload missing {role!r} KV rows — "
                        f"the pool indexed a block this scheduler cannot "
                        f"restore")
                rows = payload[role]
                is_partial = payload is match.partial_payload \
                    and match.partial_tokens
                for key, arr in rows.items():
                    part = arr[:match.partial_tokens] if is_partial else arr
                    parts.setdefault(key, []).append(part)
            leaves = {k: (np.concatenate(v, axis=0) if len(v) > 1 else v[0])
                      for k, v in parts.items()}
            setattr(self, attr, self._restore_slot_kv(
                getattr(self, attr), slot, leaves, match.cached_tokens))

    def _publish_prefix(self, slot: int, req: Request) -> None:
        """Index ``req``'s committed full blocks (prompt at the
        PREFILL->ACTIVE transition, prompt + generated output at
        retirement — multi-turn conversations re-match their own
        history). The pool calls ``fetch`` only for blocks not already
        hashed, so shared prefixes publish their KV rows exactly once."""
        if self.prefix_cache != "on":
            return
        committed = int(self._lengths[slot])
        if committed < self.pool.block_size:
            return
        tokens = np.concatenate([
            np.asarray(req.prompt, np.int64),
            np.asarray(req.output, np.int64)])[:committed]

        # ONE device_get per leaf per publish, host-sliced per block:
        # per-block device slices would compile a fresh XLA program per
        # (start, stop) offset and dominate the tick under load. Lazy and
        # tail-only — the pool walks blocks in order and calls fetch only
        # for blocks not yet indexed, so the first call's ``start`` is the
        # first new row: a finish-time publish whose prompt blocks are
        # already shared transfers just the output tail (or, when every
        # full block is already indexed, nothing at all)
        full: dict = {}

        def fetch(start: int, stop: int) -> dict:
            if not full:
                full["base"] = start
                full["target"] = self._kv_rows(self._cache, slot,
                                               start, committed)
                if self._drafter is not None:
                    full["drafter"] = self._kv_rows(self._drafter_cache,
                                                    slot, start, committed)
            base = full["base"]
            return {role: {k: arr[start - base:stop - base]
                           for k, arr in rows.items()}
                    for role, rows in full.items() if role != "base"}

        self.pool.publish(req.request_id, tokens, fetch=fetch)

    # ------------------------------------------------------------------
    # tick
    # ------------------------------------------------------------------
    def step(self, admit: bool = True) -> str:
        """One scheduler tick; returns the tick kind it ran
        (``prefill`` | ``decode`` | ``spec`` | ``idle``)."""
        step_no = sum(self.ticks.values()) + 1
        # lazily-traced programs must bind THIS scheduler's write mode even
        # if another scheduler re-installed the default since construction
        set_default_kv_write(self.config.kv_write)
        set_default_weight_dtype(self.config.weight_dtype)
        if self.telemetry is not None:
            self.telemetry.begin_step(step_no)
        with self._span("serve_admit"):
            if admit:
                self._admit()
        prefilling = [i for i, r in enumerate(self._slot_req)
                      if r is not None and r.state == PREFILL]
        active = [i for i, r in enumerate(self._slot_req)
                  if r is not None and r.state == ACTIVE]
        if prefilling and (not active or self._decode_ticks_since_prefill
                           >= self.config.prefill_interleave):
            kind = "prefill"
            with self._span("serve_prefill"):
                self._prefill_tick(prefilling)
            self._decode_ticks_since_prefill = 0
        elif active:
            kind = "spec" if self.spec_k else "decode"
            with self._span(f"serve_{kind}"):
                if self.spec_k:
                    self._spec_tick(active)
                else:
                    self._decode_tick(active)
            self._decode_ticks_since_prefill += 1
        else:
            kind = "idle"
        if kind != "idle" and self._serve_t0 is None:
            self._serve_t0 = self.clock()
        self.ticks[kind] += 1
        if self.telemetry is not None:
            self.telemetry.end_step(step_no)
            every = self.config.tick_telemetry_every
            if every and step_no % every == 0:
                # the fleet router/autoscaler input signals, landed as a
                # schema'd JSONL event (events.SERVE_EVENT_SCHEMAS);
                # buffered — the window flush syncs, not every tick
                self.telemetry.emit("serve_tick", flush=False,
                                    tick=step_no, kind=kind, **self.signals())
        self._touch_serving_heartbeat(step_no)
        return kind

    # ------------------------------------------------------------------
    # load signals (graft-fleet: the router/autoscaler currency)
    # ------------------------------------------------------------------
    def signals(self) -> dict:
        """The per-tick load signals ``stats()`` always computed but never
        published: queue depth, in-flight slots, TTFT p50/p99, BlockPool
        occupancy/fragmentation. This exact dict is (a) the ``serve_tick``
        telemetry event body, (b) the replica's ``tick`` protocol message
        to the fleet router, and (c) the autoscaler's decision input."""
        ttft = self.ttft_hist
        return {
            "queue_depth": len(self.queue),
            "in_flight": len(self.in_flight),
            "slots": self.slots,
            "free_slots": len(self._free_slots()),
            "finished": len(self.finished),
            "ttft_p50": ttft.percentile(50) if ttft.count else None,
            "ttft_p99": ttft.percentile(99) if ttft.count else None,
            "pool_free_blocks": self.pool.free_blocks,
            "pool_fragmentation_tokens": self.pool.fragmentation_tokens(),
            "achieved_tok_s": self._achieved_tok_s(),
            # graft-prefix-cache evidence (schema'd serve_tick fields) +
            # the affinity advertisement the fleet router matches against
            "prefix_cache_hit_rate": self.pool.prefix_hit_rate(),
            "cached_blocks": self.pool.cached_blocks,
            "prefix_hot": self.pool.hot_prefixes(),
            "prefix_block_size": self.pool.block_size,
            # graft-rlhf rollout evidence (schema'd serve_tick fields)
            "rollout_experience": self.rollout_experience,
            "learner_steps_overlapped": self.learner_steps_overlapped,
            "weight_sync_generation": self.weight_sync_generation,
        }

    def _achieved_tok_s(self) -> Optional[float]:
        """Run-to-date generated tokens per wall second since the first
        non-idle tick (finished + in-flight outputs) — the measured side
        graft-calibrate fits against the ``serve_decode`` static price the
        fleet worker stamps. ``None`` until the replica has both tokens
        and wall time, so a cold replica never reports a fake zero rate."""
        if self._serve_t0 is None:
            return None
        wall = self.clock() - self._serve_t0
        tokens = (sum(len(r.output) for r in self.finished)
                  + sum(len(r.output) for r in self.in_flight))
        if wall <= 0 or not tokens:
            return None
        return tokens / wall

    def serving_static_price(self) -> dict:
        """Static price of the steady-state serving program (the verify
        pass under speculation, plain decode otherwise) — jaxpr-only, the
        exact dict ``static_price_from_jaxpr`` gives a train step, so the
        fleet worker can stamp it into its telemetry run header and
        serving programs enter the graft-calibrate fit in the same units
        as training steps. Degrades to an ``{"error": ...}`` stamp (the
        engine run-header contract) rather than refusing to serve."""
        try:
            from deepspeed_tpu.analysis.cost import static_price_from_jaxpr
            name = "verify" if self.spec_k else "decode"
            if self.spec_k:
                args = (jax.numpy.zeros((self.slots, self.spec_k + 1),
                                        jax.numpy.int32),)
            else:
                args = (jax.numpy.zeros((self.slots,), jax.numpy.int32),)
                if self.config.do_sample:
                    args += (jax.random.PRNGKey(0),)
            closed = jax.make_jaxpr(self.fns[name])(
                self._serve_params, self._cache, *args)
            return static_price_from_jaxpr(closed, name=f"serve_{name}",
                                           kind="serve_decode")
        except Exception as e:  # pricing must never take the replica down
            return {"error": f"{type(e).__name__}: {str(e)[:200]}"}

    # ------------------------------------------------------------------
    # graft-rlhf: weight hot-swap seam
    # ------------------------------------------------------------------
    def swap_served_params(self, params, expected_digest: Optional[str] = None,
                           generation: Optional[int] = None,
                           evidence: Optional[dict] = None) -> None:
        """Hot-swap the served params between decode ticks (graft-rlhf
        weight sync). Every serving program takes ``self._serve_params``
        explicitly per call, so swapping the attribute swaps the weights
        the NEXT tick serves with zero recompile — KV already written
        stays valid (it was computed under the generation that wrote it;
        in-flight requests finish on the new weights, which is the
        standard in-flight RLHF staleness contract).

        The new tree must match the served tree exactly (structure,
        shapes, dtypes) — a drifted learner tree is refused loudly, not
        served. When ``expected_digest`` is given the placed params are
        re-digested and verified against what the learner published, so
        generation N's served weights are proven bit-identical to the
        sync evidence. Under a quantized weight view (``weight_dtype !=
        "fp"``) the fp params are re-encoded through ``_quant_view`` and
        digest verification is refused (the re-encode is lossy by
        design — the caller must not expect fp-bit identity)."""
        if self.weight_dtype != "fp":
            if expected_digest is not None:
                raise ValueError(
                    f"digest verification is meaningless under a quantized "
                    f"weight view (wq={self.weight_dtype}): the served "
                    f"params are a lossy re-encode of what the learner "
                    f"published — pass expected_digest=None")
            _, new_params = _quant_view(self.engine.module, params,
                                        self.weight_dtype,
                                        self.config.weight_group_size)
        else:
            new_params = params

        old_leaves, old_def = jax.tree_util.tree_flatten_with_path(
            self._serve_params)
        new_leaves, new_def = jax.tree_util.tree_flatten_with_path(new_params)
        if old_def != new_def:
            raise ValueError(
                "swap_served_params: new tree structure differs from the "
                "served tree — the learner's params drifted from what this "
                "scheduler compiled against")
        problems = []
        for (path, old), (_, new) in zip(old_leaves, new_leaves):
            if getattr(old, "shape", None) != getattr(new, "shape", None) \
                    or getattr(old, "dtype", None) != getattr(new, "dtype", None):
                problems.append(
                    f"{jax.tree_util.keystr(path)}: served "
                    f"{getattr(old, 'shape', '?')}/{getattr(old, 'dtype', '?')}"
                    f" vs new {getattr(new, 'shape', '?')}/"
                    f"{getattr(new, 'dtype', '?')}")
        if problems:
            raise ValueError("swap_served_params: leaf drift — "
                             + "; ".join(problems[:5]))

        placed = jax.tree.map(
            lambda v, old: jax.device_put(v, old.sharding),  # graft-lint: waive R008 jax-owned served weights, never donated
            new_params, self._serve_params)
        jax.block_until_ready(placed)
        digest_verified = False
        if expected_digest is not None:
            from deepspeed_tpu.runtime.rlhf.sync import params_digest
            got = params_digest(placed)
            if got != expected_digest:
                raise ValueError(
                    f"swap_served_params: digest mismatch after placement — "
                    f"learner published {expected_digest[:16]}… but the "
                    f"placed params digest to {got[:16]}…")
            digest_verified = True
        self._serve_params = placed
        self.weight_sync_generation = (generation if generation is not None
                                       else self.weight_sync_generation + 1)
        self.last_weight_sync = dict(evidence or {},
                                     digest_verified=digest_verified)
        if self.telemetry is not None:
            ev = evidence or {}
            self.telemetry.emit(
                "rlhf_weight_sync", generation=self.weight_sync_generation,
                gather_bytes=ev.get("gather_bytes"),
                total_bytes=ev.get("total_bytes"),
                digest_verified=digest_verified,
                in_flight=len(self.in_flight))

    def _touch_serving_heartbeat(self, tick: int) -> None:
        """Refresh the PR-13 supervisor heartbeat with a serving role
        block (slots in flight, queue depth, last tick monotonic) at
        ``heartbeat_interval`` cadence. A no-op outside a supervised
        process (no ``DS_ELASTIC_HEARTBEAT_FILE``) — the env check is the
        first thing ``touch_heartbeat`` does."""
        import os
        from deepspeed_tpu.elasticity.elastic_agent import (HEARTBEAT_ENV,
                                                            touch_heartbeat)
        if not os.environ.get(HEARTBEAT_ENV):
            return
        touch_heartbeat(
            min_interval=self.config.heartbeat_interval,
            payload={"role": "serving", "tick": tick,
                     "slots_in_flight": len(self.in_flight),
                     "queue_depth": len(self.queue),
                     "last_tick_monotonic": time.monotonic()})

    # -- prefill -------------------------------------------------------
    def _prefill_tick(self, slots: List[int]) -> None:
        C = self.config.prefill_chunk
        ids = np.zeros((self.slots, C), np.int32)
        last_idx = np.full(self.slots, C - 1, np.int32)
        write_pos = np.full(self.slots, self.capacity, np.int64)
        rems: Dict[int, int] = {}
        for i in slots:
            req = self._slot_req[i]
            chunk = req.prompt[req.prefill_pos:req.prefill_pos + C]
            rems[i] = rem = len(chunk)
            ids[i, :rem] = chunk
            last_idx[i] = rem - 1
            write_pos[i] = self._lengths[i]
        cache = stamp_lengths(self._cache, write_pos)
        args = (self._serve_params, cache, jax.numpy.asarray(ids),
                jax.numpy.asarray(last_idx))
        if self.config.do_sample:
            self._rng, key = jax.random.split(self._rng)
            self._cache, tok = self.fns["prefill"](*args, key)
        else:
            self._cache, tok = self.fns["prefill"](*args)
        if self._drafter is not None:
            d_module, d_params = self._drafter
            d_cache = stamp_lengths(self._drafter_cache, write_pos)
            d_args = (d_params, d_cache, jax.numpy.asarray(ids),
                      jax.numpy.asarray(last_idx))
            if self.config.do_sample:
                self._rng, dkey = jax.random.split(self._rng)
                self._drafter_cache, _ = self.dfns["prefill"](*d_args, dkey)
            else:
                self._drafter_cache, _ = self.dfns["prefill"](*d_args)
        with self._span("serve_device_wait"):
            tok = np.asarray(tok)
        now = self.clock()
        for i in slots:
            req, rem = self._slot_req[i], rems[i]
            req.prefill_pos += rem
            self._lengths[i] += rem
            self.pool.advance(req.request_id, rem)
            if req.prefill_pos >= req.prompt_len:
                # prompt complete: the chunk's last-position logits sampled
                # the FIRST new token — TTFT stops here. The committed
                # prompt's full blocks enter the hash index now, so the
                # next same-prefix request skips their prefill entirely
                self._publish_prefix(i, req)
                req.state = ACTIVE
                req.record_token(int(tok[i]), now)
                self._next_token[i] = tok[i]
                self._maybe_finish(i, now)

    # -- plain decode --------------------------------------------------
    def _decode_tick(self, slots: List[int]) -> None:
        write_pos = np.full(self.slots, self.capacity, np.int64)
        tokens = np.zeros(self.slots, np.int32)
        for i in slots:
            write_pos[i] = self._lengths[i]
            tokens[i] = self._next_token[i]
        cache = stamp_lengths(self._cache, write_pos)
        args = (self._serve_params, cache, jax.numpy.asarray(tokens))
        if self.config.do_sample:
            self._rng, key = jax.random.split(self._rng)
            self._cache, tok = self.fns["decode"](*args, key)
        else:
            self._cache, tok = self.fns["decode"](*args)
        with self._span("serve_device_wait"):
            tok = np.asarray(tok)
        now = self.clock()
        for i in slots:
            req = self._slot_req[i]
            self._lengths[i] += 1  # the fed token's KV is now committed
            self.pool.advance(req.request_id, 1)
            req.record_token(int(tok[i]), now)
            self._next_token[i] = tok[i]
            self._maybe_finish(i, now)

    # -- speculative decode --------------------------------------------
    def _spec_tick(self, slots: List[int]) -> None:
        """One speculation round: k drafter steps, one batched target
        verify over the k+1 block, host-side longest-prefix acceptance.
        The drafter re-feeds the verify block only when some slot accepted
        every draft (its own pass never wrote the kth draft's KV)."""
        k = self.spec_k
        d_module, d_params = self._drafter
        write_pos = np.full(self.slots, self.capacity, np.int64)
        for i in slots:
            write_pos[i] = self._lengths[i]
        # committed to the mesh placement so iteration 1's input sharding
        # matches iterations 2..k (which feed the previous jit output back);
        # an uncommitted first feed would cost a second decode compile
        cur = jax.device_put(  # graft-lint: waive R008 host token mirror to mesh placement, never donated
            np.asarray([self._next_token[i] if self._slot_req[i] is not None
                        and self._slot_req[i].state == ACTIVE else 0
                        for i in range(self.slots)], np.int32), self._placement)
        drafts = []
        with self._span("serve_spec_draft"):
            for j in range(k):
                d_cache = stamp_lengths(self._drafter_cache, write_pos + j)
                self._drafter_cache, cur = self.dfns["decode"](d_params, d_cache, cur)
                drafts.append(cur)
            drafts = np.stack([np.asarray(d) for d in drafts], axis=1)  # [S, k]
        block = np.zeros((self.slots, k + 1), np.int32)
        for i in slots:
            block[i, 0] = self._next_token[i]
            block[i, 1:] = drafts[i]
        with self._span("serve_spec_verify"):
            cache = stamp_lengths(self._cache, write_pos)
            self._cache, greedy = self.fns["verify"](
                self._serve_params, cache, jax.numpy.asarray(block))
            greedy = np.asarray(greedy)  # [S, k+1] target argmax per position
        refeed = False
        now = self.clock()
        for i in slots:
            req = self._slot_req[i]
            # longest prefix of drafts the target reproduces
            a = 0
            while a < k and drafts[i, a] == greedy[i, a]:
                a += 1
            emitted = list(drafts[i, :a]) + [greedy[i, a]]
            req.drafted_tokens += k
            req.accepted_tokens += a
            self.drafted_total += k
            self.accepted_total += a
            if a == k:
                refeed = True  # drafter never wrote d_k's KV — resync below
            # budget/eos truncation
            room = req.max_new_tokens - len(req.output)
            emitted = emitted[:room]
            if req.eos_token_id is not None and req.eos_token_id in emitted:
                emitted = emitted[:emitted.index(req.eos_token_id) + 1]
            for t in emitted:
                req.record_token(int(t), now)
            # committed KV: the fed block prefix [last, d_1..d_{m-1}]
            self._lengths[i] += len(emitted)
            self.pool.advance(req.request_id, len(emitted))
            self._next_token[i] = emitted[-1]
            self._maybe_finish(i, now)
        if refeed and any(self._slot_req[i] is not None for i in slots):
            with self._span("serve_spec_refeed"):
                d_cache = stamp_lengths(self._drafter_cache, write_pos)
                self._drafter_cache, _ = self.dfns["verify"](
                    d_params, d_cache, jax.numpy.asarray(block))

    # ------------------------------------------------------------------
    # live KV migration (graft-fleet)
    # ------------------------------------------------------------------
    def _kv_slot_leaves(self, cache, slot: int, length: int) -> Dict[str, np.ndarray]:
        """Host copies of one slot's committed KV rows — every pool leaf
        (``KV_LEAVES``) plus its kv_quant ``*_scale`` companion, keyed by
        the leaf's ``keystr`` path so target and drafter caches (same leaf
        names, different depths) stay unambiguous. Only ``[:length]`` rows
        travel: everything past the committed prefix is scratch."""
        out: Dict[str, np.ndarray] = {}
        for path, leaf in jax.tree_util.tree_flatten_with_path(cache)[0]:
            name = _leaf_name(path)
            if name in KV_LEAVES or name.endswith("_scale"):
                host = np.asarray(jax.device_get(leaf))
                # np.array copy=True, NOT ascontiguousarray: a row-prefix
                # slice is already contiguous, so ascontiguousarray would
                # return a zero-copy VIEW into the device buffer — which
                # the next donated decode step frees under the payload
                out[jax.tree_util.keystr(path)] = np.array(
                    host[slot, :length], copy=True)
        return out

    def _restore_slot_kv(self, cache, slot: int, leaves: Dict[str, np.ndarray],
                         length: int):
        """Write migrated KV rows back into one slot of ``cache`` on
        device (``.at[slot, :length].set``). Refuses — ``MigrationError``
        — on a missing/mis-shaped/mis-typed leaf rather than serving a
        half-restored cache.

        The write must stay on device: a ``device_put`` of a host-mutated
        copy is zero-copy on the CPU backend, so the restored leaf would
        alias numpy-owned memory — and the next decode step DONATES the
        cache, handing XLA a buffer it doesn't own to free (heap
        corruption, found the hard way). All leaves update in ONE jitted,
        cache-donating program (``_restore_rows_jit``): prefix-cache
        restores run this per admission, and per-leaf eager ``.at[].set``
        would copy the whole pool once per leaf.

        Donation makes validation ordering load-bearing: callers
        restoring SEVERAL caches (target + drafter) must
        :meth:`_validate_slot_kv` every one of them BEFORE applying the
        first — once a cache is donated, its old buffers are gone, so a
        late validation failure could no longer leave the scheduler
        untouched."""
        flat, treedef, kv_idx, rows = self._validate_slot_kv(cache, leaves,
                                                             length)
        new_flat = _restore_rows_jit([leaf for _, leaf in flat], rows,
                                     np.int32(slot), tuple(kv_idx))
        return jax.tree_util.tree_unflatten(treedef, new_flat)

    def _validate_slot_kv(self, cache, leaves: Dict[str, np.ndarray],
                          length: int):
        """Check ``leaves`` against ``cache``'s KV geometry WITHOUT
        touching the cache; raises :class:`MigrationError` on a
        missing/mis-shaped/mis-typed leaf. Returns the flattened pieces
        :meth:`_restore_slot_kv` applies."""
        flat, treedef = jax.tree_util.tree_flatten_with_path(cache)
        kv_idx, rows = [], []
        for i, (path, leaf) in enumerate(flat):
            name = _leaf_name(path)
            if name not in KV_LEAVES and not name.endswith("_scale"):
                continue
            key = jax.tree_util.keystr(path)
            src = leaves.get(key)
            if src is None:
                raise MigrationError(f"migration bundle missing KV leaf {key}")
            src = np.asarray(src)
            want_shape = (length,) + tuple(leaf.shape[2:])
            want_dtype = np.dtype(leaf.dtype)
            if src.shape != want_shape or src.dtype != want_dtype:
                raise MigrationError(
                    f"KV leaf {key} mismatch: bundle {src.dtype}{src.shape} "
                    f"vs cache row {want_dtype}{want_shape} — replicas must "
                    f"share kv_quant/geometry to migrate")
            kv_idx.append(i)
            rows.append(np.ascontiguousarray(src))
        return flat, treedef, kv_idx, rows

    def export_inflight(self, release: bool = True) -> List[dict]:
        """Serialize every in-flight request — host bookkeeping plus its
        committed per-slot KV — into migration payloads a peer's
        :meth:`admit_migrated` restores bit-exactly.

        Refusal conditions (``MigrationError``, loudly, BEFORE any slot is
        released): sampled decoding (the scheduler-global rng stream is
        not per-request state), or a request outside ``MIGRATABLE_STATES``.
        Greedy decoding is what makes the contract checkable: the migrated
        continuation must be bit-identical to the uninterrupted run.

        ``release=True`` (the SIGTERM path) frees each exported request's
        pool blocks and parks its slot, so the drain loop sees an empty
        scheduler and exits without generating further tokens here."""
        if self.config.do_sample:
            raise MigrationError(
                "sampled decoding cannot migrate: the sampling rng stream is "
                "scheduler-global, not per-request — drain instead")
        for req in self.in_flight:
            if req.state not in MIGRATABLE_STATES:
                raise MigrationError(f"request {req.request_id} in state "
                                     f"{req.state!r} is not migratable")
        payloads: List[dict] = []
        for slot, req in enumerate(self._slot_req):
            if req is None:
                continue
            length = int(self._lengths[slot])
            kv = {"target": self._kv_slot_leaves(self._cache, slot, length)}
            if self._drafter is not None:
                kv["drafter"] = self._kv_slot_leaves(self._drafter_cache,
                                                     slot, length)
            payloads.append({
                "request_id": req.request_id,
                "state": req.state,
                "prompt": np.asarray(req.prompt, np.int32),
                "max_new_tokens": req.max_new_tokens,
                "eos_token_id": req.eos_token_id,
                "arrival_time": req.arrival_time,
                "output": list(req.output),
                "prefill_pos": req.prefill_pos,
                "first_token_time": req.first_token_time,
                "token_times": list(req.token_times),
                "drafted_tokens": req.drafted_tokens,
                "accepted_tokens": req.accepted_tokens,
                "meta": dict(req.meta),
                "length": length,
                "next_token": int(self._next_token[slot]),
                "cached_prefix_tokens": req.cached_prefix_tokens,
                # compat envelope: the importer refuses on any mismatch.
                # prefix_cache rides in it because the KV slices below are
                # already MATERIALIZED (per-slot dense rows — shared
                # blocks export their bytes, not their refs), but the
                # receiving pool's hash envelope must agree before the
                # restored request can publish/re-match over there
                "kv_quant": self.kv_quant,
                "weight_dtype": self.weight_dtype,
                "capacity": self.capacity,
                "spec_k": self.spec_k,
                "prefix_cache": self.prefix_cache,
                "kv": kv,
            })
            if release:
                self.pool.free(req.request_id)
                self._slot_req[slot] = None
                self._lengths[slot] = self.capacity  # park
        return payloads

    def release_inflight(self) -> int:
        """Free every in-flight request's pool blocks and park its slot —
        the post-export half of a migrate-out, split from
        :meth:`export_inflight(release=False)` so a failed bundle save
        leaves the requests still serveable here (drain fallback)."""
        n = 0
        for slot, req in enumerate(self._slot_req):
            if req is None:
                continue
            self.pool.free(req.request_id)
            self._slot_req[slot] = None
            self._lengths[slot] = self.capacity  # park
            n += 1
        return n

    def admit_migrated(self, payload: dict) -> Optional[Request]:
        """Admit one migrated request into a free slot, restoring its KV.

        Returns the (re-identified) local :class:`Request`, or ``None``
        when this replica has no free slot / pool blocks for its worst
        case — a *capacity* refusal the router retries elsewhere, distinct
        from the *compat* refusals (kv_quant / weight dtype / speculation
        geometry mismatch) that raise :class:`MigrationError` because no
        retry can fix them. The request gets a FRESH local id (both
        processes count from 0 — the wire id would collide) with the
        origin id kept in ``meta["migrated_from"]`` for at-most-once
        completion accounting."""
        for knob in ("kv_quant", "weight_dtype", "spec_k", "capacity",
                     "prefix_cache"):
            if payload.get(knob) != getattr(self, knob):
                raise MigrationError(
                    f"migration compat mismatch on {knob}: bundle "
                    f"{payload.get(knob)!r} vs replica {getattr(self, knob)!r}")
        if payload["state"] not in MIGRATABLE_STATES:
            raise MigrationError(f"bundle request state {payload['state']!r} "
                                 f"is not migratable")
        free = self._free_slots()
        if not free:
            return None
        req = Request(prompt=payload["prompt"],
                      max_new_tokens=payload["max_new_tokens"],
                      eos_token_id=payload["eos_token_id"],
                      arrival_time=payload["arrival_time"])
        if not self.pool.can_allocate(req.total_tokens):
            return None
        req.meta.update(payload.get("meta", {}))
        req.meta["migrated_from"] = payload["request_id"]
        req.state = payload["state"]
        req.output = [int(t) for t in payload["output"]]
        req.prefill_pos = int(payload["prefill_pos"])
        req.first_token_time = payload["first_token_time"]
        req.token_times = list(payload["token_times"])
        req.drafted_tokens = int(payload["drafted_tokens"])
        req.accepted_tokens = int(payload["accepted_tokens"])
        req.cached_prefix_tokens = int(payload.get("cached_prefix_tokens", 0))
        length = int(payload["length"])
        slot = free[0]
        # validate EVERY role before restoring ANY — a MigrationError here
        # must leave the replica untouched (no reserved blocks, no
        # occupied slot, and no cache buffer already donated away by a
        # first restore when a second role's leaves turn out bad)
        self._validate_slot_kv(self._cache, payload["kv"]["target"], length)
        if self._drafter is not None:
            self._validate_slot_kv(self._drafter_cache,
                                   payload["kv"].get("drafter", {}), length)
        self._cache = self._restore_slot_kv(self._cache, slot,
                                            payload["kv"]["target"], length)
        if self._drafter is not None:
            self._drafter_cache = self._restore_slot_kv(
                self._drafter_cache, slot, payload["kv"]["drafter"], length)
        self.pool.reserve(req.request_id, req.total_tokens)
        self.pool.advance(req.request_id, length)
        self._slot_req[slot] = req
        self._lengths[slot] = length
        self._next_token[slot] = payload["next_token"]
        if self.telemetry is not None:
            self.telemetry.emit("serve_admit_migrated",
                                request_id=req.request_id,
                                migrated_from=payload["request_id"],
                                state=req.state, length=length)
        return req

    # -- retire --------------------------------------------------------
    def _maybe_finish(self, slot: int, now: float) -> None:
        req = self._slot_req[slot]
        done = len(req.output) >= req.max_new_tokens
        if req.eos_token_id is not None and req.output and \
                req.output[-1] == req.eos_token_id:
            done = True
        if not done:
            return
        req.state = FINISHED
        req.finish_time = now
        # index the full blocks over prompt + output before the free, so
        # the freed blocks park on the cached LRU instead of zeroing —
        # a follow-up turn (prompt = this conversation + more) re-matches
        self._publish_prefix(slot, req)
        self.pool.free(req.request_id)
        self._slot_req[slot] = None
        self._lengths[slot] = self.capacity  # park
        self.finished.append(req)
        if req.ttft is not None:
            self.ttft_hist.record(req.ttft)
        for prev, cur in zip(req.token_times, req.token_times[1:]):
            self.tok_hist.record(cur - prev)
        if self.telemetry is not None:
            self.telemetry.emit("serve_request", **req.stats())

    # ------------------------------------------------------------------
    # loops
    # ------------------------------------------------------------------
    def run_until_drained(self, max_ticks: int = 10**9, admit: bool = True) -> int:
        """Tick until queue + slots are empty; returns ticks run."""
        n = 0
        while (self.in_flight or len(self.queue)) and n < max_ticks:
            self.step(admit=admit)
            n += 1
        return n

    def serve(self, requests=(), guard=None, migrate=None) -> int:
        """Serve ``requests`` to completion under a preemption guard.

        SIGTERM/SIGINT mid-serve triggers the drain contract (reusing
        PR 9's ``runtime/resilience`` signal handling): stop admitting,
        terminally REFUSE everything still queued, FINISH every in-flight
        request, and return ``DEFAULT_PREEMPT_EXIT_CODE`` (143) so a
        supervisor reads preemption, not success. Returns 0 on a normal
        complete drain.

        ``migrate`` (graft-fleet): optional ``migrate(scheduler, signal)
        -> {"migrated": int, "bundle": str}`` hook tried on preemption
        AFTER the queue is refused. On success (the hook exported every
        in-flight request — :meth:`export_inflight` released the slots)
        a ``serve_migrate_out`` event lands and the loop exits without
        generating further tokens here; on :class:`MigrationError` the
        PR-14 drain contract resumes untouched — in-flight requests
        finish locally."""
        from deepspeed_tpu.runtime.resilience.signals import (
            DEFAULT_PREEMPT_EXIT_CODE, PreemptionGuard)
        own_guard = guard is None
        if own_guard:
            guard = PreemptionGuard().install()
        preempted = None
        try:
            for r in requests:
                self.submit(r)
            while self.in_flight or len(self.queue):
                if guard.requested and preempted is None:
                    preempted = guard.consume()
                    refused = self.queue.refuse_all(f"draining on {preempted}")
                    log_dist(f"graft-serve: {preempted} — draining "
                             f"{len(self.in_flight)} in-flight, refused "
                             f"{len(refused)} queued")
                    if self.telemetry is not None:
                        self.telemetry.emit("serve_drain", signal=preempted,
                                            in_flight=len(self.in_flight),
                                            refused=len(refused))
                    if migrate is not None and self.in_flight:
                        try:
                            out = migrate(self, preempted)
                        except MigrationError as e:
                            log_dist(f"graft-serve: migration refused "
                                     f"({e}) — draining instead")
                        else:
                            if self.telemetry is not None:
                                self.telemetry.emit(
                                    "serve_migrate_out", signal=preempted,
                                    migrated=int(out.get("migrated", 0)),
                                    bundle=str(out.get("bundle", "")))
                            continue  # slots released — loop re-checks
                self.step(admit=preempted is None)
        finally:
            if own_guard:
                guard.uninstall()
        return DEFAULT_PREEMPT_EXIT_CODE if preempted else 0

    # ------------------------------------------------------------------
    def stats(self) -> dict:
        """Aggregate serving evidence: latency distributions, goodput
        inputs, speculation acceptance, pool accounting, tick mix."""
        done = [r for r in self.finished]
        pool = dict(self.pool.counters())
        # admission-depth units (satellite: visible in every bench row,
        # not just the A/B summary): bytes per KV block and blocks per GB
        # from the measured per-token cache footprint
        block_bytes = max(1, int(round(self._kv_bytes_per_token()
                                       * self.pool.block_size)))
        pool["kv_block_bytes"] = block_bytes
        pool["kv_blocks_per_gb"] = (1 << 30) // block_bytes
        out = {
            "finished": len(done),
            "refused": self.queue.refused,
            "generated_tokens": sum(len(r.output) for r in done),
            "ticks": dict(self.ticks),
            "pool": pool,
            "kv_write": self.kv_write,
            "kv_write_source": self.kv_write_source,
            "weight_dtype": self.weight_dtype,
            "weight_dtype_source": self.weight_dtype_source,
            "kv_quant": self.kv_quant,
            "prefix_cache": self.prefix_cache,
            "prefix_cache_source": self.prefix_cache_source,
            "cached_prefix_tokens": sum(r.cached_prefix_tokens for r in done),
            "ttft": self.ttft_hist.snapshot() if self.ttft_hist.count else None,
            "per_token": self.tok_hist.snapshot() if self.tok_hist.count else None,
        }
        if self.spec_k:
            out["spec_k"] = self.spec_k
            out["drafted"] = self.drafted_total
            out["accepted"] = self.accepted_total
            out["acceptance_rate"] = (self.accepted_total / self.drafted_total
                                      if self.drafted_total else None)
        if (self.rollout_experience or self.weight_sync_generation
                or self.learner_steps_overlapped):
            # graft-rlhf rollout evidence (present iff this scheduler
            # served an RLHF loop — plain serving stats stay unchanged)
            out["rollout"] = {
                "experience": self.rollout_experience,
                "learner_steps_overlapped": self.learner_steps_overlapped,
                "weight_sync_generation": self.weight_sync_generation,
                "last_weight_sync": self.last_weight_sync,
            }
        return out
