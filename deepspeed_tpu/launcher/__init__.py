"""Launcher (reference ``deepspeed/launcher/``)."""
