"""Per-node process spawner (reference ``deepspeed/launcher/launch.py``:
``main`` :132, signal handling / ``terminate_process_tree`` :118).

TPU difference: ONE worker process per host — JAX drives every local chip
from a single process, and ``jax.distributed.initialize`` (seeded from the
env set here) replaces per-rank NCCL rendezvous. The reference's
one-process-per-GPU fanout collapses to a single child with supervision.
"""

import argparse
import os
import signal
import socket
import subprocess
import sys
import time

from deepspeed_tpu.utils.logging import logger


def infer_node_rank(default: int = 0) -> int:
    """Derive this host's node rank when the launcher ran an identical
    command on every node (pdsh/mpirun/srun — reference
    ``launcher/launch.py:132`` reads RANK-style env per backend).

    Priority: scheduler-provided rank env (OpenMPI/MPICH/Slurm), then
    position of the local hostname in ``DS_NODE_LIST`` (set by PDSHRunner).
    A DS_NODE_LIST that does not contain this host is a hard error — every
    node silently claiming rank ``default`` would deadlock the rendezvous.
    """
    for var in ("OMPI_COMM_WORLD_RANK", "PMI_RANK", "SLURM_NODEID"):
        if os.environ.get(var):
            return int(os.environ[var])
    node_list = os.environ.get("DS_NODE_LIST", "")
    if node_list:
        hosts = node_list.split(",")
        if len(hosts) == 1:
            return 0  # unambiguous regardless of how the host is spelled
        candidates = {socket.gethostname(), socket.gethostname().split(".")[0]}
        try:
            candidates.add(socket.gethostbyname(socket.gethostname()))
        except OSError:
            pass
        for rank, host in enumerate(hosts):
            if host in candidates:
                return rank
        raise RuntimeError(
            f"cannot infer node rank: DS_NODE_LIST={node_list} does not contain this "
            f"host (known identities: {sorted(candidates)}); use IPs/hostnames in the "
            f"hostfile that the nodes recognize, or the ssh launcher which assigns "
            f"explicit ranks")
    if default < 0:
        raise RuntimeError("node rank not determinable: no scheduler rank env "
                           "(OMPI_COMM_WORLD_RANK/PMI_RANK/SLURM_NODEID), no DS_NODE_LIST, "
                           "and no explicit --node_rank")
    return default


def parse_args(args=None):
    parser = argparse.ArgumentParser()
    parser.add_argument("--node_rank", type=int, default=0)
    parser.add_argument("--nnodes", type=int, default=1)
    parser.add_argument("--master_addr", type=str, default="127.0.0.1")
    parser.add_argument("--master_port", type=int, default=29500)
    parser.add_argument("--num_chips", type=int, default=0)
    # reference launch.py:92 --bind_cores_to_rank: pin each node process to
    # its share of host cores (input pipeline / offload-optimizer threads)
    parser.add_argument("--bind_cores_to_rank", action="store_true")
    parser.add_argument("--bind_core_list", type=str, default=None)
    parser.add_argument("user_script", type=str)
    parser.add_argument("user_args", nargs=argparse.REMAINDER)
    return parser.parse_args(args)


def terminate_process_tree(pid: int):
    """Kill a child and its descendants (reference ``launch.py:118``)."""
    try:
        os.killpg(os.getpgid(pid), signal.SIGTERM)
        time.sleep(2)
        os.killpg(os.getpgid(pid), signal.SIGKILL)
    except ProcessLookupError:
        pass


def build_child_env(node_rank: int, nnodes: int, master_addr: str, master_port: int,
                    num_chips: int = 0) -> dict:
    """Env contract consumed by ``comm.init_distributed`` →
    ``jax.distributed.initialize``."""
    env = os.environ.copy()
    env["COORDINATOR_ADDRESS"] = f"{master_addr}:{master_port}"
    env["JAX_COORDINATOR_ADDRESS"] = env["COORDINATOR_ADDRESS"]
    env["NODE_RANK"] = str(node_rank)
    env["JAX_PROCESS_ID"] = str(node_rank)
    env["JAX_NUM_PROCESSES"] = str(nnodes)
    # the comm bootstrap's primary env family (comm.init_distributed) —
    # set both so user scripts and the test harness see one contract
    env["DSTPU_COORDINATOR_ADDRESS"] = env["COORDINATOR_ADDRESS"]
    env["DSTPU_PROCESS_ID"] = str(node_rank)
    env["DSTPU_NUM_PROCESSES"] = str(nnodes)
    # reference-compatible names so user scripts keep working
    env["RANK"] = str(node_rank)
    env["LOCAL_RANK"] = "0"
    env["WORLD_SIZE"] = str(nnodes)
    env["MASTER_ADDR"] = master_addr
    env["MASTER_PORT"] = str(master_port)
    if num_chips:
        env["DS_TPU_NUM_CHIPS"] = str(num_chips)
    return env


def main(args=None):
    args = parse_args(args)
    if args.node_rank >= 0:
        # explicit rank (SSHRunner assigns these per host); an inherited
        # scheduler rank env must not silently override it
        node_rank = args.node_rank
        for var in ("OMPI_COMM_WORLD_RANK", "PMI_RANK", "SLURM_NODEID"):
            val = os.environ.get(var)
            if val and int(val) != node_rank:
                logger.warning(f"{var}={val} disagrees with explicit --node_rank "
                               f"{node_rank}; using --node_rank")
    else:
        node_rank = infer_node_rank(default=-1)
    env = build_child_env(node_rank, args.nnodes, args.master_addr, args.master_port,
                          args.num_chips)
    cmd = [sys.executable, args.user_script] + args.user_args
    if args.bind_cores_to_rank:
        # this launcher spawns ONE process per node (LOCAL_RANK=0), so the
        # bind is over all of this host's cores (or the user's core list) —
        # the (num_local_procs, local_rank) slice is (1, 0), NOT the global
        # node rank: slicing by node rank would strand most of each host
        from deepspeed_tpu.utils.numa import get_numactl_cmd
        cores_per_rank, numactl_prefix = get_numactl_cmd(args.bind_core_list, 1, 0)
        env["OMP_NUM_THREADS"] = str(cores_per_rank)
        if numactl_prefix:
            cmd = numactl_prefix + cmd
        else:
            # no numactl on the host: the child binds itself
            env["DS_BIND_CORES"] = args.bind_core_list or "all"
            env["DS_BIND_RANK"] = "0"
            env["DS_BIND_NPROCS"] = "1"
    logger.info(f"node {node_rank}/{args.nnodes}: spawning {' '.join(cmd)}")
    child = subprocess.Popen(cmd, env=env, start_new_session=True)

    def handler(signum, frame):
        logger.warning(f"signal {signum}: terminating child {child.pid}")
        terminate_process_tree(child.pid)
        sys.exit(128 + signum)

    signal.signal(signal.SIGTERM, handler)
    signal.signal(signal.SIGINT, handler)
    return child.wait()


if __name__ == "__main__":
    sys.exit(main())
