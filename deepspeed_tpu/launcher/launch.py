"""Per-node process spawner (reference ``deepspeed/launcher/launch.py``:
``main`` :132, signal handling / ``terminate_process_tree`` :118).

TPU difference: ONE worker process per host — JAX drives every local chip
from a single process, and ``jax.distributed.initialize`` (seeded from the
env set here) replaces per-rank NCCL rendezvous. The reference's
one-process-per-GPU fanout collapses to a single child with supervision.
"""

import argparse
import os
import signal
import subprocess
import sys
import time

from deepspeed_tpu.utils.logging import logger


def parse_args(args=None):
    parser = argparse.ArgumentParser()
    parser.add_argument("--node_rank", type=int, default=0)
    parser.add_argument("--nnodes", type=int, default=1)
    parser.add_argument("--master_addr", type=str, default="127.0.0.1")
    parser.add_argument("--master_port", type=int, default=29500)
    parser.add_argument("--num_chips", type=int, default=0)
    parser.add_argument("user_script", type=str)
    parser.add_argument("user_args", nargs=argparse.REMAINDER)
    return parser.parse_args(args)


def terminate_process_tree(pid: int):
    """Kill a child and its descendants (reference ``launch.py:118``)."""
    try:
        os.killpg(os.getpgid(pid), signal.SIGTERM)
        time.sleep(2)
        os.killpg(os.getpgid(pid), signal.SIGKILL)
    except ProcessLookupError:
        pass


def build_child_env(node_rank: int, nnodes: int, master_addr: str, master_port: int,
                    num_chips: int = 0) -> dict:
    """Env contract consumed by ``comm.init_distributed`` →
    ``jax.distributed.initialize``."""
    env = os.environ.copy()
    env["COORDINATOR_ADDRESS"] = f"{master_addr}:{master_port}"
    env["JAX_COORDINATOR_ADDRESS"] = env["COORDINATOR_ADDRESS"]
    env["NODE_RANK"] = str(node_rank)
    env["JAX_PROCESS_ID"] = str(node_rank)
    env["JAX_NUM_PROCESSES"] = str(nnodes)
    # reference-compatible names so user scripts keep working
    env["RANK"] = str(node_rank)
    env["LOCAL_RANK"] = "0"
    env["WORLD_SIZE"] = str(nnodes)
    env["MASTER_ADDR"] = master_addr
    env["MASTER_PORT"] = str(master_port)
    if num_chips:
        env["DS_TPU_NUM_CHIPS"] = str(num_chips)
    return env


def main(args=None):
    args = parse_args(args)
    env = build_child_env(args.node_rank, args.nnodes, args.master_addr, args.master_port,
                          args.num_chips)
    cmd = [sys.executable, args.user_script] + args.user_args
    logger.info(f"node {args.node_rank}/{args.nnodes}: spawning {' '.join(cmd)}")
    child = subprocess.Popen(cmd, env=env, start_new_session=True)

    def handler(signum, frame):
        logger.warning(f"signal {signum}: terminating child {child.pid}")
        terminate_process_tree(child.pid)
        sys.exit(128 + signum)

    signal.signal(signal.SIGTERM, handler)
    signal.signal(signal.SIGINT, handler)
    return child.wait()


if __name__ == "__main__":
    sys.exit(main())
