"""Multi-node runner backends (reference ``launcher/multinode_runner.py``:
``MultiNodeRunner`` :18, PDSH :51, OpenMPI :107, MPICH :160, Slurm :313).

Each runner turns (resource pool, env, user command) into the backend's
launch command line. On TPU pods the per-node payload is
``deepspeed_tpu.launcher.launch`` with node-rank/coordinator env.
"""

import os
import shutil
import sys
from abc import ABC, abstractmethod
from shlex import quote


class MultiNodeRunner(ABC):

    def __init__(self, args, world_info_base64, master_addr):
        self.args = args
        self.world_info_base64 = world_info_base64
        self.master_addr = master_addr
        self.user_arguments = list(args.user_args)
        self.user_script = args.user_script

    @abstractmethod
    def backend_exists(self) -> bool:
        ...

    @abstractmethod
    def get_cmd(self, environment, active_resources):
        ...

    @property
    def name(self) -> str:
        return self.__class__.__name__.lower().replace("runner", "")

    def _node_payload(self, node_rank: int, nnodes: int):
        return [sys.executable, "-m", "deepspeed_tpu.launcher.launch",
                "--node_rank", str(node_rank), "--nnodes", str(nnodes),
                "--master_addr", self.master_addr,
                "--master_port", str(self.args.master_port),
                self.user_script] + self.user_arguments


class PDSHRunner(MultiNodeRunner):
    """Reference ``:51``: pdsh fanout; node rank derived from %n on each
    target via the hostlist ordering."""

    def backend_exists(self):
        return shutil.which("pdsh") is not None

    def get_cmd(self, environment, active_resources):
        environment["PDSH_RCMD_TYPE"] = "ssh"
        hosts = ",".join(active_resources.keys())
        exports = " ".join(f"export {k}={quote(str(environment[k]))};"
                           for k in ("PYTHONPATH", "PATH") if k in environment)
        # pdsh runs an identical command on all hosts: launch.py infers its
        # node rank from DS_NODE_LIST (position of the local hostname)
        node_list = ",".join(active_resources.keys())
        cmd_to_run = (f"{exports} cd {os.path.abspath('.')}; "
                      f"DS_NODE_LIST={node_list} DS_WORLD_INFO={self.world_info_base64} "
                      + " ".join(map(quote, self._node_payload(-1, len(active_resources)))))
        return ["pdsh", "-S", "-f", "1024", "-w", hosts, cmd_to_run]


class OpenMPIRunner(MultiNodeRunner):
    """Reference ``:107``."""

    def backend_exists(self):
        return shutil.which("ompi_info") is not None

    def get_cmd(self, environment, active_resources):
        nnodes = len(active_resources)
        hosts = ",".join(f"{h}:1" for h in active_resources)  # 1 process per host
        mpirun = ["mpirun", "-n", str(nnodes), "--host", hosts, "--map-by", "ppr:1:node"]
        for var in ("PYTHONPATH", "PATH"):
            if var in environment:
                mpirun += ["-x", var]
        if self.args.launcher_args:
            mpirun += self.args.launcher_args.split()
        # node_rank=-1: launch.py infers the rank from OMPI_COMM_WORLD_RANK
        return mpirun + self._node_payload(-1, nnodes)


class MPICHRunner(OpenMPIRunner):
    """Reference ``:160``."""

    def backend_exists(self):
        return shutil.which("mpirun") is not None and shutil.which("ompi_info") is None


class SlurmRunner(MultiNodeRunner):
    """Reference ``:313``."""

    def backend_exists(self):
        return shutil.which("srun") is not None

    def get_cmd(self, environment, active_resources):
        nnodes = len(active_resources)
        srun = ["srun", "--nodes", str(nnodes), "--ntasks-per-node", "1"]
        if getattr(self.args, "include", ""):
            srun += ["--nodelist", ",".join(active_resources.keys())]
        if self.args.launcher_args:
            srun += self.args.launcher_args.split()
        # node_rank=-1: launch.py infers the rank from SLURM_NODEID
        return srun + self._node_payload(-1, nnodes)


class SSHRunner(MultiNodeRunner):
    """Plain-ssh fallback: one ssh per node with explicit node rank (no
    fanout tool required; useful on bare TPU-VM pods)."""

    def backend_exists(self):
        return shutil.which("ssh") is not None

    def get_cmd(self, environment, active_resources):
        # emits a shell script executing one ssh per node, backgrounded
        lines = []
        nnodes = len(active_resources)
        for rank, host in enumerate(active_resources):
            payload = " ".join(map(quote, self._node_payload(rank, nnodes)))
            lines.append(f"ssh {host} {quote(f'cd {os.path.abspath(os.curdir)} && {payload}')} &")
        lines.append("wait")
        return ["bash", "-c", "\n".join(lines)]


def get_runner(name: str, args, world_info, active_resources, master_addr) -> MultiNodeRunner:
    runners = {"pdsh": PDSHRunner, "openmpi": OpenMPIRunner, "mpich": MPICHRunner,
               "slurm": SlurmRunner, "ssh": SSHRunner}
    if name not in runners:
        raise ValueError(f"unknown launcher {name!r}; available: {sorted(runners)}")
    return runners[name](args, world_info, master_addr)
