"""Multi-host launch CLI (reference ``deepspeed/launcher/runner.py``:
``main`` :387, hostfile parse :199, --include/--exclude filters :254,
world-info encode :352).

TPU semantics: one worker **process per host** (JAX owns all local chips;
``jax.distributed.initialize`` replaces the per-rank NCCL rendezvous), so a
"slot" in the hostfile is a chip for accounting but processes are spawned
per node. The per-node spawner is ``launcher/launch.py``.
"""

import argparse
import base64
import json
import os
import re
import subprocess
import sys
from collections import OrderedDict
from typing import Dict

from deepspeed_tpu.utils.logging import logger

DLTS_HOSTFILE = "/job/hostfile"
EXPORT_ENVS = ["PYTHONPATH", "PATH", "JAX_PLATFORMS", "TPU_CHIPS_PER_HOST_BOUNDS", "XLA_FLAGS",
               "DS_AUTOTUNING"]


def parse_args(args=None):
    parser = argparse.ArgumentParser(
        description="deepspeed-tpu launcher",
        formatter_class=argparse.ArgumentDefaultsHelpFormatter)
    parser.add_argument("-H", "--hostfile", type=str, default=DLTS_HOSTFILE,
                        help="Hostfile: lines of '<host> slots=<n_chips>'")
    parser.add_argument("-i", "--include", type=str, default="",
                        help="Include hosts/chips, e.g. 'host1@host2:0,2'")
    parser.add_argument("-e", "--exclude", type=str, default="",
                        help="Exclude hosts/chips, same syntax as --include")
    parser.add_argument("--num_nodes", type=int, default=-1)
    parser.add_argument("--num_gpus", "--num_chips", dest="num_gpus", type=int, default=-1)
    parser.add_argument("--master_addr", type=str, default="")
    parser.add_argument("--master_port", type=int, default=29500)
    parser.add_argument("--launcher", type=str, default="pdsh",
                        choices=["pdsh", "openmpi", "mpich", "slurm", "ssh"])
    parser.add_argument("--launcher_args", type=str, default="")
    parser.add_argument("--force_multi", action="store_true")
    parser.add_argument("--elastic_training", action="store_true")
    parser.add_argument("--autotuning", type=str, default="", choices=["", "tune", "run"],
                        help="Run the autotuner before training: 'tune' writes the optimal "
                             "config and exits; 'run' continues training under it "
                             "(reference runner.py:358)")
    parser.add_argument("user_script", type=str, help="training script to launch")
    parser.add_argument("user_args", nargs=argparse.REMAINDER)
    return parser.parse_args(args)


def fetch_hostfile(hostfile_path: str) -> Dict[str, int]:
    """Parse ``host slots=N`` lines (reference ``runner.py:199``)."""
    if not os.path.isfile(hostfile_path):
        return {}
    resource_pool = OrderedDict()
    with open(hostfile_path) as f:
        for line in f:
            line = line.strip()
            if not line or line.startswith("#"):
                continue
            m = re.match(r"^(\S+)\s+slots=(\d+)$", line)
            if m is None:
                raise ValueError(f"hostfile line malformed: {line!r} (want '<host> slots=<n>')")
            host, slots = m.group(1), int(m.group(2))
            if host in resource_pool:
                raise ValueError(f"hostfile contains duplicate host {host}")
            resource_pool[host] = slots
    return resource_pool


def _parse_filter(spec: str) -> Dict[str, list]:
    """``host1@host2:0,2`` → {host1: [], host2: [0, 2]} (reference
    ``parse_resource_filter`` semantics; [] = whole host)."""
    out = OrderedDict()
    if not spec:
        return out
    for part in spec.split("@"):
        if ":" in part:
            host, slots = part.split(":", 1)
            out[host] = [int(s) for s in slots.split(",")]
        else:
            out[part] = []
    return out


def parse_resource_filter(resource_pool: Dict[str, int], include_str="", exclude_str=""):
    """Apply --include/--exclude (reference ``runner.py:254``)."""
    if include_str and exclude_str:
        raise ValueError("--include and --exclude are mutually exclusive")
    active = OrderedDict()
    if include_str:
        for host, slots in _parse_filter(include_str).items():
            if host not in resource_pool:
                raise ValueError(f"included host {host} not in hostfile")
            avail = resource_pool[host]
            if slots:
                bad = [s for s in slots if s >= avail]
                if bad:
                    raise ValueError(f"host {host} has {avail} slots; invalid: {bad}")
                active[host] = len(slots)
            else:
                active[host] = avail
        return active
    if exclude_str:
        excl = _parse_filter(exclude_str)
        for host, avail in resource_pool.items():
            if host in excl:
                slots = excl[host]
                if not slots:
                    continue  # whole host excluded
                remaining = avail - len(slots)
                if remaining > 0:
                    active[host] = remaining
            else:
                active[host] = avail
        return active
    return OrderedDict(resource_pool)


def encode_world_info(resource_pool: Dict[str, int]) -> str:
    """base64 world info handed to every node (reference ``runner.py:352``)."""
    return base64.urlsafe_b64encode(json.dumps(resource_pool).encode()).decode()


def main(args=None):
    args = parse_args(args)
    if args.autotuning:
        # the in-process tuner engages at the engine's first batch
        os.environ["DS_AUTOTUNING"] = args.autotuning
    resource_pool = fetch_hostfile(args.hostfile)

    if not resource_pool:
        # single node: all local chips
        n = args.num_gpus if args.num_gpus > 0 else 0
        env = os.environ.copy()
        cmd = [sys.executable, "-m", "deepspeed_tpu.launcher.launch",
               "--node_rank", "0", "--nnodes", "1",
               "--master_addr", args.master_addr or "127.0.0.1",
               "--master_port", str(args.master_port)]
        if n:
            cmd += ["--num_chips", str(n)]
        cmd += [args.user_script] + args.user_args
        logger.info(f"single-node launch: {' '.join(cmd)}")
        return subprocess.call(cmd, env=env)

    active = parse_resource_filter(resource_pool, args.include, args.exclude)
    if args.num_nodes > 0:
        active = OrderedDict(list(active.items())[:args.num_nodes])
    world_info = encode_world_info(active)
    master_addr = args.master_addr or list(active.keys())[0]

    from deepspeed_tpu.launcher.multinode_runner import get_runner
    runner = get_runner(args.launcher, args, world_info, active, master_addr)
    if not runner.backend_exists():
        raise RuntimeError(f"launcher backend {args.launcher!r} not available on this system")
    cmd = runner.get_cmd(os.environ.copy(), active)
    logger.info(f"multi-node launch ({args.launcher}): {' '.join(cmd)}")
    return subprocess.call(cmd)


if __name__ == "__main__":
    sys.exit(main())
