"""Import-path parity with reference ``deepspeed/model_implementations``:
the served-model wrappers (diffusers UNet/VAE; the transformer serving
implementations live in ``deepspeed_tpu.inference``)."""
from deepspeed_tpu.models.diffusion import DSUNet, DSVAE

__all__ = ["DSUNet", "DSVAE"]
