from deepspeed_tpu.model_implementations.diffusers.unet import DSUNet
from deepspeed_tpu.model_implementations.diffusers.vae import DSVAE

__all__ = ["DSUNet", "DSVAE"]
