"""Reference path shim: ``deepspeed.model_implementations.diffusers.unet``.
The implementation lives with the model family (models/diffusion.py)."""
from deepspeed_tpu.models.diffusion import DSUNet

__all__ = ["DSUNet"]
