"""Reference path shim: ``deepspeed.model_implementations.diffusers.vae``."""
from deepspeed_tpu.models.diffusion import DSVAE

__all__ = ["DSVAE"]
