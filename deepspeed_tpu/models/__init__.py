from deepspeed_tpu.models.gpt2 import (GPT2Config, GPT2LMHeadModel, GPT2_CONFIGS, get_gpt2_config,
                                       cross_entropy_loss)
