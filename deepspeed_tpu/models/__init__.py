from deepspeed_tpu.models.gpt2 import (GPT2Config, GPT2LMHeadModel, GPT2_CONFIGS, get_gpt2_config,
                                       cross_entropy_loss)
from deepspeed_tpu.models.llama import (LlamaConfig, LlamaForCausalLM, LLAMA_CONFIGS, get_llama_config)
from deepspeed_tpu.models.bert import (BertConfig, BertModel, BertForMaskedLM, BERT_CONFIGS,
                                       get_bert_config, bert_mlm_loss)
from deepspeed_tpu.models.opt import (OPTConfig, OPTForCausalLM, OPT_CONFIGS, get_opt_config)
from deepspeed_tpu.models.gpt_neox import (GPTNeoXConfig, GPTNeoXForCausalLM, GPT_NEOX_CONFIGS,
                                            get_gpt_neox_config)
from deepspeed_tpu.models.bloom import (BloomConfig, BloomForCausalLM, BLOOM_CONFIGS,
                                        get_bloom_config)
from deepspeed_tpu.models.t5 import (T5Config, T5ForConditionalGeneration, T5_CONFIGS,
                                     get_t5_config)
from deepspeed_tpu.models.falcon import (FalconConfig, FalconForCausalLM, FALCON_CONFIGS,
                                          get_falcon_config)
from deepspeed_tpu.models.gptj import (GPTJConfig, GPTJForCausalLM, GPTJ_CONFIGS,
                                       get_gptj_config)
from deepspeed_tpu.models.gpt_neo import (GPTNeoConfig, GPTNeoForCausalLM, GPT_NEO_CONFIGS,
                                          get_gpt_neo_config)
from deepspeed_tpu.models.clip import (CLIPTextConfig, CLIPTextModel, CLIP_TEXT_CONFIGS,
                                       get_clip_text_config)
