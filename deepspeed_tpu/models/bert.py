"""BERT family — bidirectional encoder for the MLM/pretraining configs
(judged ladder: BERT-large ZeRO-1 + FusedAdam, BASELINE.md; the reference's
fastest-BERT benchmark is its fused training transformer,
``csrc/transformer/ds_transformer_cuda.cpp``, and its test fixture is a
vendored BERT, ``tests/unit/modeling.py``).

Post-LN encoder (original BERT), logical sharding names as in gpt2.py.
"""

import dataclasses
from typing import Any, Optional

import flax.linen as nn
import jax
import jax.numpy as jnp

from deepspeed_tpu.models.common import (attention_geometry_kwargs, config_from,
                                         dense_init as _init, normalize_padding_mask)
from deepspeed_tpu.ops.transformer.attention import dot_product_attention


@dataclasses.dataclass(frozen=True)
class BertConfig:
    vocab_size: int = 30522
    hidden_size: int = 768
    num_hidden_layers: int = 12
    num_attention_heads: int = 12
    intermediate_size: int = 3072
    max_position_embeddings: int = 512
    type_vocab_size: int = 2
    layer_norm_eps: float = 1e-12
    # "gelu_tanh"/"gelu_new" (default: the reference training kernel's
    # approximation, ``csrc/transformer/gelu_kernels.cu``), "gelu" (exact
    # erf — what HF BERT/DistilBERT checkpoints use; the converters raise
    # on a mismatch), or "relu"
    hidden_act: str = "gelu_tanh"
    hidden_dropout_prob: float = 0.0
    dtype: Any = jnp.float32
    param_dtype: Any = jnp.float32
    remat: bool = False
    attention_backend: str = "xla"
    # flash-backend block geometry / bwd policy override, as a spec string
    # (models/common.py attention_geometry_kwargs); None = resolve via
    # env/config/autotune layers
    attention_blocks: Optional[str] = None
    # progressive layer drop (arXiv:2010.13369 targets BERT; reference
    # ``runtime/progressive_layer_drop.py``): stochastically skip sublayers
    # at train time with depth-scaled keep probability when the engine
    # passes ``pld_theta``
    progressive_layer_drop: bool = False
    # [B, L] attention masks are treated as CONTIGUOUS right-padding (the
    # HF standard) so the flash kernel can mask natively via per-sequence
    # lengths. Set False for non-prefix [B, L] masks (e.g. left padding)
    # to route them through the exact mask= path instead.
    flash_prefix_padding: bool = True

    @property
    def head_dim(self):
        return self.hidden_size // self.num_attention_heads

    @property
    def dropout(self):
        # engine looks at cfg.dropout to decide whether to thread rngs
        return self.hidden_dropout_prob


BERT_CONFIGS = {
    "test": dict(vocab_size=256, hidden_size=64, num_hidden_layers=2, num_attention_heads=4,
                 intermediate_size=128, max_position_embeddings=128),
    "base": dict(hidden_size=768, num_hidden_layers=12, num_attention_heads=12,
                 intermediate_size=3072),
    "large": dict(hidden_size=1024, num_hidden_layers=24, num_attention_heads=16,
                  intermediate_size=4096),
    # DistilBERT serves through the BERT family (see load_hf_distilbert):
    # 6 layers, no token types, exact gelu
    "distilbert": dict(vocab_size=30522, hidden_size=768, num_hidden_layers=6,
                       num_attention_heads=12, intermediate_size=3072,
                       type_vocab_size=1, hidden_act="gelu"),
}


def get_bert_config(name: str, **overrides) -> BertConfig:
    return config_from(BERT_CONFIGS, BertConfig, name, **overrides)


def _activation(cfg: BertConfig, h):
    """Dispatch ``cfg.hidden_act`` — unknown names raise instead of
    silently falling back to an approximation."""
    if cfg.hidden_act == "gelu":
        return jax.nn.gelu(h, approximate=False)
    if cfg.hidden_act in ("gelu_tanh", "gelu_new"):
        return jax.nn.gelu(h, approximate=True)
    if cfg.hidden_act == "relu":
        return jax.nn.relu(h)
    raise ValueError(f"unknown hidden_act {cfg.hidden_act!r}; "
                     f"choose from ['gelu', 'gelu_tanh', 'gelu_new', 'relu']")


class BertLayerNorm(nn.Module):
    config: BertConfig

    @nn.compact
    def __call__(self, x):
        cfg = self.config
        return nn.LayerNorm(epsilon=cfg.layer_norm_eps, dtype=cfg.dtype, param_dtype=cfg.param_dtype,
                            scale_init=nn.with_logical_partitioning(nn.initializers.ones, ("embed",)),
                            bias_init=nn.with_logical_partitioning(nn.initializers.zeros, ("embed",)))(x)


class BertSelfAttention(nn.Module):
    config: BertConfig

    @nn.compact
    def __call__(self, x, attention_mask=None, deterministic: bool = True):
        cfg = self.config

        def proj(name):
            return nn.DenseGeneral(features=(cfg.num_attention_heads, cfg.head_dim), axis=-1,
                                   dtype=cfg.dtype, param_dtype=cfg.param_dtype,
                                   kernel_init=nn.with_logical_partitioning(_init(), ("embed", "heads", "kv")),
                                   bias_init=nn.with_logical_partitioning(nn.initializers.zeros,
                                                                          ("heads", "kv")),
                                   name=name)

        q, k, v = proj("query")(x), proj("key")(x), proj("value")(x)
        if (attention_mask is not None and attention_mask.ndim == 2
                and cfg.attention_backend == "flash" and cfg.flash_prefix_padding):
            # [B, L] 0/1 padding mask: under the flash backend, pass the
            # valid-prefix lengths so the kernel masks natively instead of
            # falling back to XLA. Contract (cfg.flash_prefix_padding):
            # [B, L] masks are CONTIGUOUS right-padding (the HF standard);
            # left-padded or holey masks must set the flag False (or come
            # in pre-broadcast [B,1,1,L]) to take the exact mask= path.
            out = dot_product_attention(q, k, v, backend=cfg.attention_backend,
                                        causal=False,
                                        kv_lengths=attention_mask.sum(axis=-1).astype(jnp.int32),
                                        **attention_geometry_kwargs(cfg))
        else:
            mask = normalize_padding_mask(attention_mask)
            out = dot_product_attention(q, k, v, backend=cfg.attention_backend,
                                        causal=False, mask=mask,
                                        **attention_geometry_kwargs(cfg))
        out = nn.DenseGeneral(features=cfg.hidden_size, axis=(-2, -1), dtype=cfg.dtype,
                              param_dtype=cfg.param_dtype,
                              kernel_init=nn.with_logical_partitioning(_init(), ("heads", "kv", "embed")),
                              bias_init=nn.with_logical_partitioning(nn.initializers.zeros, ("embed",)),
                              name="output")(out)
        if not deterministic and cfg.hidden_dropout_prob > 0:
            out = nn.Dropout(rate=cfg.hidden_dropout_prob)(out, deterministic=False)
        return out


class BertLayer(nn.Module):
    """Post-LN transformer encoder layer (original BERT ordering; the
    reference's fused layer supports both pre/post-LN,
    ``ds_transformer_cuda.cpp`` pre_or_postLayerNorm)."""

    config: BertConfig

    def _pld_gate(self, branch, keep):
        # post-LN form: LN(x + b·f(x)/keep)
        from deepspeed_tpu.models.common import pld_gate
        return pld_gate(self, branch, keep)[0]

    @nn.compact
    def __call__(self, x, attention_mask=None, deterministic: bool = True, pld_keep=None):
        cfg = self.config
        keep = None if (deterministic or pld_keep is None) else pld_keep
        attn = BertSelfAttention(cfg, name="attention")(x, attention_mask, deterministic)
        x = BertLayerNorm(cfg, name="attention_ln")(x + self._pld_gate(attn, keep))
        h = nn.Dense(features=cfg.intermediate_size, dtype=cfg.dtype, param_dtype=cfg.param_dtype,
                     kernel_init=nn.with_logical_partitioning(_init(), ("embed", "mlp")),
                     bias_init=nn.with_logical_partitioning(nn.initializers.zeros, ("mlp",)),
                     name="intermediate")(x)
        h = _activation(cfg, h)
        h = nn.Dense(features=cfg.hidden_size, dtype=cfg.dtype, param_dtype=cfg.param_dtype,
                     kernel_init=nn.with_logical_partitioning(_init(), ("mlp", "embed")),
                     bias_init=nn.with_logical_partitioning(nn.initializers.zeros, ("embed",)),
                     name="output")(h)
        if not deterministic and cfg.hidden_dropout_prob > 0:
            h = nn.Dropout(rate=cfg.hidden_dropout_prob)(h, deterministic=False)
        return BertLayerNorm(cfg, name="output_ln")(x + self._pld_gate(h, keep))


class BertModel(nn.Module):
    """Embeddings + encoder stack (+ pooler on [CLS])."""

    # offload_param streaming: these block subtrees self-stream inside
    # their remat region (param_offload.stream_block_params); the engine
    # top-streams only the remaining leaves
    streamed_block_prefixes = ("layer_",)


    config: BertConfig

    @nn.compact
    def __call__(self, input_ids, token_type_ids=None, attention_mask=None,
                 deterministic: bool = True, pld_theta=None):
        cfg = self.config
        word = self.param("word_embeddings", nn.with_logical_partitioning(_init(), ("vocab", "embed")),
                          (cfg.vocab_size, cfg.hidden_size), cfg.param_dtype)
        pos = self.param("position_embeddings", nn.with_logical_partitioning(_init(), (None, "embed")),
                         (cfg.max_position_embeddings, cfg.hidden_size), cfg.param_dtype)
        typ = self.param("token_type_embeddings", nn.with_logical_partitioning(_init(), (None, "embed")),
                         (cfg.type_vocab_size, cfg.hidden_size), cfg.param_dtype)
        word_v, pos_v, typ_v = (p.value if isinstance(p, nn.meta.AxisMetadata) else p
                                for p in (word, pos, typ))

        b, l = input_ids.shape
        if token_type_ids is None:
            token_type_ids = jnp.zeros_like(input_ids)
        from deepspeed_tpu.models.common import embed_lookup
        x = (embed_lookup(word_v, input_ids, getattr(cfg, 'embed_onehot_grad', None))
             + pos_v[None, :l]
             + jnp.take(typ_v, token_type_ids, axis=0)).astype(cfg.dtype)
        x = BertLayerNorm(cfg, name="embeddings_ln")(x)

        from deepspeed_tpu.runtime.zero.param_offload import stream_block_params
        layer_cls = stream_block_params(BertLayer)
        if cfg.remat:
            layer_cls = nn.remat(layer_cls, static_argnums=(3,), prevent_cse=False)
        from deepspeed_tpu.models.common import constrain_activation
        # batch-parallel residual stream over fsdp-sharded weights — see
        # constrain_activation (the ZeRO-3 weak-scaling invariant)
        x = constrain_activation(x, "batch", "length", "embed")
        use_pld = cfg.progressive_layer_drop and pld_theta is not None and not deterministic
        for i in range(cfg.num_hidden_layers):
            # PLD depth scaling (paper eq. 6): deeper blocks drop more often
            keep_i = (1.0 - (i + 1) / cfg.num_hidden_layers * (1.0 - pld_theta)
                      if use_pld else None)
            x = layer_cls(cfg, name=f"layer_{i}")(x, attention_mask, deterministic, keep_i)
            x = constrain_activation(x, "batch", "length", "embed")

        pooled = nn.Dense(features=cfg.hidden_size, dtype=cfg.dtype, param_dtype=cfg.param_dtype,
                          kernel_init=nn.with_logical_partitioning(_init(), ("embed", "embed2")),
                          bias_init=nn.with_logical_partitioning(nn.initializers.zeros, ("embed",)),
                          name="pooler")(x[:, 0])
        pooled = jnp.tanh(pooled)
        # word_v is returned so heads can tie their decoder to the embedding
        return x, pooled, word_v


class BertForMaskedLM(nn.Module):
    """MLM head tied to the word embeddings; returns logits [B, L, V]."""

    # offload_param streaming: these block subtrees self-stream inside
    # their remat region (param_offload.stream_block_params); the engine
    # top-streams only the remaining leaves
    streamed_block_prefixes = ("layer_",)


    config: BertConfig

    @nn.compact
    def __call__(self, input_ids, token_type_ids=None, attention_mask=None,
                 deterministic: bool = True, pld_theta=None):
        cfg = self.config
        encoder = BertModel(cfg, name="bert")
        x, _, wte = encoder(input_ids, token_type_ids, attention_mask, deterministic,
                            pld_theta=pld_theta)
        x = nn.Dense(features=cfg.hidden_size, dtype=cfg.dtype, param_dtype=cfg.param_dtype,
                     kernel_init=nn.with_logical_partitioning(_init(), ("embed", "embed2")),
                     bias_init=nn.with_logical_partitioning(nn.initializers.zeros, ("embed",)),
                     name="transform")(x)
        x = _activation(cfg, x)
        x = BertLayerNorm(cfg, name="transform_ln")(x)
        bias = self.param("decoder_bias", nn.with_logical_partitioning(nn.initializers.zeros, ("vocab",)),
                          (cfg.vocab_size,), cfg.param_dtype)
        bias = bias.value if isinstance(bias, nn.meta.AxisMetadata) else bias
        logits = jnp.einsum("ble,ve->blv", x, wte.astype(cfg.dtype),
                            preferred_element_type=jnp.float32) + bias.astype(jnp.float32)
        return logits


def bert_mlm_loss(logits, batch):
    """Masked-LM cross entropy: ``labels == -100`` positions are ignored."""
    from deepspeed_tpu.models.gpt2 import cross_entropy_loss

    labels = batch["labels"] if isinstance(batch, dict) else batch
    return cross_entropy_loss(logits, labels)
