"""BLOOM family — alibi-biased attention, embedding layernorm, fused
per-head QKV (the reference serves BLOOM through kernel injection,
``module_inject/containers/bloom.py``; its alibi build lives in the fused
softmax kernel, ``csrc/transformer/inference/csrc/softmax.cu`` alibi
variants).

TPU formulation: alibi is an additive attention bias ``slope[h] * k_pos``
(softmax is shift-invariant per query row, so keying on absolute k
position equals the relative form and stays valid for KV-cache decode).
The bias rides the attention seam's ``bias`` argument — the XLA backend
adds it inside the fp32 softmax; same conventions as the rest of the zoo
otherwise.
"""

import dataclasses
import math
from typing import Any

import flax.linen as nn
import jax
import jax.numpy as jnp

from deepspeed_tpu.models.common import config_from, dense_init as _init
from deepspeed_tpu.ops.transformer.attention import dot_product_attention


@dataclasses.dataclass(frozen=True)
class BloomConfig:
    vocab_size: int = 250880
    hidden_size: int = 64
    n_head: int = 8
    n_layer: int = 2
    layer_norm_epsilon: float = 1e-5
    max_position_embeddings: int = 2048  # cache size only; alibi needs no table
    dtype: Any = jnp.float32
    param_dtype: Any = jnp.float32
    remat: bool = False
    # >0: loss via the chunked fused LM head when called with labels=
    # (models/common.py fused_lm_head_loss) — no [B, L, V] logits buffer
    fused_head_loss_chunk: int = 0
    attention_backend: str = "xla"

    @property
    def head_dim(self):
        return self.hidden_size // self.n_head


BLOOM_CONFIGS = {
    "test": dict(vocab_size=256, hidden_size=64, n_head=4, n_layer=2,
                 max_position_embeddings=128),
    "560m": dict(hidden_size=1024, n_head=16, n_layer=24),
    "1b7": dict(hidden_size=2048, n_head=16, n_layer=24),
    "7b1": dict(hidden_size=4096, n_head=32, n_layer=30),
    "176b": dict(hidden_size=14336, n_head=112, n_layer=70),
}


def get_bloom_config(name: str, **overrides) -> BloomConfig:
    return config_from(BLOOM_CONFIGS, BloomConfig, name, **overrides)


def alibi_slopes(n_head: int) -> jnp.ndarray:
    """Per-head alibi slopes (the HF/paper construction: powers of
    2^(-8/n) for the nearest power-of-two head count, interleaved extras
    for non-power-of-two)."""
    def pow2_slopes(n):
        start = 2.0 ** (-(2.0 ** -(math.log2(n) - 3)))
        return [start * (start ** i) for i in range(n)]

    if math.log2(n_head).is_integer():
        slopes = pow2_slopes(n_head)
    else:
        closest = 2 ** math.floor(math.log2(n_head))
        slopes = pow2_slopes(closest)
        extra = pow2_slopes(2 * closest)
        slopes += extra[0::2][:n_head - closest]
    return jnp.asarray(slopes, jnp.float32)


def alibi_bias(n_head: int, kv_len: int) -> jnp.ndarray:
    """[1, H, 1, Lk] additive logit bias: slope[h] * k_pos. Broadcasts over
    batch and query positions; per-row shift-equal to the relative form."""
    slopes = alibi_slopes(n_head)
    return (slopes[:, None] * jnp.arange(kv_len, dtype=jnp.float32)[None, :])[None, :, None, :]


class BloomAttention(nn.Module):
    config: BloomConfig
    decode: bool = False

    @nn.compact
    def __call__(self, x):
        cfg = self.config
        b, l, _ = x.shape
        qkv = nn.DenseGeneral(features=(cfg.n_head, 3, cfg.head_dim), axis=-1,
                              dtype=cfg.dtype, param_dtype=cfg.param_dtype,
                              kernel_init=nn.with_logical_partitioning(
                                  _init(), ("embed", "heads", None, "kv")),
                              bias_init=nn.with_logical_partitioning(
                                  nn.initializers.zeros, ("heads", None, "kv")),
                              name="query_key_value")(x)
        q, k, v = qkv[..., 0, :], qkv[..., 1, :], qkv[..., 2, :]
        causal, decode_lengths = True, None
        if self.decode:
            shape = (b, cfg.max_position_embeddings, cfg.n_head, cfg.head_dim)
            cached_k = self.variable("cache", "cached_key", jnp.zeros, shape, k.dtype)
            cached_v = self.variable("cache", "cached_value", jnp.zeros, shape, v.dtype)
            cache_index = self.variable("cache", "cache_index", lambda: jnp.zeros([], jnp.int32))
            idx = cache_index.value
            cached_k.value = jax.lax.dynamic_update_slice(cached_k.value, k, (0, idx, 0, 0))
            cached_v.value = jax.lax.dynamic_update_slice(cached_v.value, v, (0, idx, 0, 0))
            cache_index.value = idx + l
            k, v = cached_k.value, cached_v.value
            decode_lengths = jnp.broadcast_to(idx + l, (b,))
            causal = False
        bias = alibi_bias(cfg.n_head, k.shape[1])
        out = dot_product_attention(q, k, v, backend=cfg.attention_backend,
                                    causal=causal, bias=bias,
                                    decode_lengths=decode_lengths)
        return nn.DenseGeneral(features=cfg.hidden_size, axis=(-2, -1),
                               dtype=cfg.dtype, param_dtype=cfg.param_dtype,
                               kernel_init=nn.with_logical_partitioning(_init(), ("heads", "kv", "embed")),
                               bias_init=nn.with_logical_partitioning(nn.initializers.zeros, ("embed",)),
                               name="dense")(out)


class BloomBlock(nn.Module):
    config: BloomConfig
    decode: bool = False

    @nn.compact
    def __call__(self, x):
        cfg = self.config
        ln = lambda name: nn.LayerNorm(epsilon=cfg.layer_norm_epsilon, dtype=cfg.dtype,
                                       param_dtype=cfg.param_dtype, name=name)
        x = x + BloomAttention(cfg, self.decode, name="self_attention")(
            ln("input_layernorm")(x))
        h = ln("post_attention_layernorm")(x)
        h = nn.Dense(features=4 * cfg.hidden_size, dtype=cfg.dtype, param_dtype=cfg.param_dtype,
                     kernel_init=nn.with_logical_partitioning(_init(), ("embed", "mlp")),
                     bias_init=nn.with_logical_partitioning(nn.initializers.zeros, ("mlp",)),
                     name="dense_h_to_4h")(h)
        h = jax.nn.gelu(h, approximate=True)  # HF Bloom uses tanh-approx gelu
        h = nn.Dense(features=cfg.hidden_size, dtype=cfg.dtype, param_dtype=cfg.param_dtype,
                     kernel_init=nn.with_logical_partitioning(_init(), ("mlp", "embed")),
                     bias_init=nn.with_logical_partitioning(nn.initializers.zeros, ("embed",)),
                     name="dense_4h_to_h")(h)
        return x + h


class BloomForCausalLM(nn.Module):
    """BLOOM with tied word-embedding head and embedding layernorm."""

    # offload_param streaming: these block subtrees self-stream inside
    # their remat region (param_offload.stream_block_params); the engine
    # top-streams only the remaining leaves
    streamed_block_prefixes = ("h_",)


    config: BloomConfig

    @nn.compact
    def __call__(self, input_ids, *, deterministic: bool = True, decode: bool = False,
                 labels=None):
        cfg = self.config
        wte = self.param("word_embeddings", nn.with_logical_partitioning(_init(), ("vocab", "embed")),
                         (cfg.vocab_size, cfg.hidden_size), cfg.param_dtype)
        wte_v = wte.value if isinstance(wte, nn.meta.AxisMetadata) else wte
        from deepspeed_tpu.models.common import embed_lookup
        x = embed_lookup(wte_v, input_ids,
                         getattr(cfg, 'embed_onehot_grad', None), decode).astype(cfg.dtype)
        x = nn.LayerNorm(epsilon=cfg.layer_norm_epsilon, dtype=cfg.dtype,
                         param_dtype=cfg.param_dtype, name="word_embeddings_layernorm")(x)
        from deepspeed_tpu.runtime.zero.param_offload import stream_block_params
        block_cls = stream_block_params(BloomBlock)
        if cfg.remat:
            block_cls = nn.remat(block_cls, prevent_cse=False)
        from deepspeed_tpu.models.common import constrain_activation
        # batch-parallel residual stream over fsdp-sharded weights — see
        # constrain_activation (the ZeRO-3 weak-scaling invariant)
        x = constrain_activation(x, "batch", "length", "embed")
        for i in range(cfg.n_layer):
            x = block_cls(cfg, decode, name=f"h_{i}")(x)
            x = constrain_activation(x, "batch", "length", "embed")
        x = nn.LayerNorm(epsilon=cfg.layer_norm_epsilon, dtype=cfg.dtype,
                         param_dtype=cfg.param_dtype, name="ln_f")(x)
        if labels is not None and cfg.fused_head_loss_chunk > 0:
            from deepspeed_tpu.models.common import fused_head_loss_output
            return fused_head_loss_output(x, wte_v.astype(cfg.dtype), labels,
                                          0.0, deterministic, cfg, vocab_major=True)
        return jnp.einsum("ble,ve->blv", x, wte_v.astype(cfg.dtype),
                          preferred_element_type=cfg.dtype)
