"""CLIP text encoder — the conditioning tower of the reference's diffusers
serving path (``module_inject/containers/clip.py``,
``model_implementations/transformers/clip_encoder.py``).

CLIP quirks kept for checkpoint parity: CAUSAL attention in the text
encoder (despite being an "encoder"), quick-gelu (``x * sigmoid(1.702x)``),
pre-LN blocks with biased q/k/v/out projections, learned positions, final
LayerNorm, and an EOS-token pooled output (first ``eos_token_id``
occurrence when configured, else HF's legacy raw-argmax-of-ids pooling).
"""

import dataclasses
from typing import Any

import flax.linen as nn
import jax
import jax.numpy as jnp

from deepspeed_tpu.models.common import config_from, dense_init as _init
from deepspeed_tpu.ops.transformer.attention import dot_product_attention


@dataclasses.dataclass(frozen=True)
class CLIPTextConfig:
    vocab_size: int = 49408
    hidden_size: int = 512
    intermediate_size: int = 2048
    num_hidden_layers: int = 12
    num_attention_heads: int = 8
    max_position_embeddings: int = 77
    # pooled-output position: None → argmax of input_ids (HF's legacy
    # eos_token_id==2 path); an int → FIRST occurrence of that token id
    # (HF's current path — CLIP checkpoints ship eos_token_id=49407)
    eos_token_id: Any = None
    layer_norm_eps: float = 1e-5
    # "quick_gelu" (original OpenAI CLIP) or "gelu" (exact erf —
    # OpenCLIP-lineage towers, e.g. SD-2.x / ViT-H); converters validate
    hidden_act: str = "quick_gelu"
    dtype: Any = jnp.float32
    param_dtype: Any = jnp.float32
    remat: bool = False
    remat_every: int = 1
    remat_policy: Any = None
    attention_backend: str = "xla"

    @property
    def head_dim(self):
        return self.hidden_size // self.num_attention_heads


CLIP_TEXT_CONFIGS = {
    "test": dict(vocab_size=256, hidden_size=64, intermediate_size=128, num_hidden_layers=2,
                 num_attention_heads=4, max_position_embeddings=32),
    # openai/clip-vit-base-patch32 text tower
    "base": dict(hidden_size=512, intermediate_size=2048, num_hidden_layers=12,
                 num_attention_heads=8, eos_token_id=49407),
    # openai/clip-vit-large-patch14 text tower (stable-diffusion v1 conditioning)
    "large": dict(hidden_size=768, intermediate_size=3072, num_hidden_layers=12,
                  num_attention_heads=12, eos_token_id=49407),
}


def get_clip_text_config(name: str, **overrides) -> CLIPTextConfig:
    return config_from(CLIP_TEXT_CONFIGS, CLIPTextConfig, name, **overrides)


def quick_gelu(x):
    return x * jax.nn.sigmoid(1.702 * x)


def _activation(cfg: CLIPTextConfig, h):
    if cfg.hidden_act == "quick_gelu":
        return quick_gelu(h)
    if cfg.hidden_act == "gelu":
        return jax.nn.gelu(h, approximate=False)
    raise ValueError(f"unknown hidden_act {cfg.hidden_act!r}; "
                     f"choose from ['quick_gelu', 'gelu']")


class CLIPEncoderLayer(nn.Module):
    config: CLIPTextConfig

    @nn.compact
    def __call__(self, x):
        cfg = self.config

        def proj(name):
            return nn.DenseGeneral(features=(cfg.num_attention_heads, cfg.head_dim), axis=-1,
                                   dtype=cfg.dtype, param_dtype=cfg.param_dtype,
                                   kernel_init=nn.with_logical_partitioning(
                                       _init(), ("embed", "heads", "kv")),
                                   bias_init=nn.with_logical_partitioning(
                                       nn.initializers.zeros, ("heads", "kv")),
                                   name=name)

        ln = lambda name: nn.LayerNorm(epsilon=cfg.layer_norm_eps, dtype=cfg.dtype,
                                       param_dtype=cfg.param_dtype, name=name)
        h = ln("layer_norm1")(x)
        q, k, v = proj("q_proj")(h), proj("k_proj")(h), proj("v_proj")(h)
        # text tower attends causally (HF CLIPTextTransformer builds a
        # causal mask even though the module is named an encoder)
        attn = dot_product_attention(q, k, v, backend=cfg.attention_backend, causal=True)
        attn = nn.DenseGeneral(features=cfg.hidden_size, axis=(-2, -1), dtype=cfg.dtype,
                               param_dtype=cfg.param_dtype,
                               kernel_init=nn.with_logical_partitioning(_init(), ("heads", "kv", "embed")),
                               bias_init=nn.with_logical_partitioning(nn.initializers.zeros, ("embed",)),
                               name="out_proj")(attn)
        x = x + attn
        h = ln("layer_norm2")(x)
        h = nn.Dense(features=cfg.intermediate_size, dtype=cfg.dtype, param_dtype=cfg.param_dtype,
                     kernel_init=nn.with_logical_partitioning(_init(), ("embed", "mlp")),
                     bias_init=nn.with_logical_partitioning(nn.initializers.zeros, ("mlp",)),
                     name="fc1")(h)
        h = _activation(cfg, h)
        h = nn.Dense(features=cfg.hidden_size, dtype=cfg.dtype, param_dtype=cfg.param_dtype,
                     kernel_init=nn.with_logical_partitioning(_init(), ("mlp", "embed")),
                     bias_init=nn.with_logical_partitioning(nn.initializers.zeros, ("embed",)),
                     name="fc2")(h)
        return x + h


class CLIPTextModel(nn.Module):
    """Text tower: returns (last_hidden_state [B, L, E], pooled [B, E])."""

    # offload_param streaming: these block subtrees self-stream inside
    # their remat region (param_offload.stream_block_params); the engine
    # top-streams only the remaining leaves
    streamed_block_prefixes = ("layers_",)


    config: CLIPTextConfig

    @nn.compact
    def __call__(self, input_ids, *, deterministic: bool = True):
        cfg = self.config
        tok = self.param("token_embedding", nn.with_logical_partitioning(_init(), ("vocab", "embed")),
                         (cfg.vocab_size, cfg.hidden_size), cfg.param_dtype)
        pos = self.param("position_embedding", nn.with_logical_partitioning(_init(0.01), (None, "embed")),
                         (cfg.max_position_embeddings, cfg.hidden_size), cfg.param_dtype)
        tok = tok.value if isinstance(tok, nn.meta.AxisMetadata) else tok
        pos = pos.value if isinstance(pos, nn.meta.AxisMetadata) else pos
        b, l = input_ids.shape
        from deepspeed_tpu.models.common import embed_lookup
        x = (embed_lookup(tok, input_ids, getattr(cfg, 'embed_onehot_grad', None))
             + pos[None, :l]).astype(cfg.dtype)
        from deepspeed_tpu.models.common import constrain_activation, maybe_remat
        # batch-parallel residual stream over fsdp-sharded weights — see
        # constrain_activation (the ZeRO-3 weak-scaling invariant)
        x = constrain_activation(x, "batch", "length", "embed")
        for i in range(cfg.num_hidden_layers):
            layer_cls = maybe_remat(CLIPEncoderLayer, cfg, i)
            x = layer_cls(cfg, name=f"layers_{i}")(x)
            x = constrain_activation(x, "batch", "length", "embed")
        x = nn.LayerNorm(epsilon=cfg.layer_norm_eps, dtype=cfg.dtype,
                         param_dtype=cfg.param_dtype, name="final_layer_norm")(x)
        # pooled = hidden state at the EOS token: first occurrence of
        # eos_token_id when configured (HF current semantics), else argmax
        # of ids (HF legacy eos_token_id==2 semantics — EOS is the highest
        # id in the original CLIP vocabulary)
        if cfg.eos_token_id is not None:
            eos_idx = jnp.argmax((input_ids == cfg.eos_token_id).astype(jnp.int32), axis=-1)
        else:
            eos_idx = jnp.argmax(input_ids, axis=-1)
        pooled = jnp.take_along_axis(x, eos_idx[:, None, None].astype(jnp.int32), axis=1)[:, 0]
        return x, pooled
