"""Shared model-zoo helpers."""

import flax.linen as nn


def dense_init(scale: float = 0.02):
    return nn.initializers.normal(stddev=scale)


def config_from(table: dict, cls, name: str, **overrides):
    """Look up a named config dict and build ``cls`` with overrides."""
    base = dict(table[name])
    base.update(overrides)
    return cls(**base)


def normalize_padding_mask(attention_mask, ndim_target: int = 4):
    """[B, L] 0/1 padding mask → [B, 1, 1, L] boolean; pass through masks
    that already have a broadcastable rank."""
    if attention_mask is None:
        return None
    if attention_mask.ndim == 2:
        return attention_mask[:, None, None, :].astype(bool)
    return attention_mask.astype(bool)
