"""Shared model-zoo helpers."""

import functools
from typing import Any

import jax
import jax.numpy as jnp

import flax.linen as nn


def is_seq2seq_module(model: nn.Module) -> bool:
    """True when the module's __call__ takes decoder_input_ids (encoder-
    decoder models such as T5) — shared probe for init_cache and the
    inference engine so the two can never disagree."""
    import inspect
    try:
        return "decoder_input_ids" in inspect.signature(type(model).__call__).parameters
    except (TypeError, ValueError):
        return False


def init_cache(model: nn.Module, batch_size: int, rng=None):
    """Build a zeroed decode cache for any model supporting ``decode=True``
    (the reference's ``allocate_workspace`` KV-cache setup,
    ``csrc/transformer/inference/csrc/pt_binding.cpp:1928``).

    Uses ``eval_shape`` so no compute runs and the cache index starts at 0
    (``model.init(decode=True)`` would advance it by tracing the call body).
    """
    ids = jnp.zeros((batch_size, 1), jnp.int32)
    rng = rng if rng is not None else jax.random.PRNGKey(0)
    kwargs = {"decoder_input_ids": ids} if is_seq2seq_module(model) else {}
    shapes = jax.eval_shape(lambda: model.init(rng, ids, decode=True, **kwargs))
    return jax.tree.map(lambda s: jnp.zeros(s.shape, s.dtype), shapes["cache"])


def dense_init(scale: float = 0.02):
    return nn.initializers.normal(stddev=scale)


import contextlib
import contextvars

_constraints_disabled = contextvars.ContextVar("ds_activation_constraints_disabled",
                                               default=False)


@contextlib.contextmanager
def activation_constraints_disabled():
    """Disable ``constrain_activation`` while tracing code that runs inside
    a manual ``shard_map`` body (qcomm / 1-bit collectives): per-shard code
    already IS the sharding, and ``nn.remat`` hides the surrounding mesh
    context so the constraint cannot reliably self-detect manual axes."""
    token = _constraints_disabled.set(True)
    try:
        yield
    finally:
        _constraints_disabled.reset(token)


def constrain_activation(x, *logical_names: str):
    """Pin an activation's sharding by logical axis names (t5x-style).

    Without activation constraints GSPMD is free to re-shard the forward
    however its cost model likes; on fsdp-sharded (ZeRO-3) weights it can
    settle on replicated-batch compute with per-layer contraction
    all-reduces — per-chip wire bytes then GROW with the mesh instead of
    staying flat (the reference never faces this choice: its DP ranks
    replicate compute by construction and its partitioning is imperative,
    ``stage3.py:1099``). Constraining the residual stream to
    ``("batch", "length", ...)`` makes the batch-parallel strategy the
    only consistent one, so weights get all-gathered (flat per-chip
    payload) — the ZeRO-3 weak-scaling invariant.

    No-op when no topology is set, on a trivial mesh, or when the mesh's
    axes are manual (inside ``shard_map`` bodies, e.g. the pipeline
    engine's stage loop)."""
    from jax.sharding import NamedSharding

    from deepspeed_tpu.parallel.sharding import logical_to_mesh_spec
    from deepspeed_tpu.parallel.topology import get_topology

    if _constraints_disabled.get():
        return x
    topo = get_topology()
    if topo is None:
        return x
    mesh = topo.mesh
    if mesh.size == 1:
        return x
    try:
        # inside shard_map bodies the mesh axes are Manual — per-shard code
        # already IS the sharding; a constraint there breaks lowering.
        # (Paths that remat the model inside shard_map additionally trace
        # under activation_constraints_disabled(): remat hides this mesh
        # context, see qcomm.py/zeroone.py.)
        if any(t == jax.sharding.AxisType.Manual for t in getattr(
                jax.sharding.get_abstract_mesh(), "axis_types", ())):
            return x
    except Exception:
        pass  # probe failed: proceed to constrain — the constraint is the
        # load-bearing part (weak scaling), the probe is the edge case
    spec = logical_to_mesh_spec(logical_names)
    try:
        return jax.lax.with_sharding_constraint(x, NamedSharding(mesh, spec))
    except ValueError:
        # rank mismatch or incompatible mesh: leave unconstrained
        return x


def maybe_remat(block_cls, cfg, layer_idx: int, static_argnums=(), enabled=None):
    """Zoo-shared selective activation checkpointing: wrap ``block_cls`` in
    ``jax.checkpoint`` (with the config's ``remat_policy``) when remat is on
    and ``layer_idx`` hits the ``remat_every`` stride; otherwise return the
    class unchanged. ``enabled`` overrides ``cfg.remat`` for callers with
    extra conditions (e.g. llama skips remat during decode).

    Every block additionally passes through ``stream_block_params`` — a
    no-op unless a ZeRO-Infinity ``offload_param`` engine is tracing, in
    which case the block's params are h2d-streamed *inside* the remat
    region so backward re-streams per layer instead of holding every
    layer's device copy from forward to backward (reference param
    coordinator re-fetch, ``partitioned_param_coordinator.py:479``)."""
    from deepspeed_tpu.runtime.zero.param_offload import stream_block_params
    block_cls = stream_block_params(block_cls)
    enabled = getattr(cfg, "remat", False) if enabled is None else enabled
    if not enabled or layer_idx % max(getattr(cfg, "remat_every", 1), 1) != 0:
        return block_cls
    from deepspeed_tpu.runtime.activation_checkpointing.checkpointing import get_remat_policy
    return nn.remat(block_cls, static_argnums=static_argnums, prevent_cse=False,
                    policy=get_remat_policy(getattr(cfg, "remat_policy", None)))


def pld_gate(module: nn.Module, branch, keep):
    """Zoo-shared Switchable-Transformer gate (PLD, arXiv:2010.13369 §3):
    keep the sublayer output with probability ``keep`` and rescale by
    1/keep so expectations match; a dropped sublayer contributes nothing.
    Returns ``(gated_branch, keep_decision)`` — the decision lets callers
    gate side outputs (e.g. a dropped MoE layer's router aux loss). The
    FLOPs are still spent under jit; the TPU benefit is regularization
    parity, which is why the engine anneals theta in-graph instead of
    re-tracing."""
    if keep is None:
        return branch, None
    b = jax.random.bernoulli(module.make_rng("pld"), keep)
    return jnp.where(b, branch / keep, jnp.zeros_like(branch)), b


def rms_norm(x, weight, eps: float, out_dtype):
    """Shared RMS-norm core (LLaMA RMSNorm, T5 LayerNorm): fp32 accumulate,
    scale, cast back."""
    x32 = x.astype(jnp.float32)
    var = jnp.mean(jnp.square(x32), axis=-1, keepdims=True)
    return (x32 * jax.lax.rsqrt(var + eps) * weight.astype(jnp.float32)).astype(out_dtype)


_ONEHOT_CHUNK = 1024  # tokens per backward chunk — bounds the one-hot buffer


@functools.lru_cache(maxsize=None)
def _onehot_embed_fn(vocab: int, dtype_name: str):
    @jax.custom_vjp
    def f(wte, ids):
        return jnp.take(wte, ids, axis=0)

    def fwd(wte, ids):
        return jnp.take(wte, ids, axis=0), ids

    def bwd(ids, g):
        # chunk the token axis: a single-shot one_hot is [T, V] in the grad
        # dtype (~824 MB at T=4k, V=50k, fp32); scanning T in chunks of
        # _ONEHOT_CHUNK with a bf16 one-hot (fp32 accumulation via
        # preferred_element_type) bounds the buffer to a few tens of MB
        ids_f = ids.reshape(-1)
        g_f = g.reshape(-1, g.shape[-1]).astype(jnp.float32)
        t = ids_f.shape[0]
        ch = _ONEHOT_CHUNK
        if t <= ch:
            onehot = jax.nn.one_hot(ids_f, vocab, dtype=jnp.bfloat16)
            gw = jax.lax.dot_general(onehot, g_f, (((0,), (0,)), ((), ())),
                                     preferred_element_type=jnp.float32)
        else:
            # pad to a chunk multiple — padded rows carry zero cotangent so
            # they contribute nothing, and the memory bound holds for EVERY
            # shape (a full-T fallback would reintroduce the [T, V] spike)
            pad = (-t) % ch
            if pad:
                ids_f = jnp.concatenate([ids_f, jnp.zeros((pad,), ids_f.dtype)])
                g_f = jnp.concatenate([g_f, jnp.zeros((pad, g_f.shape[-1]), g_f.dtype)])
            def body(acc, xs):
                i_c, g_c = xs
                oh = jax.nn.one_hot(i_c, vocab, dtype=jnp.bfloat16)
                return acc + jax.lax.dot_general(oh, g_c, (((0,), (0,)), ((), ())),
                                                 preferred_element_type=jnp.float32), None

            gw, _ = jax.lax.scan(body, jnp.zeros((vocab, g_f.shape[-1]), jnp.float32),
                                 (ids_f.reshape(-1, ch), g_f.reshape(-1, ch, g_f.shape[-1])))
        return gw.astype(dtype_name), None

    f.defvjp(fwd, bwd)
    return f


def take_embed_onehot_grad(wte, ids):
    """Embedding lookup whose BACKWARD is a one-hot matmul instead of a
    scatter-add. TPU scatter lowers to a serialized per-index update; the
    [T, V] x [T, E] matmul form rides the MXU (the standard TPU trick —
    costs ~V*T*E extra FLOPs, usually a small fraction of a transformer
    step). Forward is a plain gather either way."""
    return _onehot_embed_fn(int(wte.shape[0]), jnp.dtype(wte.dtype).name)(wte, ids)


def lookup_table_view(table):
    """A gather-friendly view of an embedding table on tensor/sequence
    meshes.

    With the vocab dim sharded over ``tensor`` (logical rules), GSPMD
    partitions ``take`` by psum-ing partial gathers and leaves the output
    embed-sharded; the residual-stream constraint then needs a transition
    the partitioner cannot produce — it replicates the whole activation
    ("Involuntary full rematerialization", ``spmd_partitioner.cc:652``;
    MULTICHIP_r03 tail). Pinning the TABLE un-sharded for the lookup moves
    the reshard onto the parameter (an ordinary all-gather — exactly the
    ZeRO-3 gather-on-use) so the gather emits (batch, length, embed)
    directly. Skipped on tensor=sequence=1 meshes, where the default
    strategy is already transition-free and the extra constraint would
    pin the ZeRO-3 table gather into a fixed materialization."""
    from deepspeed_tpu.parallel.topology import get_topology
    topo = get_topology()
    if topo is None or (topo.tensor_parallel_size <= 1
                        and topo.sequence_parallel_size <= 1):
        return table
    return constrain_activation(table, None, None)


def embed_lookup(wte, ids, onehot_grad=None, decode: bool = False):
    """Token-embedding gather, shared across the model zoo.

    ``onehot_grad`` (None = policy default, on): backward as a one-hot einsum instead of a
    scatter-add — MXU-friendly and cleanly partitionable (the scatter's
    batch→embed update reshard is a GSPMD involuntary-remat source).
    ``decode``: per-token serving step — skip the table reshard
    (:func:`lookup_table_view`); a whole-table all-gather per generated
    token would dwarf the [B,1,E] gather it optimizes, and the decode
    gather's output transition is negligible at one token."""
    if onehot_grad is None:
        onehot_grad = True  # the one policy site; callers pass getattr(cfg, ..., None)
    if not decode:
        wte = lookup_table_view(wte)
    if onehot_grad and not decode:
        return take_embed_onehot_grad(wte, ids)
    return jnp.take(wte, ids, axis=0)


def config_from(table: dict, cls, name: str, **overrides):
    """Look up a named config dict and build ``cls`` with overrides."""
    base = dict(table[name])
    base.update(overrides)
    return cls(**base)


def attention_geometry_kwargs(cfg):
    """Per-model flash-attention geometry overrides, zoo-shared.

    ``cfg.attention_blocks`` is a spec string (the grammar of
    ``ops/pallas/attention_geometry.parse_spec``, e.g.
    ``"block_q=256,block_k=512,policy=recompute"`` — a string so frozen
    model configs stay hashable). Returns ``dot_product_attention`` kwargs
    for the flash backend, ``{}`` otherwise: the XLA/ring backends have no
    block geometry and must not receive the kwargs. Passed as
    ``geometry_spec`` (not direct block kwargs) so the pinned blocks CLAMP
    to each call shape's divisors instead of knocking untileable shapes
    off the kernel; unset fields still resolve through the engine config /
    env / autotune-cache layers inside the kernel."""
    spec = getattr(cfg, "attention_blocks", None)
    if not spec or getattr(cfg, "attention_backend", "xla") != "flash":
        return {}
    return {"geometry_spec": spec}


def normalize_padding_mask(attention_mask, ndim_target: int = 4):
    """[B, L] 0/1 padding mask → [B, 1, 1, L] boolean; pass through masks
    that already have a broadcastable rank."""
    if attention_mask is None:
        return None
    if attention_mask.ndim == 2:
        return attention_mask[:, None, None, :].astype(bool)
    return attention_mask.astype(bool)


@functools.lru_cache(maxsize=None)
def _fused_lm_head_loss_fn(vocab: int, x_dtype_name: str, w_dtype_name: str,
                           chunk: int, ignore_index: int, vocab_major: bool,
                           has_bias: bool = False):
    """Chunked LM-head + cross-entropy with a custom VJP.

    Computes mean next-token NLL from HIDDEN STATES without ever
    materializing the [B, T, V] logits (the largest allocation of a
    causal-LM train step: 2 x 1.5 GiB at mb16/seq1024/GPT-2 vocab, and far
    worse for 32k-152k-vocab families). Token chunks of size ``chunk``
    stream through a lax.scan: forward keeps only per-token lse / label
    logits; backward recomputes each chunk's logits and feeds the
    (softmax - onehot) cotangent straight into the two matmuls.

    Math matches ``models.gpt2.cross_entropy_loss`` applied to
    ``einsum('bte,ve->btv', x, W)``: logits at the compute dtype, fp32
    reductions (sub-ulp reduction-order differences only). Replaces the
    reference's fused softmax-xent CUDA path the TPU way — XLA fuses each
    chunk's convert/exp/mask into the matmuls, no hand-written kernel
    needed.
    """
    x_dtype = jnp.dtype(x_dtype_name)

    def _chunks(arr, c):
        return arr.reshape((-1, c) + arr.shape[1:])

    def _pad_tokens(x_f, lab_f):
        n = x_f.shape[0]
        pad = (-n) % chunk
        if pad:
            x_f = jnp.concatenate([x_f, jnp.zeros((pad, x_f.shape[1]), x_f.dtype)])
            lab_f = jnp.concatenate(
                [lab_f, jnp.full((pad,), ignore_index, lab_f.dtype)])
        return x_f, lab_f

    # weight layout: [V, E] (tied embedding, GPT-2) or [E, V] (untied
    # Dense head, LLaMA) — contraction dims differ, no transpose copies
    w_contract = (1,) if vocab_major else (0,)

    def _chunk_logits(x_c, w, bias):
        out = jax.lax.dot_general(x_c, w, (((1,), w_contract), ((), ())),
                                  preferred_element_type=x_dtype)  # [C, V]
        return out + bias if has_bias else out

    @jax.custom_vjp
    def f(x, w, bias, labels):
        out, _ = fwd(x, w, bias, labels)
        return out

    def fwd(x, w, bias, labels):
        b, t, e = x.shape
        x_f, lab_f = _pad_tokens(x.reshape(-1, e), labels.reshape(-1))
        valid_all = lab_f != ignore_index
        denom = jnp.maximum(jnp.sum(valid_all), 1).astype(jnp.float32)

        def body(acc, xs):
            x_c, lab_c = xs
            logits = _chunk_logits(x_c, w, bias)
            valid = lab_c != ignore_index
            safe = jnp.where(valid, lab_c, 0)
            logz = jax.nn.logsumexp(logits.astype(jnp.float32), axis=-1)
            ll = jnp.take_along_axis(logits, safe[:, None], axis=-1)[:, 0]
            nll = (logz - ll.astype(jnp.float32)) * valid
            return acc + nll.sum(), None

        total, _ = jax.lax.scan(body, jnp.zeros([], jnp.float32),
                                (_chunks(x_f, chunk), _chunks(lab_f, chunk)))
        return total / denom, (x, w, bias, labels, denom)

    def bwd(res, g):
        x, w, bias, labels, denom = res
        b, t, e = x.shape
        x_f, lab_f = _pad_tokens(x.reshape(-1, e), labels.reshape(-1))
        scale = g / denom

        def body(carry, xs):
            dw_acc, db_acc = carry
            x_c, lab_c = xs
            logits = _chunk_logits(x_c, w, bias)
            valid = lab_c != ignore_index
            safe = jnp.where(valid, lab_c, 0)
            p = jax.nn.softmax(logits.astype(jnp.float32), axis=-1)
            coeff32 = p - jax.nn.one_hot(safe, vocab, dtype=jnp.float32)
            coeff32 = coeff32 * (valid * scale)[:, None]  # [C, V]
            coeff = coeff32.astype(x_dtype)
            dx_c = jax.lax.dot_general(
                coeff, w, (((1,), (0,) if vocab_major else (1,)), ((), ())),
                preferred_element_type=jnp.float32)
            if vocab_major:
                dw_c = jax.lax.dot_general(coeff, x_c, (((0,), (0,)), ((), ())),
                                           preferred_element_type=jnp.float32)
            else:
                dw_c = jax.lax.dot_general(x_c, coeff, (((0,), (0,)), ((), ())),
                                           preferred_element_type=jnp.float32)
            db_acc = db_acc + coeff32.sum(0) if has_bias else db_acc
            return (dw_acc + dw_c, db_acc), dx_c.astype(x.dtype)

        dw_shape = (vocab, e) if vocab_major else (e, vocab)
        db0 = jnp.zeros((vocab,), jnp.float32) if has_bias else jnp.zeros([], jnp.float32)
        (dw, db), dx_chunks = jax.lax.scan(
            body, (jnp.zeros(dw_shape, jnp.float32), db0),
            (_chunks(x_f, chunk), _chunks(lab_f, chunk)))
        dx = dx_chunks.reshape(-1, e)[:b * t].reshape(b, t, e)
        db_out = db.astype(jnp.dtype(w_dtype_name)) if has_bias else None
        return dx, dw.astype(jnp.dtype(w_dtype_name)), db_out, None

    f.defvjp(fwd, bwd)
    return f


def fused_lm_head_loss(x, embedding, labels, *, bias=None, chunk: int = 1024,
                       ignore_index: int = -100, vocab_major: bool = True):
    """Mean next-token cross-entropy straight from hidden states.

    ``x``: [B, T, E] hidden states (already shifted — token t predicts
    ``labels[:, t]``); ``embedding``: the LM head at the compute dtype —
    [V, E] tied embedding (``vocab_major=True``, GPT-2) or [E, V] untied
    Dense kernel (``vocab_major=False``, LLaMA); ``bias``: optional [V]
    head bias at the compute dtype (GPT-J), added per chunk with its grad
    accumulated in the backward scan; ``labels``: [B, T] int with
    ``ignore_index`` masking. See ``_fused_lm_head_loss_fn`` for the
    memory story.
    """
    vocab = int(embedding.shape[0] if vocab_major else embedding.shape[1])
    fn = _fused_lm_head_loss_fn(vocab,
                                jnp.dtype(x.dtype).name,
                                jnp.dtype(embedding.dtype).name,
                                int(chunk), int(ignore_index), bool(vocab_major),
                                bias is not None)
    return fn(x, embedding, bias, labels)


def fused_head_loss_output(x, weight, labels, aux_total, deterministic, cfg, *,
                           vocab_major: bool, bias=None):
    """Shared fused-head dispatch for causal-LM model families: applies the
    next-token shift, runs :func:`fused_lm_head_loss`, and adds the MoE aux
    loss in training only (eval reports pure CE, matching the engine's
    unfused eval branch). Keeping the shift convention and aux policy here
    means every family adopting ``fused_head_loss_chunk`` stays in
    lockstep."""
    loss = fused_lm_head_loss(x[:, :-1], weight, labels[:, 1:], bias=bias,
                              chunk=cfg.fused_head_loss_chunk,
                              vocab_major=vocab_major)
    if getattr(cfg, "moe_num_experts", 0) > 0 and not deterministic:
        loss = loss + aux_total * cfg.moe_aux_loss_coef
    return loss


class UntiedHeadKernel(nn.Module):
    """Declares an untied LM-head kernel at the same param path as
    ``nn.Dense(name=<name>)`` ([E, V], same init/partitioning) so a fused-
    loss branch shares weights with the logits branch (used by LLaMA's
    ``lm_head`` and GPT-NeoX's ``embed_out``). With ``use_bias`` it also
    declares the Dense-compatible bias and returns ``(kernel, bias)``
    (GPT-J's biased head)."""

    in_features: int
    out_features: int
    param_dtype: Any = jnp.float32
    use_bias: bool = False

    @nn.compact
    def __call__(self):
        unbox = lambda p: p.value if isinstance(p, nn.meta.AxisMetadata) else p
        kernel = unbox(self.param(
            "kernel", nn.with_logical_partitioning(dense_init(), ("embed", "vocab")),
            (self.in_features, self.out_features), self.param_dtype))
        if not self.use_bias:
            return kernel
        bias = unbox(self.param(
            "bias", nn.with_logical_partitioning(nn.initializers.zeros, ("vocab",)),
            (self.out_features,), self.param_dtype))
        return kernel, bias
