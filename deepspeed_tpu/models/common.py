"""Shared model-zoo helpers."""

import functools

import jax
import jax.numpy as jnp

import flax.linen as nn


def is_seq2seq_module(model: nn.Module) -> bool:
    """True when the module's __call__ takes decoder_input_ids (encoder-
    decoder models such as T5) — shared probe for init_cache and the
    inference engine so the two can never disagree."""
    import inspect
    try:
        return "decoder_input_ids" in inspect.signature(type(model).__call__).parameters
    except (TypeError, ValueError):
        return False


def init_cache(model: nn.Module, batch_size: int, rng=None):
    """Build a zeroed decode cache for any model supporting ``decode=True``
    (the reference's ``allocate_workspace`` KV-cache setup,
    ``csrc/transformer/inference/csrc/pt_binding.cpp:1928``).

    Uses ``eval_shape`` so no compute runs and the cache index starts at 0
    (``model.init(decode=True)`` would advance it by tracing the call body).
    """
    ids = jnp.zeros((batch_size, 1), jnp.int32)
    rng = rng if rng is not None else jax.random.PRNGKey(0)
    kwargs = {"decoder_input_ids": ids} if is_seq2seq_module(model) else {}
    shapes = jax.eval_shape(lambda: model.init(rng, ids, decode=True, **kwargs))
    return jax.tree.map(lambda s: jnp.zeros(s.shape, s.dtype), shapes["cache"])


def dense_init(scale: float = 0.02):
    return nn.initializers.normal(stddev=scale)


def maybe_remat(block_cls, cfg, layer_idx: int, static_argnums=(), enabled=None):
    """Zoo-shared selective activation checkpointing: wrap ``block_cls`` in
    ``jax.checkpoint`` (with the config's ``remat_policy``) when remat is on
    and ``layer_idx`` hits the ``remat_every`` stride; otherwise return the
    class unchanged. ``enabled`` overrides ``cfg.remat`` for callers with
    extra conditions (e.g. llama skips remat during decode)."""
    enabled = getattr(cfg, "remat", False) if enabled is None else enabled
    if not enabled or layer_idx % max(getattr(cfg, "remat_every", 1), 1) != 0:
        return block_cls
    from deepspeed_tpu.runtime.activation_checkpointing.checkpointing import get_remat_policy
    return nn.remat(block_cls, static_argnums=static_argnums, prevent_cse=False,
                    policy=get_remat_policy(getattr(cfg, "remat_policy", None)))


def rms_norm(x, weight, eps: float, out_dtype):
    """Shared RMS-norm core (LLaMA RMSNorm, T5 LayerNorm): fp32 accumulate,
    scale, cast back."""
    x32 = x.astype(jnp.float32)
    var = jnp.mean(jnp.square(x32), axis=-1, keepdims=True)
    return (x32 * jax.lax.rsqrt(var + eps) * weight.astype(jnp.float32)).astype(out_dtype)


_ONEHOT_CHUNK = 1024  # tokens per backward chunk — bounds the one-hot buffer


@functools.lru_cache(maxsize=None)
def _onehot_embed_fn(vocab: int, dtype_name: str):
    @jax.custom_vjp
    def f(wte, ids):
        return jnp.take(wte, ids, axis=0)

    def fwd(wte, ids):
        return jnp.take(wte, ids, axis=0), ids

    def bwd(ids, g):
        # chunk the token axis: a single-shot one_hot is [T, V] in the grad
        # dtype (~824 MB at T=4k, V=50k, fp32); scanning T in chunks of
        # _ONEHOT_CHUNK with a bf16 one-hot (fp32 accumulation via
        # preferred_element_type) bounds the buffer to a few tens of MB
        ids_f = ids.reshape(-1)
        g_f = g.reshape(-1, g.shape[-1]).astype(jnp.float32)
        t = ids_f.shape[0]
        ch = _ONEHOT_CHUNK
        if t <= ch:
            onehot = jax.nn.one_hot(ids_f, vocab, dtype=jnp.bfloat16)
            gw = jax.lax.dot_general(onehot, g_f, (((0,), (0,)), ((), ())),
                                     preferred_element_type=jnp.float32)
        else:
            # pad to a chunk multiple — padded rows carry zero cotangent so
            # they contribute nothing, and the memory bound holds for EVERY
            # shape (a full-T fallback would reintroduce the [T, V] spike)
            pad = (-t) % ch
            if pad:
                ids_f = jnp.concatenate([ids_f, jnp.zeros((pad,), ids_f.dtype)])
                g_f = jnp.concatenate([g_f, jnp.zeros((pad, g_f.shape[-1]), g_f.dtype)])
            def body(acc, xs):
                i_c, g_c = xs
                oh = jax.nn.one_hot(i_c, vocab, dtype=jnp.bfloat16)
                return acc + jax.lax.dot_general(oh, g_c, (((0,), (0,)), ((), ())),
                                                 preferred_element_type=jnp.float32), None

            gw, _ = jax.lax.scan(body, jnp.zeros((vocab, g_f.shape[-1]), jnp.float32),
                                 (ids_f.reshape(-1, ch), g_f.reshape(-1, ch, g_f.shape[-1])))
        return gw.astype(dtype_name), None

    f.defvjp(fwd, bwd)
    return f


def take_embed_onehot_grad(wte, ids):
    """Embedding lookup whose BACKWARD is a one-hot matmul instead of a
    scatter-add. TPU scatter lowers to a serialized per-index update; the
    [T, V] x [T, E] matmul form rides the MXU (the standard TPU trick —
    costs ~V*T*E extra FLOPs, usually a small fraction of a transformer
    step). Forward is a plain gather either way."""
    return _onehot_embed_fn(int(wte.shape[0]), jnp.dtype(wte.dtype).name)(wte, ids)


def embed_lookup(wte, ids, onehot_grad: bool = False):
    """Token-embedding gather with a selectable backward formulation."""
    if onehot_grad:
        return take_embed_onehot_grad(wte, ids)
    return jnp.take(wte, ids, axis=0)


def config_from(table: dict, cls, name: str, **overrides):
    """Look up a named config dict and build ``cls`` with overrides."""
    base = dict(table[name])
    base.update(overrides)
    return cls(**base)


def normalize_padding_mask(attention_mask, ndim_target: int = 4):
    """[B, L] 0/1 padding mask → [B, 1, 1, L] boolean; pass through masks
    that already have a broadcastable rank."""
    if attention_mask is None:
        return None
    if attention_mask.ndim == 2:
        return attention_mask[:, None, None, :].astype(bool)
    return attention_mask.astype(bool)
