"""Shared model-zoo helpers."""

import jax
import jax.numpy as jnp

import flax.linen as nn


def init_cache(model: nn.Module, batch_size: int, rng=None):
    """Build a zeroed decode cache for any model supporting ``decode=True``
    (the reference's ``allocate_workspace`` KV-cache setup,
    ``csrc/transformer/inference/csrc/pt_binding.cpp:1928``).

    Uses ``eval_shape`` so no compute runs and the cache index starts at 0
    (``model.init(decode=True)`` would advance it by tracing the call body).
    """
    ids = jnp.zeros((batch_size, 1), jnp.int32)
    rng = rng if rng is not None else jax.random.PRNGKey(0)
    shapes = jax.eval_shape(lambda: model.init(rng, ids, decode=True))
    return jax.tree.map(lambda s: jnp.zeros(s.shape, s.dtype), shapes["cache"])


def dense_init(scale: float = 0.02):
    return nn.initializers.normal(stddev=scale)


def config_from(table: dict, cls, name: str, **overrides):
    """Look up a named config dict and build ``cls`` with overrides."""
    base = dict(table[name])
    base.update(overrides)
    return cls(**base)


def normalize_padding_mask(attention_mask, ndim_target: int = 4):
    """[B, L] 0/1 padding mask → [B, 1, 1, L] boolean; pass through masks
    that already have a broadcastable rank."""
    if attention_mask is None:
        return None
    if attention_mask.ndim == 2:
        return attention_mask[:, None, None, :].astype(bool)
    return attention_mask.astype(bool)
