"""Diffusion model family: SD-style conditional UNet + KL autoencoder
(reference serving surface: ``model_implementations/diffusers/unet.py`` /
``vae.py`` wrap HuggingFace diffusers modules; generic diffusers injection
``module_inject/replace_module.py:187``).

The reference WRAPS torch diffusers modules (cuda-graph capture + kernel
injection); diffusers is not available here, so the family is implemented
natively in flax, TPU-first:

* NHWC layout end to end — convs tile the MXU in NHWC on TPU; the
  ``ops/spatial`` nhwc bias/add fusions are the matching elementwise ops;
* GroupNorm in fp32 accumulation, SiLU fused by XLA;
* attention (self + cross) over flattened spatial tokens through the same
  pluggable backend seam as the LM zoo (``ops/transformer/attention``);
* every conv/dense kernel carries t5x-style logical axis names so the
  ZeRO planner/TP rules place them like any other family.

Serving wrappers :class:`DSUNet` / :class:`DSVAE` (reference
``diffusers/unet.py:15`` / ``vae.py:13``) hold (module, params) and serve
through a shape-keyed jit cache — the role the reference fills with CUDA
graphs: first call traces/compiles, repeats replay.
"""

import dataclasses
import math
from typing import Any, Optional, Tuple

import flax.linen as nn
import jax
import jax.numpy as jnp
import numpy as np

from deepspeed_tpu.models.common import dense_init as _init

Dtype = Any


@dataclasses.dataclass(frozen=True)
class UNetConfig:
    """SD-1.x-shaped config, scaled by ``block_out_channels``."""
    in_channels: int = 4
    out_channels: int = 4
    block_out_channels: Tuple[int, ...] = (32, 64)
    layers_per_block: int = 1
    attention_head_dim: int = 8
    cross_attention_dim: int = 32
    norm_num_groups: int = 8
    dtype: Any = jnp.float32
    param_dtype: Any = jnp.float32


@dataclasses.dataclass(frozen=True)
class VAEConfig:
    in_channels: int = 3
    latent_channels: int = 4
    block_out_channels: Tuple[int, ...] = (32, 64)
    layers_per_block: int = 1
    norm_num_groups: int = 8
    scaling_factor: float = 0.18215  # SD latent scale
    dtype: Any = jnp.float32
    param_dtype: Any = jnp.float32


def timestep_embedding(t, dim: int, max_period: float = 10000.0):
    """Sinusoidal timestep features (DDPM convention), fp32."""
    t = jnp.asarray(t, jnp.float32).reshape(-1)
    half = dim // 2
    freqs = jnp.exp(-math.log(max_period) * jnp.arange(half, dtype=jnp.float32) / half)
    args = t[:, None] * freqs[None]
    return jnp.concatenate([jnp.cos(args), jnp.sin(args)], axis=-1)


class GroupNorm32(nn.Module):
    """GroupNorm with fp32 statistics regardless of compute dtype (output
    follows the input dtype)."""
    groups: int

    @nn.compact
    def __call__(self, x):
        orig = x.dtype
        y = nn.GroupNorm(num_groups=self.groups, dtype=jnp.float32,
                         param_dtype=jnp.float32)(x.astype(jnp.float32))
        return y.astype(orig)


def _conv(cfg, features, kernel=3, name=None, strides=1):
    return nn.Conv(features, (kernel, kernel), strides=(strides, strides),
                   padding="SAME", dtype=cfg.dtype,
                   param_dtype=cfg.param_dtype,
                   kernel_init=nn.with_logical_partitioning(
                       nn.initializers.lecun_normal(), (None, None, "embed", "mlp")),
                   bias_init=nn.with_logical_partitioning(nn.initializers.zeros, ("mlp",)),
                   name=name)


class ResnetBlock(nn.Module):
    """GN → SiLU → conv ×2 with a timestep-embedding shift and a learned
    skip when channels change (NHWC)."""
    config: Any
    out_ch: int

    @nn.compact
    def __call__(self, x, temb=None):
        cfg = self.config
        h = _conv(cfg, self.out_ch, name="conv1")(
            nn.silu(GroupNorm32(cfg.norm_num_groups, name="norm1")(x)))
        if temb is not None:
            shift = nn.Dense(self.out_ch, dtype=cfg.dtype, param_dtype=cfg.param_dtype,
                             kernel_init=nn.with_logical_partitioning(
                                 _init(), ("embed", "mlp")),
                             name="time_emb_proj")(nn.silu(temb))
            h = h + shift[:, None, None, :].astype(h.dtype)
        h = _conv(cfg, self.out_ch, name="conv2")(
            nn.silu(GroupNorm32(cfg.norm_num_groups, name="norm2")(h)))
        if x.shape[-1] != self.out_ch:
            x = nn.Conv(self.out_ch, (1, 1), dtype=cfg.dtype, param_dtype=cfg.param_dtype,
                        kernel_init=nn.with_logical_partitioning(
                            nn.initializers.lecun_normal(), (None, None, "embed", "mlp")),
                        name="conv_shortcut")(x)
        return x + h


class SpatialTransformer(nn.Module):
    """Self-attention + cross-attention + GEGLU FF over flattened HxW
    tokens (the SD transformer block; width follows the input tensor),
    NHWC in/out."""
    config: UNetConfig

    @nn.compact
    def __call__(self, x, context=None):
        cfg = self.config
        b, hgt, wid, c = x.shape
        heads = max(c // cfg.attention_head_dim, 1)
        resid = x
        h = GroupNorm32(cfg.norm_num_groups, name="norm")(x).reshape(b, hgt * wid, c)

        def attn(q_src, kv_src, name):
            from deepspeed_tpu.ops.transformer.attention import dot_product_attention
            head_dim = c // heads
            dg = dict(dtype=cfg.dtype, param_dtype=cfg.param_dtype)
            q = nn.DenseGeneral((heads, head_dim), axis=-1,
                                kernel_init=nn.with_logical_partitioning(
                                    _init(), ("embed", "heads", "kv")),
                                use_bias=False, name=f"{name}_q", **dg)(q_src)
            k = nn.DenseGeneral((heads, head_dim), axis=-1,
                                kernel_init=nn.with_logical_partitioning(
                                    _init(), ("embed", "heads", "kv")),
                                use_bias=False, name=f"{name}_k", **dg)(kv_src)
            v = nn.DenseGeneral((heads, head_dim), axis=-1,
                                kernel_init=nn.with_logical_partitioning(
                                    _init(), ("embed", "heads", "kv")),
                                use_bias=False, name=f"{name}_v", **dg)(kv_src)
            o = dot_product_attention(q, k, v, backend="xla", causal=False)
            return nn.DenseGeneral(c, axis=(-2, -1),
                                   kernel_init=nn.with_logical_partitioning(
                                       _init(), ("heads", "kv", "embed")),
                                   name=f"{name}_out", **dg)(o)

        h1 = nn.LayerNorm(dtype=cfg.dtype, name="ln1")(h)
        h = h + attn(h1, h1, "self_attn")
        ctx = h if context is None else context.astype(h.dtype)
        h = h + attn(nn.LayerNorm(dtype=cfg.dtype, name="ln2")(h), ctx, "cross_attn")
        # GEGLU feed-forward
        ff_in = nn.LayerNorm(dtype=cfg.dtype, name="ln3")(h)
        gate = nn.Dense(c * 8, dtype=cfg.dtype, param_dtype=cfg.param_dtype,
                        kernel_init=nn.with_logical_partitioning(_init(), ("embed", "mlp")),
                        name="ff_in")(ff_in)
        a, g = jnp.split(gate, 2, axis=-1)
        h = h + nn.Dense(c, dtype=cfg.dtype, param_dtype=cfg.param_dtype,
                         kernel_init=nn.with_logical_partitioning(_init(), ("mlp", "embed")),
                         name="ff_out")(a * nn.gelu(g))
        return resid + h.reshape(b, hgt, wid, c)


class UNet2DConditionModel(nn.Module):
    """Conditional denoising UNet (reference serving target
    ``diffusers/unet.py``; forward contract (sample, timestep,
    encoder_hidden_states) -> eps prediction, NHWC)."""
    config: UNetConfig

    @nn.compact
    def __call__(self, sample, timesteps, encoder_hidden_states=None):
        cfg = self.config
        ch0 = cfg.block_out_channels[0]
        temb = timestep_embedding(timesteps, ch0)
        temb = nn.Dense(ch0 * 4, dtype=cfg.dtype, param_dtype=cfg.param_dtype,
                        kernel_init=nn.with_logical_partitioning(_init(), ("embed", "mlp")),
                        name="time_dense1")(temb.astype(cfg.dtype))
        temb = nn.Dense(ch0 * 4, dtype=cfg.dtype, param_dtype=cfg.param_dtype,
                        kernel_init=nn.with_logical_partitioning(_init(), ("mlp", "embed")),
                        name="time_dense2")(nn.silu(temb))

        h = _conv(cfg, ch0, name="conv_in")(sample.astype(cfg.dtype))
        skips = [h]
        # down path: resnets (+ attention except at the last level) then stride-2 conv
        for i, ch in enumerate(cfg.block_out_channels):
            for j in range(cfg.layers_per_block):
                h = ResnetBlock(cfg, ch, name=f"down_{i}_res_{j}")(h, temb)
                if i < len(cfg.block_out_channels) - 1:
                    h = SpatialTransformer(cfg, name=f"down_{i}_attn_{j}")(
                        h, encoder_hidden_states)
                skips.append(h)
            if i < len(cfg.block_out_channels) - 1:
                h = _conv(cfg, ch, name=f"down_{i}_downsample", strides=2)(h)
                skips.append(h)
        mid_ch = cfg.block_out_channels[-1]
        h = ResnetBlock(cfg, mid_ch, name="mid_res_1")(h, temb)
        h = SpatialTransformer(cfg, name="mid_attn")(h, encoder_hidden_states)
        h = ResnetBlock(cfg, mid_ch, name="mid_res_2")(h, temb)
        # up path: consume skips in reverse, nearest-neighbor upsample
        for i, ch in reversed(list(enumerate(cfg.block_out_channels))):
            for j in range(cfg.layers_per_block + 1):
                h = jnp.concatenate([h, skips.pop()], axis=-1)
                h = ResnetBlock(cfg, ch, name=f"up_{i}_res_{j}")(h, temb)
                if i < len(cfg.block_out_channels) - 1:
                    h = SpatialTransformer(cfg, name=f"up_{i}_attn_{j}")(
                        h, encoder_hidden_states)
            if i > 0:
                b, hh, ww, c = h.shape
                h = jax.image.resize(h, (b, hh * 2, ww * 2, c), "nearest")
                h = _conv(cfg, c, name=f"up_{i}_upsample")(h)
        h = nn.silu(GroupNorm32(cfg.norm_num_groups, name="norm_out")(h))
        return _conv(cfg, cfg.out_channels, name="conv_out")(h)


class _VAEBlockStack(nn.Module):
    config: VAEConfig
    channels: Tuple[int, ...]
    downsample: bool

    @nn.compact
    def __call__(self, h):
        cfg = self.config
        n = len(self.channels)
        for i, ch in enumerate(self.channels):
            for j in range(cfg.layers_per_block):
                h = ResnetBlock(cfg, ch, name=f"res_{i}_{j}")(h)
            resize = i < n - 1
            if self.downsample and resize:
                h = _conv(cfg, ch, name=f"down_{i}", strides=2)(h)
            elif not self.downsample and resize:
                b, hh, ww, c = h.shape
                h = jax.image.resize(h, (b, hh * 2, ww * 2, c), "nearest")
                h = _conv(cfg, c, name=f"up_{i}")(h)
        return h


class AutoencoderKL(nn.Module):
    """KL autoencoder (reference serving target ``diffusers/vae.py``):
    ``encode`` -> latent moments (mean, logvar), ``decode`` -> image,
    ``__call__`` = roundtrip reconstruction. NHWC."""
    config: VAEConfig

    def setup(self):
        cfg = self.config
        self.encoder = _VAEBlockStack(cfg, cfg.block_out_channels, True, name="encoder")
        self.decoder = _VAEBlockStack(cfg, tuple(reversed(cfg.block_out_channels)), False,
                                      name="decoder")
        self.conv_in = _conv(cfg, cfg.block_out_channels[0], name="conv_in")
        self.quant_conv = _conv(cfg, 2 * cfg.latent_channels, kernel=1, name="quant_conv")
        self.post_quant_conv = _conv(cfg, cfg.block_out_channels[-1], kernel=1,
                                     name="post_quant_conv")
        self.conv_out = _conv(cfg, cfg.in_channels, name="conv_out")
        self.norm_out = GroupNorm32(cfg.norm_num_groups, name="norm_out")

    def encode(self, x):
        h = self.encoder(self.conv_in(x.astype(self.config.dtype)))
        moments = self.quant_conv(h)
        mean, logvar = jnp.split(moments, 2, axis=-1)
        return mean, jnp.clip(logvar, -30.0, 20.0)

    def decode(self, z):
        h = self.decoder(self.post_quant_conv(z.astype(self.config.dtype)))
        return self.conv_out(nn.silu(self.norm_out(h)))

    def __call__(self, x, rng=None):
        mean, logvar = self.encode(x)
        z = mean if rng is None else mean + jnp.exp(0.5 * logvar) * \
            jax.random.normal(rng, mean.shape, mean.dtype)
        return self.decode(z)


class _JitServed:
    """Shape-keyed jit cache around (module, params) — the reference wraps
    these modules in CUDA graphs (``diffusers/unet.py:27`` enable_cuda_graph);
    on TPU the compiled XLA executable IS the captured graph: first call
    per shape traces, repeats replay."""

    def __init__(self, module, params, dtype=None):
        import flax.linen as fnn
        self.module = module
        self.params = fnn.meta.unbox(params)
        if dtype is not None:
            self.params = jax.tree.map(
                lambda p: p.astype(dtype) if jnp.issubdtype(
                    jnp.asarray(p).dtype, jnp.floating) else p, self.params)
        self._fns = {}

    def _jitted(self, method: Optional[str], shapes):
        key = (method, shapes)
        if key not in self._fns:
            def fn(params, *args):
                if method is None:
                    return self.module.apply({"params": params}, *args)
                return self.module.apply({"params": params}, *args, method=method)
            self._fns[key] = jax.jit(fn, static_argnums=())
        return self._fns[key]

    @staticmethod
    def _norm(a):
        # jit cannot consume foreign tensor types: torch CPU tensors (the
        # diffusers-parity calling convention) normalize to numpy views
        # host-side; jax/numpy arrays pass through untouched
        if isinstance(a, jax.Array) or isinstance(a, np.ndarray):
            return a
        return np.asarray(a)

    def _shapes(self, args):
        # no jnp.asarray here: it would device_put full inputs just to
        # read a dtype on the per-step serving hot path. jax/numpy arrays
        # answer via .dtype.name; anything else (torch CPU tensors through
        # __array__, python scalars) normalizes host-side via np.asarray —
        # a view/scalar op, never a device transfer
        return tuple((tuple(jnp.shape(a)),
                      getattr(getattr(a, "dtype", None), "name", None)
                      or np.asarray(a).dtype.name)
                     for a in args)


class DSUNet(_JitServed):
    """Reference ``model_implementations/diffusers/unet.py`` ``DSUNet``."""

    def __call__(self, sample, timesteps, encoder_hidden_states=None):
        args = (sample, timesteps) + (() if encoder_hidden_states is None
                                      else (encoder_hidden_states,))
        args = tuple(self._norm(a) for a in args)
        return self._jitted(None, self._shapes(args))(self.params, *args)


class DSVAE(_JitServed):
    """Reference ``model_implementations/diffusers/vae.py`` ``DSVAE``."""

    def encode(self, x):
        x = self._norm(x)
        return self._jitted("encode", self._shapes((x,)))(self.params, x)

    def decode(self, z):
        z = self._norm(z)
        return self._jitted("decode", self._shapes((z,)))(self.params, z)

    def __call__(self, x):
        x = self._norm(x)
        return self._jitted(None, self._shapes((x,)))(self.params, x)
