"""Falcon family — grouped/multi-query attention with parallel residual
(the reference serves Falcon through kernel injection; HF
``FalconForCausalLM`` is the checkpoint source).

Same TPU conventions as the rest of the zoo. Falcon quirks kept for
checkpoint parity: the fused QKV is GROUP-interleaved ([kv_group][q x G,
k, v] rather than per-head q/k/v), rotary covers the full head dim
(half-split convention), projections carry no biases, attention and MLP
read the same residual input (parallel residual), and the LN scheme
follows ``new_decoder_architecture`` — one shared ``input_layernorm``
(7B-style, MQA via ``num_kv_heads=1``) or separate ``ln_attn``/``ln_mlp``
(40B/180B-style GQA).
"""

import dataclasses
from typing import Any

import flax.linen as nn
import jax
import jax.numpy as jnp

from deepspeed_tpu.models.common import config_from, dense_init as _init
from deepspeed_tpu.models.llama import rotary_embedding
from deepspeed_tpu.ops.transformer.attention import dot_product_attention


@dataclasses.dataclass(frozen=True)
class FalconConfig:
    vocab_size: int = 65024
    hidden_size: int = 4544
    num_attention_heads: int = 71
    num_kv_heads: int = 1  # 1 = multi-query (7B); >1 = grouped (40B/180B)
    num_hidden_layers: int = 32
    max_position_embeddings: int = 2048
    layer_norm_epsilon: float = 1e-5
    rope_theta: float = 10000.0
    new_decoder_architecture: bool = False  # True: separate ln_attn/ln_mlp
    dtype: Any = jnp.float32
    param_dtype: Any = jnp.float32
    remat: bool = False
    # >0: loss via the chunked fused LM head when called with labels=
    # (models/common.py fused_lm_head_loss) — no [B, L, V] logits buffer
    fused_head_loss_chunk: int = 0
    attention_backend: str = "xla"

    @property
    def head_dim(self):
        return self.hidden_size // self.num_attention_heads

    @property
    def q_per_kv(self):
        return self.num_attention_heads // self.num_kv_heads


FALCON_CONFIGS = {
    "test": dict(vocab_size=256, hidden_size=64, num_attention_heads=4, num_kv_heads=1,
                 num_hidden_layers=2, max_position_embeddings=128),
    "test-gqa": dict(vocab_size=256, hidden_size=64, num_attention_heads=4, num_kv_heads=2,
                     num_hidden_layers=2, max_position_embeddings=128,
                     new_decoder_architecture=True),
    "7b": dict(hidden_size=4544, num_attention_heads=71, num_kv_heads=1,
               num_hidden_layers=32),
    "40b": dict(hidden_size=8192, num_attention_heads=128, num_kv_heads=8,
                num_hidden_layers=60, new_decoder_architecture=True),
}


def get_falcon_config(name: str, **overrides) -> FalconConfig:
    return config_from(FALCON_CONFIGS, FalconConfig, name, **overrides)


class FalconAttention(nn.Module):
    config: FalconConfig
    decode: bool = False

    @nn.compact
    def __call__(self, x):
        cfg = self.config
        b, l, _ = x.shape
        kv, g, d = cfg.num_kv_heads, cfg.q_per_kv, cfg.head_dim
        # fused group-interleaved qkv: per kv group G query heads, one k, one v
        qkv = nn.DenseGeneral(features=(kv, g + 2, d), axis=-1, use_bias=False,
                              dtype=cfg.dtype, param_dtype=cfg.param_dtype,
                              kernel_init=nn.with_logical_partitioning(
                                  _init(), ("embed", "heads", None, "kv")),
                              name="query_key_value")(x)
        q = qkv[..., :g, :].reshape(b, l, kv * g, d)   # [B, L, H, D]
        k = qkv[..., g, :]                             # [B, L, KV, D]
        v = qkv[..., g + 1, :]
        causal, decode_lengths = True, None
        if self.decode:
            cache_index = self.variable("cache", "cache_index", lambda: jnp.zeros([], jnp.int32))
            idx = cache_index.value
            positions = idx + jnp.broadcast_to(jnp.arange(l)[None, :], (b, l))
            q = rotary_embedding(q, positions, cfg.rope_theta)
            k = rotary_embedding(k, positions, cfg.rope_theta)
            shape = (b, cfg.max_position_embeddings, kv, d)
            cached_k = self.variable("cache", "cached_key", jnp.zeros, shape, k.dtype)
            cached_v = self.variable("cache", "cached_value", jnp.zeros, shape, v.dtype)
            cached_k.value = jax.lax.dynamic_update_slice(cached_k.value, k, (0, idx, 0, 0))
            cached_v.value = jax.lax.dynamic_update_slice(cached_v.value, v, (0, idx, 0, 0))
            cache_index.value = idx + l
            k, v = cached_k.value, cached_v.value
            decode_lengths = jnp.broadcast_to(idx + l, (b,))
            causal = False
        else:
            positions = jnp.broadcast_to(jnp.arange(l)[None, :], (b, l))
            q = rotary_embedding(q, positions, cfg.rope_theta)
            k = rotary_embedding(k, positions, cfg.rope_theta)
        if g > 1 or kv != cfg.num_attention_heads:
            k = jnp.repeat(k, g, axis=2)
            v = jnp.repeat(v, g, axis=2)
        out = dot_product_attention(q, k, v, backend=cfg.attention_backend,
                                    causal=causal, decode_lengths=decode_lengths)
        return nn.DenseGeneral(features=cfg.hidden_size, axis=(-2, -1), use_bias=False,
                               dtype=cfg.dtype, param_dtype=cfg.param_dtype,
                               kernel_init=nn.with_logical_partitioning(
                                   _init(), ("heads", "kv", "embed")),
                               name="dense")(out)


class FalconBlock(nn.Module):
    config: FalconConfig
    decode: bool = False

    @nn.compact
    def __call__(self, x):
        cfg = self.config
        ln = lambda name: nn.LayerNorm(epsilon=cfg.layer_norm_epsilon, dtype=cfg.dtype,
                                       param_dtype=cfg.param_dtype, name=name)
        if cfg.new_decoder_architecture:
            attn_in = ln("ln_attn")(x)
            mlp_in = ln("ln_mlp")(x)
        else:
            attn_in = mlp_in = ln("input_layernorm")(x)
        attn_out = FalconAttention(cfg, self.decode, name="self_attention")(attn_in)
        h = nn.Dense(features=4 * cfg.hidden_size, use_bias=False, dtype=cfg.dtype,
                     param_dtype=cfg.param_dtype,
                     kernel_init=nn.with_logical_partitioning(_init(), ("embed", "mlp")),
                     name="dense_h_to_4h")(mlp_in)
        h = jax.nn.gelu(h, approximate=False)
        h = nn.Dense(features=cfg.hidden_size, use_bias=False, dtype=cfg.dtype,
                     param_dtype=cfg.param_dtype,
                     kernel_init=nn.with_logical_partitioning(_init(), ("mlp", "embed")),
                     name="dense_4h_to_h")(h)
        return x + attn_out + h  # parallel residual


class FalconForCausalLM(nn.Module):
    """Falcon with tied word-embedding head."""

    # offload_param streaming: these block subtrees self-stream inside
    # their remat region (param_offload.stream_block_params); the engine
    # top-streams only the remaining leaves
    streamed_block_prefixes = ("h_",)


    config: FalconConfig

    @nn.compact
    def __call__(self, input_ids, *, deterministic: bool = True, decode: bool = False,
                 labels=None):
        cfg = self.config
        wte = self.param("word_embeddings", nn.with_logical_partitioning(_init(), ("vocab", "embed")),
                         (cfg.vocab_size, cfg.hidden_size), cfg.param_dtype)
        wte_v = wte.value if isinstance(wte, nn.meta.AxisMetadata) else wte
        from deepspeed_tpu.models.common import embed_lookup
        x = embed_lookup(wte_v, input_ids,
                         getattr(cfg, 'embed_onehot_grad', None), decode).astype(cfg.dtype)
        from deepspeed_tpu.runtime.zero.param_offload import stream_block_params
        block_cls = stream_block_params(FalconBlock)
        if cfg.remat:
            block_cls = nn.remat(block_cls, prevent_cse=False)
        from deepspeed_tpu.models.common import constrain_activation
        # batch-parallel residual stream over fsdp-sharded weights — see
        # constrain_activation (the ZeRO-3 weak-scaling invariant)
        x = constrain_activation(x, "batch", "length", "embed")
        for i in range(cfg.num_hidden_layers):
            x = block_cls(cfg, decode, name=f"h_{i}")(x)
            x = constrain_activation(x, "batch", "length", "embed")
        x = nn.LayerNorm(epsilon=cfg.layer_norm_epsilon, dtype=cfg.dtype,
                         param_dtype=cfg.param_dtype, name="ln_f")(x)
        if labels is not None and cfg.fused_head_loss_chunk > 0:
            from deepspeed_tpu.models.common import fused_head_loss_output
            return fused_head_loss_output(x, wte_v.astype(cfg.dtype), labels,
                                          0.0, deterministic, cfg, vocab_major=True)
        return jnp.einsum("ble,ve->blv", x, wte_v.astype(cfg.dtype),
                          preferred_element_type=cfg.dtype)
