"""GPT-2 family — the flagship decoder-only model (config ladder:
125M → 350M → 760M → XL-1.5B, BASELINE.md).

TPU-first design notes:
* every parameter carries t5x-style logical axis names via
  ``nn.with_partitioning`` so the ZeRO planner
  (``deepspeed_tpu.parallel.sharding``) can derive tensor-parallel and
  fsdp shardings declaratively — the role the reference fills with
  Megatron mpu slicing + ``zero.Init`` (``partition_parameters.py``);
* attention goes through the pluggable backend seam
  (``deepspeed_tpu.ops.transformer.attention``) so the XLA reference path
  and the Pallas flash kernel are interchangeable;
* ``remat`` wraps each block with ``jax.checkpoint`` — the analog of the
  reference's activation checkpointing (``runtime/activation_checkpointing``).
"""

import dataclasses
from typing import Any, Optional

import flax.linen as nn
import jax
import jax.numpy as jnp

from deepspeed_tpu.models.common import embed_lookup
from deepspeed_tpu.ops.transformer.attention import dot_product_attention

Dtype = Any


@dataclasses.dataclass(frozen=True)
class GPT2Config:
    vocab_size: int = 50257
    n_positions: int = 1024
    n_embd: int = 768
    n_layer: int = 12
    n_head: int = 12
    dropout: float = 0.0
    layer_norm_epsilon: float = 1e-5
    dtype: Any = jnp.float32  # compute dtype; params stay in param_dtype
    param_dtype: Any = jnp.float32
    remat: bool = False
    # jax.checkpoint policy name (runtime/activation_checkpointing: e.g.
    # "dots_saveable" keeps matmul outputs, None = full recompute) and
    # selective application (checkpoint every Nth block; reference
    # ``number_checkpoints`` semantics)
    remat_policy: Optional[str] = None
    remat_every: int = 1
    attention_backend: str = "xla"
    # flash-backend block geometry / bwd policy override, as a spec string
    # ("block_q=256,block_k=512,policy=recompute", see models/common.py
    # attention_geometry_kwargs); None = resolve via env/config/autotune
    attention_blocks: Optional[str] = None
    # QKV projection as ONE fused [E,3,H,D] GEMM (default, the historical
    # program) vs three sliced GEMMs over the SAME parameter — a program-
    # shape dimension graft-search enumerates (analysis/search.py; engine
    # "program" config block). Checkpoint layout is identical either way.
    attn_fused_qkv: bool = True
    # attention-output projection contracting (heads, kv) directly off the
    # [B,L,H,D] attention output (default) vs an explicit [B,L,H*D]
    # reshape then a 2D GEMM — same parameter, different program shape
    attn_fused_out: bool = True
    # backward of the token-embedding gather as a one-hot matmul instead of
    # a scatter-add. Default ON: scatter serializes on TPU (measured +10%
    # with the matmul form, PERF.md r3 session 3) AND the scatter-add's
    # batch-sharded→embed-sharded update reshard is the "Involuntary full
    # rematerialization" GSPMD warns about on expert/fsdp meshes — the
    # einsum backward partitions cleanly (contraction psum)
    embed_onehot_grad: bool = True
    # >0: when called with ``labels=``, compute the loss via the chunked
    # fused LM head (models/common.py fused_lm_head_loss) — never
    # materializes [B, L, V] logits; the value is tokens per chunk
    fused_head_loss_chunk: int = 0
    # progressive layer drop (arXiv:2010.13369; reference
    # ``runtime/progressive_layer_drop.py``): when True and the engine
    # passes ``pld_theta``, each sublayer is stochastically skipped at
    # train time with depth-scaled keep probability
    progressive_layer_drop: bool = False
    # MoE (reference GPT-MoE configs: every other layer is an MoE FFN)
    moe_num_experts: int = 0  # 0 = dense model
    moe_layer_freq: int = 2  # MoE every Nth block (reference expert-interval)
    moe_k: int = 1
    moe_capacity_factor: float = 1.25
    moe_eval_capacity_factor: float = 2.0
    moe_min_capacity: int = 4
    moe_aux_loss_coef: float = 0.01
    moe_noisy_gate_policy: Optional[str] = None
    moe_use_residual: bool = False
    moe_drop_tokens: bool = True
    moe_use_rts: bool = True
    # dispatch/combine route pin ("dense"|"sorted"); None resolves through
    # DS_MOE_ROUTE env > engine "moe" config block > default (moe/routing.py)
    moe_route: Optional[str] = None
    # graft-quant-serve: served weight dtype this module instance was BUILT
    # for ("int8"|"int4"). None (training, lockstep generate) keeps the fp
    # projections. Set explicitly by the serving scheduler / scenarios —
    # never resolved from env here, because the param tree's code layout
    # must match what the projections statically declare (int4 halves the
    # contraction axis); the DS_SERVE_WQ env seam lives at the builder
    # (serving/scheduler.py, analysis/scenarios.py), where drift changes
    # which program gets traced and the cost gate catches it
    serve_weight_dtype: Optional[str] = None

    @property
    def head_dim(self):
        return self.n_embd // self.n_head


GPT2_CONFIGS = {
    # tiny config for unit tests (vocab multiple of 8 for mesh divisibility)
    "test": dict(vocab_size=256, n_positions=128, n_embd=64, n_layer=2, n_head=4),
    "125m": dict(n_embd=768, n_layer=12, n_head=12),
    "350m": dict(n_embd=1024, n_layer=24, n_head=16),
    "760m": dict(n_embd=1536, n_layer=24, n_head=16),
    "xl": dict(n_embd=1600, n_layer=48, n_head=25),
}


def get_gpt2_config(name: str, **overrides) -> GPT2Config:
    from deepspeed_tpu.models.common import config_from
    return config_from(GPT2_CONFIGS, GPT2Config, name, **overrides)


def _dense_init(scale=0.02):
    from deepspeed_tpu.models.common import dense_init
    return dense_init(scale)


_QUANT_BITS = {"int8": 8, "int4": 4}


def _serve_quant_mode(module, cfg) -> str:
    """Resolved weight dtype for a projection: quantized only when the
    module was built for it (``serve_weight_dtype`` set) AND this scope's
    scales ride along in the ``"quant"`` collection — leaves the skip list
    (``ops/quantizer/weights.py``) keeps fp stay fp automatically."""
    swd = getattr(cfg, "serve_weight_dtype", None)
    if swd is None:
        return "fp"
    from deepspeed_tpu.inference.serving.config import resolve_weight_dtype
    mode, _ = resolve_weight_dtype(swd)  # explicit layer; validates choice
    if mode == "fp" or not module.has_variable("quant", "kernel_scale"):
        return "fp"
    return mode


def _kv_quantize(vals):
    """Per-(slot, token, head) symmetric int8 KV quantization through the
    one grouped quantizer in the repo (``ops/quantizer/core``). The
    last-axis form keeps the reduce on the (unsharded) head_dim axis, so
    a head-sharded KV write on a tensor mesh quantizes in place instead
    of all-gathering the pool. Returns (codes [b, l, h, d] int8,
    scales [b, l, h, 1] in KV dtype)."""
    from deepspeed_tpu.ops.quantizer.core import quantize_lastaxis
    codes, scale = quantize_lastaxis(vals, num_bits=8)
    return codes, scale.astype(vals.dtype)


class QKVProj(nn.Module):
    """QKV projection over ONE fused ``[E, 3, H, D]`` parameter (the exact
    layout/init ``nn.DenseGeneral(features=(3, H, D))`` declared here
    historically, so checkpoints are unchanged) with two program forms:
    ``attn_fused_qkv=True`` emits the single fused GEMM; ``False`` emits
    three sliced GEMMs — identical math, different program shape for the
    scheduler/partitioner, the fusion dimension graft-search prices."""

    config: GPT2Config

    @nn.compact
    def __call__(self, x):
        cfg = self.config
        unbox = lambda p: p.value if isinstance(p, nn.meta.AxisMetadata) else p
        wq = _serve_quant_mode(self, cfg)
        kshape = (cfg.n_embd, 3, cfg.n_head, cfg.head_dim)
        if wq == "int4":
            kshape = (cfg.n_embd // 2,) + kshape[1:]  # packed contraction axis
        kernel = unbox(self.param(
            "kernel", nn.with_logical_partitioning(_dense_init(), ("embed", None, "heads", "kv")),
            kshape, cfg.param_dtype))
        bias = unbox(self.param(
            "bias", nn.with_logical_partitioning(nn.initializers.zeros, (None, "heads", "kv")),
            (3, cfg.n_head, cfg.head_dim), cfg.param_dtype))
        x = x.astype(cfg.dtype)
        bias = bias.astype(cfg.dtype)
        if wq != "fp":
            # dequant fused into the GEMM; always the fused program form —
            # the quantized serving program is one GEMM per projection
            from deepspeed_tpu.ops.pallas.quant_matmul import quant_dense_general
            qkv = quant_dense_general(x, kernel,
                                      self.get_variable("quant", "kernel_scale"),
                                      bits=_QUANT_BITS[wq], n_contract=1)
            qkv = qkv + jnp.reshape(bias, (1,) * (qkv.ndim - bias.ndim) + bias.shape)
            return qkv[..., 0, :, :], qkv[..., 1, :, :], qkv[..., 2, :, :]
        kernel = kernel.astype(cfg.dtype)
        contract = ((x.ndim - 1,), (0,))
        if cfg.attn_fused_qkv:
            qkv = jax.lax.dot_general(x, kernel, (contract, ((), ())))
            qkv = qkv + jnp.reshape(bias, (1,) * (qkv.ndim - bias.ndim) + bias.shape)
            return qkv[..., 0, :, :], qkv[..., 1, :, :], qkv[..., 2, :, :]
        outs = []
        for i in range(3):
            o = jax.lax.dot_general(x, kernel[:, i], (contract, ((), ())))
            outs.append(o + jnp.reshape(bias[i], (1,) * (o.ndim - 2) + bias[i].shape))
        return tuple(outs)


class AttnOutProj(nn.Module):
    """Attention-output projection over the ``[H, D, E]`` parameter
    ``nn.DenseGeneral(features=E, axis=(-2, -1))`` declared here
    historically. ``attn_fused_out=True`` contracts (heads, kv) directly
    off the ``[B, L, H, D]`` attention output; ``False`` reshapes to
    ``[B, L, H*D]`` first and runs a 2D GEMM — same parameter, the second
    fusion dimension graft-search prices."""

    config: GPT2Config

    @nn.compact
    def __call__(self, x):
        cfg = self.config
        unbox = lambda p: p.value if isinstance(p, nn.meta.AxisMetadata) else p
        wq = _serve_quant_mode(self, cfg)
        kshape = (cfg.n_head, cfg.head_dim, cfg.n_embd)
        if wq == "int4":
            kshape = (cfg.n_head, cfg.head_dim // 2, cfg.n_embd)
        kernel = unbox(self.param(
            "kernel", nn.with_logical_partitioning(_dense_init(), ("heads", "kv", "embed")),
            kshape, cfg.param_dtype))
        bias = unbox(self.param(
            "bias", nn.with_logical_partitioning(nn.initializers.zeros, ("embed",)),
            (cfg.n_embd,), cfg.param_dtype))
        x = x.astype(cfg.dtype)
        bias = bias.astype(cfg.dtype)
        if wq != "fp":
            from deepspeed_tpu.ops.pallas.quant_matmul import quant_dense_general
            out = quant_dense_general(x, kernel,
                                      self.get_variable("quant", "kernel_scale"),
                                      bits=_QUANT_BITS[wq], n_contract=2)
            return out + bias
        kernel = kernel.astype(cfg.dtype)
        if cfg.attn_fused_out:
            out = jax.lax.dot_general(
                x, kernel, (((x.ndim - 2, x.ndim - 1), (0, 1)), ((), ())))
        else:
            flat = x.reshape(x.shape[:-2] + (cfg.n_head * cfg.head_dim,))
            out = jax.lax.dot_general(
                flat, kernel.reshape(cfg.n_head * cfg.head_dim, cfg.n_embd),
                (((flat.ndim - 1,), (0,)), ((), ())))
        return out + bias


class SelfAttention(nn.Module):
    config: GPT2Config
    decode: bool = False

    @nn.compact
    def __call__(self, x, *, deterministic: bool = True):
        cfg = self.config
        q, k, v = QKVProj(cfg, name="c_attn")(x)
        dropout_rng = None
        if not deterministic and cfg.dropout > 0.0:
            dropout_rng = self.make_rng("dropout")
        causal, decode_lengths = True, None
        if self.decode:
            # incremental decoding against a static-shape KV cache (the
            # reference's inference workspace, inference_context.h)
            b, l = x.shape[0], x.shape[1]
            cached_k = self.variable("cache", "cached_key", jnp.zeros,
                                     (b, cfg.n_positions, cfg.n_head, cfg.head_dim), k.dtype)
            cached_v = self.variable("cache", "cached_value", jnp.zeros,
                                     (b, cfg.n_positions, cfg.n_head, cfg.head_dim), v.dtype)
            # int8 KV pools (graft-quant-serve, the serving default): codes
            # plus per-(slot, position, head) scales, quantize-on-write /
            # dequantize-on-read — PagedKVCache(quantize=True) applied to
            # the per-slot cache. Only serving.make_slot_cache(kv_quant=
            # True) builds these pools, so which path traces is decided by
            # the provided cache dtype, statically.
            kv_q = cached_k.value.dtype == jnp.int8
            if kv_q:
                k_scale = self.variable("cache", "cached_key_scale", jnp.zeros,
                                        (b, cfg.n_positions, cfg.n_head, 1), k.dtype)
                v_scale = self.variable("cache", "cached_value_scale", jnp.zeros,
                                        (b, cfg.n_positions, cfg.n_head, 1), v.dtype)
            cache_index = self.variable("cache", "cache_index", lambda: jnp.zeros([], jnp.int32))
            idx = cache_index.value
            if idx.ndim:
                # graft-serve per-slot ragged cache: ``cache_index`` arrives
                # as a [B] write-position vector (serving.make_slot_cache),
                # so every slot of an in-flight batch appends at its OWN
                # length — the join/leave masking is positional: a parked
                # slot's sentinel position (>= n_positions) makes its
                # scatter writes drop out of bounds, no jnp.where over the
                # pool. decode_lengths becomes genuinely per-slot, which
                # the attention backends already mask per sequence.
                from deepspeed_tpu.inference.serving.config import resolve_kv_write
                mode, _ = resolve_kv_write(getattr(cfg, "serve_kv_write", None))
                pos = idx[:, None] + jnp.arange(l)[None, :]  # [b, l]
                if kv_q:
                    k_w, k_s = _kv_quantize(k)
                    v_w, v_s = _kv_quantize(v)
                else:
                    k_w, v_w = k, v
                if mode == "dense":
                    # masked full-pool rebuild: one [b, l, P] one-hot and a
                    # [b, P, h, d] temporary PER LAYER per tick — kept as the
                    # DS_SERVE_KV_WRITE seeded regression for the R010 gate
                    # (semantically identical: out-of-bounds one-hot rows are
                    # zero, so parked slots still drop their writes)
                    onehot = jax.nn.one_hot(pos, cfg.n_positions, dtype=jnp.float32)
                    written = (onehot.sum(1) > 0)[..., None, None]  # [b, P, 1, 1]

                    def _dense_put(pool, vals, round_int=False):
                        upd = jnp.einsum("blp,blhd->bphd", onehot,
                                         vals.astype(jnp.float32))
                        if round_int:
                            # int8 codes survive the fp32 einsum exactly
                            # (±127 ≪ 2^24); rint guards the cast back
                            upd = jnp.rint(upd)
                        return jnp.where(written, upd.astype(pool.dtype), pool)

                    cached_k.value = _dense_put(cached_k.value, k_w, round_int=kv_q)
                    cached_v.value = _dense_put(cached_v.value, v_w, round_int=kv_q)
                    if kv_q:
                        k_scale.value = _dense_put(k_scale.value, k_s)
                        v_scale.value = _dense_put(v_scale.value, v_s)
                else:
                    bidx = jnp.arange(b)[:, None]
                    # default scatter mode drops out-of-bounds updates —
                    # exactly the parked-slot contract
                    cached_k.value = cached_k.value.at[bidx, pos].set(k_w)
                    cached_v.value = cached_v.value.at[bidx, pos].set(v_w)
                    if kv_q:
                        k_scale.value = k_scale.value.at[bidx, pos].set(k_s)
                        v_scale.value = v_scale.value.at[bidx, pos].set(v_s)
                decode_lengths = idx + l
            else:
                if kv_q:
                    raise NotImplementedError(
                        "int8 KV pools are a per-slot serving cache "
                        "(make_slot_cache(kv_quant=True)); lockstep decode "
                        "uses fp KV")
                cached_k.value = jax.lax.dynamic_update_slice(cached_k.value, k, (0, idx, 0, 0))
                cached_v.value = jax.lax.dynamic_update_slice(cached_v.value, v, (0, idx, 0, 0))
                # per-sequence live-length vector — the flash backend's decode
                # kernel skips dead KV blocks; the XLA backend derives the
                # validity mask from it
                decode_lengths = jnp.broadcast_to(idx + l, (b,))
            cache_index.value = idx + l
            if kv_q:
                # gather-dequant: attention reads fp values, HBM holds codes
                k = cached_k.value.astype(q.dtype) * k_scale.value
                v = cached_v.value.astype(q.dtype) * v_scale.value
            else:
                k, v = cached_k.value, cached_v.value
            causal = False
        from deepspeed_tpu.models.common import attention_geometry_kwargs
        attn_out = dot_product_attention(q,
                                         k,
                                         v,
                                         backend=cfg.attention_backend,
                                         causal=causal,
                                         decode_lengths=decode_lengths,
                                         dropout_rate=0.0 if deterministic else cfg.dropout,
                                         dropout_rng=dropout_rng,
                                         **attention_geometry_kwargs(cfg))
        out = AttnOutProj(cfg, name="c_proj")(attn_out)
        if not deterministic and cfg.dropout > 0.0:
            out = nn.Dropout(rate=cfg.dropout)(out, deterministic=False)
        return out


class QuantDense(nn.Module):
    """Drop-in for ``nn.Dense`` (identical param names/shapes/init/
    partitioning, so checkpoints and shardings are unchanged) that adds
    the quantized serving path: when built with ``serve_weight_dtype``
    and this scope carries quant scales, the kernel arrives as int8/int4
    codes and dequant fuses into the GEMM
    (``ops/pallas/quant_matmul.py``)."""

    config: GPT2Config
    features: int
    kernel_axes: Any = ("embed", "mlp")
    bias_axes: Any = ("mlp",)

    @nn.compact
    def __call__(self, x):
        cfg = self.config
        unbox = lambda p: p.value if isinstance(p, nn.meta.AxisMetadata) else p
        wq = _serve_quant_mode(self, cfg)
        in_features = x.shape[-1]
        kshape = (in_features // 2 if wq == "int4" else in_features, self.features)
        kernel = unbox(self.param(
            "kernel", nn.with_logical_partitioning(_dense_init(), self.kernel_axes),
            kshape, cfg.param_dtype))
        bias = unbox(self.param(
            "bias", nn.with_logical_partitioning(nn.initializers.zeros, self.bias_axes),
            (self.features,), cfg.param_dtype))
        x = x.astype(cfg.dtype)
        bias = bias.astype(cfg.dtype)
        if wq != "fp":
            from deepspeed_tpu.ops.pallas.quant_matmul import quant_dense_general
            out = quant_dense_general(x, kernel,
                                      self.get_variable("quant", "kernel_scale"),
                                      bits=_QUANT_BITS[wq], n_contract=1)
            return out + bias
        out = jax.lax.dot_general(x, kernel.astype(cfg.dtype),
                                  (((x.ndim - 1,), (0,)), ((), ())))
        return out + bias


class MLP(nn.Module):
    config: GPT2Config

    @nn.compact
    def __call__(self, x, *, deterministic: bool = True):
        cfg = self.config
        h = QuantDense(cfg, features=4 * cfg.n_embd,
                       kernel_axes=("embed", "mlp"), bias_axes=("mlp",),
                       name="c_fc")(x)
        h = jax.nn.gelu(h, approximate=True)
        h = QuantDense(cfg, features=cfg.n_embd,
                       kernel_axes=("mlp", "embed"), bias_axes=("embed",),
                       name="c_proj")(h)
        if not deterministic and cfg.dropout > 0.0:
            h = nn.Dropout(rate=cfg.dropout)(h, deterministic=False)
        return h


class LayerNorm(nn.Module):
    config: GPT2Config

    @nn.compact
    def __call__(self, x):
        cfg = self.config
        return nn.LayerNorm(epsilon=cfg.layer_norm_epsilon,
                            dtype=cfg.dtype,
                            param_dtype=cfg.param_dtype,
                            scale_init=nn.with_logical_partitioning(nn.initializers.ones, ("embed",)),
                            bias_init=nn.with_logical_partitioning(nn.initializers.zeros, ("embed",)))(x)


class Block(nn.Module):
    config: GPT2Config
    use_moe: bool = False
    decode: bool = False

    def _pld_gate(self, branch, keep):
        from deepspeed_tpu.models.common import pld_gate
        return pld_gate(self, branch, keep)

    @nn.compact
    def __call__(self, x, deterministic: bool = True, pld_keep=None):
        # deterministic is positional (not kw-only) so nn.remat can mark it
        # static (static_argnums below)
        cfg = self.config
        keep = None if (deterministic or pld_keep is None) else pld_keep
        attn_out = SelfAttention(cfg, self.decode, name="attn")(LayerNorm(cfg, name="ln_1")(x),
                                                                deterministic=deterministic)
        gated_attn, _ = self._pld_gate(attn_out, keep)
        x = x + gated_attn
        h = LayerNorm(cfg, name="ln_2")(x)
        if self.use_moe:
            from deepspeed_tpu.moe import MoE
            moe_out, l_aux, _ = MoE(hidden_size=cfg.n_embd,
                                    expert=MLP(cfg),
                                    num_experts=cfg.moe_num_experts,
                                    k=cfg.moe_k,
                                    capacity_factor=cfg.moe_capacity_factor,
                                    eval_capacity_factor=cfg.moe_eval_capacity_factor,
                                    min_capacity=cfg.moe_min_capacity,
                                    use_residual=cfg.moe_use_residual,
                                    noisy_gate_policy=cfg.moe_noisy_gate_policy,
                                    drop_tokens=cfg.moe_drop_tokens,
                                    use_rts=cfg.moe_use_rts,
                                    route=cfg.moe_route,
                                    name="moe")(h, deterministic=deterministic)
            gated_moe, b = self._pld_gate(moe_out, keep)
            x = x + gated_moe
            if b is not None:
                # a dropped expert layer must not push balancing gradients
                # into its router either
                l_aux = jnp.where(b, l_aux, jnp.zeros_like(l_aux))
            return x, l_aux
        gated_mlp, _ = self._pld_gate(MLP(cfg, name="mlp")(h, deterministic=deterministic), keep)
        x = x + gated_mlp
        return x, jnp.zeros([], jnp.float32)


class GPT2LMHeadModel(nn.Module):
    """GPT-2 with tied-embedding LM head. Returns logits [B, L, V]."""

    config: GPT2Config
    # offload_param streaming: h_* blocks self-stream inside their remat
    # region (maybe_remat); the engine top-streams only the rest (wte/wpe/
    # ln_f), keeping per-layer device copies out of the remat residuals
    streamed_block_prefixes = ("h_",)

    @nn.compact
    def __call__(self, input_ids, *, deterministic: bool = True, decode: bool = False,
                 labels=None, pld_theta=None):
        cfg = self.config
        wte = self.param("wte", nn.with_logical_partitioning(_dense_init(), ("vocab", "embed")),
                         (cfg.vocab_size, cfg.n_embd), cfg.param_dtype)
        wpe = self.param("wpe", nn.with_logical_partitioning(_dense_init(0.01), (None, "embed")),
                         (cfg.n_positions, cfg.n_embd), cfg.param_dtype)
        wte_value = wte.value if isinstance(wte, nn.meta.AxisMetadata) else wte
        wpe_value = wpe.value if isinstance(wpe, nn.meta.AxisMetadata) else wpe

        _, seq_len = input_ids.shape
        x = embed_lookup(wte_value, input_ids, cfg.embed_onehot_grad, decode).astype(cfg.dtype)
        if decode:
            # position offset for wpe; advances in lockstep with each
            # attention layer's cache_index (same increment per call — flax
            # offers no clean cross-module read, so the counter is duplicated)
            pos_idx = self.variable("cache", "position_index", lambda: jnp.zeros([], jnp.int32))
            if pos_idx.value.ndim:
                # per-slot serving cache: [B] positions (clip keeps parked
                # slots' sentinel positions in-table; their rows are dead)
                positions = jnp.clip(pos_idx.value[:, None] + jnp.arange(seq_len)[None, :],
                                     0, cfg.n_positions - 1)
                x = x + jnp.take(wpe_value, positions, axis=0).astype(cfg.dtype)
            else:
                positions = pos_idx.value + jnp.arange(seq_len)
                x = x + jnp.take(wpe_value, positions, axis=0).astype(cfg.dtype)[None]
            pos_idx.value = pos_idx.value + seq_len
        else:
            x = x + wpe_value[:seq_len].astype(cfg.dtype)
        if not deterministic and cfg.dropout > 0.0:
            x = nn.Dropout(rate=cfg.dropout)(x, deterministic=False)

        from deepspeed_tpu.models.common import constrain_activation, maybe_remat
        # pin the residual stream to batch-parallel sharding: without this
        # GSPMD may replicate the batch over fsdp-sharded (ZeRO-3) weights
        # and all-reduce per-layer contractions — per-chip bytes that grow
        # with the mesh (see constrain_activation)
        x = constrain_activation(x, "batch", "length", "embed")
        aux_total = jnp.zeros([], jnp.float32)
        use_pld = cfg.progressive_layer_drop and pld_theta is not None and not deterministic
        for i in range(cfg.n_layer):
            use_moe = cfg.moe_num_experts > 0 and (i % cfg.moe_layer_freq == cfg.moe_layer_freq - 1)
            block_cls = maybe_remat(Block, cfg, i, static_argnums=(2,))
            # PLD depth scaling (paper eq. 6): deeper blocks drop more often
            keep_i = 1.0 - (i + 1) / cfg.n_layer * (1.0 - pld_theta) if use_pld else None
            x, l_aux = block_cls(cfg, use_moe, decode, name=f"h_{i}")(x, deterministic, keep_i)
            x = constrain_activation(x, "batch", "length", "embed")
            aux_total = aux_total + l_aux
        x = LayerNorm(cfg, name="ln_f")(x)
        if labels is not None and cfg.fused_head_loss_chunk > 0:
            # chunked fused head: next-token NLL straight from hidden
            # states, no [B,L,V] logits buffer (shift/aux policy lives in
            # fused_head_loss_output, shared across families)
            from deepspeed_tpu.models.common import fused_head_loss_output
            return fused_head_loss_output(x, wte_value.astype(cfg.dtype), labels,
                                          aux_total, deterministic, cfg,
                                          vocab_major=True)
        # tied LM head. Logits stay at the COMPUTE dtype: [B,L,V] is the
        # single largest activation (824MB fp32 at bs4/seq1024/GPT-2 vocab)
        # and the loss does its softmax reductions in fp32 anyway
        # (cross_entropy_loss) — bf16 logits halve the dominant HBM traffic
        # of the step (PERF.md hypothesis #2)
        logits = jnp.einsum("ble,ve->blv", x, wte_value.astype(cfg.dtype),
                            preferred_element_type=cfg.dtype)
        if cfg.moe_num_experts > 0:
            return logits, aux_total * cfg.moe_aux_loss_coef
        return logits


# ---------------------------------------------------------------------------
# Pipeline-parallel layer adapters (reference expresses GPT-2 for pipelining
# as a LayerSpec list — e.g. Megatron's GPT2ModelPipe; here the specs feed
# deepspeed_tpu.runtime.pipe.module.PipelineModule)
# ---------------------------------------------------------------------------
class GPT2EmbedPipe(nn.Module):
    """Token+position embedding; ``attend`` is the tied LM head."""

    config: GPT2Config

    def setup(self):
        cfg = self.config
        self.wte = self.param("wte", nn.with_logical_partitioning(_dense_init(), ("vocab", "embed")),
                              (cfg.vocab_size, cfg.n_embd), cfg.param_dtype)
        self.wpe = self.param("wpe", nn.with_logical_partitioning(_dense_init(0.01), (None, "embed")),
                              (cfg.n_positions, cfg.n_embd), cfg.param_dtype)

    def __call__(self, input_ids):
        cfg = self.config
        wte = self.wte.value if isinstance(self.wte, nn.meta.AxisMetadata) else self.wte
        wpe = self.wpe.value if isinstance(self.wpe, nn.meta.AxisMetadata) else self.wpe
        x = embed_lookup(wte, input_ids, cfg.embed_onehot_grad).astype(cfg.dtype)
        return x + wpe[:input_ids.shape[-1]].astype(cfg.dtype)

    def attend(self, x):
        wte = self.wte.value if isinstance(self.wte, nn.meta.AxisMetadata) else self.wte
        return jnp.einsum("...le,ve->...lv", x, wte.astype(self.config.dtype),
                          preferred_element_type=self.config.dtype)


class GPT2BlockPipe(nn.Module):
    """One transformer block with a plain ``x -> x`` signature (pipeline
    stages stream activations only; deterministic — pipeline dropout would
    need per-stage rng plumbing)."""

    config: GPT2Config

    @nn.compact
    def __call__(self, x):
        out, _ = Block(self.config, name="block")(x, True)
        return out


class GPT2LNPipe(nn.Module):
    config: GPT2Config

    @nn.compact
    def __call__(self, x):
        return LayerNorm(self.config, name="ln_f")(x)


def gpt2_pipe_layers(config: GPT2Config):
    """The LayerSpec list for a pipelined GPT-2 (embedding tied to the LM
    head, reference ``TiedLayerSpec`` semantics)."""
    from deepspeed_tpu.runtime.pipe.module import LayerSpec, TiedLayerSpec

    if config.moe_num_experts > 0:
        raise ValueError("MoE blocks are not supported in the pipelined GPT-2: the pipeline "
                         "stage body is deterministic and drops the aux loss. Combine "
                         "expert parallelism with ZeRO/TP instead (expert mesh axis).")

    return [
        TiedLayerSpec("embed", GPT2EmbedPipe, config, tied_weight_attr="wte"),
        *[LayerSpec(GPT2BlockPipe, config) for _ in range(config.n_layer)],
        LayerSpec(GPT2LNPipe, config),
        TiedLayerSpec("embed", GPT2EmbedPipe, config, tied_weight_attr="wte",
                      forward_fn=lambda m, x: m.attend(x)),
    ]


def cross_entropy_loss(logits, labels, ignore_index: int = -100):
    """Mean token cross-entropy with label masking (fp32 accumulation).

    The fp32 upcast feeds ONLY the logsumexp reduction so XLA fuses the
    convert into the reduce; the label gather reads the compute-dtype
    logits and upcasts the [B,L] result — bit-identical (f32(bf16) is
    exact) but avoids materializing [B,L,V] in fp32, the single largest
    allocation of the train step (3 GiB at mb16/seq1024/GPT-2 vocab).
    """
    valid = labels != ignore_index
    safe_labels = jnp.where(valid, labels, 0)
    logz = jax.nn.logsumexp(logits.astype(jnp.float32), axis=-1)
    label_logit = jnp.take_along_axis(
        logits, safe_labels[..., None], axis=-1)[..., 0].astype(jnp.float32)
    nll = (logz - label_logit) * valid
    return nll.sum() / jnp.maximum(valid.sum(), 1)
