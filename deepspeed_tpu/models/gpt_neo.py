"""GPT-Neo family (125M/1.3B/2.7B) — learned positions with alternating
global/local (sliding-window) attention layers (the reference serves
GPT-Neo through kernel injection, ``module_inject/containers/gptneo.py``).

Same TPU conventions as the rest of the zoo. GPT-Neo quirks kept for
checkpoint parity: UNSCALED attention logits (no 1/sqrt(d) — the original
mesh-tensorflow training choice HF preserves), bias-free q/k/v with biased
out_proj, odd layers attending only the last ``window_size`` positions
(the flash kernel skips out-of-window blocks; the xla backend masks),
tanh-gelu MLP, tied LM head. Window masking applies to training/prefill —
decode attends the whole cache (same convention as the Mistral preset,
``models/llama.py``).
"""

import dataclasses
from typing import Any, Optional

import flax.linen as nn
import jax
import jax.numpy as jnp

from deepspeed_tpu.models.common import config_from, dense_init as _init, maybe_remat
from deepspeed_tpu.ops.transformer.attention import dot_product_attention


@dataclasses.dataclass(frozen=True)
class GPTNeoConfig:
    vocab_size: int = 50257
    hidden_size: int = 768
    intermediate_size: int = 3072
    num_hidden_layers: int = 12
    num_attention_heads: int = 12
    max_position_embeddings: int = 2048
    # every odd layer is "local": attends (pos - window_size, pos]
    window_size: int = 256
    layer_norm_eps: float = 1e-5
    dtype: Any = jnp.float32
    param_dtype: Any = jnp.float32
    remat: bool = False
    remat_every: int = 1
    remat_policy: Optional[str] = None
    # >0: loss via the chunked fused LM head when called with labels=
    fused_head_loss_chunk: int = 0
    attention_backend: str = "xla"

    @property
    def head_dim(self):
        return self.hidden_size // self.num_attention_heads

    def attention_type(self, layer_idx: int) -> str:
        # HF attention_types [[["global", "local"], n/2]] — even global,
        # odd local
        return "local" if layer_idx % 2 else "global"


GPT_NEO_CONFIGS = {
    "test": dict(vocab_size=256, hidden_size=64, intermediate_size=128, num_hidden_layers=2,
                 num_attention_heads=4, max_position_embeddings=128, window_size=8),
    "125m": dict(hidden_size=768, intermediate_size=3072, num_hidden_layers=12,
                 num_attention_heads=12),
    "1.3b": dict(hidden_size=2048, intermediate_size=8192, num_hidden_layers=24,
                 num_attention_heads=16),
    "2.7b": dict(hidden_size=2560, intermediate_size=10240, num_hidden_layers=32,
                 num_attention_heads=20),
}


def get_gpt_neo_config(name: str, **overrides) -> GPTNeoConfig:
    return config_from(GPT_NEO_CONFIGS, GPTNeoConfig, name, **overrides)


class GPTNeoAttention(nn.Module):
    config: GPTNeoConfig
    layer_idx: int = 0
    decode: bool = False

    @nn.compact
    def __call__(self, x):
        cfg = self.config
        b, l, _ = x.shape
        local = cfg.attention_type(self.layer_idx) == "local"

        def proj(name):
            return nn.DenseGeneral(features=(cfg.num_attention_heads, cfg.head_dim), axis=-1,
                                   use_bias=False, dtype=cfg.dtype, param_dtype=cfg.param_dtype,
                                   kernel_init=nn.with_logical_partitioning(
                                       _init(), ("embed", "heads", "kv")),
                                   name=name)(x)

        q, k, v = proj("q_proj"), proj("k_proj"), proj("v_proj")  # [B, L, H, D]
        causal, decode_lengths, window = True, None, cfg.window_size if local else None
        if self.decode:
            cache_index = self.variable("cache", "cache_index", lambda: jnp.zeros([], jnp.int32))
            idx = cache_index.value
            shape = (b, cfg.max_position_embeddings, cfg.num_attention_heads, cfg.head_dim)
            cached_k = self.variable("cache", "cached_key", jnp.zeros, shape, k.dtype)
            cached_v = self.variable("cache", "cached_value", jnp.zeros, shape, v.dtype)
            cached_k.value = jax.lax.dynamic_update_slice(cached_k.value, k, (0, idx, 0, 0))
            cached_v.value = jax.lax.dynamic_update_slice(cached_v.value, v, (0, idx, 0, 0))
            cache_index.value = idx + l
            k, v = cached_k.value, cached_v.value
            decode_lengths = jnp.broadcast_to(idx + l, (b,))
            causal, window = False, None  # decode attends the whole cache
        # GPT-Neo computes UNSCALED attention logits (scale=1.0)
        out = dot_product_attention(q, k, v, backend=cfg.attention_backend,
                                    causal=causal, scale=1.0,
                                    decode_lengths=decode_lengths, window=window)
        return nn.DenseGeneral(features=cfg.hidden_size, axis=(-2, -1), use_bias=True,
                               dtype=cfg.dtype, param_dtype=cfg.param_dtype,
                               kernel_init=nn.with_logical_partitioning(_init(), ("heads", "kv", "embed")),
                               bias_init=nn.with_logical_partitioning(nn.initializers.zeros, ("embed",)),
                               name="out_proj")(out)


class GPTNeoBlock(nn.Module):
    config: GPTNeoConfig
    layer_idx: int = 0
    decode: bool = False

    @nn.compact
    def __call__(self, x):
        cfg = self.config
        ln = lambda name: nn.LayerNorm(epsilon=cfg.layer_norm_eps, dtype=cfg.dtype,
                                       param_dtype=cfg.param_dtype, name=name)
        x = x + GPTNeoAttention(cfg, self.layer_idx, self.decode,
                                name="attn")(ln("ln_1")(x))
        h = nn.Dense(features=cfg.intermediate_size, dtype=cfg.dtype, param_dtype=cfg.param_dtype,
                     kernel_init=nn.with_logical_partitioning(_init(), ("embed", "mlp")),
                     bias_init=nn.with_logical_partitioning(nn.initializers.zeros, ("mlp",)),
                     name="c_fc")(ln("ln_2")(x))
        h = jax.nn.gelu(h, approximate=True)  # HF GPT-Neo uses gelu_new
        h = nn.Dense(features=cfg.hidden_size, dtype=cfg.dtype, param_dtype=cfg.param_dtype,
                     kernel_init=nn.with_logical_partitioning(_init(), ("mlp", "embed")),
                     bias_init=nn.with_logical_partitioning(nn.initializers.zeros, ("embed",)),
                     name="c_proj")(h)
        return x + h


class GPTNeoForCausalLM(nn.Module):
    """GPT-Neo with TIED LM head. Returns logits [B, L, V] (or the scalar
    loss when ``labels`` ride the fused head)."""

    # offload_param streaming: blocks self-stream inside their remat
    # region; the engine top-streams only the remaining leaves
    streamed_block_prefixes = ("h_",)

    config: GPTNeoConfig

    @nn.compact
    def __call__(self, input_ids, *, deterministic: bool = True, decode: bool = False,
                 labels=None):
        cfg = self.config
        wte = self.param("wte", nn.with_logical_partitioning(_init(), ("vocab", "embed")),
                         (cfg.vocab_size, cfg.hidden_size), cfg.param_dtype)
        wpe = self.param("wpe", nn.with_logical_partitioning(_init(0.01), (None, "embed")),
                         (cfg.max_position_embeddings, cfg.hidden_size), cfg.param_dtype)
        wte = wte.value if isinstance(wte, nn.meta.AxisMetadata) else wte
        wpe = wpe.value if isinstance(wpe, nn.meta.AxisMetadata) else wpe

        b, l = input_ids.shape
        from deepspeed_tpu.models.common import embed_lookup
        x = embed_lookup(wte, input_ids,
                         getattr(cfg, 'embed_onehot_grad', None), decode).astype(cfg.dtype)
        if decode:
            pos_idx = self.variable("cache", "position_index", lambda: jnp.zeros([], jnp.int32))
            positions = pos_idx.value + jnp.arange(l)
            pos_idx.value = pos_idx.value + l
            x = x + jnp.take(wpe, positions, axis=0).astype(cfg.dtype)[None]
        else:
            x = x + wpe[:l].astype(cfg.dtype)
        from deepspeed_tpu.models.common import constrain_activation
        # batch-parallel residual stream over fsdp-sharded weights — see
        # constrain_activation (the ZeRO-3 weak-scaling invariant)
        x = constrain_activation(x, "batch", "length", "embed")
        for i in range(cfg.num_hidden_layers):
            block_cls = maybe_remat(GPTNeoBlock, cfg, i, enabled=cfg.remat and not decode)
            x = block_cls(cfg, i, decode, name=f"h_{i}")(x)
            x = constrain_activation(x, "batch", "length", "embed")
        x = nn.LayerNorm(epsilon=cfg.layer_norm_eps, dtype=cfg.dtype,
                         param_dtype=cfg.param_dtype, name="ln_f")(x)
        if labels is not None and cfg.fused_head_loss_chunk > 0:
            from deepspeed_tpu.models.common import fused_head_loss_output
            return fused_head_loss_output(x, wte.astype(cfg.dtype), labels,
                                          0.0, deterministic, cfg, vocab_major=True)
        return jnp.einsum("ble,ve->blv", x, wte.astype(cfg.dtype),
                          preferred_element_type=cfg.dtype)
