"""GPT-NeoX family (Pythia/20B) — partial-rotary attention with parallel
residual (the reference serves NeoX through kernel injection,
``module_inject/containers/gptneox.py``).

Same TPU conventions as the rest of the zoo (logical axis names → ZeRO
planner, pluggable attention backend with ``decode_lengths`` decode, flax
``cache`` collection). NeoX quirks kept for checkpoint parity: rotary on
only the first ``rotary_pct`` of each head dim, parallel residual
(``x + attn(ln1(x)) + mlp(ln2(x))``), untied ``embed_out`` LM head, and
biased projections throughout.
"""

import dataclasses
from typing import Any

import flax.linen as nn
import jax
import jax.numpy as jnp

from deepspeed_tpu.models.common import config_from, dense_init as _init
from deepspeed_tpu.models.llama import rotary_embedding
from deepspeed_tpu.ops.transformer.attention import dot_product_attention


@dataclasses.dataclass(frozen=True)
class GPTNeoXConfig:
    vocab_size: int = 50432
    hidden_size: int = 768
    intermediate_size: int = 3072
    num_hidden_layers: int = 12
    num_attention_heads: int = 12
    max_position_embeddings: int = 2048
    rotary_pct: float = 0.25
    rotary_emb_base: float = 10000.0
    use_parallel_residual: bool = True
    layer_norm_eps: float = 1e-5
    dtype: Any = jnp.float32
    param_dtype: Any = jnp.float32
    remat: bool = False
    # >0: loss via the chunked fused LM head when called with labels=
    # (models/common.py fused_lm_head_loss) — no [B, L, V] logits buffer
    fused_head_loss_chunk: int = 0
    attention_backend: str = "xla"

    @property
    def head_dim(self):
        return self.hidden_size // self.num_attention_heads

    @property
    def rotary_ndims(self):
        return int(self.head_dim * self.rotary_pct)


GPT_NEOX_CONFIGS = {
    "test": dict(vocab_size=256, hidden_size=64, intermediate_size=128, num_hidden_layers=2,
                 num_attention_heads=4, max_position_embeddings=128),
    "pythia-160m": dict(vocab_size=50304, hidden_size=768, intermediate_size=3072,
                        num_hidden_layers=12, num_attention_heads=12),
    "pythia-1.4b": dict(vocab_size=50304, hidden_size=2048, intermediate_size=8192,
                        num_hidden_layers=24, num_attention_heads=16),
    "pythia-6.9b": dict(hidden_size=4096, intermediate_size=16384, num_hidden_layers=32,
                        num_attention_heads=32),
    "20b": dict(vocab_size=50432, hidden_size=6144, intermediate_size=24576,
                num_hidden_layers=44, num_attention_heads=64),
}


def get_gpt_neox_config(name: str, **overrides) -> GPTNeoXConfig:
    return config_from(GPT_NEOX_CONFIGS, GPTNeoXConfig, name, **overrides)


def _partial_rotary(x, positions, rotary_ndims: int, base: float):
    """RoPE on the first ``rotary_ndims`` of the head dim, rest passes
    through (NeoX convention)."""
    rot, rest = x[..., :rotary_ndims], x[..., rotary_ndims:]
    rot = rotary_embedding(rot, positions, base)
    return jnp.concatenate([rot, rest], axis=-1)


class GPTNeoXAttention(nn.Module):
    config: GPTNeoXConfig
    decode: bool = False

    @nn.compact
    def __call__(self, x):
        cfg = self.config
        b, l, _ = x.shape
        # fused qkv in NeoX's per-head-interleaved layout: [E] -> [H, 3, D]
        qkv = nn.DenseGeneral(features=(cfg.num_attention_heads, 3, cfg.head_dim), axis=-1,
                              dtype=cfg.dtype, param_dtype=cfg.param_dtype,
                              kernel_init=nn.with_logical_partitioning(
                                  _init(), ("embed", "heads", None, "kv")),
                              bias_init=nn.with_logical_partitioning(
                                  nn.initializers.zeros, ("heads", None, "kv")),
                              name="query_key_value")(x)
        q, k, v = qkv[..., 0, :], qkv[..., 1, :], qkv[..., 2, :]  # [B, L, H, D]
        causal, decode_lengths = True, None
        if self.decode:
            cache_index = self.variable("cache", "cache_index", lambda: jnp.zeros([], jnp.int32))
            idx = cache_index.value
            positions = idx + jnp.broadcast_to(jnp.arange(l)[None, :], (b, l))
            q = _partial_rotary(q, positions, cfg.rotary_ndims, cfg.rotary_emb_base)
            k = _partial_rotary(k, positions, cfg.rotary_ndims, cfg.rotary_emb_base)
            shape = (b, cfg.max_position_embeddings, cfg.num_attention_heads, cfg.head_dim)
            cached_k = self.variable("cache", "cached_key", jnp.zeros, shape, k.dtype)
            cached_v = self.variable("cache", "cached_value", jnp.zeros, shape, v.dtype)
            cached_k.value = jax.lax.dynamic_update_slice(cached_k.value, k, (0, idx, 0, 0))
            cached_v.value = jax.lax.dynamic_update_slice(cached_v.value, v, (0, idx, 0, 0))
            cache_index.value = idx + l
            k, v = cached_k.value, cached_v.value
            decode_lengths = jnp.broadcast_to(idx + l, (b,))
            causal = False
        else:
            positions = jnp.broadcast_to(jnp.arange(l)[None, :], (b, l))
            q = _partial_rotary(q, positions, cfg.rotary_ndims, cfg.rotary_emb_base)
            k = _partial_rotary(k, positions, cfg.rotary_ndims, cfg.rotary_emb_base)
        out = dot_product_attention(q, k, v, backend=cfg.attention_backend,
                                    causal=causal, decode_lengths=decode_lengths)
        return nn.DenseGeneral(features=cfg.hidden_size, axis=(-2, -1),
                               dtype=cfg.dtype, param_dtype=cfg.param_dtype,
                               kernel_init=nn.with_logical_partitioning(_init(), ("heads", "kv", "embed")),
                               bias_init=nn.with_logical_partitioning(nn.initializers.zeros, ("embed",)),
                               name="dense")(out)


class GPTNeoXBlock(nn.Module):
    config: GPTNeoXConfig
    decode: bool = False

    @nn.compact
    def __call__(self, x):
        cfg = self.config
        ln = lambda name: nn.LayerNorm(epsilon=cfg.layer_norm_eps, dtype=cfg.dtype,
                                       param_dtype=cfg.param_dtype, name=name)

        def mlp(h):
            h = nn.Dense(features=cfg.intermediate_size, dtype=cfg.dtype, param_dtype=cfg.param_dtype,
                         kernel_init=nn.with_logical_partitioning(_init(), ("embed", "mlp")),
                         bias_init=nn.with_logical_partitioning(nn.initializers.zeros, ("mlp",)),
                         name="dense_h_to_4h")(h)
            h = jax.nn.gelu(h, approximate=False)  # HF NeoX uses exact (erf) gelu
            return nn.Dense(features=cfg.hidden_size, dtype=cfg.dtype, param_dtype=cfg.param_dtype,
                            kernel_init=nn.with_logical_partitioning(_init(), ("mlp", "embed")),
                            bias_init=nn.with_logical_partitioning(nn.initializers.zeros, ("embed",)),
                            name="dense_4h_to_h")(h)

        attn_out = GPTNeoXAttention(cfg, self.decode, name="attention")(
            ln("input_layernorm")(x))
        if cfg.use_parallel_residual:
            # x + attn(ln1(x)) + mlp(ln2(x)) — one residual stream
            mlp_out = mlp(ln("post_attention_layernorm")(x))
            return x + attn_out + mlp_out
        x = x + attn_out
        return x + mlp(ln("post_attention_layernorm")(x))


class GPTNeoXForCausalLM(nn.Module):
    """GPT-NeoX with UNTIED ``embed_out`` head. Returns logits [B, L, V]."""

    # offload_param streaming: blocks self-stream inside their remat
    # region; the engine top-streams only the remaining leaves
    streamed_block_prefixes = ("layers_",)

    config: GPTNeoXConfig

    @nn.compact
    def __call__(self, input_ids, *, deterministic: bool = True, decode: bool = False,
                 labels=None):
        cfg = self.config
        embed_in = self.param("embed_in", nn.with_logical_partitioning(_init(), ("vocab", "embed")),
                              (cfg.vocab_size, cfg.hidden_size), cfg.param_dtype)
        wte = embed_in.value if isinstance(embed_in, nn.meta.AxisMetadata) else embed_in
        from deepspeed_tpu.models.common import embed_lookup
        x = embed_lookup(wte, input_ids,
                         getattr(cfg, 'embed_onehot_grad', None), decode).astype(cfg.dtype)
        from deepspeed_tpu.runtime.zero.param_offload import stream_block_params
        block_cls = stream_block_params(GPTNeoXBlock)
        if cfg.remat:
            block_cls = nn.remat(block_cls, prevent_cse=False)
        from deepspeed_tpu.models.common import constrain_activation
        # batch-parallel residual stream over fsdp-sharded weights — see
        # constrain_activation (the ZeRO-3 weak-scaling invariant)
        x = constrain_activation(x, "batch", "length", "embed")
        for i in range(cfg.num_hidden_layers):
            x = block_cls(cfg, decode, name=f"layers_{i}")(x)
            x = constrain_activation(x, "batch", "length", "embed")
        x = nn.LayerNorm(epsilon=cfg.layer_norm_eps, dtype=cfg.dtype,
                         param_dtype=cfg.param_dtype, name="final_layer_norm")(x)
        if labels is not None and cfg.fused_head_loss_chunk > 0:
            from deepspeed_tpu.models.common import UntiedHeadKernel, fused_head_loss_output
            kernel = UntiedHeadKernel(cfg.hidden_size, cfg.vocab_size,
                                      cfg.param_dtype, name="embed_out")()
            return fused_head_loss_output(x, kernel.astype(cfg.dtype), labels,
                                          0.0, deterministic, cfg, vocab_major=False)
        return nn.Dense(features=cfg.vocab_size, use_bias=False, dtype=cfg.dtype,
                        param_dtype=cfg.param_dtype,
                        kernel_init=nn.with_logical_partitioning(_init(), ("embed", "vocab")),
                        name="embed_out")(x)
