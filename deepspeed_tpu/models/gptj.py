"""GPT-J family (6B) — interleaved partial rotary, single-LayerNorm parallel
residual (the reference serves GPT-J through kernel injection,
``module_inject/containers/gptj.py``; its rotary kernel is
``csrc/transformer/inference/csrc/apply_rotary_pos_emb.cu``).

Same TPU conventions as the rest of the zoo (logical axis names → ZeRO
planner, pluggable attention backend, flax ``cache`` collection). GPT-J
quirks kept for checkpoint parity: rotary on only the first ``rotary_dim``
of each head dim in the INTERLEAVED (rotate-every-two) convention — not the
half-split convention NeoX/LLaMA use — one shared ``ln_1`` feeding both
attention and MLP (``x + attn(ln(x)) + mlp(ln(x))``), bias-free q/k/v/out
projections, and an untied ``lm_head`` WITH bias.
"""

import dataclasses
from typing import Any, Optional

import flax.linen as nn
import jax
import jax.numpy as jnp

from deepspeed_tpu.models.common import config_from, dense_init as _init, maybe_remat
from deepspeed_tpu.ops.transformer.attention import dot_product_attention


@dataclasses.dataclass(frozen=True)
class GPTJConfig:
    vocab_size: int = 50400
    hidden_size: int = 4096
    intermediate_size: int = 16384
    num_hidden_layers: int = 28
    num_attention_heads: int = 16
    max_position_embeddings: int = 2048
    rotary_dim: int = 64
    rotary_emb_base: float = 10000.0
    layer_norm_eps: float = 1e-5
    dtype: Any = jnp.float32
    param_dtype: Any = jnp.float32
    remat: bool = False
    remat_every: int = 1
    remat_policy: Optional[str] = None
    # >0: loss via the chunked fused LM head when called with labels=
    # (models/common.py fused_lm_head_loss, bias= path) — no [B, L, V]
    # logits buffer
    fused_head_loss_chunk: int = 0
    attention_backend: str = "xla"

    @property
    def head_dim(self):
        return self.hidden_size // self.num_attention_heads


GPTJ_CONFIGS = {
    "test": dict(vocab_size=256, hidden_size=64, intermediate_size=128, num_hidden_layers=2,
                 num_attention_heads=4, max_position_embeddings=128, rotary_dim=8),
    "6b": dict(vocab_size=50400, hidden_size=4096, intermediate_size=16384,
               num_hidden_layers=28, num_attention_heads=16, rotary_dim=64),
}


def get_gptj_config(name: str, **overrides) -> GPTJConfig:
    return config_from(GPTJ_CONFIGS, GPTJConfig, name, **overrides)


def rotary_embedding_interleaved(x, positions, theta: float = 10000.0):
    """RoPE in GPT-J's interleaved (rotate-every-two) convention: pairs are
    adjacent lanes ``(2i, 2i+1)``, not split halves. ``x`` [B, L, H, D] at
    ``positions`` [B, L]."""
    d = x.shape[-1]
    inv_freq = 1.0 / (theta**(jnp.arange(0, d, 2, dtype=jnp.float32) / d))
    angles = positions[..., None].astype(jnp.float32) * inv_freq  # [B, L, D/2]
    cos = jnp.cos(angles)[:, :, None, :]  # [B, L, 1, D/2]
    sin = jnp.sin(angles)[:, :, None, :]
    xf = x.astype(jnp.float32)
    x1, x2 = xf[..., 0::2], xf[..., 1::2]
    out = jnp.stack([x1 * cos - x2 * sin, x2 * cos + x1 * sin], axis=-1)
    return out.reshape(x.shape).astype(x.dtype)


def _partial_rotary(x, positions, rotary_dim: int, base: float):
    """Interleaved RoPE on the first ``rotary_dim`` of the head dim, rest
    passes through (GPT-J convention)."""
    rot, rest = x[..., :rotary_dim], x[..., rotary_dim:]
    rot = rotary_embedding_interleaved(rot, positions, base)
    return jnp.concatenate([rot, rest], axis=-1)


class GPTJAttention(nn.Module):
    config: GPTJConfig
    decode: bool = False

    @nn.compact
    def __call__(self, x):
        cfg = self.config
        b, l, _ = x.shape

        def proj(name):
            return nn.DenseGeneral(features=(cfg.num_attention_heads, cfg.head_dim), axis=-1,
                                   use_bias=False, dtype=cfg.dtype, param_dtype=cfg.param_dtype,
                                   kernel_init=nn.with_logical_partitioning(
                                       _init(), ("embed", "heads", "kv")),
                                   name=name)(x)

        q, k, v = proj("q_proj"), proj("k_proj"), proj("v_proj")  # [B, L, H, D]
        causal, decode_lengths = True, None
        if self.decode:
            cache_index = self.variable("cache", "cache_index", lambda: jnp.zeros([], jnp.int32))
            idx = cache_index.value
            positions = idx + jnp.broadcast_to(jnp.arange(l)[None, :], (b, l))
            q = _partial_rotary(q, positions, cfg.rotary_dim, cfg.rotary_emb_base)
            k = _partial_rotary(k, positions, cfg.rotary_dim, cfg.rotary_emb_base)
            shape = (b, cfg.max_position_embeddings, cfg.num_attention_heads, cfg.head_dim)
            cached_k = self.variable("cache", "cached_key", jnp.zeros, shape, k.dtype)
            cached_v = self.variable("cache", "cached_value", jnp.zeros, shape, v.dtype)
            cached_k.value = jax.lax.dynamic_update_slice(cached_k.value, k, (0, idx, 0, 0))
            cached_v.value = jax.lax.dynamic_update_slice(cached_v.value, v, (0, idx, 0, 0))
            cache_index.value = idx + l
            k, v = cached_k.value, cached_v.value
            decode_lengths = jnp.broadcast_to(idx + l, (b,))
            causal = False
        else:
            positions = jnp.broadcast_to(jnp.arange(l)[None, :], (b, l))
            q = _partial_rotary(q, positions, cfg.rotary_dim, cfg.rotary_emb_base)
            k = _partial_rotary(k, positions, cfg.rotary_dim, cfg.rotary_emb_base)
        out = dot_product_attention(q, k, v, backend=cfg.attention_backend,
                                    causal=causal, decode_lengths=decode_lengths)
        return nn.DenseGeneral(features=cfg.hidden_size, axis=(-2, -1), use_bias=False,
                               dtype=cfg.dtype, param_dtype=cfg.param_dtype,
                               kernel_init=nn.with_logical_partitioning(_init(), ("heads", "kv", "embed")),
                               name="out_proj")(out)


class GPTJBlock(nn.Module):
    config: GPTJConfig
    decode: bool = False

    @nn.compact
    def __call__(self, x):
        cfg = self.config
        # ONE LayerNorm feeds both branches (vs NeoX's two):
        # x + attn(ln_1(x)) + mlp(ln_1(x))
        h = nn.LayerNorm(epsilon=cfg.layer_norm_eps, dtype=cfg.dtype,
                         param_dtype=cfg.param_dtype, name="ln_1")(x)
        attn_out = GPTJAttention(cfg, self.decode, name="attn")(h)
        m = nn.Dense(features=cfg.intermediate_size, dtype=cfg.dtype, param_dtype=cfg.param_dtype,
                     kernel_init=nn.with_logical_partitioning(_init(), ("embed", "mlp")),
                     bias_init=nn.with_logical_partitioning(nn.initializers.zeros, ("mlp",)),
                     name="fc_in")(h)
        m = jax.nn.gelu(m, approximate=True)  # HF GPT-J uses gelu_new (tanh)
        m = nn.Dense(features=cfg.hidden_size, dtype=cfg.dtype, param_dtype=cfg.param_dtype,
                     kernel_init=nn.with_logical_partitioning(_init(), ("mlp", "embed")),
                     bias_init=nn.with_logical_partitioning(nn.initializers.zeros, ("embed",)),
                     name="fc_out")(m)
        return x + attn_out + m


class GPTJForCausalLM(nn.Module):
    """GPT-J with UNTIED, BIASED ``lm_head``. Returns logits [B, L, V] (or
    the scalar loss when ``labels`` ride the fused head)."""

    # offload_param streaming: these block subtrees self-stream inside
    # their remat region (param_offload.stream_block_params); the engine
    # top-streams only the remaining leaves
    streamed_block_prefixes = ("h_",)


    config: GPTJConfig

    @nn.compact
    def __call__(self, input_ids, *, deterministic: bool = True, decode: bool = False,
                 labels=None):
        cfg = self.config
        wte = self.param("wte", nn.with_logical_partitioning(_init(), ("vocab", "embed")),
                         (cfg.vocab_size, cfg.hidden_size), cfg.param_dtype)
        wte = wte.value if isinstance(wte, nn.meta.AxisMetadata) else wte
        from deepspeed_tpu.models.common import embed_lookup
        x = embed_lookup(wte, input_ids,
                         getattr(cfg, 'embed_onehot_grad', None), decode).astype(cfg.dtype)
        from deepspeed_tpu.models.common import constrain_activation
        # batch-parallel residual stream over fsdp-sharded weights — see
        # constrain_activation (the ZeRO-3 weak-scaling invariant)
        x = constrain_activation(x, "batch", "length", "embed")
        for i in range(cfg.num_hidden_layers):
            block_cls = maybe_remat(GPTJBlock, cfg, i, enabled=cfg.remat and not decode)
            x = block_cls(cfg, decode, name=f"h_{i}")(x)
            x = constrain_activation(x, "batch", "length", "embed")
        x = nn.LayerNorm(epsilon=cfg.layer_norm_eps, dtype=cfg.dtype,
                         param_dtype=cfg.param_dtype, name="ln_f")(x)
        if labels is not None and cfg.fused_head_loss_chunk > 0:
            from deepspeed_tpu.models.common import (UntiedHeadKernel,
                                                     fused_head_loss_output)
            kernel, bias = UntiedHeadKernel(cfg.hidden_size, cfg.vocab_size,
                                            cfg.param_dtype, use_bias=True,
                                            name="lm_head")()
            return fused_head_loss_output(x, kernel.astype(cfg.dtype), labels, 0.0,
                                          deterministic, cfg, vocab_major=False,
                                          bias=bias.astype(cfg.dtype))
        return nn.Dense(features=cfg.vocab_size, use_bias=True, dtype=cfg.dtype,
                        param_dtype=cfg.param_dtype,
                        kernel_init=nn.with_logical_partitioning(_init(), ("embed", "vocab")),
                        bias_init=nn.with_logical_partitioning(nn.initializers.zeros, ("vocab",)),
                        name="lm_head")(x)
