"""LLaMA family — RMSNorm + RoPE + SwiGLU + GQA decoder
(judged config ladder includes LLaMA-7B ZeRO-3 + ZeRO++, BASELINE.md; the
reference supports LLaMA through kernel injection,
``module_inject/containers/llama.py``).

TPU-first notes, same conventions as ``models/gpt2.py``:
* logical axis names via ``nn.with_logical_partitioning`` drive the ZeRO
  planner (fsdp/TP shardings are derived, never hand-sliced);
* attention goes through the pluggable backend seam (xla/flash/ring);
* a flax ``cache`` collection implements incremental decoding (the role of
  the reference's KV-cache workspace,
  ``csrc/transformer/inference/includes/inference_context.h``) — static
  cache shape ``[batch, max_len, kv_heads, head_dim]`` with a scalar write
  index, jit-friendly.
"""

import dataclasses
from typing import Any, Optional

import flax.linen as nn
import jax
import jax.numpy as jnp

from deepspeed_tpu.models.common import (config_from, dense_init as _init,
                                         normalize_padding_mask, rms_norm)
from deepspeed_tpu.ops.transformer.attention import dot_product_attention


@dataclasses.dataclass(frozen=True)
class LlamaConfig:
    vocab_size: int = 32000
    hidden_size: int = 4096
    intermediate_size: int = 11008
    num_hidden_layers: int = 32
    num_attention_heads: int = 32
    num_key_value_heads: int = 32  # < num_attention_heads → GQA
    max_position_embeddings: int = 2048
    rms_norm_eps: float = 1e-6
    rope_theta: float = 10000.0
    dtype: Any = jnp.float32
    param_dtype: Any = jnp.float32
    remat: bool = False
    # jax.checkpoint policy name + selective application (same semantics as
    # GPT2Config.remat_policy/remat_every; runtime/activation_checkpointing)
    remat_policy: Optional[str] = None
    remat_every: int = 1
    attention_backend: str = "xla"
    # flash-backend block geometry / bwd policy override, as a spec string
    # (models/common.py attention_geometry_kwargs); None = resolve via
    # env/config/autotune layers
    attention_blocks: Optional[str] = None
    attention_bias: bool = False  # Qwen2-style biased q/k/v projections
    # Mistral-style sliding-window attention: each token attends the last
    # ``sliding_window`` positions. Training/prefill only — the flash
    # kernel skips out-of-window blocks (O(L*window)); decode attends the
    # whole cache (window >= cache length in practice).
    sliding_window: Optional[int] = None
    # >0: when called with ``labels=``, compute the loss via the chunked
    # fused LM head (models/common.py fused_lm_head_loss) — never
    # materializes [B, L, V] logits (32k-152k vocabs make that the
    # dominant buffer); the value is tokens per chunk
    fused_head_loss_chunk: int = 0
    # Mixtral-style sparse MoE FFN (reference GPT-MoE wiring; MoE every
    # moe_layer_freq-th layer replaces the SwiGLU MLP with experts)
    moe_num_experts: int = 0  # 0 = dense
    moe_layer_freq: int = 1   # Mixtral: every layer
    moe_k: int = 2            # Mixtral: top-2
    moe_capacity_factor: float = 1.25
    moe_eval_capacity_factor: float = 2.0  # serving must not under-provision vs training
    moe_min_capacity: int = 4
    moe_aux_loss_coef: float = 0.01
    # dispatch/combine route pin ("dense"|"sorted"); None resolves through
    # DS_MOE_ROUTE env > engine "moe" config block > default (moe/routing.py)
    moe_route: Optional[str] = None

    @property
    def head_dim(self):
        return self.hidden_size // self.num_attention_heads


LLAMA_CONFIGS = {
    "test": dict(vocab_size=256, hidden_size=64, intermediate_size=128, num_hidden_layers=2,
                 num_attention_heads=4, num_key_value_heads=2, max_position_embeddings=128),
    "160m": dict(hidden_size=768, intermediate_size=2048, num_hidden_layers=12,
                 num_attention_heads=12, num_key_value_heads=12),
    "1b": dict(hidden_size=2048, intermediate_size=5504, num_hidden_layers=24,
               num_attention_heads=16, num_key_value_heads=16),
    "7b": dict(hidden_size=4096, intermediate_size=11008, num_hidden_layers=32,
               num_attention_heads=32, num_key_value_heads=32),
    # Mistral-7B: llama blocks + GQA(8) + 14336 MLP + 4096 sliding window
    "mistral-7b": dict(vocab_size=32000, hidden_size=4096, intermediate_size=14336,
                       num_hidden_layers=32, num_attention_heads=32,
                       num_key_value_heads=8, max_position_embeddings=32768,
                       sliding_window=4096),
    "13b": dict(hidden_size=5120, intermediate_size=13824, num_hidden_layers=40,
                num_attention_heads=40, num_key_value_heads=40),
    # Mixtral-8x7B shape: llama blocks, top-2 of 8 SwiGLU experts per layer
    "mixtral-8x7b": dict(hidden_size=4096, intermediate_size=14336, num_hidden_layers=32,
                         num_attention_heads=32, num_key_value_heads=8,
                         max_position_embeddings=4096, rope_theta=1e6,
                         moe_num_experts=8, moe_k=2),
    "mixtral-test": dict(vocab_size=256, hidden_size=64, intermediate_size=128,
                         num_hidden_layers=2, num_attention_heads=4, num_key_value_heads=2,
                         max_position_embeddings=128, moe_num_experts=4, moe_k=2),
    # Qwen2 family: llama architecture + biased q/k/v projections
    "qwen2-7b": dict(vocab_size=152064, hidden_size=3584, intermediate_size=18944,
                     num_hidden_layers=28, num_attention_heads=28, num_key_value_heads=4,
                     max_position_embeddings=32768, rope_theta=1e6, attention_bias=True),
}


def get_llama_config(name: str, **overrides) -> LlamaConfig:
    return config_from(LLAMA_CONFIGS, LlamaConfig, name, **overrides)


class RMSNorm(nn.Module):
    """Root-mean-square norm (reference fused kernel
    ``csrc/transformer/inference/csrc/rms_norm.cu``; XLA fuses this)."""

    config: LlamaConfig

    @nn.compact
    def __call__(self, x):
        cfg = self.config
        w = self.param("weight", nn.with_logical_partitioning(nn.initializers.ones, ("embed",)),
                       (x.shape[-1],), cfg.param_dtype)
        w = w.value if isinstance(w, nn.meta.AxisMetadata) else w
        return rms_norm(x, w, cfg.rms_norm_eps, cfg.dtype)


def rotary_embedding(x, positions, theta: float = 10000.0):
    """Apply RoPE to ``x`` [B, L, H, D] at ``positions`` [B, L]
    (reference fused kernel ``apply_rotary_pos_emb.cu``; half-split layout)."""
    d = x.shape[-1]
    inv_freq = 1.0 / (theta**(jnp.arange(0, d, 2, dtype=jnp.float32) / d))
    angles = positions[..., None].astype(jnp.float32) * inv_freq  # [B, L, D/2]
    cos = jnp.cos(angles)[:, :, None, :]  # [B, L, 1, D/2]
    sin = jnp.sin(angles)[:, :, None, :]
    x1, x2 = jnp.split(x.astype(jnp.float32), 2, axis=-1)
    out = jnp.concatenate([x1 * cos - x2 * sin, x2 * cos + x1 * sin], axis=-1)
    return out.astype(x.dtype)


class LlamaAttention(nn.Module):
    """GQA attention with RoPE and an optional decode cache."""

    config: LlamaConfig

    @nn.compact
    def __call__(self, x, positions=None, *, decode: bool = False, attention_mask=None):
        cfg = self.config
        b, l, _ = x.shape
        n_rep = cfg.num_attention_heads // cfg.num_key_value_heads

        def proj(heads, name):
            # q/k/v projections only (o_proj is built separately, always
            # bias-free); Qwen2-style configs bias these three
            return nn.DenseGeneral(features=(heads, cfg.head_dim), axis=-1,
                                   use_bias=cfg.attention_bias,
                                   dtype=cfg.dtype, param_dtype=cfg.param_dtype,
                                   kernel_init=nn.with_logical_partitioning(_init(), ("embed", "heads", "kv")),
                                   bias_init=nn.with_logical_partitioning(nn.initializers.zeros,
                                                                          ("heads", "kv")),
                                   name=name)

        q = proj(cfg.num_attention_heads, "q_proj")(x)
        k = proj(cfg.num_key_value_heads, "k_proj")(x)
        v = proj(cfg.num_key_value_heads, "v_proj")(x)

        causal = True
        decode_lengths = None
        # attention_mask: [B, L] 0/1 padding mask (or a pre-broadcast boolean
        # mask). In decode mode L must span the cache (max_position_embeddings).
        mask = normalize_padding_mask(attention_mask)
        if decode:
            # static-shape KV cache (flax convention: cache collection)
            cached_k = self.variable("cache", "cached_key",
                                     jnp.zeros, (b, cfg.max_position_embeddings,
                                                 cfg.num_key_value_heads, cfg.head_dim), k.dtype)
            cached_v = self.variable("cache", "cached_value",
                                     jnp.zeros, (b, cfg.max_position_embeddings,
                                                 cfg.num_key_value_heads, cfg.head_dim), v.dtype)
            cache_index = self.variable("cache", "cache_index",
                                        lambda: jnp.zeros([], jnp.int32))
            idx = cache_index.value
            if positions is None:
                positions = idx + jnp.broadcast_to(jnp.arange(l)[None, :], (b, l))
            q = rotary_embedding(q, positions, cfg.rope_theta)
            k = rotary_embedding(k, positions, cfg.rope_theta)
            cached_k.value = jax.lax.dynamic_update_slice(cached_k.value, k, (0, idx, 0, 0))
            cached_v.value = jax.lax.dynamic_update_slice(cached_v.value, v, (0, idx, 0, 0))
            cache_index.value = idx + l
            k = cached_k.value
            v = cached_v.value
            # per-sequence live lengths (positions may differ per batch row);
            # the backend derives causal validity over cache slots from them —
            # flash's decode kernel additionally skips dead KV blocks' DMA.
            # Any caller padding mask rides alongside (flash falls back to
            # XLA when both are present).
            decode_lengths = positions[:, -1] + 1
            causal = False
        else:
            if positions is None:
                positions = jnp.broadcast_to(jnp.arange(l)[None, :], (b, l))
            q = rotary_embedding(q, positions, cfg.rope_theta)
            k = rotary_embedding(k, positions, cfg.rope_theta)

        if n_rep > 1:  # GQA: expand kv heads to full heads
            k = jnp.repeat(k, n_rep, axis=2)
            v = jnp.repeat(v, n_rep, axis=2)

        if cfg.sliding_window is not None and cfg.attention_backend not in ("flash", "xla"):
            # silently ignoring the window would change the model's math
            raise ValueError(f"sliding_window is supported by the flash/xla attention "
                             f"backends, not {cfg.attention_backend!r}")
        from deepspeed_tpu.models.common import attention_geometry_kwargs
        out = dot_product_attention(q, k, v, backend=cfg.attention_backend, causal=causal,
                                    mask=mask, decode_lengths=decode_lengths,
                                    window=cfg.sliding_window if not decode else None,
                                    **attention_geometry_kwargs(cfg))
        return nn.DenseGeneral(features=cfg.hidden_size, axis=(-2, -1), use_bias=False,
                               dtype=cfg.dtype, param_dtype=cfg.param_dtype,
                               kernel_init=nn.with_logical_partitioning(_init(), ("heads", "kv", "embed")),
                               name="o_proj")(out)


class LlamaMLP(nn.Module):
    """SwiGLU MLP (reference fused GEGLU/gated-mlp inference kernels,
    ``csrc/transformer/inference/csrc/gelu.cu`` fused_gemm_gelu family)."""

    config: LlamaConfig

    @nn.compact
    def __call__(self, x, deterministic: bool = True):
        cfg = self.config

        def dense(feat, names, name):
            return nn.Dense(features=feat, use_bias=False, dtype=cfg.dtype, param_dtype=cfg.param_dtype,
                            kernel_init=nn.with_logical_partitioning(_init(), names), name=name)

        gate = dense(cfg.intermediate_size, ("embed", "mlp"), "gate_proj")(x)
        up = dense(cfg.intermediate_size, ("embed", "mlp"), "up_proj")(x)
        return dense(cfg.hidden_size, ("mlp", "embed"), "down_proj")(jax.nn.silu(gate) * up)


class LlamaDecoderLayer(nn.Module):
    config: LlamaConfig
    use_moe: bool = False

    @nn.compact
    def __call__(self, x, positions=None, decode: bool = False, attention_mask=None,
                 deterministic: bool = True):
        cfg = self.config
        x = x + LlamaAttention(cfg, name="self_attn")(
            RMSNorm(cfg, name="input_layernorm")(x), positions, decode=decode,
            attention_mask=attention_mask)
        h = RMSNorm(cfg, name="post_attention_layernorm")(x)
        if self.use_moe:
            from deepspeed_tpu.moe import MoE
            moe_out, l_aux, _ = MoE(hidden_size=cfg.hidden_size,
                                    expert=LlamaMLP(cfg),
                                    num_experts=cfg.moe_num_experts,
                                    k=cfg.moe_k,
                                    capacity_factor=cfg.moe_capacity_factor,
                                    eval_capacity_factor=cfg.moe_eval_capacity_factor,
                                    min_capacity=cfg.moe_min_capacity,
                                    route=cfg.moe_route,
                                    name="moe")(h, deterministic=deterministic)
            return x + moe_out, l_aux
        return x + LlamaMLP(cfg, name="mlp")(h), jnp.zeros([], jnp.float32)


from deepspeed_tpu.models.common import init_cache  # noqa: E402  (re-export)


class LlamaForCausalLM(nn.Module):
    """LLaMA with an untied LM head. Returns logits [B, L, V].

    ``decode=True`` runs incrementally against the flax ``cache`` collection
    (pass ``mutable=["cache"]`` to ``apply``).
    """

    # offload_param streaming: these block subtrees self-stream inside
    # their remat region (param_offload.stream_block_params); the engine
    # top-streams only the remaining leaves
    streamed_block_prefixes = ("layers_",)


    config: LlamaConfig

    @nn.compact
    def __call__(self, input_ids, *, deterministic: bool = True, decode: bool = False,
                 positions=None, attention_mask=None, labels=None):
        cfg = self.config
        wte = self.param("embed_tokens", nn.with_logical_partitioning(_init(), ("vocab", "embed")),
                         (cfg.vocab_size, cfg.hidden_size), cfg.param_dtype)
        wte_value = wte.value if isinstance(wte, nn.meta.AxisMetadata) else wte
        from deepspeed_tpu.models.common import embed_lookup
        x = embed_lookup(wte_value, input_ids,
                         getattr(cfg, 'embed_onehot_grad', None), decode).astype(cfg.dtype)

        from deepspeed_tpu.models.common import constrain_activation, maybe_remat
        # residual stream stays batch-parallel over fsdp-sharded weights —
        # see constrain_activation (the ZeRO-3 weak-scaling invariant)
        x = constrain_activation(x, "batch", "length", "embed")
        aux_total = jnp.zeros([], jnp.float32)
        for i in range(cfg.num_hidden_layers):
            use_moe = (cfg.moe_num_experts > 0
                       and i % max(cfg.moe_layer_freq, 1) == max(cfg.moe_layer_freq, 1) - 1)
            block_cls = maybe_remat(LlamaDecoderLayer, cfg, i, static_argnums=(3, 5),
                                    enabled=cfg.remat and not decode)
            x, l_aux = block_cls(cfg, use_moe, name=f"layers_{i}")(
                x, positions, decode, attention_mask, deterministic)
            x = constrain_activation(x, "batch", "length", "embed")
            aux_total = aux_total + l_aux
        x = RMSNorm(cfg, name="norm")(x)
        if labels is not None and cfg.fused_head_loss_chunk > 0:
            # chunked fused head on the [E, V] Dense kernel — same param
            # path ("lm_head"/"kernel") as the unfused branch, so
            # checkpoints and HF converters are unaffected (shift/aux
            # policy lives in fused_head_loss_output, shared across
            # families)
            from deepspeed_tpu.models.common import UntiedHeadKernel, fused_head_loss_output
            kernel = UntiedHeadKernel(cfg.hidden_size, cfg.vocab_size,
                                      cfg.param_dtype, name="lm_head")()
            return fused_head_loss_output(x, kernel.astype(cfg.dtype), labels,
                                          aux_total, deterministic, cfg,
                                          vocab_major=False)
        # logits at compute dtype: the loss reduces in fp32 (PERF.md #2)
        logits = nn.Dense(features=cfg.vocab_size, use_bias=False, dtype=cfg.dtype,
                          param_dtype=cfg.param_dtype,
                          kernel_init=nn.with_logical_partitioning(_init(), ("embed", "vocab")),
                          name="lm_head")(x)
        if cfg.moe_num_experts > 0:
            return logits, aux_total * cfg.moe_aux_loss_coef
        return logits
