"""OPT family — decoder with learned positions (offset 2) and ReLU MLP
(the reference serves OPT through kernel injection,
``module_inject/containers/opt.py``; HF ``OPTForCausalLM`` is the
checkpoint source).

Same TPU conventions as ``models/gpt2.py``: logical axis names drive the
ZeRO planner, attention goes through the pluggable backend seam
(xla/flash with ``decode_lengths`` for KV-cache decode), and a flax
``cache`` collection holds the static-shape decode state.

OPT quirks kept for checkpoint parity: positions are looked up at
``position + 2`` (HF ``OPTLearnedPositionalEmbedding`` offset), q/k/v/out
projections carry biases, 350m-style checkpoints project embeddings
through ``project_in``/``project_out`` when ``word_embed_proj_dim`` differs
from ``hidden_size``, and ``do_layer_norm_before`` selects pre- vs post-LN
blocks.
"""

import dataclasses
from typing import Any, Optional

import flax.linen as nn
import jax
import jax.numpy as jnp

from deepspeed_tpu.models.common import config_from, dense_init as _init
from deepspeed_tpu.ops.transformer.attention import dot_product_attention


@dataclasses.dataclass(frozen=True)
class OPTConfig:
    vocab_size: int = 50272
    hidden_size: int = 768
    ffn_dim: int = 3072
    num_hidden_layers: int = 12
    num_attention_heads: int = 12
    max_position_embeddings: int = 2048
    word_embed_proj_dim: Optional[int] = None  # != hidden_size → project_in/out
    do_layer_norm_before: bool = True
    layer_norm_eps: float = 1e-5
    dtype: Any = jnp.float32
    param_dtype: Any = jnp.float32
    remat: bool = False
    attention_backend: str = "xla"
    # >0: loss via the chunked fused LM head when called with labels=
    # (models/common.py fused_lm_head_loss) — no [B, L, V] logits buffer
    fused_head_loss_chunk: int = 0

    @property
    def head_dim(self):
        return self.hidden_size // self.num_attention_heads

    @property
    def embed_dim(self):
        return self.word_embed_proj_dim or self.hidden_size

    @property
    def has_embed_proj(self) -> bool:
        """project_in/out exist only when the embedding width differs from
        the hidden width (HF sets word_embed_proj_dim == hidden_size for all
        non-350m checkpoints — that means NO projection layers)."""
        return self.word_embed_proj_dim not in (None, self.hidden_size)


OPT_CONFIGS = {
    "test": dict(vocab_size=256, hidden_size=64, ffn_dim=128, num_hidden_layers=2,
                 num_attention_heads=4, max_position_embeddings=128),
    "125m": dict(hidden_size=768, ffn_dim=3072, num_hidden_layers=12, num_attention_heads=12),
    "350m": dict(hidden_size=1024, ffn_dim=4096, num_hidden_layers=24, num_attention_heads=16,
                 word_embed_proj_dim=512, do_layer_norm_before=False),
    "1.3b": dict(hidden_size=2048, ffn_dim=8192, num_hidden_layers=24, num_attention_heads=32),
    "6.7b": dict(hidden_size=4096, ffn_dim=16384, num_hidden_layers=32, num_attention_heads=32),
}

POSITION_OFFSET = 2  # HF OPTLearnedPositionalEmbedding.offset


def get_opt_config(name: str, **overrides) -> OPTConfig:
    return config_from(OPT_CONFIGS, OPTConfig, name, **overrides)


class OPTAttention(nn.Module):
    config: OPTConfig
    decode: bool = False

    @nn.compact
    def __call__(self, x):
        cfg = self.config

        def proj(name):
            return nn.DenseGeneral(features=(cfg.num_attention_heads, cfg.head_dim), axis=-1,
                                   dtype=cfg.dtype, param_dtype=cfg.param_dtype,
                                   kernel_init=nn.with_logical_partitioning(_init(), ("embed", "heads", "kv")),
                                   bias_init=nn.with_logical_partitioning(nn.initializers.zeros, ("heads", "kv")),
                                   name=name)

        q = proj("q_proj")(x)
        k = proj("k_proj")(x)
        v = proj("v_proj")(x)
        causal, decode_lengths = True, None
        if self.decode:
            b, l = x.shape[0], x.shape[1]
            shape = (b, cfg.max_position_embeddings, cfg.num_attention_heads, cfg.head_dim)
            cached_k = self.variable("cache", "cached_key", jnp.zeros, shape, k.dtype)
            cached_v = self.variable("cache", "cached_value", jnp.zeros, shape, v.dtype)
            cache_index = self.variable("cache", "cache_index", lambda: jnp.zeros([], jnp.int32))
            idx = cache_index.value
            cached_k.value = jax.lax.dynamic_update_slice(cached_k.value, k, (0, idx, 0, 0))
            cached_v.value = jax.lax.dynamic_update_slice(cached_v.value, v, (0, idx, 0, 0))
            cache_index.value = idx + l
            k, v = cached_k.value, cached_v.value
            decode_lengths = jnp.broadcast_to(idx + l, (b,))
            causal = False
        out = dot_product_attention(q, k, v, backend=cfg.attention_backend,
                                    causal=causal, decode_lengths=decode_lengths)
        return nn.DenseGeneral(features=cfg.hidden_size, axis=(-2, -1),
                               dtype=cfg.dtype, param_dtype=cfg.param_dtype,
                               kernel_init=nn.with_logical_partitioning(_init(), ("heads", "kv", "embed")),
                               bias_init=nn.with_logical_partitioning(nn.initializers.zeros, ("embed",)),
                               name="out_proj")(out)


class OPTBlock(nn.Module):
    config: OPTConfig
    decode: bool = False

    @nn.compact
    def __call__(self, x):
        cfg = self.config
        ln = lambda name: nn.LayerNorm(epsilon=cfg.layer_norm_eps, dtype=cfg.dtype,
                                       param_dtype=cfg.param_dtype, name=name)
        h = x
        if cfg.do_layer_norm_before:
            h = ln("self_attn_layer_norm")(h)
        h = OPTAttention(cfg, self.decode, name="self_attn")(h)
        x = x + h
        if not cfg.do_layer_norm_before:
            x = ln("self_attn_layer_norm")(x)

        h = x
        if cfg.do_layer_norm_before:
            h = ln("final_layer_norm")(h)
        h = nn.Dense(features=cfg.ffn_dim, dtype=cfg.dtype, param_dtype=cfg.param_dtype,
                     kernel_init=nn.with_logical_partitioning(_init(), ("embed", "mlp")),
                     bias_init=nn.with_logical_partitioning(nn.initializers.zeros, ("mlp",)),
                     name="fc1")(h)
        h = jax.nn.relu(h)
        h = nn.Dense(features=cfg.hidden_size, dtype=cfg.dtype, param_dtype=cfg.param_dtype,
                     kernel_init=nn.with_logical_partitioning(_init(), ("mlp", "embed")),
                     bias_init=nn.with_logical_partitioning(nn.initializers.zeros, ("embed",)),
                     name="fc2")(h)
        x = x + h
        if not cfg.do_layer_norm_before:
            x = ln("final_layer_norm")(x)
        return x


class OPTForCausalLM(nn.Module):
    """OPT with tied-embedding LM head. Returns logits [B, L, V]."""

    # offload_param streaming: these block subtrees self-stream inside
    # their remat region (param_offload.stream_block_params); the engine
    # top-streams only the remaining leaves
    streamed_block_prefixes = ("layers_",)


    config: OPTConfig

    @nn.compact
    def __call__(self, input_ids, *, deterministic: bool = True, decode: bool = False,
                 labels=None):
        cfg = self.config
        embed_tokens = self.param(
            "embed_tokens", nn.with_logical_partitioning(_init(), ("vocab", "embed")),
            (cfg.vocab_size, cfg.embed_dim), cfg.param_dtype)
        embed_positions = self.param(
            "embed_positions", nn.with_logical_partitioning(_init(0.01), (None, "embed")),
            (cfg.max_position_embeddings + POSITION_OFFSET, cfg.hidden_size), cfg.param_dtype)
        wte = embed_tokens.value if isinstance(embed_tokens, nn.meta.AxisMetadata) else embed_tokens
        wpe = embed_positions.value if isinstance(embed_positions, nn.meta.AxisMetadata) else embed_positions

        b, l = input_ids.shape
        from deepspeed_tpu.models.common import embed_lookup
        x = embed_lookup(wte, input_ids,
                         getattr(cfg, 'embed_onehot_grad', None), decode).astype(cfg.dtype)
        if cfg.has_embed_proj:
            x = nn.Dense(features=cfg.hidden_size, use_bias=False, dtype=cfg.dtype,
                         param_dtype=cfg.param_dtype,
                         kernel_init=nn.with_logical_partitioning(_init(), ("embed", "mlp")),
                         name="project_in")(x)
        if decode:
            pos_idx = self.variable("cache", "position_index", lambda: jnp.zeros([], jnp.int32))
            positions = pos_idx.value + jnp.arange(l)
            pos_idx.value = pos_idx.value + l
            x = x + jnp.take(wpe, positions + POSITION_OFFSET, axis=0).astype(cfg.dtype)[None]
        else:
            x = x + wpe[POSITION_OFFSET:POSITION_OFFSET + l].astype(cfg.dtype)

        from deepspeed_tpu.runtime.zero.param_offload import stream_block_params
        block_cls = stream_block_params(OPTBlock)
        if cfg.remat:
            block_cls = nn.remat(block_cls, prevent_cse=False)
        from deepspeed_tpu.models.common import constrain_activation
        # batch-parallel residual stream over fsdp-sharded weights — see
        # constrain_activation (the ZeRO-3 weak-scaling invariant)
        x = constrain_activation(x, "batch", "length", "embed")
        for i in range(cfg.num_hidden_layers):
            x = block_cls(cfg, decode, name=f"layers_{i}")(x)
            x = constrain_activation(x, "batch", "length", "embed")
        if cfg.do_layer_norm_before:
            x = nn.LayerNorm(epsilon=cfg.layer_norm_eps, dtype=cfg.dtype,
                             param_dtype=cfg.param_dtype, name="final_layer_norm")(x)
        if cfg.has_embed_proj:
            x = nn.Dense(features=cfg.embed_dim, use_bias=False, dtype=cfg.dtype,
                         param_dtype=cfg.param_dtype,
                         kernel_init=nn.with_logical_partitioning(_init(), ("mlp", "embed")),
                         name="project_out")(x)
        if labels is not None and cfg.fused_head_loss_chunk > 0:
            from deepspeed_tpu.models.common import fused_head_loss_output
            return fused_head_loss_output(x, wte.astype(cfg.dtype), labels,
                                          0.0, deterministic, cfg, vocab_major=True)
        return jnp.einsum("ble,ve->blv", x, wte.astype(cfg.dtype),
                          preferred_element_type=cfg.dtype)
