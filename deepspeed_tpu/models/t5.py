"""T5 family — encoder-decoder with relative position biases (the
reference serves T5 through kernel injection,
``module_inject/containers`` T5-style policies; HF
``T5ForConditionalGeneration`` is the checkpoint source).

Same TPU conventions as the rest of the zoo (logical axis names → ZeRO
planner, ``cache`` collection for decoder self-attention). T5 quirks kept
for checkpoint parity: RMS layer norm without bias, UNSCALED attention
(no 1/sqrt(d)), a learned relative-position bias computed by the FIRST
layer of each stack and shared down the stack, ReLU (v1.0) or gated-GELU
(v1.1) feed-forward, and logits scaled by d_model^-0.5 when the head is
tied to the shared embedding.

Cross-attention K/V are projected from the encoder output on every decode
step (encoder sequences are short relative to generation length; a
cached-projection variant belongs with paged serving if profiling asks).
"""

import dataclasses
from typing import Any, Optional

import flax.linen as nn
import jax
import jax.numpy as jnp

from deepspeed_tpu.models.common import config_from, dense_init as _init, rms_norm


@dataclasses.dataclass(frozen=True)
class T5Config:
    vocab_size: int = 32128
    d_model: int = 512
    d_kv: int = 64
    d_ff: int = 2048
    num_layers: int = 6
    num_decoder_layers: Optional[int] = None
    num_heads: int = 8
    relative_attention_num_buckets: int = 32
    relative_attention_max_distance: int = 128
    layer_norm_epsilon: float = 1e-6
    feed_forward_proj: str = "relu"  # "relu" (t5) | "gated-gelu" (t5 v1.1)
    max_cache_length: int = 512  # decoder self-attention cache capacity
    tie_word_embeddings: bool = True
    decoder_start_token_id: int = 0  # T5 seeds decoding from pad (HF convention)
    dtype: Any = jnp.float32
    param_dtype: Any = jnp.float32
    remat: bool = False

    @property
    def n_dec_layers(self):
        return self.num_decoder_layers if self.num_decoder_layers is not None else self.num_layers

    @property
    def is_gated(self):
        return self.feed_forward_proj.startswith("gated")


T5_CONFIGS = {
    "test": dict(vocab_size=256, d_model=64, d_kv=16, d_ff=128, num_layers=2, num_heads=4),
    "small": dict(d_model=512, d_kv=64, d_ff=2048, num_layers=6, num_heads=8),
    "base": dict(d_model=768, d_kv=64, d_ff=3072, num_layers=12, num_heads=12),
    "large": dict(d_model=1024, d_kv=64, d_ff=4096, num_layers=24, num_heads=16),
    "3b": dict(d_model=1024, d_kv=128, d_ff=16384, num_layers=24, num_heads=32),
}


def get_t5_config(name: str, **overrides) -> T5Config:
    return config_from(T5_CONFIGS, T5Config, name, **overrides)


def relative_position_bucket(relative_position, bidirectional: bool,
                             num_buckets: int, max_distance: int):
    """The standard T5 log-bucketing of relative positions."""
    ret = 0
    n = -relative_position
    if bidirectional:
        num_buckets //= 2
        ret += (n < 0).astype(jnp.int32) * num_buckets
        n = jnp.abs(n)
    else:
        n = jnp.maximum(n, 0)
    max_exact = num_buckets // 2
    is_small = n < max_exact
    val_if_large = max_exact + (
        jnp.log(n.astype(jnp.float32) / max_exact + 1e-6)
        / jnp.log(max_distance / max_exact) * (num_buckets - max_exact)).astype(jnp.int32)
    val_if_large = jnp.minimum(val_if_large, num_buckets - 1)
    return ret + jnp.where(is_small, n, val_if_large)


class T5LayerNorm(nn.Module):
    """RMS norm, no bias, no mean subtraction (T5 convention)."""

    config: T5Config

    @nn.compact
    def __call__(self, x):
        cfg = self.config
        w = self.param("weight", nn.with_logical_partitioning(nn.initializers.ones, ("embed",)),
                       (x.shape[-1],), cfg.param_dtype)
        w = w.value if isinstance(w, nn.meta.AxisMetadata) else w
        return rms_norm(x, w, cfg.layer_norm_epsilon, cfg.dtype)


class T5Attention(nn.Module):
    """Unscaled multi-head attention with optional relative-position bias.
    ``kv`` (cross-attention source) defaults to ``x``; ``decode`` is a CALL
    argument so the same parameters serve full and incremental passes."""

    config: T5Config
    has_relative_bias: bool = False
    bidirectional: bool = True
    cache_name: str = "self"

    def _rel_bias(self, q_len, k_len, q_offset):
        cfg = self.config
        rel_embed = self.param(
            "relative_attention_bias",
            nn.with_logical_partitioning(_init(), (None, "heads")),
            (cfg.relative_attention_num_buckets, cfg.num_heads), cfg.param_dtype)
        rel_embed = rel_embed.value if isinstance(rel_embed, nn.meta.AxisMetadata) else rel_embed
        ctx = jnp.arange(q_len)[:, None] + q_offset
        mem = jnp.arange(k_len)[None, :]
        buckets = relative_position_bucket(mem - ctx, self.bidirectional,
                                           cfg.relative_attention_num_buckets,
                                           cfg.relative_attention_max_distance)
        bias = jnp.take(rel_embed, buckets, axis=0)  # [q, k, heads]
        return bias.transpose(2, 0, 1)[None]  # [1, heads, q, k]

    @nn.compact
    def __call__(self, x, kv=None, mask=None, position_bias=None, decode: bool = False):
        cfg = self.config
        kv = x if kv is None else kv
        b, lq = x.shape[0], x.shape[1]

        def proj(name, src):
            return nn.DenseGeneral(features=(cfg.num_heads, cfg.d_kv), axis=-1, use_bias=False,
                                   dtype=cfg.dtype, param_dtype=cfg.param_dtype,
                                   kernel_init=nn.with_logical_partitioning(
                                       _init(), ("embed", "heads", "kv")),
                                   name=name)(src)

        q = proj("q", x)
        k = proj("k", kv)
        v = proj("v", kv)
        q_offset = 0
        if decode and self.cache_name == "self":
            shape = (b, cfg.max_cache_length, cfg.num_heads, cfg.d_kv)
            cached_k = self.variable("cache", "cached_key", jnp.zeros, shape, k.dtype)
            cached_v = self.variable("cache", "cached_value", jnp.zeros, shape, v.dtype)
            cache_index = self.variable("cache", "cache_index", lambda: jnp.zeros([], jnp.int32))
            idx = cache_index.value
            cached_k.value = jax.lax.dynamic_update_slice(cached_k.value, k, (0, idx, 0, 0))
            cached_v.value = jax.lax.dynamic_update_slice(cached_v.value, v, (0, idx, 0, 0))
            cache_index.value = idx + lq
            k, v = cached_k.value, cached_v.value
            q_offset = idx
        lk = k.shape[1]

        if position_bias is None and self.has_relative_bias:
            position_bias = self._rel_bias(lq, lk, q_offset)
        # UNSCALED scores (T5: scaling folded into init)
        scores = jnp.einsum("bqhd,bkhd->bhqk", q, k,
                            preferred_element_type=jnp.float32)
        if position_bias is not None:
            scores = scores + position_bias.astype(jnp.float32)
        neg = jnp.finfo(jnp.float32).min
        if decode and self.cache_name == "self":
            valid = jnp.arange(lk)[None, None, None, :] <= (q_offset + jnp.arange(lq))[None, None, :, None]
            scores = jnp.where(valid, scores, neg)
        elif not self.bidirectional:
            causal = jnp.arange(lq)[:, None] >= jnp.arange(lk)[None, :]
            scores = jnp.where(causal[None, None], scores, neg)
        if mask is not None:
            scores = jnp.where(mask, scores, neg)
        probs = jax.nn.softmax(scores, axis=-1).astype(v.dtype)
        out = jnp.einsum("bhqk,bkhd->bqhd", probs, v)
        out = nn.DenseGeneral(features=cfg.d_model, axis=(-2, -1), use_bias=False,
                              dtype=cfg.dtype, param_dtype=cfg.param_dtype,
                              kernel_init=nn.with_logical_partitioning(
                                  _init(), ("heads", "kv", "embed")),
                              name="o")(out)
        return out, position_bias


class T5FF(nn.Module):
    config: T5Config

    @nn.compact
    def __call__(self, x):
        cfg = self.config
        dense = lambda feat, name, axes: nn.Dense(
            features=feat, use_bias=False, dtype=cfg.dtype, param_dtype=cfg.param_dtype,
            kernel_init=nn.with_logical_partitioning(_init(), axes), name=name)
        if cfg.is_gated:
            # HF "gated-gelu" is NewGELU (tanh approximation)
            h = jax.nn.gelu(dense(cfg.d_ff, "wi_0", ("embed", "mlp"))(x), approximate=True) \
                * dense(cfg.d_ff, "wi_1", ("embed", "mlp"))(x)
        else:
            h = jax.nn.relu(dense(cfg.d_ff, "wi", ("embed", "mlp"))(x))
        return dense(cfg.d_model, "wo", ("mlp", "embed"))(h)


class T5Block(nn.Module):
    config: T5Config
    is_decoder: bool = False
    has_relative_bias: bool = False

    @nn.compact
    def __call__(self, x, enc=None, self_bias=None, enc_mask=None, decode: bool = False):
        cfg = self.config
        h, self_bias = T5Attention(cfg, has_relative_bias=self.has_relative_bias,
                                   bidirectional=not self.is_decoder,
                                   cache_name="self",
                                   name="SelfAttention")(
            T5LayerNorm(cfg, name="ln_self")(x), position_bias=self_bias, decode=decode)
        x = x + h
        if self.is_decoder:
            h, _ = T5Attention(cfg, bidirectional=True, cache_name="cross",
                               name="EncDecAttention")(
                T5LayerNorm(cfg, name="ln_cross")(x), kv=enc, mask=enc_mask)
            x = x + h
        x = x + T5FF(cfg, name="ff")(T5LayerNorm(cfg, name="ln_ff")(x))
        return x, self_bias


class T5Stack(nn.Module):
    config: T5Config
    is_decoder: bool = False

    @nn.compact
    def __call__(self, x, enc=None, enc_mask=None, decode: bool = False):
        cfg = self.config
        n = cfg.n_dec_layers if self.is_decoder else cfg.num_layers
        bias = None
        from deepspeed_tpu.runtime.zero.param_offload import stream_block_params
        block_cls = stream_block_params(T5Block)
        if cfg.remat:
            # decode is arg index 5 of T5Block.__call__ (static python bool)
            block_cls = nn.remat(block_cls, static_argnums=(5,), prevent_cse=False)
        from deepspeed_tpu.models.common import constrain_activation
        # batch-parallel residual stream over fsdp-sharded weights — see
        # constrain_activation (the ZeRO-3 weak-scaling invariant)
        x = constrain_activation(x, "batch", "length", "embed")
        for i in range(n):
            x, bias = block_cls(cfg, self.is_decoder, has_relative_bias=(i == 0),
                                name=f"block_{i}")(
                x, enc, bias, enc_mask, decode)
            x = constrain_activation(x, "batch", "length", "embed")
        return T5LayerNorm(cfg, name="final_layer_norm")(x)


class T5ForConditionalGeneration(nn.Module):
    """Encoder-decoder LM. ``__call__(input_ids, decoder_input_ids)`` →
    logits; ``decode=True`` runs incremental decoder steps against a cached
    self-attention state (``encoder_outputs`` must then be supplied)."""

    # offload_param streaming: these block subtrees self-stream inside
    # their remat region (param_offload.stream_block_params); the engine
    # top-streams only the remaining leaves
    streamed_block_prefixes = ("block_",)


    config: T5Config

    def setup(self):
        cfg = self.config
        self.shared = self.param("shared", nn.with_logical_partitioning(_init(), ("vocab", "embed")),
                                 (cfg.vocab_size, cfg.d_model), cfg.param_dtype)
        self.encoder = T5Stack(cfg, is_decoder=False, name="encoder")
        self.decoder = T5Stack(cfg, is_decoder=True, name="decoder")
        if not cfg.tie_word_embeddings:
            self.lm_head = nn.Dense(features=cfg.vocab_size, use_bias=False,
                                    dtype=cfg.dtype, param_dtype=cfg.param_dtype,
                                    kernel_init=nn.with_logical_partitioning(
                                        _init(), ("embed", "vocab")),
                                    name="lm_head")

    def _embed(self, ids, decode=False):
        from deepspeed_tpu.models.common import embed_lookup
        w = self.shared.value if isinstance(self.shared, nn.meta.AxisMetadata) else self.shared
        return embed_lookup(w, ids, getattr(self.config, 'embed_onehot_grad', None),
                            decode).astype(self.config.dtype)

    def _head(self, x):
        cfg = self.config
        if cfg.tie_word_embeddings:
            w = self.shared.value if isinstance(self.shared, nn.meta.AxisMetadata) else self.shared
            # tied head scales activations by d_model^-0.5 (HF convention)
            return jnp.einsum("ble,ve->blv", x * (cfg.d_model ** -0.5),
                              w.astype(cfg.dtype), preferred_element_type=cfg.dtype)
        return self.lm_head(x)

    def encode(self, input_ids):
        return self.encoder(self._embed(input_ids))

    def __call__(self, input_ids=None, decoder_input_ids=None, *,
                 encoder_outputs=None, decode: bool = False, deterministic: bool = True):
        if encoder_outputs is None:
            encoder_outputs = self.encode(input_ids)
        x = self.decoder(self._embed(decoder_input_ids, decode=decode), enc=encoder_outputs, decode=decode)
        return self._head(x)
