"""Module injection: inference kernel policies, AutoTP, HF weight loading
(reference ``deepspeed/module_inject/``)."""

from deepspeed_tpu.module_inject.auto_tp import AutoTP
from deepspeed_tpu.module_inject.layers import LinearAllreduce, LinearLayer
from deepspeed_tpu.module_inject.load_checkpoint import (load_hf_checkpoint, load_hf_gpt2,
                                                         load_hf_llama, load_hf_opt,
                                                         load_hf_gpt_neox, load_hf_bloom, load_hf_t5,
                                                         load_hf_falcon, load_hf_gptj,
                                                         load_hf_bert, load_hf_distilbert,
                                                         load_hf_gpt_neo, load_hf_clip_text)
from deepspeed_tpu.module_inject.from_hf import from_hf
from deepspeed_tpu.module_inject.replace_module import (generic_injection, replace_transformer_layer,
                                                        tp_shard_params)

__all__ = ["AutoTP", "from_hf", "LinearAllreduce", "LinearLayer", "load_hf_checkpoint", "load_hf_gpt2", "load_hf_llama", "load_hf_opt", "load_hf_gpt_neox", "load_hf_bloom", "load_hf_t5", "load_hf_falcon", "load_hf_gptj", "load_hf_bert", "load_hf_distilbert", "load_hf_gpt_neo", "load_hf_clip_text", "generic_injection",
           "replace_transformer_layer", "tp_shard_params"]
