"""Module injection: inference kernel policies, AutoTP, HF weight loading
(reference ``deepspeed/module_inject/``)."""

from deepspeed_tpu.module_inject.auto_tp import AutoTP
from deepspeed_tpu.module_inject.layers import LinearAllreduce, LinearLayer
from deepspeed_tpu.module_inject.load_checkpoint import (load_hf_checkpoint, load_hf_gpt2,
                                                         load_hf_llama, load_hf_opt,
                                                         load_hf_gpt_neox, load_hf_bloom, load_hf_t5,
                                                         load_hf_falcon, load_hf_gptj,
                                                         load_hf_bert, load_hf_distilbert,
                                                         load_hf_gpt_neo, load_hf_clip_text)
from deepspeed_tpu.module_inject.from_hf import from_hf
from deepspeed_tpu.module_inject.replace_module import (generic_injection, replace_transformer_layer,
                                                        revert_transformer_layer, tp_shard_params)
from deepspeed_tpu.module_inject.replace_policy import (BLOOMLayerPolicy, DSPolicy,
                                                        GPTNEOXLayerPolicy, HFBertLayerPolicy,
                                                        HFCLIPLayerPolicy, HFDistilBertLayerPolicy,
                                                        HFGPT2LayerPolicy, HFGPTJLayerPolicy,
                                                        HFGPTNEOLayerPolicy, HFOPTLayerPolicy,
                                                        LLAMALayerPolicy, MegatronLayerPolicy,
                                                        UNetPolicy, VAEPolicy,
                                                        generic_policies, replace_policies)

__all__ = ["AutoTP", "from_hf", "LinearAllreduce", "LinearLayer", "load_hf_checkpoint", "load_hf_gpt2", "load_hf_llama", "load_hf_opt", "load_hf_gpt_neox", "load_hf_bloom", "load_hf_t5", "load_hf_falcon", "load_hf_gptj", "load_hf_bert", "load_hf_distilbert", "load_hf_gpt_neo", "load_hf_clip_text", "generic_injection",
           "replace_transformer_layer", "revert_transformer_layer", "tp_shard_params",
           "DSPolicy", "HFBertLayerPolicy", "HFGPT2LayerPolicy", "LLAMALayerPolicy",
           "BLOOMLayerPolicy", "GPTNEOXLayerPolicy", "HFCLIPLayerPolicy",
           "HFDistilBertLayerPolicy", "HFGPTJLayerPolicy", "HFGPTNEOLayerPolicy",
           "HFOPTLayerPolicy", "MegatronLayerPolicy", "UNetPolicy", "VAEPolicy",
           "replace_policies", "generic_policies"]
