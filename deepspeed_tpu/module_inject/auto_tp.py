"""Automatic tensor parallelism for unannotated models
(reference ``module_inject/auto_tp.py:13`` ``AutoTP``).

The reference walks the torch module graph classifying ``nn.Linear`` layers
into column-parallel (shard output dim) vs row-parallel (shard input dim,
all-reduce output) and slices weights. Here models already computed are flax
param pytrees; ``AutoTP`` classifies each 2-D+ kernel by its *path name*
using the same layer vocabulary the reference's parser learns from
supported architectures, and emits a ``PartitionSpec`` tree — XLA inserts
the (all-gather / all-reduce) collectives a Megatron layout implies.
"""

from typing import Optional, Sequence

import numpy as np

import jax
from jax.sharding import PartitionSpec as P

from deepspeed_tpu.parallel.topology import TENSOR_AXIS

# layer-name vocabulary → Megatron role (reference auto_tp.py builds this by
# parsing supported HF architectures; kept explicit here)
COLUMN_PARALLEL_NAMES = (
    # attention input projections and MLP up-projections: shard the OUTPUT dim
    "q_proj", "k_proj", "v_proj", "query", "key", "value", "c_attn", "query_key_value",
    "gate_proj", "up_proj", "c_fc", "fc1", "wi", "intermediate", "dense_h_to_4h",
)
ROW_PARALLEL_NAMES = (
    # attention output and MLP down-projections: shard the INPUT dim,
    # all-reduce the output (reference LinearAllreduce, module_inject/layers.py:15)
    "o_proj", "out_proj", "down_proj", "c_proj", "fc2", "wo", "dense_4h_to_h",
)
VOCAB_PARALLEL_NAMES = ("wte", "embed_tokens", "word_embeddings", "lm_head", "embed_out")


from deepspeed_tpu.utils.tree import keypath_parts as _path_parts  # shared stringification


class AutoTP:
    """Classify params into TP shardings by path (reference ``AutoTP``)."""

    _warned: set = set()

    @staticmethod
    def _warn_unmatched(path: str, shape) -> None:
        if path not in AutoTP._warned:
            AutoTP._warned.add(path)
            from deepspeed_tpu.utils.logging import logger
            logger.warning(f"AutoTP: no sharding rule matched {path!r} {tuple(shape)}; "
                           f"the param stays REPLICATED — if this is a projection of an "
                           f"unrecognized naming convention, pass an injection_policy "
                           f"(reference auto_tp.py parses module graphs here)")

    @staticmethod
    def classify(path_parts: Sequence[str]) -> Optional[str]:
        for part in path_parts:
            low = part.lower()
            if any(n == low or low.endswith(n) for n in ROW_PARALLEL_NAMES):
                return "row"
            if any(n == low or low.endswith(n) for n in COLUMN_PARALLEL_NAMES):
                return "column"
            if any(n == low or low.endswith(n) for n in VOCAB_PARALLEL_NAMES):
                return "vocab"
        return None

    @staticmethod
    def normalize_policy(policy) -> list:
        """User ``injection_policy`` → [(path_substring, role), ...].

        Accepts both forms: the reference's
        ``{ModuleClass_or_name: ("attn.out_proj", ...)}`` where the tuple
        lists the projections whose output needs an all-reduce (row
        parallel — reference ``LinearAllreduce``, ``auto_tp.py:13``), and
        the explicit ``{"path.substring": "row"|"column"|"vocab"|
        "replicate"}`` mapping."""
        rules = []

        def add(substr, role, origin):
            if role not in ("row", "column", "vocab", "replicate"):
                raise ValueError(f"injection_policy role {role!r} for {origin!r}: expected "
                                 "'row', 'column', 'vocab' or 'replicate'")
            rules.append((str(substr), role))

        for key, val in (policy or {}).items():
            if hasattr(val, "tp_rules"):
                # a replace_policy.DSPolicy (class or instance): expand its
                # per-arch role mapping (same role validation as strings)
                expanded = val.tp_rules()
                if not expanded:
                    from deepspeed_tpu.utils.logging import logger
                    logger.warning(f"injection_policy {getattr(val, '__name__', val)!r} for "
                                   f"{key!r} carries no TP rules (generic/spatial policy) — "
                                   f"it does not change any weight layout")
                for substr, role in expanded.items():
                    add(substr, role, val)
                continue
            if isinstance(val, str):
                add(key, val, key)
            else:
                for name in (val if isinstance(val, (tuple, list)) else (val,)):
                    add(name, "row", key)
        # most-specific (longest) substring wins: {"attn": "row",
        # "attn.c_attn": "column"} must let the second rule reach c_attn
        rules.sort(key=lambda r: len(r[0]), reverse=True)
        return rules

    @staticmethod
    def warn_unmatched_policy(params, rules: list) -> None:
        """Warn for policy rules that matched NO param path — the escape
        hatch must not fail open silently (typos, torch-style paths)."""
        if not rules:
            return
        all_parts = []
        jax.tree_util.tree_map_with_path(
            lambda path, leaf: all_parts.append(_path_parts(path)), params)
        from deepspeed_tpu.utils.logging import logger
        for substr, role in rules:
            # same matcher the rules are applied with (policy_role), so a
            # rule that would silently no-op is exactly what warns
            if not any(AutoTP.policy_role(parts, [(substr, role)]) is not None
                       for parts in all_parts):
                sample = "/".join(all_parts[0]) if all_parts else "<empty>"
                logger.warning(f"injection_policy rule {substr!r} -> {role} matched no "
                               f"param path; the override did NOT apply (param paths "
                               f"look like {sample!r})")

    @staticmethod
    def policy_role(path_parts: Sequence[str], rules: list) -> Optional[str]:
        """Match policy rules against a param path. Multi-part rules
        ("attn/c_proj", "attention.output.dense") substring-match the
        joined path; single-token rules ("query", "value") match whole
        path PARTS (exact or suffix, like :meth:`classify`) — raw
        containment would turn e.g. "value" into a trap for any path
        containing "value_head" or "key_value_cache"."""
        low_parts = [p.lower() for p in path_parts]
        path = "/".join(low_parts)
        dotted = path.replace("/", ".")
        for substr, role in rules:
            s = substr.lower()
            if "/" in s or "." in s:
                if s in path or s in dotted:
                    return role
            elif any(p == s or p.endswith(s) for p in low_parts):
                # same suffix semantics as classify()'s built-in vocabulary
                return role
        return None

    @staticmethod
    def spec_for(path_parts: Sequence[str], shape: Sequence[int], tp_size: int,
                 policy_rules: Optional[list] = None) -> P:
        """PartitionSpec for one param. Kernels are [in, ..., out] (flax
        convention); biases follow the output dim of their layer."""
        if tp_size <= 1:
            return P()
        role = AutoTP.policy_role(path_parts, policy_rules) if policy_rules else None
        if role == "replicate":
            return P()
        if role is None:
            role = AutoTP.classify(path_parts)
        is_bias = path_parts and path_parts[-1] in ("bias",)
        if role is None and not is_bias and len(shape) == 2:
            # shape heuristic for unknown naming conventions (the reference
            # reads the module graph instead, auto_tp.py:13): Megatron-shaped
            # projections are non-square — expanding [d, k*d] (fused QKV,
            # gated/up MLP) shards the output dim, contracting [k*d, d]
            # shards the input dim. Square kernels stay ambiguous.
            rows, cols = int(shape[0]), int(shape[1])
            if cols >= 2 * rows:
                role = "column"
            elif rows >= 2 * cols:
                role = "row"
        if role is None:
            # the reference parses module graphs and errors on unsupported
            # architectures (auto_tp.py is_load_module checks); name matching
            # must at least SAY when a big kernel falls through to replication
            if len(shape) >= 2 and int(np.prod(shape)) >= 1 << 16:
                AutoTP._warn_unmatched("/".join(path_parts), shape)
            return P()
        if role == "vocab":
            if len(shape) >= 2 and shape[0] % tp_size == 0:
                return P(TENSOR_AXIS)  # [vocab, embed]
            return P()
        if is_bias:
            if role == "column" and shape and shape[-1] % tp_size == 0:
                parts = [None] * (len(shape) - 1) + [TENSOR_AXIS]
                return P(*parts)
            return P()  # row-parallel bias is replicated (added post-allreduce)
        if len(shape) < 2:
            return P()
        if role == "column" and shape[-1] % tp_size == 0:
            parts = [None] * (len(shape) - 1) + [TENSOR_AXIS]
            return P(*parts)
        if role == "row" and shape[0] % tp_size == 0:
            parts = [TENSOR_AXIS] + [None] * (len(shape) - 1)
            return P(*parts)
        return P()

    @staticmethod
    def tp_parser(params, tp_size: int, policy=None):
        """Emit a PartitionSpec pytree for a raw param tree
        (reference ``AutoTP.tp_parser`` + ``ReplaceWithTensorSlicing``).
        ``policy`` (user ``injection_policy``) overrides name classification
        for matched paths."""
        rules = AutoTP.normalize_policy(policy)
        if rules:
            AutoTP.warn_unmatched_policy(params, rules)
        return jax.tree_util.tree_map_with_path(
            lambda path, leaf: AutoTP.spec_for(_path_parts(path), getattr(leaf, "shape", ()),
                                               tp_size, policy_rules=rules or None),
            params)
