"""One-call HF import: torch model → (flax module, converted params).

The reference's ``init_inference`` accepts the HF torch module directly and
injects kernels into it (``module_inject/replace_module.py:283``); the TPU
analog is a conversion: detect the architecture from ``config.model_type``,
derive the matching model-zoo config from the HF config, and remap the
weights with the per-arch converter. ``init_inference`` calls this
automatically when handed a torch module.
"""

from typing import Any, Optional

from deepspeed_tpu.module_inject.load_checkpoint import load_hf_checkpoint

_CONFIG_CLASS = {"gpt2": "GPT2Config", "llama": "LlamaConfig", "opt": "OPTConfig",
                 "gpt_neox": "GPTNeoXConfig", "gptj": "GPTJConfig",
                 "gpt_neo": "GPTNeoConfig", "bloom": "BloomConfig",
                 "falcon": "FalconConfig", "t5": "T5Config", "bert": "BertConfig",
                 "clip": "CLIPTextConfig"}


def _gptj_inner(hf):
    return hf.n_inner if getattr(hf, "n_inner", None) else 4 * hf.n_embd


def _llama_like(hf, **extra):
    out = dict(vocab_size=hf.vocab_size, hidden_size=hf.hidden_size,
               intermediate_size=hf.intermediate_size,
               num_hidden_layers=hf.num_hidden_layers,
               num_attention_heads=hf.num_attention_heads,
               num_key_value_heads=getattr(hf, "num_key_value_heads", None)
               or hf.num_attention_heads,
               max_position_embeddings=hf.max_position_embeddings,
               rms_norm_eps=hf.rms_norm_eps,
               rope_theta=getattr(hf, "rope_theta", 10000.0),
               attention_bias=bool(getattr(hf, "attention_bias", False)))
    out.update(extra)
    return out


def _spec(model_type: str, hf):
    """(family module name, model class name, config class kwargs, converter arch)."""
    if model_type == "gpt2":
        return ("gpt2", "GPT2LMHeadModel", dict(
            vocab_size=hf.vocab_size, n_positions=hf.n_positions, n_embd=hf.n_embd,
            n_layer=hf.n_layer, n_head=hf.n_head,
            layer_norm_epsilon=hf.layer_norm_epsilon), "gpt2")
    if model_type == "llama":
        return ("llama", "LlamaForCausalLM", _llama_like(hf), "llama")
    if model_type == "mistral":
        return ("llama", "LlamaForCausalLM",
                _llama_like(hf, sliding_window=getattr(hf, "sliding_window", None)), "llama")
    if model_type == "qwen2":
        sw = getattr(hf, "sliding_window", None) if getattr(hf, "use_sliding_window", False) else None
        return ("llama", "LlamaForCausalLM",
                _llama_like(hf, attention_bias=True, sliding_window=sw), "llama")
    if model_type == "mixtral":
        return ("llama", "LlamaForCausalLM",
                _llama_like(hf, moe_num_experts=hf.num_local_experts,
                            moe_k=hf.num_experts_per_tok), "llama")
    if model_type == "opt":
        return ("opt", "OPTForCausalLM", dict(
            vocab_size=hf.vocab_size, hidden_size=hf.hidden_size, ffn_dim=hf.ffn_dim,
            num_hidden_layers=hf.num_hidden_layers, num_attention_heads=hf.num_attention_heads,
            max_position_embeddings=hf.max_position_embeddings,
            word_embed_proj_dim=hf.word_embed_proj_dim,
            do_layer_norm_before=hf.do_layer_norm_before), "opt")
    if model_type == "gpt_neox":
        return ("gpt_neox", "GPTNeoXForCausalLM", dict(
            vocab_size=hf.vocab_size, hidden_size=hf.hidden_size,
            intermediate_size=hf.intermediate_size, num_hidden_layers=hf.num_hidden_layers,
            num_attention_heads=hf.num_attention_heads,
            max_position_embeddings=hf.max_position_embeddings,
            rotary_pct=hf.rotary_pct,
            rotary_emb_base=getattr(hf, "rotary_emb_base", None) or getattr(hf, "rope_theta", 10000.0),
            use_parallel_residual=hf.use_parallel_residual,
            layer_norm_eps=hf.layer_norm_eps), "gpt_neox")
    if model_type == "gptj":
        return ("gptj", "GPTJForCausalLM", dict(
            vocab_size=hf.vocab_size, hidden_size=hf.n_embd, intermediate_size=_gptj_inner(hf),
            num_hidden_layers=hf.n_layer, num_attention_heads=hf.n_head,
            max_position_embeddings=hf.n_positions, rotary_dim=hf.rotary_dim or hf.n_embd
            // hf.n_head, layer_norm_eps=hf.layer_norm_epsilon), "gptj")
    if model_type == "gpt_neo":
        inner = getattr(hf, "intermediate_size", None) or 4 * hf.hidden_size
        return ("gpt_neo", "GPTNeoForCausalLM", dict(
            vocab_size=hf.vocab_size, hidden_size=hf.hidden_size, intermediate_size=inner,
            num_hidden_layers=hf.num_layers, num_attention_heads=hf.num_heads,
            max_position_embeddings=hf.max_position_embeddings,
            window_size=hf.window_size, layer_norm_eps=hf.layer_norm_epsilon), "gpt_neo")
    if model_type == "bloom":
        return ("bloom", "BloomForCausalLM", dict(
            vocab_size=hf.vocab_size, hidden_size=hf.hidden_size, n_head=hf.n_head,
            n_layer=hf.n_layer, layer_norm_epsilon=hf.layer_norm_epsilon), "bloom")
    if model_type == "falcon":
        if getattr(hf, "new_decoder_architecture", False):
            kv = hf.num_kv_heads
        else:
            kv = 1 if getattr(hf, "multi_query", True) else hf.num_attention_heads
        return ("falcon", "FalconForCausalLM", dict(
            vocab_size=hf.vocab_size, hidden_size=hf.hidden_size,
            num_attention_heads=hf.num_attention_heads, num_kv_heads=kv,
            num_hidden_layers=hf.num_hidden_layers,
            max_position_embeddings=getattr(hf, "max_position_embeddings", 2048),
            layer_norm_epsilon=hf.layer_norm_epsilon,
            rope_theta=getattr(hf, "rope_theta", 10000.0),
            new_decoder_architecture=getattr(hf, "new_decoder_architecture", False)), "falcon")
    if model_type == "t5":
        return ("t5", "T5ForConditionalGeneration", dict(
            vocab_size=hf.vocab_size, d_model=hf.d_model, d_kv=hf.d_kv, d_ff=hf.d_ff,
            num_layers=hf.num_layers, num_decoder_layers=hf.num_decoder_layers,
            num_heads=hf.num_heads,
            relative_attention_num_buckets=hf.relative_attention_num_buckets,
            relative_attention_max_distance=hf.relative_attention_max_distance,
            layer_norm_epsilon=hf.layer_norm_epsilon,
            feed_forward_proj=hf.feed_forward_proj,
            tie_word_embeddings=hf.tie_word_embeddings,
            decoder_start_token_id=hf.decoder_start_token_id), "t5")
    if model_type == "bert":
        return ("bert", "BertForMaskedLM", dict(
            vocab_size=hf.vocab_size, hidden_size=hf.hidden_size,
            num_hidden_layers=hf.num_hidden_layers, num_attention_heads=hf.num_attention_heads,
            intermediate_size=hf.intermediate_size,
            max_position_embeddings=hf.max_position_embeddings,
            type_vocab_size=hf.type_vocab_size, layer_norm_eps=hf.layer_norm_eps,
            hidden_act=hf.hidden_act), "bert")
    if model_type == "distilbert":
        return ("bert", "BertForMaskedLM", dict(
            vocab_size=hf.vocab_size, hidden_size=hf.dim, num_hidden_layers=hf.n_layers,
            num_attention_heads=hf.n_heads, intermediate_size=hf.hidden_dim,
            max_position_embeddings=hf.max_position_embeddings,
            type_vocab_size=1, hidden_act=hf.activation), "distilbert")
    if model_type in ("clip", "clip_text_model"):
        text = getattr(hf, "text_config", hf)
        return ("clip", "CLIPTextModel", dict(
            vocab_size=text.vocab_size, hidden_size=text.hidden_size,
            intermediate_size=text.intermediate_size,
            num_hidden_layers=text.num_hidden_layers,
            num_attention_heads=text.num_attention_heads,
            max_position_embeddings=text.max_position_embeddings,
            # HF special-cases eos_token_id==2 to legacy argmax pooling;
            # the zoo encodes that mode as None
            eos_token_id=(lambda e: None if e == 2 else e)(getattr(text, "eos_token_id", None)),
            hidden_act=text.hidden_act, layer_norm_eps=text.layer_norm_eps), "clip")
    raise ValueError(f"no deepspeed_tpu mapping for HF model_type {model_type!r}; "
                     f"convert manually via module_inject.load_hf_checkpoint")


def from_hf(hf_model, dtype: Optional[Any] = None, weights: bool = True,
            **config_overrides):
    """HF torch model → ``(flax module, converted params)``.

    ``dtype`` sets the compute dtype of the returned module (params stay at
    the checkpoint precision); extra kwargs override derived config fields
    (e.g. ``attention_backend="flash"``, ``fused_head_loss_chunk=1024``).

    ``weights=False`` skips the state_dict conversion and returns
    ``(module, None)`` — the reference's meta-tensor convention
    (``inference/engine.py:336``): arch/config from the module, weights
    loaded later from an explicit checkpoint. Avoids a full-model host
    copy when the converted weights would be thrown away.
    """
    import importlib

    hf_cfg = getattr(hf_model, "config", None)
    model_type = getattr(hf_cfg, "model_type", None)
    if model_type is None:
        raise ValueError("from_hf needs a HF model with config.model_type; got "
                         f"{type(hf_model).__name__}")
    family, cls_name, kwargs, arch = _spec(model_type, hf_cfg)
    if dtype is not None:
        kwargs["dtype"] = dtype
    kwargs.update(config_overrides)
    mod = importlib.import_module(f"deepspeed_tpu.models.{family}")
    cfg_cls = getattr(mod, _CONFIG_CLASS[family])
    cfg = cfg_cls(**kwargs)
    model = getattr(mod, cls_name)(cfg)
    params = load_hf_checkpoint(hf_model, arch, cfg) if weights else None
    return model, params
