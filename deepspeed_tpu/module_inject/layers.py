"""Tensor-parallel building-block layers — reference
``module_inject/layers.py`` (``LinearLayer`` column-parallel at :32,
``LinearAllreduce`` row-parallel at :15, used by kernel injection and by
users hand-building TP models).

TPU redesign: the reference slices weights per rank and inserts explicit
``all_reduce`` calls; here each layer is an ``nn.Dense`` whose kernel
carries LOGICAL axis names (``parallel/sharding.DEFAULT_LOGICAL_RULES``
maps "mlp" to the tensor axis) and GSPMD inserts the collective — a
column-parallel ``LinearLayer`` feeding a row-parallel
``LinearAllreduce`` compiles to exactly one psum over the tensor axis,
same wire traffic as the reference pair, with no rank arithmetic in user
code."""
from typing import Any, Callable, Optional

import jax.numpy as jnp

import flax.linen as nn

from deepspeed_tpu.models.common import dense_init


def _dense(features, use_bias, dtype, param_dtype, kernel_init,
           kernel_axes, bias_axes, name=None):
    return nn.Dense(
        features=features, use_bias=use_bias, dtype=dtype, param_dtype=param_dtype,
        kernel_init=nn.with_logical_partitioning(kernel_init or dense_init(), kernel_axes),
        bias_init=nn.with_logical_partitioning(nn.initializers.zeros, bias_axes),
        name=name)


class LinearLayer(nn.Module):
    """Column-parallel linear: output features shard over the tensor axis
    (logical "mlp"); the input stays replicated across TP ranks. Follow
    with :class:`LinearAllreduce` to return to replicated activations."""

    features: int
    use_bias: bool = True
    dtype: Any = jnp.float32
    param_dtype: Any = jnp.float32
    kernel_init: Optional[Callable] = None

    @nn.compact
    def __call__(self, x):
        dense = _dense(self.features, self.use_bias, self.dtype, self.param_dtype,
                       self.kernel_init, ("embed", "mlp"), ("mlp",))
        nn.share_scope(self, dense)  # params at <name>/kernel, not <name>/Dense_0/...
        return dense(x)


class LinearAllreduce(nn.Module):
    """Row-parallel linear: input features shard over the tensor axis, and
    the partial products sum across ranks (GSPMD materializes the psum the
    reference calls explicitly after its sliced matmul). The replicated
    bias applies after the reduction, as in the reference."""

    features: int
    use_bias: bool = True
    dtype: Any = jnp.float32
    param_dtype: Any = jnp.float32
    kernel_init: Optional[Callable] = None

    @nn.compact
    def __call__(self, x):
        dense = _dense(self.features, self.use_bias, self.dtype, self.param_dtype,
                       self.kernel_init, ("mlp", "embed"), ("embed",))
        nn.share_scope(self, dense)  # params at <name>/kernel, not <name>/Dense_0/...
        return dense(x)
