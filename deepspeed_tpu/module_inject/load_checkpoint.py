"""HF-checkpoint → flax param-tree converters
(reference ``module_inject/load_checkpoint.py`` + the per-arch containers
``module_inject/containers/{gpt2,llama,bert}.py`` which slice HF weights
into the injected modules).

These let a reference user bring their torch checkpoints: a HF torch model
(or its state dict) is remapped into the deepspeed_tpu model-zoo layout.
Numerical parity is covered by tests (HF torch CPU forward vs ours).
"""

from typing import Any, Dict

import numpy as np

import jax.numpy as jnp


def _np(t):
    return np.asarray(t.detach().cpu().numpy() if hasattr(t, "detach") else t)


def _sd(model_or_sd) -> Dict[str, Any]:
    if hasattr(model_or_sd, "state_dict"):
        return {k: _np(v) for k, v in model_or_sd.state_dict().items()}
    return {k: _np(v) for k, v in model_or_sd.items()}


def _lin(sd, name):
    """torch Linear [out, in] (+bias) → flax Dense {kernel [in, out], bias}."""
    return {"kernel": jnp.asarray(sd[name + ".weight"].T),
            "bias": jnp.asarray(sd[name + ".bias"])}


def _ln(sd, name):
    """torch LayerNorm weight/bias → flax scale/bias."""
    return {"scale": jnp.asarray(sd[name + ".weight"]),
            "bias": jnp.asarray(sd[name + ".bias"])}


# HF activation-name aliases → one canonical name per numeric function
_ACT_CANON = {"gelu": "gelu", "gelu_new": "gelu_tanh", "gelu_tanh": "gelu_tanh",
              "gelu_pytorch_tanh": "gelu_tanh", "relu": "relu",
              "quick_gelu": "quick_gelu"}


def _check_activation(hf_cfg, cfg, hf_field: str):
    """Raise if the HF config's activation disagrees with the target config
    (weights trained with erf-gelu silently drift under tanh-gelu). Only
    checkable when a model (not a bare state dict) is passed."""
    if hf_cfg is None:
        return
    hf_act = getattr(hf_cfg, hf_field, None)
    if hf_act is None:
        return
    if _ACT_CANON.get(hf_act) != _ACT_CANON.get(cfg.hidden_act):
        raise ValueError(
            f"HF checkpoint activation {hf_act!r} != target config hidden_act "
            f"{cfg.hidden_act!r}; build the config with the matching hidden_act "
            f"(HF BERT/DistilBERT default is exact 'gelu'; original CLIP is "
            f"'quick_gelu')")


def load_hf_gpt2(model_or_sd, cfg) -> dict:
    """HF ``GPT2LMHeadModel`` → ``models.gpt2.GPT2LMHeadModel`` params.

    HF GPT-2 uses Conv1D ([in, out] kernels, same as flax Dense); qkv is one
    fused [E, 3E] matrix split into our [E, 3, H, D] layout.
    """
    sd = _sd(model_or_sd)
    pre = "transformer." if any(k.startswith("transformer.") for k in sd) else ""
    E, H, D = cfg.n_embd, cfg.n_head, cfg.head_dim
    params = {
        "wte": jnp.asarray(sd[f"{pre}wte.weight"]),
        "wpe": jnp.asarray(sd[f"{pre}wpe.weight"]),
        "ln_f": {"LayerNorm_0": {"scale": jnp.asarray(sd[f"{pre}ln_f.weight"]),
                                 "bias": jnp.asarray(sd[f"{pre}ln_f.bias"])}},
    }
    for i in range(cfg.n_layer):
        p = f"{pre}h.{i}."
        c_attn_w = sd[p + "attn.c_attn.weight"].reshape(E, 3, H, D)
        c_attn_b = sd[p + "attn.c_attn.bias"].reshape(3, H, D)
        c_proj_w = sd[p + "attn.c_proj.weight"].reshape(H, D, E)
        params[f"h_{i}"] = {
            "ln_1": {"LayerNorm_0": {"scale": jnp.asarray(sd[p + "ln_1.weight"]),
                                     "bias": jnp.asarray(sd[p + "ln_1.bias"])}},
            "ln_2": {"LayerNorm_0": {"scale": jnp.asarray(sd[p + "ln_2.weight"]),
                                     "bias": jnp.asarray(sd[p + "ln_2.bias"])}},
            "attn": {
                "c_attn": {"kernel": jnp.asarray(c_attn_w), "bias": jnp.asarray(c_attn_b)},
                "c_proj": {"kernel": jnp.asarray(c_proj_w), "bias": jnp.asarray(sd[p + "attn.c_proj.bias"])},
            },
            "mlp": {
                "c_fc": {"kernel": jnp.asarray(sd[p + "mlp.c_fc.weight"]),
                         "bias": jnp.asarray(sd[p + "mlp.c_fc.bias"])},
                "c_proj": {"kernel": jnp.asarray(sd[p + "mlp.c_proj.weight"]),
                           "bias": jnp.asarray(sd[p + "mlp.c_proj.bias"])},
            },
        }
    return params


def load_hf_llama(model_or_sd, cfg) -> dict:
    """HF ``LlamaForCausalLM`` → ``models.llama.LlamaForCausalLM`` params.

    HF Linear weights are [out, in] — transposed into flax [in, out]; q/k/v
    reshape into [in, heads, head_dim]. NOTE: HF LLaMA uses the
    interleaved-rotary convention permuted at conversion time; weights
    converted by HF's own script are compatible with half-split RoPE.
    """
    sd = _sd(model_or_sd)
    pre = "model." if any(k.startswith("model.") for k in sd) else ""
    E, H, KV, D = cfg.hidden_size, cfg.num_attention_heads, cfg.num_key_value_heads, cfg.head_dim

    def lin_t(name):  # [out, in] -> [in, out]
        return jnp.asarray(sd[name].T)

    bias_attn = bool(getattr(cfg, "attention_bias", False))
    has_bias_keys = any(k.endswith("self_attn.q_proj.bias") for k in sd)
    if has_bias_keys != bias_attn:
        raise ValueError(
            f"checkpoint {'has' if has_bias_keys else 'lacks'} attention biases but "
            f"cfg.attention_bias={bias_attn} — silently "
            f"{'dropping biases would corrupt logits' if has_bias_keys else 'inventing zero biases is unsupported'}; "
            f"set attention_bias={has_bias_keys} (Qwen2-style checkpoints carry q/k/v biases)")

    def heads_t(name, heads):  # [heads*D, in] -> [in, heads, D]
        out = {"kernel": jnp.asarray(sd[name + ".weight"].T.reshape(E, heads, D))}
        if bias_attn:
            out["bias"] = jnp.asarray(sd[name + ".bias"].reshape(heads, D))
        return out

    params = {
        "embed_tokens": jnp.asarray(sd[f"{pre}embed_tokens.weight"]),
        "norm": {"weight": jnp.asarray(sd[f"{pre}norm.weight"])},
        # tied-embedding checkpoints (tie_word_embeddings=True) omit lm_head
        "lm_head": {"kernel": lin_t("lm_head.weight") if "lm_head.weight" in sd
                    else jnp.asarray(sd[f"{pre}embed_tokens.weight"].T)},
    }
    n_experts = getattr(cfg, "moe_num_experts", 0)
    freq = max(getattr(cfg, "moe_layer_freq", 1), 1)
    for i in range(cfg.num_hidden_layers):
        p = f"{pre}layers.{i}."
        o_w = jnp.asarray(sd[p + "self_attn.o_proj.weight"].T.reshape(H, D, E))
        layer = {
            "input_layernorm": {"weight": jnp.asarray(sd[p + "input_layernorm.weight"])},
            "post_attention_layernorm": {"weight": jnp.asarray(sd[p + "post_attention_layernorm.weight"])},
            "self_attn": {
                "q_proj": heads_t(p + "self_attn.q_proj", H),
                "k_proj": heads_t(p + "self_attn.k_proj", KV),
                "v_proj": heads_t(p + "self_attn.v_proj", KV),
                "o_proj": {"kernel": o_w},
            },
        }
        is_moe_layer = n_experts > 0 and i % freq == freq - 1
        if is_moe_layer:
            # Mixtral checkpoints: block_sparse_moe.gate + experts.N.{w1,w3,w2}
            # (w1=gate_proj, w3=up_proj, w2=down_proj); experts stack on a
            # leading dim matching the vmapped expert layout
            bs = p + "block_sparse_moe."
            stack = lambda name: jnp.stack(
                [jnp.asarray(sd[f"{bs}experts.{n}.{name}.weight"].T)
                 for n in range(n_experts)])
            layer["moe"] = {"deepspeed_moe": {
                "gate": {"wg": jnp.asarray(sd[bs + "gate.weight"].T)},
                "experts": {"deepspeed_experts": {
                    "gate_proj": {"kernel": stack("w1")},
                    "up_proj": {"kernel": stack("w3")},
                    "down_proj": {"kernel": stack("w2")},
                }},
            }}
        else:
            layer["mlp"] = {
                "gate_proj": {"kernel": lin_t(p + "mlp.gate_proj.weight")},
                "up_proj": {"kernel": lin_t(p + "mlp.up_proj.weight")},
                "down_proj": {"kernel": lin_t(p + "mlp.down_proj.weight")},
            }
        params[f"layers_{i}"] = layer
    return params


def load_hf_opt(model_or_sd, cfg) -> dict:
    """HF ``OPTForCausalLM`` → ``models.opt.OPTForCausalLM`` params
    (reference ``module_inject/containers/opt.py`` slices the same tensors
    into its injected module).

    HF Linear weights are [out, in] → flax [in, out]; q/k/v reshape to
    [E, heads, D] and out_proj to [heads, D, E]; LayerNorm weight→scale.
    """
    sd = _sd(model_or_sd)
    pre = "model.decoder." if any(k.startswith("model.decoder.") for k in sd) else "decoder."
    if not any(k.startswith(pre) for k in sd):
        pre = ""
    E, H, D = cfg.hidden_size, cfg.num_attention_heads, cfg.head_dim

    lin = lambda name: _lin(sd, name)
    ln = lambda name: _ln(sd, name)

    params = {
        "embed_tokens": jnp.asarray(sd[f"{pre}embed_tokens.weight"]),
        "embed_positions": jnp.asarray(sd[f"{pre}embed_positions.weight"]),
    }
    if cfg.do_layer_norm_before and f"{pre}final_layer_norm.weight" in sd:
        params["final_layer_norm"] = ln(f"{pre}final_layer_norm")
    if cfg.has_embed_proj:
        params["project_in"] = {"kernel": jnp.asarray(sd[f"{pre}project_in.weight"].T)}
        params["project_out"] = {"kernel": jnp.asarray(sd[f"{pre}project_out.weight"].T)}
    for i in range(cfg.num_hidden_layers):
        p = f"{pre}layers.{i}."

        def heads_in(name):  # [H*D, E] -> [E, H, D]
            return {"kernel": jnp.asarray(sd[name + ".weight"].T.reshape(E, H, D)),
                    "bias": jnp.asarray(sd[name + ".bias"].reshape(H, D))}

        params[f"layers_{i}"] = {
            "self_attn_layer_norm": ln(p + "self_attn_layer_norm"),
            "final_layer_norm": ln(p + "final_layer_norm"),
            "self_attn": {
                "q_proj": heads_in(p + "self_attn.q_proj"),
                "k_proj": heads_in(p + "self_attn.k_proj"),
                "v_proj": heads_in(p + "self_attn.v_proj"),
                "out_proj": {"kernel": jnp.asarray(sd[p + "self_attn.out_proj.weight"].T.reshape(H, D, E)),
                             "bias": jnp.asarray(sd[p + "self_attn.out_proj.bias"])},
            },
            "fc1": lin(p + "fc1"),
            "fc2": lin(p + "fc2"),
        }
    return params


def load_hf_gpt_neox(model_or_sd, cfg) -> dict:
    """HF ``GPTNeoXForCausalLM`` → ``models.gpt_neox.GPTNeoXForCausalLM``
    params (reference ``module_inject/containers/gptneox.py``).

    The fused qkv is per-head interleaved: torch [3E, E] transposes to
    [E, 3E] and reshapes to [E, H, 3, D] (matching our DenseGeneral); HF
    NeoX rotary is the half-split (rotate_half) convention our
    ``rotary_embedding`` implements.
    """
    sd = _sd(model_or_sd)
    pre = "gpt_neox." if any(k.startswith("gpt_neox.") for k in sd) else ""
    E, H, D = cfg.hidden_size, cfg.num_attention_heads, cfg.head_dim

    lin = lambda name: _lin(sd, name)
    ln = lambda name: _ln(sd, name)

    params = {
        "embed_in": jnp.asarray(sd[f"{pre}embed_in.weight"]),
        "final_layer_norm": ln(f"{pre}final_layer_norm"),
        "embed_out": {"kernel": jnp.asarray(sd["embed_out.weight"].T)},
    }
    for i in range(cfg.num_hidden_layers):
        p = f"{pre}layers.{i}."
        params[f"layers_{i}"] = {
            "input_layernorm": ln(p + "input_layernorm"),
            "post_attention_layernorm": ln(p + "post_attention_layernorm"),
            "attention": {
                "query_key_value": {
                    "kernel": jnp.asarray(sd[p + "attention.query_key_value.weight"].T
                                          .reshape(E, H, 3, D)),
                    "bias": jnp.asarray(sd[p + "attention.query_key_value.bias"]
                                        .reshape(H, 3, D)),
                },
                "dense": {"kernel": jnp.asarray(sd[p + "attention.dense.weight"].T.reshape(H, D, E)),
                          "bias": jnp.asarray(sd[p + "attention.dense.bias"])},
            },
            "dense_h_to_4h": lin(p + "mlp.dense_h_to_4h"),
            "dense_4h_to_h": lin(p + "mlp.dense_4h_to_h"),
        }
    return params


def load_hf_bert(model_or_sd, cfg) -> dict:
    """HF ``BertForMaskedLM`` → ``models.bert.BertForMaskedLM`` params
    (reference ``module_inject/containers/bert.py``).

    HF checkpoints use exact (erf) gelu — build the target config with
    ``hidden_act="gelu"``. ``BertForMaskedLM`` checkpoints carry no pooler
    (``add_pooling_layer=False``); ours always declares one, so a zero
    pooler is synthesized (unused by the MLM head).
    """
    _check_activation(getattr(model_or_sd, "config", None), cfg, "hidden_act")
    sd = _sd(model_or_sd)
    pre = "bert." if any(k.startswith("bert.") for k in sd) else ""
    E, H, D = cfg.hidden_size, cfg.num_attention_heads, cfg.head_dim

    lin = lambda name: _lin(sd, name)
    ln = lambda name: {"LayerNorm_0": _ln(sd, name)}

    bert = {
        "word_embeddings": jnp.asarray(sd[f"{pre}embeddings.word_embeddings.weight"]),
        "position_embeddings": jnp.asarray(sd[f"{pre}embeddings.position_embeddings.weight"]),
        "token_type_embeddings": jnp.asarray(sd[f"{pre}embeddings.token_type_embeddings.weight"]),
        "embeddings_ln": ln(f"{pre}embeddings.LayerNorm"),
    }
    if f"{pre}pooler.dense.weight" in sd:
        bert["pooler"] = lin(f"{pre}pooler.dense")
    else:
        bert["pooler"] = {"kernel": jnp.zeros((E, E), jnp.float32),
                          "bias": jnp.zeros((E,), jnp.float32)}
    for i in range(cfg.num_hidden_layers):
        p = f"{pre}encoder.layer.{i}."

        def heads_in(name):
            return {"kernel": jnp.asarray(sd[name + ".weight"].T.reshape(E, H, D)),
                    "bias": jnp.asarray(sd[name + ".bias"].reshape(H, D))}

        bert[f"layer_{i}"] = {
            "attention": {
                "query": heads_in(p + "attention.self.query"),
                "key": heads_in(p + "attention.self.key"),
                "value": heads_in(p + "attention.self.value"),
                "output": {"kernel": jnp.asarray(sd[p + "attention.output.dense.weight"].T
                                                 .reshape(H, D, E)),
                           "bias": jnp.asarray(sd[p + "attention.output.dense.bias"])},
            },
            "attention_ln": ln(p + "attention.output.LayerNorm"),
            "intermediate": lin(p + "intermediate.dense"),
            "output": lin(p + "output.dense"),
            "output_ln": ln(p + "output.LayerNorm"),
        }
    return {
        "bert": bert,
        "transform": lin("cls.predictions.transform.dense"),
        "transform_ln": ln("cls.predictions.transform.LayerNorm"),
        "decoder_bias": jnp.asarray(sd["cls.predictions.bias"]),
    }


def load_hf_distilbert(model_or_sd, cfg) -> dict:
    """HF ``DistilBertForMaskedLM`` → ``models.bert.BertForMaskedLM`` params
    (reference ``module_inject/containers/distil_bert.py``).

    DistilBERT is served through the BERT family: no token-type embeddings
    (build the config with ``type_vocab_size=1`` — a zero row is
    synthesized so the default ``token_type_ids=0`` contributes nothing),
    no pooler (zero-synthesized), ``vocab_projector`` tied to the word
    embeddings with its bias → ``decoder_bias``. Use ``hidden_act="gelu"``.
    """
    _check_activation(getattr(model_or_sd, "config", None), cfg, "activation")
    sd = _sd(model_or_sd)
    pre = "distilbert." if any(k.startswith("distilbert.") for k in sd) else ""
    E, H, D = cfg.hidden_size, cfg.num_attention_heads, cfg.head_dim

    lin = lambda name: _lin(sd, name)
    ln = lambda name: {"LayerNorm_0": _ln(sd, name)}

    bert = {
        "word_embeddings": jnp.asarray(sd[f"{pre}embeddings.word_embeddings.weight"]),
        "position_embeddings": jnp.asarray(sd[f"{pre}embeddings.position_embeddings.weight"]),
        "token_type_embeddings": jnp.zeros((cfg.type_vocab_size, E), jnp.float32),
        "embeddings_ln": ln(f"{pre}embeddings.LayerNorm"),
        "pooler": {"kernel": jnp.zeros((E, E), jnp.float32),
                   "bias": jnp.zeros((E,), jnp.float32)},
    }
    for i in range(cfg.num_hidden_layers):
        p = f"{pre}transformer.layer.{i}."

        def heads_in(name):
            return {"kernel": jnp.asarray(sd[name + ".weight"].T.reshape(E, H, D)),
                    "bias": jnp.asarray(sd[name + ".bias"].reshape(H, D))}

        bert[f"layer_{i}"] = {
            "attention": {
                "query": heads_in(p + "attention.q_lin"),
                "key": heads_in(p + "attention.k_lin"),
                "value": heads_in(p + "attention.v_lin"),
                "output": {"kernel": jnp.asarray(sd[p + "attention.out_lin.weight"].T
                                                 .reshape(H, D, E)),
                           "bias": jnp.asarray(sd[p + "attention.out_lin.bias"])},
            },
            "attention_ln": ln(p + "sa_layer_norm"),
            "intermediate": lin(p + "ffn.lin1"),
            "output": lin(p + "ffn.lin2"),
            "output_ln": ln(p + "output_layer_norm"),
        }
    return {
        "bert": bert,
        "transform": lin("vocab_transform"),
        "transform_ln": ln("vocab_layer_norm"),
        "decoder_bias": jnp.asarray(sd["vocab_projector.bias"]),
    }


def load_hf_gptj(model_or_sd, cfg) -> dict:
    """HF ``GPTJForCausalLM`` → ``models.gptj.GPTJForCausalLM`` params
    (reference ``module_inject/containers/gptj.py``).

    q/k/v/out are separate bias-free Linears: torch [E, E] transposes to
    [E, E] and reshapes to [E, H, D] (out: [E(H·D), E] → [H, D, E]); HF
    GPT-J rotary is the interleaved (rotate-every-two) convention our
    ``rotary_embedding_interleaved`` implements; ``lm_head`` keeps its bias.
    """
    sd = _sd(model_or_sd)
    pre = "transformer." if any(k.startswith("transformer.") for k in sd) else ""
    E, H, D = cfg.hidden_size, cfg.num_attention_heads, cfg.head_dim

    lin = lambda name: _lin(sd, name)
    ln = lambda name: _ln(sd, name)

    params = {
        "wte": jnp.asarray(sd[f"{pre}wte.weight"]),
        "ln_f": ln(f"{pre}ln_f"),
        "lm_head": lin("lm_head"),
    }
    for i in range(cfg.num_hidden_layers):
        p = f"{pre}h.{i}."
        params[f"h_{i}"] = {
            "ln_1": ln(p + "ln_1"),
            "attn": {
                "q_proj": {"kernel": jnp.asarray(sd[p + "attn.q_proj.weight"].T.reshape(E, H, D))},
                "k_proj": {"kernel": jnp.asarray(sd[p + "attn.k_proj.weight"].T.reshape(E, H, D))},
                "v_proj": {"kernel": jnp.asarray(sd[p + "attn.v_proj.weight"].T.reshape(E, H, D))},
                "out_proj": {"kernel": jnp.asarray(sd[p + "attn.out_proj.weight"].T.reshape(H, D, E))},
            },
            "fc_in": lin(p + "mlp.fc_in"),
            "fc_out": lin(p + "mlp.fc_out"),
        }
    return params


def load_hf_gpt_neo(model_or_sd, cfg) -> dict:
    """HF ``GPTNeoForCausalLM`` → ``models.gpt_neo.GPTNeoForCausalLM``
    params (reference ``module_inject/containers/gptneo.py``).

    GPT-Neo uses plain ``nn.Linear`` ([out, in] — transposed here), not
    GPT-2's Conv1D; q/k/v carry no biases; the LM head is tied (any
    ``lm_head.weight`` in the state dict is the embedding and is ignored).
    The target model hardcodes the standard even-global/odd-local layer
    pattern, so checkpoints with a different ``attention_types`` schedule
    or ``window_size`` are rejected rather than silently mis-masked.
    """
    hf_cfg = getattr(model_or_sd, "config", None)
    if hf_cfg is not None:
        hf_layers = list(getattr(hf_cfg, "attention_layers", []) or [])
        if hf_layers:
            ours = [cfg.attention_type(i) for i in range(cfg.num_hidden_layers)]
            if hf_layers != ours:
                raise ValueError(
                    f"HF attention_types expand to {hf_layers} but the target "
                    f"model masks layers as {ours} (even-global/odd-local); "
                    f"this checkpoint's schedule is unsupported")
        hf_window = getattr(hf_cfg, "window_size", None)
        if hf_window is not None and hf_window != cfg.window_size:
            raise ValueError(
                f"HF window_size={hf_window} != target config window_size="
                f"{cfg.window_size}; build the config with the matching window")
    sd = _sd(model_or_sd)
    pre = "transformer." if any(k.startswith("transformer.") for k in sd) else ""
    E, H, D = cfg.hidden_size, cfg.num_attention_heads, cfg.head_dim

    lin = lambda name: _lin(sd, name)
    ln = lambda name: _ln(sd, name)

    params = {
        "wte": jnp.asarray(sd[f"{pre}wte.weight"]),
        "wpe": jnp.asarray(sd[f"{pre}wpe.weight"]),
        "ln_f": ln(f"{pre}ln_f"),
    }
    for i in range(cfg.num_hidden_layers):
        p = f"{pre}h.{i}."
        a = p + "attn.attention."
        params[f"h_{i}"] = {
            "ln_1": ln(p + "ln_1"),
            "ln_2": ln(p + "ln_2"),
            "attn": {
                "q_proj": {"kernel": jnp.asarray(sd[a + "q_proj.weight"].T.reshape(E, H, D))},
                "k_proj": {"kernel": jnp.asarray(sd[a + "k_proj.weight"].T.reshape(E, H, D))},
                "v_proj": {"kernel": jnp.asarray(sd[a + "v_proj.weight"].T.reshape(E, H, D))},
                "out_proj": {"kernel": jnp.asarray(sd[a + "out_proj.weight"].T.reshape(H, D, E)),
                             "bias": jnp.asarray(sd[a + "out_proj.bias"])},
            },
            "c_fc": lin(p + "mlp.c_fc"),
            "c_proj": lin(p + "mlp.c_proj"),
        }
    return params


def load_hf_clip_text(model_or_sd, cfg) -> dict:
    """HF ``CLIPTextModel`` (or full ``CLIPModel``) →
    ``models.clip.CLIPTextModel`` params (reference
    ``module_inject/containers/clip.py``)."""
    hf_cfg = getattr(model_or_sd, "config", None)
    _check_activation(getattr(hf_cfg, "text_config", hf_cfg), cfg, "hidden_act")
    sd = _sd(model_or_sd)
    pre = ""
    for cand in ("text_model.", "clip.text_model."):
        if any(k.startswith(cand) for k in sd):
            pre = cand
            break
    E, H, D = cfg.hidden_size, cfg.num_attention_heads, cfg.head_dim

    lin = lambda name: _lin(sd, name)
    ln = lambda name: _ln(sd, name)

    def heads_in(name):
        return {"kernel": jnp.asarray(sd[name + ".weight"].T.reshape(E, H, D)),
                "bias": jnp.asarray(sd[name + ".bias"].reshape(H, D))}

    params = {
        "token_embedding": jnp.asarray(sd[f"{pre}embeddings.token_embedding.weight"]),
        "position_embedding": jnp.asarray(sd[f"{pre}embeddings.position_embedding.weight"]),
        "final_layer_norm": ln(f"{pre}final_layer_norm"),
    }
    for i in range(cfg.num_hidden_layers):
        p = f"{pre}encoder.layers.{i}."
        params[f"layers_{i}"] = {
            "layer_norm1": ln(p + "layer_norm1"),
            "layer_norm2": ln(p + "layer_norm2"),
            "q_proj": heads_in(p + "self_attn.q_proj"),
            "k_proj": heads_in(p + "self_attn.k_proj"),
            "v_proj": heads_in(p + "self_attn.v_proj"),
            "out_proj": {"kernel": jnp.asarray(sd[p + "self_attn.out_proj.weight"].T
                                               .reshape(H, D, E)),
                         "bias": jnp.asarray(sd[p + "self_attn.out_proj.bias"])},
            "fc1": lin(p + "mlp.fc1"),
            "fc2": lin(p + "mlp.fc2"),
        }
    return params


def load_hf_bloom(model_or_sd, cfg) -> dict:
    """HF ``BloomForCausalLM`` → ``models.bloom.BloomForCausalLM`` params
    (reference ``module_inject/containers/bloom.py``). The fused qkv is
    head-major interleaved like NeoX: torch [3E, E] → [E, H, 3, D]."""
    sd = _sd(model_or_sd)
    pre = "transformer." if any(k.startswith("transformer.") for k in sd) else ""
    E, H, D = cfg.hidden_size, cfg.n_head, cfg.head_dim

    lin = lambda name: _lin(sd, name)
    ln = lambda name: _ln(sd, name)

    params = {
        "word_embeddings": jnp.asarray(sd[f"{pre}word_embeddings.weight"]),
        "word_embeddings_layernorm": ln(f"{pre}word_embeddings_layernorm"),
        "ln_f": ln(f"{pre}ln_f"),
    }
    for i in range(cfg.n_layer):
        p = f"{pre}h.{i}."
        params[f"h_{i}"] = {
            "input_layernorm": ln(p + "input_layernorm"),
            "post_attention_layernorm": ln(p + "post_attention_layernorm"),
            "self_attention": {
                "query_key_value": {
                    "kernel": jnp.asarray(sd[p + "self_attention.query_key_value.weight"].T
                                          .reshape(E, H, 3, D)),
                    "bias": jnp.asarray(sd[p + "self_attention.query_key_value.bias"]
                                        .reshape(H, 3, D)),
                },
                "dense": {"kernel": jnp.asarray(sd[p + "self_attention.dense.weight"].T.reshape(H, D, E)),
                          "bias": jnp.asarray(sd[p + "self_attention.dense.bias"])},
            },
            "dense_h_to_4h": lin(p + "mlp.dense_h_to_4h"),
            "dense_4h_to_h": lin(p + "mlp.dense_4h_to_h"),
        }
    return params


def load_hf_t5(model_or_sd, cfg) -> dict:
    """HF ``T5ForConditionalGeneration`` → ``models.t5`` params. Attention
    projections reshape torch [inner, d_model] into [d_model, H, d_kv]
    (and o into [H, d_kv, d_model]); T5 LayerNorm has weight only."""
    sd = _sd(model_or_sd)
    D, H, KV = cfg.d_model, cfg.num_heads, cfg.d_kv

    def attn(prefix, has_rel):
        out = {
            "q": {"kernel": jnp.asarray(sd[prefix + ".q.weight"].T.reshape(D, H, KV))},
            "k": {"kernel": jnp.asarray(sd[prefix + ".k.weight"].T.reshape(D, H, KV))},
            "v": {"kernel": jnp.asarray(sd[prefix + ".v.weight"].T.reshape(D, H, KV))},
            "o": {"kernel": jnp.asarray(sd[prefix + ".o.weight"].T.reshape(H, KV, D))},
        }
        if has_rel:
            out["relative_attention_bias"] = jnp.asarray(
                sd[prefix + ".relative_attention_bias.weight"])
        return out

    def ff(prefix):
        if cfg.is_gated:
            return {"wi_0": {"kernel": jnp.asarray(sd[prefix + ".wi_0.weight"].T)},
                    "wi_1": {"kernel": jnp.asarray(sd[prefix + ".wi_1.weight"].T)},
                    "wo": {"kernel": jnp.asarray(sd[prefix + ".wo.weight"].T)}}
        return {"wi": {"kernel": jnp.asarray(sd[prefix + ".wi.weight"].T)},
                "wo": {"kernel": jnp.asarray(sd[prefix + ".wo.weight"].T)}}

    def lnw(name):
        return {"weight": jnp.asarray(sd[name + ".weight"])}

    def stack(side, n_layers, is_decoder):
        st = {"final_layer_norm": lnw(f"{side}.final_layer_norm")}
        for i in range(n_layers):
            p = f"{side}.block.{i}.layer"
            blk = {
                "SelfAttention": attn(f"{p}.0.SelfAttention", has_rel=(i == 0)),
                "ln_self": lnw(f"{p}.0.layer_norm"),
            }
            if is_decoder:
                blk["EncDecAttention"] = attn(f"{p}.1.EncDecAttention", has_rel=False)
                blk["ln_cross"] = lnw(f"{p}.1.layer_norm")
                blk["ff"] = ff(f"{p}.2.DenseReluDense")
                blk["ln_ff"] = lnw(f"{p}.2.layer_norm")
            else:
                blk["ff"] = ff(f"{p}.1.DenseReluDense")
                blk["ln_ff"] = lnw(f"{p}.1.layer_norm")
            st[f"block_{i}"] = blk
        return st

    params = {
        "shared": jnp.asarray(sd["shared.weight"]),
        "encoder": stack("encoder", cfg.num_layers, False),
        "decoder": stack("decoder", cfg.n_dec_layers, True),
    }
    if not cfg.tie_word_embeddings:
        params["lm_head"] = {"kernel": jnp.asarray(sd["lm_head.weight"].T)}
    return params


def load_hf_falcon(model_or_sd, cfg) -> dict:
    """HF ``FalconForCausalLM`` → ``models.falcon.FalconForCausalLM`` params.
    The fused qkv is group-interleaved: torch [(KV*(G+2))*D, E] transposes
    and reshapes to [E, KV, G+2, D]; LN names follow
    ``new_decoder_architecture`` (ln_attn/ln_mlp vs input_layernorm)."""
    hf_cfg = getattr(model_or_sd, "config", None)
    if hf_cfg is not None:
        # reject variants this module does not model — converting them
        # would produce plausible-looking but silently wrong logits
        if getattr(hf_cfg, "alibi", False):
            raise ValueError("falcon-rw style checkpoints (alibi=True) are not supported "
                             "by models.falcon (rotary only); use the BLOOM family for "
                             "alibi attention")
        if not getattr(hf_cfg, "parallel_attn", True):
            raise ValueError("sequential-attention Falcon variants (parallel_attn=False) "
                             "are not supported by models.falcon (parallel residual only)")
        if (not getattr(hf_cfg, "new_decoder_architecture", False)
                and not getattr(hf_cfg, "multi_query", True)):
            raise ValueError("per-head-interleaved Falcon QKV (multi_query=False without "
                             "new_decoder_architecture) is not supported — the loader "
                             "assumes the group-interleaved layout")
    sd = _sd(model_or_sd)
    pre = "transformer." if any(k.startswith("transformer.") for k in sd) else ""
    E, H, KV, D = (cfg.hidden_size, cfg.num_attention_heads,
                   cfg.num_kv_heads, cfg.head_dim)
    G = cfg.q_per_kv
    ln = lambda name: _ln(sd, name)

    params = {
        "word_embeddings": jnp.asarray(sd[f"{pre}word_embeddings.weight"]),
        "ln_f": ln(f"{pre}ln_f"),
    }
    for i in range(cfg.num_hidden_layers):
        p = f"{pre}h.{i}."
        layer = {
            "self_attention": {
                "query_key_value": {"kernel": jnp.asarray(
                    sd[p + "self_attention.query_key_value.weight"].T
                    .reshape(E, KV, G + 2, D))},
                "dense": {"kernel": jnp.asarray(
                    sd[p + "self_attention.dense.weight"].T.reshape(H, D, E))},
            },
            "dense_h_to_4h": {"kernel": jnp.asarray(sd[p + "mlp.dense_h_to_4h.weight"].T)},
            "dense_4h_to_h": {"kernel": jnp.asarray(sd[p + "mlp.dense_4h_to_h.weight"].T)},
        }
        if cfg.new_decoder_architecture:
            layer["ln_attn"] = ln(p + "ln_attn")
            layer["ln_mlp"] = ln(p + "ln_mlp")
        else:
            layer["input_layernorm"] = ln(p + "input_layernorm")
        params[f"h_{i}"] = layer
    return params


def load_hf_checkpoint(hf_model, arch: str, cfg) -> dict:
    """Dispatch by architecture (reference per-arch policy containers)."""
    loaders = {"gpt2": load_hf_gpt2, "llama": load_hf_llama, "opt": load_hf_opt,
               "gpt_neox": load_hf_gpt_neox, "gptneox": load_hf_gpt_neox,
               "bloom": load_hf_bloom, "t5": load_hf_t5, "falcon": load_hf_falcon,
               "gptj": load_hf_gptj, "gpt-j": load_hf_gptj,
               "bert": load_hf_bert, "distilbert": load_hf_distilbert,
               "gpt_neo": load_hf_gpt_neo, "gptneo": load_hf_gpt_neo,
               "clip": load_hf_clip_text, "clip_text": load_hf_clip_text}
    if arch not in loaders:
        raise ValueError(f"no HF converter for architecture {arch!r}; available: {sorted(loaders)}")
    return loaders[arch](hf_model, cfg)
